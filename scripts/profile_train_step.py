"""Profile the single-chip training step (the bench.py phase-2 workload).

Produces, in one run:
  - an XLA profiler trace (view in TensorBoard/XProf) of N timed steps,
  - the compiled step's cost analysis (FLOPs, bytes accessed, arithmetic
    intensity) via utils.profiling.cost_summary,
  - device memory stats after the run.

This is the round-3 entry point for the MFU investigation: the measured
5.5% MFU (BENCH r2) with an XLA-counted ~0.87x-of-formula FLOP count and
very high bytes-accessed suggests an HBM-bound step — the trace says
where.

Usage:  python scripts/profile_train_step.py [--logdir /tmp/tdx-trace]
        TDX_BENCH_TRAIN_MODEL=llama_1b TDX_BENCH_SEQ=2048 control the
        workload like bench.py's train phase.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--logdir", default="/tmp/tdx-trace")
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    p = os.environ.get("TDX_BENCH_PLATFORM")
    if p:
        import jax

        jax.config.update("jax_platforms", p)
    import numpy as np

    from torchdistx_tpu.utils import profiling
    from torchdistx_tpu.utils.benchmarks import (
        V5E_PEAK_BF16,
        build_train_workload,
        warm_to_steady_state,
    )

    # the SAME workload bench.py scores (shared builder)
    w = build_train_workload(args.steps)
    run, carry = w["run"], w["carry"]

    # cost analysis BEFORE executing (compile-only)
    cs = profiling.cost_summary(run, carry, peak_flops=V5E_PEAK_BF16)
    print(json.dumps({"cost_analysis": cs, "workload": {
        k: w[k] for k in ("name", "n_params", "batch", "seq")
    }}))

    # warm to the layout fixpoint outside the trace — a single warm call
    # would put the donated-carry recompile inside the traced window,
    # round-2's measurement bug (see utils.benchmarks.warm_to_steady_state;
    # shared with bench.py so what we profile stays what we score)
    carry, _, warm_converged = warm_to_steady_state(
        run, carry, sync=lambda losses: float(np.asarray(losses[-1]))
    )
    if not warm_converged:
        print(
            json.dumps({"warning": "warm-up did not reach the compile "
                        "fixpoint; the trace may contain a recompile"}),
            file=sys.stderr,
        )

    with profiling.trace(args.logdir):
        with profiling.annotate("timed_steps"):
            carry, losses = run(carry)
            final = float(np.asarray(losses[-1]))

    print(json.dumps({"final_loss": round(final, 4), "trace": args.logdir}))
    print(profiling.format_memory_stats())


if __name__ == "__main__":
    main()
