"""Profile the single-chip training step (the bench.py phase-2 workload).

One run emits, through the unified telemetry layer (torchdistx_tpu.obs):
  - an XLA profiler trace (view in TensorBoard/XProf) of N timed steps,
  - a host-side Perfetto trace (``<logdir>/host_trace.json`` — open in
    ui.perfetto.dev) of the same run: warm-up calls, the timed window,
    any replay spans,
  - the compiled step's cost analysis (FLOPs, bytes accessed, arithmetic
    intensity) via utils.profiling.cost_summary,
  - recompile-watcher counters (obs.RecompileWatcher): every XLA compile
    attributed to warm-up vs the timed window — the donated-carry
    recompile is a NUMBER here, not a timing anomaly,
  - device memory stats and a Prometheus exposition snapshot of the
    run's metrics.

Output contract (same as bench.py): progress lines stream as they
happen, and the LAST stdout line is the full parseable JSON record.

This is the round-3 entry point for the MFU investigation: the measured
5.5% MFU (BENCH r2) with an XLA-counted ~0.87x-of-formula FLOP count and
very high bytes-accessed suggests an HBM-bound step — the trace says
where.

Usage:  python scripts/profile_train_step.py [--logdir /tmp/tdx-trace]
        TDX_BENCH_TRAIN_MODEL=llama_1b TDX_BENCH_SEQ=2048 control the
        workload like bench.py's train phase.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--logdir", default="/tmp/tdx-trace")
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    p = os.environ.get("TDX_BENCH_PLATFORM")
    if p:
        import jax

        jax.config.update("jax_platforms", p)
    import numpy as np

    from torchdistx_tpu import obs
    from torchdistx_tpu.utils import profiling
    from torchdistx_tpu.utils.benchmarks import (
        V5E_PEAK_BF16,
        build_train_workload,
        warm_to_steady_state,
    )

    os.makedirs(args.logdir, exist_ok=True)
    record: dict = {"profile": "train_step", "logdir": args.logdir}
    tracer = obs.enable_tracing(
        jsonl_path=os.path.join(args.logdir, "events.jsonl")
    )
    watcher = obs.RecompileWatcher()
    registry = obs.MetricsRegistry()
    registry.register_collector(watcher.collector())

    # the SAME workload bench.py scores (shared builder)
    with tracer.span("profile/build_workload"):
        w = build_train_workload(args.steps)
    run, carry = w["run"], w["carry"]
    record["workload"] = {
        k: w[k] for k in ("name", "n_params", "batch", "seq")
    }

    # cost analysis BEFORE executing (compile-only)
    with tracer.span("profile/cost_analysis"), watcher.scope(
        "cost_analysis"
    ):
        record["cost_analysis"] = profiling.cost_summary(
            run, carry, peak_flops=V5E_PEAK_BF16
        )
    print(json.dumps({"cost_analysis": record["cost_analysis"]}), flush=True)

    # warm to the layout fixpoint outside the trace — a single warm call
    # would put the donated-carry recompile inside the traced window,
    # round-2's measurement bug (see utils.benchmarks.warm_to_steady_state;
    # shared with bench.py so what we profile stays what we score).  The
    # watcher attributes warm-up compiles to "warmup", so the record
    # shows the donated-carry recompile count explicitly.
    carry, warm_times, warm_converged = warm_to_steady_state(
        run,
        carry,
        sync=lambda losses: float(np.asarray(losses[-1])),
        watcher=watcher,
        label="warmup",
    )
    record["warm_calls_s"] = [round(t, 3) for t in warm_times]
    record["warm_converged"] = warm_converged
    if not warm_converged:
        print(
            json.dumps({"warning": "warm-up did not reach the compile "
                        "fixpoint; the trace may contain a recompile"}),
            file=sys.stderr,
        )

    with profiling.trace(args.logdir):
        with profiling.timed_annotation("timed_steps") as timing:
            carry, losses = run(carry)
            final = float(np.asarray(losses[-1]))
    record["final_loss"] = round(final, 4)
    record["timed_window_s"] = round(timing["seconds"], 3)
    # compiles attributed per phase: anything under "timed_steps" means
    # the timed window was NOT steady state — the exact artifact
    # warm_to_steady_state exists to prevent, now visible as a counter
    record["recompile"] = watcher.snapshot()
    record["memory_stats"] = profiling.device_memory_stats()
    print(profiling.format_memory_stats(record["memory_stats"]), flush=True)

    record["host_trace"] = tracer.export(
        os.path.join(args.logdir, "host_trace.json")
    )
    record["metrics_prom"] = os.path.join(args.logdir, "metrics.prom")
    with open(record["metrics_prom"], "w") as f:
        f.write(registry.render())
    obs.disable_tracing()  # flush + close the JSONL sink

    # the bench.py consumer contract: the full record is the LAST line
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
