"""Serving throughput: continuous batching through ``serve.ServeEngine``,
with a fused multi-step decode A/B, a persistent-loop A/B, and an
optional shared-prefix A/B.

Phases: the K=1 baseline FIRST (one host sync per token), then one phase
per ``--decode-chunk`` value (K decode steps fused into one ``lax.scan``
dispatch, one sync per K tokens), then — with ``persistent`` in
``--decode-mode`` (the default) — the persistent whole-loop phase (one
``lax.while_loop`` dispatch per generation wave, host syncs = ring
drains only; its summary carries ``syncs_reduction_vs_k16`` against the
K=16 fused baseline that ran before it), then — with ``--speculate
0,2,4`` — one persistent-loop phase per K on a repetition-heavy workload
(the prompt-lookup drafter's food), K=0 FIRST as the baseline leg; the
K>0 summaries carry ``accepted_tokens_per_iteration`` and
``loop_iterations_reduction_vs_spec0``, and a K>0 phase flags ``error``
unless it accepted more than one token per iteration, ran strictly fewer
loop iterations than spec0, and kept ``host_syncs`` EXACTLY equal to the
baseline's (speculation multiplies tokens per sync; it may never add
one); then — with ``--prefix-share``
— one paged-engine phase that runs the SAME repeated-system-prompt burst
twice through one engine: cold (empty prefix index) and warm (index
populated by the cold pass).  Warm prefill must compute strictly fewer padded
tokens than cold (suffix-only prefill); the phase reports both passes'
full metrics (``ServeMetrics.to_json()``) plus the warm prefix hit-rate
and pages-in-use high water, and flags ``error`` when the inequality
fails (so ``TDX_SERVE_STRICT`` CI catches a broken prefix cache); then —
with ``--kv-dtype`` (every phase's engines store KV quantized) or
``--kv-quant-ab`` (only the A/B phase; default phases untouched) — the
``kv_quant`` phase: a bfloat16-cache baseline vs the quantized engine on
one greedy workload, STRICT on the exactly-halved ``memory_plan()`` KV
pool (int8), the pinned stream-divergence tolerance against the
model-dtype oracle, decode tok/s, and strictly-lower decode-program
``bytes_accessed``.  Each
phase embeds ``engine.metrics.to_json()`` verbatim under ``"metrics"`` —
one schema for tests, bench, and CI to parse — plus the recompile
watcher's counters (``recompile_warmup`` / ``recompile_measure``: XLA
compiles attributed serve/prefill vs serve/decode; the measured window
is expected to compile NOTHING, and ``measure_compiles`` in the summary
says so per phase).  With ``TDX_SERVE_TRACE_DIR`` set, each phase also
writes a Perfetto host trace (per-request lifecycle tracks included)
and a Prometheus exposition snapshot there, paths embedded in the
record (``trace_path`` / ``metrics_prom_path`` — what the nightly
observability smoke validates).

Same output contract as bench.py: a FULL parseable JSON record is the
LAST stdout line after EVERY phase, baseline included — so a relay that
wedges mid-sweep still yields a degraded-but-parseable record containing
every phase that finished.  Each phase runs in its own subprocess under
the remaining share of ``TDX_BENCH_DEADLINE`` (default 1500 s total),
because a wedged axon relay hangs inside a C dispatch where no in-process
handler can fire (CLAUDE.md); phases run strictly serially (never two TPU
processes).  The final record is also written to ``BENCH_SERVE_<CPU|TPU>.json``
at the repo root.

Usage (TPU):  python scripts/bench_serve.py   # K=1 vs 4,8,16 vs persistent
Smoke (CPU):  TDX_BENCH_PLATFORM=cpu TDX_SERVE_MODEL=tiny \
                  python scripts/bench_serve.py --decode-chunk 4 \
                  --requests 6 --max-new 8 --slots 2
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _ledger():
    """Load ``torchdistx_tpu/obs/ledger.py`` WITHOUT importing the
    package: the supervising parent must never pull in jax or the
    native build (the parent-never-touches-the-device rule), and the
    ledger module is stdlib-only by design.  Memoized in ``sys.modules``
    so repeat calls share one module instance (and its git-sha cache)."""
    import importlib.util

    mod = sys.modules.get("_tdx_ledger")
    if mod is not None:
        return mod
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "torchdistx_tpu", "obs", "ledger.py",
    )
    spec = importlib.util.spec_from_file_location("_tdx_ledger", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    sys.modules["_tdx_ledger"] = mod
    return mod


def _parse_args():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=None)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument(
        "--decode-chunk",
        default="4,8,16",
        help="comma-separated fused-decode chunk sizes to A/B against the "
        "always-run K=1 baseline",
    )
    ap.add_argument(
        "--decode-mode",
        default="chunked,persistent",
        help="comma-separated engine decode modes to bench: 'chunked' "
        "runs the K=1 baseline + the --decode-chunk sweep, 'persistent' "
        "appends the whole-loop phase (always after a fused K baseline, "
        "so the record carries the A/B)",
    )
    ap.add_argument(
        "--ring",
        type=int,
        default=None,
        help="persistent-mode ring capacity (default: the engine's "
        "max_len — one drain per generation wave)",
    )
    ap.add_argument(
        "--speculate",
        default="",
        help="comma-separated self-speculation depths to A/B through the "
        "persistent loop on a repetition-heavy workload (e.g. '0,2,4'); "
        "the K=0 baseline leg always runs first, like the K=1 fused "
        "baseline",
    )
    ap.add_argument(
        "--spec-ngram",
        type=int,
        default=2,
        help="prompt-lookup n-gram width for the --speculate phases",
    )
    ap.add_argument(
        "--prefix-share",
        action="store_true",
        help="append a paged-engine phase A/Bing a repeated-system-prompt "
        "burst cold vs warm (prefix cache empty vs populated)",
    )
    ap.add_argument(
        "--page-size",
        type=int,
        default=16,
        help="KV page size (tokens) for the --prefix-share phase",
    )
    ap.add_argument(
        "--tp",
        type=int,
        default=1,
        help="tensor-parallel degree: every phase runs its engine on a "
        "('tp',) mesh of this many devices (params Megatron-sharded, KV "
        "head-sharded) and embeds the phase's comm-audit bytes; on the "
        "CPU smoke the parent raises the child's virtual device count "
        "to match",
    )
    ap.add_argument(
        "--chunked-prefill",
        type=int,
        default=None,
        metavar="T",
        help="append a chunked-prefill A/B phase: a long-prompt admission "
        "mid-decode, unchunked vs chunked at threshold T (must be a "
        "prefill bucket) — the headline is the active requests' max "
        "inter-token gap, chunked strictly below unchunked",
    )
    ap.add_argument(
        "--migrate-tp-to",
        type=int,
        default=None,
        metavar="N",
        help="append an elastic-migration phase: drain a --tp engine "
        "mid-decode and migrate_to() a tp=N engine, pinning zero drops, "
        "bit-identical streams, and the closed-form migration wire bytes "
        "as ledger counter rows (workload key 'mesh_to')",
    )
    ap.add_argument(
        "--fleet",
        type=int,
        default=None,
        metavar="N",
        help="append the fleet phases (ISSUE 13): an N-replica "
        "ServeFleet A/B on a shared-prefix arrival stream — affinity vs "
        "round-robin routing, prefix hit-rate and p50 TTFT, streams "
        "pinned bit-identical to one engine — plus a mid-workload "
        "fleet.remove() drain leg (zero drops)",
    )
    ap.add_argument(
        "--disaggregate",
        action="store_true",
        help="with --fleet: append the disaggregated leg — a prefill "
        "(tp=2) and a decode (tp=1) engine behind the router, every "
        "finished prefill's KV handed off as an explicit head-axis "
        "redistribution pinned closed-form against the comm audit",
    )
    ap.add_argument(
        "--scenario",
        default=None,
        metavar="NAMES",
        help="comma-separated open-loop traffic scenarios from the "
        "serve/workload.py catalog (poisson, diurnal, bursty, "
        "flash_crowd): each appends an autoscale A/B phase replaying "
        "the scenario's deterministic tick-stamped arrival stream "
        "through every static fleet size the policy allows AND a "
        "closed-loop AutoscaleController fleet — the STRICT verdict is "
        "that autoscaling beats every static of equal-or-lower "
        "replica-tick cost on deadline attainment, is Pareto-undominated, "
        "executes a full scale-up + scale-down cycle, and keeps every "
        "stream bit-identical to the single-engine oracle",
    )
    ap.add_argument(
        "--autoscale",
        default=None,
        metavar="POLICY",
        help="ScalingPolicy for the --scenario phases: 'default', an "
        "inline JSON object, or a path to one (serve/autoscale.py "
        "schema); defaults to 'default' when --scenario is given",
    )
    ap.add_argument(
        "--slo",
        default=None,
        metavar="SPEC",
        help="path to a JSON SloSpec (obs/slo.py): every fleet phase "
        "evaluates it over the fleet's finished requests and embeds "
        "the tdx-slo-v1 report as the phase's 'slo' block (the routing "
        "A/B embeds one report per policy — the SLO-attainment axis of "
        "the affinity-vs-RR verdict); a breached evaluation lands a "
        "named slo_burn flight event",
    )
    ap.add_argument(
        "--slo-strict",
        action="store_true",
        help="with --slo: a breached report (or a burning window) is a "
        "phase error and the run exits nonzero — the nightly "
        "injected-burn leg's contract",
    )
    ap.add_argument(
        "--kv-dtype",
        default=None,
        metavar="DTYPE",
        help="KV-cache storage dtype for EVERY phase's engines (int8 "
        "quantizes on write with per-row power-of-two scales; bfloat16/"
        "float16/float32 cast).  Also appends the kv_quant A/B phase: "
        "a bfloat16-baseline vs --kv-dtype engine pair on the same "
        "greedy workload, STRICT on the halved memory_plan() KV pool, "
        "the pinned stream-divergence tolerance, decode tok/s, and a "
        "strictly-lower cost-card bytes_accessed for every decode "
        "program.  Phase records gain a 'kv_dtype' ledger workload key "
        "(only when set — default-run fingerprints never drift)",
    )
    ap.add_argument(
        "--kv-quant-ab",
        default=None,
        metavar="DTYPE",
        help="append ONLY the kv_quant A/B phase at this quantized dtype "
        "while every other phase keeps its default (model-dtype) cache — "
        "the nightly default-smoke rider: existing fingerprints stay "
        "byte-stable and the record gains the int8 family.  Use "
        "--kv-dtype instead to run the WHOLE sweep quantized",
    )
    ap.add_argument(
        "--numerics",
        action="store_true",
        help="append the numerics-observatory A/B phase (ISSUE 19): a "
        "digest-off and a digest-on engine serve the SAME greedy "
        "workload; STRICT on bit-identical streams and EXACTLY equal "
        "host_syncs / decode_dispatches / decode_steps (digests fuse "
        "into the existing programs and harvest at existing syncs — "
        "enabling them must cost zero dispatches).  The on-leg embeds "
        "the tdx-numerics-v1 digest book; its exact integer fields "
        "land as ledger counter rows (workload keys 'numerics' + "
        "'numerics_site') that perf_gate pins bit-identically across "
        "runs.  Default phases never build digest engines, so "
        "pre-existing fingerprints stay byte-stable",
    )
    ap.add_argument(
        "--record",
        action="store_true",
        help="incident time machine (ISSUE 20): after each phase's "
        "measured window, re-serve the identical workload on a fresh "
        "engine with session recording on (obs/blackbox.py tdx-session-v1 "
        "black box), then self-replay the recording and embed the STRICT "
        "verdict — every drain-boundary digest chain must be "
        "bit-identical, and the recording engine's counters must equal "
        "the unrecorded measured run's (the zero-overhead pin)",
    )
    ap.add_argument(
        "--artifact",
        default=None,
        help="override the BENCH_SERVE_<CPU|TPU>.json artifact path "
        "(the nightly 2-device-mesh leg writes its own file so the "
        "single-chip artifact is never clobbered)",
    )
    return ap.parse_args()


def _chunk_values(args) -> list:
    ks = [int(k) for k in str(args.decode_chunk).split(",") if str(k).strip()]
    if any(k < 1 for k in ks):
        raise SystemExit(f"--decode-chunk values must be >= 1, got {ks}")
    # K=1 baseline always runs first so a wedge mid-sweep still leaves a
    # comparable record; dedupe (order-preserving — repeats would burn a
    # phase's deadline share and silently overwrite its record)
    return [1] + [k for k in dict.fromkeys(ks) if k != 1]


def _spec_values(args) -> list:
    """The ``--speculate`` sweep: K=0 (the classic persistent program)
    always FIRST so a wedge mid-sweep still leaves the baseline leg of
    the A/B, then the deduped K>0 depths."""
    ks = [int(k) for k in str(args.speculate).split(",") if str(k).strip()]
    if not ks:
        return []
    if any(k < 0 for k in ks):
        raise SystemExit(f"--speculate values must be >= 0, got {ks}")
    return [0] + [k for k in dict.fromkeys(ks) if k != 0]


def _scenario_values(args) -> list:
    """The ``--scenario`` sweep, deduped in request order.  Validated
    against a literal copy of the serve/workload.py catalog names — the
    parent must stay import-free (a parent touching jax alongside a TPU
    child is the two-process relay wedge), so it cannot ask the module."""
    names = [
        s.strip() for s in str(args.scenario or "").split(",") if s.strip()
    ]
    unknown = set(names) - {"poisson", "diurnal", "bursty", "flash_crowd"}
    if unknown:
        raise SystemExit(f"unknown --scenario names: {sorted(unknown)}")
    return list(dict.fromkeys(names))


def _phase_summary(rec: dict) -> dict:
    """The A/B headline numbers of one phase record, lifted out of its
    embedded ``metrics`` (``ServeMetrics.to_json()``) object."""
    m = rec.get("metrics") or {}
    derived = m.get("derived") or {}
    counters = m.get("counters") or {}
    hists = m.get("histograms") or {}
    out = {
        "decode_tokens_per_sec": derived.get("decode_tokens_per_sec"),
        "wall_tokens_per_sec": derived.get("wall_tokens_per_sec"),
        "syncs_per_token": derived.get("syncs_per_token"),
        "host_syncs": counters.get("host_syncs"),
        "decode_token_s_p50": (hists.get("decode_token_s") or {}).get("p50"),
        "decode_token_s_p95": (hists.get("decode_token_s") or {}).get("p95"),
        "masked_slot_steps": counters.get("masked_slot_steps"),
        # compiles inside the measured window (recompile watcher):
        # anything nonzero means the phase's timings include XLA
        # compiles.  available=False means the jax.monitoring hook is
        # missing and the count is UNKNOWN — surface null, never a
        # clean-looking 0 (the watcher's snapshot contract)
        "measure_compiles": (
            (rec.get("recompile_measure") or {}).get("compiles_total")
            if (rec.get("recompile_measure") or {}).get("available")
            else None
        ),
        "error": rec.get("error"),
    }
    if rec.get("decode_mode") == "persistent":
        gauges = m.get("gauges") or {}
        out.update(
            ring_drains=counters.get("ring_drains"),
            loop_iterations=counters.get("loop_iterations"),
            ring_occupancy_hwm=gauges.get("ring_occupancy_hwm"),
        )
    if rec.get("speculate") is not None:  # the self-speculation A/B
        out.update(
            speculate=rec.get("speculate"),
            accept_rate=derived.get("accept_rate"),
            accepted_tokens_per_iteration=derived.get(
                "accepted_tokens_per_iteration"
            ),
            draft_tokens_proposed=counters.get("draft_tokens_proposed"),
            draft_tokens_accepted=counters.get("draft_tokens_accepted"),
            loop_iterations_reduction_vs_spec0=rec.get(
                "loop_iterations_reduction_vs_spec0"
            ),
        )
    if "warm" in rec:  # the prefix-share phase
        out.update(
            prefix_hit_rate_warm=rec.get("prefix_hit_rate_warm"),
            tokens_prefilled_cold=rec.get("tokens_prefilled_cold"),
            tokens_prefilled_warm=rec.get("tokens_prefilled_warm"),
            pages_in_use_hwm=rec.get("pages_in_use_hwm"),
        )
    if "max_gap_s_chunked" in rec:  # the chunked-prefill A/B phase
        out.update(
            max_gap_s_unchunked=rec.get("max_gap_s_unchunked"),
            max_gap_s_chunked=rec.get("max_gap_s_chunked"),
            gap_reduction=rec.get("gap_reduction"),
            interleaved_dispatches=rec.get("interleaved_dispatches"),
        )
    if "prefix_hit_rate_affinity" in rec:  # the fleet routing A/B
        out.update(
            prefix_hit_rate_affinity=rec.get("prefix_hit_rate_affinity"),
            prefix_hit_rate_round_robin=rec.get(
                "prefix_hit_rate_round_robin"
            ),
            ttft_p50_s_affinity=rec.get("ttft_p50_s_affinity"),
            ttft_p50_s_round_robin=rec.get("ttft_p50_s_round_robin"),
            streams_identical=rec.get("streams_identical"),
        )
    if "kv_bytes_factor" in rec:  # the kv_quant A/B phase
        out.update(
            kv_dtype=rec.get("kv_dtype"),
            kv_bytes_factor=rec.get("kv_bytes_factor"),
            stream_prefix_agreement=rec.get("stream_prefix_agreement"),
            streams_identical_frac=rec.get("streams_identical_frac"),
            decode_tokens_per_sec_baseline=rec.get(
                "decode_tokens_per_sec_baseline"
            ),
        )
    if "remove_summary" in rec:  # the fleet drain leg
        out.update(
            streams_identical=rec.get("streams_identical"),
            migrated_running=(rec.get("remove_summary") or {}).get(
                "migrated_running"
            ),
            migrated_queued=(rec.get("remove_summary") or {}).get(
                "migrated_queued"
            ),
        )
    if "autoscale_verdict" in rec:  # the closed-loop autoscale A/B
        v = rec.get("autoscale_verdict") or {}
        out.update(
            scenario=rec.get("scenario"),
            autoscale_ok=v.get("ok"),
            requests=v.get("requests"),
            attained_autoscale=v.get("attained_autoscale"),
            replica_ticks_autoscale=v.get("replica_ticks_autoscale"),
            attained_static=v.get("attained_static"),
            replica_ticks_static=v.get("replica_ticks_static"),
            scale_ups=v.get("scale_ups"),
            scale_downs=v.get("scale_downs"),
            streams_identical=v.get("streams_identical"),
        )
    if "handoff_wire_bytes_expected" in rec:  # the disaggregated leg
        out.update(
            streams_identical=rec.get("streams_identical"),
            handoff_wire_bytes=counters.get("handoff_wire_bytes"),
            requests_handed_off=counters.get("requests_handed_off"),
        )
    if (rec.get("mesh") or 1) > 1:
        # the tdx-comm-v1 profile embedded by the TP phases
        comm = rec.get("comm") or {}
        out["comm_wire_bytes"] = sum(
            (comm.get("bytes_by_axis") or {}).values()
        )
    slo = rec.get("slo") or {}
    if "schema" in slo:  # one report per phase
        out["slo_attainment"] = (slo.get("attainment") or {}).get(
            "overall"
        )
        out["slo_breached"] = slo.get("breached")
        out["slo_burn_state"] = (slo.get("burn") or {}).get("state")
    elif slo:  # the routing A/B carries one report per policy
        for pol, r in sorted(slo.items()):
            if isinstance(r, dict) and "schema" in r:
                out[f"slo_attainment_{pol}"] = (
                    r.get("attainment") or {}
                ).get("overall")
                out[f"slo_breached_{pol}"] = r.get("breached")
    return out


def _supervise(args) -> None:
    """Run one child per K under the global deadline; the parent never
    touches the device (a parent + child both on the TPU would be the
    two-process relay wedge this guards against), and phases are strictly
    serial for the same reason."""
    deadline = float(os.environ.get("TDX_BENCH_DEADLINE", "1500"))
    t0 = time.monotonic()
    chunks = _chunk_values(args)
    modes = [m for m in str(args.decode_mode).split(",") if m.strip()]
    unknown = set(modes) - {"chunked", "persistent"}
    if unknown:
        raise SystemExit(f"unknown --decode-mode values: {sorted(unknown)}")
    if "chunked" not in modes:
        # the persistent A/B still needs its fused baselines: K=1 (the
        # sweep's anchor) and the largest requested K (the comparator)
        chunks = [1] + ([chunks[-1]] if chunks[-1] != 1 else [])
    specs = _spec_values(args)
    record: dict = {
        "bench": "serve",
        # commit + schema attribution (the perf-sentinel requirement:
        # a record that can't name its sha can't join the trajectory)
        **_ledger().record_stamp(),
        "model": os.environ.get("TDX_SERVE_MODEL", "llama_1b"),
        "deadline_s": deadline,
        "decode_chunks": chunks,
        "decode_modes": modes,
        "speculate_sweep": specs,
        "mesh": args.tp,
        "phases": {},
    }
    # phase plan: K=1 baseline, the chunk A/B, the persistent loop
    # (always AFTER its fused baselines), then (opt-in) the paged
    # shared-prefix cold/warm A/B at the largest requested chunk
    plan = [(f"k{k}", {"TDX_SERVE_CHUNK": str(k)}) for k in chunks]
    if "persistent" in modes:
        plan.append(("persistent", {"TDX_SERVE_PHASE": "persistent"}))
    for k in specs:
        plan.append(
            (
                f"spec{k}",
                {
                    "TDX_SERVE_PHASE": "speculate",
                    "TDX_SERVE_SPECULATE": str(k),
                },
            )
        )
    if args.prefix_share:
        plan.append(
            (
                "prefix_share",
                {
                    "TDX_SERVE_CHUNK": str(chunks[-1]),
                    "TDX_SERVE_PHASE": "prefix_share",
                },
            )
        )
    if args.chunked_prefill is not None:
        plan.append(
            (
                "chunked_prefill",
                {
                    "TDX_SERVE_CHUNK": str(chunks[-1]),
                    "TDX_SERVE_PHASE": "chunked_prefill",
                },
            )
        )
    if args.migrate_tp_to is not None:
        plan.append(
            (
                "migrate",
                {
                    "TDX_SERVE_CHUNK": str(chunks[-1]),
                    "TDX_SERVE_PHASE": "migrate",
                },
            )
        )
    if args.kv_dtype or args.kv_quant_ab:
        plan.append(
            (
                "kv_quant",
                {
                    "TDX_SERVE_CHUNK": str(chunks[-1]),
                    "TDX_SERVE_PHASE": "kv_quant",
                },
            )
        )
    if args.numerics:
        plan.append(
            (
                "numerics",
                {
                    "TDX_SERVE_CHUNK": str(chunks[-1]),
                    "TDX_SERVE_PHASE": "numerics",
                },
            )
        )
    if args.fleet is not None:
        # the routing A/B first (its STRICT verdict is the headline),
        # then the scale-event leg, then (opt-in) disaggregation
        for fname in ["fleet", "fleet_drain"] + (
            ["fleet_disagg"] if args.disaggregate else []
        ):
            plan.append(
                (
                    fname,
                    {
                        "TDX_SERVE_CHUNK": str(chunks[-1]),
                        "TDX_SERVE_PHASE": fname,
                    },
                )
            )
    for sc in _scenario_values(args):
        # one A/B phase per traffic scenario; the child pins its own
        # engine geometry to the scenario's token envelope, so no
        # TDX_SERVE_CHUNK override here
        plan.append(
            (
                f"autoscale_{sc}",
                {
                    "TDX_SERVE_PHASE": "autoscale",
                    "TDX_SERVE_SCENARIO": sc,
                },
            )
        )

    def emit():
        # the speculation A/B verdict, before the summary snapshots it:
        # a K>0 leg must beat spec0 on iteration economy WITHOUT moving
        # the sync count (speculation multiplies tokens per sync — one
        # extra host sync means the engine broke the drain discipline).
        # Idempotent across the per-phase emits: same inputs, same
        # fields, and a flagged error short-circuits further rewrites.
        spec0 = record["phases"].get("spec0") or {}
        base_c = (spec0.get("metrics") or {}).get("counters") or {}
        for name, rec in record["phases"].items():
            if not (name.startswith("spec") and name != "spec0"):
                continue
            if "error" in rec or "error" in spec0 or not base_c:
                continue
            c = (rec.get("metrics") or {}).get("counters") or {}
            it, base_it = c.get("loop_iterations"), base_c.get(
                "loop_iterations"
            )
            rec["loop_iterations_reduction_vs_spec0"] = (
                round(base_it / it, 3) if it and base_it else None
            )
            if it and base_it and not it < base_it:
                rec["error"] = (
                    "speculation did not reduce loop iterations "
                    f"({it} vs {base_it} at spec0)"
                )
            elif c.get("host_syncs") != base_c.get("host_syncs"):
                rec["error"] = (
                    "speculation changed the host sync count "
                    f"({c.get('host_syncs')} vs "
                    f"{base_c.get('host_syncs')} at spec0)"
                )
        # phases run (and are recorded) in plan order; dict order is the
        # summary order
        record["summary"] = {
            name: _phase_summary(rec)
            for name, rec in record["phases"].items()
        }
        summ = record["summary"]
        if "persistent" in summ:
            # the tentpole headline: persistent syncs/token vs the
            # largest fused-K baseline that ran before it (k16 on the
            # default sweep) — >= 4x is the acceptance bar
            baseline = max(
                (n for n in summ if n.startswith("k") and n[1:].isdigit()),
                key=lambda n: int(n[1:]),
                default=None,
            )
            if baseline is not None:
                spt = summ["persistent"].get("syncs_per_token")
                base_spt = summ[baseline].get("syncs_per_token")
                summ["persistent"][f"syncs_reduction_vs_{baseline}"] = (
                    base_spt / spt if spt and base_spt else None
                )
        print(json.dumps(record), flush=True)

    for name, phase_env in plan:
        left = deadline - (time.monotonic() - t0)
        if left <= 5:
            record["phases"][name] = {
                "error": "global deadline exhausted before phase start"
            }
            emit()
            continue
        cmd = [sys.executable, os.path.abspath(__file__)] + sys.argv[1:]
        env = dict(os.environ, TDX_SERVE_CHILD="1", **phase_env)
        n_dev = max(
            args.tp,
            args.migrate_tp_to or 1,
            # the disaggregated fleet leg builds its prefill engine on a
            # 2-device ('tp',) mesh regardless of --tp
            2 if (args.fleet is not None and args.disaggregate) else 1,
        )
        if n_dev > 1 and env.get("TDX_BENCH_PLATFORM") == "cpu":
            # the CPU smoke needs enough virtual devices for the mesh
            # (the migrate phase may need MORE than --tp for its target);
            # the flag must be set before the child imports jax
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n_dev}"
            ).strip()
        phase: dict = {}
        try:
            proc = subprocess.run(
                cmd, env=env, timeout=left, capture_output=True, text=True
            )
            lines = [
                ln for ln in (proc.stdout or "").splitlines() if ln.strip()
            ]
            if lines:
                try:
                    phase = json.loads(lines[-1])
                except ValueError:
                    phase = {"error": f"unparseable child record: {lines[-1][:200]}"}
            else:
                phase = {
                    "error": f"child exited {proc.returncode} with no "
                    f"record: {(proc.stderr or '')[-400:]}"
                }
        except subprocess.TimeoutExpired:
            phase = {
                "error": f"deadline share ({left:.0f}s) exceeded — relay "
                "wedge?"
            }
            record["phases"][name] = phase
            emit()
            break  # a wedged relay poisons every later phase; stop here
        record["phases"][name] = phase
        emit()  # full record after EVERY phase — the consumer contract

    _write_artifact(record, args.artifact)
    # perf-sentinel hook: normalize this run into LEDGER.jsonl rows so
    # the trajectory (and the nightly gate's baselines) grow with every
    # run — never raises, disabled by TDX_LEDGER=0
    _ledger().append_record_rows(record, source="bench_serve")
    failed = [
        name
        for name, p in sorted(record["phases"].items())
        if "error" in p
    ] or (["no phase ran"] if not record["phases"] else [])
    if failed and (os.environ.get("TDX_SERVE_STRICT") or args.slo_strict):
        # CI smoke mode: the record stays parseable on stdout either way,
        # but a phase error must FAIL the step — without this, the
        # degraded-record contract would let a fully broken fused-decode
        # path keep a green nightly.  --slo-strict opts into the same
        # contract even without TDX_SERVE_STRICT (the injected-burn leg
        # must exit nonzero on its own)
        print(f"bench_serve: failed phases: {failed}", file=sys.stderr)
        sys.exit(1)


def _write_artifact(record: dict, artifact: str = None) -> None:
    """Persist the record as BENCH_SERVE_<CPU|TPU>.json (or the --artifact
    override) — but never let a
    run that produced no phase evidence misfile or clobber real evidence
    (the KERNEL_ACCEPT guard convention): the platform comes from what
    the phases actually REPORTED, falling back to the requested platform,
    and an all-error record never replaces an existing error-free one."""
    phases = record["phases"].values()
    if artifact:
        out_path = os.path.abspath(artifact)
    else:
        reported = {p.get("platform") for p in phases if p.get("platform")}
        if reported:
            plat = "CPU" if "cpu" in reported else "TPU"
        elif os.environ.get("TDX_BENCH_PLATFORM"):
            plat = (
                "CPU" if os.environ["TDX_BENCH_PLATFORM"] == "cpu" else "TPU"
            )
        else:
            return  # nothing reported where it ran: print-only, no file
        out_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            f"BENCH_SERVE_{plat}.json",
        )
    all_error = all("error" in p for p in phases) or not record["phases"]
    if all_error and os.path.exists(out_path):
        try:
            with open(out_path) as f:
                prior = json.load(f)
            if any(
                "error" not in p for p in prior.get("phases", {}).values()
            ):
                return  # keep the prior good evidence; stdout has this run
        except (OSError, ValueError):
            pass  # unreadable prior record: replacing it loses nothing
    try:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    except OSError:
        pass  # the stdout record is the contract; the file is a courtesy


def _phase_setup(args, **extra) -> tuple:
    """Shared child-phase bring-up: pin the requested platform BEFORE
    the first jax op and build the common record header.  One
    definition for every phase flavor, so a setup change (env knob,
    platform pinning, dtype rule) can never leave one phase
    benchmarking a differently-configured engine."""
    import jax

    plat = os.environ.get("TDX_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    if os.environ.get("TDX_SERVE_TRACE_DIR"):
        # host tracing for this phase: spans land in the per-phase
        # Perfetto file _dump_obs writes at the end of the child
        from torchdistx_tpu import obs

        obs.enable_tracing()
    k_chunk = int(os.environ.get("TDX_SERVE_CHUNK", "1"))
    mode = (
        "persistent"
        if os.environ.get("TDX_SERVE_PHASE") == "persistent"
        else "chunked"
    )
    name = os.environ.get("TDX_SERVE_MODEL", "llama_1b")
    record: dict = {
        "bench": "serve",
        "model": name,
        "platform": jax.devices()[0].platform,
        "requests": args.requests,
        "max_new_tokens": args.max_new,
        "num_slots": args.slots,
        "decode_chunk": k_chunk,
        "decode_mode": mode,
        # ALWAYS emitted (1 when single-chip): a ledger workload key, so
        # TP-mesh counter rows can never collide with single-chip pins
        "mesh": args.tp,
        **extra,
    }
    if args.kv_dtype:
        # a ledger workload key ONLY when requested: int8 fingerprints
        # get their own family while default-run pins stay byte-stable
        record["kv_dtype"] = args.kv_dtype
    return record, name, k_chunk, plat


def _mesh_kwargs(args, tp: int = None) -> dict:
    """``ServeEngine(mesh=...)`` kwargs for the requested TP degree
    (empty when tp is 1: the single-chip engine path stays the
    reference).  ``tp`` overrides ``args.tp`` — the migrate phase builds
    its target engine on a different degree."""
    tp = args.tp if tp is None else tp
    if tp <= 1:
        return {}
    import numpy as np

    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < tp:
        raise RuntimeError(
            f"--tp {tp} needs {tp} devices, found {len(devs)}"
        )
    return {"mesh": Mesh(np.asarray(devs[:tp]), ("tp",))}


def _kv_kwargs(args, kv_dtype: str = None) -> dict:
    """``ServeEngine(kv_dtype=...)`` kwargs (empty without ``--kv-dtype``,
    so default phases build byte-identical engines).  ``kv_dtype``
    overrides ``args.kv_dtype`` — the kv_quant phase builds its bfloat16
    baseline engine beside the quantized one."""
    kv = args.kv_dtype if kv_dtype is None else kv_dtype
    return {"kv_dtype": kv} if kv else {}


def _kv_entry_wire_bytes(entry, g: int) -> int:
    """Ring all-gather wire for ONE slot row (or page) of one layer's
    full cache entry at gather group ``g``: ``unit * (g-1)/g`` summed
    per array — the ``(k, v)`` pair, plus the f32 scale arrays when the
    cache is quantized, each priced at its OWN dtype (the int8 closed
    form's dtype factor)."""
    import numpy as np

    if g <= 1:
        return 0
    total = 0
    for a in entry:
        unit = int(np.prod(a.shape[1:])) * np.dtype(a.dtype).itemsize
        total += unit * (g - 1) // g
    return total


def _embed_cost(record: dict, engine) -> None:
    """Cost-observatory fields of one phase record (obs.cost): the
    per-program CostCards (ledger counter rows + the --cost CI schema
    check read these), the live HBM capacity plan the admission gate
    consults, and per-span roofline/MFU attribution — prefill and
    decode each get their own measured MFU instead of one end-of-run
    number.  A span's MFU is only computed when ONE program served it
    (several prefill buckets mixing would attribute dishonestly) and a
    chip peak is known (None on the CPU smoke, by design).  The
    persistent while-loop program's XLA FLOP count covers ONE loop
    body, so its executions count is ``loop_iterations`` (bodies run),
    not ``decode_dispatches`` (ring drains) — using drains would
    understate MFU by the iterations-per-drain factor; the remaining
    per-dispatch caveat is flagged in the entry's note."""
    from torchdistx_tpu.obs.cost import span_mfu
    from torchdistx_tpu.utils.benchmarks import V5E_PEAK_BF16

    record["cost_cards"] = engine.cost_book.to_json()
    record["memory_plan"] = engine.memory_plan()
    peak = V5E_PEAK_BF16 if record.get("platform") == "tpu" else None
    m = engine.metrics
    cards = engine.cost_book.cards()
    spans = {}
    groups = {
        "prefill": (
            "serve/prefill",
            m.counters["prefill_calls"],
            m.prefill_s.total,
        ),
        "decode": (
            "serve/decode",
            m.counters["decode_dispatches"],
            m.decode_s.total,
        ),
    }
    for span, (prefix, execs, secs) in groups.items():
        cs = [c for n, c in sorted(cards.items()) if n.startswith(prefix)]
        if not cs:
            continue
        entry: dict = {
            "programs": [c.program for c in cs],
            "executions": execs,
            "span_s": round(secs, 4),
        }
        if len(cs) == 1:
            entry["flops_per_dispatch"] = cs[0].flops
            if "persistent" in cs[0].program:
                # the card counts ONE while_loop body: executions for
                # the MFU must be bodies run (loop_iterations), never
                # ring drains
                entry["executions"] = m.counters["loop_iterations"]
                entry["note"] = (
                    "while-loop program: XLA counts one loop body; "
                    "executions = loop_iterations, and "
                    "flops_per_dispatch understates a multi-iteration "
                    "dispatch"
                )
            entry["mfu"] = span_mfu(
                cs[0],
                executions=entry["executions"],
                seconds=secs,
                peak_flops=peak,
            )
        spans[span] = entry
    record["roofline"] = spans


def _dump_obs(record: dict, engine, tag: str) -> None:
    """Per-phase observability artifacts (opt-in via
    ``TDX_SERVE_TRACE_DIR``): a Perfetto trace of the phase — tracer
    spans + one lifecycle track per finished request — and the
    Prometheus exposition of the phase's final metrics.  Paths and a
    small summary are embedded in the phase record (additive keys;
    existing consumers parse the last line unchanged)."""
    out_dir = os.environ.get("TDX_SERVE_TRACE_DIR")
    if not out_dir:
        return
    from torchdistx_tpu import obs

    os.makedirs(out_dir, exist_ok=True)
    trace_path = os.path.join(out_dir, f"{tag}_trace.json")
    engine.dump_trace(trace_path)
    finished = engine.finished_requests()
    record["trace_path"] = trace_path
    record["trace_summary"] = {
        "requests": len(finished),
        "lifecycle_events": sum(len(r.events) for r in finished),
        "tracer_spans": len(obs.get_tracer().events()),
    }
    registry = obs.MetricsRegistry()
    registry.register_collector(engine.metrics.collector())
    # the cost observatory's third export: the same cards the record
    # embeds, as tdx_cost_*{program=...} gauges on the exposition
    registry.register_collector(engine.cost_book.collector())
    # numerics observatory: tdx_numerics_*{site=...} gauges — only
    # digest engines register it, so default phases' expositions stay
    # byte-stable; check_obs_artifacts --numerics cross-checks these
    # samples against the embedded book exactly
    book = getattr(engine, "numerics_book", None)
    if getattr(engine, "numerics", False) and book is not None:
        registry.register_collector(book.collector(), obj=book)
    prom_path = os.path.join(out_dir, f"{tag}_metrics.prom")
    with open(prom_path, "w") as f:
        f.write(registry.render())
    record["metrics_prom_path"] = prom_path


def _build_model(name: str, plat):
    import jax.numpy as jnp

    import torchdistx_tpu as tdx
    from torchdistx_tpu.models import Llama

    dtype = jnp.bfloat16 if plat != "cpu" else jnp.float32
    tdx.manual_seed(0)
    model = tdx.deferred_init(Llama.from_name, name, dtype=dtype)
    tdx.materialize_module(model)
    return model


def _session_selftest(
    args, record, model, name, plat, engine_kw, work, tag
) -> None:
    """``--record``: the phase's incident-time-machine leg.  Re-serves
    the phase's measured workload on a FRESH engine with session
    recording on (a fresh engine because recording must start at
    construction — mid-run ``reset_metrics`` would fold negative
    counter deltas), writes the ``tdx-session-v1`` black box, then
    self-replays it in-process and embeds the verdict.  STRICT: a
    non-match verdict is a phase ``error``.  The recording engine's
    counters are compared against the unrecorded measured run's — the
    zero-overhead evidence (recording adds no host syncs, no
    dispatches, nothing countable).

    Call AFTER ``record['recompile_measure']`` and ``_dump_obs`` so
    this leg's compiles never pollute the measured compile count."""
    if not getattr(args, "record", False):
        return
    from torchdistx_tpu.obs.blackbox import (
        geometry_kwargs,
        load_session,
        replay_session,
    )
    from torchdistx_tpu.serve import ServeEngine

    rec, path = _session_recorder(args, name, plat, tag)
    engine = ServeEngine(model, record=rec, **engine_kw)
    engine.run([dict(w) for w in work])
    rec.close()

    events, _notes = load_session(path)

    def engine_factory(rep_rec, geom):
        # recorded geometry wins; non-geometry extras (mesh, numerics)
        # come from the phase's own kwargs
        return ServeEngine(
            model, record=rep_rec, **{**engine_kw, **geometry_kwargs(geom)}
        )

    verdict = replay_session(events, engine_factory=engine_factory)

    counters = {
        k: v
        for k, v in engine.metrics.counters.items()
        if isinstance(v, int)
    }
    _embed_session_verdict(record, path, verdict, counters)


def _session_recorder(args, name, plat, tag):
    """The selftest recording sink: one ``tdx-session-v1`` file per
    phase under ``TDX_SERVE_TRACE_DIR`` (tmpdir fallback), seeded with
    the ``model_spec`` event ``scripts/replay_session.py`` rebuilds
    the model from."""
    from torchdistx_tpu.obs.blackbox import SessionRecorder

    out_dir = os.environ.get("TDX_SERVE_TRACE_DIR") or tempfile.gettempdir()
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"session_{tag}_{os.getpid()}.jsonl")
    if os.path.exists(path):
        os.remove(path)
    rec = SessionRecorder(path, enabled=True)
    rec.record(
        "model_spec",
        name=name,
        seed=0,
        dtype="bfloat16" if plat != "cpu" else "float32",
    )
    return rec, path


def _embed_session_verdict(record, path, verdict, counters) -> None:
    """Embed the self-replay verdict + the zero-overhead counter pin
    in the phase record; STRICT turns either failure into the phase
    ``error``."""
    measured = ((record.get("metrics") or {}).get("counters")) or {}
    unequal = {
        k: (counters.get(k), measured.get(k))
        for k in sorted(counters)
        if counters.get(k) != measured.get(k)
    }
    record["session"] = {
        "path": path,
        "drains": verdict.get("drains_recorded"),
        "verdict": verdict.get("verdict"),
        "match": bool(verdict.get("match")),
        "first_divergence": verdict.get("first_divergence"),
        "counters_equal": not unequal,
        "counters_unequal": unequal,
    }
    if not verdict.get("match") and "error" not in record:
        d = verdict.get("first_divergence") or {}
        record["error"] = (
            f"session replay {verdict.get('verdict')}: first divergence "
            f"at drain seq={d.get('seq')} tick={d.get('tick')} "
            f"counters={d.get('counters')} rids={d.get('rids')}"
        )
    elif unequal and "error" not in record:
        record["error"] = (
            "session recording moved engine counters vs the unrecorded "
            f"measured run (recorded, measured): {unequal}"
        )


def _session_selftest_fleet(
    args, record, model, name, plat, build, work, tag, *, policy="affinity"
) -> None:
    """``--record``, fleet posture: re-drives the phase's workload
    through a FRESH recording fleet (same online arrival, same policy
    as the measured affinity side), writes the ``tdx-session-v1`` black
    box with the FLEET as the driver (per-replica geometry, routing
    ticks), then self-replays it from the recording alone — each
    replica rebuilt from ITS geometry event, the shared model from
    ``model_spec``.  Same STRICT verdict and zero-overhead counter pin
    as the single-engine selftest, against the fleet's summed
    aggregate."""
    if not getattr(args, "record", False):
        return
    from torchdistx_tpu.obs.blackbox import (
        geometry_kwargs,
        load_session,
        replay_session,
    )
    from torchdistx_tpu.serve import ServeEngine, ServeFleet

    rec, path = _session_recorder(args, name, plat, tag)
    fleet = ServeFleet(
        [build() for _ in range(int(args.fleet))],
        policy=policy,
        record=rec,
    )
    for w in work:  # online arrival, like the measured A/B
        fleet.submit(**dict(w))
        fleet.step()
    while fleet.step():
        pass
    rec.close()

    events, _notes = load_session(path)

    def engine_factory(rep_rec, geom):
        return ServeEngine(model, record=rep_rec, **geometry_kwargs(geom))

    verdict = replay_session(events, engine_factory=engine_factory)
    counters = {
        k: v
        for k, v in fleet.metrics_json()["counters"].items()
        if isinstance(v, int)
    }
    _embed_session_verdict(record, path, verdict, counters)


def _child(args) -> None:
    """One phase: one engine at one decode_chunk (or the persistent
    loop), warm then measure."""
    record, name, k_chunk, plat = _phase_setup(args)
    persistent = record["decode_mode"] == "persistent"

    import numpy as np

    from torchdistx_tpu import obs
    from torchdistx_tpu.serve import ServeEngine

    # counts every XLA compile, attributed serve/prefill vs serve/decode
    # by the engine's timed_annotation regions; warm-up compiles and
    # steady-state compiles (expected: zero) are reported separately
    watcher = obs.RecompileWatcher()
    try:
        model = _build_model(name, plat)
        limit = model.cfg.max_seq_len
        max_len = args.max_len or min(limit, 8 * args.max_new)
        engine_kw: dict = dict(decode_chunk=k_chunk)
        if persistent:
            engine_kw = dict(decode_mode="persistent", ring_capacity=args.ring)
        engine = ServeEngine(
            model,
            num_slots=args.slots,
            max_len=max_len,
            **engine_kw,
            **_mesh_kwargs(args),
            **_kv_kwargs(args),
        )
        if persistent:
            record["ring_capacity"] = engine.ring_capacity
        rs = np.random.RandomState(0)
        max_prompt = max(1, min(max_len - args.max_new, max_len // 2))
        prompts = [
            rs.randint(0, 256, (int(n),)).astype(np.int32)
            for n in rs.randint(1, max_prompt + 1, args.requests)
        ]

        # Warm every program the workload can reach PAST the
        # donated-carry layout recompile (CLAUDE.md: never time the
        # second call): two requests per reachable prefill bucket, with
        # enough tokens that the decode program dispatches at least
        # twice (k_chunk + 2 => two chunks past the prefill token; the
        # persistent loop dispatches once per run, so the two warm runs
        # per bucket cover its second-call recompile too), then reset
        # metrics so TTFT/prefill/decode histograms measure steady-state
        # dispatch, not XLA compiles.
        warm_new = min(max(3, k_chunk + 2), max_len - max_prompt)
        for b in engine.prefill_buckets:
            plen = max(1, min(b, max_prompt))
            for j in range(2):
                # two SERIAL runs of a two-request batch: the repeat
                # covers the donated-carry second-call recompile even
                # when one persistent loop drains the whole wave, and
                # the simultaneous pair covers the persistent path's
                # chained pending-first-token splice (its second
                # scatter has a different committed-ness signature
                # than the first)
                engine.run([
                    {"prompt": rs.randint(0, 256, (plen,)).astype(np.int32),
                     "max_new_tokens": warm_new,
                     "temperature": args.temperature,
                     "seed": 10**6 + 2 * j + i}
                    for i in range(2)
                ])
            if plen < b:
                break  # larger buckets unreachable by this workload
        engine.reset_metrics()
        record["recompile_warmup"] = watcher.snapshot()
        watcher.reset()  # the measured window must compile NOTHING

        from torchdistx_tpu.obs.comm import comm_audit

        work = [
            {
                "prompt": p,
                "max_new_tokens": args.max_new,
                "temperature": args.temperature,
                "seed": i,
            }
            for i, p in enumerate(prompts)
        ]
        t0 = time.perf_counter()
        with comm_audit() as comm_prof:
            results = engine.run([dict(w) for w in work])
        wall = time.perf_counter() - t0

        # per-phase collective traffic (tdx-comm-v1): the engine's
        # closed-form TP all-reduce accounting — empty at --tp 1
        record["comm"] = comm_prof.to_json()
        record["metrics"] = engine.metrics.to_json()
        _embed_cost(record, engine)
        # compiles DURING the measured window: nonzero means the warm-up
        # missed a program and the timings above include XLA compiles
        record["recompile_measure"] = watcher.snapshot()
        record.update(
            max_len=max_len,
            drain_wall_s=round(wall, 3),
            compiled_programs=engine.num_compiled_programs(),
            prompt_tokens=int(sum(p.size for p in prompts)),
            finish_reasons=sorted({r.finish_reason for r in results}),
            kv_cache_gb=round(engine.cache.nbytes / 1e9, 3),
        )
        tag = "persistent" if persistent else f"k{k_chunk}"
        _dump_obs(record, engine, tag)
        _session_selftest(
            args,
            record,
            model,
            name,
            plat,
            dict(
                num_slots=args.slots,
                max_len=max_len,
                **engine_kw,
                **_mesh_kwargs(args),
                **_kv_kwargs(args),
            ),
            work,
            tag,
        )
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def _child_spec(args) -> None:
    """One leg of the self-speculation A/B: a persistent-loop engine at
    ``speculate=K`` (K=0 compiles the classic persistent program — the
    baseline leg) over a repetition-heavy workload, the shape
    prompt-lookup drafting feeds on (vLLM's ngram speculator makes the
    same bet).  The prompts are period-1..4 cycles and every leg draws
    them from the same seeded stream, so the K legs serve the IDENTICAL
    workload and greedy bit-identity (pinned by tests) makes their
    token streams — and therefore token totals — comparable.  The
    headline is iteration economy: ``accepted_tokens_per_iteration``
    must clear 1.0 (flagged ``error`` here otherwise), and the
    supervisor cross-checks strictly-fewer ``loop_iterations`` plus an
    unchanged ``host_syncs`` against the spec0 leg."""
    spec_k = int(os.environ.get("TDX_SERVE_SPECULATE", "0"))
    record, name, k_chunk, plat = _phase_setup(
        args, phase="speculate", speculate=spec_k, spec_ngram=args.spec_ngram
    )
    record["decode_mode"] = "persistent"

    import numpy as np

    from torchdistx_tpu import obs
    from torchdistx_tpu.serve import ServeEngine

    watcher = obs.RecompileWatcher()
    try:
        model = _build_model(name, plat)
        limit = model.cfg.max_seq_len
        # a cycle only earns acceptance once it has RECURRED in the
        # history: give every request enough budget to get past the
        # first occurrence even on the tiny-model smoke geometry
        spec_new = min(max(args.max_new, 24), limit // 2)
        max_len = args.max_len or min(limit, 8 * spec_new)
        engine_kw: dict = dict(
            decode_mode="persistent", ring_capacity=args.ring
        )
        if spec_k:
            engine_kw.update(speculate=spec_k, spec_ngram=args.spec_ngram)
        engine = ServeEngine(
            model,
            num_slots=args.slots,
            max_len=max_len,
            **engine_kw,
            **_mesh_kwargs(args),
            **_kv_kwargs(args),
        )
        record["ring_capacity"] = engine.ring_capacity
        record["max_new_tokens"] = spec_new
        rs = np.random.RandomState(0)
        max_prompt = max(2, min(max_len - spec_new, max_len // 2))
        prompts = []
        for _ in range(args.requests):
            period = int(rs.randint(1, 5))
            pat = rs.randint(0, 256, (period,)).astype(np.int32)
            plen = int(rs.randint(period + 1, max_prompt + 1))
            prompts.append(np.tile(pat, -(-plen // period))[:plen])

        # warm every reachable program past the donated-carry recompile
        # (CLAUDE.md: never time the second call) — same discipline as
        # the fused/persistent phases
        warm_new = min(8, max_len - max_prompt)
        for b in engine.prefill_buckets:
            plen = max(1, min(b, max_prompt))
            for j in range(2):
                engine.run([
                    {"prompt": rs.randint(0, 256, (plen,)).astype(np.int32),
                     "max_new_tokens": warm_new,
                     "temperature": args.temperature,
                     "seed": 10**6 + 2 * j + i}
                    for i in range(2)
                ])
            if plen < b:
                break
        engine.reset_metrics()
        record["recompile_warmup"] = watcher.snapshot()
        watcher.reset()  # the measured window must compile NOTHING

        from torchdistx_tpu.obs.comm import comm_audit

        work = [
            {
                "prompt": p,
                "max_new_tokens": spec_new,
                "temperature": args.temperature,
                "seed": i,
            }
            for i, p in enumerate(prompts)
        ]
        t0 = time.perf_counter()
        with comm_audit() as comm_prof:
            results = engine.run([dict(w) for w in work])
        wall = time.perf_counter() - t0

        record["comm"] = comm_prof.to_json()
        m = engine.metrics.to_json()
        record["metrics"] = m
        record["accept_rate"] = m["derived"]["accept_rate"]
        record["accepted_tokens_per_iteration"] = m["derived"][
            "accepted_tokens_per_iteration"
        ]
        _embed_cost(record, engine)
        record["recompile_measure"] = watcher.snapshot()
        record.update(
            max_len=max_len,
            drain_wall_s=round(wall, 3),
            compiled_programs=engine.num_compiled_programs(),
            prompt_tokens=int(sum(p.size for p in prompts)),
            finish_reasons=sorted({r.finish_reason for r in results}),
            kv_cache_gb=round(engine.cache.nbytes / 1e9, 3),
        )
        atpi = record["accepted_tokens_per_iteration"]
        if spec_k and not (atpi or 0) > 1.0:
            record["error"] = (
                "speculation accepted no drafts "
                f"(accepted_tokens_per_iteration={atpi})"
            )
        _dump_obs(record, engine, f"spec{spec_k}")
        _session_selftest(
            args,
            record,
            model,
            name,
            plat,
            dict(
                num_slots=args.slots,
                max_len=max_len,
                **engine_kw,
                **_mesh_kwargs(args),
                **_kv_kwargs(args),
            ),
            work,
            f"spec{spec_k}",
        )
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def _child_prefix(args) -> None:
    """The shared-prefix A/B phase: ONE paged engine, the SAME
    repeated-system-prompt burst twice — cold (empty radix index) then
    warm (index populated by the cold pass).  Metrics reset between
    passes, so each pass's ``to_json()`` is self-contained; the headline
    is warm prefill tokens strictly below cold (suffix-only prefill)."""
    record, name, k_chunk, plat = _phase_setup(
        args, phase="prefix_share", page_size=args.page_size
    )

    import numpy as np

    from torchdistx_tpu import obs
    from torchdistx_tpu.serve import ServeEngine

    watcher = obs.RecompileWatcher()
    try:
        model = _build_model(name, plat)
        limit = model.cfg.max_seq_len
        ps = args.page_size
        max_len = args.max_len or min(limit, 8 * args.max_new)
        # paged geometry needs max_len | page_size: round UP (capped at
        # the model limit's own page multiple) — rounding down could
        # zero out a small --max-new budget entirely
        max_len = min(-(-max_len // ps) * ps, limit - limit % ps)
        engine = ServeEngine(
            model,
            num_slots=args.slots,
            max_len=max_len,
            decode_chunk=k_chunk,
            page_size=ps,
            **_mesh_kwargs(args),
            **_kv_kwargs(args),
        )
        # the production shape: every request opens with the same long
        # system prompt, tails differ
        rs = np.random.RandomState(0)
        max_prompt = max(1, min(max_len - args.max_new, max_len // 2))
        sys_len = max(ps, (max_prompt // 2) - (max_prompt // 2) % ps)
        system = rs.randint(0, 256, (sys_len,)).astype(np.int32)
        burst = []
        for i in range(args.requests):
            tail = rs.randint(
                0, 256, (1 + int(rs.randint(0, max(1, max_prompt - sys_len))),)
            ).astype(np.int32)
            burst.append(
                {
                    "prompt": np.concatenate([system, tail])[:max_prompt],
                    "max_new_tokens": args.max_new,
                    "temperature": args.temperature,
                    "seed": i,
                }
            )

        def run_pass():
            engine.reset_metrics()
            t0 = time.perf_counter()
            results = engine.run([dict(r) for r in burst])
            wall = time.perf_counter() - t0
            return {
                "metrics": engine.metrics.to_json(),
                "drain_wall_s": round(wall, 3),
                "finish_reasons": sorted(
                    {r.finish_reason for r in results}
                ),
            }

        # Warm every reachable program past the donated-carry recompile
        # (CLAUDE.md: never time the second call): one throwaway burst
        # compiles the COLD prefill buckets + decode scan, a second
        # compiles the WARM (prefix-hit) prefill family those hits
        # unlock.  Then evict the index back to empty so the timed cold
        # pass is cold of CONTENT while the programs stay compiled —
        # otherwise the warm pass would be charged its own program
        # family's XLA compiles and could read slower than cold.
        engine.run([dict(r) for r in burst])
        engine.run([dict(r) for r in burst])
        engine.prefix_index.evict(engine.pool, engine.pool.capacity)
        record["recompile_warmup"] = watcher.snapshot()
        watcher.reset()  # both timed passes must compile nothing

        from torchdistx_tpu.obs.comm import comm_audit

        with comm_audit() as comm_prof:
            record["cold"] = run_pass()
            record["warm"] = run_pass()
        record["recompile_measure"] = watcher.snapshot()
        # both passes' analytic collective profile (mesh runs)
        record["comm"] = comm_prof.to_json()
        cold_m, warm_m = record["cold"]["metrics"], record["warm"]["metrics"]
        record["tokens_prefilled_cold"] = cold_m["counters"][
            "tokens_prefilled"
        ]
        record["tokens_prefilled_warm"] = warm_m["counters"][
            "tokens_prefilled"
        ]
        record["prefill_calls_cold"] = cold_m["counters"]["prefill_calls"]
        record["prefill_calls_warm"] = warm_m["counters"]["prefill_calls"]
        record["prefix_hit_rate_warm"] = warm_m["derived"]["prefix_hit_rate"]
        record["pages_in_use_hwm"] = warm_m["gauges"]["pages_in_use_hwm"]
        # the phase's whole point: the warm cache must shrink prefill
        # work — surface a broken prefix cache as a phase error so the
        # STRICT nightly fails on it
        if not record["tokens_prefilled_warm"] < record["tokens_prefilled_cold"]:
            record["error"] = (
                "warm prefix cache did not reduce prefill tokens "
                f"({record['tokens_prefilled_warm']} vs "
                f"{record['tokens_prefilled_cold']} cold)"
            )
        # the warm pass's full metrics double as the phase metrics for
        # the shared summary schema
        record["metrics"] = warm_m
        _embed_cost(record, engine)
        _dump_obs(record, engine, "prefix_share")
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def _child_chunked_prefill(args) -> None:
    """The chunked-prefill A/B phase: short requests decoding, then ONE
    long-prompt admission mid-flight — unchunked (the long prefill is a
    single dispatch that stalls every active slot) vs chunked at
    threshold T (the engine interleaves a decode dispatch between
    chunks).  The headline is the short requests' max inter-token gap
    across the admission window, computed from the ``decode_chunk``
    lifecycle events (one host timestamp per dispatch walk); the phase
    flags ``error`` when chunking does not strictly shrink the gap, so
    the STRICT nightly catches a broken interleave.  Token streams must
    be bit-identical between the two engines (chunking may never change
    what a request decodes, only when the host sees it)."""
    t_chunk = int(args.chunked_prefill)
    record, name, k_chunk, plat = _phase_setup(
        args, phase="chunked_prefill", chunked_prefill=t_chunk
    )

    import numpy as np

    from torchdistx_tpu import obs
    from torchdistx_tpu.serve import ServeEngine

    watcher = obs.RecompileWatcher()
    try:
        model = _build_model(name, plat)
        limit = model.cfg.max_seq_len
        max_len = args.max_len or min(limit, 8 * args.max_new)
        if t_chunk >= max_len:
            raise ValueError(
                f"--chunked-prefill {t_chunk} must be < max_len {max_len}"
            )
        # one bucket per side of the threshold: long prompts pad to
        # max_len (the stall being A/B'd), chunks dispatch through the
        # T-bucket program
        buckets = (t_chunk, max_len)
        # geometry: the shorts must still be DECODING through the whole
        # admission window — two settled chunks before the admission
        # (1 + 2K tokens) plus one chunk per interleave — while the long
        # request only needs its first token, so it gets the minimum
        # budget and the longest admissible prompt
        short_len = max(1, t_chunk // 2)
        short_new = min(
            max_len - short_len,
            max(args.max_new, 4 * k_chunk + 4),
        )
        long_new = 2
        long_len = max_len - long_new
        if long_len <= t_chunk:
            raise ValueError(
                f"max_len {max_len} leaves no long prompt above the "
                f"chunk threshold {t_chunk}"
            )
        n_short = max(1, min(args.slots - 1, 4))
        rs = np.random.RandomState(0)
        shorts = [
            rs.randint(0, 256, (short_len,)).astype(np.int32)
            for _ in range(n_short)
        ]
        long_prompt = rs.randint(0, 256, (long_len,)).astype(np.int32)

        def scenario(engine):
            """Shorts first, two settled decode chunks, then the long
            admission; returns (short_results, long_result)."""
            hs = [
                engine.submit(
                    p,
                    max_new_tokens=short_new,
                    temperature=args.temperature,
                    seed=100 + i,
                )
                for i, p in enumerate(shorts)
            ]
            engine.step()
            engine.step()
            t_submit = time.monotonic()
            hl = engine.submit(
                long_prompt,
                max_new_tokens=long_new,
                temperature=args.temperature,
                seed=7,
            )
            while engine.step():
                pass
            return [h.result() for h in hs], hl.result(), t_submit

        def max_gap(short_results, long_result, t_submit):
            """Largest inter-token wall gap of any short request whose
            gap interval overlaps the long request's admission window
            (submit .. first token) — the stall being measured."""
            t_first = next(
                (ts for nm, ts, _ in long_result.events
                 if nm == "first_token"),
                None,
            )
            if t_first is None:
                raise RuntimeError("long request never emitted a token")
            worst = 0.0
            for r in short_results:
                times = [
                    ts
                    for nm, ts, _ in r.events
                    if nm in ("first_token", "decode_chunk")
                ]
                for a, b in zip(times, times[1:]):
                    if b >= t_submit and a <= t_first:
                        worst = max(worst, b - a)
            return worst

        def run_side(chunked: bool):
            engine = ServeEngine(
                model,
                num_slots=args.slots,
                max_len=max_len,
                decode_chunk=k_chunk,
                prefill_buckets=buckets,
                chunked_prefill=t_chunk if chunked else None,
                **_mesh_kwargs(args),
                **_kv_kwargs(args),
            )
            # warm both prefill buckets (+ the chunked warm-prefill
            # program) and the decode program past the donated-carry
            # second-call recompile: the full scenario, twice
            scenario(engine)
            scenario(engine)
            # min over repeats: the structural stall (the long prefill
            # blocking the decode walk) is a FLOOR on the max gap —
            # host noise (GC, scheduler) only ever adds, so the min is
            # the robust estimator and keeps the strict A/B from
            # flaking on tiny CPU-smoke intervals.  Metrics and the
            # comm profile are reset per repeat so the embedded
            # (deterministic, gated) counters cover exactly ONE
            # scenario.
            gap = None
            for _ in range(3):
                engine.reset_metrics()
                watcher.reset()
                with comm_audit() as comm_prof:
                    s, l, t_submit = scenario(engine)
                g = max_gap(s, l, t_submit)
                gap = g if gap is None else min(gap, g)
            return engine, gap, s, l, comm_prof

        from torchdistx_tpu.obs.comm import comm_audit

        eng_a, gap_a, shorts_a, long_a, _ = run_side(chunked=False)
        eng_b, gap_b, shorts_b, long_b, comm_b = run_side(chunked=True)
        record["recompile_measure"] = watcher.snapshot()
        # the chunked side's analytic collective profile (mesh runs)
        record["comm"] = comm_b.to_json()

        record["max_gap_s_unchunked"] = round(gap_a, 6)
        record["max_gap_s_chunked"] = round(gap_b, 6)
        record["gap_reduction"] = round(gap_a / gap_b, 3) if gap_b else None
        mb = eng_b.metrics.to_json()
        record["interleaved_dispatches"] = mb["counters"].get(
            "prefill_interleaved_dispatches", 0
        )
        record["prefill_chunks"] = mb["counters"].get("prefill_chunks", 0)
        streams_equal = all(
            np.array_equal(ra.tokens, rb.tokens)
            for ra, rb in zip(shorts_a, shorts_b)
        ) and np.array_equal(long_a.tokens, long_b.tokens)
        record["streams_identical"] = streams_equal
        record["max_len"] = max_len
        record["long_prompt_tokens"] = int(long_len)
        # the chunked engine's metrics double as the phase metrics
        record["metrics"] = mb
        _embed_cost(record, eng_b)
        if not streams_equal:
            record["error"] = (
                "chunked prefill changed a token stream — interleaving "
                "must be latency-only"
            )
        elif record["interleaved_dispatches"] < 1:
            record["error"] = (
                "chunked prefill never interleaved a decode dispatch "
                f"(long prompt {long_len} tokens, threshold {t_chunk})"
            )
        elif not gap_b < gap_a:
            record["error"] = (
                "chunked prefill did not shrink the admission stall "
                f"(max inter-token gap {gap_b:.4f}s chunked vs "
                f"{gap_a:.4f}s unchunked)"
            )
        _dump_obs(record, eng_b, "chunked_prefill")
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def _child_migrate(args) -> None:
    """The elastic-migration phase (ISSUE 12): a tp=``--tp`` engine is
    drained mid-decode and ``migrate_to()``'d onto a tp=``--migrate-tp-to``
    engine with a different slot count.  The phase flags ``error`` unless
    every request completes (zero drops), the greedy token streams are
    BIT-identical to an undrained run on the source shape, and the
    migration's wire bytes match the ``parallel/reshard.py`` ring closed
    form — the counters land as ledger rows under workload key
    ``mesh_to`` so ``perf_gate.py --strict`` pins each shape pair."""
    tp_to = int(args.migrate_tp_to)
    record, name, k_chunk, plat = _phase_setup(
        args, phase="migrate", mesh_to=tp_to
    )

    import numpy as np

    from torchdistx_tpu.obs.comm import comm_audit
    from torchdistx_tpu.serve import ServeEngine

    try:
        model = _build_model(name, plat)
        limit = model.cfg.max_seq_len
        max_len = args.max_len or min(limit, 8 * args.max_new)
        bucket = 16
        if max_len <= bucket:
            raise ValueError(
                f"max_len {max_len} leaves no decode room past the "
                f"{bucket}-token prefill bucket"
            )
        max_new = min(args.max_new, max_len - bucket)
        n_req = max(2, min(args.requests, args.slots + 2))
        rs = np.random.RandomState(0)
        prompts = [
            rs.randint(0, 256, (int(rs.randint(5, bucket)),)).astype(np.int32)
            for _ in range(n_req)
        ]
        work = [
            dict(prompt=p, max_new_tokens=max_new, temperature=0.0)
            for p in prompts
        ]

        def build(tp, slots):
            return ServeEngine(
                model,
                num_slots=slots,
                max_len=max_len,
                decode_chunk=k_chunk,
                prefill_buckets=(bucket,),
                **_mesh_kwargs(args, tp=tp),
                **_kv_kwargs(args),
            )

        # undrained reference on the source shape: the bit-identity oracle
        ref_tokens = [
            r.tokens for r in build(args.tp, args.slots).run(work)
        ]

        src = build(args.tp, args.slots)
        dst = build(tp_to, args.slots + 1)  # a DIFFERENT slot count
        handles = [src.submit(**w) for w in work]
        # decode just far enough that the drain suspends requests
        # MID-stream (the KV handoff being pinned) — never to completion
        for _ in range(max(1, (max_new - 1) // (2 * k_chunk))):
            src.step()
        t0 = time.monotonic()
        src.drain()
        with comm_audit() as prof:
            summary = src.migrate_to(dst)
        record["migrate_s"] = round(time.monotonic() - t0, 6)
        while dst.step():
            pass

        results = [h.result() for h in handles]
        streams_equal = all(
            np.array_equal(r.tokens, ref)
            for r, ref in zip(results, ref_tokens)
        )
        record["streams_identical"] = streams_equal
        record["migrate_summary"] = summary
        record["max_len"] = max_len
        record["comm"] = prof.to_json()
        # the ring closed form, computed independently of the engine:
        # gather group g = tp_from / gcd(tp_from, tp_to), one all-gather
        # per migrated slot row per layer per cache array at unit*(g-1)/g
        # — summed over the layer's FULL entry (k/v plus the f32 scale
        # arrays of a quantized cache, each at its own dtype width)
        g = max(1, args.tp // int(np.gcd(args.tp, tp_to)))
        expect = (
            summary["migrated_running"]
            * len(src.cache.kv)
            * _kv_entry_wire_bytes(src.cache.kv[0], g)
        )
        # the target finishes the streams, so its metrics are the phase
        # metrics; graft the source-side migration counters in so ONE
        # counter dict carries the whole pinned footprint
        mb = dst.metrics.to_json()
        for cname in ("migration_wire_bytes", "requests_migrated_out"):
            mb["counters"][cname] = src.metrics.counters[cname]
        mb["counters"]["migration_collectives"] = summary["collectives"]
        record["metrics"] = mb
        _embed_cost(record, dst)
        if not streams_equal:
            record["error"] = (
                "migration changed a token stream — the handoff must be "
                "value-exact"
            )
        elif summary["migrated_running"] < 1:
            record["error"] = (
                "nothing was suspended mid-stream — the workload finished "
                "before drain(), so the phase pinned no KV handoff"
            )
        elif any(r.finish_reason != "length" for r in results):
            record["error"] = (
                "a migrated request was dropped or cut short: "
                f"{[r.finish_reason for r in results]}"
            )
        elif summary["wire_bytes"] != expect:
            record["error"] = (
                f"migration wire bytes {summary['wire_bytes']} != ring "
                f"closed form {expect} (tp {args.tp}->{tp_to}, g={g})"
            )
        elif int(prof.wire_bytes()) != summary["wire_bytes"]:
            record["error"] = (
                f"comm audit wire {int(prof.wire_bytes())} disagrees with "
                f"the migration summary {summary['wire_bytes']}"
            )
        _dump_obs(record, dst, "migrate")
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def _child_kv_quant(args) -> None:
    """The ``--kv-dtype`` A/B (ISSUE 17 tentpole evidence): one
    bfloat16-cache baseline engine and one ``--kv-dtype`` engine serve
    the SAME greedy workload, and the phase flags ``error`` unless
    (int8) the ``memory_plan()`` KV pool is EXACTLY halved (the
    double-the-pages factor at a constant byte budget), the greedy
    streams stay within the pinned divergence tolerance against the
    model-dtype oracle (``TDX_KV_QUANT_STREAM_TOL``, mean
    longest-common-prefix fraction), decode tok/s holds the baseline
    (``TDX_KV_QUANT_TOKS_SLACK`` — CPU-smoke timing noise gets slack,
    the TPU leg runs tight), and every decode program's cost-card
    ``bytes_accessed`` is STRICTLY lower than its baseline twin (the
    halved-HBM-traffic claim, priced by XLA, not assumed)."""
    record, name, k_chunk, plat = _phase_setup(args, phase="kv_quant")
    kv_dtype = args.kv_dtype or args.kv_quant_ab or "int8"
    record["kv_dtype"] = kv_dtype

    import numpy as np

    from torchdistx_tpu.serve import ServeEngine

    try:
        model = _build_model(name, plat)
        limit = model.cfg.max_seq_len
        max_len = args.max_len or min(limit, 8 * args.max_new)
        n_req = max(2, min(args.requests, 2 * args.slots))
        rs = np.random.RandomState(5)
        max_prompt = max(1, min(max_len - args.max_new, max_len // 2))
        work = [
            dict(
                prompt=rs.randint(0, 256, (int(n),)).astype(np.int32),
                max_new_tokens=args.max_new,
                temperature=0.0,  # the verdict IS greedy-argmax robustness
            )
            for n in rs.randint(1, max_prompt + 1, n_req)
        ]
        record["max_len"] = max_len

        def build(kv):
            # kv=None is the MODEL-dtype oracle — never fall back to
            # --kv-dtype here (that leg must stay unquantized)
            return ServeEngine(
                model,
                num_slots=args.slots,
                max_len=max_len,
                decode_chunk=k_chunk,
                kv_dtype=kv,
                **_mesh_kwargs(args),
            )

        def measure(engine):
            # warm past the donated-carry second-call recompile (two
            # serial runs), then measure steady-state dispatch only
            for _ in range(2):
                engine.run([dict(w) for w in work])
            engine.reset_metrics()
            out = engine.run([dict(w) for w in work])
            return [r.tokens for r in out]

        base = build("bfloat16")
        quant = build(kv_dtype)
        base_tokens = measure(base)
        quant_tokens = measure(quant)

        # the divergence oracle is the MODEL-dtype cache (f32 on the CPU
        # smoke); when the model already runs bf16 the baseline IS the
        # oracle and the third run would duplicate it
        if base.cache.kv[0][0].dtype == np.dtype(model.cfg.dtype):
            ref_tokens = base_tokens
        else:
            ref_tokens = measure(build(None))

        def lcp_frac(a, b):
            a, b = np.asarray(a), np.asarray(b)
            n = min(a.size, b.size)
            neq = np.nonzero(a[:n] != b[:n])[0]
            lcp = int(neq[0]) if neq.size else n
            return lcp / max(1, max(a.size, b.size))

        fracs = [lcp_frac(q, r) for q, r in zip(quant_tokens, ref_tokens)]
        agreement = float(np.mean(fracs)) if fracs else 1.0
        identical = sum(
            np.array_equal(q, r) for q, r in zip(quant_tokens, ref_tokens)
        )
        record["stream_prefix_agreement"] = round(agreement, 4)
        record["streams_identical_frac"] = round(identical / n_req, 4)

        plan_base = base.memory_plan()
        plan_quant = quant.memory_plan()
        record["memory_plan"] = plan_quant
        record["memory_plan_baseline"] = plan_base
        kv_base = plan_base["components"]["kv_cache"]
        kv_quant = plan_quant["components"]["kv_cache"]
        # data-plane halving == doubled page capacity at a constant HBM
        # budget; the f32 scale sidecar is priced separately (kv_scales)
        record["kv_bytes_factor"] = round(kv_base / kv_quant, 4)

        mb = base.metrics.to_json()
        mq = quant.metrics.to_json()
        record["metrics"] = mq
        record["metrics_baseline"] = mb
        toks_base = (mb["derived"] or {}).get("decode_tokens_per_sec")
        toks_quant = (mq["derived"] or {}).get("decode_tokens_per_sec")
        record["decode_tokens_per_sec_baseline"] = toks_base

        _embed_cost(record, quant)
        cards_base = base.cost_book.to_json()
        cards_quant = quant.cost_book.to_json()
        decode_bytes = {}
        for prog, cq in sorted(cards_quant.items()):
            if not prog.startswith("serve/decode"):
                continue
            cb = cards_base.get(prog) or {}
            decode_bytes[prog] = {
                "bytes_accessed": cq.get("bytes_accessed"),
                "bytes_accessed_baseline": cb.get("bytes_accessed"),
            }
        record["decode_bytes_accessed"] = decode_bytes

        stream_tol = float(
            os.environ.get("TDX_KV_QUANT_STREAM_TOL", "0.5")
        )
        # CPU interpret-mode dequant is real ALU work with no HBM saving
        # to offset it (and tiny-workload timings are noisy), so the CPU
        # smoke gets a sanity floor; the TPU leg — where the halved HBM
        # read is the point — runs tight
        toks_slack = float(
            os.environ.get(
                "TDX_KV_QUANT_TOKS_SLACK",
                "0.5" if record["platform"] == "cpu" else "0.05",
            )
        )
        record["stream_tol"] = stream_tol
        record["toks_slack"] = toks_slack
        not_priced = [
            p
            for p, d in decode_bytes.items()
            if not (
                d["bytes_accessed"] and d["bytes_accessed_baseline"]
            )
        ]
        if kv_dtype == "int8" and kv_quant * 2 != kv_base:
            record["error"] = (
                f"int8 KV pool {kv_quant} B is not exactly half the "
                f"bfloat16 pool {kv_base} B in memory_plan()"
            )
        elif agreement < stream_tol:
            record["error"] = (
                f"greedy stream prefix agreement {agreement:.3f} below "
                f"the pinned tolerance {stream_tol}"
            )
        elif not (toks_base and toks_quant):
            record["error"] = "a leg produced no decode throughput figure"
        elif toks_quant < toks_base * (1.0 - toks_slack):
            record["error"] = (
                f"quantized decode {toks_quant:.1f} tok/s fell below the "
                f"baseline {toks_base:.1f} beyond the {toks_slack} slack"
            )
        elif not decode_bytes:
            record["error"] = (
                "no decode cost cards — the bytes_accessed verdict has "
                "no evidence (is TDX_COST_CARDS off?)"
            )
        elif not_priced:
            record["error"] = (
                f"decode programs missing bytes_accessed: {not_priced}"
            )
        elif not all(
            d["bytes_accessed"] < d["bytes_accessed_baseline"]
            for d in decode_bytes.values()
        ):
            worst = {
                p: (d["bytes_accessed"], d["bytes_accessed_baseline"])
                for p, d in decode_bytes.items()
                if d["bytes_accessed"] >= d["bytes_accessed_baseline"]
            }
            record["error"] = (
                "a quantized decode program reads at least as many bytes "
                f"as its bfloat16 twin: {worst}"
            )
        _dump_obs(record, quant, "kv_quant")
        # record + self-replay the QUANTIZED leg (the one the verdict
        # rides on); record["metrics"] is the quant leg's counters, so
        # the zero-overhead comparison lines up
        _session_selftest(
            args,
            record,
            model,
            name,
            plat,
            dict(
                num_slots=args.slots,
                max_len=max_len,
                decode_chunk=k_chunk,
                kv_dtype=kv_dtype,
                **_mesh_kwargs(args),
            ),
            work,
            "kv_quant",
        )
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def _child_numerics(args) -> None:
    """The numerics-observatory A/B (ISSUE 19 tentpole evidence): one
    digest-off engine and one digest-on engine serve the SAME greedy
    workload, and the phase flags ``error`` unless the streams are
    bit-identical AND every deterministic engine counter is EXACTLY
    equal — digests fuse into the existing jitted programs as one extra
    trailing output and harvest only at existing sync boundaries, so
    enabling them must change neither ``host_syncs`` nor
    ``decode_dispatches`` nor anything else countable.  The on-leg's
    digest book (``tdx-numerics-v1``) is embedded whole; its integer
    fields are reduction-order-invariant counts, so the ledger rows
    they become gate bit-identically across runs in ``perf_gate
    --strict``."""
    record, name, k_chunk, plat = _phase_setup(
        args, phase="numerics", numerics=True
    )

    import numpy as np

    from torchdistx_tpu.serve import ServeEngine

    try:
        model = _build_model(name, plat)
        limit = model.cfg.max_seq_len
        max_len = args.max_len or min(limit, 8 * args.max_new)
        n_req = max(2, min(args.requests, 2 * args.slots))
        rs = np.random.RandomState(5)
        max_prompt = max(1, min(max_len - args.max_new, max_len // 2))
        work = [
            dict(
                prompt=rs.randint(0, 256, (int(n),)).astype(np.int32),
                max_new_tokens=args.max_new,
                temperature=0.0,  # the verdict is bit-identity
            )
            for n in rs.randint(1, max_prompt + 1, n_req)
        ]
        record["max_len"] = max_len

        def build(numerics):
            return ServeEngine(
                model,
                num_slots=args.slots,
                max_len=max_len,
                decode_chunk=k_chunk,
                numerics=numerics,
                **_mesh_kwargs(args),
                **_kv_kwargs(args),
            )

        def measure(engine):
            for _ in range(2):  # warm past the donated-carry recompile
                engine.run([dict(w) for w in work])
            engine.reset_metrics()
            out = engine.run([dict(w) for w in work])
            return [r.tokens for r in out]

        off = build(False)
        on = build(True)
        off_tokens = measure(off)
        on_tokens = measure(on)

        m_off = off.metrics.to_json()
        m_on = on.metrics.to_json()
        record["metrics"] = m_on
        record["metrics_baseline"] = m_off
        book = on.numerics_book
        record["numerics_book"] = book.to_json()
        record["numerics_sites"] = book.sites()
        _embed_cost(record, on)

        identical = all(
            np.array_equal(a, b) for a, b in zip(on_tokens, off_tokens)
        )
        c_off = m_off.get("counters") or {}
        c_on = m_on.get("counters") or {}
        unequal = {
            k: (c_on.get(k), c_off.get(k))
            for k in sorted(set(c_off) | set(c_on))
            if c_on.get(k) != c_off.get(k)
        }
        bad_sites = [
            s
            for s, d in (record["numerics_book"].get("sites") or {}).items()
            if d["count"]
            != d["nonfinite"] + d["zeros"] + sum(d["exp_hist"])
        ]
        if not identical:
            record["error"] = (
                "enabling digests changed a sampled stream — taps must "
                "be identities"
            )
        elif unequal:
            record["error"] = (
                "enabling digests moved engine counters (on vs off): "
                f"{unequal}"
            )
        elif not book.sites():
            record["error"] = (
                "digest-on engine harvested no sites — is the tape "
                "wired into the programs?"
            )
        elif book.digest("logits") is None:
            record["error"] = (
                f"no 'logits' digest (sites: {book.sites()})"
            )
        elif bad_sites:
            record["error"] = (
                "digest partition identity violated (count != nonfinite "
                f"+ zeros + sum(exp_hist)) at: {bad_sites}"
            )
        elif book.first_nonfinite_site() is not None:
            record["error"] = (
                "healthy workload digested a nonfinite at "
                f"{book.first_nonfinite_site()}"
            )
        _dump_obs(record, on, "numerics")
        # record + self-replay the digest-ON leg; numerics is not a
        # geometry field (digests are counter-neutral by ISSUE 19's
        # contract), so the replay engine rebuilds digest-on via the
        # phase kwargs and must still chain bit-identically
        _session_selftest(
            args,
            record,
            model,
            name,
            plat,
            dict(
                num_slots=args.slots,
                max_len=max_len,
                decode_chunk=k_chunk,
                numerics=True,
                **_mesh_kwargs(args),
                **_kv_kwargs(args),
            ),
            work,
            "numerics",
        )
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def _slo_spec(args):
    """The committed ``--slo`` spec, parsed per use (cheap; children are
    one-shot processes).  None without the flag."""
    if not getattr(args, "slo", None):
        return None
    from torchdistx_tpu.obs.slo import SloSpec

    return SloSpec.from_json(args.slo)


def _eval_slo(args, requests, policy=None):
    """Evaluate the ``--slo`` spec over finished requests into a
    ``tdx-slo-v1`` report (obs/slo.py) — a breached evaluation also
    lands a named ``slo_burn`` flight event in the global recorder.
    None without ``--slo``."""
    spec = _slo_spec(args)
    if spec is None:
        return None
    from torchdistx_tpu.obs.slo import evaluate_slo

    return evaluate_slo(spec, requests, policy=policy)


def _maybe_slo_error(args, record: dict) -> None:
    """``--slo-strict``: a breached report (or a burning window — the
    same condition that fires the flight event) becomes the phase
    ``error``, which the parent's strict path turns into a nonzero
    exit.  A phase already in error keeps its original cause."""
    if not getattr(args, "slo_strict", False) or "error" in record:
        return
    slo = record.get("slo") or {}
    reports = (
        [slo]
        if "schema" in slo
        else [v for v in slo.values() if isinstance(v, dict) and "schema" in v]
    )
    bad = [
        r
        for r in reports
        if r.get("breached") or (r.get("burn") or {}).get("state") != "ok"
    ]
    if bad:
        detail = "; ".join(
            f"{(r.get('spec') or {}).get('name', '?')}"
            f"[{r.get('policy') or '-'}]: attainment="
            f"{(r.get('attainment') or {}).get('overall')} "
            f"target={(r.get('attainment') or {}).get('target')} "
            f"state={(r.get('burn') or {}).get('state')} "
            f"axes={r.get('breached_axes')}"
            for r in bad
        )
        record["error"] = f"SLO breached under --slo-strict: {detail}"


def _dump_obs_fleet(
    record: dict, fleet, tag: str, slo_spec=None, collectors=()
) -> None:
    """``_dump_obs`` for a whole fleet: ONE scrape surface — the
    exposition renders the fleet collector (replica-summed
    ``tdx_serve_*_total`` counters, so ``check_obs_artifacts`` validates
    them against the embedded aggregate ``metrics`` exactly as for a
    single engine, plus per-replica ``tdx_fleet_*`` gauges and latency
    quantile summaries, plus — with ``--slo`` — the ``tdx_slo_*``
    projection) — and ONE merged Perfetto trace
    (``fleet.dump_trace``): per-replica process tracks with every
    request's route/queued/prefill/handoff/decode spans flow-linked on
    its ``trace_id``, retired replicas included."""
    out_dir = os.environ.get("TDX_SERVE_TRACE_DIR")
    if not out_dir:
        return
    from torchdistx_tpu import obs

    os.makedirs(out_dir, exist_ok=True)
    trace_path = os.path.join(out_dir, f"{tag}_trace.json")
    fleet.dump_trace(trace_path)
    finished = fleet.finished_requests()
    record["trace_path"] = trace_path
    record["trace_summary"] = {
        "requests": len(finished),
        "lifecycle_events": sum(len(r.events) for r in finished),
        "tracer_spans": len(obs.get_tracer().events()),
    }
    rep = max(
        fleet.replicas, key=lambda r: len(r.engine.finished_requests())
    )
    registry = obs.MetricsRegistry()
    registry.register_collector(fleet.collector())
    registry.register_collector(rep.engine.cost_book.collector())
    for extra in collectors:
        # e.g. the AutoscaleController's tdx_autoscale_* family — the
        # scale loop scrapes from the SAME surface as the fleet
        registry.register_collector(extra)
    if slo_spec is not None:
        registry.register_collector(
            obs.slo_collector(slo_spec, fleet), obj=fleet
        )
    prom_path = os.path.join(out_dir, f"{tag}_metrics.prom")
    with open(prom_path, "w") as f:
        f.write(registry.render())
    record["metrics_prom_path"] = prom_path


def _fleet_workload(args, n_replicas: int, page_size: int, bucket: int):
    """The shared-prefix arrival stream of the fleet A/B: n_replicas + 1
    prefix groups (one MORE group than replicas, so round-robin can
    never accidentally colocate every group) arriving interleaved —
    request k belongs to group k % groups.  Prefixes are page-aligned
    (two pages each) so a follower's radix match is exact."""
    import numpy as np

    groups = n_replicas + 1
    rs = np.random.RandomState(0)
    prefix_len = 2 * page_size
    prefixes = [
        rs.randint(0, 256, (prefix_len,)).astype(np.int32)
        for _ in range(groups)
    ]
    work = []
    for k in range(args.requests):
        tail = rs.randint(
            0, 256, (1 + int(rs.randint(0, bucket - prefix_len)),)
        ).astype(np.int32)
        work.append(
            {
                "prompt": np.concatenate([prefixes[k % groups], tail])[
                    :bucket
                ],
                "max_new_tokens": None,  # filled by the caller
                "temperature": args.temperature,
                "seed": k,
            }
        )
    return work, groups


def _child_fleet(args) -> None:
    """The fleet routing A/B (ISSUE 13 tentpole): the SAME shared-prefix
    arrival stream through an N-replica ``ServeFleet`` twice — affinity
    (read-only ``match_len`` warmth, headroom tie-break) vs round-robin
    — with fresh engines per policy.  Requests arrive online (one
    ``submit`` + one ``step`` each), so affinity sees the caches its own
    earlier routing warmed.  STRICT errors unless BOTH policies' greedy
    streams are bit-identical to one engine serving the same requests
    (routing decides where, never what) AND affinity's aggregate
    ``prefix_hit_rate`` strictly beats round-robin's."""
    n = int(args.fleet)
    ps = 4  # small pages so a 16-token-bucket prompt spans whole pages
    record, name, k_chunk, plat = _phase_setup(
        args, phase="fleet", fleet=n, page_size=ps
    )

    import numpy as np

    from torchdistx_tpu.serve import ServeEngine, ServeFleet

    try:
        model = _build_model(name, plat)
        limit = model.cfg.max_seq_len
        bucket = 16
        max_len = args.max_len or min(limit, 8 * args.max_new)
        max_len = min(-(-max_len // ps) * ps, limit - limit % ps)
        max_new = min(args.max_new, max_len - bucket)
        work, groups = _fleet_workload(args, n, ps, bucket)
        for w in work:
            w["max_new_tokens"] = max_new
        record["max_len"] = max_len
        record["prefix_groups"] = groups

        def build():
            return ServeEngine(
                model,
                num_slots=args.slots,
                max_len=max_len,
                decode_chunk=k_chunk,
                prefill_buckets=(bucket,),
                page_size=ps,
                **_mesh_kwargs(args),
                **_kv_kwargs(args),
            )

        # the bit-identity oracle: one engine, same requests
        ref_tokens = [r.tokens for r in build().run([dict(w) for w in work])]

        def run_policy(policy):
            fleet = ServeFleet([build() for _ in range(n)], policy=policy)
            t0 = time.perf_counter()
            handles = []
            for w in work:  # online arrival: submit, then one tick
                handles.append(fleet.submit(**dict(w)))
                fleet.step()
            while fleet.step():
                pass
            wall = time.perf_counter() - t0
            results = [h.result() for h in handles]
            ttft = sorted(
                s
                for rep in fleet.replicas
                for s in rep.engine.metrics.ttft_s._samples
            )
            return fleet, {
                "streams": [r.tokens for r in results],
                "hit_rate": fleet.metrics_json()["derived"][
                    "prefix_hit_rate"
                ],
                "ttft_p50_s": (
                    round(ttft[len(ttft) // 2], 6) if ttft else None
                ),
                "wall_s": round(wall, 3),
            }

        fleet_rr, rr = run_policy("round-robin")
        fleet_aff, aff = run_policy("affinity")
        streams_equal = all(
            np.array_equal(s, ref)
            for side in (rr, aff)
            for s, ref in zip(side["streams"], ref_tokens)
        )
        record["streams_identical"] = streams_equal
        record["prefix_hit_rate_affinity"] = aff["hit_rate"]
        record["prefix_hit_rate_round_robin"] = rr["hit_rate"]
        record["ttft_p50_s_affinity"] = aff["ttft_p50_s"]
        record["ttft_p50_s_round_robin"] = rr["ttft_p50_s"]
        record["drain_wall_s"] = aff["wall_s"]
        record["routed_per_replica_affinity"] = [
            r["requests_routed"]
            for r in fleet_aff.metrics_json()["fleet"]["replicas"]
        ]
        # the affinity fleet's aggregate is the phase metrics: its
        # counters (hit/lookup tokens included) are the pinned rows
        record["metrics"] = fleet_aff.metrics_json()
        # the SLO-attainment axis of the A/B: one tdx-slo-v1 report per
        # policy, each over that fleet's own finished-request history
        slo_aff = _eval_slo(
            args, fleet_aff.finished_requests(), policy="affinity"
        )
        if slo_aff is not None:
            record["slo"] = {
                "affinity": slo_aff,
                "round_robin": _eval_slo(
                    args,
                    fleet_rr.finished_requests(),
                    policy="round_robin",
                ),
            }
        busiest = max(
            fleet_aff.replicas,
            key=lambda r: len(r.engine.finished_requests()),
        )
        _embed_cost(record, busiest.engine)
        if not streams_equal:
            record["error"] = (
                "a fleet-routed stream diverged from the single-engine "
                "oracle — routing must decide where, never what"
            )
        elif not (
            aff["hit_rate"] is not None
            and rr["hit_rate"] is not None
            and aff["hit_rate"] > rr["hit_rate"]
        ):
            record["error"] = (
                f"affinity prefix_hit_rate {aff['hit_rate']} does not "
                f"strictly beat round-robin {rr['hit_rate']}"
            )
        _maybe_slo_error(args, record)
        _dump_obs_fleet(record, fleet_aff, "fleet", slo_spec=_slo_spec(args))
        _session_selftest_fleet(
            args, record, model, name, plat, build, work, "fleet"
        )
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def _child_fleet_drain(args) -> None:
    """The fleet scale-event leg: N replicas mid-workload, one
    ``fleet.remove()`` — the victim drains and its in-flight requests
    redistribute into the survivors (whole-engine ``migrate_to`` fast
    path, or per-request scatter when no single survivor fits).  STRICT
    errors unless every request completes with streams bit-identical to
    an undisturbed single engine — zero drops."""
    n = int(args.fleet)
    record, name, k_chunk, plat = _phase_setup(
        args, phase="fleet_drain", fleet=n
    )

    import numpy as np

    from torchdistx_tpu.serve import ServeEngine, ServeFleet

    try:
        model = _build_model(name, plat)
        limit = model.cfg.max_seq_len
        bucket = 16
        max_len = args.max_len or min(limit, 8 * args.max_new)
        max_new = min(args.max_new, max_len - bucket)
        # scale-down needs headroom: cap the in-flight load at what the
        # survivors can absorb ((n-1) replicas x slots), or the victim's
        # requests would have nowhere to land until slots free up
        n_req = max(2, min(args.requests, (n - 1) * args.slots))
        rs = np.random.RandomState(1)
        work = [
            dict(
                prompt=rs.randint(
                    0, 256, (int(rs.randint(5, bucket)),)
                ).astype(np.int32),
                max_new_tokens=max_new,
                temperature=0.0,
            )
            for _ in range(n_req)
        ]
        record["max_len"] = max_len

        def build():
            return ServeEngine(
                model,
                num_slots=args.slots,
                max_len=max_len,
                decode_chunk=k_chunk,
                prefill_buckets=(bucket,),
                **_mesh_kwargs(args),
                **_kv_kwargs(args),
            )

        ref_tokens = [r.tokens for r in build().run([dict(w) for w in work])]

        fleet = ServeFleet([build() for _ in range(n)], policy="round-robin")
        handles = [fleet.submit(**dict(w)) for w in work]
        # decode just far enough that the remove() lands MID-stream
        for _ in range(max(1, (max_new - 1) // (2 * k_chunk))):
            fleet.step()
        victim = fleet.replicas[0]
        if not victim.engine.scheduler.has_work():
            raise RuntimeError(
                "the victim replica holds no in-flight work — nothing "
                "to redistribute"
            )
        t0 = time.monotonic()
        summary = fleet.remove(victim.rid)
        record["remove_s"] = round(time.monotonic() - t0, 6)
        while fleet.step():
            pass
        results = [h.result() for h in handles]
        streams_equal = all(
            np.array_equal(r.tokens, ref)
            for r, ref in zip(results, ref_tokens)
        )
        record["streams_identical"] = streams_equal
        record["remove_summary"] = {
            k: v for k, v in summary.items() if k != "to"
        }
        # retired-replica counters stay in the fleet aggregate (the
        # scrape surface is monotonic), so migration counters are
        # pinnable straight off the embedded metrics
        record["metrics"] = fleet.metrics_json()
        slo_rep = _eval_slo(args, fleet.finished_requests())
        if slo_rep is not None:
            record["slo"] = slo_rep
        busiest = max(
            fleet.replicas,
            key=lambda r: len(r.engine.finished_requests()),
        )
        _embed_cost(record, busiest.engine)
        if not streams_equal:
            record["error"] = (
                "fleet.remove() changed a token stream — the "
                "redistribution must be value-exact"
            )
        elif any(r.finish_reason != "length" for r in results):
            record["error"] = (
                "a request was dropped or cut short across the remove: "
                f"{[r.finish_reason for r in results]}"
            )
        elif (
            summary["migrated_running"] + summary["migrated_queued"] < 1
        ):
            record["error"] = (
                "the victim held nothing by remove() time — the leg "
                "pinned no redistribution"
            )
        _maybe_slo_error(args, record)
        _dump_obs_fleet(
            record, fleet, "fleet_drain", slo_spec=_slo_spec(args)
        )
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def _child_fleet_disagg(args) -> None:
    """The disaggregated fleet leg: a prefill engine on a 2-device
    ('tp',) mesh and a single-chip decode engine behind the router.
    Every request prefills on the prefill role, hands its KV slab row to
    the decode role (explicit head-axis redistribution: tp=2 -> tp=1 is
    gather group g=2), and decodes there.  STRICT errors unless streams
    are bit-identical to a co-located engine, every request handed off
    exactly once, and the handoff wire bytes equal the
    ``parallel/reshard.py`` ring closed form — summary == comm audit ==
    counters."""
    n = int(args.fleet) if args.fleet else 2
    record, name, k_chunk, plat = _phase_setup(
        args, phase="fleet_disagg", fleet=2, disaggregate=True
    )

    import numpy as np

    from torchdistx_tpu.obs.comm import comm_audit
    from torchdistx_tpu.serve import ServeEngine, ServeFleet

    try:
        del n  # the leg is always 1 prefill + 1 decode
        model = _build_model(name, plat)
        limit = model.cfg.max_seq_len
        bucket = 16
        max_len = args.max_len or min(limit, 8 * args.max_new)
        max_new = min(args.max_new, max_len - bucket)
        n_req = max(2, min(args.requests, args.slots + 2))
        rs = np.random.RandomState(2)
        work = [
            dict(
                prompt=rs.randint(
                    0, 256, (int(rs.randint(5, bucket)),)
                ).astype(np.int32),
                max_new_tokens=max_new,
                temperature=0.0,
            )
            for _ in range(n_req)
        ]
        record["max_len"] = max_len

        def build(tp):
            return ServeEngine(
                model,
                num_slots=args.slots,
                max_len=max_len,
                decode_chunk=k_chunk,
                prefill_buckets=(bucket,),
                **_mesh_kwargs(args, tp=tp),
                **_kv_kwargs(args),
            )

        ref_tokens = [
            r.tokens for r in build(1).run([dict(w) for w in work])
        ]
        tp_pre, tp_dec = 2, 1
        pre, dec = build(tp_pre), build(tp_dec)
        fleet = ServeFleet(
            [pre, dec], disaggregate=True, roles=["prefill", "decode"]
        )
        with comm_audit() as prof:
            results = fleet.run(
                [dict(w) for w in work], max_new_tokens=max_new
            )
        streams_equal = all(
            np.array_equal(r.tokens, ref)
            for r, ref in zip(results, ref_tokens)
        )
        record["streams_identical"] = streams_equal
        record["comm"] = prof.to_json()
        # the ring closed form, computed independently of the engine —
        # per-array dtype widths over the full entry tuple, so a
        # quantized pool prices int8 data + f32 scales exactly
        g = max(1, tp_pre // int(np.gcd(tp_pre, tp_dec)))
        expect = (
            n_req
            * len(pre.cache.kv)
            * _kv_entry_wire_bytes(pre.cache.kv[0], g)
        )
        record["handoff_wire_bytes_expected"] = expect
        record["metrics"] = fleet.metrics_json()
        slo_rep = _eval_slo(args, fleet.finished_requests())
        if slo_rep is not None:
            record["slo"] = slo_rep
        c = record["metrics"]["counters"]
        _embed_cost(record, dec)
        if not streams_equal:
            record["error"] = (
                "disaggregated streams diverged from the co-located "
                "oracle — the handoff must be value-exact"
            )
        elif c.get("requests_handed_off") != n_req:
            record["error"] = (
                f"{c.get('requests_handed_off')} handoffs for {n_req} "
                "requests — every request must hand off exactly once"
            )
        elif c.get("handoff_wire_bytes") != expect:
            record["error"] = (
                f"handoff wire bytes {c.get('handoff_wire_bytes')} != "
                f"ring closed form {expect} (tp {tp_pre}->{tp_dec}, "
                f"g={g})"
            )
        elif int(prof.wire_bytes("all_gather", "tp")) != expect:
            record["error"] = (
                f"comm audit wire {int(prof.wire_bytes('all_gather', 'tp'))} "
                f"disagrees with the closed form {expect}"
            )
        _maybe_slo_error(args, record)
        _dump_obs_fleet(
            record, fleet, "fleet_disagg", slo_spec=_slo_spec(args)
        )
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def _child_autoscale(args) -> None:
    """The closed-loop autoscale A/B (ISSUE 16 tentpole): one
    deterministic open-loop scenario (serve/workload.py — every sample
    from the utils/rng.py counter stream, so same seed => bit-identical
    arrival stream) replayed tick-for-tick through every STATIC fleet
    size the policy allows and through a fleet driven by an
    ``AutoscaleController``.  Attainment and cost are measured in fleet
    TICKS (finish_tick - arrival_tick <= deadline_ticks; cost =
    replica-ticks), so the verdict is wall-clock-free and the counter
    rows pin exactly.  STRICT errors unless autoscaling strictly beats
    every static of equal-or-lower cost on attainment, no static
    dominates it, at least one scale-up AND one scale-down executed,
    and every stream (static and autoscaled) is bit-identical to the
    single-engine oracle — scaling decides capacity, never tokens."""
    sc_name = os.environ["TDX_SERVE_SCENARIO"]
    policy_arg = args.autoscale or "default"
    record, name, _k, plat = _phase_setup(
        args, phase=f"autoscale_{sc_name}", scenario=sc_name,
        autoscale=policy_arg,
    )

    import numpy as np

    from torchdistx_tpu import obs
    from torchdistx_tpu.serve import (
        AutoscaleController,
        ScalingPolicy,
        ServeEngine,
        ServeFleet,
        generate,
        scenario,
        workload_counters,
    )

    try:
        policy = ScalingPolicy.from_json(policy_arg)
        spec = scenario(sc_name)
        work = generate(spec)
        model = _build_model(name, plat)
        limit = model.cfg.max_seq_len
        # geometry pinned to the scenario's token envelope (NOT the
        # sweep's --decode-chunk/--slots): the catalog's arrival rates
        # are calibrated against this capacity, so the A/B's pressure
        # dynamics must not drift with unrelated CLI knobs
        bucket = -(-spec.max_prompt_len // 8) * 8
        max_len = bucket + spec.max_output_len
        if max_len > limit:
            raise RuntimeError(
                f"scenario {sc_name} needs max_len {max_len} > model "
                f"limit {limit}"
            )
        slots, k_chunk = 2, 4
        record.update(
            decode_chunk=k_chunk,
            num_slots=slots,
            requests=len(work),
            max_len=max_len,
            scenario_spec=spec.to_json(),
            policy=policy.to_json(),
        )

        def build(role="serve"):
            return ServeEngine(
                model,
                num_slots=slots,
                max_len=max_len,
                decode_chunk=k_chunk,
                prefill_buckets=(bucket,),
                **_mesh_kwargs(args),
                **_kv_kwargs(args),
            )

        watcher = obs.RecompileWatcher()
        # the bit-identity oracle compiles every program the replays can
        # reach (both donated-carry call signatures included): engines
        # share the model-level jit store, so the A/B fleets below —
        # and the controller's warmed mid-replay adds — dispatch
        # compile-free
        ref_tokens = [
            r.tokens for r in build().run([w.submit_kwargs() for w in work])
        ]
        record["recompile_warmup"] = watcher.snapshot()
        watcher.reset()  # the measured replays must compile NOTHING

        def replay(fleet, ctrl=None):
            """Open-loop tick replay: submissions between step N and
            N+1 carry arrival tick N (the fleet.tick contract), one
            controller evaluation per fleet tick."""
            handles, finish_tick, i, tick = {}, {}, 0, 0
            while i < len(work) or any(
                not h.done() for h in handles.values()
            ):
                while i < len(work) and work[i].arrival_tick <= tick:
                    handles[i] = fleet.submit(**work[i].submit_kwargs())
                    i += 1
                fleet.step()
                tick = fleet.tick
                if ctrl is not None:
                    ctrl.tick()
                for k, h in handles.items():
                    if k not in finish_tick and h.done():
                        finish_tick[k] = tick
            streams_ok = len(handles) == len(work) and all(
                np.array_equal(handles[k].result().tokens, ref_tokens[k])
                for k in range(len(work))
            )
            attained = sum(
                1
                for k, ft in finish_tick.items()
                if ft - work[k].arrival_tick <= work[k].deadline_ticks
            )
            return attained, tick, streams_ok

        statics = {}
        for n in range(policy.min_replicas, policy.max_replicas + 1):
            att, ticks, s_ok = replay(
                ServeFleet([build() for _ in range(n)])
            )
            statics[n] = {
                "attained": att,
                "replica_ticks": n * ticks,
                "ticks": ticks,
                "streams_identical": s_ok,
            }

        fleet_auto = ServeFleet(
            [build() for _ in range(policy.min_replicas)]
        )
        ctrl = AutoscaleController(
            fleet_auto, policy, engine_factory=build
        )
        att_auto, ticks_auto, auto_ok = replay(fleet_auto, ctrl)
        record["recompile_measure"] = watcher.snapshot()

        auto_cost = ctrl.counters["autoscale_replica_ticks"]
        ups = ctrl.counters["autoscale_scale_ups"]
        downs = ctrl.counters["autoscale_scale_downs"]
        streams_equal = auto_ok and all(
            s["streams_identical"] for s in statics.values()
        )
        comparable = {
            n: s
            for n, s in statics.items()
            if s["replica_ticks"] <= auto_cost
        }
        dominated = any(
            s["attained"] >= att_auto and s["replica_ticks"] <= auto_cost
            for s in statics.values()
        )
        verdict_ok = (
            streams_equal
            and bool(comparable)
            and all(
                att_auto > s["attained"] for s in comparable.values()
            )
            and not dominated
            and ups >= 1
            and downs >= 1
        )
        record["autoscale_verdict"] = {
            "ok": verdict_ok,
            "requests": len(work),
            "attained_autoscale": att_auto,
            "replica_ticks_autoscale": auto_cost,
            "ticks_autoscale": ticks_auto,
            "attained_static": {
                str(n): s["attained"] for n, s in statics.items()
            },
            "replica_ticks_static": {
                str(n): s["replica_ticks"] for n, s in statics.items()
            },
            "scale_ups": ups,
            "scale_downs": downs,
            "reroles": ctrl.counters["autoscale_reroles"],
            "streams_identical": streams_equal,
        }
        # every scale decision with its FULL signal vector — the
        # flight recorder and check_obs_artifacts --autoscale read the
        # same stream from the record
        record["scale_events"] = [
            data for ev, _ts, data in fleet_auto.events if ev == "scale"
        ]
        # the pinned counter rows: the autoscaled fleet's aggregate
        # stays pure in ``metrics`` (its exposition projection is
        # exact-gated), while the controller's decision counters, the
        # workload's exact shape, and both sides' tick-space A/B axes
        # ride in ``autoscale_metrics`` (ints only — the ledger ingests
        # both blocks and perf_gate --strict holds every row exactly)
        record["metrics"] = fleet_auto.metrics_json()
        ab = dict(workload_counters(work))
        ab.update(ctrl.counters)
        # NOT autoscale_-prefixed: that namespace is reserved for the
        # controller counters the tdx_autoscale_* exposition projects
        ab["attained_requests_auto"] = att_auto
        ab["total_ticks_auto"] = ticks_auto
        for n, s in statics.items():
            ab[f"static{n}_attained_requests"] = s["attained"]
            ab[f"static{n}_replica_ticks"] = s["replica_ticks"]
        record["autoscale_metrics"] = {
            "counters": ab,
            "gauges": ctrl.metrics_json()["gauges"],
        }
        busiest = max(
            fleet_auto.replicas,
            key=lambda r: len(r.engine.finished_requests()),
        )
        _embed_cost(record, busiest.engine)
        slo = _eval_slo(args, fleet_auto.finished_requests())
        if slo is not None:
            record["slo"] = slo
        if not streams_equal:
            record["error"] = (
                "a replayed stream diverged from the single-engine "
                "oracle — scaling must decide capacity, never tokens"
            )
        elif not verdict_ok:
            record["error"] = (
                f"autoscale A/B verdict failed on {sc_name}: "
                f"auto {att_auto}/{len(work)} @ {auto_cost} "
                "replica-ticks vs static "
                + ", ".join(
                    f"n={n}: {s['attained']}/{len(work)} @ "
                    f"{s['replica_ticks']}"
                    for n, s in statics.items()
                )
                + f" (scale_ups={ups}, scale_downs={downs})"
            )
        _maybe_slo_error(args, record)
        _dump_obs_fleet(
            record,
            fleet_auto,
            f"autoscale_{sc_name}",
            slo_spec=_slo_spec(args),
            collectors=[ctrl.collector()],
        )
        out_dir = os.environ.get("TDX_SERVE_TRACE_DIR")
        if out_dir:
            # the flight dump carries every scale decision (controller
            # records them as kind="scale") for postmortem replay
            from torchdistx_tpu.obs.flight import get_flight_recorder

            record["flight_path"] = get_flight_recorder().dump(
                os.path.join(
                    out_dir, f"autoscale_{sc_name}_flight.jsonl"
                ),
                reason=f"bench_serve autoscale_{sc_name}",
            )
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def main() -> None:
    args = _parse_args()
    if os.environ.get("TDX_SERVE_CHILD") == "1":
        phase = os.environ.get("TDX_SERVE_PHASE")
        if phase == "prefix_share":
            _child_prefix(args)
        elif phase == "chunked_prefill":
            _child_chunked_prefill(args)
        elif phase == "speculate":
            _child_spec(args)
        elif phase == "migrate":
            _child_migrate(args)
        elif phase == "kv_quant":
            _child_kv_quant(args)
        elif phase == "numerics":
            _child_numerics(args)
        elif phase == "fleet":
            _child_fleet(args)
        elif phase == "fleet_drain":
            _child_fleet_drain(args)
        elif phase == "fleet_disagg":
            _child_fleet_disagg(args)
        elif phase == "autoscale":
            _child_autoscale(args)
        else:
            _child(args)
    else:
        _supervise(args)


if __name__ == "__main__":
    main()
