"""Serving throughput: continuous batching through ``serve.ServeEngine``.

Submits a mixed-length request burst deeper than the slot count (so slot
churn, padded-bucket prefill, and late admissions all happen), drives the
engine to drain, and reports the metrics snapshot — tokens/s,
time-to-first-token, slot occupancy, queue depth.

Same output contract as bench.py: a full parseable JSON record is the
LAST stdout line, even on failure.  The workload runs in a subprocess
under ``TDX_BENCH_DEADLINE`` (default 1500 s) because a wedged axon relay
hangs inside a C dispatch where no in-process handler can fire
(CLAUDE.md) — on timeout or crash the parent emits a degraded-but-
parseable record instead.

Usage (TPU):  python scripts/bench_serve.py
Smoke (CPU):  TDX_BENCH_PLATFORM=cpu TDX_SERVE_MODEL=tiny \
                  python scripts/bench_serve.py --requests 6 --max-new 8
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_args():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=None)
    ap.add_argument("--temperature", type=float, default=0.0)
    return ap.parse_args()


def _supervise() -> None:
    """Run the workload in a child under the global deadline; the parent
    never touches the device (a parent + child both on the TPU would be
    the two-process relay wedge this guards against)."""
    deadline = float(os.environ.get("TDX_BENCH_DEADLINE", "1500"))
    record = {
        "bench": "serve",
        "model": os.environ.get("TDX_SERVE_MODEL", "llama_1b"),
        "deadline_s": deadline,
    }
    cmd = [sys.executable, os.path.abspath(__file__)] + sys.argv[1:]
    env = dict(os.environ, TDX_SERVE_CHILD="1")
    try:
        proc = subprocess.run(
            cmd, env=env, timeout=deadline, capture_output=True, text=True
        )
        out = proc.stdout or ""
        if out.strip():
            # the child printed its own (possibly degraded) record;
            # forward it verbatim as our last line
            sys.stdout.write(out)
            return
        record["error"] = (
            f"child exited {proc.returncode} with no record: "
            f"{(proc.stderr or '')[-400:]}"
        )
    except subprocess.TimeoutExpired:
        record["error"] = f"deadline ({deadline:.0f}s) exceeded — relay wedge?"
    print(json.dumps(record))


def main() -> None:
    if os.environ.get("TDX_SERVE_CHILD") != "1":
        _supervise()
        return
    args = _parse_args()

    import jax

    plat = os.environ.get("TDX_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    import numpy as np

    import torchdistx_tpu as tdx
    from torchdistx_tpu.models import Llama
    from torchdistx_tpu.serve import ServeEngine

    name = os.environ.get("TDX_SERVE_MODEL", "llama_1b")
    record: dict = {
        "bench": "serve",
        "model": name,
        "platform": jax.devices()[0].platform,
        "requests": args.requests,
        "max_new_tokens": args.max_new,
        "num_slots": args.slots,
    }
    try:
        import jax.numpy as jnp

        dtype = jnp.bfloat16 if plat != "cpu" else jnp.float32
        tdx.manual_seed(0)
        model = tdx.deferred_init(Llama.from_name, name, dtype=dtype)
        tdx.materialize_module(model)

        limit = model.cfg.max_seq_len
        max_len = args.max_len or min(limit, 8 * args.max_new)
        engine = ServeEngine(
            model, num_slots=args.slots, max_len=max_len
        )
        rs = np.random.RandomState(0)
        max_prompt = max(1, min(max_len - args.max_new, max_len // 2))
        prompts = [
            rs.randint(0, 256, (int(n),)).astype(np.int32)
            for n in rs.randint(1, max_prompt + 1, args.requests)
        ]

        # Warm every program the workload can reach PAST the
        # donated-carry layout recompile (CLAUDE.md: never time the
        # second call): two requests per reachable prefill bucket, a few
        # decode steps each, then reset metrics so TTFT/prefill/decode
        # histograms measure steady-state dispatch, not XLA compiles.
        from torchdistx_tpu.serve.metrics import ServeMetrics

        for b in engine.prefill_buckets:
            plen = max(1, min(b, max_prompt))
            engine.run([
                {"prompt": rs.randint(0, 256, (plen,)).astype(np.int32),
                 "max_new_tokens": 3, "temperature": args.temperature,
                 "seed": 10**6 + j}
                for j in range(2)
            ])
            if plen < b:
                break  # larger buckets unreachable by this workload
        engine.metrics = ServeMetrics(engine.num_slots)

        t0 = time.perf_counter()
        results = engine.run(
            [
                {
                    "prompt": p,
                    "max_new_tokens": args.max_new,
                    "temperature": args.temperature,
                    "seed": i,
                }
                for i, p in enumerate(prompts)
            ]
        )
        wall = time.perf_counter() - t0

        record.update(engine.metrics.snapshot())
        record.update(
            max_len=max_len,
            drain_wall_s=round(wall, 3),
            compiled_programs=engine.num_compiled_programs(),
            prompt_tokens=int(sum(p.size for p in prompts)),
            finish_reasons=sorted({r.finish_reason for r in results}),
            kv_cache_gb=round(engine.cache.nbytes / 1e9, 3),
        )
    except Exception as e:  # degraded-but-parseable, bench.py contract
        record["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


if __name__ == "__main__":
    main()
