#!/usr/bin/env python
"""Docs consistency gate (stdlib-only, runs where mkdocs cannot).

Checks, over ``docs/*.md`` and ``mkdocs.yml``:

- every relative markdown link/image target exists;
- every ``docs/*.md`` page is reachable from the mkdocs nav;
- every nav entry points at an existing page;
- in-page anchors referenced as ``page.md#anchor`` exist as headings.

CI runs this before ``mkdocs build --strict`` so a broken cross-reference
fails fast with a precise message; locally it is the whole docs gate
(mkdocs is not installed in the locked image).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
DOCS = ROOT / "docs"

LINK_RE = re.compile(r"!?\[[^\]]*\]\(([^)\s]+)\)")
HEADING_RE = re.compile(r"^#+\s+(.*)$", re.MULTILINE)


def slugify(heading: str) -> str:
    s = re.sub(r"[`*_]", "", heading.strip().lower())
    s = re.sub(r"[^\w\s-]", "", s)
    return re.sub(r"[\s]+", "-", s)


def main() -> int:
    errors: list[str] = []
    pages = sorted(DOCS.glob("*.md"))
    anchors = {
        p.name: {slugify(h) for h in HEADING_RE.findall(p.read_text())}
        for p in pages
    }

    for page in pages:
        for target in LINK_RE.findall(page.read_text()):
            if target.startswith(("http://", "https://", "mailto:")):
                continue
            path, _, frag = target.partition("#")
            if path:
                resolved = (page.parent / path).resolve()
                if not resolved.exists():
                    errors.append(f"{page.name}: broken link -> {target}")
                    continue
            name = path or page.name
            if frag and name in anchors and frag not in anchors[name]:
                errors.append(f"{page.name}: missing anchor -> {target}")

    nav_entries = set()
    mkdocs = ROOT / "mkdocs.yml"
    if mkdocs.exists():
        for m in re.finditer(r":\s*([\w./-]+\.md)\s*$",
                             mkdocs.read_text(), re.MULTILINE):
            nav_entries.add(m.group(1))
        for entry in sorted(nav_entries):
            if not (DOCS / entry).exists():
                errors.append(f"mkdocs.yml: nav entry missing -> {entry}")
        for page in pages:
            if page.name not in nav_entries:
                errors.append(f"mkdocs.yml: page not in nav -> {page.name}")
    else:
        errors.append("mkdocs.yml not found")

    for e in errors:
        print(f"ERROR: {e}")
    print(f"checked {len(pages)} pages, {len(nav_entries)} nav entries: "
          f"{'FAIL' if errors else 'OK'}")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
