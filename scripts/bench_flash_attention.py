"""Flash-attention vs reference attention on the real TPU chip.

Times fwd and fwd+bwd at Llama-7B attention shapes (H=32, D=128, bf16)
across sequence lengths.  Each measurement jits a lax.scan of ``iters``
applications so the timed region is multi-second — per-op timings through
the axon relay are unreliable (CLAUDE.md).

Usage: python scripts/bench_flash_attention.py [--seqs 2048,4096,8192,16384]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

from torchdistx_tpu.obs.ledger import record_stamp as _stamp
from torchdistx_tpu.ops.attention import multihead_attention
from torchdistx_tpu.ops.flash_attention import flash_attention

B, H, D = 1, 32, 128


def _inputs(seq, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)  # tdx-lint: disable=TDX102 -- fixed-seed bench input data, not parameter init
    shape = (B, seq, H, D)
    return tuple(
        jax.random.normal(k, shape, jnp.bfloat16) / math.sqrt(D) for k in ks
    )


def _time(fn, *args, iters):
    import numpy as np

    @jax.jit
    def many(q, k, v):
        def body(c, _):
            # the carry perturbs q so each iteration depends on the last —
            # without this XLA hoists the loop-invariant attention out of
            # the scan and the "benchmark" measures one application
            out = fn(q * (1.0 + c * 1e-30).astype(q.dtype), k, v)
            return out, None

        c, _ = lax.scan(
            body, jnp.zeros((), jnp.float32), None, length=iters
        )
        return c

    # block_until_ready is unreliable through the axon relay (async
    # batching); a host fetch of the scalar result forces real completion
    float(np.asarray(many(*args)))  # compile + warm
    t0 = time.perf_counter()
    float(np.asarray(many(*args)))
    dt = time.perf_counter() - t0
    return dt / iters


def attention_flops(seq, fwd_only):
    # 2 matmuls (QK^T, PV): 4*B*H*S^2*D fwd; bwd ~2x fwd (recompute ~+1x)
    f = 4 * B * H * seq * seq * D
    return f if fwd_only else 3 * f


def bias_rows(seqs):
    """Biased (T5 relative-position) fwd+bwd: pallas kernel backward vs
    the round-3 chunked-recompute backward.  Bias is O(H*S^2) memory, so
    realistic seqs stop well short of the bias-free 64k rows."""
    from torchdistx_tpu.ops import flash_attention as fa

    results = []
    for seq in seqs:
        q, k, v = _inputs(seq)
        bias = (
            jax.random.normal(jax.random.PRNGKey(7), (H, seq, seq), jnp.bfloat16)  # tdx-lint: disable=TDX102 -- fixed-seed bench bias data, not parameter init
            * 0.02
        )
        per_iter = attention_flops(seq, False)
        iters = int(os.environ.get(
            "TDX_BENCH_ITERS",
            max(4, min(1024, int(3.0 * 100e12 / per_iter))),
        ))

        def biased_loss(q, k, v, b):
            return (
                fa.flash_attention(q, k, v, bias=b, causal=True)
                .mean()
                .astype(jnp.float32)
            )

        def step(q, k, v):
            # consume EVERY gradient: an unused dk/dv/dbias is dead code
            # XLA eliminates, and the leg would time only the dq kernel
            grads = jax.grad(biased_loss, (0, 1, 2, 3))(q, k, v, bias)
            return sum(g.mean().astype(jnp.float32) for g in grads)

        row = {"seq": seq, "bias": True, **_stamp()}
        for name, forced in (("kernel_bwd", False), ("chunked_bwd", True)):
            fa._FORCE_CHUNKED_BWD = forced
            try:
                dt = _time(step, q, k, v, iters=iters)
                row[name] = dt
                row[name + "_tflops"] = (
                    attention_flops(seq, False) / dt / 1e12
                )
            except Exception as e:  # noqa: BLE001 — OOM at long seq is data
                row[name] = None
                row[name + "_err"] = f"{type(e).__name__}"
            finally:
                fa._FORCE_CHUNKED_BWD = False
        if row.get("kernel_bwd") and row.get("chunked_bwd"):
            row["kernel_speedup"] = row["chunked_bwd"] / row["kernel_bwd"]
        results.append(row)
        print(json.dumps(row))
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="2048,4096,8192,16384")
    ap.add_argument(
        "--bias", action="store_true",
        help="measure the biased (T5) fwd+bwd kernel-vs-chunked A/B instead",
    )
    args = ap.parse_args()
    # smoke-testing hook (same as bench.py): sitecustomize pins the axon
    # platform; only a pre-device jax.config update overrides it
    plat = os.environ.get("TDX_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    seqs = [int(s) for s in args.seqs.split(",")]
    if args.bias:
        print(f"platform={jax.devices()[0].platform} B={B} H={H} D={D} "
              f"bf16 biased")
        return bias_rows(seqs)
    print(f"platform={jax.devices()[0].platform} B={B} H={H} D={D} bf16")
    results = []
    for seq in seqs:
        q, k, v = _inputs(seq)
        # size the scan so the timed region is multi-second at ~100 TFLOP/s
        # effective (relay-proof timing, CLAUDE.md)
        per_iter = attention_flops(seq, True)
        iters = int(os.environ.get(
            "TDX_BENCH_ITERS",
            max(8, min(4096, int(4.0 * 100e12 / per_iter))),
        ))

        def ref_fwd(q, k, v):
            return multihead_attention(q, k, v, causal=True).mean().astype(
                jnp.float32
            )

        def flash_fwd(q, k, v):
            return flash_attention(q, k, v, causal=True).mean().astype(
                jnp.float32
            )

        def ref_step(q, k, v):
            # sum over ALL grads — keeping only dq lets XLA dead-code the
            # dK/dV work out of the timed region (round-3 rows used [0];
            # re-measured rows supersede them)
            grads = jax.grad(
                lambda a, b, c: ref_fwd(a, b, c).sum(), (0, 1, 2)
            )(q, k, v)
            return sum(g.mean().astype(jnp.float32) for g in grads)

        def flash_step(q, k, v):
            grads = jax.grad(
                lambda a, b, c: flash_fwd(a, b, c).sum(), (0, 1, 2)
            )(q, k, v)
            return sum(g.mean().astype(jnp.float32) for g in grads)

        row = {"seq": seq, **_stamp()}
        for name, fn, fwd_only in (
            ("ref_fwd", ref_fwd, True),
            ("flash_fwd", flash_fwd, True),
            ("ref_fwdbwd", ref_step, False),
            ("flash_fwdbwd", flash_step, False),
        ):
            try:
                dt = _time(fn, q, k, v, iters=iters)
                row[name] = dt
                row[name + "_tflops"] = attention_flops(seq, fwd_only) / dt / 1e12
            except Exception as e:  # noqa: BLE001 — OOM at long seq is data
                row[name] = None
                row[name + "_err"] = f"{type(e).__name__}"
        if row.get("ref_fwd") and row.get("flash_fwd"):
            row["fwd_speedup"] = row["ref_fwd"] / row["flash_fwd"]
        if row.get("ref_fwdbwd") and row.get("flash_fwdbwd"):
            row["fwdbwd_speedup"] = row["ref_fwdbwd"] / row["flash_fwdbwd"]
        results.append(row)
        print(json.dumps(row))
    return results


if __name__ == "__main__":
    main()
