"""The whole on-chip evidence queue as ONE serial command.

Runs, in priority order and strictly serially (CLAUDE.md: never two TPU
processes), every measurement the round needs from a relay-alive window:

1. ``bench.py``             — headline record (train MFU, 7B materialize,
                              kernel-acceptance sweep, fused-CE A/B)
2. ``bench_serve``          — first on-chip serve record
                              (BENCH_SERVE_TPU.json does not exist yet):
                              fused K sweep + persistent-loop A/B +
                              shared-prefix cold/warm
3. ``bench_serve --speculate 0,2,4`` — self-speculative decode A/B
                              through the persistent loop
                              (BENCH_SERVE_TPU_SPEC.json)
4. ``bench_flash_attention``— corrected long-context fwd+bwd rows
                              (the round-3 32k/64k rows were invalidated
                              by gradient DCE; the harness now consumes
                              every gradient)
5. ``bench_fused_ce``       — kernel-level fused-vs-unfused loss A/B
6. ``bench.py --train-phase`` with TDX_BENCH_OPT=8bit      — optimizer A/B
7. ``bench.py --train-phase`` with REMAT=1 x {full, dots}  — remat A/B
8. ``bench_generate``       — int8 decode A/B
9. ``bench_t5_train``       — biased-kernel train delta

Each step is a subprocess under its own slice of a global deadline
(``TDX_CAMPAIGN_DEADLINE``, default 5400 s); stdout JSON lines are
harvested (even from killed steps) into ``CAMPAIGN.json`` after every
step, so a window that closes mid-run still leaves everything captured
so far.  A wedged relay costs one bench preflight (~75 s) and produces a
degraded-but-parseable record.

Usage:  python scripts/onchip_campaign.py
Smoke:  TDX_CAMPAIGN_PLATFORM=cpu TDX_CAMPAIGN_DEADLINE=600 \
            python scripts/onchip_campaign.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_PATH = os.path.join(REPO, "CAMPAIGN.json")


def _ledger():
    """Load ``torchdistx_tpu/obs/ledger.py`` WITHOUT importing the
    package: the campaign driver runs every TPU step as a subprocess
    and must never touch jax itself; the ledger module is stdlib-only
    by design.  Memoized in ``sys.modules`` so repeat calls share one
    module instance (and its git-sha cache)."""
    import importlib.util

    mod = sys.modules.get("_tdx_ledger")
    if mod is not None:
        return mod
    path = os.path.join(REPO, "torchdistx_tpu", "obs", "ledger.py")
    spec = importlib.util.spec_from_file_location("_tdx_ledger", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    sys.modules["_tdx_ledger"] = mod
    return mod


def _steps() -> list:
    py = sys.executable
    bench = os.path.join(REPO, "bench.py")
    sdir = os.path.join(REPO, "scripts")
    smoke = os.environ.get("TDX_CAMPAIGN_PLATFORM") == "cpu"
    # (name, cmd, extra_env, budget_s).  bench_full's budget sits ABOVE
    # bench.py's internal 1500 s deadline so its graceful final record
    # emit never races the subprocess kill.
    return [
        ("bench_full", [py, bench], {}, 1600),
        # serve A/B right after the headline: BENCH_SERVE_TPU.json does
        # not exist yet (ROADMAP standing constraint) — the first
        # healthy-relay window must land it.  Default phases: K=1
        # baseline, the fused K sweep, the persistent whole-loop A/B,
        # the shared-prefix cold/warm pass, and the chunked-prefill
        # long-admission A/B (ISSUE 10); bench_serve's own deadline sits
        # UNDER the step budget so its graceful final record emit never
        # races the subprocess kill.  TP degree: the CPU smoke runs the
        # 2-device mesh leg (virtual devices); the real machine has ONE
        # v5e chip, so the on-chip record runs --tp 1 through the SAME
        # mesh engine path (sharded programs, degenerate mesh) — the
        # multi-chip numbers come from the dryrun driver's CPU-mesh leg
        # until more chips exist.
        ("serve_engine_ab",
         [py, os.path.join(sdir, "bench_serve.py"), "--prefix-share"]
         + (["--tp", "2", "--chunked-prefill", "16", "--decode-chunk",
             "4", "--requests", "6", "--max-new", "8", "--slots", "2",
             "--max-len", "64"] if smoke
            else ["--tp", "1", "--chunked-prefill", "256"]),
         {} if smoke else {"TDX_BENCH_DEADLINE": "800"}, 900),
        # self-speculation A/B (ISSUE 11): spec0 baseline vs K=2,4
        # through the persistent loop on the repetition-heavy workload —
        # the first on-chip evidence of whether prompt-lookup drafting
        # pays on the relay (each accepted draft is one more token per
        # while-loop iteration at zero extra host syncs).  Its own
        # artifact: the serve_engine_ab record above keeps the canonical
        # BENCH_SERVE_TPU.json name (smoke redirects to /tmp so the
        # committed CPU record, pinned by the perf gate, is never
        # clobbered by campaign-smoke geometry).
        ("serve_spec_ab",
         [py, os.path.join(sdir, "bench_serve.py"),
          "--decode-mode", "persistent", "--speculate", "0,2,4"]
         + (["--requests", "6", "--max-new", "8", "--slots", "2",
             "--max-len", "64",
             "--artifact", "/tmp/BENCH_SERVE_CPU_SPEC.json"] if smoke
            else ["--artifact", "BENCH_SERVE_TPU_SPEC.json"]),
         {} if smoke else {"TDX_BENCH_DEADLINE": "700"}, 800),
        # int8 KV-cache A/B (ISSUE 17): the WHOLE sweep quantized
        # (--kv-dtype plumbs int8 into every phase's engines) — the
        # kv_quant phase's strict verdict (halved memory_plan() KV pool,
        # pinned greedy-stream divergence, decode tok/s vs the bfloat16
        # baseline, strictly-lower decode bytes_accessed) is the first
        # on-chip pricing of half-width KV against real HBM bandwidth.
        # Own artifact for the same clobber reason as serve_spec_ab.
        ("serve_kv_quant_ab",
         [py, os.path.join(sdir, "bench_serve.py"),
          "--kv-dtype", "int8", "--decode-mode", "chunked"]
         + (["--decode-chunk", "4", "--requests", "6", "--max-new", "8",
             "--slots", "2", "--max-len", "64",
             "--artifact", "/tmp/BENCH_SERVE_CPU_KVQUANT.json"] if smoke
            else ["--artifact", "BENCH_SERVE_TPU_KVQUANT.json"]),
         {} if smoke else {"TDX_BENCH_DEADLINE": "700"}, 800),
        ("flash_long_context",
         [py, os.path.join(sdir, "bench_flash_attention.py")]
         + (["--seqs", "256"] if smoke else
            ["--seqs", "8192,32768,65536"]),
         {}, 900),
        ("fused_ce_kernel_ab",
         [py, os.path.join(sdir, "bench_fused_ce.py")]
         + (["--cpu", "--shapes", "256x128x512", "--iters", "2"]
            if smoke else []),
         {}, 600),
        ("train_8bit_opt", [py, bench, "--train-phase"],
         {"TDX_BENCH_OPT": "8bit"}, 400),
        ("train_remat_full", [py, bench, "--train-phase"],
         {"TDX_BENCH_REMAT": "1"}, 400),
        ("train_remat_dots", [py, bench, "--train-phase"],
         {"TDX_BENCH_REMAT": "1", "TDX_BENCH_REMAT_POLICY": "dots"}, 400),
        ("generate_bf16", [py, os.path.join(sdir, "bench_generate.py")],
         {}, 400),
        ("generate_int8",
         [py, os.path.join(sdir, "bench_generate.py"), "--quantize"],
         {}, 400),
        ("t5_biased_kernels", [py, os.path.join(sdir, "bench_t5_train.py")],
         {}, 500),
    ]


def _harvest(out: str) -> list:
    recs = []
    for line in (out or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return recs


def main() -> None:
    deadline = time.monotonic() + float(
        os.environ.get("TDX_CAMPAIGN_DEADLINE", "5400")
    )
    platform_env = {}
    if os.environ.get("TDX_CAMPAIGN_PLATFORM"):
        p = os.environ["TDX_CAMPAIGN_PLATFORM"]
        # bench.py maps TDX_BENCH_PLATFORM into its chained sweep itself
        platform_env = {"TDX_BENCH_PLATFORM": p}
        if p == "cpu":  # tiny shapes for the harness smoke
            platform_env.update(
                TDX_BENCH_MODEL="tiny", TDX_BENCH_TRAIN_MODEL="tiny",
                TDX_BENCH_SEQ="64", TDX_BENCH_DEADLINE="300",
                TDX_GEN_MODEL="tiny", TDX_T5_MODEL="tiny",
                TDX_SERVE_MODEL="tiny",
            )

    results: dict = {}
    # commit + schema attribution, stamped once at campaign start (the
    # perf-sentinel satellite: every emitter names its producing sha)
    stamp = _ledger().record_stamp()

    def write(status: str) -> None:
        with open(OUT_PATH, "w") as f:
            json.dump({"status": status, **stamp, "steps": results}, f,
                      indent=1)
        print(json.dumps({"campaign": status,
                          "done": list(results)}), flush=True)

    def relay_wedged(recs: list) -> bool:
        # bench.py's record carries the preflight verdict; a failed
        # preflight means every further TPU step would hang to its full
        # budget for nothing (the docstring's ~75 s promise)
        for r in reversed(recs):
            pre = r.get("extra", {}).get("preflight")
            if isinstance(pre, dict):
                return not pre.get("ok", False)
        return False

    write("started")
    wedged = False
    for name, cmd, extra, budget in _steps():
        left = deadline - time.monotonic()
        if wedged:
            results[name] = {"skipped": "relay wedged at bench preflight"}
            continue
        if left <= 30:
            results[name] = {"skipped": "campaign deadline exhausted"}
            continue
        env = dict(os.environ, **platform_env, **extra)
        t0 = time.time()
        err = ""
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True,
                timeout=min(budget, left), env=env, cwd=REPO,
            )
            out, rc = proc.stdout, proc.returncode
            err = proc.stderr or ""
        except subprocess.TimeoutExpired as e:
            out = e.stdout
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            err = e.stderr or ""
            if isinstance(err, bytes):
                err = err.decode(errors="replace")
            rc = "timeout"
        recs = _harvest(out)
        results[name] = {
            "rc": rc,
            "wall_s": round(time.time() - t0, 1),
            "records": recs[-8:],  # the tail is the signal
        }
        if rc != 0 or not recs:
            # evidence for the post-mortem after the window closes
            results[name]["stderr_tail"] = err[-2000:]
        if name == "bench_full" and relay_wedged(recs):
            wedged = True
        write("running")
    skipped = [n for n, v in results.items() if "skipped" in v]
    status = "wedged" if wedged else ("partial" if skipped else "complete")
    write(status)
    # perf-sentinel hook: per-step rc/wall rows, plus KILLED bench /
    # bench_serve steps' harvested tails, normalized into LEDGER.jsonl
    # (never raises; TDX_LEDGER=0 disables).  Gracefully-exited bench /
    # bench_serve steps appended their own rows in-process; the ad-hoc
    # per-script emitters (generate/t5/flash/fused_ce) have no ledger
    # family and ride only as step rc/wall rows
    _ledger().append_record_rows(
        {"status": status, **stamp, "steps": results}, source="campaign"
    )


if __name__ == "__main__":
    main()
