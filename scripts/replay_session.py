"""Incident time machine CLI — replay a ``tdx-session-v1`` black box.

Takes one recording written by a ``ServeEngine(record=...)`` /
``ServeFleet(record=...)`` session (``obs/blackbox.py``), rebuilds the
engine/fleet from the recorded geometry, re-drives the exact submit/
step/tick/signal stream on this host's mesh (CPU by default — the CI
posture), and prints the verdict:

- ``match`` — every drain-boundary digest is bit-identical: the
  incident reproduces deterministically and can be debugged offline.
- ``truncated_match`` — the recording ends without a ``session_end``
  (killed run); the complete prefix replays bit-identically and the
  truncation point is named.
- ``divergent`` — the chains split; the periodic snapshots bracket the
  window and the verdict names the FIRST divergent drain (seq + tick),
  the differing counters, and the affected session request ids.
- ``geometry_mismatch`` — the rebuilt engine does not match the
  recorded geometry (named fields); nothing was re-driven.

Model reconstruction: the recording's ``model_spec`` event (written by
``bench_serve.py --record`` and the dryrun ``blackbox`` leg) names the
catalog model; ``--model`` overrides it for recordings that lack one.

Usage:
  python scripts/replay_session.py SESSION.jsonl            # verdict
  python scripts/replay_session.py SESSION.jsonl --strict   # CI: exit 1
  python scripts/replay_session.py SESSION.jsonl --validate-only

The full JSON verdict is the LAST stdout line (the repo's
consumers-parse-the-last-line contract).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="replay a tdx-session-v1 recording and report the "
        "digest-chain verdict"
    )
    p.add_argument("recording", help="path to the session JSONL")
    p.add_argument(
        "--platform",
        default="cpu",
        help="jax platform to replay on (default: cpu — a TPU recording "
        "replayed here judges platform determinism, not the code)",
    )
    p.add_argument(
        "--model",
        default=None,
        help="catalog model name override when the recording has no "
        "model_spec event",
    )
    p.add_argument(
        "--validate-only",
        action="store_true",
        help="schema + digest-chain integrity only; no re-execution",
    )
    p.add_argument(
        "--allow-truncated",
        action="store_true",
        help="validation: a missing session_end is a note, not an error",
    )
    p.add_argument(
        "--strict",
        action="store_true",
        help="exit 1 unless the verdict is match/truncated_match",
    )
    p.add_argument(
        "--json", dest="json_out", default=None,
        help="also write the verdict to this path",
    )
    return p.parse_args(argv)


def _model_factory(events, override):
    """Build the recorded model: ``model_spec`` names it (bench --record
    and the dryrun leg write one); --model overrides.  Seeded through
    the rng counter stream, so the build is bit-identical every time."""
    spec = next(
        (e for e in events if e.get("kind") == "model_spec"), None
    )
    name = override or (spec or {}).get("name")
    if name is None:
        raise SystemExit(
            "recording has no model_spec event — pass --model <catalog "
            "name> (e.g. tiny) to name the model it served"
        )
    seed = int((spec or {}).get("seed", 0))
    dtype_name = (spec or {}).get("dtype", "float32")

    def build():
        import jax.numpy as jnp

        import torchdistx_tpu as tdx
        from torchdistx_tpu.models import Llama

        tdx.manual_seed(seed)
        model = tdx.deferred_init(
            Llama.from_name, name, dtype=getattr(jnp, dtype_name)
        )
        tdx.materialize_module(model)
        return model

    return build


def main(argv=None) -> int:
    args = _parse_args(argv)

    import jax

    jax.config.update("jax_platforms", args.platform)

    from torchdistx_tpu.obs.blackbox import (
        geometry_kwargs,
        load_session,
        replay_session,
        validate_session_jsonl,
    )

    errors = validate_session_jsonl(
        args.recording, allow_truncated=args.allow_truncated
    )
    for e in errors:
        print(f"INVALID: {e}")
    if args.validate_only:
        out = {
            "schema": "tdx-session-verdict-v1",
            "verdict": "valid" if not errors else "invalid",
            "errors": errors,
        }
        print(json.dumps(out))
        return 1 if errors and args.strict else 0
    # a torn/truncated recording still replays its complete prefix;
    # only a corrupt CHAIN is unreplayable evidence
    fatal = [e for e in errors if "chain" in e or "unparseable" in e]
    if fatal:
        print(json.dumps({
            "schema": "tdx-session-verdict-v1",
            "verdict": "invalid",
            "errors": errors,
        }))
        return 1

    events, _notes = load_session(args.recording)
    build_model = _model_factory(events, args.model)
    is_fleet = any(e.get("kind") == "fleet" for e in events)
    if is_fleet:
        # one deterministic model shared by every rebuilt replica (the
        # fleet posture); each replica rebuilds from ITS geometry event
        from torchdistx_tpu.serve import ServeEngine

        model = build_model()

        def engine_factory(rec, geom):
            return ServeEngine(
                model, record=rec, **geometry_kwargs(geom)
            )

        verdict = replay_session(events, engine_factory=engine_factory)
    else:
        verdict = replay_session(events, model_factory=build_model)

    ok = bool(verdict.get("match"))
    v = verdict.get("verdict")
    if ok:
        print(
            f"REPLAY {v.upper()}: {verdict.get('drains_replayed')} drains "
            f"bit-identical (chain {str(verdict.get('chain_replayed'))[:16]}...)"
        )
    elif v == "geometry_mismatch":
        print(
            "REPLAY GEOMETRY MISMATCH: fields "
            f"{verdict.get('geometry_fields')} differ from the recording"
        )
    else:
        d = verdict.get("first_divergence") or {}
        print(
            f"REPLAY DIVERGENT at drain seq={d.get('seq')} "
            f"tick={d.get('tick')} source={d.get('source')}: "
            f"counters={d.get('counters')} request_ids={d.get('rids')}"
        )
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(verdict, f, indent=2)
    print(json.dumps(verdict))
    return 0 if ok or not args.strict else 1


if __name__ == "__main__":
    sys.exit(main())
