"""tdx-lint CLI — AST invariant checker gated by an exact-findings baseline.

Runs the ``torchdistx_tpu.analysis`` rule pack (TDX101..TDX106, plus
TDX100 malformed-suppression) over the lint scope and compares the
findings EXACTLY against the committed baseline, perf-gate style:

- a **new** finding fails CI naming the rule and ``file:line`` — fix it
  or suppress it on the line with a justification
  (``# tdx-lint: disable=TDXnnn -- why``);
- a **fixed** finding (in the baseline, no longer found) also fails,
  so the baseline only shrinks via an explicit ``--update-baseline``
  refresh that reviewers see in the diff.

Prints per-finding lines and a markdown verdict, then the full JSON
verdict as the LAST stdout line (the repo's consumers-parse-the-last-
line contract); exits 1 under ``--strict`` when not ok, 2 on usage
errors.

Usage:
  python scripts/tdx_lint.py --strict
  python scripts/tdx_lint.py --update-baseline   # after an intended change
  python scripts/tdx_lint.py path/to/file.py --no-baseline   # ad-hoc scan
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Import torchdistx_tpu.analysis WITHOUT the parent package.

    The analysis package is pure stdlib, but ``torchdistx_tpu/__init__``
    imports jax and builds the csrc extension — neither exists in the CI
    lint container, and this linter must stay runnable there (and can
    never wedge the TPU relay).
    """
    pkg_dir = os.path.join(REPO_ROOT, "torchdistx_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "_tdx_analysis",
        os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_tdx_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


_analysis = _load_analysis()
RULE_CATALOG = _analysis.RULE_CATALOG
compare_to_baseline = _analysis.compare_to_baseline
default_rules = _analysis.default_rules
run_lint = _analysis.run_lint

#: the committed lint scope — product code, drivers, scripts, examples.
DEFAULT_PATHS = (
    "torchdistx_tpu",
    "scripts",
    "__graft_entry__.py",
    "examples",
    "bench.py",
)
DEFAULT_BASELINE = "expectations/static_analysis_baseline.json"


def _parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description="AST invariant checker (exact-findings baseline gate)"
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=[],
        help="files/dirs to scan (default: the committed lint scope)",
    )
    ap.add_argument(
        "--baseline",
        default=os.path.join(REPO_ROOT, DEFAULT_BASELINE),
        help="committed tdx-lint-v1 baseline (default: %s)" % DEFAULT_BASELINE,
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="skip the baseline compare (ad-hoc scans of arbitrary paths)",
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="exit nonzero when the verdict is not ok (CI mode)",
    )
    ap.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline from this scan instead of gating — the "
        "refresh workflow after an intended fix or accepted finding",
    )
    ap.add_argument(
        "--json-out",
        default=None,
        help="also write the JSON verdict to this path",
    )
    ap.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = _parse_args(argv)
    if args.list_rules:
        for rid in sorted(RULE_CATALOG):
            sev, summary = RULE_CATALOG[rid]
            print("%s  %-7s %s" % (rid, sev, summary))
        return 0

    paths = args.paths or list(DEFAULT_PATHS)
    report = run_lint(paths, default_rules(), root=REPO_ROOT)

    for f in report["findings"]:
        print(
            "%s %s:%d:%d %s"
            % (f["rule"], f["path"], f["line"], f["col"], f["message"])
        )

    if args.update_baseline:
        doc = dict(report)
        doc["description"] = (
            "exact-findings lint baseline; refresh ONLY via "
            "scripts/tdx_lint.py --update-baseline after an intended change"
        )
        os.makedirs(os.path.dirname(args.baseline), exist_ok=True)
        with open(args.baseline, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(
            "tdx_lint: pinned %d finding(s) across %d file(s) into %s"
            % (len(report["findings"]), report["files_scanned"], args.baseline)
        )
        return 0

    verdict = {
        "schema": "tdx-lint-verdict-v1",
        "ok": True,
        "files_scanned": report["files_scanned"],
        "findings": len(report["findings"]),
        "suppressions": len(report["suppressions"]),
        "new": [],
        "fixed": [],
    }
    if args.no_baseline:
        verdict["ok"] = not report["findings"]
        verdict["new"] = list(report["findings"])
    else:
        if not os.path.exists(args.baseline):
            print(
                "tdx_lint: baseline %s not found (run --update-baseline "
                "to create it)" % args.baseline,
                file=sys.stderr,
            )
            return 2
        with open(args.baseline) as fh:
            baseline = json.load(fh)
        diff = compare_to_baseline(report, baseline)
        verdict["new"] = diff["new"]
        verdict["fixed"] = diff["fixed"]
        verdict["ok"] = not diff["new"] and not diff["fixed"]

    print("## tdx-lint verdict")
    print(
        "- scanned %d file(s): %d finding(s), %d suppression(s)"
        % (
            verdict["files_scanned"],
            verdict["findings"],
            verdict["suppressions"],
        )
    )
    status = "OK" if verdict["ok"] else "FAIL"
    print("- status: **%s**" % status)
    for f in verdict["new"]:
        print(
            "FAIL: new finding %s at %s:%d — %s"
            % (f["rule"], f["path"], f["line"], f["message"]),
            file=sys.stderr,
        )
    for f in verdict["fixed"]:
        print(
            "FAIL: baseline finding %s at %s:%d no longer present — "
            "refresh with --update-baseline" % (f["rule"], f["path"], f["line"]),
            file=sys.stderr,
        )

    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(verdict, fh, indent=1)
            fh.write("\n")
    # the consumer contract: full JSON verdict as the last stdout line
    print(json.dumps(verdict))
    return 1 if (args.strict and not verdict["ok"]) else 0


if __name__ == "__main__":
    sys.exit(main())
