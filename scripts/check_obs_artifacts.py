"""Validate the observability artifacts of a bench_serve run (CI smoke).

Given the bench record (``BENCH_SERVE_CPU.json`` or a file holding the
last stdout line), for every phase that embedded observability paths:

- the Perfetto trace must ``json.load`` and satisfy the catapult
  ``traceEvents`` schema (list of events with ``name``/``ph``; complete
  events carry numeric ``ts``/``dur``; at least one per-request
  lifecycle track is present);
- the Prometheus exposition must round-trip through the stdlib line
  parser (``obs.parse_prometheus``) with every serve counter EQUAL to
  the same counter in the phase's embedded ``metrics`` JSON — the
  exposition is a projection of ``to_json()``, and this is the gate
  that keeps the two schemas from drifting apart.

Exit nonzero (with a reason per failure) when anything is off; print a
one-line OK summary otherwise.  Stdlib + torchdistx_tpu.obs only.

Usage:  python scripts/check_obs_artifacts.py BENCH_SERVE_CPU.json
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchdistx_tpu.obs import parse_prometheus  # noqa: E402


def check_trace(path: str, errors: list) -> int:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        errors.append(f"{path}: unreadable trace JSON: {e}")
        return 0
    evs = doc.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        errors.append(f"{path}: no traceEvents list")
        return 0
    request_spans = 0
    for ev in evs:
        if not isinstance(ev, dict) or "ph" not in ev or "name" not in ev:
            errors.append(f"{path}: malformed event {ev!r:.120}")
            return 0
        if ev["ph"] == "X":
            if not (
                isinstance(ev.get("ts"), (int, float))
                and isinstance(ev.get("dur"), (int, float))
                and ev["dur"] >= 0
            ):
                errors.append(f"{path}: X event without ts/dur: {ev!r:.120}")
                return 0
            if ev.get("cat") == "request":
                request_spans += 1
    if request_spans == 0:
        errors.append(f"{path}: no per-request lifecycle spans")
    return len(evs)


def check_prom(path: str, metrics_json: dict, errors: list) -> int:
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        errors.append(f"{path}: unreadable exposition: {e}")
        return 0
    try:
        parsed = parse_prometheus(text)
    except ValueError as e:
        errors.append(f"{path}: exposition does not parse: {e}")
        return 0
    samples = parsed["samples"]
    counters = (metrics_json or {}).get("counters") or {}
    if not counters:
        errors.append(f"{path}: phase record embeds no metrics counters")
        return 0
    for name, v in counters.items():
        key = (f"tdx_serve_{name}_total", ())
        if key not in samples:
            errors.append(f"{path}: missing exposition sample {key[0]}")
        elif samples[key] != v:
            errors.append(
                f"{path}: {key[0]} is {samples[key]} in exposition but "
                f"{v} in metrics JSON — the projection drifted"
            )
    return len(samples)


def main() -> None:
    if len(sys.argv) != 2:
        raise SystemExit(__doc__)
    with open(sys.argv[1]) as f:
        record = json.load(f)
    errors: list = []
    checked = 0
    for name, phase in (record.get("phases") or {}).items():
        if "error" in phase:
            errors.append(f"phase {name}: {phase['error']}")
            continue
        if "trace_path" not in phase:
            continue  # phase ran without TDX_SERVE_TRACE_DIR
        checked += 1
        n_events = check_trace(phase["trace_path"], errors)
        n_samples = check_prom(
            phase.get("metrics_prom_path", ""),
            phase.get("metrics"),
            errors,
        )
        print(
            f"phase {name}: {n_events} trace events, "
            f"{n_samples} exposition samples"
        )
    if checked == 0:
        errors.append(
            "no phase carried observability artifacts — was "
            "TDX_SERVE_TRACE_DIR set for the bench run?"
        )
    if errors:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(f"observability artifacts OK ({checked} phase(s))")


if __name__ == "__main__":
    main()
