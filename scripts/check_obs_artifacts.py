"""Validate the observability artifacts of a bench run (CI smoke).

Given a bench record (``BENCH_SERVE_CPU.json``, a ``bench.py`` record,
or a file holding the last stdout line), for every phase that embedded
observability paths:

- the Perfetto trace must ``json.load`` and satisfy the catapult
  ``traceEvents`` schema (list of events with ``name``/``ph``; complete
  events carry numeric ``ts``/``dur``; at least one per-request
  lifecycle track is present);
- the Prometheus exposition must round-trip through the stdlib line
  parser (``obs.parse_prometheus``) with every serve counter EQUAL to
  the same counter in the phase's embedded ``metrics`` JSON — the
  exposition is a projection of ``to_json()``, and this is the gate
  that keeps the two schemas from drifting apart;
- any embedded ``flight_dump`` path must be a schema-valid flight JSONL
  (``obs.flight.validate_flight_jsonl``) and any embedded ``comm``
  profile must satisfy the ``tdx-comm-v1`` schema
  (``obs.comm.validate_comm_profile``).

Exit nonzero (with a reason per failure) when anything is off; print a
one-line OK summary otherwise.  Stdlib + torchdistx_tpu.obs only.

Usage:
  python scripts/check_obs_artifacts.py BENCH_SERVE_CPU.json
  python scripts/check_obs_artifacts.py --flight /path/flight.jsonl
    (standalone flight-record validation — the nightly crash-injection
    smoke's gate; with --expect-rollback the record must also contain a
    rollback entry naming the restored step and checkpoint)
  python scripts/check_obs_artifacts.py --ledger LEDGER.jsonl
    (tdx-ledger-v1 schema validation: every line must parse and every
    row must validate — the perf-sentinel half of the nightly gate)
  python scripts/check_obs_artifacts.py --cost BENCH_SERVE_CPU.json
    (cost-card schema validation: every non-error serve phase must
    embed a non-empty ``cost_cards`` object of valid tdx-cost-v1
    cards — numeric flops/bytes, peak source NAMED — and a bench.py
    record's ``extra.train_cost_card`` is checked the same way; the
    cost-observatory half of the nightly gate)
  python scripts/check_obs_artifacts.py --numerics BENCH_SERVE_CPU.json
    (numerics-observatory validation: every embedded ``tdx-numerics-v1``
    digest book — a serve ``numerics`` A/B phase's or a bench.py train
    phase's ``extra.numerics_book`` — must be schema-valid with the
    exact partition identity ``count == nonfinite + zeros +
    sum(exp_hist)`` intact per site, and the serve phase must carry its
    zero-overhead evidence: digest-on engine counters EXACTLY equal to
    the digest-off baseline's — plus, when the phase dumped an
    exposition, tdx_numerics_*{site=} samples equal to the embedded
    book's exact integer fields)
  python scripts/check_obs_artifacts.py --slo BENCH_SERVE_CPU_FLEET.json
    (SLO-observatory validation: every non-error fleet phase must embed
    a schema-valid ``tdx-slo-v1`` block — spec echoed, attainment in
    [0, 1], burn windows ordered (``obs.slo.validate_slo_report``) —
    and every phase trace dump must satisfy Perfetto flow-event
    referential integrity: each flow id resolves to BOTH endpoints
    (one ``ph:"s"`` open and one ``ph:"f"`` close, opened before
    closed), so every cross-replica request chain is stitched, never
    dangling)
  python scripts/check_obs_artifacts.py --autoscale BENCH_SERVE_CPU_AUTOSCALE.json
    (autoscale-observatory validation: every non-error autoscale phase
    must embed its FULL decision stream — one ``scale_events`` entry
    per controller tick with strictly increasing fleet ticks, a legal
    action, and the complete signal vector (burn state, windows,
    per-replica load) — with stream-derived decision/scale-up/down
    counts EQUAL to the phase's ``autoscale_metrics`` counters and to
    the ``tdx_autoscale_*_total`` exposition samples, a passing
    ``autoscale_verdict``, and (when dumped) a schema-valid flight
    record carrying the same ``scale`` entries)
  python scripts/check_obs_artifacts.py --lint LINT_REPORT.json
    (tdx-lint-v1 schema validation for a ``scripts/tdx_lint.py
    --json-out``/``--update-baseline`` artifact — including the
    committed ``expectations/static_analysis_baseline.json``; checks
    field types, TDXnnn rule ids, severities, and that every recorded
    suppression carries justification text)
  python scripts/check_obs_artifacts.py --session SESSION.jsonl
    (session black-box validation — the incident time machine's
    integrity gate: ``tdx-session-v1`` schema, header stamped, drain
    seqs dense from 0, the SHA-256 digest chain recomputable from the
    drain payloads, every periodic snapshot anchored to its drain with
    counters equal to the accumulated deltas, and a ``session_end``
    whose chain/count match; --allow-truncated downgrades a missing
    session_end — the killed-run case — to a note, since the complete
    prefix still replays via scripts/replay_session.py)
  Flight validation accepts --expect-slo-burn alongside
  --expect-rollback: the record must then contain an ``slo_burn``
  entry naming the breached objective (the injected-burn CI leg's
  gate).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchdistx_tpu.obs import parse_prometheus  # noqa: E402
from torchdistx_tpu.obs.comm import validate_comm_profile  # noqa: E402
from torchdistx_tpu.obs.flight import validate_flight_jsonl  # noqa: E402


def check_trace(path: str, errors: list) -> int:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        errors.append(f"{path}: unreadable trace JSON: {e}")
        return 0
    evs = doc.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        errors.append(f"{path}: no traceEvents list")
        return 0
    request_spans = 0
    for ev in evs:
        if not isinstance(ev, dict) or "ph" not in ev or "name" not in ev:
            errors.append(f"{path}: malformed event {ev!r:.120}")
            return 0
        if ev["ph"] == "X":
            if not (
                isinstance(ev.get("ts"), (int, float))
                and isinstance(ev.get("dur"), (int, float))
                and ev["dur"] >= 0
            ):
                errors.append(f"{path}: X event without ts/dur: {ev!r:.120}")
                return 0
            if ev.get("cat") == "request":
                request_spans += 1
    if request_spans == 0:
        errors.append(f"{path}: no per-request lifecycle spans")
    return len(evs)


def check_prom(path: str, metrics_json: dict, errors: list) -> int:
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        errors.append(f"{path}: unreadable exposition: {e}")
        return 0
    try:
        parsed = parse_prometheus(text)
    except ValueError as e:
        errors.append(f"{path}: exposition does not parse: {e}")
        return 0
    samples = parsed["samples"]
    counters = (metrics_json or {}).get("counters") or {}
    if not counters:
        errors.append(f"{path}: phase record embeds no metrics counters")
        return 0
    for name, v in counters.items():
        key = (f"tdx_serve_{name}_total", ())
        if key not in samples:
            errors.append(f"{path}: missing exposition sample {key[0]}")
        elif samples[key] != v:
            errors.append(
                f"{path}: {key[0]} is {samples[key]} in exposition but "
                f"{v} in metrics JSON — the projection drifted"
            )
    return len(samples)


def check_flight(
    path: str,
    errors: list,
    expect_rollback: bool = False,
    expect_slo_burn: bool = False,
) -> int:
    errs = validate_flight_jsonl(path)
    errors.extend(errs)
    if errs:
        return 0
    with open(path) as f:
        records = [json.loads(ln) for ln in f.read().splitlines() if ln.strip()]
    for rec in records:
        if isinstance(rec.get("comm"), dict) and "schema" in rec["comm"]:
            errors.extend(
                f"{path}: {e}" for e in validate_comm_profile(rec["comm"])
            )
    if expect_slo_burn:
        burns = [r for r in records if r.get("kind") == "slo_burn"]
        if not burns:
            errors.append(f"{path}: no slo_burn entry in flight record")
        for r in burns:
            if not r.get("slo") or r.get("state") not in (
                "ok", "warn", "page"
            ):
                errors.append(
                    f"{path}: slo_burn entry lacks slo name/state: "
                    f"{r!r:.200}"
                )
    if expect_rollback:
        rollbacks = [r for r in records if r.get("kind") == "rollback"]
        if not rollbacks:
            errors.append(f"{path}: no rollback entry in flight record")
        for r in rollbacks:
            if not isinstance(r.get("restored_step"), int) or not r.get(
                "checkpoint"
            ):
                errors.append(
                    f"{path}: rollback entry lacks restored_step/checkpoint: "
                    f"{r!r:.200}"
                )
    return len(records)


def _check_flight_main(argv: list) -> None:
    expect_rollback = "--expect-rollback" in argv
    expect_slo_burn = "--expect-slo-burn" in argv
    unknown = [
        a
        for a in argv
        if a.startswith("--")
        and a not in ("--expect-rollback", "--expect-slo-burn")
    ]
    if unknown:
        # a typoed flag must NOT silently weaken the gate (e.g.
        # --expect_rollback passing a rollback-free dump as OK)
        raise SystemExit(f"unknown flag(s) {unknown}\n\n{__doc__}")
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        raise SystemExit(__doc__)
    errors: list = []
    for p in paths:
        n = check_flight(
            p,
            errors,
            expect_rollback=expect_rollback,
            expect_slo_burn=expect_slo_burn,
        )
        print(f"flight {p}: {n} records")
    if errors:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(f"flight records OK ({len(paths)} file(s))")


def _check_ledger_main(paths: list) -> None:
    from torchdistx_tpu.obs.ledger import validate_ledger_file

    if not paths:
        raise SystemExit(__doc__)
    errors: list = []
    for p in paths:
        errs = validate_ledger_file(p)
        errors.extend(errs)
        if not errs:
            with open(p) as f:
                n = sum(1 for ln in f if ln.strip())
            print(f"ledger {p}: {n} rows")
    if errors:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(f"ledger OK ({len(paths)} file(s))")


def _check_numerics(tag: str, book, errors: list) -> int:
    """One embedded tdx-numerics-v1 digest book: schema, integer-typed
    exact fields, the partition identity (``count == nonfinite + zeros
    + sum(exp_hist)`` — exact by construction, so a violation means the
    digest math itself broke), and the f64-exact ``hist_hash`` range.
    Returns the number of sites checked."""
    if not isinstance(book, dict):
        errors.append(f"{tag}: numerics_book is not an object")
        return 0
    if "error" in book:
        errors.append(f"{tag}: numerics_book errored: {book['error']}")
        return 0
    if book.get("schema") != "tdx-numerics-v1":
        errors.append(
            f"{tag}: numerics_book schema {book.get('schema')!r} != "
            "'tdx-numerics-v1'"
        )
        return 0
    sites = book.get("sites")
    if not isinstance(sites, dict) or not sites:
        errors.append(f"{tag}: numerics_book has no sites")
        return 0
    n = 0
    for site, d in sorted(sites.items()):
        n += 1
        stag = f"{tag} site {site}"
        ints = {k: d.get(k) for k in ("nonfinite", "zeros", "count",
                                      "hist_hash")}
        bad = [
            k for k, v in ints.items()
            if not isinstance(v, int) or isinstance(v, bool) or v < 0
        ]
        hist = d.get("exp_hist")
        if bad:
            errors.append(f"{stag}: non-integer exact fields {bad}")
            continue
        if not (
            isinstance(hist, list)
            and hist
            and all(isinstance(b, int) and b >= 0 for b in hist)
        ):
            errors.append(f"{stag}: exp_hist is not a list of counts")
            continue
        if ints["count"] != ints["nonfinite"] + ints["zeros"] + sum(hist):
            errors.append(
                f"{stag}: partition identity violated — count "
                f"{ints['count']} != nonfinite {ints['nonfinite']} + "
                f"zeros {ints['zeros']} + sum(exp_hist) {sum(hist)}"
            )
        if not 0 <= ints["hist_hash"] < 2**53:
            errors.append(
                f"{stag}: hist_hash {ints['hist_hash']} outside the "
                "f64-exact range [0, 2**53)"
            )
        for k in ("max_abs", "rms"):
            v = d.get(k)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                errors.append(f"{stag}: gauge {k} is not numeric")
    return n


def _check_numerics_main(paths: list) -> None:
    """Numerics-observatory validation: every embedded digest book must
    be schema-valid with the partition identity intact per site, and a
    serve ``numerics`` A/B phase must carry its zero-overhead evidence —
    the on-leg's engine counters EXACTLY equal to the off-leg's
    (``metrics`` vs ``metrics_baseline``), since digests ride existing
    program outputs and harvest at existing syncs."""
    if not paths:
        raise SystemExit(__doc__)
    errors: list = []
    checked_sites = 0
    checked_books = 0
    for path in paths:
        with open(path) as f:
            record = json.load(f)
        phases = record.get("phases") or {}
        books = []  # (tag, book, phase-or-None)
        for name, phase in phases.items():
            if isinstance(phase, dict) and "numerics_book" in phase:
                books.append((f"{path} phase {name}", phase["numerics_book"],
                              phase))
        # bench.py records embed the train phase's book under extra
        train_book = (record.get("extra") or {}).get("numerics_book")
        if train_book is not None:
            books.append((f"{path} train phase", train_book, None))
        if not books:
            errors.append(
                f"{path}: no numerics_book anywhere — was the numerics "
                "phase (bench_serve --numerics) or TDX_NUMERICS=1 "
                "(bench.py) on for this run?"
            )
            continue
        for tag, book, phase in books:
            if phase is not None and "error" in phase:
                errors.append(f"{tag}: {phase['error']}")
                continue
            checked_books += 1
            checked_sites += _check_numerics(tag, book, errors)
            if phase is None:
                continue
            c_on = (phase.get("metrics") or {}).get("counters") or {}
            c_off = (
                phase.get("metrics_baseline") or {}
            ).get("counters") or {}
            if not c_on or not c_off:
                errors.append(
                    f"{tag}: missing metrics/metrics_baseline counters — "
                    "no zero-overhead evidence"
                )
            elif c_on != c_off:
                unequal = {
                    k: (c_on.get(k), c_off.get(k))
                    for k in sorted(set(c_on) | set(c_off))
                    if c_on.get(k) != c_off.get(k)
                }
                errors.append(
                    f"{tag}: digest-on counters differ from digest-off: "
                    f"{unequal}"
                )
            # exposition cross-check: the tdx_numerics_*{site=} gauges
            # the phase rendered must equal the embedded book's exact
            # integer fields — the exposition is a projection of
            # to_json(), and this keeps the two surfaces from drifting
            prom_path = phase.get("metrics_prom_path")
            if prom_path and isinstance(book, dict):
                try:
                    with open(prom_path) as f:
                        parsed = parse_prometheus(f.read())
                except (OSError, ValueError) as e:
                    errors.append(f"{tag}: numerics exposition: {e}")
                    continue
                samples = parsed["samples"]
                for site, d in sorted((book.get("sites") or {}).items()):
                    if not isinstance(d, dict):
                        continue
                    for field in ("nonfinite", "zeros", "count",
                                  "hist_hash"):
                        key = (
                            f"tdx_numerics_{field}",
                            (("site", site),),
                        )
                        if key not in samples:
                            errors.append(
                                f"{tag}: missing exposition sample "
                                f"tdx_numerics_{field}{{site=\"{site}\"}}"
                            )
                        elif samples[key] != d.get(field):
                            errors.append(
                                f"{tag}: tdx_numerics_{field}"
                                f"{{site=\"{site}\"}} is {samples[key]} "
                                f"in exposition but {d.get(field)} in "
                                "the embedded book — the projection "
                                "drifted"
                            )
    if errors:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(
        f"numerics OK ({checked_books} book(s), {checked_sites} site(s), "
        "zero-overhead counters equal)"
    )


def _check_cost_main(paths: list) -> None:
    from torchdistx_tpu.obs.cost import validate_cost_card

    if not paths:
        raise SystemExit(__doc__)
    errors: list = []
    checked = 0
    for path in paths:
        n_file = 0
        try:
            with open(path) as f:
                record = json.load(f)
        except (OSError, ValueError) as e:
            errors.append(f"{path}: unreadable record: {e}")
            continue
        for name, phase in (record.get("phases") or {}).items():
            if not isinstance(phase, dict) or "error" in phase:
                continue
            cards = phase.get("cost_cards")
            if not isinstance(cards, dict) or not cards:
                errors.append(
                    f"{path}: phase {name} embeds no cost_cards — was the "
                    "engine built with cost_cards=False (or "
                    "TDX_COST_CARDS=0)?"
                )
                continue
            for prog, card in cards.items():
                errors.extend(
                    validate_cost_card(card, f"{path}:{name}:{prog}")
                )
                n_file += 1
        # bench.py records: the train phase's card lives in extra
        card = (record.get("extra") or {}).get("train_cost_card")
        if isinstance(card, dict) and "error" not in card:
            errors.extend(validate_cost_card(card, f"{path}:train"))
            n_file += 1
        checked += n_file
        print(f"cost {path}: {n_file} card(s)")
    if checked == 0:
        errors.append("no cost cards found in any record")
    if errors:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(f"cost cards OK ({checked} card(s), {len(paths)} file(s))")


def check_flow_integrity(path: str, errors: list) -> int:
    """Perfetto flow-event referential integrity for one trace dump:
    every flow ``id`` must resolve to BOTH endpoints — at least one
    ``ph:"s"`` open and one ``ph:"f"`` close — with the open no later
    than the close.  A dangling flow means a request chain lost one of
    its replicas in the merge."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        errors.append(f"{path}: unreadable trace JSON: {e}")
        return 0
    flows: dict = {}
    for ev in doc.get("traceEvents") or []:
        if not isinstance(ev, dict) or ev.get("ph") not in ("s", "t", "f"):
            continue
        fid = ev.get("id")
        if fid is None:
            errors.append(f"{path}: flow event without id: {ev!r:.120}")
            continue
        flows.setdefault(fid, {"s": [], "t": [], "f": []})[ev["ph"]].append(
            ev.get("ts")
        )
    for fid, phs in sorted(flows.items(), key=lambda kv: str(kv[0])):
        if not phs["s"]:
            errors.append(f"{path}: flow {fid} has no start endpoint (s)")
        if not phs["f"]:
            errors.append(f"{path}: flow {fid} has no finish endpoint (f)")
        if phs["s"] and phs["f"] and min(phs["s"]) > max(phs["f"]):
            errors.append(
                f"{path}: flow {fid} closes before it opens "
                f"(s at {min(phs['s'])}, f at {max(phs['f'])})"
            )
    return len(flows)


def _check_slo_main(paths: list) -> None:
    from torchdistx_tpu.obs.slo import validate_slo_report

    if not paths:
        raise SystemExit(__doc__)
    errors: list = []
    n_reports = n_flows = 0
    for path in paths:
        try:
            with open(path) as f:
                record = json.load(f)
        except (OSError, ValueError) as e:
            errors.append(f"{path}: unreadable record: {e}")
            continue
        for name, phase in (record.get("phases") or {}).items():
            if not isinstance(phase, dict) or "error" in phase:
                continue
            slo = phase.get("slo")
            if isinstance(slo, dict):
                # one report, or a dict of per-policy reports (the
                # affinity-vs-round-robin A/B embeds both)
                reports = (
                    {"": slo}
                    if "schema" in slo
                    else {
                        k: v
                        for k, v in slo.items()
                        if isinstance(v, dict) and "schema" in v
                    }
                )
                if not reports:
                    errors.append(
                        f"{path}: phase {name} slo block holds no "
                        "tdx-slo-v1 report"
                    )
                for key, rep in sorted(reports.items()):
                    tag = f"{name}[{key}]" if key else name
                    errors.extend(
                        f"{path}: phase {tag}: {e}"
                        for e in validate_slo_report(rep)
                    )
                    n_reports += 1
            if "trace_path" in phase:
                n_flows += check_flow_integrity(phase["trace_path"], errors)
        print(f"slo {path}: {n_reports} report(s), {n_flows} flow(s)")
    if n_reports == 0:
        errors.append(
            "no tdx-slo-v1 block found in any phase — was the bench run "
            "with --slo <spec>?"
        )
    if errors:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(f"slo artifacts OK ({n_reports} report(s), {n_flows} flow(s))")


def _check_autoscale_main(paths: list) -> None:
    """``--autoscale``: the scale-decision stream is the subsystem's
    black box — every decision must be present, schema-complete, and
    agree with the counters and the scrape surface, or a silent scaling
    bug could hide behind a green verdict."""
    if not paths:
        raise SystemExit(__doc__)
    actions = {"hold", "scale_up", "scale_down"}
    states = {"ok", "warn", "page"}
    required = (
        "tick",
        "action",
        "reason",
        "replicas_before",
        "replicas_after",
        "sustain",
        "cooldown_remaining",
        "policy",
        "signal",
    )
    errors: list = []
    n_phases = n_events = 0
    for path in paths:
        try:
            with open(path) as f:
                record = json.load(f)
        except (OSError, ValueError) as e:
            errors.append(f"{path}: unreadable record: {e}")
            continue
        for name, phase in (record.get("phases") or {}).items():
            if not isinstance(phase, dict) or "error" in phase:
                continue
            if "autoscale_verdict" not in phase:
                continue
            n_phases += 1
            tag = f"{path}: phase {name}"
            events = phase.get("scale_events")
            if not isinstance(events, list) or not events:
                errors.append(f"{tag}: no scale_events stream")
                continue
            n_events += len(events)
            last_tick, ups, downs = -1, 0, 0
            for i, ev in enumerate(events):
                where = f"{tag} scale_events[{i}]"
                if not isinstance(ev, dict):
                    errors.append(f"{where}: not an object")
                    continue
                missing = [k for k in required if k not in ev]
                if missing:
                    errors.append(f"{where}: missing {missing}")
                    continue
                if not isinstance(ev["tick"], int) or ev["tick"] <= last_tick:
                    errors.append(
                        f"{where}: fleet ticks must strictly increase "
                        f"({ev['tick']!r} after {last_tick})"
                    )
                else:
                    last_tick = ev["tick"]
                if ev["action"] not in actions:
                    errors.append(f"{where}: unknown action {ev['action']!r}")
                ups += ev["action"] == "scale_up"
                downs += ev["action"] == "scale_down"
                sig = ev["signal"]
                if not isinstance(sig, dict) or sig.get("state") not in states:
                    errors.append(
                        f"{where}: signal lacks a legal burn state: "
                        f"{sig!r:.120}"
                    )
                elif not isinstance(sig.get("windows"), list):
                    errors.append(f"{where}: signal carries no burn windows")
                elif not (
                    isinstance(sig.get("replicas"), list) and sig["replicas"]
                ):
                    errors.append(
                        f"{where}: signal carries no per-replica load vector"
                    )
            counters = (phase.get("autoscale_metrics") or {}).get(
                "counters"
            ) or {}
            for key, want in (
                ("autoscale_decisions", len(events)),
                ("autoscale_scale_ups", ups),
                ("autoscale_scale_downs", downs),
            ):
                if counters.get(key) != want:
                    errors.append(
                        f"{tag}: counter {key}={counters.get(key)} "
                        f"disagrees with the event stream ({want})"
                    )
            if ups < 1 or downs < 1:
                errors.append(
                    f"{tag}: no full scale cycle in the stream "
                    f"(ups={ups}, downs={downs})"
                )
            if not (phase.get("autoscale_verdict") or {}).get("ok"):
                errors.append(f"{tag}: autoscale_verdict is not ok")
            pp = phase.get("metrics_prom_path")
            if pp:
                try:
                    with open(pp) as f:
                        parsed = parse_prometheus(f.read())
                except (OSError, ValueError) as e:
                    errors.append(f"{tag}: exposition unreadable: {e}")
                else:
                    for key, v in counters.items():
                        if not key.startswith("autoscale_"):
                            continue  # workload/static rows: ledger-only
                        fam = f"tdx_autoscale_{key[10:]}_total"
                        got = parsed["samples"].get((fam, ()))
                        if got != v:
                            errors.append(
                                f"{tag}: {fam} is {got} in exposition "
                                f"but {v} in autoscale_metrics"
                            )
            fp = phase.get("flight_path")
            if fp:
                errs = validate_flight_jsonl(fp)
                errors.extend(f"{tag}: {e}" for e in errs)
                if not errs:
                    with open(fp) as f:
                        kinds = [
                            json.loads(ln).get("kind")
                            for ln in f.read().splitlines()
                            if ln.strip()
                        ]
                    if kinds.count("scale") < ups + downs:
                        errors.append(
                            f"{tag}: flight dump holds "
                            f"{kinds.count('scale')} scale record(s), "
                            f"fewer than the {ups + downs} executed "
                            "actions"
                        )
        print(f"autoscale {path}: {n_phases} phase(s), {n_events} decision(s)")
    if n_phases == 0:
        errors.append(
            "no autoscale phase found in any record — was the bench run "
            "with --scenario/--autoscale?"
        )
    if errors:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(
        f"autoscale artifacts OK ({n_phases} phase(s), "
        f"{n_events} decision(s))"
    )


def _check_session_main(argv: list) -> None:
    from torchdistx_tpu.obs.blackbox import validate_session_jsonl

    allow_truncated = "--allow-truncated" in argv
    unknown = [
        a
        for a in argv
        if a.startswith("--") and a != "--allow-truncated"
    ]
    if unknown:
        # a typoed flag must NOT silently weaken the gate (the --flight
        # discipline)
        raise SystemExit(f"unknown flag(s) {unknown}\n\n{__doc__}")
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        raise SystemExit(__doc__)
    errors: list = []
    for p in paths:
        errs = validate_session_jsonl(p, allow_truncated=allow_truncated)
        errors.extend(errs)
        if not errs:
            with open(p) as f:
                lines = [ln for ln in f if ln.strip()]
            drains = sum(
                1
                for ln in lines
                if '"kind": "drain"' in ln or '"kind":"drain"' in ln
            )
            print(f"session {p}: {len(lines)} events, {drains} drains")
    if errors:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(f"session black box OK ({len(paths)} file(s))")


def _check_lint_main(paths: list) -> None:
    from torchdistx_tpu.analysis import validate_lint_report

    if not paths:
        raise SystemExit(__doc__)
    errors: list = []
    for p in paths:
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            errors.append(f"{p}: unreadable lint report: {e}")
            continue
        errs = validate_lint_report(doc)
        errors.extend(f"{p}: {e}" for e in errs)
        if not errs:
            print(
                f"lint {p}: {len(doc['findings'])} finding(s), "
                f"{len(doc['suppressions'])} suppression(s), "
                f"{doc['files_scanned']} file(s) scanned"
            )
    if errors:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(f"lint reports OK ({len(paths)} file(s))")


def main() -> None:
    if len(sys.argv) >= 2 and sys.argv[1] == "--flight":
        _check_flight_main(sys.argv[2:])
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--ledger":
        _check_ledger_main(sys.argv[2:])
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--cost":
        _check_cost_main(sys.argv[2:])
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--numerics":
        _check_numerics_main(sys.argv[2:])
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--slo":
        _check_slo_main(sys.argv[2:])
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--autoscale":
        _check_autoscale_main(sys.argv[2:])
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--session":
        _check_session_main(sys.argv[2:])
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--lint":
        _check_lint_main(sys.argv[2:])
        return
    if len(sys.argv) != 2:
        raise SystemExit(__doc__)
    with open(sys.argv[1]) as f:
        record = json.load(f)
    errors: list = []
    checked = 0
    for name, phase in (record.get("phases") or {}).items():
        if "error" in phase:
            errors.append(f"phase {name}: {phase['error']}")
            continue
        if "trace_path" not in phase:
            continue  # phase ran without TDX_SERVE_TRACE_DIR
        checked += 1
        n_events = check_trace(phase["trace_path"], errors)
        n_samples = check_prom(
            phase.get("metrics_prom_path", ""),
            phase.get("metrics"),
            errors,
        )
        print(
            f"phase {name}: {n_events} trace events, "
            f"{n_samples} exposition samples"
        )
    # bench.py records: a top-level flight_dump (train phase's black box)
    # must be schema-valid when present and readable on this host
    dump = record.get("flight_dump")
    if dump and os.path.exists(dump):
        checked += 1
        n = check_flight(dump, errors)
        print(f"flight {dump}: {n} records")
    if checked == 0:
        errors.append(
            "no phase carried observability artifacts — was "
            "TDX_SERVE_TRACE_DIR set for the bench run?"
        )
    if errors:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(f"observability artifacts OK ({checked} check(s))")


if __name__ == "__main__":
    main()
