"""Perf regression gate CLI — the CI face of the perf sentinel
(``obs/ledger.py`` + ``obs/gate.py``).

Ingests one bench artifact (any family ``obs.ledger`` knows:
``BENCH_SERVE_*.json``, a ``bench.py`` record, ``MULTICHIP_r*.json``,
``CAMPAIGN.json``, ...), then:

- compares every **counter** metric EXACTLY against the committed
  expectations file (deterministic counters regress like correctness
  bugs — an extra host sync fails CI);
- checks every **timing** metric against a direction-aware tolerance
  band around the best prior complete ledger row of the same platform +
  workload fingerprint (degraded rows never baseline; improvements
  always pass);
- prints a markdown verdict, then the full JSON verdict as the LAST
  stdout line (the repo's consumers-parse-the-last-line contract);
- exits nonzero under ``--strict`` when the verdict is not ok.

Usage:
  python scripts/perf_gate.py BENCH_SERVE_CPU.json \
      --expectations expectations/serve_cpu_smoke.json \
      --ledger LEDGER.jsonl --strict

Refreshing the pins after an INTENDED counter change (new decode path,
different sync discipline — anything that legitimately moves a
deterministic counter):
  python scripts/perf_gate.py <fresh record> \
      --update-expectations expectations/serve_cpu_smoke.json

``--append`` adds the ingested rows to the ledger AFTER gating (so a
run is never its own baseline); the bench emitters already append on
emission, so CI normally gates without it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchdistx_tpu.obs import gate as gate_mod  # noqa: E402
from torchdistx_tpu.obs import ledger as ledger_mod  # noqa: E402


def _parse_args():
    ap = argparse.ArgumentParser(
        description="exact-counter + timing-band perf gate"
    )
    ap.add_argument("record", help="bench artifact to gate (any family)")
    ap.add_argument(
        "--expectations",
        default=None,
        help="committed tdx-expect-v1 file of exact counter pins",
    )
    ap.add_argument(
        "--ledger",
        default=None,
        help="tdx-ledger-v1 JSONL of prior runs (timing baselines); "
        "default <repo>/LEDGER.jsonl",
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="exit nonzero when the gate fails (CI mode)",
    )
    ap.add_argument(
        "--append",
        action="store_true",
        help="append the ingested rows to the ledger after gating",
    )
    ap.add_argument(
        "--run-id", default=None, help="override the run id (default: "
        "artifact basename)"
    )
    ap.add_argument(
        "--update-expectations",
        metavar="PATH",
        default=None,
        help="(re)write the expectations file from this record's counter "
        "rows instead of gating — the refresh workflow after an "
        "intended counter change",
    )
    ap.add_argument(
        "--json-out",
        default=None,
        help="also write the JSON verdict to this path",
    )
    return ap.parse_args()


def main() -> None:
    args = _parse_args()
    rows = ledger_mod.ingest_artifact(args.record, run_id=args.run_id)

    if args.update_expectations:
        doc = gate_mod.build_expectations(
            rows,
            description=f"pinned from {os.path.basename(args.record)} "
            f"@ {rows[0].get('git_sha') if rows else None}",
        )
        with open(args.update_expectations, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        n = sum(len(m) for m in doc["counters"].values())
        print(
            f"perf_gate: pinned {n} counter(s) across "
            f"{len(doc['counters'])} fingerprint(s) into "
            f"{args.update_expectations}"
        )
        return

    expectations = None
    if args.expectations:
        with open(args.expectations) as f:
            expectations = json.load(f)
    ledger_path = args.ledger or ledger_mod.default_ledger_path()
    ledger_rows = ledger_mod.read_ledger(ledger_path)

    verdict = gate_mod.gate_rows(rows, expectations, ledger_rows)
    print(gate_mod.render_gate_markdown(verdict))
    for f in verdict["failures"]:
        print(
            f"FAIL: {f.get('kind')}: {f.get('metric')}: "
            f"{f.get('detail', '')}",
            file=sys.stderr,
        )
    if args.append:
        n = ledger_mod.append_rows(ledger_path, rows)
        print(f"perf_gate: appended {n} row(s) to {ledger_path}")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(verdict, f, indent=1)
            f.write("\n")
    # the consumer contract: full JSON verdict as the last stdout line
    print(json.dumps(verdict))
    if args.strict and not verdict["ok"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
