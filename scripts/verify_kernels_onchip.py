"""On-chip acceptance sweep for every pallas flash-attention configuration.

Purpose: the pytest suite runs the kernels in interpret mode only
(tests/conftest.py forces the CPU platform), so compiled Mosaic behavior —
VMEM scratch sizing, output-block revisiting, the bucket-bias select
chains — is exactly what the suite cannot catch.  This script runs every
kernel configuration (causal x bias x table x window x GQA x shape class)
COMPILED on the attached TPU and diffs each against the independent jnp
reference (`ops.attention.multihead_attention` and local biased variants).
The suite's interpret-mode parity tests already pin interpret == reference,
so compiled == reference here closes compiled == interpret transitively.

Outage armor (same pattern as bench.py — a wedged axon relay hangs
`jax.devices()` forever):

- a ~75 s relay preflight runs first; if it hangs, a degraded-but-parseable
  record is emitted immediately;
- cases are grouped into a few phase subprocesses (compile cache amortized
  within each); each case prints ONE flushed JSON line, and the parent
  harvests partial stdout even when it must kill a hung phase — so any
  ~10-minute relay-alive window captures durable per-case evidence;
- everything runs under a global deadline (TDX_VERIFY_DEADLINE, default
  1200 s) and the cumulative record is rewritten after every phase.

Case order is by evidentiary value: the flagship causal path first, then
the round-4 features that have never run compiled (window, bias + dbias,
bucket table + dtable), then large-shape stress.

Artifact honesty: KERNEL_ACCEPT.json is reserved for COMPILED evidence —
it is only written when the attached device platform is "tpu" (the same
predicate the kernels use to pick Mosaic over interpret mode).  Any other
platform (including the env-drift case where the relay silently falls
back to CPU) writes KERNEL_ACCEPT_SMOKE.json instead, with
``"mode": "interpret-smoke"`` and a distinct ``metric``, so a smoke run
can never masquerade as — or clobber — the on-chip acceptance record.

Smoke (harness check, interpret mode, no TPU):
    TDX_VERIFY_PLATFORM=cpu python scripts/verify_kernels_onchip.py
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time
import zlib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ACCEPT_PATH = os.path.join(REPO, "KERNEL_ACCEPT.json")
SMOKE_PATH = os.path.join(REPO, "KERNEL_ACCEPT_SMOKE.json")
if REPO not in sys.path:  # children are launched by script path
    sys.path.insert(0, REPO)

# (name, phase, spec) — spec drives one fwd+bwd parity check
CASES = [
    # --- core: the flagship train/decode paths ---
    ("causal_mha_1024", "core",
     dict(b=2, sq=1024, skv=1024, hq=8, hkv=8, d=64, causal=True)),
    ("causal_gqa_1024", "core",
     dict(b=2, sq=1024, skv=1024, hq=8, hkv=2, d=64, causal=True)),
    ("noncausal_512", "core",
     dict(b=2, sq=512, skv=512, hq=4, hkv=4, d=64, causal=False)),
    ("decode_cross_256_1024", "core",
     dict(b=1, sq=256, skv=1024, hq=8, hkv=8, d=64, causal=True)),
    ("oddlen_384_blockshrink", "core",
     dict(b=2, sq=384, skv=384, hq=4, hkv=4, d=64, causal=True)),
    ("causal_f32_512", "core",
     dict(b=1, sq=512, skv=512, hq=4, hkv=4, d=64, causal=True,
          dtype="float32")),
    # --- features: round-4 paths never run compiled ---
    ("window_256_of_1024", "features",
     dict(b=2, sq=1024, skv=1024, hq=4, hkv=4, d=64, causal=True,
          window=256)),
    ("window_gqa_128", "features",
     dict(b=1, sq=1024, skv=1024, hq=8, hkv=2, d=64, causal=True,
          window=128)),
    ("bias_noncausal_512", "features",
     dict(b=2, sq=512, skv=512, hq=4, hkv=4, d=64, causal=False,
          bias=True)),
    ("bias_causal_512", "features",
     dict(b=2, sq=512, skv=512, hq=4, hkv=4, d=64, causal=True,
          bias=True)),
    ("bucket_table_enc_512", "features",
     dict(b=2, sq=512, skv=512, hq=4, hkv=4, d=64, causal=False,
          table=True, bidirectional=True)),
    ("bucket_table_dec_512", "features",
     dict(b=2, sq=512, skv=512, hq=4, hkv=4, d=64, causal=True,
          table=True, bidirectional=False)),
    # --- stress: multi-block grids at training scale ---
    ("causal_mha_4096", "stress",
     dict(b=1, sq=4096, skv=4096, hq=8, hkv=8, d=128, causal=True)),
    ("window_1024_of_4096", "stress",
     dict(b=1, sq=4096, skv=4096, hq=8, hkv=2, d=128, causal=True,
          window=1024)),
    ("causal_8192_fwd_only", "stress",
     dict(b=1, sq=8192, skv=8192, hq=4, hkv=4, d=128, causal=True,
          fwd_only=True)),
    # --- fused LM-head cross-entropy (ops/fused_ce.py) ---
    ("fused_ce_small", "fusedce",
     dict(kind="fused_ce", n=512, d=256, v=2048)),
    ("fused_ce_oddvocab", "fusedce",
     dict(kind="fused_ce", n=384, d=128, v=1000)),
    ("fused_ce_bench_shape", "fusedce",
     dict(kind="fused_ce", n=4096, d=2048, v=32000, dtype="bfloat16")),
]

PHASES = ["core", "features", "stress", "fusedce"]


def _set_platform():
    p = os.environ.get("TDX_VERIFY_PLATFORM")
    if p:
        import jax

        jax.config.update("jax_platforms", p)


def _preflight() -> dict:
    _set_platform()
    import jax
    import jax.numpy as jnp

    t0 = time.time()
    x = jnp.ones((512, 512), jnp.bfloat16)
    jax.block_until_ready(x @ x)
    return {"ok": True, "preflight_s": round(time.time() - t0, 2),
            "device": str(jax.devices()[0]),
            "platform": jax.devices()[0].platform}


def _ref_attention(q, k, v, *, causal, bias=None, window=None):
    """Independent jnp reference: einsum + f32 softmax (+ additive bias).

    Matches `ops.attention.multihead_attention` math; biased variant kept
    local so this script never depends on the code under test beyond the
    kernel entry point itself."""
    import jax
    import jax.numpy as jnp

    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    if hq != hkv:
        k = jnp.repeat(k, hq // hkv, axis=2)
        v = jnp.repeat(v, hq // hkv, axis=2)
    scale = 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias[None].astype(jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((sq, skv), bool), k=skv - sq)
        if window is not None:
            mask = mask & jnp.triu(
                jnp.ones((sq, skv), bool), k=skv - sq - (window - 1)
            )
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _max_rel_err(a, b) -> float:
    import numpy as np

    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    denom = np.max(np.abs(b)) + 1e-6
    return float(np.max(np.abs(a - b)) / denom)


def _run_fused_ce_case(name: str, spec: dict) -> dict:
    import jax
    import jax.numpy as jnp

    from torchdistx_tpu.ops.fused_ce import fused_linear_cross_entropy

    dtype = jnp.dtype(spec.get("dtype", "float32"))
    n, d, v = spec["n"], spec["d"], spec["v"]
    seed = zlib.crc32(name.encode())
    k = jax.random.split(jax.random.PRNGKey(seed % (2**31)), 3)  # tdx-lint: disable=TDX102 -- name-derived verification inputs, stable across processes; not parameter init
    x = jax.random.normal(k[0], (n, d), dtype)
    w = jax.random.normal(k[1], (v, d), dtype) * 0.1
    y = jax.random.randint(k[2], (n,), 0, v)

    def ref(x, w):
        logits = jnp.einsum("nd,vd->nv", x, w).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
        )

    rec = {"case": name, "spec": spec, "dtype": str(dtype)}
    t0 = time.time()
    lf = float(jax.block_until_ready(
        jax.jit(lambda x, w: fused_linear_cross_entropy(x, w, y))(x, w)
    ))
    rec["fwd_compile_run_s"] = round(time.time() - t0, 2)
    lr = float(jax.jit(ref)(x, w))
    rec["fwd_max_rel_err"] = abs(lf - lr) / (abs(lr) + 1e-8)

    t0 = time.time()
    gk = jax.block_until_ready(jax.jit(jax.grad(
        lambda x, w: fused_linear_cross_entropy(x, w, y), argnums=(0, 1)
    ))(x, w))
    rec["bwd_compile_run_s"] = round(time.time() - t0, 2)
    gr = jax.jit(jax.grad(ref, argnums=(0, 1)))(x, w)
    for gname, a_, b_ in zip(["dx", "dw"], gk, gr):
        rec[f"{gname}_max_rel_err"] = _max_rel_err(a_, b_)

    tol = 2e-2
    errs = {k_: v_ for k_, v_ in rec.items() if k_.endswith("_max_rel_err")}
    rec["ok"] = all(e <= tol for e in errs.values())
    rec["tol"] = tol
    return rec


def _run_case(name: str, spec: dict) -> dict:
    import jax
    import jax.numpy as jnp

    from torchdistx_tpu.ops.flash_attention import (
        flash_attention,
        rel_pos_bucket,
    )

    dtype = jnp.dtype(spec.get("dtype", "bfloat16"))
    b, sq, skv = spec["b"], spec["sq"], spec["skv"]
    hq, hkv, d = spec["hq"], spec["hkv"], spec["d"]
    causal = spec["causal"]
    window = spec.get("window")
    buckets, max_dist = 32, 128
    bidirectional = spec.get("bidirectional", False)

    seed = zlib.crc32(name.encode())  # stable across processes/runs
    keys = jax.random.split(jax.random.PRNGKey(seed % (2**31)), 6)  # tdx-lint: disable=TDX102 -- name-derived verification inputs, stable across processes; not parameter init
    q = jax.random.normal(keys[0], (b, sq, hq, d), dtype)
    k = jax.random.normal(keys[1], (b, skv, hkv, d), dtype)
    v = jax.random.normal(keys[2], (b, skv, hkv, d), dtype)
    w = jax.random.normal(keys[3], (b, sq, hq, d), dtype)  # cotangent probe

    bias = table = None
    if spec.get("bias"):
        bias = 0.5 * jax.random.normal(keys[4], (hq, sq, skv), jnp.float32)
    if spec.get("table"):
        table = 0.5 * jax.random.normal(keys[4], (hq, buckets), jnp.float32)

    def kernel_fn(q, k, v, bias, table):
        return flash_attention(
            q, k, v, causal=causal, bias=bias, window=window,
            rel_bias_table=table, rel_bias_buckets=buckets,
            rel_bias_max_dist=max_dist,
            rel_bias_bidirectional=bidirectional,
        )

    def ref_fn(q, k, v, bias, table):
        if table is not None:
            rel = (
                jnp.arange(skv)[None, :] - jnp.arange(sq)[:, None]
            )
            idx = rel_pos_bucket(
                rel, bidirectional=bidirectional, buckets=buckets,
                max_dist=max_dist,
            )
            bias = table[:, idx]  # (H, Sq, Skv)
        return _ref_attention(
            q, k, v, causal=causal, bias=bias, window=window
        )

    rec = {"case": name, "spec": spec, "dtype": str(dtype)}
    t0 = time.time()
    out_k = jax.block_until_ready(
        jax.jit(kernel_fn)(q, k, v, bias, table)
    )
    rec["fwd_compile_run_s"] = round(time.time() - t0, 2)
    out_r = jax.block_until_ready(jax.jit(ref_fn)(q, k, v, bias, table))
    rec["fwd_max_rel_err"] = _max_rel_err(out_k, out_r)

    if not spec.get("fwd_only"):
        def loss(fn):
            def f(q, k, v, bias, table):
                return jnp.sum(
                    fn(q, k, v, bias, table).astype(jnp.float32)
                    * w.astype(jnp.float32)
                )
            return f

        argnums = [0, 1, 2]
        grad_names = ["dq", "dk", "dv"]
        if bias is not None:
            argnums.append(3)
            grad_names.append("dbias")
        if table is not None:
            argnums.append(4)
            grad_names.append("dtable")
        t0 = time.time()
        gk = jax.block_until_ready(
            jax.jit(jax.grad(loss(kernel_fn), argnums=tuple(argnums)))(
                q, k, v, bias, table
            )
        )
        rec["bwd_compile_run_s"] = round(time.time() - t0, 2)
        gr = jax.block_until_ready(
            jax.jit(jax.grad(loss(ref_fn), argnums=tuple(argnums)))(
                q, k, v, bias, table
            )
        )
        for gname, a_, b_ in zip(grad_names, gk, gr):
            rec[f"{gname}_max_rel_err"] = _max_rel_err(a_, b_)

    # bf16 inputs with f32 kernel accumulation: errors land ~1e-3;
    # 2e-2 is the alarm threshold, not the expectation
    tol = 2e-2
    errs = {k_: v_ for k_, v_ in rec.items() if k_.endswith("_max_rel_err")}
    rec["ok"] = all(e <= tol for e in errs.values())
    rec["tol"] = tol
    return rec


def _phase_main(phase: str) -> None:
    _set_platform()
    for name, ph, spec in CASES:
        if ph != phase:
            continue
        try:
            runner = (
                _run_fused_ce_case
                if spec.get("kind") == "fused_ce"
                else _run_case
            )
            rec = runner(name, spec)
        except Exception as e:  # keep sweeping: one bad case != no record
            rec = {"case": name, "spec": spec, "ok": False,
                   "error": f"{type(e).__name__}: {e}"[:500]}
        print(json.dumps(rec), flush=True)


def _harvest(stdout: str) -> list:
    recs = []
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return recs


def _run_phase_subprocess(arg: str, timeout_s: float) -> tuple:
    """Returns (records, status). Harvests partial output on timeout."""
    if timeout_s <= 5:
        return [], "skipped: deadline exhausted"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), arg],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as e:
        out = e.stdout
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return _harvest(out), (
            f"killed: exceeded its {timeout_s:.0f}s budget (slow cases or "
            "a wedged relay); partial records harvested"
        )
    recs = _harvest(proc.stdout)
    if proc.returncode != 0:
        tail = (proc.stdout[-300:] + proc.stderr[-300:]).strip()
        return recs, f"rc={proc.returncode}: {tail[-300:]}"
    return recs, "ok"


def _write_record(preflight, phase_status, cases, progress, path, mode):
    """Emit the cumulative record: summary line to stdout always; the
    durable file only when ``path`` is set (``None`` = print-only, used
    for provisional/degraded states that must not clobber a prior
    compiled artifact — parents harvest stdout either way)."""
    n_ok = sum(1 for c in cases if c.get("ok"))
    # standalone-load the (stdlib-only) ledger module: the supervising
    # parent must not import the package (jax + native build); memoized
    # in sys.modules so per-phase record writes share one instance
    import importlib.util

    _ledger = sys.modules.get("_tdx_ledger")
    if _ledger is None:
        spec = importlib.util.spec_from_file_location(
            "_tdx_ledger",
            os.path.join(REPO, "torchdistx_tpu", "obs", "ledger.py"),
        )
        _ledger = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(_ledger)
        sys.modules["_tdx_ledger"] = _ledger

    record = {
        # interpret-mode smoke runs get a distinct metric name so no
        # consumer can mistake them for compiled-Mosaic acceptance
        "metric": ("flash_kernel_onchip_acceptance"
                   if mode == "compiled-mosaic"
                   else "flash_kernel_interpret_smoke"),
        **_ledger.record_stamp(),
        "mode": mode,
        "progress": progress,
        "preflight": preflight,
        "phase_status": phase_status,
        "cases_total_defined": len(CASES),
        "cases_run": len(cases),
        "cases_ok": n_ok,
        # the sweep's promise is the WHOLE surface: partial runs never
        # report aggregate acceptance
        "all_ok": n_ok == len(CASES),
        "cases": cases,
    }
    if path is not None:
        with open(path, "w") as f:
            json.dump(record, f, indent=1)
    print(json.dumps({k: v for k, v in record.items() if k != "cases"}),
          flush=True)


def main() -> None:
    deadline = time.monotonic() + float(
        os.environ.get("TDX_VERIFY_DEADLINE", "1200")
    )

    def left() -> float:
        return deadline - time.monotonic()

    # Path/mode resolution: trust an explicit TDX_VERIFY_PLATFORM before
    # preflight; an unset/tpu value is re-checked against the device the
    # preflight actually reaches (env drift can silently yield CPU).
    env_platform = os.environ.get("TDX_VERIFY_PLATFORM")
    compiled = env_platform in (None, "tpu")
    out_path = ACCEPT_PATH if compiled else SMOKE_PATH
    mode = "compiled-mosaic" if compiled else "interpret-smoke"
    # Prior compiled evidence must survive until THIS run has produced
    # real evidence of its own: while one exists, provisional/degraded
    # states are print-only (no window where a hard kill mid-preflight
    # leaves a 'started' stub where the real record was); it is also
    # stashed so the soft env-drift path can restore it.
    def _load_prior(path):
        if not os.path.exists(path):
            return None, False
        with open(path) as f:
            text = f.read()
        try:
            complete = json.loads(text).get("progress") == "complete"
        except json.JSONDecodeError:
            complete = False
        return text, complete

    # both artifacts get the same protection: the committed smoke record
    # is evidence too, and an early-dying smoke rerun (e.g. the CPU
    # bench hitting its deadline) must not leave a caseless stub there
    prior_accept, prior_complete = _load_prior(out_path)

    phase_status: dict = {}
    cases: list = []

    def record_path(final_complete=False):
        # Evidence must never be replaced by strictly worse evidence:
        # no prior artifact -> always write; prior partial -> write once
        # this run has harvested a case (fresher partial supersedes
        # partial, caseless stubs never land); prior COMPLETE -> write
        # only the final record of a run that also completed.  Print-only
        # states still reach stdout, which parents harvest.
        if prior_accept is None:
            return out_path
        if prior_complete:
            return out_path if final_complete else None
        return out_path if cases else None

    _write_record({"skipped": "not reached"}, phase_status, cases,
                  "started", record_path(), mode)

    pre_recs, pre_status = _run_phase_subprocess(
        "--preflight", min(75.0, left())
    )
    preflight = pre_recs[-1] if pre_recs else {"ok": False,
                                              "status": pre_status}
    if compiled and preflight.get("ok") and \
            preflight.get("platform") != "tpu":
        # env drift: the relay handed us a non-TPU device — divert to
        # the smoke artifact; if this run's caseless stub reached
        # ACCEPT_PATH (possible only with no prior artifact), drop it
        if prior_accept is not None:
            with open(ACCEPT_PATH, "w") as f:  # no-op safety rewrite
                f.write(prior_accept)
        elif os.path.exists(ACCEPT_PATH):
            os.remove(ACCEPT_PATH)
        compiled = False
        out_path = SMOKE_PATH
        mode = "interpret-smoke"
        # the acceptance file is settled; from here the guard protects
        # whatever already lives at the smoke path
        prior_accept, prior_complete = _load_prior(SMOKE_PATH)
    _write_record(preflight, phase_status, cases, "preflight-done",
                  record_path(), mode)
    if not preflight.get("ok"):
        # degraded stub: harvested from stdout by any parent; the
        # durable file keeps prior compiled evidence (record_path is
        # None while one exists and no new cases were captured)
        preflight.setdefault(
            "note", "relay unresponsive; kernel acceptance not captured"
        )
        _write_record(preflight, phase_status, cases, "preflight-failed",
                      record_path(), mode)
        return

    for i, phase in enumerate(PHASES):
        # per-phase budget: split what REMAINS over the remaining phases
        n_left = len(PHASES) - i
        budget = max(min(left() / n_left, left() - 10), 120.0)
        recs, status = _run_phase_subprocess(
            f"--phase={phase}", min(budget, left())
        )
        phase_status[phase] = status
        cases.extend(recs)
        _write_record(preflight, phase_status, cases, f"{phase}-done",
                      record_path(), mode)

    # "complete" is reserved for a full sweep: every phase ok AND every
    # defined case ran (a killed phase must not read as completion)
    done = (all(s == "ok" for s in phase_status.values())
            and len(cases) == len(CASES))
    _write_record(preflight, phase_status, cases,
                  "complete" if done else "incomplete",
                  record_path(final_complete=done), mode)


if __name__ == "__main__":
    if "--preflight" in sys.argv:
        print(json.dumps(_preflight()), flush=True)
    elif any(a.startswith("--phase=") for a in sys.argv):
        _phase_main(next(a.split("=", 1)[1] for a in sys.argv
                         if a.startswith("--phase=")))
    else:
        main()
