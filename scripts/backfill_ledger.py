"""Backfill ``LEDGER.jsonl`` from every committed bench artifact.

Normalizes the whole committed evidence trail — ``BENCH_r01..r05``,
``BENCH_r03_local``, ``BENCH_SERVE_<CPU|TPU>.json``,
``MULTICHIP_r01..r05``, ``CAMPAIGN.json``, ``KERNEL_ACCEPT*.json`` —
into ``tdx-ledger-v1`` rows, attributed to the commit that landed each
artifact (``git log -1`` sha + author time, since the old records carry
no stamp of their own) and ordered by that time, so the perf trajectory
is populated from PR 1 onward.  Degraded rounds (the r02 crash, the r03
timeout, the r04/r05 wedged-relay runs) land with ``quality: degraded``
— recorded, never a baseline.

The live ledger is append-only; this script is the one sanctioned
rewrite (regenerating history from the artifacts it is derived from),
so it refuses to touch an existing file without ``--force``.

Usage:
  python scripts/backfill_ledger.py              # writes <repo>/LEDGER.jsonl
  python scripts/backfill_ledger.py --force      # regenerate in place
  python scripts/backfill_ledger.py --out /tmp/ledger.jsonl
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchdistx_tpu.obs import ledger as ledger_mod  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ARTIFACT_GLOBS = (
    "BENCH_r*.json",
    "BENCH_SERVE_*.json",
    "MULTICHIP_r*.json",
    "CAMPAIGN.json",
    "KERNEL_ACCEPT.json",
    "KERNEL_ACCEPT_SMOKE.json",
)


def collect_rows(repo: str = REPO) -> tuple:
    rows, report = [], []
    for pattern in ARTIFACT_GLOBS:
        for path in sorted(glob.glob(os.path.join(repo, pattern))):
            try:
                got = ledger_mod.ingest_artifact(path)
            except (OSError, ValueError) as e:
                report.append((os.path.basename(path), f"SKIPPED: {e}"))
                continue
            rows.extend(got)
            quals = sorted({r["quality"] for r in got})
            report.append(
                (os.path.basename(path),
                 f"{len(got)} row(s), quality={','.join(quals) or 'n/a'}")
            )
    rows.sort(key=lambda r: (r.get("ts") or 0, r["run_id"], r["metric"]))
    return rows, report


def main() -> None:
    ap = argparse.ArgumentParser(description="regenerate the ledger from "
                                 "committed artifacts")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  ledger_mod.LEDGER_BASENAME))
    ap.add_argument("--force", action="store_true",
                    help="overwrite an existing ledger")
    args = ap.parse_args()
    if os.path.exists(args.out) and not args.force:
        raise SystemExit(
            f"{args.out} exists — the ledger is append-only; pass --force "
            "to regenerate it from the committed artifacts"
        )
    rows, report = collect_rows()
    for name, line in report:
        print(f"  {name}: {line}")
    if not rows:
        raise SystemExit("backfill_ledger: no artifacts ingested")
    if os.path.exists(args.out):
        os.remove(args.out)
    n = ledger_mod.append_rows(args.out, rows)
    errs = ledger_mod.validate_ledger_file(args.out)
    if errs:
        raise SystemExit("backfill produced an invalid ledger: "
                         + "; ".join(errs[:5]))
    print(f"backfill_ledger: {n} row(s) from {len(report)} artifact(s) "
          f"-> {args.out}")


if __name__ == "__main__":
    main()
