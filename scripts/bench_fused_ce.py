"""Fused LM-head cross-entropy vs the unfused logits path, on-chip.

Kernel-level companion to the end-to-end ``TDX_BENCH_FUSED_CE=1 bench.py``
A/B: times value_and_grad of the loss alone (matmul + CE fwd + dX + dW)
at LM-head shapes, fused (``ops.fused_ce``: logits never in HBM) vs
unfused (XLA einsum + f32 log-softmax).  Each measurement jits a
lax.scan of ``iters`` applications so the timed region is multi-second —
per-op timings through the axon relay are unreliable (CLAUDE.md).

Usage:
    python scripts/bench_fused_ce.py            # real TPU
    python scripts/bench_fused_ce.py --cpu --shapes 256x128x1000 --iters 2
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--shapes",
        # NxDxV: bench shape (2x2048 tokens, llama_1b head) plus a 7B-ish
        # head and a small control
        default="4096x2048x32000,4096x4096x32000,1024x1024x32000",
    )
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--cpu", action="store_true", help="smoke on CPU")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    from jax import lax

    from torchdistx_tpu.nn import functional
    from torchdistx_tpu.ops.fused_ce import fused_linear_cross_entropy

    def unfused(x, w, y):
        return functional.cross_entropy(jnp.einsum("nd,vd->nv", x, w), y)

    def fused(x, w, y):
        return fused_linear_cross_entropy(x, w, y)

    def timed(fn, x, w, y, iters):
        import numpy as np

        grad = jax.value_and_grad(fn, argnums=(0, 1))

        @jax.jit
        def many(x, w, y):
            def body(c, _):
                # perturb x by the carry so iterations chain — otherwise
                # XLA hoists the loop-invariant loss out of the scan
                l, (dx, dw) = grad(
                    x * (1.0 + c * 1e-30).astype(x.dtype), w, y
                )
                # consume EVERY gradient: an unused dx/dw is dead code XLA
                # eliminates, and the timed region would be forward-only
                # (the round-3 flash-bench lesson, BASELINE.md)
                c = (
                    l.astype(jnp.float32)
                    + dx.sum().astype(jnp.float32) * 1e-30
                    + dw.sum().astype(jnp.float32) * 1e-30
                )
                return c, None
            out, _ = lax.scan(body, jnp.float32(0), None, length=iters)
            return out

        r = many(x, w, y)  # compile + warm
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        r = many(x, w, y)
        jax.block_until_ready(r)
        dt = time.perf_counter() - t0
        assert np.isfinite(float(r))
        return dt / iters

    for spec in args.shapes.split(","):
        n, d, v = (int(s) for s in spec.split("x"))
        ks = jax.random.split(jax.random.PRNGKey(0), 3)  # tdx-lint: disable=TDX102 -- fixed-seed bench input data, not parameter init
        x = jax.random.normal(ks[0], (n, d), jnp.bfloat16)
        w = jax.random.normal(ks[1], (v, d), jnp.bfloat16) * 0.1
        y = jax.random.randint(ks[2], (n,), 0, v)
        t_un = timed(unfused, x, w, y, args.iters)
        t_fu = timed(fused, x, w, y, args.iters)
        from torchdistx_tpu.obs.ledger import record_stamp

        print(json.dumps({
            **record_stamp(),
            "shape": spec,
            "unfused_ms": round(t_un * 1e3, 3),
            "fused_ms": round(t_fu * 1e3, 3),
            "speedup": round(t_un / t_fu, 3),
            "device": str(jax.devices()[0]),
        }), flush=True)


if __name__ == "__main__":
    main()
