"""Decode throughput: tokens/sec for KV-cache generation, bf16 vs
weight-only int8 (``--quantize``).

Decode is weight-read-bound — each generated token streams the full
parameter set from HBM — so int8 weights should approach 2x bf16 decode
throughput on large models.  Timed over a multi-token window (per-op
timings through the axon relay are unreliable, CLAUDE.md).

Usage (TPU):  python scripts/bench_generate.py [--quantize]
Smoke (CPU):  TDX_BENCH_PLATFORM=cpu TDX_GEN_MODEL=tiny \
                  python scripts/bench_generate.py --new-tokens 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quantize", action="store_true",
                    help="weight-only int8 (nn.quantize_module)")
    ap.add_argument("--new-tokens", type=int, default=256)
    ap.add_argument("--batch", type=int, default=1)
    args = ap.parse_args()

    import jax

    plat = os.environ.get("TDX_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    import jax.numpy as jnp
    import numpy as np

    import torchdistx_tpu as tdx
    from torchdistx_tpu.generation import generate
    from torchdistx_tpu.models import Llama
    from torchdistx_tpu.nn import quantize_module

    name = os.environ.get("TDX_GEN_MODEL", "llama_1b")
    dtype = jnp.bfloat16 if plat != "cpu" else jnp.float32

    tdx.manual_seed(0)
    model = tdx.deferred_init(Llama.from_name, name, dtype=dtype)
    tdx.materialize_module(model)
    if args.quantize:
        quantize_module(model)
    n_bytes = sum(
        p.size * p.dtype.itemsize for _, p in model.named_parameters()
    )

    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, (args.batch, 32)),
        jnp.int32,
    )
    # warm: first call compiles prefill + decode scan
    out = generate(model, prompt, max_new_tokens=args.new_tokens)
    np.asarray(out)
    t0 = time.perf_counter()
    out = generate(model, prompt, max_new_tokens=args.new_tokens)
    np.asarray(out)
    dt = time.perf_counter() - t0

    toks = args.batch * args.new_tokens
    from torchdistx_tpu.obs.ledger import record_stamp

    print(json.dumps({
        **record_stamp(),
        "model": name,
        "quantized": args.quantize,
        "param_bytes_gb": round(n_bytes / 1e9, 3),
        "batch": args.batch,
        "new_tokens": args.new_tokens,
        "window_s": round(dt, 3),
        "decode_tokens_per_sec": round(toks / dt, 1),
        # weight-streaming bound: bytes * tokens / window
        "effective_weight_bw_gbps": round(n_bytes * toks / dt / 1e9, 1),
    }))


if __name__ == "__main__":
    main()
