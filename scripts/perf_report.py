"""Trend and differential analytics over the benchmark ledger
(``LEDGER.jsonl``, schema ``tdx-ledger-v1``).

Two modes, both rendering markdown to stdout:

- **trend** (default): one time-series table per (platform, metric,
  fingerprint) group, rows ordered by timestamp — run id, git sha,
  quality, value, and the delta vs the previous COMPLETE row.  Degraded
  rows are shown (the trajectory never hides a wedged round) but never
  used as the delta base.
- **A/B** (``--ab RUN_A RUN_B``): a differential table of every metric
  the two runs share (matched by fingerprint + metric), with the delta
  signed by the metric's direction (``obs.gate.timing_direction``) so
  "better"/"worse" reads correctly for tok/s and for seconds alike.

Usage:
  python scripts/perf_report.py                         # full trend
  python scripts/perf_report.py --metric host_syncs --platform cpu
  python scripts/perf_report.py --source bench_serve --class counter
  python scripts/perf_report.py --ab BENCH_r01 BENCH_r03_local
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchdistx_tpu.obs.gate import timing_direction  # noqa: E402
from torchdistx_tpu.obs.ledger import (  # noqa: E402
    default_ledger_path,
    read_ledger,
)


def _parse_args():
    ap = argparse.ArgumentParser(description="ledger trend/A/B report")
    ap.add_argument("--ledger", default=None, help="default <repo>/LEDGER.jsonl")
    ap.add_argument("--metric", action="append", default=None,
                    help="restrict to metric name(s); repeatable")
    ap.add_argument("--platform", default=None, help="cpu|tpu filter")
    ap.add_argument("--source", default=None,
                    help="artifact family filter (bench, bench_serve, ...)")
    ap.add_argument("--class", dest="metric_class", default=None,
                    choices=["counter", "timing"],
                    help="restrict to one metric class")
    ap.add_argument("--ab", nargs=2, metavar=("RUN_A", "RUN_B"),
                    default=None, help="differential between two run ids")
    ap.add_argument("--max-rows", type=int, default=40,
                    help="per-series row cap in the trend tables")
    return ap.parse_args()


def _fmt(v) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _filter(rows, args):
    out = []
    for r in rows:
        if args.metric and r.get("metric") not in args.metric:
            continue
        if args.platform and r.get("platform") != args.platform:
            continue
        if args.source and r.get("source") != args.source:
            continue
        if args.metric_class and r.get("metric_class") != args.metric_class:
            continue
        out.append(r)
    return out


def _series_key(r):
    return (
        r.get("source") or "",
        r.get("platform") or "",
        r.get("metric") or "",
        r.get("fingerprint") or "",
    )


def trend_report(rows, max_rows: int) -> str:
    series = defaultdict(list)
    for r in rows:
        series[_series_key(r)].append(r)
    lines = ["# Perf trend report", "",
             f"{len(rows)} row(s), {len(series)} series", ""]
    for key in sorted(series):
        source, platform, metric, fp = key
        pts = sorted(series[key], key=lambda r: (r.get("ts") or 0,
                                                 r.get("run_id") or ""))
        if len(pts) > max_rows:
            dropped = len(pts) - max_rows
            pts = pts[-max_rows:]
        else:
            dropped = 0
        head = f"## `{metric}` — {source} / {platform or '?'}"
        lines += [head, "", f"fingerprint: `{fp or '(none)'}`", ""]
        if dropped:
            lines.append(f"_{dropped} older row(s) elided_\n")
        lines += ["| run | git sha | quality | value | Δ vs prev complete |",
                  "| --- | --- | --- | --- | --- |"]
        prev = None
        for p in pts:
            v = p.get("value")
            delta = "—"
            if prev is not None and isinstance(v, (int, float)):
                d = v - prev
                pct = f" ({d / prev * 100:+.1f}%)" if prev else ""
                delta = f"{d:+.6g}{pct}"
            lines.append(
                f"| {p.get('run_id')} | {p.get('git_sha') or '—'} "
                f"| {p.get('quality')} | {_fmt(v)} | {delta} |"
            )
            if p.get("quality") == "complete" and isinstance(
                v, (int, float)
            ):
                prev = v
        lines.append("")
    return "\n".join(lines)


def ab_report(rows, run_a: str, run_b: str) -> str:
    def index(run_id):
        out = {}
        for r in rows:
            if r.get("run_id") == run_id:
                out[(r.get("fingerprint"), r.get("metric"))] = r
        return out

    a, b = index(run_a), index(run_b)
    if not a or not b:
        missing = [rid for rid, idx in ((run_a, a), (run_b, b)) if not idx]
        return (
            f"# A/B report\n\nno ledger rows for run id(s): "
            f"{', '.join(missing)}\n"
        )
    shared = sorted(set(a) & set(b), key=lambda k: (k[1], k[0]))
    lines = [
        f"# A/B: `{run_a}` vs `{run_b}`",
        "",
        f"{len(shared)} shared metric(s) "
        f"({len(a)} in A, {len(b)} in B)",
        "",
        "| metric | fingerprint | A | B | Δ | verdict |",
        "| --- | --- | --- | --- | --- | --- |",
    ]
    for fp, metric in shared:
        ra, rb = a[(fp, metric)], b[(fp, metric)]
        va, vb = ra.get("value"), rb.get("value")
        if not isinstance(va, (int, float)) or not isinstance(
            vb, (int, float)
        ):
            continue
        d = vb - va
        pct = f" ({d / va * 100:+.1f}%)" if va else ""
        if ra.get("metric_class") == "counter":
            verdict = "same" if d == 0 else "**changed**"
        else:
            better_high = timing_direction(metric) == "higher"
            if d == 0:
                verdict = "same"
            elif (d > 0) == better_high:
                verdict = "better"
            else:
                verdict = "worse"
        degraded = "degraded" in (ra.get("quality"), rb.get("quality"))
        if degraded:
            verdict += " (degraded)"
        short_fp = fp if len(fp) <= 48 else fp[:45] + "..."
        # the fingerprint separator is '|' — escape it or it splits the
        # markdown table cells
        short_fp = short_fp.replace("|", "\\|")
        lines.append(
            f"| `{metric}` | `{short_fp}` | {_fmt(va)} | {_fmt(vb)} "
            f"| {d:+.6g}{pct} | {verdict} |"
        )
    lines.append("")
    return "\n".join(lines)


def main() -> None:
    args = _parse_args()
    path = args.ledger or default_ledger_path()
    rows = read_ledger(path)
    if not rows:
        raise SystemExit(f"perf_report: no valid ledger rows in {path}")
    rows = _filter(rows, args)
    if args.ab:
        print(ab_report(rows, args.ab[0], args.ab[1]))
    else:
        print(trend_report(rows, args.max_rows))


if __name__ == "__main__":
    main()
