"""Nightly crash-injection smoke: an injected-NaN ``fit()`` MUST leave a
flight-record dump behind (ISSUE 5 crash-path contract).

Builds a tiny FSDP trainer on the 8-virtual-CPU mesh, trains a few clean
steps (so a health-gated checkpoint exists), poisons a parameter with
NaN, and lets the failure policy roll back.  The gate then demands:

- ``Trainer.last_flight_dump`` exists inside ``TDX_FLIGHT_DIR``;
- the dump is schema-valid (``check_obs_artifacts.py --flight`` logic)
  AND its tail shows the rollback (restored step + checkpoint path);
- the streaming sink (``flight_<pid>.jsonl``, the per-event-flush
  kill -9 channel) also exists and validates — the evidence a hard kill
  would have left.

A second leg (ISSUE 12) injects a ``device_loss`` under
``on_failure="reshard"`` and demands the dump tail show the elastic
recovery: ``failure`` -> ``reshard_start`` -> ``reshard_done`` (naming
both mesh shapes) -> ``rollback``.

Exit nonzero with a reason when any artifact is missing — a crash that
leaves no black box is THE regression this smoke exists to catch.

Usage:  TDX_FLIGHT_DIR=/tmp/flight python scripts/crash_injection_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if not os.environ.get("TDX_FLIGHT_DIR"):
    os.environ["TDX_FLIGHT_DIR"] = tempfile.mkdtemp(prefix="tdx_flight_")
FLIGHT_DIR = os.environ["TDX_FLIGHT_DIR"]

# numerics observatory ON (ISSUE 19): the injected-NaN leg must name the
# poisoned parameter in the failure/rollback flight records, which
# requires the step's fused digests.  setdefault so an explicit
# TDX_NUMERICS=0 run still exercises the plain crash path.
os.environ.setdefault("TDX_NUMERICS", "1")

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import torchdistx_tpu as tdx  # noqa: E402
from torchdistx_tpu import nn  # noqa: E402
from torchdistx_tpu.nn import functional_call  # noqa: E402
from torchdistx_tpu.parallel import ShardedTrainStep, create_mesh  # noqa: E402
from torchdistx_tpu.trainer import Trainer  # noqa: E402
from torchdistx_tpu.utils.failure import FailureDetector  # noqa: E402

from check_obs_artifacts import check_flight  # noqa: E402


class _MLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 64)
        self.fc2 = nn.Linear(64, 16)

    def forward(self, x):
        return self.fc2(jax.nn.relu(self.fc1(x)))


def _build_trainer(seed: int, on_failure: str):
    mesh = create_mesh({"fsdp": 8})
    tdx.manual_seed(seed)
    model = tdx.deferred_init(_MLP)
    tdx.materialize_module(model)
    params = dict(model.named_parameters())

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((functional_call(model, p, (x,)) - y) ** 2)

    step = ShardedTrainStep(loss_fn, optax.sgd(1e-2), mesh, shard_axis="fsdp")
    params = step.shard_params(params)
    opt_state = step.init_optimizer(params)

    rs = np.random.RandomState(seed)
    batches = [(b, b) for b in (rs.randn(8, 16).astype(np.float32)
                                for _ in range(8))]
    detector = FailureDetector(nan_tolerance=0)
    trainer = Trainer(
        step, params, opt_state,
        checkpoint_dir=tempfile.mkdtemp(prefix="crash_smoke_ck_"),
        checkpoint_every=2, log_every=1, log_fn=lambda m: None,
        failure_detector=detector,
        on_failure=on_failure,
    )
    return trainer, detector, batches


def _device_loss_leg(errors: list) -> None:
    """ISSUE 12: a handled ``device_loss`` must leave a schema-valid dump
    whose tail shows the elastic reshard — ``failure`` (kind
    ``device_loss``) then ``reshard_start``/``reshard_done`` naming both
    mesh shapes, then the ``rollback`` bookkeeping entry."""
    trainer, detector, batches = _build_trainer(1, on_failure="reshard")
    trainer.fit(batches[:4])
    detector.inject_device_loss(4)
    res = trainer.fit(batches[4:])

    dump = trainer.last_flight_dump
    if not dump:
        errors.append("device_loss fit() produced NO flight dump")
        return
    check_flight(dump, errors, expect_rollback=True)
    with open(dump) as f:
        records = [json.loads(ln) for ln in f.read().splitlines() if ln.strip()]
    # the flight ring is process-global: earlier legs' records share the
    # dump — anchor on THIS leg's device_loss failure, not the first one
    i_fail = next(
        (i for i, r in enumerate(records)
         if r.get("kind") == "failure"
         and r.get("failure_kind") == "device_loss"),
        None,
    )
    if i_fail is None:
        errors.append(f"device_loss dump {dump}: no device_loss failure record")
        return
    tail_kinds = [r.get("kind") for r in records[i_fail:]]
    for want in ("reshard_start", "reshard_done", "rollback"):
        if want not in tail_kinds:
            errors.append(
                f"device_loss dump {dump}: no {want!r} record after the "
                f"device_loss failure"
            )
            return
    if not (
        tail_kinds.index("reshard_start")
        < tail_kinds.index("reshard_done")
        < tail_kinds.index("rollback")
    ):
        errors.append(f"device_loss dump: out-of-order tail {tail_kinds}")
    done = records[i_fail + tail_kinds.index("reshard_done")]
    if done.get("mesh_from") != {"fsdp": 8} or done.get("mesh_to") != {"fsdp": 4}:
        errors.append(
            f"device_loss dump: reshard_done names "
            f"{done.get('mesh_from')} -> {done.get('mesh_to')}, "
            f"want fsdp 8 -> 4"
        )
    if not np.isfinite(res["loss"]):
        errors.append(f"post-reshard run not recovered: {res}")
    print(f"device-loss dump {dump}: {len(records)} records, reshard OK")


def main() -> None:
    trainer, _, batches = _build_trainer(0, on_failure="restore")
    trainer.fit(batches[:4])

    poisoned = dict(trainer.params)
    k0 = next(iter(poisoned))
    poisoned[k0] = poisoned[k0] * jnp.float32(np.nan)
    trainer.params = poisoned
    res = trainer.fit(batches[4:])

    errors: list = []
    dump = trainer.last_flight_dump
    if not dump:
        errors.append("injected-NaN fit() produced NO flight dump")
    elif not dump.startswith(FLIGHT_DIR):
        errors.append(
            f"dump {dump} landed outside TDX_FLIGHT_DIR={FLIGHT_DIR}"
        )
    else:
        n = check_flight(dump, errors, expect_rollback=True)
        print(f"crash dump {dump}: {n} records")
        # ISSUE 19 provenance: the failure AND rollback records must name
        # the injected site exactly — the digest engine saw the NaN in
        # the poisoned parameter before anything downstream of it.
        if os.environ.get("TDX_NUMERICS") not in ("0", "false", ""):
            want_site = f"params/{k0}"
            with open(dump) as f:
                records = [
                    json.loads(ln) for ln in f.read().splitlines()
                    if ln.strip()
                ]
            for kind in ("failure", "rollback"):
                rec = next(
                    (r for r in records if r.get("kind") == kind), None
                )
                if rec is None:
                    errors.append(f"numerics leg: no {kind!r} record")
                elif rec.get("nonfinite_site") != want_site:
                    errors.append(
                        f"numerics provenance: {kind} record names "
                        f"nonfinite_site={rec.get('nonfinite_site')!r}, "
                        f"want {want_site!r}"
                    )
            book = trainer.numerics_book
            if book.first_nonfinite_site() != want_site:
                errors.append(
                    f"numerics book names {book.first_nonfinite_site()!r},"
                    f" want {want_site!r}"
                )
            else:
                print(f"numerics provenance: {want_site} named in dump")

    stream = os.path.join(FLIGHT_DIR, f"flight_{os.getpid()}.jsonl")
    if not os.path.exists(stream):
        errors.append(f"per-event streaming sink missing: {stream}")
    else:
        check_flight(stream, errors)

    if not np.isfinite(res["loss"]):
        errors.append(f"rollback did not recover the run: {res}")

    _device_loss_leg(errors)

    if errors:
        for e in errors:
            print(f"FAIL: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(
        "crash-injection smoke OK: "
        + json.dumps({"dump": dump, "stream": stream, "final": res})
    )


if __name__ == "__main__":
    main()
