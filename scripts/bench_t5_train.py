"""T5 train-step throughput: the biased-flash-backward delta on real TPU.

Measures one encoder-decoder T5 train step (relative-position bias
streamed into the flash kernels, AnyPrecisionAdamW) with the pallas
biased backward vs the round-3 chunked-recompute backward
(``--chunked-bwd``), using the same multi-second lax.scan window +
layout-fixpoint warmup as bench.py's train phase.

Usage (TPU):  python scripts/bench_t5_train.py [--chunked-bwd]
Smoke (CPU):  TDX_BENCH_PLATFORM=cpu TDX_T5_MODEL=tiny TDX_BENCH_SEQ=64 \
                  python scripts/bench_t5_train.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--chunked-bwd", action="store_true",
        help="force the round-3 chunked-recompute biased backward (A/B)",
    )
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    import jax

    plat = os.environ.get("TDX_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    import torchdistx_tpu as tdx
    from torchdistx_tpu.nn import functional
    from torchdistx_tpu.nn.module import functional_call
    from torchdistx_tpu.models import T5
    from torchdistx_tpu.models.t5 import t5_configs
    from torchdistx_tpu.optimizers import anyprecision_adamw
    from torchdistx_tpu.ops import flash_attention as fa
    from torchdistx_tpu.utils.benchmarks import (
        V5E_PEAK_BF16,
        warm_to_steady_state,
    )

    fa._FORCE_CHUNKED_BWD = args.chunked_bwd

    name = os.environ.get("TDX_T5_MODEL", "t5_large")
    batch = int(os.environ.get("TDX_BENCH_BATCH", "4"))
    seq = int(os.environ.get("TDX_BENCH_SEQ", "512"))
    dtype = jnp.bfloat16 if plat != "cpu" else jnp.float32

    tdx.manual_seed(0)
    model = tdx.deferred_init(
        T5.from_name, name, dtype=dtype, use_flash=True
    )
    tdx.materialize_module(model)
    params = dict(model.named_parameters())
    n_params = model.num_params()

    tx = anyprecision_adamw(1e-4)
    opt_state = tx.init(params)

    cfg = t5_configs[name]
    vocab = cfg.get("vocab_size", 32128)
    rs = np.random.RandomState(0)
    src = jnp.asarray(rs.randint(0, vocab, (batch, seq)), jnp.int32)
    tgt = jnp.asarray(rs.randint(0, vocab, (batch, seq)), jnp.int32)

    def loss_fn(p):
        logits = functional_call(model, p, (src, tgt))
        return functional.cross_entropy(logits, tgt)

    def one_step(carry, _):
        p, s = carry
        loss, g = jax.value_and_grad(loss_fn)(p)
        u, s = tx.update(g, s, p)
        import optax

        return (optax.apply_updates(p, u), s), loss

    n_steps = args.steps

    @jax.jit
    def run(carry):
        return lax.scan(one_step, carry, None, length=n_steps)

    carry = (params, opt_state)
    carry, warm_times, converged = warm_to_steady_state(
        run, carry, sync=lambda losses: float(np.asarray(losses[-1]))
    )
    t0 = time.perf_counter()
    carry, losses = run(carry)
    final = float(np.asarray(losses[-1]))
    dt = time.perf_counter() - t0

    # model FLOPs: 6 * params * tokens (enc+dec both seq-length) + attention
    toks = n_steps * batch * seq
    tokens_per_sec = toks / dt
    flops_per_token = 6 * n_params
    from torchdistx_tpu.obs.ledger import record_stamp

    print(json.dumps({
        **record_stamp(),
        "model": name,
        "params": int(n_params),
        "batch": batch,
        "seq": seq,
        "backward": "chunked" if args.chunked_bwd else "kernel",
        "steps": n_steps,
        "window_s": round(dt, 3),
        "warm_calls_s": [round(t, 2) for t in warm_times],
        "warm_converged": converged,
        "tokens_per_sec": round(tokens_per_sec, 1),
        "approx_mfu": round(
            tokens_per_sec * flops_per_token / V5E_PEAK_BF16, 4
        ),
        "final_loss": round(final, 4),
    }))


if __name__ == "__main__":
    main()
