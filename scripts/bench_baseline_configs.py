"""Measure BASELINE.json configs 1-3 and print one JSON line per config.

  1. deferred_init(Linear(1024, 1024)) -> materialize on CPU PJRT
  2. deferred_init(ResNet-50)          -> materialize on one TPU chip
  3. deferred_init(GPT-2-large)        -> materialize SHARDED across 8
     devices, with peak host RSS (the O(one-tensor) host-RAM claim)

Config 3 runs on the 8-virtual-device CPU mesh when 8 real chips are not
attached (this environment has one TPU); the host-RSS discipline being
measured is host-side either way.  Run config 1+3 with:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/bench_baseline_configs.py --cpu

and config 2 with a TPU attached: python scripts/bench_baseline_configs.py
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def config1():
    import jax

    import torchdistx_tpu as tdx
    from torchdistx_tpu import nn

    t0 = time.time()
    m = tdx.deferred_init(lambda: nn.Linear(1024, 1024))
    tdx.materialize_module(m)
    jax.block_until_ready(m.weight)
    return {
        "config": 1,
        "what": "Linear(1024,1024) deferred+materialize, CPU PJRT",
        "wall_s": round(time.time() - t0, 3),
        "params": m.num_params(),
    }


def config2(replay_mode: str = "auto"):
    import jax

    import torchdistx_tpu as tdx
    from torchdistx_tpu._graph import RecordingSession
    from torchdistx_tpu.models.resnet import resnet50

    # "auto" resolves to chunked replay on TPU for the conv graph: its 34
    # distinct conv/BN closure shapes made op-by-op eager replay compile-
    # dominated through the device relay (21.6 s on-chip, round 3), while
    # the schedule chunks into 7 repeated jitted chunks.  --replay-mode
    # eager reproduces the old path for the A/B.
    RecordingSession.replay_mode = replay_mode
    t0 = time.time()
    tdx.manual_seed(0)
    m = tdx.deferred_init(resnet50)
    t_defer = time.time() - t0
    p0 = next(p for _, p in m.named_parameters())
    sess = p0._session
    t0 = time.time()
    tdx.materialize_module(m)
    jax.block_until_ready([p for _, p in m.named_parameters()])
    resolved = replay_mode
    if replay_mode == "auto":
        # self-describing A/B record: which executor actually ran
        resolved = "chunked" if sess.chunk_dispatches > 0 else "eager"
    return {
        "config": 2,
        "what": "ResNet-50 deferred+materialize, one TPU chip",
        "replay_mode_requested": replay_mode,
        "replay_mode_resolved": resolved,
        "chunk_compiles": sess.chunk_compiles,
        "chunk_dispatches": sess.chunk_dispatches,
        "deferred_s": round(t_defer, 3),
        "materialize_s": round(time.time() - t0, 3),
        "params": m.num_params(),
        "device": str(jax.devices()[0]),
    }


def config3():
    import jax

    import torchdistx_tpu as tdx
    from torchdistx_tpu.models import GPT2
    from torchdistx_tpu.parallel import create_mesh, fsdp_shard_rule

    mesh = create_mesh({"fsdp": 8})
    rss_before = _rss_gb()
    t0 = time.time()
    tdx.manual_seed(0)
    m = tdx.deferred_init(GPT2.from_name, "gpt2_large")
    t_defer = time.time() - t0
    t0 = time.time()
    tdx.materialize_module(m, sharding_rule=fsdp_shard_rule(mesh))
    jax.block_until_ready([p for _, p in m.named_parameters()])
    t_mat = time.time() - t0
    rss_after = _rss_gb()
    n = m.num_params()
    return {
        "config": 3,
        "what": "GPT-2-large deferred+materialize SHARDED over 8 devices",
        "deferred_s": round(t_defer, 3),
        "materialize_s": round(t_mat, 3),
        "params": n,
        "param_bytes_gb": round(n * 4 / 1e9, 3),
        "peak_host_rss_delta_gb": round(rss_after - rss_before, 3),
        "n_devices": len(jax.devices()),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="configs 1+3 on CPU mesh")
    ap.add_argument(
        "--replay-mode",
        default="auto",
        choices=("auto", "eager", "chunked"),
        help="config-2 replay executor (auto -> chunked on TPU conv graphs)",
    )
    args = ap.parse_args()
    import jax

    from torchdistx_tpu.obs.ledger import record_stamp

    stamp = record_stamp()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps({**stamp, **config1()}))
        print(json.dumps({**stamp, **config3()}))
    else:
        print(json.dumps({**stamp, **config2(args.replay_mode)}))


if __name__ == "__main__":
    main()
