"""Optimizer numerics.

AnyPrecisionAdamW spec: with fp32 states and Kahan off it must match
standard AdamW (reference test_anyprecision_optimizer.py:24-59 checks
equivalence to torch.optim.AdamW over 6 steps); Kahan+bf16 must track an
fp32 run more closely than plain bf16.  SlowMomentum spec: closed-form slow
update check (reference test_comm_hooks_fsdp.py:242-260)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchdistx_tpu.optimizers import AnyPrecisionAdamW, anyprecision_adamw
from torchdistx_tpu.slowmo import SlowMomentumOptimizer, slow_momentum


def _problem(seed=0, dtype=np.float32):
    rs = np.random.RandomState(seed)
    params = {
        "w": jnp.asarray(rs.randn(8, 4).astype(dtype)),
        "b": jnp.asarray(rs.randn(4).astype(dtype)),
    }
    x = jnp.asarray(rs.randn(16, 8).astype(dtype))
    y = jnp.asarray(rs.randn(16, 4).astype(dtype))

    def loss_fn(p):
        return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

    return params, loss_fn


class TestAnyPrecisionAdamW:
    def test_fp32_no_kahan_matches_adamw(self):
        params, loss_fn = _problem()
        tx = anyprecision_adamw(
            1e-2,
            weight_decay=0.01,
            momentum_dtype=jnp.float32,
            variance_dtype=jnp.float32,
            use_kahan_summation=False,
        )
        ref_tx = optax.adamw(1e-2, weight_decay=0.01)

        p1, s1 = dict(params), tx.init(params)
        p2, s2 = dict(params), ref_tx.init(params)
        for _ in range(6):
            g1 = jax.grad(loss_fn)(p1)
            u1, s1 = tx.update(g1, s1, p1)
            p1 = jax.tree_util.tree_map(lambda a, b: a + b, p1, u1)
            g2 = jax.grad(loss_fn)(p2)
            u2, s2 = ref_tx.update(g2, s2, p2)
            p2 = jax.tree_util.tree_map(lambda a, b: a + b, p2, u2)
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=1e-5, atol=1e-6
            )

    def test_matches_torch_adamw(self):
        torch = pytest.importorskip("torch")
        params, loss_fn = _problem(seed=3)
        tx = anyprecision_adamw(
            1e-2,
            weight_decay=0.01,
            momentum_dtype=jnp.float32,
            variance_dtype=jnp.float32,
        )
        p, s = dict(params), tx.init(params)

        tw = torch.nn.Parameter(torch.tensor(np.asarray(params["w"])))
        tb = torch.nn.Parameter(torch.tensor(np.asarray(params["b"])))
        topt = torch.optim.AdamW([tw, tb], lr=1e-2, weight_decay=0.01)

        for _ in range(6):
            g = jax.grad(loss_fn)(p)
            u, s = tx.update(g, s, p)
            p = jax.tree_util.tree_map(lambda a, b: a + b, p, u)

            topt.zero_grad()
            tw.grad = torch.tensor(np.asarray(g["w"]))
            tb.grad = torch.tensor(np.asarray(g["b"]))
            # keep gradients identical on both sides: feed JAX grads at the
            # matching parameter point is only valid while trajectories agree,
            # which equivalence guarantees inductively
            topt.step()
        np.testing.assert_allclose(
            np.asarray(p["w"]), tw.detach().numpy(), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(p["b"]), tb.detach().numpy(), rtol=1e-4, atol=1e-5
        )

    def test_kahan_bf16_tracks_fp32_better(self):
        # bf16 params, tiny updates: Kahan must stay closer to the fp32 run
        n_steps = 200
        params32 = {"w": jnp.ones((256,), jnp.float32)}
        params16 = {"w": jnp.ones((256,), jnp.bfloat16)}
        grad32 = {"w": jnp.full((256,), 1e-3, jnp.float32)}
        grad16 = {"w": jnp.full((256,), 1e-3, jnp.bfloat16)}

        def run(params, grads, **kw):
            tx = anyprecision_adamw(1e-4, **kw)
            p, s = dict(params), tx.init(params)
            step = jax.jit(lambda p, s: tx.update(grads, s, p))
            for _ in range(n_steps):
                u, s = step(p, s)
                p = jax.tree_util.tree_map(lambda a, b: a + b, p, u)
            return np.asarray(p["w"], np.float64)

        ref = run(
            params32,
            grad32,
            momentum_dtype=jnp.float32,
            variance_dtype=jnp.float32,
        )
        plain = run(params16, grad16, use_kahan_summation=False)
        kahan = run(params16, grad16, use_kahan_summation=True)
        err_plain = np.abs(plain - ref).mean()
        err_kahan = np.abs(kahan - ref).mean()
        assert err_kahan < err_plain
        # the compensation accounts for the train step's second rounding
        # (p + round(new_p - p)), so the tracked error stays under a bf16
        # ulp at 1.0 (~3.9e-3) while the plain run loses every update
        assert err_kahan < 2e-3

    def test_class_wrapper(self):
        params, loss_fn = _problem(seed=1)
        opt = AnyPrecisionAdamW(params, lr=1e-2)
        g = jax.grad(loss_fn)(params)
        p2 = opt.step(params, g)
        assert p2["w"].shape == params["w"].shape
        assert float(loss_fn(p2)) < float(loss_fn(params))


class TestSlowMomentum:
    def test_closed_form_slow_update(self):
        # scalar problem, slowmo_freq=2, identity averaging (single replica)
        base_lr = 0.1
        tx = slow_momentum(
            optax.sgd(base_lr),
            slowmo_freq=2,
            slowmo_factor=0.5,
            slowmo_lr=1.0,
            base_lr=base_lr,
            average_fn=lambda t: t,
        )
        p0 = {"w": jnp.asarray(1.0)}
        grads = {"w": jnp.asarray(0.2)}
        s = tx.init(p0)
        # step 1: fast only: w = 1 - 0.1*0.2 = 0.98
        u, s = tx.update(grads, s, p0)
        p1 = {"w": p0["w"] + u["w"]}
        np.testing.assert_allclose(float(p1["w"]), 0.98, rtol=1e-6)
        # step 2: fast: 0.98 - 0.02 = 0.96; slow: v = 0.5*0 + (1-0.96)/0.1
        # = 0.4; w = 1 - 1.0*0.1*0.4 = 0.96  (first avg reduces to fast)
        u, s = tx.update(grads, s, p1)
        p2 = {"w": p1["w"] + u["w"]}
        np.testing.assert_allclose(float(p2["w"]), 0.96, rtol=1e-6)
        # prev_params updated to 0.96, momentum to 0.4
        np.testing.assert_allclose(float(s.slow_momentum["w"]), 0.4, rtol=1e-6)
        np.testing.assert_allclose(float(s.prev_params["w"]), 0.96, rtol=1e-6)
        # steps 3+4: fast to 0.92; slow: v = 0.5*0.4 + (0.96-0.92)/0.1 = 0.6
        # w = 0.96 - 0.06 = 0.90
        u, s = tx.update(grads, s, p2)
        p3 = {"w": p2["w"] + u["w"]}
        u, s = tx.update(grads, s, p3)
        p4 = {"w": p3["w"] + u["w"]}
        np.testing.assert_allclose(float(p4["w"]), 0.90, rtol=1e-5)

    def test_replica_average_on_stacked(self):
        # divergent-replica layout: averaging equalizes replicas every freq
        tx = slow_momentum(
            optax.sgd(0.1), slowmo_freq=1, base_lr=0.1, slowmo_lr=1.0,
            slowmo_factor=0.0,
        )
        p = {"w": jnp.asarray([[1.0], [3.0]])}  # 2 replicas
        g = {"w": jnp.zeros((2, 1))}
        s = tx.init(p)
        u, s = tx.update(g, s, p)
        p = jax.tree_util.tree_map(lambda a, b: a + b, p, u)
        # avg = 2.0; v = (prev - avg)/lr = [[-10],[10]]; w = prev - 0.1*v
        # prev=[1,3] -> w = [1+1, 3-1] = [2,2]
        np.testing.assert_allclose(np.asarray(p["w"]), [[2.0], [2.0]])

    def test_state_dict_roundtrip(self):
        params = {"w": jnp.ones((4,))}
        opt = SlowMomentumOptimizer(
            params, optax.sgd(0.1), slowmo_freq=3, base_lr=0.1
        )
        g = {"w": jnp.full((4,), 0.1)}
        params = opt.step(params, g)
        sd = opt.state_dict()
        opt2 = SlowMomentumOptimizer(
            {"w": jnp.zeros((4,))}, optax.sgd(0.1), base_lr=0.1
        )
        opt2.load_state_dict(sd)
        assert opt2.slowmo_freq == 3
        assert int(opt2.state.count) == 1
        np.testing.assert_allclose(
            np.asarray(opt2.state.prev_params["w"]), np.ones(4)
        )

    def test_load_state_dict_governs_behavior(self):
        # regression: restored hyperparams must drive the actual update, not
        # just the attributes — the loaded slowmo_freq=2 (vs constructed
        # default 48) must trigger the slow update at the right step
        params = {"w": jnp.asarray([[1.0], [3.0]])}  # 2 divergent replicas
        opt = SlowMomentumOptimizer(
            params, optax.sgd(0.1), slowmo_freq=2, base_lr=0.1,
            slowmo_factor=0.0, slowmo_lr=1.0,
        )
        sd = opt.state_dict()
        opt2 = SlowMomentumOptimizer(
            params, optax.sgd(0.1), base_lr=0.1
        )  # default freq=48
        opt2.load_state_dict(sd)
        g = {"w": jnp.zeros((2, 1))}
        p = opt2.step(params, g)          # count=1: fast only
        assert not np.allclose(np.asarray(p["w"])[0], np.asarray(p["w"])[1])
        p = opt2.step(p, g)               # count=2: slow update -> averaged
        np.testing.assert_allclose(np.asarray(p["w"]), [[2.0], [2.0]])


class TestAdam8bit:
    """Blockwise int8 moment state: quantization error bounds, convergence
    tracking f32 AdamW, and the ~3x state-size reduction that motivates it
    (optimizer HBM traffic, round-3 profile)."""

    def test_quantize_roundtrip_error_bound(self):
        from torchdistx_tpu.optimizers import (
            blockwise_dequantize,
            blockwise_quantize,
        )

        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(3, 1000).astype(np.float32))
        codes, scales = blockwise_quantize(x, 256, signed=True)
        back = blockwise_dequantize(codes, scales, x.shape)
        # error per element <= half a quantization step of its block
        err = np.abs(np.asarray(back - x))
        step_bound = np.asarray(scales).max() * 0.5 + 1e-12
        assert err.max() <= step_bound
        # unsigned (second-moment) path: power-law codes — absolute error
        # bounded by half the map's max step (absmax * p / 510), and
        # small-but-nonzero values must NOT collapse to zero (the Adam
        # divergence hazard the power map exists to prevent)
        v = jnp.abs(x)
        codes_u, absmax = blockwise_quantize(v, 256, signed=False)
        back_u = blockwise_dequantize(codes_u, absmax, v.shape)
        assert np.abs(np.asarray(back_u - v)).max() <= (
            np.asarray(absmax).max() * (4.0 / 510.0) * 1.01
        )
        assert codes_u.dtype == jnp.uint8 and codes.dtype == jnp.int8
        tiny = jnp.full((256,), 1e-6).at[0].set(1.0)  # 1e-6 of absmax
        ct, st = blockwise_quantize(tiny, 256, signed=False)
        bt = blockwise_dequantize(ct, st, tiny.shape)
        assert float(bt[1]) > 0, "small v must stay representable"
        np.testing.assert_allclose(float(bt[1]), 1e-6, rtol=0.5)

    def test_converges_like_f32_adamw(self):
        from torchdistx_tpu.optimizers import adamw_8bit

        rs = np.random.RandomState(1)
        w_true = rs.randn(16, 1).astype(np.float32)
        X = rs.randn(256, 16).astype(np.float32)
        y = X @ w_true

        def loss_fn(p):
            return jnp.mean((jnp.asarray(X) @ p["w"] - jnp.asarray(y)) ** 2)

        losses = {}
        for name, tx in (
            ("8bit", adamw_8bit(3e-2)),
            ("f32", optax.adamw(3e-2)),
        ):
            p = {"w": jnp.zeros((16, 1), jnp.float32)}
            s = tx.init(p)

            @jax.jit
            def step(p, s, tx=tx):
                g = jax.grad(loss_fn)(p)
                u, s = tx.update(g, s, p)
                return optax.apply_updates(p, u), s

            for _ in range(300):
                p, s = step(p, s)
            losses[name] = float(loss_fn(p))
        # both must solve the problem; 8-bit within 10x of f32's residual
        assert losses["f32"] < 1e-3
        assert losses["8bit"] < max(10 * losses["f32"], 1e-2), losses

    def test_tuple_containing_params_pytree(self):
        # the flat-list state layout must handle ANY params structure —
        # a params-shaped tree of (codes, scales) pairs was misparsed by
        # tuple-leaf extraction before
        from torchdistx_tpu.optimizers import adamw_8bit

        tx = adamw_8bit(1e-2)
        p = {"layers": [(jnp.ones((4, 4)), jnp.zeros((4,)))]}
        s = tx.init(p)
        g = jax.tree_util.tree_map(jnp.ones_like, p)
        u, s = tx.update(g, s, p)
        assert jax.tree_util.tree_structure(u) == (
            jax.tree_util.tree_structure(p)
        )
        for leaf in jax.tree_util.tree_leaves(u):
            assert bool(jnp.all(jnp.isfinite(leaf)))

    def test_nonconvex_multiscale_tracks_f32(self):
        # regression pin for the linear-v-codes divergence: an MLP's v
        # spans orders of magnitude within a block; with linear codes
        # small v collapsed to 0 -> 1/eps updates -> loss exploded by
        # step ~5 (observed on GPT-2).  The power-law map must track f32
        # AdamW through real nonconvex training.
        from torchdistx_tpu.optimizers import adamw_8bit

        rs = np.random.RandomState(3)
        X = jnp.asarray(rs.randn(128, 16).astype(np.float32))
        y = jnp.asarray(np.sin(np.asarray(X).sum(1, keepdims=True)))
        p0 = {
            "w1": jnp.asarray(rs.randn(16, 64).astype(np.float32) * 0.1),
            "b1": jnp.zeros((64,), jnp.float32),
            "w2": jnp.asarray(rs.randn(64, 1).astype(np.float32) * 0.1),
        }

        def loss_fn(p):
            h = jax.nn.gelu(X @ p["w1"] + p["b1"])
            return jnp.mean((h @ p["w2"] - y) ** 2)

        finals = {}
        for name, tx in (
            ("8bit", adamw_8bit(1e-2)),
            ("f32", optax.adamw(1e-2)),
        ):
            p = dict(p0)
            s = tx.init(p)

            @jax.jit
            def step(p, s, tx=tx):
                g = jax.grad(loss_fn)(p)
                u, s = tx.update(g, s, p)
                return optax.apply_updates(p, u), s

            traj = []
            for _ in range(200):
                p, s = step(p, s)
                traj.append(float(loss_fn(p)))
            assert all(np.isfinite(traj)), f"{name} diverged"
            finals[name] = traj[-1]
        assert finals["f32"] < 0.05
        assert finals["8bit"] < 3 * finals["f32"] + 0.02, finals

    def test_state_bytes_reduction(self):
        from torchdistx_tpu.optimizers import adamw_8bit, anyprecision_adamw

        p = {"w": jnp.zeros((4096, 256), jnp.bfloat16)}
        s8 = adamw_8bit(1e-3).init(p)
        sap = anyprecision_adamw(1e-3).init(p)

        def nbytes(tree):
            return sum(
                x.size * x.dtype.itemsize
                for x in jax.tree_util.tree_leaves(tree)
                if hasattr(x, "dtype")
            )

        n_params = 4096 * 256
        assert nbytes(s8) < 2.2 * n_params       # ~2.03 B/param
        assert nbytes(sap) >= 6 * n_params       # f32 m + bf16 v

    def test_works_under_scan_and_checkpoint_roundtrip(self):
        from torchdistx_tpu.optimizers import adamw_8bit

        tx = adamw_8bit(1e-2)
        p = {"w": jnp.ones((8, 8), jnp.float32)}
        s = tx.init(p)

        def body(carry, _):
            p, s = carry
            g = jax.tree_util.tree_map(jnp.ones_like, p)
            u, s = tx.update(g, s, p)
            return (optax.apply_updates(p, u), s), None

        (p2, s2), _ = jax.jit(
            lambda c: jax.lax.scan(body, c, None, length=4)
        )((p, s))
        assert int(s2.count) == 4
        # state is a plain pytree of arrays: flatten/unflatten round-trips
        leaves, treedef = jax.tree_util.tree_flatten(s2)
        s3 = jax.tree_util.tree_unflatten(treedef, leaves)
        chex_like = jax.tree_util.tree_all(
            jax.tree_util.tree_map(
                lambda a, b: bool(jnp.all(a == b)), s2, s3
            )
        )
        assert chex_like

    def test_state_shardings_helper(self):
        # ZeRO-style placement: code/scale arrays shard their leading
        # n_blocks dim over the axis when divisible, else replicate
        import jax

        from torchdistx_tpu.optimizers import (
            adam8bit_state_shardings,
            adamw_8bit,
        )
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"fsdp": 8})
        tx = adamw_8bit(1e-3)
        p = {"w": jnp.zeros((4096, 64)), "b": jnp.zeros((17,))}
        s = tx.init(p)
        shardings = adam8bit_state_shardings(s, mesh)
        placed = jax.device_put(s, shardings)
        # w: 4096*64/256 = 1024 blocks -> sharded; b: 1 block -> replicated
        big = [x for x in placed.m_codes if x.shape[0] % 8 == 0]
        assert all(x.sharding.spec[0] == "fsdp" for x in big)
        # non-divisible n_blocks falls back to the (always power-of-2)
        # block dim instead of silently replicating
        small = [x for x in placed.m_codes if x.shape[0] % 8 != 0]
        assert all(
            len(x.sharding.spec) >= 2 and x.sharding.spec[1] == "fsdp"
            for x in small
        )
        # a quantized update runs on the placed state
        g = jax.tree_util.tree_map(jnp.ones_like, p)
        u, s2 = tx.update(g, placed, p)
        assert int(s2.count) == 1


class TestParamGroups:
    """Per-group hyperparameters: the reference's param_groups protocol
    (anyprecision_optimizer.py:75-107 iterates groups with their own
    lr/betas/eps/weight_decay) mapped to labeled pytree leaves."""

    def test_two_groups_match_torch_adamw(self):
        torch = pytest.importorskip("torch")
        from torchdistx_tpu.optimizers import with_param_groups

        params, loss_fn = _problem(seed=5)
        tx = with_param_groups(
            anyprecision_adamw,
            groups={
                "decay": {"weight_decay": 0.01},
                "no_decay": {"weight_decay": 0.0, "learning_rate": 5e-3},
            },
            labels={"w": "decay", "b": "no_decay"},
            learning_rate=1e-2,
            momentum_dtype=jnp.float32,
            variance_dtype=jnp.float32,
        )
        p, s = dict(params), tx.init(params)

        tw = torch.nn.Parameter(torch.tensor(np.asarray(params["w"])))
        tb = torch.nn.Parameter(torch.tensor(np.asarray(params["b"])))
        topt = torch.optim.AdamW(
            [
                {"params": [tw], "weight_decay": 0.01},
                {"params": [tb], "weight_decay": 0.0, "lr": 5e-3},
            ],
            lr=1e-2,
        )
        for _ in range(6):
            g = jax.grad(loss_fn)(p)
            u, s = tx.update(g, s, p)
            p = jax.tree_util.tree_map(lambda a, b: a + b, p, u)
            topt.zero_grad()
            tw.grad = torch.tensor(np.asarray(g["w"]))
            tb.grad = torch.tensor(np.asarray(g["b"]))
            topt.step()
        np.testing.assert_allclose(
            np.asarray(p["w"]), tw.detach().numpy(), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(p["b"]), tb.detach().numpy(), rtol=1e-4, atol=1e-5
        )

    def test_class_group_list_matches_torch(self):
        # the torch-style constructor surface on the stateful class
        torch = pytest.importorskip("torch")
        params, loss_fn = _problem(seed=7)
        opt = AnyPrecisionAdamW(
            [
                {"params": {"w": params["w"]}, "weight_decay": 0.01},
                {"params": {"b": params["b"]}, "weight_decay": 0.0,
                 "lr": 5e-3},
            ],
            lr=1e-2,
            momentum_dtype=jnp.float32,
            variance_dtype=jnp.float32,
        )
        p = [{"w": params["w"]}, {"b": params["b"]}]

        tw = torch.nn.Parameter(torch.tensor(np.asarray(params["w"])))
        tb = torch.nn.Parameter(torch.tensor(np.asarray(params["b"])))
        topt = torch.optim.AdamW(
            [
                {"params": [tw], "weight_decay": 0.01},
                {"params": [tb], "weight_decay": 0.0, "lr": 5e-3},
            ],
            lr=1e-2,
        )
        for _ in range(6):
            flat = {"w": p[0]["w"], "b": p[1]["b"]}
            g = jax.grad(loss_fn)(flat)
            p = opt.step(p, [{"w": g["w"]}, {"b": g["b"]}])
            topt.zero_grad()
            tw.grad = torch.tensor(np.asarray(g["w"]))
            tb.grad = torch.tensor(np.asarray(g["b"]))
            topt.step()
        np.testing.assert_allclose(
            np.asarray(p[0]["w"]), tw.detach().numpy(), rtol=1e-4,
            atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(p[1]["b"]), tb.detach().numpy(), rtol=1e-4,
            atol=1e-5,
        )

    def test_class_group_list_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown keys"):
            AnyPrecisionAdamW(
                [{"params": {"w": jnp.ones(3)}, "lr_wrong": 1.0}]
            )

    def test_decay_labels_heuristic(self):
        from torchdistx_tpu.optimizers import decay_labels

        params = {
            "blocks": [{"attn_w": jnp.ones((4, 4)), "bias": jnp.ones(4)}],
            "ln_scale": jnp.ones(4),
            "norm_w": jnp.ones((4, 4)),  # 2D but norm-named
        }
        labels = decay_labels(params)
        assert labels["blocks"][0]["attn_w"] == "decay"
        assert labels["blocks"][0]["bias"] == "no_decay"
        assert labels["ln_scale"] == "no_decay"
        assert labels["norm_w"] == "no_decay"

    def test_unknown_label_raises(self):
        from torchdistx_tpu.optimizers import with_param_groups

        with pytest.raises(ValueError, match="undefined groups"):
            with_param_groups(
                anyprecision_adamw,
                groups={"decay": {}},
                labels={"w": "decay", "b": "typo"},
            )

    def test_adamw_8bit_per_group_lr(self):
        # the same combinator over the quantized-state factory: a frozen
        # group (lr=0) must not move while the live group trains
        from torchdistx_tpu.optimizers import adamw_8bit, with_param_groups

        params, loss_fn = _problem(seed=9)
        tx = with_param_groups(
            adamw_8bit,
            groups={"live": {}, "frozen": {"learning_rate": 0.0}},
            labels={"w": "live", "b": "frozen"},
            learning_rate=1e-2,
        )
        p, s = dict(params), tx.init(params)
        for _ in range(3):
            g = jax.grad(loss_fn)(p)
            u, s = tx.update(g, s, p)
            p = jax.tree_util.tree_map(lambda a, b: a + b, p, u)
        np.testing.assert_allclose(
            np.asarray(p["b"]), np.asarray(params["b"]), atol=0
        )
        assert not np.allclose(np.asarray(p["w"]), np.asarray(params["w"]))

    def test_state_checkpoint_roundtrip(self, tmp_path):
        # grouped state is an ordinary pytree: orbax save -> template
        # restore -> bit-identical continued trajectory
        from torchdistx_tpu.optimizers import with_param_groups
        from torchdistx_tpu.utils.checkpoint import (
            restore_checkpoint,
            save_checkpoint,
        )

        params, loss_fn = _problem(seed=11)

        def make_tx():
            return with_param_groups(
                anyprecision_adamw,
                groups={"decay": {"weight_decay": 0.01},
                        "no_decay": {"weight_decay": 0.0}},
                labels={"w": "decay", "b": "no_decay"},
                learning_rate=1e-2,
                use_kahan_summation=True,
            )

        tx = make_tx()
        p, s = dict(params), tx.init(params)
        for _ in range(3):
            g = jax.grad(loss_fn)(p)
            u, s = tx.update(g, s, p)
            p = jax.tree_util.tree_map(lambda a, b: a + b, p, u)
        save_checkpoint(str(tmp_path / "pg"), {"state": s, "params": p})

        tx2 = make_tx()
        template = tx2.init(params)
        out = restore_checkpoint(
            str(tmp_path / "pg"), like={"state": template, "params": p}
        )
        p2, s2 = out["params"], out["state"]

        def advance(p_, s_, tx_):
            g = jax.grad(loss_fn)(p_)
            u, s_ = tx_.update(g, s_, p_)
            return jax.tree_util.tree_map(lambda a, b: a + b, p_, u), s_

        p, s = advance(p, s, tx)
        p2, s2 = advance(p2, s2, tx2)
        for k in p:
            np.testing.assert_array_equal(np.asarray(p[k]), np.asarray(p2[k]))

    def test_grouped_state_shardings_follow_params(self, mesh8):
        # multi_transform moment trees carry MaskedNode holes; the
        # sharding derivation must still route each moment leaf to its
        # parameter's sharding instead of the replicated fallback
        # (replicated 7B moments = the HBM-overcommit class)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torchdistx_tpu.optimizers import with_param_groups
        from torchdistx_tpu.parallel import create_mesh
        from torchdistx_tpu.parallel.fsdp import optimizer_state_shardings

        params = {
            "w": jax.device_put(
                jnp.zeros((64, 8)), NamedSharding(mesh8, P("fsdp"))
            ),
            "b": jax.device_put(
                jnp.zeros((8,)), NamedSharding(mesh8, P())
            ),
        }
        tx = with_param_groups(
            anyprecision_adamw,
            groups={"decay": {"weight_decay": 0.01}, "no_decay": {}},
            labels={"w": "decay", "b": "no_decay"},
            learning_rate=1e-3,
            momentum_dtype=jnp.float32,
            variance_dtype=jnp.float32,
        )
        state_shape = jax.eval_shape(tx.init, params)
        sh = optimizer_state_shardings(state_shape, params, mesh8)
        decay = sh.inner_states["decay"].inner_state
        no_decay = sh.inner_states["no_decay"].inner_state
        assert decay.exp_avg["w"].spec == P("fsdp")
        assert decay.exp_avg_sq["w"].spec == P("fsdp")
        assert no_decay.exp_avg["b"].spec == P()
        assert decay.count.spec == P()
