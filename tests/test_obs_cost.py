"""Device cost observatory (obs.cost), HBM capacity planner
(obs.memory.capacity_plan), and dispatch-stall watchdog (obs.watchdog)
— the ISSUE 8 pinned invariants:

- **Card determinism**: two cards of the same program carry bit-identical
  XLA flop/byte counts on a fixed platform — the property that lets the
  perf gate pin them exactly like host_syncs.
- **Single implementation**: ``utils.profiling.cost_summary`` is a
  projection of ``obs.cost.compute_cost_card`` (same numbers, same
  schema as before the refactor).
- **Named provenance**: every card names its peak-bytes source; an
  unnamed source fails schema validation, and a runtime-watermark peak
  never joins the deterministic counter fields.
- **Three exports**: a recorded card is queryable from the book,
  renders as ``tdx_cost_*{program=...}`` through the Prometheus
  registry, lands a Perfetto counter sample on the shared timebase,
  and normalizes into exact-gating ledger counter rows.
- **Capacity planning**: ``capacity_plan`` headroom/fits arithmetic;
  ``sharding_report(budget_bytes_per_device=...)`` per-shard budgets
  (flag-free under budget, ``over_budget`` flag past it).
- **Watchdog**: a simulated expiry (injected fake timer — no sleeping)
  dumps a schema-valid flight record naming the in-flight program AND
  its cost card; a normal exit cancels the timer.

The engine-level admission-gate pins live in tests/test_serve.py
(TestHBMBudgetGate); the dryrun TP leg asserts the per-shard budget
report flag-free in ``__graft_entry__.py``.
"""

import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu import obs
from torchdistx_tpu.models import Llama
from torchdistx_tpu.obs.cost import (
    CostBook,
    CostCard,
    compute_cost_card,
    span_mfu,
    validate_cost_card,
)
from torchdistx_tpu.obs.flight import FlightRecorder, validate_flight_jsonl
from torchdistx_tpu.obs.memory import capacity_plan, sharding_report
from torchdistx_tpu.obs.watchdog import DispatchWatchdog
from torchdistx_tpu.serve import ServeEngine
from torchdistx_tpu.utils import profiling


@pytest.fixture
def cards_on(monkeypatch):
    """Re-enable cost-card capture (conftest defaults TDX_COST_CARDS=0
    to keep the suite fast)."""
    monkeypatch.setenv("TDX_COST_CARDS", "1")


def _toy(x):
    return (x @ x).sum()


_X = jnp.ones((32, 32), jnp.float32)


class TestCostCard:
    def test_card_fields_and_schema(self):
        card = compute_cost_card(_toy, _X, name="toy")
        assert card.program == "toy"
        assert card.flops and card.flops > 0
        assert card.bytes_accessed and card.bytes_accessed > 0
        # this jax's memory_analysis has no peak field: the shim must
        # NAME the fallback, never report an unsourced number
        assert card.peak_source in ("xla_peak", "arg+out+temp")
        assert card.peak_bytes and card.peak_bytes > 0
        assert validate_cost_card(card.to_json()) == []

    def test_deterministic_counts(self):
        """The exact-gate premise: same program, same platform ⇒
        bit-identical counts."""
        a = compute_cost_card(_toy, _X, name="a")
        b = compute_cost_card(_toy, _X, name="b")
        assert a.counter_fields() == b.counter_fields()

    def test_flop_attribution(self):
        analytic = 2.0 * 32 * 32 * 32  # the matmul term alone
        card = compute_cost_card(
            _toy, _X, name="toy", analytic_flops=analytic
        )
        # XLA additionally counts the reduction; the ratio must land
        # near 1, not at it
        assert 0.5 < card.flop_attribution < 1.5

    def test_scope_attribution(self):
        """The card records the ENCLOSING recompile scope (what a
        dispatch-path compile would be attributed to), while its own
        compile is attributed to a cost_card/ scope — never confused
        with a real recompile."""
        # a shape no other test compiles, so the card's own compile
        # really happens (a cache hit emits no event); built OUTSIDE
        # the scope — array creation itself is a backend compile
        x = jnp.ones((17, 17))
        watcher = obs.RecompileWatcher()
        try:
            with obs.recompile_scope("serve/decode"):
                card = compute_cost_card(_toy, x, name="scoped")
        finally:
            watcher.uninstall()
        assert card.scope == "serve/decode"
        if watcher.available:
            assert "serve/decode" not in watcher.counts
            assert any(
                k.startswith("cost_card/") for k in watcher.counts
            ), watcher.counts

    def test_watermark_peak_never_gates(self):
        card = CostCard(
            program="p", flops=1.0, bytes_accessed=1.0,
            peak_bytes=123, peak_source="hbm_watermark:host_rusage",
        )
        assert "cost_peak_bytes" not in card.counter_fields()
        assert "cost_flops" in card.counter_fields()

    def test_validate_errors(self):
        errs = validate_cost_card({"schema": "tdx-cost-v1"})
        assert any("program" in e for e in errs)
        assert any("flops" in e for e in errs)
        assert any("source not named" in e for e in errs)

    def test_cost_summary_is_a_projection(self):
        """The satellite refactor: cost_summary delegates to the card
        and keeps its record schema (profile_train_step contract)."""
        card = compute_cost_card(_toy, _X, name="toy")
        out = profiling.cost_summary(_toy, _X, peak_flops=1e12)
        assert out["flops"] == card.flops
        assert out["bytes_accessed"] == card.bytes_accessed
        assert set(out) == {
            "flops", "bytes_accessed", "arithmetic_intensity",
            "output_bytes", "transcendentals", "compute_bound_s",
        }
        assert out["compute_bound_s"] == card.flops / 1e12

    def test_kill_switch_spellings_agree(self, monkeypatch):
        """cards_enabled and force_disabled must read ONE off-list: an
        empty or case-variant TDX_COST_CARDS can never half-engage the
        kill switch (replay sites off but engine/trainer still on)."""
        from torchdistx_tpu.obs.cost import cards_enabled, force_disabled

        for off in ("0", "false", "False", "FALSE", "", " 0 "):
            monkeypatch.setenv("TDX_COST_CARDS", off)
            assert not cards_enabled(default=True)
            assert force_disabled()
        for on in ("1", "true", "yes"):
            monkeypatch.setenv("TDX_COST_CARDS", on)
            assert cards_enabled(default=False)
            assert not force_disabled()
        monkeypatch.delenv("TDX_COST_CARDS")
        assert cards_enabled(default=True) and not cards_enabled(
            default=False
        )
        assert not force_disabled()  # unset = defaults apply, no force

    def test_span_mfu(self):
        card = CostCard(program="p", flops=100.0)
        assert span_mfu(
            card, executions=5, seconds=2.0, peak_flops=1000.0
        ) == pytest.approx(0.25)
        assert span_mfu(
            card, executions=5, seconds=2.0, peak_flops=None
        ) is None


class TestCostBook:
    def test_record_and_query(self):
        book = CostBook()
        compute_cost_card(_toy, _X, name="toy", book=book)
        assert book.get("toy").flops > 0
        assert list(book.to_json()) == ["toy"]
        assert book.max_temp_bytes() == book.get("toy").temp_bytes

    def test_prometheus_projection(self):
        book = CostBook()
        card = compute_cost_card(_toy, _X, name="toy", book=book)
        reg = obs.MetricsRegistry()
        reg.register_collector(book.collector())
        parsed = obs.parse_prometheus(reg.render())
        key = ("tdx_cost_flops", (("program", "toy"),))
        assert parsed["samples"][key] == card.flops
        peak_key = (
            "tdx_cost_peak_bytes",
            (("program", "toy"), ("source", card.peak_source)),
        )
        assert parsed["samples"][peak_key] == card.peak_bytes

    def test_perfetto_counter_track(self):
        t = obs.enable_tracing()
        t.clear()
        try:
            book = CostBook()
            compute_cost_card(_toy, _X, name="toy", book=book)
            counters = [
                ev for ev in t.events()
                if ev["ph"] == "C" and ev["name"] == "cost/toy"
            ]
            assert counters and counters[0]["args"]["flops"] > 0
        finally:
            obs.disable_tracing()
            t.clear()


class TestCapacityPlan:
    def test_fits_arithmetic(self):
        plan = capacity_plan(
            {"weights": 100, "kv_cache": 50}, budget_bytes=200
        )
        assert plan["projected_peak_bytes"] == 150
        assert plan["headroom_bytes"] == 50
        assert plan["fits"] is True
        assert plan["budget_source"] == "explicit"
        assert capacity_plan({"weights": 100}, budget_bytes=99)["fits"] is False

    def test_unknown_budget_is_unknown_not_yes(self):
        # the CPU mesh reports no PJRT bytes_limit: fits must be None
        plan = capacity_plan({"weights": 100})
        assert plan["fits"] is None
        assert plan["headroom_bytes"] is None

    def test_non_numeric_components_dropped(self):
        plan = capacity_plan(
            {"weights": 10, "bogus": None, "flag": True}, budget_bytes=20
        )
        assert plan["components"] == {"weights": 10}

    def test_sharding_report_shard_budget(self):
        params = {"w": jnp.ones((64, 64)), "b": jnp.ones((64,))}
        opt = {"mu['w']": jnp.ones((64, 64))}
        per_dev = (64 * 64 + 64 + 64 * 64) * 4
        rep = sharding_report(
            params, optimizer_state=None,
            budget_bytes_per_device=per_dev + 1000,
        )
        assert rep["shard_budget"]["bytes_per_device"] <= per_dev
        assert rep["shard_budget"]["headroom_bytes"] > 0
        assert not any(f["kind"] == "over_budget" for f in rep["flags"])
        over = sharding_report(
            params, optimizer_state=opt, budget_bytes_per_device=100
        )
        # optimizer state counts toward the per-shard footprint
        assert (
            over["shard_budget"]["bytes_per_device"]
            == over["bytes_per_device"] + over["optimizer_bytes_per_device"]
        )
        assert any(f["kind"] == "over_budget" for f in over["flags"])
        assert over["shard_budget"]["headroom_bytes"] < 0


class _FakeTimer:
    """Injected timer: never sleeps; the test fires it by hand."""

    instances: list = []

    def __init__(self, interval, fn):
        self.interval = interval
        self.fn = fn
        self.started = False
        self.cancelled = False
        _FakeTimer.instances.append(self)

    def start(self):
        self.started = True

    def cancel(self):
        self.cancelled = True

    def fire(self):
        self.fn()


class TestWatchdog:
    def setup_method(self):
        _FakeTimer.instances = []

    def test_expiry_dumps_flight_with_program_and_card(self, tmp_path):
        flight = FlightRecorder(dump_dir=str(tmp_path))
        book = CostBook()
        book.record(
            CostCard(
                program="serve/decode/k4", flops=123.0,
                bytes_accessed=9.0, peak_bytes=7, peak_source="arg+out+temp",
            )
        )
        fake_now = [100.0]
        dog = DispatchWatchdog(
            5.0, flight=flight, book=book,
            clock=lambda: fake_now[0], timer=_FakeTimer,
        )
        with dog.arm("serve/decode/k4"):
            fake_now[0] = 107.5  # the region overran its deadline
            _FakeTimer.instances[-1].fire()
        assert dog.stalls_total == 1
        assert dog.last_dump_path and validate_flight_jsonl(
            dog.last_dump_path
        ) == []
        with open(dog.last_dump_path) as f:
            records = [json.loads(ln) for ln in f if ln.strip()]
        header = records[0]
        assert header["kind"] == "flight_header"
        assert header["reason"] == "watchdog_stall:serve/decode/k4"
        stall = next(r for r in records if r["kind"] == "stall")
        assert stall["program"] == "serve/decode/k4"
        assert stall["armed_s"] == pytest.approx(7.5)
        assert stall["cost_card"]["flops"] == 123.0

    def test_normal_exit_cancels(self, tmp_path):
        flight = FlightRecorder(dump_dir=str(tmp_path))
        dog = DispatchWatchdog(5.0, flight=flight, timer=_FakeTimer)
        with dog.arm("trainer/step"):
            pass
        t = _FakeTimer.instances[-1]
        assert t.started and t.cancelled
        assert dog.stalls_total == 0
        assert dog.last_dump_path is None
        assert dog.last_program == "trainer/step"  # attribution persists

    def test_rejects_nonpositive_timeout(self):
        with pytest.raises(ValueError):
            DispatchWatchdog(0.0)


class TestServeEngineCards:
    def test_every_dispatched_program_has_a_card(self, cards_on):
        tdx.manual_seed(0)
        model = Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)
        engine = ServeEngine(model, num_slots=2, max_len=64)
        rs = np.random.RandomState(0)
        engine.run(
            [
                {"prompt": rs.randint(0, 64, (6,)).astype(np.int32),
                 "max_new_tokens": 3}
                for _ in range(3)
            ]
        )
        cards = engine.cost_book.cards()
        assert "serve/prefill/b16" in cards
        assert "serve/decode/k1" in cards
        for card in cards.values():
            assert validate_cost_card(card.to_json()) == []
        plan = engine.memory_plan()
        assert plan["components"]["program_temp"] == (
            engine.cost_book.max_temp_bytes()
        )
        assert plan["components"]["kv_cache"] == engine.cache.nbytes
        assert plan["projected_peak_bytes"] == sum(
            plan["components"].values()
        )

    def test_persistent_program_card(self, cards_on):
        tdx.manual_seed(0)
        model = Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)
        engine = ServeEngine(
            model, num_slots=2, max_len=64,
            decode_mode="persistent", ring_capacity=8,
        )
        engine.run([{"prompt": np.arange(1, 5, dtype=np.int32),
                     "max_new_tokens": 3}])
        assert "serve/decode/persistent/r8" in engine.cost_book.cards()

    def test_kill_switch(self):
        # conftest sets TDX_COST_CARDS=0: the default-on engine must
        # honor the force-disable and capture nothing
        tdx.manual_seed(0)
        model = Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)
        engine = ServeEngine(model, num_slots=2, max_len=64)
        engine.run([{"prompt": np.arange(1, 5, dtype=np.int32),
                     "max_new_tokens": 2}])
        assert len(engine.cost_book) == 0

    def test_watchdog_attribution_after_run(self, cards_on):
        tdx.manual_seed(0)
        model = Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)
        engine = ServeEngine(
            model, num_slots=2, max_len=64, stall_timeout_s=300.0
        )
        engine.run([{"prompt": np.arange(1, 5, dtype=np.int32),
                     "max_new_tokens": 2}])
        assert engine.watchdog.stalls_total == 0
        assert engine.watchdog.last_program.startswith("serve/decode")


class TestTrainerCostCard:
    def _fit(self, **kw):
        from torchdistx_tpu.trainer import Trainer

        @jax.jit
        def step(p, s, batch):
            x, y = batch
            loss = jnp.mean((x @ p["w"] - y) ** 2)
            return p, s, loss

        params = {"w": jnp.ones((8, 8))}
        batches = [
            (np.ones((2, 8), np.float32), np.zeros((2, 8), np.float32))
            for _ in range(3)
        ]
        trainer = Trainer(
            step, params, opt_state={}, log_every=1,
            log_fn=lambda m: None, tokens_per_batch=16,
            flops_per_token=64.0, **kw,
        )
        trainer.fit(batches)
        return trainer

    def test_card_and_per_window_mfu_xla(self, cards_on):
        trainer = self._fit()
        assert trainer.cost_card is not None
        assert trainer.cost_card.program == "trainer/step"
        assert trainer.cost_card.flops > 0
        # per-window attribution, not an end-of-run aggregate: both the
        # XLA-counted MFU and the analytic/XLA ratio are live gauges
        assert trainer.metrics["mfu_xla"] > 0
        assert trainer.metrics["flop_attribution"] == (
            trainer.cost_card.flop_attribution
        )
        reg = obs.MetricsRegistry()
        reg.register_collector(trainer.metrics_collector(), obj=trainer)
        parsed = obs.parse_prometheus(reg.render())
        assert ("tdx_train_mfu_xla", ()) in parsed["samples"]

    def test_disabled_by_param(self, cards_on):
        trainer = self._fit(cost_card=False)
        assert trainer.cost_card is None
        assert trainer.metrics["mfu_xla"] is None


class TestLedgerCostRows:
    def _phase(self):
        return {
            "platform": "cpu",
            "model": "tiny",
            "num_slots": 2,
            "decode_chunk": 1,
            "decode_mode": "chunked",
            "metrics": {"counters": {"host_syncs": 3}},
            "cost_cards": {
                "serve/decode/k1": {
                    "schema": "tdx-cost-v1",
                    "program": "serve/decode/k1",
                    "flops": 703242.0,
                    "bytes_accessed": 100.0,
                    "temp_bytes": 7,
                    "peak_bytes": 17,
                    "peak_source": "arg+out+temp",
                },
                "serve/prefill/b16": {
                    "schema": "tdx-cost-v1",
                    "program": "serve/prefill/b16",
                    "flops": 1.0,
                    "bytes_accessed": 2.0,
                    "peak_bytes": 999,
                    "peak_source": "hbm_watermark:host_rusage",
                },
            },
        }

    def test_serve_cards_become_exact_counter_rows(self):
        from torchdistx_tpu.obs.ledger import (
            ingest_serve_record,
            validate_ledger_row,
        )

        rows = ingest_serve_record(
            {"phases": {"k1": self._phase()}}, run_id="r", ts=1.0
        )
        assert all(validate_ledger_row(r) == [] for r in rows)
        cost_rows = [r for r in rows if r["metric"].startswith("cost_")]
        assert all(r["metric_class"] == "counter" for r in cost_rows)
        by = {
            (r["workload"].get("program"), r["metric"]): r["value"]
            for r in cost_rows
        }
        assert by[("serve/decode/k1", "cost_flops")] == 703242.0
        assert by[("serve/decode/k1", "cost_peak_bytes")] == 17
        # a watermark-sourced peak is load-dependent: never a counter
        assert ("serve/prefill/b16", "cost_peak_bytes") not in by
        assert by[("serve/prefill/b16", "cost_flops")] == 1.0
        # program-tagged fingerprints keep per-program pins distinct
        fps = {r["fingerprint"] for r in cost_rows}
        assert len(fps) == 2

    def test_bench_train_card_rows(self):
        from torchdistx_tpu.obs.ledger import ingest_bench_record

        record = {
            "metric": "m", "value": 1.0,
            "extra": {
                "progress": "complete",
                "device": "TFRT_CPU_0",
                "train_model": "tiny",
                "train_cost_card": {
                    "schema": "tdx-cost-v1",
                    "program": "train/step",
                    "flops": 5.0,
                    "bytes_accessed": 6.0,
                    "flop_attribution": 0.9,
                    "peak_source": "arg+out+temp",
                    "peak_bytes": 3,
                },
                "mfu_xla": 0.5,
            },
        }
        rows = ingest_bench_record(record, run_id="r")
        metrics = {r["metric"]: r for r in rows}
        assert metrics["cost_flops"]["value"] == 5.0
        assert metrics["cost_flops"]["metric_class"] == "counter"
        assert metrics["train_flop_attribution"]["value"] == 0.9
        assert metrics["train_flop_attribution"]["metric_class"] == "counter"
        assert metrics["mfu_xla"]["metric_class"] == "timing"

    def test_auto_pins_exclude_buffer_assignment_sizes(self):
        """Machine-written expectations pin the HLO-analysis counts
        (flops/bytes) but not allocator-dependent sizes — those drift
        across XLA versions the way warm-up compile counts do."""
        from torchdistx_tpu.obs.gate import build_expectations
        from torchdistx_tpu.obs.ledger import ingest_serve_record

        rows = ingest_serve_record(
            {"phases": {"k1": self._phase()}}, run_id="r", ts=1.0
        )
        doc = build_expectations(rows)
        pinned = {m for ms in doc["counters"].values() for m in ms}
        assert "cost_flops" in pinned
        assert "cost_bytes_accessed" in pinned
        assert "cost_temp_bytes" not in pinned
        assert "cost_peak_bytes" not in pinned


class TestCostCLI:
    def test_check_obs_artifacts_cost(self, tmp_path):
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "scripts", "check_obs_artifacts.py")
        good = {
            "phases": {
                "k1": {
                    "cost_cards": {
                        "serve/decode/k1": {
                            "schema": "tdx-cost-v1",
                            "program": "serve/decode/k1",
                            "flops": 1.0,
                            "bytes_accessed": 2.0,
                            "peak_bytes": 3,
                            "peak_source": "arg+out+temp",
                        }
                    }
                }
            }
        }
        p_good = tmp_path / "good.json"
        p_good.write_text(json.dumps(good))
        out = subprocess.run(
            [sys.executable, script, "--cost", str(p_good)],
            capture_output=True, text=True,
        )
        assert out.returncode == 0, out.stderr
        bad = {"phases": {"k1": {"metrics": {}}}}  # no cards, no error
        p_bad = tmp_path / "bad.json"
        p_bad.write_text(json.dumps(bad))
        out = subprocess.run(
            [sys.executable, script, "--cost", str(p_bad)],
            capture_output=True, text=True,
        )
        assert out.returncode == 1
        assert "cost_cards" in out.stderr
