"""Cross-replica request tracing (ISSUE 14 tentpole).

The pinned contract, on the 8-device CPU mesh:

- **Spans tile bitwise**: ``obs.trace.fleet_request_spans`` returns a
  telescoping chain — consecutive spans share their boundary float
  VERBATIM, the first starts on ``submitted_at``, the last ends on
  ``finished_at`` — so the per-span durations sum EXACTLY (as reals,
  pinned via ``fractions.Fraction`` over the float boundaries) to the
  e2e aggregate, across replicas, handoff gap included.
- **Migration never breaks the tiling**: a mid-decode ``migrate_to``
  segments the decode span at the migration boundary; the identity
  survives ``fleet.remove``.
- **One flow per request**: ``ServeFleet.dump_trace`` merges every
  replica (retired ones included) into per-replica process tracks, each
  request one flow-linked chain keyed on its process-unique
  ``trace_id`` — every flow id resolves (an ``s`` and an ``f``
  endpoint), the disaggregated chain crosses process tracks.
- **The scrape surface answers "which replica is slow"**: the fleet
  collector renders per-replica TTFT/TPOT/e2e quantile summaries.
"""

import json
from fractions import Fraction

import numpy as np
import pytest
from jax.sharding import Mesh

import jax
import torchdistx_tpu as tdx
from torchdistx_tpu.models import Llama
from torchdistx_tpu.obs import MetricsRegistry
from torchdistx_tpu.obs.trace import (
    _FLEET_PID_BASE,
    fleet_request_spans,
    fleet_request_trace_events,
)
from torchdistx_tpu.serve import ServeEngine, ServeFleet
from torchdistx_tpu.serve.scheduler import Request


def _llama():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


def _engine(tp, slots, paged=False, **kw):
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", (32,))
    kw.setdefault("decode_chunk", 2)
    if paged:
        kw.setdefault("page_size", 8)
        kw.setdefault("num_pages", 32)
    if tp > 1:
        kw["mesh"] = Mesh(np.asarray(jax.devices()[:tp]), ("tp",))
    return ServeEngine(_llama(), num_slots=slots, **kw)


def _prompts(seed, n, prefix_len=16, tail_len=4):
    rs = np.random.RandomState(seed)
    prefix = rs.randint(0, 256, (prefix_len,)).astype(np.int32)
    return [
        np.concatenate(
            [prefix, rs.randint(0, 256, (tail_len,)).astype(np.int32)]
        )
        for _ in range(n)
    ]


def _assert_tiles_bitwise(req, expect_names=None):
    """The exactness pin: telescoping boundaries + Fraction-sum identity
    (floats represent their values exactly; summing the exact per-span
    differences must reproduce the exact e2e difference)."""
    spans = fleet_request_spans(req)
    assert spans, f"no spans for request {req.rid}"
    assert spans[0][1] == req.submitted_at
    assert spans[-1][2] == req.finished_at
    for (_, _, t1), (_, t0, _) in zip(spans, spans[1:]):
        assert t1 == t0  # shared boundary, verbatim float
    total = sum(
        (Fraction(t1) - Fraction(t0) for _, t0, t1 in spans),
        Fraction(0),
    )
    assert total == Fraction(req.finished_at) - Fraction(req.submitted_at)
    if expect_names is not None:
        assert [s[0] for s in spans] == expect_names
    return spans


class TestSpanTiling:
    def test_disagg_request_chain_is_bitwise_exact(self):
        """The acceptance pin: a disaggregated request's spans — routed
        on the prefill replica, finished on the decode replica — tile
        ``[submitted_at, finished_at]`` exactly, handoff gap included."""
        reqs = [
            dict(prompt=p, max_new_tokens=m)
            for p, m in zip(_prompts(21, 3), [4, 6, 4])
        ]
        pre, dec = _engine(1, 3), _engine(1, 3)
        fleet = ServeFleet([pre, dec], disaggregate=True)
        fleet.run(reqs)
        finished = fleet.finished_requests()
        assert len(finished) == len(reqs)
        for req in finished:
            spans = _assert_tiles_bitwise(req)
            names = [s[0] for s in spans]
            assert names[:3] == ["route", "queued", "prefill"]
            assert "handoff" in names
            assert names[-1] == "decode"
        # trace ids are unique across the whole fleet and ordered
        tids = [r.trace_id for r in finished]
        assert len(set(tids)) == len(tids)
        assert tids == sorted(tids)

    def test_migrated_request_survives_remove(self):
        """A mid-decode ``fleet.remove`` migration segments the decode
        span at the boundary — the identity still holds, and the fleet's
        merged history (retired replica included) still carries every
        request."""
        reqs = [
            dict(prompt=p, max_new_tokens=8) for p in _prompts(23, 4)
        ]
        fleet = ServeFleet(
            [_engine(1, 2) for _ in range(3)], policy="round-robin"
        )
        handles = [fleet.submit(**r) for r in reqs]
        fleet.step()  # everyone admitted and mid-stream
        victim = fleet.replicas[0]
        assert victim.engine.scheduler.running
        fleet.remove(victim.rid)
        while fleet.step():
            pass
        assert all(h.done() for h in handles)
        finished = fleet.finished_requests()
        assert len(finished) == len(reqs)
        migrated = [
            r
            for r in finished
            if any(
                n == "migrated" and not (d or {}).get("queued")
                for n, _, d in r.events
            )
        ]
        assert migrated, "remove() migrated no running request"
        for req in migrated:
            spans = _assert_tiles_bitwise(req)
            # the migration split the decode window into >= 2 segments
            assert [s[0] for s in spans].count("decode") >= 2
        for req in finished:
            _assert_tiles_bitwise(req)

    def test_expired_while_queued_chain_ends_at_queued(self):
        req = Request(rid=0, prompt=np.arange(4, dtype=np.int32),
                      max_new_tokens=2, trace_id=7)
        req.submitted_at = 100.0
        req.record_event("routed", ts=100.25, replica=0)
        req.finished_at = 101.0
        _assert_tiles_bitwise(req, ["route", "queued"])
        # and without fleet context there is no route span at all
        req.events.clear()
        _assert_tiles_bitwise(req, ["queued"])


class TestMergedTrace:
    def test_dump_trace_flow_integrity_across_process_tracks(
        self, tmp_path
    ):
        """The merged Perfetto export: every request is one flow whose
        id resolves (one ``s``, one ``f``), the disaggregated chain
        crosses from the prefill track to the decode track, and both
        replicas render as named process rows."""
        reqs = [
            dict(prompt=p, max_new_tokens=4) for p in _prompts(25, 3)
        ]
        pre, dec = _engine(1, 3), _engine(1, 3)
        fleet = ServeFleet(
            [pre, dec], disaggregate=True, roles=["prefill", "decode"]
        )
        fleet.run(reqs)
        path = tmp_path / "fleet_trace.json"
        fleet.dump_trace(str(path))
        doc = json.loads(path.read_text())
        evs = doc["traceEvents"]
        pre_pid = _FLEET_PID_BASE + fleet.replicas[0].rid
        dec_pid = _FLEET_PID_BASE + fleet.replicas[1].rid
        names = {
            e["pid"]: e["args"]["name"]
            for e in evs
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        assert names[pre_pid].endswith("(prefill)")
        assert names[dec_pid].endswith("(decode)")
        for req in fleet.finished_requests():
            flow = [
                e
                for e in evs
                if e.get("cat") == "req_flow"
                and e.get("id") == req.trace_id
            ]
            phs = [e["ph"] for e in flow]
            assert phs.count("s") == 1 and phs.count("f") == 1
            assert phs[0] == "s" and phs[-1] == "f"
            spans = [
                e
                for e in evs
                if e.get("cat") == "request"
                and e.get("tid") == req.trace_id
            ]
            # routed on the prefill track, finished on the decode track
            assert {e["pid"] for e in spans} == {pre_pid, dec_pid}
            by_name = {e["name"]: e for e in spans}
            assert by_name["prefill"]["pid"] == pre_pid
            assert by_name["decode"]["pid"] == dec_pid
            # the flow endpoints live where their spans live
            assert flow[0]["pid"] == pre_pid
            assert flow[-1]["pid"] == dec_pid
        # the script-side referential-integrity check agrees
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "check_obs_artifacts",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "scripts",
                "check_obs_artifacts.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        errors = []
        mod.check_flow_integrity(str(path), errors)
        assert errors == []

    def test_single_span_chain_still_resolves(self):
        req = Request(rid=3, prompt=np.arange(4, dtype=np.int32),
                      max_new_tokens=2, trace_id=11)
        req.submitted_at = 5.0
        req.finished_at = 6.0
        evs = fleet_request_trace_events([(0, "serve", req)])
        flow = [e for e in evs if e.get("cat") == "req_flow"]
        assert [e["ph"] for e in flow] == ["s", "f"]
        assert flow[1]["bp"] == "e"

    def test_dedup_and_trace_id_ordering(self):
        """The same request arriving via two paths (live + retired)
        renders once; entries order by trace_id."""
        mk = lambda rid, tid: Request(
            rid=rid, prompt=np.arange(4, dtype=np.int32),
            max_new_tokens=2, trace_id=tid,
        )
        a, b = mk(0, 9), mk(0, 8)  # rids collide across replicas
        for r, t in ((a, 1.0), (b, 2.0)):
            r.submitted_at = t
            r.finished_at = t + 1.0
        evs = fleet_request_trace_events(
            [(0, "serve", a), (1, "serve", b), (0, "serve", a)]
        )
        rows = [
            e for e in evs if e.get("ph") == "X" and e["cat"] == "request"
        ]
        assert [e["args"]["trace_id"] for e in rows] == [8, 9]


class TestFleetCollectorQuantiles:
    def test_per_replica_latency_summaries(self):
        reqs = [
            dict(prompt=p, max_new_tokens=4) for p in _prompts(27, 4)
        ]
        fleet = ServeFleet(
            [_engine(1, 2), _engine(1, 2)], policy="round-robin"
        )
        fleet.run(reqs)
        registry = MetricsRegistry()
        registry.register_collector(fleet.collector(), obj=fleet)
        text = registry.render()
        for hname in ("ttft_s", "tpot_s", "e2e_latency_s"):
            for rep in fleet.replicas:
                rid = str(rep.rid)
                assert (
                    f'tdx_fleet_{hname}{{quantile="0.5",replica="{rid}"}}'
                    in text
                )
                assert (
                    f'tdx_fleet_{hname}{{quantile="0.95",replica="{rid}"}}'
                    in text
                )
                assert f'tdx_fleet_{hname}_count{{replica="{rid}"}}' in text
        # the quantile values agree with the engine histograms' own
        # nearest-rank estimator
        from torchdistx_tpu.obs.metrics import parse_prometheus

        parsed = parse_prometheus(text)
        rep0 = fleet.replicas[0]
        want = rep0.engine.metrics.ttft_s.quantile(0.5)
        got = parsed["samples"][
            ("tdx_fleet_ttft_s", (("quantile", "0.5"), ("replica", "0")))
        ]
        assert got == want


@pytest.mark.slow
class TestSpanTilingGridSlow:
    @pytest.mark.parametrize("paged", [False, True])
    @pytest.mark.parametrize("policy", ["affinity", "round-robin"])
    def test_fleet_grid_every_request_tiles(self, policy, paged):
        """The exhaustive sibling of the fast tiling pins: 3 replicas x
        {policy} x {slab, paged} over a 9-request shared-prefix stream
        with online arrival — every finished request tiles bitwise."""
        prompts = _prompts(29, 9)
        fleet = ServeFleet(
            [_engine(1, 2, paged=paged) for _ in range(3)],
            policy=policy,
        )
        handles = []
        for i, p in enumerate(prompts):
            handles.append(
                fleet.submit(p, max_new_tokens=4 + (i % 3) * 2)
            )
            fleet.step()
        while fleet.step():
            pass
        assert all(h.done() for h in handles)
        finished = fleet.finished_requests()
        assert len(finished) == len(prompts)
        for req in finished:
            _assert_tiles_bitwise(req)
