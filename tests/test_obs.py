"""Unified tracing & telemetry (torchdistx_tpu.obs) — the pinned invariants:

- **Aggregate/per-request agreement**: the engine's ``ttft_s`` /
  ``e2e_latency_s`` / ``tpot_s`` histograms are fed from the SAME request
  lifecycle timestamps that ``RequestResult`` and the Perfetto
  per-request tracks expose — counts and sums must reconcile exactly.
- **Chrome-trace validity**: ``dump_trace``/``Tracer.export`` emit JSON
  that ``json.load`` parses with a well-formed catapult ``traceEvents``
  list, and each finished request's queued/prefill/decode spans sum to
  its e2e latency.
- **Exposition round-trip**: ``render_prometheus`` output survives the
  stdlib ``parse_prometheus`` with every value intact, and the serve
  collector's numbers equal ``ServeMetrics.to_json()``'s.
- **Recompile accounting**: the watcher counts XLA backend compiles and
  attributes them to the active scope; ``warm_to_steady_state`` with a
  watcher registers EXACTLY the expected donated-carry recompile — one
  extra compile on the second call of a layout-changing carry (simulated
  on CPU, where real donation is a no-op and a donated jit must count
  exactly ONE compile total).
"""

import functools
import json
import os
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu import obs
from torchdistx_tpu.models import Llama
from torchdistx_tpu.obs.metrics import MetricFamily
from torchdistx_tpu.serve import ServeEngine
from torchdistx_tpu.serve.metrics import Histogram
from torchdistx_tpu.utils import profiling
from torchdistx_tpu.utils.benchmarks import warm_to_steady_state


@pytest.fixture
def tracer():
    """Enabled, empty global tracer; disabled and drained afterwards so
    other tests (and the serve engines they warm) never cross-talk."""
    t = obs.enable_tracing()
    t.clear()
    yield t
    obs.disable_tracing()
    t.clear()


def _llama():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 256, (n,)).astype(np.int32) for n in lengths]


class TestTracer:
    def test_span_instant_counter_and_export(self, tracer, tmp_path):
        with tracer.span("outer", cat="test", k=1):
            with tracer.span("inner"):
                pass
            tracer.instant("tick", note="x")
        tracer.counter("depth", a=1.0, b=2.0)
        evs = tracer.events()
        # complete events record at span EXIT: inner closes first, the
        # instant fires inside outer, outer closes last
        assert [e["name"] for e in evs] == ["inner", "tick", "outer", "depth"]
        outer = evs[2]
        assert outer["ph"] == "X" and outer["args"] == {"k": 1}
        assert outer["dur"] >= evs[0]["dur"]

        path = tracer.export(str(tmp_path / "trace.json"))
        with open(path) as f:
            doc = json.load(f)
        assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
        for ev in doc["traceEvents"]:
            assert "name" in ev and "ph" in ev and "pid" in ev
            if ev["ph"] == "X":
                assert ev["ts"] >= 0 and ev["dur"] >= 0  # microseconds

    def test_disabled_tracer_records_nothing(self):
        t = obs.get_tracer()
        assert not t.enabled
        before = len(t.events())
        with t.span("ghost"):
            t.instant("ghost")
            t.counter("ghost", v=1)
        assert len(t.events()) == before

    def test_jsonl_sink_streams_parseable_lines(self, tracer, tmp_path):
        path = tracer.open_jsonl(str(tmp_path / "events.jsonl"))
        with tracer.span("a"):
            pass
        tracer.instant("b")
        tracer.close_jsonl()
        lines = [
            json.loads(ln)
            for ln in open(path).read().splitlines()
            if ln.strip()
        ]
        assert [ev["name"] for ev in lines] == ["a", "b"]

    def test_event_cap_counts_drops(self, tmp_path):
        t = obs.Tracer(enabled=True, max_events=2)
        for i in range(5):
            t.instant(f"e{i}")
        assert len(t.events()) == 2
        doc = json.load(open(t.export(str(tmp_path / "t.json"))))
        assert doc["metadata"]["dropped_events"] == 3


class TestPrometheus:
    def test_render_parse_round_trip(self):
        reg = obs.MetricsRegistry()
        c = reg.counter("rt_requests_total", "help text")
        c.inc(3, route="/a")
        c.inc(2.5, route='/b "quoted"\nline')
        g = reg.gauge("rt_depth")
        g.set(7)
        s = reg.summary("rt_lat_seconds")
        s.observe(0.25)
        s.observe(0.75)
        text = reg.render()
        parsed = obs.parse_prometheus(text)
        assert parsed["types"]["rt_requests_total"] == "counter"
        samples = parsed["samples"]
        assert samples[("rt_requests_total", (("route", "/a"),))] == 3
        assert (
            samples[
                ("rt_requests_total", (("route", '/b "quoted"\nline'),))
            ]
            == 2.5
        )
        assert samples[("rt_depth", ())] == 7
        assert samples[("rt_lat_seconds_sum", ())] == 1.0
        assert samples[("rt_lat_seconds_count", ())] == 2

    def test_duplicate_family_rejected(self):
        reg = obs.MetricsRegistry()
        reg.counter("dup_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.counter("dup_total")
        fams = [
            MetricFamily("x", "counter").add(1),
            MetricFamily("x", "counter").add(2),
        ]
        with pytest.raises(ValueError, match="duplicate"):
            obs.render_prometheus(fams)

    def test_parser_rejects_duplicate_samples(self):
        with pytest.raises(ValueError, match="duplicate"):
            obs.parse_prometheus("a 1\na 2\n")

    def test_nonfinite_values_render_as_literals(self):
        """A NaN loss gauge (the trainer's rollback scenario) must render
        as the Prometheus ``NaN`` literal, not crash every scrape."""
        import math

        fams = [
            MetricFamily("nf_loss", "gauge")
            .add(float("nan"))
            .add(float("inf"), suffix="", kind="hi")
            .add(float("-inf"), suffix="", kind="lo"),
        ]
        text = obs.render_prometheus(fams)
        assert "nf_loss NaN" in text
        samples = obs.parse_prometheus(text)["samples"]
        assert math.isnan(samples[("nf_loss", ())])
        assert samples[("nf_loss", (("kind", "hi"),))] == float("inf")
        assert samples[("nf_loss", (("kind", "lo"),))] == float("-inf")

    def test_weakref_collector_drops_with_owner(self):
        class Owner:
            def collect(self):
                return [MetricFamily("owned_total", "counter").add(1)]

        reg = obs.MetricsRegistry()
        owner = Owner()
        reg.register_collector(owner.collect, obj=owner)
        assert "owned_total" in reg.render()
        del owner
        import gc

        gc.collect()
        assert "owned_total" not in reg.render()

    def test_serve_metrics_collector_expires_with_rebind(self):
        """The real-world case the weakref protocol exists for: a bench
        rebinds engine.metrics between passes; the old object's families
        must leave the exposition (else the registry raises on the
        duplicate family names the NEW object also exposes)."""
        import gc

        from torchdistx_tpu.serve.metrics import ServeMetrics

        reg = obs.MetricsRegistry()
        m = ServeMetrics(num_slots=2)
        m.count("requests_submitted", 3)
        reg.register_collector(m.collector(), obj=m)
        assert (
            obs.parse_prometheus(reg.render())["samples"][
                ("tdx_serve_requests_submitted_total", ())
            ]
            == 3
        )
        m = ServeMetrics(num_slots=2)  # the rebind
        gc.collect()
        reg.register_collector(m.collector(), obj=m)
        parsed = obs.parse_prometheus(reg.render())  # no duplicates
        assert parsed["samples"][
            ("tdx_serve_requests_submitted_total", ())
        ] == 0

    def test_http_metrics_endpoint(self):
        reg = obs.MetricsRegistry()
        reg.counter("http_hits_total").inc(5)
        server = obs.start_metrics_server(reg, port=0)
        try:
            port = server.server_address[1]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as resp:
                assert resp.status == 200
                assert "text/plain" in resp.headers["Content-Type"]
                body = resp.read().decode()
            parsed = obs.parse_prometheus(body)
            assert parsed["samples"][("http_hits_total", ())] == 5
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=10
                )
        finally:
            server.shutdown()


class TestRecompileWatcher:
    def test_counts_and_attributes_compiles(self):
        x_small = jnp.ones((4,))
        x_big = jnp.ones((8, 8))
        jax.block_until_ready(x_small)
        f = jax.jit(lambda x: x * 2 + 1)
        with obs.RecompileWatcher() as w:
            assert w.available  # jax.monitoring present on this stack
            with obs.recompile_scope("shape_a"):
                jax.block_until_ready(f(x_small))
            with obs.recompile_scope("shape_b"):
                jax.block_until_ready(f(x_big))  # new shape -> new compile
            with obs.recompile_scope("shape_a"):
                jax.block_until_ready(f(x_small))  # cached -> no compile
        assert w.counts["shape_a"] == 1
        assert w.counts["shape_b"] == 1
        assert w.seconds["shape_a"] > 0
        snap = w.snapshot()
        assert snap["compiles_total"] == 2
        assert set(snap["by_scope"]) == {"shape_a", "shape_b"}

    def test_uninstalled_watcher_stops_counting(self):
        w = obs.RecompileWatcher()
        w.uninstall()
        f = jax.jit(lambda x: x - 3)
        jax.block_until_ready(f(jnp.ones((5,))))
        assert w.total == 0

    def test_collector_exposes_per_scope_counters(self):
        with obs.RecompileWatcher() as w:
            with obs.recompile_scope("colfn"):
                jax.block_until_ready(jax.jit(lambda x: x / 2)(jnp.ones(6)))
            reg = obs.MetricsRegistry()
            reg.register_collector(w.collector())
            parsed = obs.parse_prometheus(reg.render())
        key = ("tdx_jit_compiles_total", (("fn", "colfn"),))
        assert parsed["samples"][key] == w.counts["colfn"]

    def test_donated_carry_compiles_once_on_cpu(self):
        """Donation is a no-op on the CPU mesh (CLAUDE.md): the donated
        jit must register EXACTLY one compile and warm_to_steady_state
        must converge on the watcher signal — the baseline against which
        the donation-capable recompile below is the +1."""

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(c):
            return c * 1.5, c.sum()

        carry = jnp.ones((8, 8))
        jax.block_until_ready(carry)
        with obs.RecompileWatcher() as w:
            carry, times, converged = warm_to_steady_state(
                step, carry, sync=float, watcher=w, label="warm"
            )
        assert converged
        assert w.counts["warm"] == 1
        assert len(times) == 2  # compile call + the zero-compile proof

    def test_warm_to_steady_state_registers_donated_carry_recompile(self):
        """THE acceptance pin: the donated-carry double compile —
        call 1 compiles, call 2 recompiles (executable-chosen carry
        layouts on donation-capable backends; simulated here with a
        static-arg flip since CPU donation is a no-op), call 3 runs the
        settled executable — shows up as EXACTLY 2 compiles under the
        warm-up label, and warm_to_steady_state converges on the first
        zero-compile call instead of inferring steadiness from wall
        times."""
        calls = {"n": 0}
        inner = jax.jit(
            lambda c, phase: (c * 2.0, c.sum()), static_argnums=(1,)
        )

        def run(carry):
            calls["n"] += 1
            return inner(carry, min(calls["n"], 2))

        carry = jnp.ones((4, 4))
        jax.block_until_ready(carry)
        with obs.RecompileWatcher() as w:
            carry, times, converged = warm_to_steady_state(
                run, carry, sync=float, watcher=w, label="donated_warm"
            )
        assert converged
        assert calls["n"] == 3  # compile, RECOMPILE, steady proof
        assert w.counts["donated_warm"] == 2
        assert w.snapshot()["by_scope"]["donated_warm"]["compiles"] == 2


class TestProfiling:
    def test_timed_annotation_sink_and_tracer_span(self, tracer):
        seen = []
        with profiling.timed_annotation("obs_region", seen.append) as t:
            time.sleep(0.01)
        assert t["seconds"] >= 0.01
        assert seen == [t["seconds"]]
        spans = [e for e in tracer.events() if e["name"] == "obs_region"]
        assert len(spans) == 1 and spans[0]["cat"] == "dispatch"

    def test_timed_annotation_attributes_compiles(self):
        with obs.RecompileWatcher() as w:
            with profiling.timed_annotation("attr_region"):
                jax.block_until_ready(
                    jax.jit(lambda x: x + 0.5)(jnp.ones((3, 3)))
                )
        assert w.counts.get("attr_region", 0) >= 1

    def test_device_memory_stats_graceful_fallback(self):
        class NoStats:
            def memory_stats(self):
                return None

            def __str__(self):
                return "dev:nostats"

        class Broken:
            def memory_stats(self):
                raise RuntimeError("no PJRT memory stats")

            def __str__(self):
                return "dev:broken"

        stats = profiling.device_memory_stats(NoStats())
        stats.update(profiling.device_memory_stats(Broken()))
        assert stats == {"dev:nostats": {}, "dev:broken": {}}
        text = profiling.format_memory_stats(stats)
        assert text.count("(no memory stats)") == 2
        rich = profiling.format_memory_stats(
            {"dev:ok": {"bytes_in_use": 2e9, "peak_bytes_in_use": 3e9,
                        "bytes_limit": 16e9}}
        )
        assert "2.00 GB in use" in rich and "peak 3.00 GB" in rich

    def test_device_memory_stats_real_devices(self):
        stats = profiling.device_memory_stats()
        assert len(stats) == len(jax.devices())
        assert all(isinstance(s, dict) for s in stats.values())
        assert isinstance(profiling.format_memory_stats(stats), str)

    def test_cost_summary_tiny_jitted_fn(self):
        x = jnp.ones((16, 16), jnp.float32)
        out = profiling.cost_summary(
            jax.jit(lambda a: a @ a), x, peak_flops=1e12
        )
        assert set(out) >= {
            "flops",
            "bytes_accessed",
            "arithmetic_intensity",
            "compute_bound_s",
        }
        assert out["flops"] > 0  # a 16x16 matmul is not free
        assert out["compute_bound_s"] == out["flops"] / 1e12


class TestHistogramWindow:
    def test_window_count_vs_lifetime_count(self):
        h = Histogram(maxlen=10)
        for v in range(100):
            h.record(float(v))
        s = h.snapshot()
        assert s["count"] == 100  # lifetime, exact
        assert abs(s["mean"] - 49.5) < 1e-9  # lifetime, exact
        assert s["window_count"] == h.window_count <= 10
        # quantiles/max describe the recent window only: every sample
        # still in the reservoir is from the tail of the stream
        assert s["p50"] >= 90 and s["max"] == 99.0

    def test_window_equals_count_before_overflow(self):
        h = Histogram(maxlen=10)
        for v in (1.0, 2.0):
            h.record(v)
        s = h.snapshot()
        assert s["window_count"] == s["count"] == 2


class TestServeIntegration:
    def _run_engine(self, tracer, n=6):
        engine = ServeEngine(_llama(), num_slots=2, max_len=32)
        reqs = [
            {"prompt": p, "max_new_tokens": 4, "seed": i}
            for i, p in enumerate(_prompts(3, [3, 5, 2, 7, 4, 6][:n]))
        ]
        results = engine.run(reqs)
        return engine, results

    def test_aggregates_agree_with_per_request_views(self, tracer):
        engine, results = self._run_engine(tracer)
        finished = engine.finished_requests()
        assert len(finished) == len(results) == 6
        m = engine.metrics
        # counts: one histogram entry per finished request
        assert m.ttft_s.count == m.e2e_latency_s.count == 6
        # sums: the aggregates were fed from the requests' own lifecycle
        # timestamps, so per-request derived values reconcile exactly
        assert sum(r.ttft_s for r in results) == pytest.approx(
            m.ttft_s.total, rel=1e-9
        )
        assert sum(r.latency_s for r in results) == pytest.approx(
            m.e2e_latency_s.total, rel=1e-9
        )
        assert sum(r.queue_wait_s for r in results) == pytest.approx(
            m.queue_wait_s.total, rel=1e-9
        )
        tpots = [r.tpot_s for r in results if r.tpot_s is not None]
        assert len(tpots) == m.tpot_s.count
        assert sum(tpots) == pytest.approx(m.tpot_s.total, rel=1e-9)

    def test_lifecycle_events_ordered_and_complete(self, tracer):
        engine, results = self._run_engine(tracer)
        for req in engine.finished_requests():
            names = [e[0] for e in req.events]
            # causal order: submit -> admitted -> prefill -> first_token
            # -> decode chunks -> finish
            for a, b in zip(
                ["submit", "admitted", "prefill", "first_token"],
                names[:4],
            ):
                assert a == b, names
            assert names[-1] == "finish"
            times = [e[1] for e in req.events]
            assert times == sorted(times)
            # every event timestamp is JSON-able data
            json.dumps(req.events)

    def test_dump_trace_valid_and_spans_sum_to_e2e(self, tracer, tmp_path):
        engine, results = self._run_engine(tracer)
        path = engine.dump_trace(str(tmp_path / "serve_trace.json"))
        with open(path) as f:
            doc = json.load(f)
        evs = doc["traceEvents"]
        assert all("name" in e and "ph" in e for e in evs)
        # the engine's dispatch spans made it in, one per dispatch
        m = engine.metrics
        assert (
            len([e for e in evs if e["name"] == "serve/prefill"])
            == m.counters["prefill_calls"]
        )
        assert (
            len([e for e in evs if e["name"] == "serve/decode"])
            == m.counters["decode_dispatches"]
        )
        # per-request tracks: queued + prefill + decode spans sum to the
        # request's e2e latency (same timestamps as e2e_latency_s)
        by_req: dict = {}
        for e in evs:
            if e.get("cat") == "request" and e["ph"] == "X":
                by_req.setdefault(e["args"]["rid"], []).append(e)
        assert len(by_req) == 6
        for req in engine.finished_requests():
            spans = by_req[req.rid]
            assert {s["name"] for s in spans} == {
                "queued",
                "prefill",
                "decode",
            }
            total_us = sum(s["dur"] for s in spans)
            e2e_us = (req.finished_at - req.submitted_at) * 1e6
            assert total_us == pytest.approx(e2e_us, abs=0.01)

    def test_exposition_matches_to_json(self, tracer):
        engine, _ = self._run_engine(tracer)
        registry = obs.MetricsRegistry()
        registry.register_collector(
            engine.metrics.collector(), obj=engine.metrics
        )
        parsed = obs.parse_prometheus(registry.render())
        j = engine.metrics.to_json()
        for name, v in j["counters"].items():
            assert (
                parsed["samples"][(f"tdx_serve_{name}_total", ())] == v
            ), name
        for name, v in j["gauges"].items():
            assert parsed["samples"][(f"tdx_serve_{name}", ())] == v, name
        # summaries: lifetime count/sum + window quantiles
        assert (
            parsed["samples"][("tdx_serve_ttft_seconds_count", ())]
            == engine.metrics.ttft_s.count
        )
        assert parsed["samples"][
            ("tdx_serve_ttft_seconds_sum", ())
        ] == pytest.approx(engine.metrics.ttft_s.total, rel=1e-6)
        assert parsed["types"]["tdx_serve_ttft_seconds"] == "summary"

    def test_finished_history_bounded_and_disableable(self, tracer):
        engine = ServeEngine(
            _llama(), num_slots=2, max_len=32, finished_history=2
        )
        engine.run(
            [{"prompt": p, "max_new_tokens": 2} for p in _prompts(5, [3] * 5)]
        )
        kept = engine.finished_requests()
        assert len(kept) == 2  # newest two only
        assert kept[-1].rid == 4
        engine_off = ServeEngine(
            _llama(), num_slots=2, max_len=32, finished_history=0
        )
        results = engine_off.run(
            [{"prompt": p, "max_new_tokens": 2} for p in _prompts(5, [3, 4])]
        )
        assert engine_off.finished_requests() == []
        # lifecycle events still ride out on the results themselves
        assert all(r.events[-1][0] == "finish" for r in results)

    def test_expired_request_gets_partial_track(self, tracer, tmp_path):
        engine = ServeEngine(_llama(), num_slots=1, max_len=32)
        # one request hogs the single slot; the second expires queued
        engine.submit(
            np.ones(3, np.int32), max_new_tokens=8, deadline_s=1e6
        )
        h2 = engine.submit(
            np.ones(4, np.int32), max_new_tokens=8, deadline_s=0.0
        )
        while engine.step():
            pass
        assert h2.result().finish_reason == "deadline"
        names = [e[0] for e in h2.result().events]
        assert names == ["submit", "expire"]
        doc = json.load(
            open(engine.dump_trace(str(tmp_path / "expired.json")))
        )
        rows = [
            e
            for e in doc["traceEvents"]
            if e.get("cat") == "request"
            and e.get("args", {}).get("rid") == h2.rid
        ]
        assert [e["name"] for e in rows] == ["queued"]


class TestTrainerTelemetry:
    def test_fit_spans_and_collector(self, tracer):
        from torchdistx_tpu.trainer import Trainer

        def step(params, opt_state, batch):
            return params, opt_state, jnp.float32(0.25)

        logs = []
        t = Trainer(
            step,
            params={},
            opt_state={},
            tokens_per_batch=16,
            log_every=1,
            log_fn=logs.append,
        )
        t.fit([None] * 3, num_steps=3)
        assert t.metrics["steps_total"] == 3
        assert t.metrics["tokens_total"] == 48
        assert t.metrics["loss"] == pytest.approx(0.25)
        spans = [
            e for e in tracer.events() if e["name"] == "trainer/step"
        ]
        assert len(spans) == 3
        reg = obs.MetricsRegistry()
        reg.register_collector(t.metrics_collector(), obj=t)
        parsed = obs.parse_prometheus(reg.render())
        assert parsed["samples"][("tdx_train_steps_total", ())] == 3
        assert parsed["samples"][("tdx_train_tokens_total", ())] == 48
        assert parsed["samples"][
            ("tdx_train_loss", ())
        ] == pytest.approx(0.25)


class TestReplaySpans:
    def test_materialize_emits_replay_spans(self, tracer):
        model = tdx.deferred_init(
            lambda: Llama.from_name("tiny", n_kv_heads=2, max_seq_len=32)
        )
        tdx.materialize_module(model)
        names = [e["name"] for e in tracer.events()]
        assert "materialize_module" in names
        assert any(n.startswith("replay/") for n in names)
