"""Pallas slot-paged decode attention (ops/decode_attention.py).

Exactness bar (kernel docstring): interpret mode is exact math modulo
floating-point association — the probabilities match the jnp path's
``jax.nn.softmax`` op order bitwise; the final P@V contraction reduction
is associated differently by XLA's batched-einsum emitter than by any
per-(slot, head) kernel dot, measured <= 2 f32 ulps.  Tests pin that bar
(atol/rtol ~1 ulp), far tighter than the flash-attention interpret
tolerance (2e-5), against ``slot_cached_attention``'s jnp path for
single-block AND multi-block configurations, all GQA widths, and the
position edges.  Engine-level BIT-identity of fused-vs-sequential decode
is pinned in tests/test_serve.py (both sides share this kernel).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchdistx_tpu.ops.attention import slot_cached_attention
from torchdistx_tpu.ops.decode_attention import (
    decode_attention,
    paged_decode_attention,
)

_ULP = 3e-7  # ~2 f32 ulps at unit scale


def _case(rs, b, hq, hkv, d, max_seq, positions, dtype=jnp.float32):
    q = jnp.asarray(rs.randn(b, 1, hq, d), dtype)
    k = jnp.asarray(rs.randn(b, 1, hkv, d), dtype)
    v = jnp.asarray(rs.randn(b, 1, hkv, d), dtype)
    cache = (
        jnp.asarray(rs.randn(b, max_seq, hkv, d), dtype),
        jnp.asarray(rs.randn(b, max_seq, hkv, d), dtype),
    )
    return q, k, v, cache, jnp.asarray(positions, jnp.int32)


class TestKernelMatchesReference:
    @pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 2), (16, 1)])
    def test_single_block_matches_jnp_path(self, hq, hkv):
        rs = np.random.RandomState(hq * 10 + hkv)
        b, d, max_seq = 3, 8, 16
        q, k, v, cache, pos = _case(
            rs, b, hq, hkv, d, max_seq, rs.randint(0, max_seq, (b,))
        )
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out = decode_attention(q, rk, rv, pos, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    @pytest.mark.parametrize("block_k", [8, 16])
    def test_multi_block_online_softmax_matches(self, block_k):
        rs = np.random.RandomState(block_k)
        b, hq, hkv, d, max_seq = 3, 4, 2, 8, 64
        # positions straddling block edges: first block only, exact edge,
        # mid-block, last row
        q, k, v, cache, pos = _case(
            rs, b, hq, hkv, d, max_seq,
            [block_k - 1, block_k, max_seq - 1],
        )
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out = decode_attention(q, rk, rv, pos, block_k=block_k, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    def test_position_zero_and_full_row(self):
        rs = np.random.RandomState(0)
        b, hq, hkv, d, max_seq = 2, 4, 2, 8, 32
        q, k, v, cache, pos = _case(rs, b, hq, hkv, d, max_seq, [0, 31])
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out = decode_attention(q, rk, rv, pos, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    def test_bf16_inputs(self):
        rs = np.random.RandomState(5)
        b, hq, hkv, d, max_seq = 2, 4, 2, 8, 16
        q, k, v, cache, pos = _case(
            rs, b, hq, hkv, d, max_seq, [3, 12], dtype=jnp.bfloat16
        )
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out = decode_attention(q, rk, rv, pos, interpret=True)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=2e-2, atol=2e-2,
        )


class TestRouting:
    def test_slot_cached_attention_routes_to_kernel(self):
        """use_flash=True takes the kernel path end to end: identical
        cache writes, output within the kernel tolerance."""
        rs = np.random.RandomState(1)
        q, k, v, cache, pos = _case(rs, 3, 4, 2, 8, 16, [2, 9, 5])
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out, (fk, fv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=True
        )
        np.testing.assert_array_equal(np.asarray(fk), np.asarray(rk))
        np.testing.assert_array_equal(np.asarray(fv), np.asarray(rv))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    def test_windowed_decode_stays_on_jnp_path(self):
        """The kernel has no sliding-window mode: window= must fall back
        to the jnp band path bit-for-bit even with use_flash on."""
        rs = np.random.RandomState(2)
        q, k, v, cache, pos = _case(rs, 2, 4, 2, 8, 16, [5, 11])
        ref, _ = slot_cached_attention(
            q, k, v, cache, pos, window=4, use_flash=False
        )
        out, _ = slot_cached_attention(
            q, k, v, cache, pos, window=4, use_flash=True
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_auto_resolution_off_tpu_is_jnp(self):
        """resolve_use_flash(None) off-TPU keeps the jnp path: the
        default engine on the CPU mesh stays on its pinned bit-exact
        decode."""
        rs = np.random.RandomState(3)
        q, k, v, cache, pos = _case(rs, 2, 4, 2, 8, 16, [5, 11])
        auto, _ = slot_cached_attention(q, k, v, cache, pos)
        ref, _ = slot_cached_attention(q, k, v, cache, pos, use_flash=False)
        if jax.devices()[0].platform == "tpu":
            pytest.skip("auto resolves to the kernel on TPU")
        np.testing.assert_array_equal(np.asarray(auto), np.asarray(ref))

    def test_rejects_multi_token(self):
        rs = np.random.RandomState(4)
        q = jnp.asarray(rs.randn(2, 2, 4, 8), jnp.float32)
        ck = jnp.asarray(rs.randn(2, 16, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="one token per slot"):
            decode_attention(q, ck, ck, jnp.zeros((2,), jnp.int32))

    def test_rejects_indivisible_heads(self):
        rs = np.random.RandomState(4)
        q = jnp.asarray(rs.randn(2, 1, 3, 8), jnp.float32)
        ck = jnp.asarray(rs.randn(2, 16, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="not a multiple"):
            decode_attention(q, ck, ck, jnp.zeros((2,), jnp.int32))


def _paged_case(rs, b, hq, hkv, d, pp, ps, positions, dtype=jnp.float32):
    """Pools + a shuffled page-table (identity mappings would let a
    kernel that ignores the table pass) + per-slot new K/V."""
    num_pages = b * pp + 1  # page 0 stays scratch, like the engine's pool
    q = jnp.asarray(rs.randn(b, 1, hq, d), dtype)
    k = jnp.asarray(rs.randn(b, 1, hkv, d), dtype)
    v = jnp.asarray(rs.randn(b, 1, hkv, d), dtype)
    pools = (
        jnp.asarray(rs.randn(num_pages, ps, hkv, d), dtype),
        jnp.asarray(rs.randn(num_pages, ps, hkv, d), dtype),
    )
    tables = 1 + rs.permutation(b * pp).reshape(b, pp).astype(np.int32)
    return (
        q, k, v, pools,
        jnp.asarray(tables), jnp.asarray(positions, jnp.int32),
    )


class TestPagedKernel:
    """paged_decode_attention vs the jnp paged path (page-table gather +
    the shared _slot_attend math) — same exactness bar as the slot
    kernel: single-page rows bitwise-softmax (<= ULP overall), multi-page
    rows the online-softmax merge at <= 2 f32 ulps."""

    def _ref_and_kernel(self, q, k, v, pools, tables, pos):
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, pools, pos, use_flash=False, page_tables=tables
        )
        out = paged_decode_attention(q, rk, rv, tables, pos, interpret=True)
        return np.asarray(ref), np.asarray(out), (rk, rv)

    @pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 2), (16, 1)])
    def test_single_page_matches_jnp_path(self, hq, hkv):
        rs = np.random.RandomState(hq * 10 + hkv)
        b, d, ps = 3, 8, 16
        case = _paged_case(rs, b, hq, hkv, d, 1, ps, rs.randint(0, ps, (b,)))
        ref, out, _ = self._ref_and_kernel(*case)
        np.testing.assert_allclose(out, ref, rtol=_ULP, atol=_ULP)

    @pytest.mark.parametrize("ps", [8, 16])
    def test_multi_page_online_softmax_matches(self, ps):
        rs = np.random.RandomState(ps)
        b, hq, hkv, d, pp = 4, 4, 2, 8, 4
        # positions straddling page edges: first page only, exact edge,
        # mid-chain, last row
        case = _paged_case(
            rs, b, hq, hkv, d, pp, ps,
            [ps - 1, ps, 2 * ps + 3, pp * ps - 1],
        )
        ref, out, _ = self._ref_and_kernel(*case)
        np.testing.assert_allclose(out, ref, rtol=_ULP, atol=_ULP)

    def test_matches_contiguous_layout_bitwise_on_jnp_path(self):
        """The jnp paged path IS the slab path behind a gather: build a
        slab holding exactly what the page chains spell and pin the
        outputs (and written rows) bit-for-bit."""
        rs = np.random.RandomState(5)
        b, hq, hkv, d, pp, ps = 3, 4, 2, 8, 4, 8
        q, k, v, pools, tables, pos = _paged_case(
            rs, b, hq, hkv, d, pp, ps, [3, 17, 30]
        )
        slab = tuple(
            jnp.stack([p.reshape(-1, hkv, d)[
                (np.asarray(tables[row])[:, None] * ps
                 + np.arange(ps)[None, :]).reshape(-1)
            ] for row in range(b)])
            for p in pools
        )
        want, _ = slot_cached_attention(
            q, k, v, slab, pos, use_flash=False
        )
        got, (gk, gv) = slot_cached_attention(
            q, k, v, pools, pos, use_flash=False, page_tables=tables
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # the write landed at page tables[b, pos//ps], offset pos%ps
        for row, p in enumerate([3, 17, 30]):
            page = int(tables[row, p // ps])
            np.testing.assert_array_equal(
                np.asarray(gk[page, p % ps]), np.asarray(k[row, 0])
            )

    def test_routing_through_slot_cached_attention(self):
        rs = np.random.RandomState(6)
        q, k, v, pools, tables, pos = _paged_case(
            rs, 2, 4, 2, 8, 2, 16, [5, 20]
        )
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, pools, pos, use_flash=False, page_tables=tables
        )
        out, (fk, fv) = slot_cached_attention(
            q, k, v, pools, pos, use_flash=True, page_tables=tables
        )
        np.testing.assert_array_equal(np.asarray(fk), np.asarray(rk))
        np.testing.assert_array_equal(np.asarray(fv), np.asarray(rv))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    def test_tiny_pages_fall_back_to_jnp(self):
        """Pages below the f32 sublane height can't feed the kernel on
        real TPUs: use_flash must quietly take the gather path."""
        rs = np.random.RandomState(7)
        q, k, v, pools, tables, pos = _paged_case(
            rs, 2, 4, 2, 8, 4, 4, [3, 11]
        )
        ref, _ = slot_cached_attention(
            q, k, v, pools, pos, use_flash=False, page_tables=tables
        )
        out, _ = slot_cached_attention(
            q, k, v, pools, pos, use_flash=True, page_tables=tables
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_rejects_bad_shapes(self):
        rs = np.random.RandomState(8)
        q = jnp.asarray(rs.randn(2, 2, 4, 8), jnp.float32)
        pool = jnp.asarray(rs.randn(5, 16, 2, 8), jnp.float32)
        pt = jnp.zeros((2, 2), jnp.int32)
        with pytest.raises(ValueError, match="one token per slot"):
            paged_decode_attention(q, pool, pool, pt, jnp.zeros(2, jnp.int32))
        q1 = jnp.asarray(rs.randn(3, 1, 4, 8), jnp.float32)
        with pytest.raises(ValueError, match="page_tables rows"):
            paged_decode_attention(
                q1, pool, pool, pt, jnp.zeros(3, jnp.int32)
            )


class TestWindowedDecodeBoundaries:
    """Windowed slot_cached_attention vs an independently computed dense
    reference, at the boundaries the paged refactor could plausibly
    break: window == page_size, window < prompt depth, and a window
    straddling a page edge.  The paged windowed path must also stay
    bit-identical to the slab windowed path (both run the shared
    _slot_attend on the same visible values)."""

    def _dense_reference(self, q, ck, cv, positions, window):
        """Per-row, slice the exact visible band and softmax over it —
        no masking tricks shared with the implementation under test."""
        outs = []
        for row, p in enumerate(positions):
            lo = max(0, int(p) - window + 1)
            ks = np.asarray(ck[row, lo : int(p) + 1], np.float32)
            vs = np.asarray(cv[row, lo : int(p) + 1], np.float32)
            qv = np.asarray(q[row, 0], np.float32)  # (Hq, D)
            n_rep = qv.shape[0] // ks.shape[1]
            ks = np.repeat(ks, n_rep, axis=1)
            vs = np.repeat(vs, n_rep, axis=1)
            logits = np.einsum("hd,khd->hk", qv, ks) / np.sqrt(qv.shape[-1])
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            outs.append(np.einsum("hk,khd->hd", probs, vs))
        return np.stack(outs)[:, None]

    @pytest.mark.parametrize(
        "window,positions",
        [
            (8, [7, 12, 20]),   # window == page_size (ps=8 in the grid)
            (5, [9, 15, 23]),   # window < prompt depth everywhere
            (6, [11, 8, 19]),   # band straddles a page edge (8, 16)
        ],
    )
    def test_windowed_matches_dense_reference(self, window, positions):
        rs = np.random.RandomState(window)
        b, hq, hkv, d, max_seq = 3, 4, 2, 8, 32
        q, k, v, cache, pos = _case(rs, b, hq, hkv, d, max_seq, positions)
        out, (ck, cv) = slot_cached_attention(
            q, k, v, cache, pos, window=window, use_flash=False
        )
        ref = self._dense_reference(q, ck, cv, positions, window)
        np.testing.assert_allclose(
            np.asarray(out), ref, rtol=1e-6, atol=1e-6
        )

    @pytest.mark.parametrize("window", [5, 8, 6])
    def test_paged_windowed_bitwise_matches_slab(self, window):
        rs = np.random.RandomState(20 + window)
        b, hq, hkv, d, pp, ps = 3, 4, 2, 8, 4, 8
        positions = [11, 8, 19]
        q, k, v, pools, tables, pos = _paged_case(
            rs, b, hq, hkv, d, pp, ps, positions
        )
        slab = tuple(
            jnp.stack([p.reshape(-1, hkv, d)[
                (np.asarray(tables[row])[:, None] * ps
                 + np.arange(ps)[None, :]).reshape(-1)
            ] for row in range(b)])
            for p in pools
        )
        want, _ = slot_cached_attention(
            q, k, v, slab, pos, window=window, use_flash=False
        )
        got, _ = slot_cached_attention(
            q, k, v, pools, pos, window=window, use_flash=False,
            page_tables=tables,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
class TestKernelSweep:
    """Full grid of (GQA width, geometry, block split, position pattern) —
    the heavyweight sibling of TestKernelMatchesReference (nightly)."""

    @pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 2), (8, 1)])
    @pytest.mark.parametrize("max_seq,block_k", [(16, 512), (64, 16), (128, 32)])
    def test_grid(self, hq, hkv, max_seq, block_k):
        rs = np.random.RandomState(hq + hkv + max_seq + block_k)
        b, d = 4, 16
        positions = np.concatenate(
            [[0, max_seq - 1], rs.randint(0, max_seq, (b - 2,))]
        )
        q, k, v, cache, pos = _case(rs, b, hq, hkv, d, max_seq, positions)
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out = decode_attention(q, rk, rv, pos, block_k=block_k, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    @pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 2), (8, 1)])
    @pytest.mark.parametrize("pp,ps", [(1, 16), (4, 8), (4, 32)])
    def test_paged_grid(self, hq, hkv, pp, ps):
        rs = np.random.RandomState(hq + hkv + pp * ps)
        b, d = 4, 16
        max_seq = pp * ps
        positions = np.concatenate(
            [[0, max_seq - 1], rs.randint(0, max_seq, (b - 2,))]
        )
        q, k, v, pools, tables, pos = _paged_case(
            rs, b, hq, hkv, d, pp, ps, positions
        )
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, pools, pos, use_flash=False, page_tables=tables
        )
        out = paged_decode_attention(q, rk, rv, tables, pos, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )
