"""Pallas slot-paged decode attention (ops/decode_attention.py).

Exactness bar (kernel docstring): interpret mode is exact math modulo
floating-point association — the probabilities match the jnp path's
``jax.nn.softmax`` op order bitwise; the final P@V contraction reduction
is associated differently by XLA's batched-einsum emitter than by any
per-(slot, head) kernel dot, measured <= 2 f32 ulps.  Tests pin that bar
(atol/rtol ~1 ulp), far tighter than the flash-attention interpret
tolerance (2e-5), against ``slot_cached_attention``'s jnp path for
single-block AND multi-block configurations, all GQA widths, and the
position edges.  Engine-level BIT-identity of fused-vs-sequential decode
is pinned in tests/test_serve.py (both sides share this kernel).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchdistx_tpu.ops.attention import slot_cached_attention
from torchdistx_tpu.ops.decode_attention import decode_attention

_ULP = 3e-7  # ~2 f32 ulps at unit scale


def _case(rs, b, hq, hkv, d, max_seq, positions, dtype=jnp.float32):
    q = jnp.asarray(rs.randn(b, 1, hq, d), dtype)
    k = jnp.asarray(rs.randn(b, 1, hkv, d), dtype)
    v = jnp.asarray(rs.randn(b, 1, hkv, d), dtype)
    cache = (
        jnp.asarray(rs.randn(b, max_seq, hkv, d), dtype),
        jnp.asarray(rs.randn(b, max_seq, hkv, d), dtype),
    )
    return q, k, v, cache, jnp.asarray(positions, jnp.int32)


class TestKernelMatchesReference:
    @pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 2), (16, 1)])
    def test_single_block_matches_jnp_path(self, hq, hkv):
        rs = np.random.RandomState(hq * 10 + hkv)
        b, d, max_seq = 3, 8, 16
        q, k, v, cache, pos = _case(
            rs, b, hq, hkv, d, max_seq, rs.randint(0, max_seq, (b,))
        )
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out = decode_attention(q, rk, rv, pos, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    @pytest.mark.parametrize("block_k", [8, 16])
    def test_multi_block_online_softmax_matches(self, block_k):
        rs = np.random.RandomState(block_k)
        b, hq, hkv, d, max_seq = 3, 4, 2, 8, 64
        # positions straddling block edges: first block only, exact edge,
        # mid-block, last row
        q, k, v, cache, pos = _case(
            rs, b, hq, hkv, d, max_seq,
            [block_k - 1, block_k, max_seq - 1],
        )
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out = decode_attention(q, rk, rv, pos, block_k=block_k, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    def test_position_zero_and_full_row(self):
        rs = np.random.RandomState(0)
        b, hq, hkv, d, max_seq = 2, 4, 2, 8, 32
        q, k, v, cache, pos = _case(rs, b, hq, hkv, d, max_seq, [0, 31])
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out = decode_attention(q, rk, rv, pos, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    def test_bf16_inputs(self):
        rs = np.random.RandomState(5)
        b, hq, hkv, d, max_seq = 2, 4, 2, 8, 16
        q, k, v, cache, pos = _case(
            rs, b, hq, hkv, d, max_seq, [3, 12], dtype=jnp.bfloat16
        )
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out = decode_attention(q, rk, rv, pos, interpret=True)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=2e-2, atol=2e-2,
        )


class TestRouting:
    def test_slot_cached_attention_routes_to_kernel(self):
        """use_flash=True takes the kernel path end to end: identical
        cache writes, output within the kernel tolerance."""
        rs = np.random.RandomState(1)
        q, k, v, cache, pos = _case(rs, 3, 4, 2, 8, 16, [2, 9, 5])
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out, (fk, fv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=True
        )
        np.testing.assert_array_equal(np.asarray(fk), np.asarray(rk))
        np.testing.assert_array_equal(np.asarray(fv), np.asarray(rv))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    def test_windowed_decode_stays_on_jnp_path(self):
        """The kernel has no sliding-window mode: window= must fall back
        to the jnp band path bit-for-bit even with use_flash on."""
        rs = np.random.RandomState(2)
        q, k, v, cache, pos = _case(rs, 2, 4, 2, 8, 16, [5, 11])
        ref, _ = slot_cached_attention(
            q, k, v, cache, pos, window=4, use_flash=False
        )
        out, _ = slot_cached_attention(
            q, k, v, cache, pos, window=4, use_flash=True
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_auto_resolution_off_tpu_is_jnp(self):
        """resolve_use_flash(None) off-TPU keeps the jnp path: the
        default engine on the CPU mesh stays on its pinned bit-exact
        decode."""
        rs = np.random.RandomState(3)
        q, k, v, cache, pos = _case(rs, 2, 4, 2, 8, 16, [5, 11])
        auto, _ = slot_cached_attention(q, k, v, cache, pos)
        ref, _ = slot_cached_attention(q, k, v, cache, pos, use_flash=False)
        if jax.devices()[0].platform == "tpu":
            pytest.skip("auto resolves to the kernel on TPU")
        np.testing.assert_array_equal(np.asarray(auto), np.asarray(ref))

    def test_rejects_multi_token(self):
        rs = np.random.RandomState(4)
        q = jnp.asarray(rs.randn(2, 2, 4, 8), jnp.float32)
        ck = jnp.asarray(rs.randn(2, 16, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="one token per slot"):
            decode_attention(q, ck, ck, jnp.zeros((2,), jnp.int32))

    def test_rejects_indivisible_heads(self):
        rs = np.random.RandomState(4)
        q = jnp.asarray(rs.randn(2, 1, 3, 8), jnp.float32)
        ck = jnp.asarray(rs.randn(2, 16, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="not a multiple"):
            decode_attention(q, ck, ck, jnp.zeros((2,), jnp.int32))


@pytest.mark.slow
class TestKernelSweep:
    """Full grid of (GQA width, geometry, block split, position pattern) —
    the heavyweight sibling of TestKernelMatchesReference (nightly)."""

    @pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 2), (8, 1)])
    @pytest.mark.parametrize("max_seq,block_k", [(16, 512), (64, 16), (128, 32)])
    def test_grid(self, hq, hkv, max_seq, block_k):
        rs = np.random.RandomState(hq + hkv + max_seq + block_k)
        b, d = 4, 16
        positions = np.concatenate(
            [[0, max_seq - 1], rs.randint(0, max_seq, (b - 2,))]
        )
        q, k, v, cache, pos = _case(rs, b, hq, hkv, d, max_seq, positions)
        ref, (rk, rv) = slot_cached_attention(
            q, k, v, cache, pos, use_flash=False
        )
        out = decode_attention(q, rk, rv, pos, block_k=block_k, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )
