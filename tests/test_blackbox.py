"""Incident time machine (ISSUE 20): the tdx-session-v1 black box.

The pinned invariants, on the 8-device CPU mesh:

- **Schema round-trip**: a recorded session streams to JSONL with
  per-event flush, loads back identically, and passes
  ``validate_session_jsonl`` (header first, dense drain seqs, the
  digest chain recomputable from the drain payloads, snapshots
  anchored, ``session_end`` consistent).
- **Bit-exact replay**: ``replay_session`` rebuilds the engine from
  the recorded geometry, re-drives the exact submit/step stream, and
  every drain-boundary digest matches — ``verdict == "match"``.
- **Kill-mid-run**: a truncated recording (no ``session_end``, torn
  final line) replays its complete prefix bit-identically and the
  verdict names the truncation point — ``truncated_match``.
- **Divergence localization**: a single perturbed counter delta, a
  single perturbed token, and a mis-built geometry each produce a
  DISTINCT named verdict — the first divergent drain seq + tick +
  counter names, the affected session request ids, or the differing
  geometry fields.  ``rechain`` makes the injected recording exactly
  as internally consistent as a live run that really diverged there.
- **Zero overhead** (satellite 3): recording changes NO engine counter
  — ``host_syncs`` included — because every hashed value is already
  host-side at the drain hook.  ``TDX_SESSION_RECORD=0`` turns every
  implicit recorder into a no-op (the TDX_COST_CARDS switch pattern).
- **Autoscale bridge** (satellite 2): the recorded live signal vectors
  feed ``serve.autoscale.replay_signal`` and the (tick, action)
  decision stream replays bit-identically against the recorded
  ``("scale", ...)`` fleet events.
"""

import json
import os

import numpy as np
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu.models import Llama
from torchdistx_tpu.obs.blackbox import (
    SESSION_SCHEMA,
    SessionRecorder,
    geometry_kwargs,
    load_session,
    rechain,
    recording_enabled,
    replay_session,
    resolve_record,
    session_force_disabled,
    signals_from_session,
    validate_session_jsonl,
)
from torchdistx_tpu.serve import (
    AutoscaleController,
    ScalingPolicy,
    ServeEngine,
    ServeFleet,
    replay_signal,
)


@pytest.fixture(scope="module")
def model():
    tdx.manual_seed(7)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


def _engine(model, rec=None, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("decode_chunk", 4)
    return ServeEngine(model, record=rec, **kw)


def _work(n=4, seed=0, max_new=4, temperature=0.0):
    rs = np.random.RandomState(seed)
    return [
        {
            "prompt": rs.randint(0, 256, (int(m),)).astype(np.int32),
            "max_new_tokens": max_new,
            "temperature": temperature,
            "seed": i,
        }
        for i, m in enumerate(rs.randint(2, 12, n))
    ]


def _record(model, path, **ekw):
    """One recorded single-engine session; returns (recorder, results)."""
    rec = SessionRecorder(path, enabled=True)
    engine = _engine(model, rec, **ekw)
    results = engine.run([dict(w) for w in _work()])
    rec.close()
    return rec, results


def _factory(model, **extra):
    def build(rep_rec, geom):
        return ServeEngine(
            model, record=rep_rec, **{**geometry_kwargs(geom), **extra}
        )

    return build


# ---------------------------------------------------------------------------
# schema round-trip


class TestSchema:
    def test_stream_roundtrip_and_validate(self, model, tmp_path):
        path = str(tmp_path / "s.jsonl")
        rec, results = _record(model, path)
        assert rec.drains > 0 and results
        assert validate_session_jsonl(path) == []
        events, notes = load_session(path)
        assert notes == []
        assert events[0]["kind"] == "session_header"
        assert events[0]["schema"] == SESSION_SCHEMA
        # the streamed file IS the in-memory record, event for event
        assert events == json.loads(
            json.dumps(rec.events)
        ), "JSONL round-trip changed an event"
        end = events[-1]
        assert end["kind"] == "session_end"
        assert end["chain"] == rec.chain and end["drains"] == rec.drains
        geom = next(e for e in events if e["kind"] == "geometry")
        for field in ("num_slots", "max_len", "decode_chunk", "kv_dtype"):
            assert field in geom
        submits = [e for e in events if e["kind"] == "submit"]
        assert [s["rid"] for s in submits] == list(range(len(submits)))
        assert all(
            isinstance(t, int) for s in submits for t in s["prompt"]
        )

    def test_snapshots_ride_along(self, model, tmp_path):
        path = str(tmp_path / "s.jsonl")
        rec = SessionRecorder(path, enabled=True, snapshot_every=2)
        engine = _engine(model, rec)
        engine.run([dict(w) for w in _work()])
        rec.close()
        assert validate_session_jsonl(path) == []
        snaps = [e for e in rec.events if e["kind"] == "snapshot"]
        assert len(snaps) == rec.drains // 2
        assert all("counters" in s and s["chain"] for s in snaps)

    def test_recorder_truncates_stale_file(self, model, tmp_path):
        """A recorder opened on an existing path must overwrite, not
        append — a crashed earlier run's leftover file would otherwise
        become a two-header recording that replays as an unhelpful
        empty-fields geometry_mismatch."""
        path = str(tmp_path / "s.jsonl")
        _record(model, path)
        first = open(path).read()
        rec, _ = _record(model, path)
        assert validate_session_jsonl(path) == []
        events, _ = load_session(path)
        assert (
            sum(1 for e in events if e["kind"] == "session_header") == 1
        )
        # and a concatenated file (older-code artifact) is named by the
        # validator, not silently replayed
        cat = str(tmp_path / "cat.jsonl")
        with open(cat, "w") as f:
            f.write(first + open(path).read())
        errors = validate_session_jsonl(cat)
        assert any("session_header events" in e for e in errors)

    def test_validator_names_breaks(self, model, tmp_path):
        path = str(tmp_path / "s.jsonl")
        _record(model, path)
        events, _ = load_session(path)
        # a flipped delta WITHOUT rechain is a broken chain, not a
        # plausible recording — the validator must say so
        for e in events:
            if e["kind"] == "drain" and e.get("delta"):
                e["delta"] = dict(e["delta"])
                k = sorted(e["delta"])[0]
                e["delta"][k] += 1
                break
        errors = validate_session_jsonl(events)
        assert any("digest chain broken" in e for e in errors)
        # rechained, the same perturbation is internally consistent
        assert validate_session_jsonl(rechain(events)) == []


# ---------------------------------------------------------------------------
# bit-exact replay


class TestReplay:
    def test_match(self, model, tmp_path):
        path = str(tmp_path / "s.jsonl")
        rec, _ = _record(model, path)
        v = replay_session(path, engine_factory=_factory(model))
        assert v["verdict"] == "match" and v["match"]
        assert v["drains_replayed"] == v["drains_recorded"] == rec.drains
        assert v["chain_replayed"] == v["chain_recorded"] == rec.chain

    def test_replay_is_deterministic_under_kill_switch(
        self, model, tmp_path, monkeypatch
    ):
        """The replay harness's own recorder is explicit enabled=True —
        production recording being switched off must not break it."""
        path = str(tmp_path / "s.jsonl")
        _record(model, path)
        monkeypatch.setenv("TDX_SESSION_RECORD", "0")
        v = replay_session(path, engine_factory=_factory(model))
        assert v["verdict"] == "match"

    def test_truncated_recording_replays_prefix(self, model, tmp_path):
        path = str(tmp_path / "s.jsonl")
        _record(model, path)
        with open(path) as f:
            lines = f.read().splitlines()
        # SIGKILL shape: session_end never written, final event torn
        torn = [ln for ln in lines if '"session_end"' not in ln]
        torn[-1] = torn[-1][: len(torn[-1]) // 2]
        with open(path, "w") as f:
            f.write("\n".join(torn) + "\n")
        errors = validate_session_jsonl(path)
        assert any("truncated" in e for e in errors)
        assert validate_session_jsonl(path, allow_truncated=True) == []
        v = replay_session(path, engine_factory=_factory(model))
        assert v["verdict"] == "truncated_match" and v["match"]
        assert v["truncated"]
        assert any("torn final event" in n for n in v["notes"])
        assert v["truncation"]["seq"] == v["drains_recorded"]
        assert v["truncation"]["drains_beyond_recording"] >= 1


# ---------------------------------------------------------------------------
# divergence localization


class TestDivergenceLocalization:
    def _perturb(self, events, mutate):
        """Copy, mutate ONE drain, rechain to internal consistency."""
        out = [dict(e) for e in events]
        target = None
        for e in out:
            if e["kind"] != "drain":
                continue
            if mutate(e):
                target = e
                break
        assert target is not None, "no drain accepted the perturbation"
        return rechain(out), target

    def test_counter_perturbation_names_drain_and_counter(
        self, model, tmp_path
    ):
        path = str(tmp_path / "s.jsonl")
        _record(model, path)
        events, _ = load_session(path)

        def bump(e):
            if not e.get("delta") or "host_syncs" not in e["delta"]:
                return False
            if e["seq"] < 2:
                return False  # a mid-session drain, not the first
            e["delta"] = dict(e["delta"], host_syncs=e["delta"]["host_syncs"] + 1)
            return True

        pert, target = self._perturb(events, bump)
        v = replay_session(pert, engine_factory=_factory(model))
        assert v["verdict"] == "divergent" and not v["match"]
        d = v["first_divergence"]
        assert d["seq"] == target["seq"] and d["tick"] == target["tick"]
        assert d["counters"] == ["host_syncs"]
        assert d["rids"] == []
        assert d["recorded_delta"]["host_syncs"] == (
            d["replayed_delta"]["host_syncs"] + 1
        )

    def test_token_perturbation_names_request(self, model, tmp_path):
        path = str(tmp_path / "s.jsonl")
        _record(model, path)
        events, _ = load_session(path)

        def flip(e):
            toks = e.get("tokens") or {}
            if not toks:
                return False
            rid = sorted(toks)[0]
            vals = list(toks[rid])
            vals[0] = (vals[0] + 1) % 256
            e["tokens"] = dict(toks, **{rid: vals})
            return True

        pert, target = self._perturb(events, flip)
        rid = int(sorted(target["tokens"])[0])
        v = replay_session(pert, engine_factory=_factory(model))
        assert v["verdict"] == "divergent"
        d = v["first_divergence"]
        assert d["seq"] == target["seq"]
        assert d["counters"] == []
        assert d["rids"] == [rid]
        assert str(rid) in d["recorded_tokens"]
        assert str(rid) in d["replayed_tokens"]

    def test_geometry_mismatch_names_fields(self, model, tmp_path):
        """The engine_factory path: the caller's rebuilt engine claims
        its TRUE geometry, so a recording that says otherwise is a
        geometry_mismatch verdict — nothing is re-driven."""
        path = str(tmp_path / "s.jsonl")
        _record(model, path)

        def wrong(rep_rec, geom):
            kw = geometry_kwargs(geom)
            kw["num_slots"] = kw.get("num_slots", 2) + 1
            return ServeEngine(model, record=rep_rec, **kw)

        v = replay_session(path, engine_factory=wrong)
        assert v["verdict"] == "geometry_mismatch" and not v["match"]
        assert v["geometry_fields"] == ["num_slots"]
        assert v["drains_replayed"] == 0
        assert "first_divergence" not in v

    def test_three_failure_modes_are_distinct(self, model, tmp_path):
        """One recording, three injections, three different verdicts."""
        path = str(tmp_path / "s.jsonl")
        _record(model, path)
        events, _ = load_session(path)

        counter, _ = self._perturb(
            events,
            lambda e: bool(e.get("delta"))
            and e.update(delta=dict(e["delta"], host_syncs=99)) is None,
        )
        vc = replay_session(counter, engine_factory=_factory(model))
        vg = replay_session(
            path,
            engine_factory=lambda r, g: ServeEngine(
                model, record=r, **dict(geometry_kwargs(g), decode_chunk=2)
            ),
        )
        vm = replay_session(path, engine_factory=_factory(model))
        assert (vm["verdict"], vc["verdict"], vg["verdict"]) == (
            "match",
            "divergent",
            "geometry_mismatch",
        )


# ---------------------------------------------------------------------------
# zero overhead + kill switch (satellite 3)


class TestRecordingOverhead:
    def test_recording_moves_no_counter(self, model):
        """The satellite-3 pin behind the serve_cpu_smoke expectations:
        an engine with recording ON serves the identical workload with
        IDENTICAL integer counters — host_syncs included — because
        every hashed value is already host-side at the drain hook."""
        bare = _engine(model)
        out_a = bare.run([dict(w) for w in _work()])
        rec = SessionRecorder(None, enabled=True)
        taped = _engine(model, rec)
        out_b = taped.run([dict(w) for w in _work()])
        rec.close()
        assert rec.drains > 0
        assert bare.metrics.counters == taped.metrics.counters
        for a, b in zip(out_a, out_b):
            np.testing.assert_array_equal(a.tokens, b.tokens)

    def test_kill_switch_spellings(self, monkeypatch):
        for off in ("0", "false", "FALSE", "", "  0  "):
            monkeypatch.setenv("TDX_SESSION_RECORD", off)
            assert not recording_enabled()
            assert session_force_disabled()
        for on in ("1", "true", "yes"):
            monkeypatch.setenv("TDX_SESSION_RECORD", on)
            assert recording_enabled()
            assert not session_force_disabled()
        monkeypatch.delenv("TDX_SESSION_RECORD")
        assert recording_enabled() and not session_force_disabled()

    def test_kill_switch_makes_recorder_noop(
        self, model, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("TDX_SESSION_RECORD", "0")
        path = str(tmp_path / "off.jsonl")
        engine = _engine(model, path)
        engine.run([dict(w) for w in _work(n=2)])
        rec = engine.recorder
        assert rec is not None and not rec.enabled
        assert rec.events == [] and rec.drains == 0
        assert not os.path.exists(path)
        # explicit enabled=True still records (the replay harness path)
        live = SessionRecorder(None, enabled=True)
        assert live.enabled and live.events

    def test_resolve_record_surface(self, tmp_path):
        assert resolve_record(None) is None
        rec = SessionRecorder(None, enabled=True)
        assert resolve_record(rec) is rec
        mem = resolve_record(True)
        assert isinstance(mem, SessionRecorder) and mem.path is None
        p = str(tmp_path / "r.jsonl")
        assert resolve_record(p).path == p
        with pytest.raises(TypeError):
            resolve_record(3.14)


# ---------------------------------------------------------------------------
# autoscale bridge (satellite 2)


class TestAutoscaleBridge:
    POLICY = ScalingPolicy(
        min_replicas=1,
        max_replicas=2,
        windows=(2, 6),
        up_sustain=2,
        down_sustain=4,
        up_cooldown=2,
        down_cooldown=4,
    )

    def test_decision_stream_replays_bit_identically(
        self, model, tmp_path
    ):
        path = str(tmp_path / "fleet.jsonl")
        rec = SessionRecorder(path, enabled=True)
        fleet = ServeFleet([_engine(model)], record=rec)
        vectors = [{"state": "warn"}] * 3 + [{"state": "ok"}] * 9
        ctrl = AutoscaleController(
            fleet,
            self.POLICY,
            engine_factory=lambda role="serve": _engine(model),
            signal_fn=replay_signal(vectors),
            flight=False,
        )
        for w in _work(n=3):
            fleet.submit(**w)
        for _ in range(len(vectors)):
            fleet.step()
            ctrl.tick()
        while fleet.step():
            pass
        rec.close()
        assert validate_session_jsonl(path) == []

        events, _ = load_session(path)
        # the recorded signal vectors ARE the controller's outside world
        recorded_sigs = signals_from_session(events)
        assert len(recorded_sigs) == len(vectors)
        assert [s["state"] for s in recorded_sigs[:3]] == ["warn"] * 3
        # recorded ctrl_tick decisions == the fleet's ("scale", ...)
        # events, tick for tick — the bridge records what happened
        scale_evs = [
            (d["tick"], d["action"])
            for n, _ts, d in fleet.events
            if n == "scale"
        ]
        ct_evs = [
            (e["tick"], e["action"])
            for e in events
            if e["kind"] == "ctrl_tick"
        ]
        assert ct_evs == scale_evs
        assert any(
            a == "scale_up" for _, a in ct_evs
        ), f"no scale-up recorded: {ct_evs}"

        v = replay_session(path, engine_factory=_factory(model))
        assert v["verdict"] == "match", v
        assert v["autoscale"] == {"ticks": len(vectors), "match": True}

    def test_perturbed_signal_diverges_the_decision_stream(
        self, model, tmp_path
    ):
        path = str(tmp_path / "fleet.jsonl")
        rec = SessionRecorder(path, enabled=True)
        fleet = ServeFleet([_engine(model)], record=rec)
        vectors = [{"state": "warn"}] * 3 + [{"state": "ok"}] * 9
        ctrl = AutoscaleController(
            fleet,
            self.POLICY,
            engine_factory=lambda role="serve": _engine(model),
            signal_fn=replay_signal(vectors),
            flight=False,
        )
        for w in _work(n=2):
            fleet.submit(**w)
        for _ in range(len(vectors)):
            fleet.step()
            ctrl.tick()
        while fleet.step():
            pass
        rec.close()
        events, _ = load_session(path)
        # flip every recorded warn to ok: the replayed controller never
        # scales, so the decision stream must diverge and say so
        out = []
        for e in events:
            e = dict(e)
            if e.get("kind") == "ctrl_tick" and e.get("signal"):
                e["signal"] = dict(e["signal"], state="ok")
            out.append(e)
        v = replay_session(rechain(out), engine_factory=_factory(model))
        assert v["autoscale"]["match"] is False
        assert v["verdict"] == "divergent"


# ---------------------------------------------------------------------------
# fleet + variant grid


class TestFleetRecording:
    def test_fleet_replay_match(self, model, tmp_path):
        path = str(tmp_path / "fleet.jsonl")
        rec = SessionRecorder(path, enabled=True)
        fleet = ServeFleet(
            [_engine(model), _engine(model)], record=rec
        )
        for w in _work(n=4):
            fleet.submit(**w)
        while fleet.step():
            pass
        rec.close()
        assert validate_session_jsonl(path) == []
        events, _ = load_session(path)
        fl_ev = next(e for e in events if e["kind"] == "fleet")
        assert len(fl_ev["replicas"]) == 2
        sources = {
            e["source"] for e in events if e["kind"] == "drain"
        }
        assert len(sources) >= 1  # per-replica digest streams
        v = replay_session(path, engine_factory=_factory(model))
        assert v["verdict"] == "match", v
        assert v["chain_replayed"] == v["chain_recorded"]


VARIANTS = {
    "paged": dict(page_size=8, num_pages=32),
    "speculative": dict(
        decode_mode="persistent", speculate=2, spec_ngram=2
    ),
    "int8": dict(kv_dtype="int8"),
    "persistent": dict(decode_mode="persistent"),
}


@pytest.mark.slow
class TestVariantGridSlow:
    """The exhaustive engine-shape grid (fast siblings above cover the
    default geometry): every variant records, validates, and replays
    bit-identically, and a counter perturbation still localizes."""

    @pytest.mark.parametrize("name", sorted(VARIANTS))
    def test_variant_replay_match(self, model, tmp_path, name):
        path = str(tmp_path / f"{name}.jsonl")
        _record(model, path, **VARIANTS[name])
        assert validate_session_jsonl(path) == []
        v = replay_session(path, engine_factory=_factory(model))
        assert v["verdict"] == "match", (name, v)

    @pytest.mark.parametrize("name", ["paged", "int8"])
    def test_variant_perturbation_localizes(self, model, tmp_path, name):
        path = str(tmp_path / f"{name}.jsonl")
        _record(model, path, **VARIANTS[name])
        events, _ = load_session(path)
        drains = [
            e for e in events if e["kind"] == "drain" and e.get("delta")
        ]
        target = drains[len(drains) // 2]
        out = []
        for e in events:
            e = dict(e)
            if e.get("kind") == "drain" and e.get("seq") == target["seq"]:
                k = sorted(e["delta"])[0]
                e["delta"] = dict(e["delta"], **{k: e["delta"][k] + 1})
            out.append(e)
        v = replay_session(rechain(out), engine_factory=_factory(model))
        assert v["verdict"] == "divergent"
        assert v["first_divergence"]["seq"] == target["seq"]

    def test_fleet_speculative_int8_composition(self, model, tmp_path):
        """The full stack in one recording: a 2-replica fleet of paged
        int8 speculative persistent engines."""
        kw = dict(
            decode_mode="persistent",
            speculate=2,
            spec_ngram=2,
            kv_dtype="int8",
        )
        path = str(tmp_path / "composed.jsonl")
        rec = SessionRecorder(path, enabled=True)
        fleet = ServeFleet(
            [_engine(model, **kw), _engine(model, **kw)], record=rec
        )
        for w in _work(n=4):
            fleet.submit(**w)
        while fleet.step():
            pass
        rec.close()
        assert validate_session_jsonl(path) == []
        v = replay_session(path, engine_factory=_factory(model))
        assert v["verdict"] == "match", v


# ---------------------------------------------------------------------------
# trainer analog


class TestTrainerRecording:
    def _trainer(self, mesh8, rec):
        from torchdistx_tpu import nn
        from torchdistx_tpu.nn import functional_call
        from torchdistx_tpu.optimizers import anyprecision_adamw
        from torchdistx_tpu.parallel import ShardedTrainStep
        from torchdistx_tpu.trainer import Trainer

        tdx.manual_seed(0)
        model = tdx.deferred_init(
            lambda: nn.Sequential(nn.Embedding(64, 32), nn.Linear(32, 64))
        )
        tdx.materialize_module(model)

        def loss_fn(p, batch):
            x, y = batch
            return nn.functional.cross_entropy(
                functional_call(model, p, (x,)), y
            )

        step = ShardedTrainStep(
            loss_fn, anyprecision_adamw(1e-2), mesh8, shard_axis="fsdp"
        )
        params = step.shard_params(dict(model.named_parameters()))
        return Trainer(step, params, record=rec, log_every=100)

    def test_fit_records_batch_identity(self, mesh8):
        from torchdistx_tpu.data import DataLoader, TokenDataset

        rec = SessionRecorder(None, enabled=True)
        tr = self._trainer(mesh8, rec)
        ds = TokenDataset(np.arange(2000) % 64, seq_len=16)
        dl = DataLoader(ds, batch_size=8, shuffle=True, seed=0, prefetch=0)
        tr.fit(iter(dl), num_steps=3)
        head = next(e for e in rec.events if e["kind"] == "trainer")
        assert head["step_type"] == "ShardedTrainStep"
        steps = [e for e in rec.events if e["kind"] == "train_step"]
        assert [e["step"] for e in steps] == [0, 1, 2]
        assert all(
            isinstance(e["batch"], str) and len(e["batch"]) == 64
            for e in steps
        )
        assert all(e["rng_counter"] is not None for e in steps)
        # same data order ⇒ same digests; the digest IS batch identity
        rec2 = SessionRecorder(None, enabled=True)
        tr2 = self._trainer(mesh8, rec2)
        dl2 = DataLoader(ds, batch_size=8, shuffle=True, seed=0, prefetch=0)
        tr2.fit(iter(dl2), num_steps=3)
        steps2 = [e for e in rec2.events if e["kind"] == "train_step"]
        assert [e["batch"] for e in steps] == [e["batch"] for e in steps2]

    def test_batch_digest_is_content_addressed(self):
        from torchdistx_tpu.trainer import batch_digest

        a = (np.arange(8, dtype=np.int32), np.ones((2, 2)))
        b = (np.arange(8, dtype=np.int32), np.ones((2, 2)))
        c = (np.arange(8, dtype=np.int32), np.zeros((2, 2)))
        assert batch_digest(a) == batch_digest(b)
        assert batch_digest(a) != batch_digest(c)
        # dtype is identity too, not just bytes
        assert batch_digest(np.arange(4, dtype=np.int32)) != batch_digest(
            np.arange(4, dtype=np.int64)
        )


# ---------------------------------------------------------------------------
# flight integration


class TestFlightIntegration:
    def test_flight_dump_names_the_session(self, model, tmp_path):
        from torchdistx_tpu.obs import get_flight_recorder

        flight = get_flight_recorder()
        before = flight.session_path
        try:
            path = str(tmp_path / "s.jsonl")
            _record(model, path)
            assert flight.session_path == path
            os.environ["TDX_FLIGHT_DIR"] = str(tmp_path)
            try:
                dump = flight.dump(reason="test")
            finally:
                os.environ.pop("TDX_FLIGHT_DIR", None)
            with open(dump) as f:
                header = json.loads(f.readline())
            assert header["session"] == path
        finally:
            flight.session_path = before
