"""Int8 KV-cache quantization (serve/kv_cache.py + ops/decode_attention.py
+ ServeEngine(kv_dtype=)).

The load-bearing invariants, pinned on the 8-device CPU mesh:

- **Exact roundtrip**: scales are POWERS OF TWO (mantissa untouched), so
  ``quantize(dequantize(quantize(x)))`` is bit-stable — the chunked /
  persistent RMW loops (quantize on write, dequantize on read, every
  step) never re-round.  This is the reason the repo deviates from
  per-tensor float scales.
- **Kernel parity**: every quantized kernel branch (slab / paged, the
  block variants ride the engine tests) matches the jnp path computed on
  the DEQUANTIZED cache at the repo's ≤2-ulp interpret bar — quantization
  error lives entirely in the stored values, never in the kernel math.
- **Within-dtype bit-identity**: int8 streams are bit-identical across
  slab / paged / speculative engines (same stored values ⇒ same math);
  divergence exists only ACROSS dtypes and is pinned at the geometry
  under test.
- **Priced end-to-end**: ``memory_plan()`` halves the KV data component
  exactly vs bf16 and surfaces the scales; migration / handoff wire
  closed forms price each entry array at its own itemsize and stay
  exact against audit + counters; mixed-dtype moves refuse loudly.
- **No stale scales**: page reuse after retire cannot leak a previous
  request's scale rows (the int8 twin of the paged stale-row
  regression).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu.models import Llama
from torchdistx_tpu.obs.comm import CommProfile, comm_audit
from torchdistx_tpu.serve import ServeEngine, ServeFleet
from torchdistx_tpu.serve.kv_cache import (
    canonicalize_kv_dtype,
    dequantize_cache,
    dequantize_kv,
    quantize_cache,
    quantize_kv,
)

_ULP = 3e-7  # ~2 f32 ulps at unit scale (tests/test_decode_attention.py)


def _llama():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 256, (n,)).astype(np.int32) for n in lengths]


def _tp_mesh(tp):
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:tp]), ("tp",))


def _engine(tp=1, slots=3, paged=False, **kw):
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", (16,))
    if paged:
        kw.setdefault("page_size", 8)
        kw.setdefault("num_pages", 32)
    if tp > 1:
        kw["mesh"] = _tp_mesh(tp)
    return ServeEngine(_llama(), num_slots=slots, **kw)


class TestQuantizeRoundtrip:
    def test_scales_are_powers_of_two(self):
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(4, 7, 2, 8) * 13.0, jnp.float32)
        _, scale = quantize_kv(x)
        m, _ = np.frexp(np.asarray(scale))
        assert np.all(m == 0.5)  # exactly 2^e: mantissa is always 0.5

    def test_roundtrip_is_idempotent(self):
        """quantize -> dequantize -> quantize is a fixpoint: int8 times a
        power of two is exact in f32, so re-quantizing re-derives the
        same scale and the same codes.  THE invariant that lets the RMW
        decode loops requantize freely."""
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(3, 5, 2, 8), jnp.float32)
        q1, s1 = quantize_kv(x)
        deq = dequantize_kv(q1, s1)
        q2, s2 = quantize_kv(deq)
        np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
        np.testing.assert_array_equal(
            np.asarray(deq), np.asarray(dequantize_kv(q2, s2))
        )

    def test_grid_covers_amax_and_clips(self):
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.randn(2, 4, 1, 16) * 100.0, jnp.float32)
        q, scale = quantize_kv(x)
        assert q.dtype == jnp.int8
        assert scale.shape == x.shape[:-1] + (1,)
        q_np = np.asarray(q, np.int32)
        assert q_np.min() >= -127 and q_np.max() <= 127
        # relative error bounded by half a step: |x - q*s| <= s/2, and
        # s < 2*amax/127 by the pow-2 ceiling
        err = np.abs(np.asarray(x) - np.asarray(dequantize_kv(q, scale)))
        assert np.all(err <= np.asarray(scale) / 2 + 1e-9)

    def test_zero_rows_are_harmless(self):
        x = jnp.zeros((2, 3, 2, 8), jnp.float32)
        q, scale = quantize_kv(x)
        assert np.all(np.asarray(q) == 0)
        assert np.all(np.isfinite(np.asarray(scale)))
        np.testing.assert_array_equal(
            np.asarray(dequantize_kv(q, scale)), np.zeros_like(x)
        )

    def test_cache_helpers_and_passthrough(self):
        rs = np.random.RandomState(3)
        kv = [
            (
                jnp.asarray(rs.randn(2, 4, 2, 8), jnp.float32),
                jnp.asarray(rs.randn(2, 4, 2, 8), jnp.float32),
            )
        ]
        quant = quantize_cache(kv)
        assert len(quant[0]) == 4
        back = dequantize_cache(quant)
        assert len(back[0]) == 2
        # unquantized pairs pass through dequantize_cache untouched
        assert dequantize_cache(kv)[0][0] is kv[0][0]

    def test_canonicalize(self):
        assert canonicalize_kv_dtype(None) is None
        assert canonicalize_kv_dtype("int8") == "int8"
        with pytest.raises(ValueError):
            canonicalize_kv_dtype("int4")


class TestQuantizedKernelParity:
    """Kernel-vs-jnp on the DEQUANTIZED cache: the quantized kernel's
    only new math is ``q * scale`` in VMEM, so it must match the jnp
    path fed the dequantized arrays at the standard interpret bar."""

    def _quant_case(self, seed, b=3, hq=4, hkv=2, d=8, max_seq=16):
        rs = np.random.RandomState(seed)
        q = jnp.asarray(rs.randn(b, 1, hq, d), jnp.float32)
        ck = jnp.asarray(rs.randn(b, max_seq, hkv, d), jnp.float32)
        cv = jnp.asarray(rs.randn(b, max_seq, hkv, d), jnp.float32)
        qk, sk = quantize_kv(ck)
        qv, sv = quantize_kv(cv)
        pos = jnp.asarray(rs.randint(0, max_seq, (b,)), jnp.int32)
        return q, (qk, qv, sk, sv), pos

    def test_slab_kernel_matches_dequantized_jnp(self):
        from torchdistx_tpu.ops.attention import slot_cached_attention
        from torchdistx_tpu.ops.decode_attention import decode_attention

        q, (qk, qv, sk, sv), pos = self._quant_case(7)
        dk, dv = dequantize_kv(qk, sk), dequantize_kv(qv, sv)
        # post-write contract: re-write the row already AT ``pos`` so the
        # jnp path attends exactly the dequantized cache, bit for bit
        idx = pos[:, None, None, None]
        ref, (rk, _) = slot_cached_attention(
            q,
            jnp.take_along_axis(dk, idx, axis=1),
            jnp.take_along_axis(dv, idx, axis=1),
            (dk, dv),
            pos,
            use_flash=False,
        )
        np.testing.assert_array_equal(np.asarray(rk), np.asarray(dk))
        out = decode_attention(
            q, qk, qv, pos, k_scale=sk, v_scale=sv, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    def test_paged_kernel_matches_dequantized_jnp(self):
        from torchdistx_tpu.ops.attention import slot_cached_attention
        from torchdistx_tpu.ops.decode_attention import (
            paged_decode_attention,
        )

        rs = np.random.RandomState(11)
        b, hq, hkv, d, pp, ps = 3, 4, 2, 8, 8, 4
        q = jnp.asarray(rs.randn(b, 1, hq, d), jnp.float32)
        ck = jnp.asarray(rs.randn(pp, ps, hkv, d), jnp.float32)
        cv = jnp.asarray(rs.randn(pp, ps, hkv, d), jnp.float32)
        qk, sk = quantize_kv(ck)
        qv, sv = quantize_kv(cv)
        tables = jnp.asarray(
            np.stack([rs.permutation(pp)[: pp // 2] for _ in range(b)]),
            jnp.int32,
        )
        pos = jnp.asarray(rs.randint(0, (pp // 2) * ps, (b,)), jnp.int32)
        dk, dv = dequantize_kv(qk, sk), dequantize_kv(qv, sv)
        # jnp reference: gather the dequantized pages into slab layout,
        # then no-op-rewrite the row at ``pos`` (post-write contract)
        slab_k = dk[tables].reshape(b, -1, hkv, d)
        slab_v = dv[tables].reshape(b, -1, hkv, d)
        idx = pos[:, None, None, None]
        ref, _ = slot_cached_attention(
            q,
            jnp.take_along_axis(slab_k, idx, axis=1),
            jnp.take_along_axis(slab_v, idx, axis=1),
            (slab_k, slab_v),
            pos,
            use_flash=False,
        )
        out = paged_decode_attention(
            q, qk, qv, tables, pos, k_scale=sk, v_scale=sv, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=_ULP, atol=_ULP
        )

    def test_scales_must_come_together_and_shaped(self):
        from torchdistx_tpu.ops.decode_attention import decode_attention

        q, (qk, qv, sk, sv), pos = self._quant_case(13)
        with pytest.raises(ValueError):
            decode_attention(q, qk, qv, pos, k_scale=sk, interpret=True)
        with pytest.raises(ValueError):
            decode_attention(
                q, qk, qv, pos, k_scale=sk[..., 0], v_scale=sv[..., 0],
                interpret=True,
            )


class TestQuantizedEngine:
    def test_streams_pinned_and_internally_bit_identical(self):
        """Divergence exists only ACROSS dtypes (pinned at this
        geometry: 4/5 greedy streams identical to f32); WITHIN int8 the
        slab, paged and speculative engines are bit-identical — same
        stored values, same kernels, same math."""
        prompts = _prompts(0, (6, 11, 9, 4, 13))
        reqs = [{"prompt": p, "max_new_tokens": 12} for p in prompts]
        t_f32 = [list(r.tokens) for r in _engine().run(reqs)]
        t_i8 = [
            list(r.tokens)
            for r in _engine(kv_dtype="int8").run(reqs)
        ]
        agree = sum(a == b for a, b in zip(t_i8, t_f32))
        assert agree >= 4  # deterministic at this seed; 5 exceeds spec
        t_paged = [
            list(r.tokens)
            for r in _engine(paged=True, kv_dtype="int8").run(reqs)
        ]
        t_spec = [
            list(r.tokens)
            for r in _engine(speculate=2, kv_dtype="int8").run(reqs)
        ]
        assert t_paged == t_i8
        assert t_spec == t_i8

    def test_memory_plan_halves_and_names_dtype(self):
        e_i8 = _engine(kv_dtype="int8")
        e_bf = _engine(kv_dtype="bfloat16")
        e_f32 = _engine()
        p_i8, p_bf, p_f32 = (
            e.memory_plan() for e in (e_i8, e_bf, e_f32)
        )
        assert p_i8["components"]["kv_cache"] * 2 == (
            p_bf["components"]["kv_cache"]
        )
        assert p_i8["components"]["kv_cache"] * 4 == (
            p_f32["components"]["kv_cache"]
        )
        assert p_i8["components"]["kv_scales"] > 0
        assert p_i8["kv_cache_dtype"] == "int8"
        # default plans: unchanged surface — data-only equals the cache
        # nbytes, no scales line, dtype named
        for e, p in ((e_bf, p_bf), (e_f32, p_f32)):
            assert "kv_scales" not in p["components"]
            assert p["components"]["kv_cache"] == e.cache.nbytes
        assert p_f32["kv_cache_dtype"] == "float32"

    def test_metrics_gauges_survive_reset(self):
        """``kv_cache_bytes`` is the TOTAL resident pool — int8 data
        plus the f32 scale sidecar — and the split reconciles exactly
        with the cache's own accounting."""
        e = _engine(kv_dtype="int8")
        g = e.metrics.to_json()["gauges"]
        assert g["kv_cache_bytes"] == e.cache.nbytes
        assert e.cache.nbytes == (
            e.cache.kv_data_nbytes + e.cache.kv_scale_nbytes
        )
        rows = e.num_slots * e.max_len
        assert g["kv_bytes_per_token"] == e.cache.nbytes // rows
        # int8 data is exactly a quarter of the f32 pool, and the total
        # stays under half of it even with the f32 sidecar riding
        f32 = _engine()
        g_f32 = f32.metrics.to_json()["gauges"]
        assert e.cache.kv_data_nbytes * 4 == f32.cache.nbytes
        assert g["kv_cache_bytes"] * 2 < g_f32["kv_cache_bytes"]
        e.reset_metrics()
        g2 = e.metrics.to_json()["gauges"]
        assert g2["kv_cache_bytes"] == g["kv_cache_bytes"]
        assert g2["kv_bytes_per_token"] == g["kv_bytes_per_token"]

    def test_static_key_separates_dtypes(self):
        assert (
            _engine(kv_dtype="int8")._static_key()
            != _engine()._static_key()
        )

    def test_submit_rejection_names_cache_dtype(self):
        e = _engine(paged=True, num_pages=4, kv_dtype="int8")
        # fits max_len (44 <= 64) and the prefill bucket (14 <= 16) but
        # needs 6 pages of 8 against a 4-page pool
        with pytest.raises(ValueError, match="int8 cache pool"):
            e.submit(_prompts(1, (14,))[0], max_new_tokens=30)

    def test_no_stale_scales_across_page_reuse(self):
        """The int8 twin of the paged stale-row regression
        (tests/test_prefix_cache.py): retire a LONG request, admit a
        SHORTER one onto its freed pages — stale SCALE rows beyond the
        new request's depth must not perturb the stream."""
        model = _llama()
        long_p, short_p = _prompts(3, (40, 6))
        engine = ServeEngine(
            model, num_slots=1, max_len=64, page_size=8,
            num_pages=8, prefix_cache=False, kv_dtype="int8",
        )
        engine.run([{"prompt": long_p, "max_new_tokens": 8}])
        assert engine.pool.in_use == 0
        got = engine.run([{"prompt": short_p, "max_new_tokens": 8}])[0]
        fresh = ServeEngine(
            model, num_slots=1, max_len=64, page_size=8,
            num_pages=8, prefix_cache=False, kv_dtype="int8",
        ).run([{"prompt": short_p, "max_new_tokens": 8}])[0]
        np.testing.assert_array_equal(got.tokens, fresh.tokens)


class TestKVQuantNumerics:
    """ISSUE 19 satellite: the numerics observatory's KV dequant-error
    digests feed ``kv_quant_err_max`` / ``kv_quant_err_rms`` gauges
    (int8 pools only), and the observed max is pinned by the power-of-
    two quantizer's round-to-nearest bound ``s/2``."""

    def _run(self, **kw):
        e = _engine(kv_dtype="int8", numerics=True, **kw)
        e.run(
            [
                {"prompt": p, "max_new_tokens": 8, "temperature": 0.0}
                for p in _prompts(11, (5, 9, 12))
            ]
        )
        return e

    @pytest.mark.parametrize("paged", [False, True])
    def test_err_max_pinned_by_half_scale(self, paged):
        e = self._run(paged=paged)
        book = e.numerics_book
        err = book.digest("kv_quant_err")
        scale = book.digest("kv_quant_scale")
        assert err is not None and err.count > 0
        assert err.nonfinite == 0
        # round-to-nearest int8 against a power-of-two scale: every
        # dequant error is <= s/2 with s the LARGEST scale the write
        # sites produced (max_abs of the scale digest) — tiny float
        # headroom only for the digest's own f32 max reduction
        bound = 0.5 * scale.max_abs
        assert err.max_abs <= bound * (1 + 1e-6), (err.max_abs, bound)
        g = e.metrics.to_json()["gauges"]
        assert g["kv_quant_err_max"] == err.max_abs
        assert g["kv_quant_err_max"] <= bound * (1 + 1e-6)
        assert 0 < g["kv_quant_err_rms"] <= g["kv_quant_err_max"]

    def test_gauges_survive_reset_metrics(self):
        e = self._run()
        g = e.metrics.to_json()["gauges"]
        e.reset_metrics()
        g2 = e.metrics.to_json()["gauges"]
        assert g2["kv_quant_err_max"] == g["kv_quant_err_max"]
        assert g2["kv_quant_err_rms"] == g["kv_quant_err_rms"]

    def test_gauges_int8_pools_only(self):
        # plain bf16/f32 caches have no quantizer, hence no error gauge
        # family — even with the observatory on
        e = _engine(numerics=True)
        e.run([{"prompt": _prompts(11, (5,))[0], "max_new_tokens": 4}])
        g = e.metrics.to_json()["gauges"]
        assert "kv_quant_err_max" not in g
        assert "kv_quant_err_rms" not in g


class TestQuantizedMoves:
    def _reqs(self):
        prompts = _prompts(7, (6, 9, 5, 11))
        mnt = [8, 10, 12, 6]
        return [
            {"prompt": p, "max_new_tokens": m}
            for p, m in zip(prompts, mnt)
        ]

    @staticmethod
    def _entry_wire_bytes(entry, g):
        """The per-layer closed form: each array of the entry tuple —
        int8 data AND f32 scales — priced at its own itemsize through
        the ring all-gather, ``unit * (g-1) // g``."""
        total = 0
        for arr in entry:
            unit = int(np.prod(arr.shape[1:])) * np.dtype(arr.dtype).itemsize
            total += unit * (g - 1) // g
        return total

    def test_migration_scales_ride_and_wire_is_exact(self):
        reqs = self._reqs()
        ref = [r.tokens for r in _engine(tp=2, kv_dtype="int8").run(reqs)]
        src = _engine(tp=2, kv_dtype="int8", decode_chunk=2)
        dst = _engine(tp=1, slots=4, kv_dtype="int8", decode_chunk=2)
        handles = [
            src.submit(r["prompt"], max_new_tokens=r["max_new_tokens"])
            for r in reqs
        ]
        for _ in range(2):
            src.step()
        src.drain()
        prof = CommProfile()
        with comm_audit(prof):
            summary = src.migrate_to(dst)
        while dst.step():
            pass
        for h, r in zip(handles, ref):
            np.testing.assert_array_equal(h.result().tokens, r)
        n_moved = summary["migrated_running"]
        expect = (
            n_moved
            * len(src.cache.kv)
            * self._entry_wire_bytes(src.cache.kv[0], 2)
        )
        assert summary["wire_bytes"] == expect
        assert int(prof.wire_bytes("all_gather", "tp")) == expect
        assert src.metrics.counters["migration_wire_bytes"] == expect
        # int8 moves strictly fewer bytes than the same scenario in bf16
        src2 = _engine(tp=2, kv_dtype="bfloat16", decode_chunk=2)
        dst2 = _engine(tp=1, slots=4, kv_dtype="bfloat16", decode_chunk=2)
        for r in reqs:
            src2.submit(r["prompt"], max_new_tokens=r["max_new_tokens"])
        for _ in range(2):
            src2.step()
        src2.drain()
        assert summary["wire_bytes"] < src2.migrate_to(dst2)["wire_bytes"]

    def test_migrate_dtype_mismatch_refused(self):
        a = _engine(slots=2, kv_dtype="int8")
        b = _engine(slots=2)
        with pytest.raises(RuntimeError, match="KV dtype mismatch"):
            a.migrate_to(b)

    def test_disagg_handoff_scales_ride_and_wire_is_exact(self):
        rs = np.random.RandomState(13)
        prefix = rs.randint(0, 256, (16,)).astype(np.int32)
        reqs = [
            {
                "prompt": np.concatenate(
                    [prefix, rs.randint(0, 256, (4,)).astype(np.int32)]
                ),
                "max_new_tokens": m,
            }
            for m in (6, 8, 6, 8)
        ]
        ref = _engine(
            slots=4, prefill_buckets=(32,), kv_dtype="int8"
        ).run(reqs)
        pre = _engine(
            tp=2, slots=4, prefill_buckets=(32,), kv_dtype="int8"
        )
        dec = _engine(
            slots=4, prefill_buckets=(32,), kv_dtype="int8"
        )
        fleet = ServeFleet(
            [pre, dec], disaggregate=True, roles=["prefill", "decode"]
        )
        prof = CommProfile()
        with comm_audit(prof):
            out = fleet.run(reqs)
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(o.tokens, r.tokens)
        expect = (
            len(reqs)
            * len(pre.cache.kv)
            * TestQuantizedMoves._entry_wire_bytes(pre.cache.kv[0], 2)
        )
        got = pre.metrics.counters["handoff_wire_bytes"]
        assert got == expect
        assert int(prof.wire_bytes("all_gather", "tp")) == expect

    def test_handoff_dtype_mismatch_refused(self):
        pre = _engine(slots=2, kv_dtype="int8")
        dec = _engine(slots=2)
        fleet = ServeFleet([pre, dec], disaggregate=True)
        with pytest.raises(RuntimeError, match="KV dtype mismatch"):
            fleet.run(
                [
                    {
                        "prompt": _prompts(15, (8,))[0],
                        "max_new_tokens": 2,
                    }
                ]
            )
