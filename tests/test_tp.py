"""Tensor parallelism: TP-sharded materialization + GSPMD train step must be
numerically exact vs single-device training (TP is an exact decomposition)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu.models import Llama
from torchdistx_tpu.nn import functional, functional_call
from torchdistx_tpu.parallel import GSPMDTrainStep, create_mesh, llama_tp_rule


def _data(vocab=256, b=4, s=16):
    rs = np.random.RandomState(0)
    tokens = rs.randint(0, vocab, (b, s)).astype(np.int32)
    labels = rs.randint(0, vocab, (b, s)).astype(np.int32)
    return tokens, labels


def test_llama_tp_rule_assignments():
    mesh = create_mesh({"dp": 2, "tp": 4})
    rule = llama_tp_rule(mesh, "tp")
    like2d = jax.ShapeDtypeStruct((64, 64), jnp.float32)
    assert rule("blocks.0.attn.wq.weight", like2d).spec == P("tp", None)
    assert rule("blocks.0.attn.wo.weight", like2d).spec == P(None, "tp")
    assert rule("blocks.0.mlp.w_down.weight", like2d).spec == P(None, "tp")
    assert rule("tok_emb.weight", like2d).spec == P("tp", None)
    assert rule("norm.weight", jax.ShapeDtypeStruct((64,), jnp.float32)).spec == P()


def test_tp_training_matches_single_device():
    mesh = create_mesh({"dp": 2, "tp": 4})
    tdx.manual_seed(0)
    model = tdx.deferred_init(Llama.from_name, "tiny")
    tdx.materialize_module(model, sharding_rule=llama_tp_rule(mesh, "tp"))
    params = dict(model.named_parameters())
    assert params["blocks.0.attn.wq.weight"].sharding.spec == P("tp", None)

    def loss_fn(p, batch):
        tokens, labels = batch
        logits = functional_call(model, p, (tokens,))
        return functional.cross_entropy(logits, labels)

    batch = _data()

    # single-device reference trajectory
    tdx.manual_seed(0)
    ref_model = tdx.deferred_init(Llama.from_name, "tiny")
    tdx.materialize_module(ref_model)
    ref_params = dict(ref_model.named_parameters())
    tx = optax.sgd(1e-1)

    @jax.jit
    def ref_step(p, s, b):
        def lf(p):
            return loss_fn(p, b)

        loss, g = jax.value_and_grad(lf)(p)
        u, s = tx.update(g, s, p)
        return jax.tree_util.tree_map(lambda a, b_: a + b_, p, u), s, loss

    ref_s = tx.init(ref_params)
    for _ in range(3):
        ref_params, ref_s, ref_loss = ref_step(ref_params, ref_s, batch)

    step = GSPMDTrainStep(loss_fn, optax.sgd(1e-1), mesh, batch_spec=P("dp"))
    s = step.init_optimizer(params)
    for _ in range(3):
        params, s, loss = step(params, s, batch)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in ref_params:
        np.testing.assert_allclose(
            np.asarray(params[k]),
            np.asarray(ref_params[k]),
            rtol=1e-4,
            atol=1e-6,
            err_msg=k,
        )
    # params kept their TP sharding through the steps
    assert params["blocks.0.attn.wq.weight"].sharding.spec == P("tp", None)


def test_tp_fsdp_2d_materialize():
    mesh = create_mesh({"fsdp": 2, "tp": 4})
    tdx.manual_seed(1)
    model = tdx.deferred_init(Llama.from_name, "tiny")
    tdx.materialize_module(
        model, sharding_rule=llama_tp_rule(mesh, "tp", fsdp_axis="fsdp")
    )
    w = dict(model.named_parameters())["blocks.0.attn.wq.weight"]
    assert w.sharding.spec == P("tp", "fsdp")
    assert len(w.sharding.device_set) == 8


def test_mismatched_batch_sharding_warns_once(mesh8):
    """VERDICT weak #7: a pre-distributed batch whose layout differs from
    batch_spec is accepted but warned about (once per layout)."""
    import warnings as _warnings

    from jax.sharding import NamedSharding

    mesh = create_mesh({"dp": 2, "tp": 4})
    tdx.manual_seed(3)
    model = tdx.deferred_init(Llama.from_name, "tiny")
    tdx.materialize_module(model, sharding_rule=llama_tp_rule(mesh, "tp"))
    params = dict(model.named_parameters())

    def loss_fn(p, batch):
        t, l = batch
        return functional.cross_entropy(
            functional_call(model, p, (t,)), l
        )

    step = GSPMDTrainStep(
        loss_fn, optax.sgd(1e-3), mesh, batch_spec=P("dp")
    )
    s = step.init_optimizer(params)
    # distribute the batch over the WRONG axis layout (tp-major)
    wrong = NamedSharding(mesh, P("tp"))
    t = jax.device_put(jnp.zeros((8, 16), jnp.int32), wrong)
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        params, s, _ = step(params, s, (t, t))
        params, s, _ = step(params, s, (t, t))  # same layout: no second warn
    msgs = [str(w.message) for w in rec if "batch_spec" in str(w.message)]
    assert len(msgs) == 1  # once per distinct (sharding, shape) layout


def test_gradient_accumulation_matches_full_batch(mesh8):
    """accum_steps=2 must produce the same update as the full batch in one
    pass (mean-reduced loss => averaged micro-gradients are identical)."""
    mesh = create_mesh({"dp": 2, "tp": 4})
    tdx.manual_seed(9)
    model = tdx.deferred_init(Llama.from_name, "tiny")
    tdx.materialize_module(model, sharding_rule=llama_tp_rule(mesh, "tp"))
    params = dict(model.named_parameters())

    def loss_fn(p, batch):
        t, l = batch
        return functional.cross_entropy(functional_call(model, p, (t,)), l)

    tokens, labels = _data(b=8, s=16)

    outs = {}
    for accum in (1, 2):
        step = GSPMDTrainStep(
            loss_fn,
            optax.sgd(1e-2),
            mesh,
            batch_spec=P("dp"),
            accum_steps=accum,
        )
        # fresh buffers per run: the jitted step donates params/opt_state
        pcopy = jax.tree_util.tree_map(lambda x: x + 0, params)
        s0 = step.init_optimizer(pcopy)
        p1, _, loss = step(pcopy, s0, (tokens, labels))
        outs[accum] = (p1, float(loss))

    assert np.isclose(outs[1][1], outs[2][1], rtol=1e-5)
    for k in outs[1][0]:
        np.testing.assert_allclose(
            np.asarray(outs[1][0][k]),
            np.asarray(outs[2][0][k]),
            rtol=3e-6,
            atol=3e-7,
            err_msg=k,
        )


def test_gradient_accumulation_indivisible_raises(mesh8):
    mesh = create_mesh({"dp": 2, "tp": 4})
    tdx.manual_seed(9)
    model = tdx.deferred_init(Llama.from_name, "tiny")
    tdx.materialize_module(model, sharding_rule=llama_tp_rule(mesh, "tp"))
    params = dict(model.named_parameters())

    def loss_fn(p, batch):
        t, l = batch
        return functional.cross_entropy(functional_call(model, p, (t,)), l)

    step = GSPMDTrainStep(
        loss_fn, optax.sgd(1e-2), mesh, batch_spec=P("dp"), accum_steps=3
    )
    pcopy = jax.tree_util.tree_map(lambda x: x + 0, params)
    s0 = step.init_optimizer(pcopy)
    tokens, labels = _data(b=8, s=16)
    with pytest.raises(ValueError, match="not divisible"):
        step(pcopy, s0, (tokens, labels))
