"""HF/torch interop: loading HF state dicts into our models must reproduce
the HF forward pass — this doubles as an architecture-fidelity check of our
GPT-2 / Llama / T5 implementations against the canonical ones.

HF models are constructed from local configs (random init, no downloads)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

import torchdistx_tpu as tdx  # noqa: E402
from torchdistx_tpu.interop import (  # noqa: E402
    from_torch_state_dict,
    gpt2_key_map,
    llama_key_map,
    t5_key_map,
)
from torchdistx_tpu.models import GPT2, Llama, T5  # noqa: E402
from torchdistx_tpu.models.gpt2 import GPT2Config  # noqa: E402
from torchdistx_tpu.models.llama import LlamaConfig  # noqa: E402
from torchdistx_tpu.models.t5 import T5Config  # noqa: E402


@pytest.mark.slow
def test_gpt2_matches_hf_forward():
    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=32, n_embd=32, n_layer=2, n_head=4
    )
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()

    ours = GPT2(GPT2Config(vocab_size=128, n_positions=32, dim=32, n_layers=2, n_heads=4))
    from_torch_state_dict(ours, hf.state_dict(), gpt2_key_map(2))

    tokens = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(tokens)).logits.numpy()
    our_logits = np.asarray(ours(jnp.asarray(tokens)))
    np.testing.assert_allclose(our_logits, hf_logits, rtol=2e-4, atol=2e-4)


def test_llama_matches_hf_forward():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=32,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    ours = Llama(
        LlamaConfig(
            vocab_size=128,
            dim=32,
            n_layers=2,
            n_heads=4,
            n_kv_heads=2,
            ffn_dim=64,
            max_seq_len=32,
            dtype=jnp.float32,
            norm_eps=1e-6,  # HF rms_norm_eps default
        )
    )
    from_torch_state_dict(ours, hf.state_dict(), llama_key_map(2))

    tokens = np.random.RandomState(1).randint(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(tokens)).logits.numpy()
    our_logits = np.asarray(ours(jnp.asarray(tokens)))
    np.testing.assert_allclose(our_logits, hf_logits, rtol=1e-3, atol=1e-3)


def test_t5_matches_hf_forward():
    hf_cfg = transformers.T5Config(
        vocab_size=128,
        d_model=32,
        d_ff=64,
        d_kv=8,
        num_heads=4,
        num_layers=2,
        relative_attention_num_buckets=8,
        relative_attention_max_distance=16,
        tie_word_embeddings=True,
    )
    hf = transformers.T5ForConditionalGeneration(hf_cfg).eval()

    ours = T5(
        T5Config(
            vocab_size=128,
            dim=32,
            d_ff=64,
            d_kv=8,
            n_heads=4,
            n_layers=2,
            rel_pos_buckets=8,
            rel_pos_max_dist=16,
        )
    )
    from_torch_state_dict(ours, hf.state_dict(), t5_key_map(2))

    enc = np.random.RandomState(2).randint(0, 128, (2, 12))
    dec = np.random.RandomState(3).randint(0, 128, (2, 6))
    with torch.no_grad():
        hf_logits = hf(
            input_ids=torch.tensor(enc), decoder_input_ids=torch.tensor(dec)
        ).logits.numpy()
    our_logits = np.asarray(ours(jnp.asarray(enc), jnp.asarray(dec)))
    np.testing.assert_allclose(our_logits, hf_logits, rtol=2e-4, atol=2e-4)


def test_shape_mismatch_raises():
    ours = GPT2(GPT2Config(vocab_size=64, n_positions=16, dim=16, n_layers=1, n_heads=2))
    bad = {"transformer.wte.weight": torch.zeros(65, 16)}
    with pytest.raises(ValueError, match="shape"):
        from_torch_state_dict(
            ours, bad, {"tok_emb.weight": ("transformer.wte.weight", None)}
        )


def test_missing_key_strictness():
    ours = GPT2(GPT2Config(vocab_size=64, n_positions=16, dim=16, n_layers=1, n_heads=2))
    with pytest.raises(KeyError, match="missing"):
        from_torch_state_dict(
            ours, {}, {"tok_emb.weight": ("transformer.wte.weight", None)}
        )
    # non-strict skips
    from_torch_state_dict(
        ours, {}, {"tok_emb.weight": ("transformer.wte.weight", None)}, strict=False
    )


def test_round_trip_export_import():
    """to_torch_state_dict is the exact inverse of from_torch_state_dict:
    exporting our GPT-2 weights to HF naming and re-importing them into a
    fresh differently-seeded model reproduces the original bit-for-bit."""
    from torchdistx_tpu.interop.torch_interop import (
        from_torch_state_dict,
        gpt2_key_map,
        to_torch_state_dict,
    )
    from torchdistx_tpu.models import GPT2

    tdx.manual_seed(0)
    src = GPT2.from_name("tiny")
    kmap = gpt2_key_map(src.cfg.n_layers)
    exported = to_torch_state_dict(src, kmap)
    assert "transformer.wte.weight" in exported
    # HF layout check: our (out, in) qkv exports as Conv1D's (in, out)
    ours = dict(src.named_parameters())["blocks.0.attn_qkv.weight"]
    assert exported["transformer.h.0.attn.c_attn.weight"].shape == ours.shape[::-1]

    tdx.manual_seed(99)
    dst = GPT2.from_name("tiny")
    from_torch_state_dict(dst, exported, kmap)
    for (k, a), (_, b) in zip(src.named_parameters(), dst.named_parameters()):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=k)


def test_mixtral_matches_hf_forward():
    hf_cfg = transformers.MixtralConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=32,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    hf = transformers.MixtralForCausalLM(hf_cfg).eval()

    from torchdistx_tpu.interop.torch_interop import mixtral_key_map
    from torchdistx_tpu.models import Mixtral
    from torchdistx_tpu.models.mixtral import MixtralConfig

    ours = Mixtral(
        MixtralConfig(
            vocab_size=128,
            dim=32,
            n_layers=2,
            n_heads=4,
            n_kv_heads=2,
            ffn_dim=64,
            n_experts=4,
            top_k=2,
            max_seq_len=32,
            dtype=jnp.float32,
            norm_eps=1e-5,  # HF MixtralConfig rms_norm_eps default
        )
    )
    from_torch_state_dict(ours, hf.state_dict(), mixtral_key_map(2, 4))

    tokens = np.random.RandomState(2).randint(0, 128, (2, 16))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(tokens)).logits.numpy()
    our_logits = np.asarray(ours(jnp.asarray(tokens)))
    np.testing.assert_allclose(our_logits, hf_logits, rtol=1e-3, atol=1e-3)


def test_mixtral_round_trip_export_import():
    from torchdistx_tpu.interop.torch_interop import (
        mixtral_key_map,
        to_torch_state_dict,
    )
    from torchdistx_tpu.models import Mixtral

    tdx.manual_seed(3)
    src = Mixtral.from_name("tiny")
    kmap = mixtral_key_map(src.cfg.n_layers, src.cfg.n_experts)
    exported = to_torch_state_dict(src, kmap)
    # stacked (E, D, F) exports as per-expert HF (F, D) Linears
    w = dict(src.named_parameters())["blocks.0.mlp.w_gate"]
    assert (
        exported["model.layers.0.block_sparse_moe.experts.0.w1.weight"].shape
        == w.shape[1:][::-1]
    )

    tdx.manual_seed(77)
    dst = Mixtral.from_name("tiny")
    from_torch_state_dict(dst, exported, kmap)
    for (k, a), (_, b) in zip(src.named_parameters(), dst.named_parameters()):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=k)


def test_partial_stacked_group_raises_even_nonstrict():
    from torchdistx_tpu.interop.torch_interop import (
        mixtral_key_map,
        to_torch_state_dict,
    )
    from torchdistx_tpu.models import Mixtral

    tdx.manual_seed(5)
    m = Mixtral.from_name("tiny")
    kmap = mixtral_key_map(m.cfg.n_layers, m.cfg.n_experts)
    sd = to_torch_state_dict(m, kmap)
    # drop ONE expert of one stacked group: a broken checkpoint, not an
    # intentional omission -> must raise even with strict=False
    del sd["model.layers.0.block_sparse_moe.experts.1.w1.weight"]
    with pytest.raises(KeyError, match="partial group"):
        from_torch_state_dict(m, sd, kmap, strict=False)


def test_mistral_matches_hf_forward():
    # Mistral = Llama keys + GQA + sliding window; llama_key_map must
    # load an HF MistralForCausalLM and match its (windowed) logits
    hf_cfg = transformers.MistralConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        sliding_window=8,
        rms_norm_eps=1e-6,
        attention_dropout=0.0,
        tie_word_embeddings=False,
    )
    hf = transformers.MistralForCausalLM(hf_cfg).eval()

    ours = Llama(
        LlamaConfig(
            vocab_size=128,
            dim=32,
            n_layers=2,
            n_heads=4,
            n_kv_heads=2,
            ffn_dim=64,
            max_seq_len=64,
            dtype=jnp.float32,
            norm_eps=1e-6,
            sliding_window=8,
            use_flash=False,
        )
    )
    from_torch_state_dict(ours, hf.state_dict(), llama_key_map(2))

    tokens = np.random.RandomState(2).randint(0, 128, (2, 24))
    with torch.no_grad():
        hf_logits = hf(torch.tensor(tokens)).logits.numpy()
    our_logits = np.asarray(ours(jnp.asarray(tokens)))
    np.testing.assert_allclose(our_logits, hf_logits, rtol=1e-3, atol=1e-3)


def test_vit_matches_hf_forward():
    hf_cfg = transformers.ViTConfig(
        image_size=32, patch_size=8, num_channels=3, hidden_size=32,
        num_hidden_layers=2, num_attention_heads=4, intermediate_size=64,
        num_labels=10,
    )
    hf = transformers.ViTForImageClassification(hf_cfg).eval()

    from torchdistx_tpu.models import ViT, ViTConfig
    from torchdistx_tpu.interop import vit_key_map

    ours = ViT(ViTConfig(
        image_size=32, patch_size=8, num_classes=10, dim=32, n_layers=2,
        n_heads=4, mlp_dim=64, norm_eps=hf_cfg.layer_norm_eps,
    ))
    from_torch_state_dict(ours, hf.state_dict(), vit_key_map(2))

    imgs = np.random.RandomState(0).randn(2, 3, 32, 32).astype(np.float32)
    with torch.no_grad():
        hf_logits = hf(torch.tensor(imgs)).logits.numpy()
    our_logits = np.asarray(ours(jnp.asarray(imgs)))
    np.testing.assert_allclose(our_logits, hf_logits, rtol=2e-4, atol=2e-4)
