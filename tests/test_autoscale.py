"""Closed-loop autoscaler + deterministic traffic generator (ISSUE 16).

The pinned invariants, on the 8-device CPU mesh:

- **Asymmetric hysteresis never flaps**: under an oscillating
  warn/ok signal the controller holds forever — capacity moves only on
  SUSTAINED runs, scale-up after ``up_sustain`` ticks, scale-down only
  after the (longer) ``down_sustain``, and each executed action arms
  its own cooldown that visibly suppresses the next eligible action.
- **Decisions replay bit-identically**: the same recorded signal
  vector through a fresh fleet + controller reproduces the decision
  stream — ticks, actions, victims, reasons, counters — exactly.
- **Scale-ups are compile-free after the oracle**: engines built on
  the same model share compiled programs, so a warmed ``fleet.add``
  during a scale-up tick leaves the recompile counters flat
  (``programs_before == programs_after`` in the add event).
- **The fleet tick is threaded into every decision event**, strictly
  increasing, with the FULL signal vector attached — the schema
  ``check_obs_artifacts.py --autoscale`` gates on.
- **The workload generator is a pure function of its spec**: every
  sample comes from ``utils/rng.py``'s counter stream under
  ``rng_scope(seed)`` — double-generate is bit-identical (prompts
  included), the ambient stream is untouched, and the module carries
  zero TDX102 stateful-RNG findings and zero suppressions (repo scan).
"""

import dataclasses

from pathlib import Path

import numpy as np
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu.models import Llama
from torchdistx_tpu.obs.recompile import RecompileWatcher
from torchdistx_tpu.obs.trace import _FLEET_TRACK_PID, fleet_scale_trace_events
from torchdistx_tpu.serve import (
    AutoscaleController,
    ScalingPolicy,
    ServeEngine,
    ServeFleet,
    generate,
    replay_signal,
    scenario,
    workload_counters,
)
from torchdistx_tpu.serve.workload import SCENARIOS, ScenarioSpec
from torchdistx_tpu.utils.rng import next_host_uniform, rng_scope

REPO_ROOT = Path(__file__).resolve().parent.parent

WARN = {"state": "warn"}
OK = {"state": "ok"}

# fast asymmetric policy used throughout: up after 2 burn ticks, down
# only after 4 idle ones, distinct cooldowns
POLICY = ScalingPolicy(
    min_replicas=1,
    max_replicas=3,
    up_sustain=2,
    down_sustain=4,
    up_cooldown=2,
    down_cooldown=4,
)


@pytest.fixture(scope="module")
def model():
    tdx.manual_seed(7)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


def _engine(model):
    return ServeEngine(
        model,
        num_slots=2,
        max_len=32,
        prefill_buckets=(16,),
        decode_chunk=4,
    )


def _controller(model, vectors, *, n_start=1, policy=POLICY):
    fleet = ServeFleet([_engine(model) for _ in range(n_start)])
    ctrl = AutoscaleController(
        fleet,
        policy,
        engine_factory=lambda role: _engine(model),
        signal_fn=replay_signal(vectors),
        flight=False,
    )
    return fleet, ctrl


def _run(ctrl, n_ticks):
    """The bench replay-loop shape: step the fleet, then evaluate."""
    out = []
    for _ in range(n_ticks):
        ctrl.fleet.step()
        out.append(ctrl.tick())
    return out


# ---------------------------------------------------------------------------
# policy surface


class TestScalingPolicy:
    def test_from_json_accepts_name_dict_and_string(self):
        assert ScalingPolicy.from_json("default") == ScalingPolicy.default()
        d = POLICY.to_json()
        assert ScalingPolicy.from_json(d) == POLICY
        import json as _json

        assert ScalingPolicy.from_json(_json.dumps(d)) == POLICY
        # round-trip through to_json is lossless
        assert ScalingPolicy.from_json(POLICY.to_json()) == POLICY

    def test_unknown_field_rejected(self):
        with pytest.raises(ValueError, match="unknown ScalingPolicy"):
            ScalingPolicy.from_json({"max_replica": 5})

    def test_validation(self):
        with pytest.raises(ValueError):
            ScalingPolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError):
            ScalingPolicy(windows=(8, 2))
        with pytest.raises(ValueError):
            ScalingPolicy(up_sustain=0)
        with pytest.raises(ValueError):
            ScalingPolicy(down_cooldown=-1)


# ---------------------------------------------------------------------------
# tentpole: asymmetric hysteresis (satellite c)


class TestHysteresis:
    def test_oscillating_signal_never_flaps(self, model):
        vec = [WARN, OK] * 10
        fleet, ctrl = _controller(model, vec, n_start=2)
        decisions = _run(ctrl, len(vec))
        assert [d["action"] for d in decisions] == ["hold"] * len(vec)
        assert ctrl.counters["autoscale_scale_ups"] == 0
        assert ctrl.counters["autoscale_scale_downs"] == 0
        assert len(fleet.replicas) == 2
        # each direction's run resets on every flip, so neither sustain
        # threshold is ever reached
        assert all(
            d["sustain"]["up"] <= 1 and d["sustain"]["down"] <= 1
            for d in decisions
        )

    def test_up_fires_fast_down_fires_slow(self, model):
        # 2 burn ticks add a replica; shedding it takes 4 idle ticks
        # (8 idle ticks total: the second shed matures at tick 9 but
        # lands in the down-cooldown window, so exactly one cycle fits)
        vec = [WARN] * 2 + [OK] * 8
        fleet, ctrl = _controller(model, vec, n_start=2)
        decisions = _run(ctrl, len(vec))
        actions = [d["action"] for d in decisions]
        assert actions[1] == "scale_up" and decisions[1]["mode"] == "add"
        assert actions[5] == "scale_down"
        assert decisions[5]["mode"] == "remove"
        assert {a for i, a in enumerate(actions) if i not in (1, 5)} == {
            "hold"
        }
        assert len(fleet.replicas) == 2  # back where it started, no flap
        assert ctrl.counters["autoscale_scale_ups"] == 1
        assert ctrl.counters["autoscale_scale_downs"] == 1

    def test_cooldown_suppresses_and_is_counted(self, model):
        # scale_up at tick 2; the next eligible up at tick 4 lands in
        # the cooldown window and is visibly suppressed, firing at 5
        vec = [WARN] * 5
        fleet, ctrl = _controller(model, vec, n_start=1)
        decisions = _run(ctrl, len(vec))
        assert [d["action"] for d in decisions] == [
            "hold",
            "scale_up",
            "hold",
            "hold",
            "scale_up",
        ]
        assert "cooldown" in decisions[3]["reason"]
        assert ctrl.counters["autoscale_cooldown_holds"] == 1
        assert len(fleet.replicas) == 3

    def test_bounds_are_hard(self, model):
        # at max_replicas sustained burn never adds; at min_replicas
        # sustained headroom never removes
        fleet, ctrl = _controller(model, [WARN] * 8, n_start=3)
        _run(ctrl, 8)
        assert ctrl.counters["autoscale_scale_ups"] == 0
        assert len(fleet.replicas) == 3
        fleet2, ctrl2 = _controller(model, [OK] * 12, n_start=1)
        _run(ctrl2, 12)
        assert ctrl2.counters["autoscale_scale_downs"] == 0
        assert len(fleet2.replicas) == 1

    def test_event_schema_and_tick_threading(self, model):
        vec = [WARN] * 2 + [OK] * 6
        fleet, ctrl = _controller(model, vec, n_start=1)
        _run(ctrl, len(vec))
        scale = [d for name, _ts, d in fleet.events if name == "scale"]
        assert len(scale) == len(vec)
        # the fleet's monotonic tick counter is threaded into every
        # decision, strictly increasing (tick N is taken after step N)
        assert [d["tick"] for d in scale] == list(range(1, len(vec) + 1))
        required = {
            "tick",
            "action",
            "mode",
            "replica",
            "role",
            "reason",
            "replicas_before",
            "replicas_after",
            "sustain",
            "cooldown_remaining",
            "policy",
            "signal",
        }
        for d in scale:
            assert required <= set(d)
            sig = d["signal"]
            assert sig["state"] in ("ok", "warn", "page")
            assert isinstance(sig["windows"], list)
            assert sig["replicas"]  # full per-replica vector attached
            assert d["policy"] == POLICY.to_json()


# ---------------------------------------------------------------------------
# tentpole: decisions pinned deterministic under a replayed vector


class TestReplayDeterminism:
    def test_decision_stream_bit_identical(self, model):
        vec = ([WARN] * 3 + [OK] * 7) * 2
        def run_once():
            fleet, ctrl = _controller(model, vec, n_start=1)
            stream = [
                (
                    d["tick"],
                    d["action"],
                    d["mode"],
                    d["replica"],
                    d["replicas_after"],
                    d["reason"],
                )
                for d in _run(ctrl, len(vec))
            ]
            return stream, dict(ctrl.counters), ctrl.metrics_json()

        s1, c1, m1 = run_once()
        s2, c2, m2 = run_once()
        assert s1 == s2
        assert c1 == c2
        assert m1 == m2
        # and the replay actually exercised a full scale cycle
        assert c1["autoscale_scale_ups"] >= 1
        assert c1["autoscale_scale_downs"] >= 1

    def test_bad_state_raises(self, model):
        fleet, ctrl = _controller(model, [{"state": "panic"}])
        with pytest.raises(ValueError, match="panic"):
            ctrl.tick()


# ---------------------------------------------------------------------------
# satellite a: warmed adds keep recompile counters flat


class TestWarmScaleUp:
    def test_scale_up_is_compile_free_after_oracle(self):
        # fresh model => fresh jit cache, so the oracle/measure split is
        # real even when other tests compiled the module-scoped model
        tdx.manual_seed(8)
        local = Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)
        watcher = RecompileWatcher()
        try:
            oracle = _engine(local)
            prompts = [
                (np.arange(n, dtype=np.int32) % 61) for n in (10, 12, 16)
            ]
            oracle.run(
                [{"prompt": p, "max_new_tokens": 8} for p in prompts]
            )
            if watcher.available:
                assert watcher.total > 0  # the oracle really compiled
            watcher.reset()
            fleet = ServeFleet([_engine(local)])
            ctrl = AutoscaleController(
                fleet,
                ScalingPolicy(up_sustain=1, max_replicas=2),
                engine_factory=lambda role: _engine(local),
                signal_fn=replay_signal([WARN]),
                flight=False,
            )
            fleet.step()
            d = ctrl.tick()
            assert d["action"] == "scale_up" and d["mode"] == "add"
            adds = [e for name, _ts, e in fleet.events if name == "add"]
            assert len(adds) == 1
            warm = adds[0]["warm"]
            # the warm-up drove real requests but compiled nothing new
            assert warm["requests"] > 0
            assert warm["programs_before"] == warm["programs_after"]
            assert watcher.total == 0
        finally:
            watcher.uninstall()


# ---------------------------------------------------------------------------
# satellite b: scale events on the Perfetto fleet track


class TestScaleTraceEvents:
    def test_scale_decisions_render_as_fleet_instants(self):
        events = [
            (
                "scale",
                12.5,
                {
                    "tick": 3,
                    "action": "scale_up",
                    "mode": "add",
                    "replica": 2,
                    "reason": "sustained burn",
                    "signal": {"state": "warn"},
                },
            )
        ]
        meta, inst = fleet_scale_trace_events(events)
        assert meta["ph"] == "M" and meta["args"]["name"] == "fleet"
        assert inst["ph"] == "i" and inst["pid"] == _FLEET_TRACK_PID
        assert inst["name"] == "scale:scale_up"
        assert inst["ts"] == 12.5
        assert inst["args"]["state"] == "warn"
        assert inst["args"]["tick"] == 3

    def test_no_control_events_no_track(self):
        assert fleet_scale_trace_events([("route", 0.0, {})]) == []


# ---------------------------------------------------------------------------
# tentpole: the deterministic open-loop generator


class TestWorkload:
    def test_double_generate_bit_identical(self):
        spec = scenario("bursty")
        a, b = generate(spec), generate(spec)
        assert len(a) == len(b) > 0
        for ra, rb in zip(a, b):
            assert (
                ra.index,
                ra.arrival_tick,
                ra.group,
                ra.max_new_tokens,
                ra.deadline_ticks,
            ) == (
                rb.index,
                rb.arrival_tick,
                rb.group,
                rb.max_new_tokens,
                rb.deadline_ticks,
            )
            assert np.array_equal(ra.prompt, rb.prompt)
        assert workload_counters(a) == workload_counters(b)

    def test_generate_leaves_ambient_stream_untouched(self):
        spec = scenario("poisson")
        with rng_scope(123):
            u1 = next_host_uniform()
            generate(spec)  # scoped to spec.seed internally
            u2 = next_host_uniform()
        with rng_scope(123):
            v1 = next_host_uniform()
            v2 = next_host_uniform()
        assert (u1, u2) == (v1, v2)

    def test_rate_envelope_closed_form(self):
        fc = SCENARIOS["flash_crowd"]
        inside = range(fc.flash_tick, fc.flash_tick + fc.flash_len)
        for t in range(fc.horizon_ticks):
            want = fc.base_rate * (fc.flash_mult if t in inside else 1.0)
            assert fc.rate_at(t) == pytest.approx(want)
        b = SCENARIOS["bursty"]
        assert b.rate_at(0) == pytest.approx(b.base_rate * b.burst_mult)
        assert b.rate_at(b.burst_len) == pytest.approx(b.base_rate)
        # the diurnal trough never goes negative
        d = SCENARIOS["diurnal"]
        assert min(d.rate_at(t) for t in range(d.horizon_ticks)) >= 0.0

    def test_counters_match_recount(self):
        work = generate(scenario("flash_crowd"))
        c = workload_counters(work)
        assert c["workload_requests"] == len(work)
        assert c["workload_prompt_tokens"] == sum(
            r.prompt.size for r in work
        )
        assert c["workload_output_token_budget"] == sum(
            r.max_new_tokens for r in work
        )
        assert c["workload_last_arrival_tick"] == max(
            r.arrival_tick for r in work
        )
        # arrivals are ordered and respect the horizon
        ticks = [r.arrival_tick for r in work]
        assert ticks == sorted(ticks)
        assert ticks[-1] < scenario("flash_crowd").horizon_ticks

    def test_catalog_and_overrides(self):
        assert scenario("poisson") is SCENARIOS["poisson"]
        alt = scenario("poisson", seed=99)
        assert alt.seed == 99 and alt.name == "poisson"
        assert dataclasses.replace(alt, seed=11) == SCENARIOS["poisson"]
        # a different seed reshuffles the arrivals
        assert [r.arrival_tick for r in generate(alt)] != [
            r.arrival_tick for r in generate(scenario("poisson"))
        ] or not np.array_equal(
            generate(alt)[0].prompt, generate(scenario("poisson"))[0].prompt
        )
        with pytest.raises(ValueError, match="unknown scenario"):
            scenario("tsunami")
        with pytest.raises(ValueError):
            ScenarioSpec(name="bad", horizon_ticks=0)

    def test_submit_kwargs_never_alias_the_spec(self):
        r = generate(scenario("poisson"))[0]
        kw = r.submit_kwargs()
        assert kw["seed"] == r.index and kw["temperature"] == 0.0
        kw["prompt"][0] = -1
        assert r.prompt[0] != -1


# ---------------------------------------------------------------------------
# satellite f: the generator is stateful-RNG-lint clean, no suppressions


class TestLintClean:
    def test_workload_module_zero_tdx102_zero_suppressions(self):
        from torchdistx_tpu.analysis import default_rules, run_lint

        report = run_lint(
            [
                "torchdistx_tpu/serve/workload.py",
                "torchdistx_tpu/serve/autoscale.py",
            ],
            default_rules(),
            root=str(REPO_ROOT),
        )
        assert report["files_scanned"] == 2
        assert [
            f for f in report["findings"] if f["rule"] == "TDX102"
        ] == []
        assert report["findings"] == []
        assert report["suppressions"] == []
