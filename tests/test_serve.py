"""Continuous-batching serving engine (torchdistx_tpu.serve).

The load-bearing invariants, pinned on the 8-device CPU mesh:

- **Exactness**: a greedy request served through the slot cache is
  bit-identical to ``generation.generate`` on that prompt alone — padding,
  slot reuse, and batch-mates change nothing.
- **Fused decode exactness**: a ``decode_chunk=K`` engine (K decode steps
  per dispatch in one on-device scan, one host sync per K tokens) emits
  BIT-identical token streams to the K=1 engine, greedy and sampled,
  full and partial slot occupancy — and a slot finishing at in-chunk
  step ``j`` contributes nothing after ``j``: its tokens stop, its KV
  rows freeze, and ``masked_slot_steps`` accounts exactly the
  ``K - 1 - j`` wasted slot-steps.
- **Dispatch discipline**: a full mixed-length continuous-batching run —
  including a late request admitted into a freed (dirty) slot — compiles
  exactly two programs (one prefill bucket + one decode scan per
  ``decode_chunk`` value).
- **Paged prefix-cache exactness**: a ``page_size=N`` engine — shared
  prefixes served from cached pages, suffix-only prefill, page-table
  decode — emits BIT-identical token streams to the contiguous
  (cache-off) engine across K x occupancy x shared/disjoint prefix
  mixes, cold AND warm (tests/test_prefix_cache.py covers the allocator
  and index units).
- **Deadlines**: expiry returns a partial result flagged ``truncated``.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu.generation import generate
from torchdistx_tpu.models import GPT2, Llama
from torchdistx_tpu.serve import Request, Scheduler, ServeEngine, SlotKVCache
from torchdistx_tpu.serve.metrics import Histogram, ServeMetrics


def _llama():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


def _gpt2():
    tdx.manual_seed(11)
    return GPT2.from_name("tiny")


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 256, (n,)).astype(np.int32) for n in lengths]


class TestSlotDecodeParity:
    """forward_decode (per-row positions) row-for-row equals
    forward_cached (scalar position) — the primitive the engine's
    bit-identity rests on."""

    def test_slot_attention_matches_scalar_cached_attention(self):
        from torchdistx_tpu.ops.attention import (
            cached_attention,
            slot_cached_attention,
        )

        rs = np.random.RandomState(3)
        b, hq, hkv, d, max_seq = 3, 4, 2, 8, 16
        q = jnp.asarray(rs.randn(b, 1, hq, d), jnp.float32)
        k = jnp.asarray(rs.randn(b, 1, hkv, d), jnp.float32)
        v = jnp.asarray(rs.randn(b, 1, hkv, d), jnp.float32)
        cache = (
            jnp.asarray(rs.randn(b, max_seq, hkv, d), jnp.float32),
            jnp.asarray(rs.randn(b, max_seq, hkv, d), jnp.float32),
        )
        positions = np.array([2, 9, 5], np.int32)
        out, (ck, cv) = slot_cached_attention(
            q, k, v, cache, jnp.asarray(positions)
        )
        for row, p in enumerate(positions):
            r = slice(row, row + 1)
            ref, (rk, rv) = cached_attention(
                q[r], k[r], v[r],
                (cache[0][r], cache[1][r]), int(p), use_flash=False,
            )
            np.testing.assert_array_equal(np.asarray(out[r]), np.asarray(ref))
            np.testing.assert_array_equal(np.asarray(ck[r]), np.asarray(rk))
            np.testing.assert_array_equal(np.asarray(cv[r]), np.asarray(rv))

    def test_model_forward_decode_matches_forward_cached(self):
        for model in (_llama(), _gpt2()):
            rs = np.random.RandomState(4)
            toks = jnp.asarray(rs.randint(0, 256, (3, 1)), jnp.int32)
            positions = np.array([1, 7, 4], np.int32)
            caches = [model.init_cache(1, 16) for _ in range(3)]
            # place a little real content at each row's depth
            seeded = []
            for row, p in enumerate(positions):
                pre = jnp.asarray(
                    rs.randint(0, 256, (1, int(p))), jnp.int32
                )
                _, c = model.forward_cached(pre, caches[row], 0)
                seeded.append(c)
            big = [
                (
                    jnp.concatenate([c[i][0] for c in seeded]),
                    jnp.concatenate([c[i][1] for c in seeded]),
                )
                for i in range(len(seeded[0]))
            ]
            logits, _ = model.forward_decode(
                toks, big, jnp.asarray(positions)
            )
            for row, p in enumerate(positions):
                r = slice(row, row + 1)
                ref, _ = model.forward_cached(toks[r], seeded[row], int(p))
                np.testing.assert_array_equal(
                    np.asarray(logits[r]), np.asarray(ref)
                )


class TestServeExactness:
    def test_greedy_bit_identical_to_sequential_generate(self):
        model = _llama()
        engine = ServeEngine(
            model, num_slots=3, max_len=64, prefill_buckets=(16,)
        )
        prompts = _prompts(0, (6, 11, 9, 4, 13))
        results = engine.run(
            [{"prompt": p, "max_new_tokens": 8} for p in prompts]
        )
        for p, r in zip(prompts, results):
            assert r.finish_reason == "length" and not r.truncated
            ref = np.asarray(generate(model, jnp.asarray(p[None]), 8))[0]
            np.testing.assert_array_equal(
                np.concatenate([p, r.tokens]), ref
            )

    def test_greedy_row_unaffected_by_sampling_batchmate(self):
        model = _gpt2()
        prompts = _prompts(1, (5, 7))
        engine = ServeEngine(model, num_slots=2, max_len=32)
        greedy = engine.submit(prompts[0], max_new_tokens=6)
        engine.submit(
            prompts[1], max_new_tokens=6, temperature=1.0, seed=3
        )
        while engine.step():
            pass
        ref = np.asarray(generate(model, jnp.asarray(prompts[0][None]), 6))[0]
        np.testing.assert_array_equal(
            np.concatenate([prompts[0], greedy.result().tokens]), ref
        )

    def test_sampling_reproducible_per_seed(self):
        model = _gpt2()
        prompt = _prompts(2, (6,))[0]
        engine = ServeEngine(model, num_slots=2, max_len=32, top_k=50)

        def sample(seed):
            h = engine.submit(
                prompt, max_new_tokens=6, temperature=0.8, seed=seed
            )
            while not h.done():
                engine.step()
            return h.result().tokens

        a, b, c = sample(7), sample(7), sample(8)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_eos_stops_with_stop_reason(self):
        model = _llama()
        prompt = _prompts(3, (5,))[0]
        first = np.asarray(generate(model, jnp.asarray(prompt[None]), 1))[
            0, -1
        ]
        engine = ServeEngine(
            model, num_slots=1, max_len=64, eos_token=int(first)
        )
        r = engine.run([{"prompt": prompt, "max_new_tokens": 8}])[0]
        assert r.finish_reason == "stop" and not r.truncated
        np.testing.assert_array_equal(r.tokens, [int(first)])


class TestContinuousBatching:
    def test_late_admit_into_freed_slot_no_recompile(self):
        """Mixed lengths, staggered finishes, a late submit landing in a
        freed (dirty) slot — and the jit cache holds exactly TWO programs
        throughout (one prefill bucket, one decode step)."""
        model = _llama()
        engine = ServeEngine(
            model, num_slots=2, max_len=64, prefill_buckets=(16,)
        )
        prompts = _prompts(5, (4, 9, 7))
        h0 = engine.submit(prompts[0], max_new_tokens=3)
        h1 = engine.submit(prompts[1], max_new_tokens=12)
        while not h0.done():
            engine.step()
        assert not h1.done()  # slot 1 still decoding
        warm = engine.num_compiled_programs()
        if warm is None:
            pytest.skip("jit cache introspection unavailable on this jax")
        assert warm == 2  # one prefill bucket + one decode step
        # late arrival: must reuse h0's freed slot while h1 keeps going
        h2 = engine.submit(prompts[2], max_new_tokens=6)
        while engine.step():
            pass
        assert engine.num_compiled_programs() == warm == 2
        for p, h, n in ((prompts[1], h1, 12), (prompts[2], h2, 6)):
            ref = np.asarray(generate(model, jnp.asarray(p[None]), n))[0]
            np.testing.assert_array_equal(
                np.concatenate([p, h.result().tokens]), ref
            )
        snap = engine.metrics.snapshot()
        assert snap["requests_completed"] == 3
        assert snap["tokens_generated"] == 3 + 12 + 6

    def test_queue_deeper_than_slots_drains_fcfs(self):
        model = _llama()
        engine = ServeEngine(
            model, num_slots=2, max_len=64, prefill_buckets=(16,)
        )
        prompts = _prompts(6, (3, 5, 7, 4, 6, 8))
        results = engine.run(
            [{"prompt": p, "max_new_tokens": 4} for p in prompts]
        )
        assert [r.rid for r in results] == sorted(r.rid for r in results)
        for p, r in zip(prompts, results):
            ref = np.asarray(generate(model, jnp.asarray(p[None]), 4))[0]
            np.testing.assert_array_equal(
                np.concatenate([p, r.tokens]), ref
            )
        assert engine.num_compiled_programs() in (2, None)

    def test_max_tokens_budget_defers_admission(self):
        model = _llama()
        engine = ServeEngine(
            model,
            num_slots=2,
            max_len=64,
            prefill_buckets=(16,),
            max_tokens_in_flight=20,
        )
        prompts = _prompts(7, (6, 6))
        engine.submit(prompts[0], max_new_tokens=8)  # cost 14
        h1 = engine.submit(prompts[1], max_new_tokens=8)  # would be 28 > 20
        engine.step()
        assert engine.scheduler.queue_depth == 1  # deferred, slot free
        while engine.step():
            pass
        assert h1.done()  # admitted after the first retired
        ref = np.asarray(generate(model, jnp.asarray(prompts[1][None]), 8))[0]
        np.testing.assert_array_equal(
            np.concatenate([prompts[1], h1.result().tokens]), ref
        )


class TestDeadlines:
    def test_running_deadline_returns_truncated_partial(self):
        model = _llama()
        engine = ServeEngine(
            model, num_slots=1, max_len=64, prefill_buckets=(16,)
        )
        prompt = _prompts(8, (5,))[0]
        h = engine.submit(prompt, max_new_tokens=40, deadline_s=0.2)
        engine.step()  # prefill + first decode: some tokens exist
        engine.step()
        time.sleep(0.25)
        engine.step()  # past deadline now
        r = h.result()
        assert r.finish_reason == "deadline" and r.truncated
        assert 0 < len(r.tokens) < 40
        # the partial prefix is still exact
        ref = np.asarray(
            generate(model, jnp.asarray(prompt[None]), len(r.tokens))
        )[0]
        np.testing.assert_array_equal(np.concatenate([prompt, r.tokens]), ref)
        assert engine.metrics.snapshot()["requests_truncated"] == 1

    def test_queued_deadline_expires_with_no_tokens(self):
        model = _llama()
        engine = ServeEngine(
            model, num_slots=1, max_len=64, prefill_buckets=(16,)
        )
        prompts = _prompts(9, (5, 6))
        engine.submit(prompts[0], max_new_tokens=30)
        h = engine.submit(prompts[1], max_new_tokens=4, deadline_s=0.0)
        engine.step()
        r = h.result()
        assert r.truncated and r.finish_reason == "deadline"
        assert r.tokens.size == 0


def _run_chunked(model, k_chunk, requests, *, num_slots=3, eos_token=None,
                 max_len=64, buckets=(16,), **engine_kw):
    engine = ServeEngine(
        model, num_slots=num_slots, max_len=max_len,
        prefill_buckets=buckets, eos_token=eos_token,
        decode_chunk=k_chunk, **engine_kw,
    )
    return engine, engine.run([dict(r) for r in requests])


class TestFusedDecode:
    """decode_chunk=K: K tokens per dispatch and per host sync, streams
    bit-identical to the K=1 engine.  The fast tests cover K=4 at both
    occupancies, greedy and sampled; the slow sweep runs the full
    K x occupancy x sampling grid (same code path, nightly)."""

    def _requests(self, lengths, temperature, n_new=8):
        return [
            {"prompt": p, "max_new_tokens": n_new,
             "temperature": temperature, "seed": i}
            for i, p in enumerate(_prompts(21, lengths))
        ]

    def _assert_identical(self, k_chunk, lengths, temperature):
        model = _llama()
        reqs = self._requests(lengths, temperature)
        _, base = _run_chunked(model, 1, reqs)
        engine, fused = _run_chunked(model, k_chunk, reqs)
        for a, b in zip(base, fused):
            assert a.finish_reason == b.finish_reason
            np.testing.assert_array_equal(a.tokens, b.tokens)
        return engine

    def test_k4_greedy_full_and_partial_occupancy(self):
        # full: 5 requests through 3 slots (churn + late admission at
        # chunk boundaries); partial: 1 request, 2 slots idle
        engine = self._assert_identical(4, (6, 11, 9, 4, 13), 0.0)
        snap = engine.metrics.snapshot()
        assert snap["decode_steps"] == 4 * snap["decode_dispatches"]
        # one sync per prefill + one per K-step dispatch, NOT per token
        assert snap["host_syncs"] == (
            snap["prefill_calls"] + snap["decode_dispatches"]
        )
        assert snap["syncs_per_token"] < 0.5  # vs ~1.1 at K=1
        self._assert_identical(4, (7,), 0.0)

    def test_k4_sampled_full_and_partial_occupancy(self):
        self._assert_identical(4, (6, 11, 9, 4, 13), 0.9)
        self._assert_identical(4, (7,), 0.9)

    def test_fused_decode_through_pallas_kernel_path(self):
        """use_flash=True routes the in-scan attention through the
        interpret-mode pallas decode kernel on CPU: fused-vs-sequential
        stays BIT-identical because both engines share the kernel."""
        tdx.manual_seed(0)
        model = Llama.from_name(
            "tiny", n_kv_heads=2, max_seq_len=64, use_flash=True
        )
        reqs = self._requests((6, 9), 0.0, n_new=6)
        _, base = _run_chunked(model, 1, reqs, num_slots=2)
        _, fused = _run_chunked(model, 4, reqs, num_slots=2)
        for a, b in zip(base, fused):
            np.testing.assert_array_equal(a.tokens, b.tokens)

    def test_program_count_one_decode_per_k(self):
        model = _llama()
        engine, _ = _run_chunked(model, 4, self._requests((6, 9), 0.0))
        warm = engine.num_compiled_programs()
        if warm is None:
            pytest.skip("jit cache introspection unavailable on this jax")
        assert warm == 2  # one prefill bucket + ONE K=4 decode scan
        # more traffic never compiles more
        engine.run([dict(r) for r in self._requests((5, 12, 8), 0.0)])
        assert engine.num_compiled_programs() == 2

    @pytest.mark.slow
    @pytest.mark.parametrize("k_chunk", [1, 4, 8])
    @pytest.mark.parametrize("lengths", [(6, 11, 9, 4, 13), (7,)])
    @pytest.mark.parametrize("temperature", [0.0, 0.9])
    def test_full_grid_bit_identical(self, k_chunk, lengths, temperature):
        self._assert_identical(k_chunk, lengths, temperature)


class TestPagedPrefixSharing:
    """page_size=N engine vs the contiguous cache-off engine: BIT
    identical streams, cold and warm — shared prefixes, disjoint
    prompts, slot churn, greedy and sampled rows.  The fast tests cover
    K=4 at both occupancies plus a warm pass; the slow sweep runs the
    full K x occupancy x prefix-mix grid (same code path, nightly)."""

    # prefix mixes: lengths with None meaning "prepend the shared
    # 20-token system prefix" (page-aligned hits at page_size=8 come
    # from its first 16 tokens)
    SHARED = (("s", 5), ("s", 9), (None, 3), ("s", 12), (None, 7))
    DISJOINT = ((None, 6), (None, 11), (None, 9), (None, 4), (None, 13))

    def _requests(self, mix, temperature, n_new=8):
        rs = np.random.RandomState(17)
        shared = rs.randint(0, 256, (20,)).astype(np.int32)
        reqs = []
        for i, (pfx, n) in enumerate(mix):
            tail = rs.randint(0, 256, (n,)).astype(np.int32)
            prompt = np.concatenate([shared, tail]) if pfx else tail
            reqs.append(
                {"prompt": prompt, "max_new_tokens": n_new,
                 "temperature": temperature, "seed": i}
            )
        return reqs

    def _assert_paged_identical(self, k_chunk, mix, temperature,
                                num_slots=3):
        model = _llama()
        reqs = self._requests(mix, temperature)
        _, base = _run_chunked(
            model, k_chunk, reqs, num_slots=num_slots, buckets=(16, 32)
        )
        paged = ServeEngine(
            model, num_slots=num_slots, max_len=64,
            prefill_buckets=(16, 32), decode_chunk=k_chunk, page_size=8,
        )
        cold = paged.run([dict(r) for r in reqs])
        warm = paged.run([dict(r) for r in reqs])  # index now populated
        for a, b, c in zip(base, cold, warm):
            assert a.finish_reason == b.finish_reason == c.finish_reason
            np.testing.assert_array_equal(a.tokens, b.tokens)
            np.testing.assert_array_equal(a.tokens, c.tokens)
        return paged

    def test_k4_greedy_shared_prefix_cold_and_warm(self):
        engine = self._assert_paged_identical(4, self.SHARED, 0.0)
        snap = engine.metrics.snapshot()
        assert snap["prefix_hit_tokens"] > 0  # sharing actually happened
        # partial occupancy: one request, slots idle
        self._assert_paged_identical(4, ((None, 7),), 0.0)

    def test_k4_sampled_shared_prefix(self):
        self._assert_paged_identical(4, self.SHARED, 0.9)

    def test_k1_disjoint_prompts(self):
        engine = self._assert_paged_identical(1, self.DISJOINT, 0.0)
        # disjoint tails shorter than a page: no false hits on the cold
        # pass (the warm pass legitimately hits its own full prompts)
        assert engine.metrics.counters["requests_completed"] == 10

    def test_paged_through_pallas_kernel_path(self):
        """use_flash=True routes the paged decode through the
        interpret-mode paged kernel: paged-vs-slab streams stay
        BIT-identical because both layouts share the kernel math."""
        tdx.manual_seed(0)
        model = Llama.from_name(
            "tiny", n_kv_heads=2, max_seq_len=64, use_flash=True
        )
        reqs = self._requests(self.SHARED[:3], 0.0, n_new=6)
        _, base = _run_chunked(
            model, 4, reqs, num_slots=2, buckets=(16, 32)
        )
        paged = ServeEngine(
            model, num_slots=2, max_len=64, prefill_buckets=(16, 32),
            decode_chunk=4, page_size=16,
        )
        got = paged.run([dict(r) for r in reqs])
        for a, b in zip(base, got):
            np.testing.assert_array_equal(a.tokens, b.tokens)

    def test_program_count_stable_after_warmup(self):
        """Paged dispatch discipline: one cold + (if hits occur) one
        warm prefill per bucket used, one decode scan — and MORE traffic
        through the warm engine never compiles another program."""
        engine = self._assert_paged_identical(4, self.SHARED, 0.0)
        warm = engine.num_compiled_programs()
        if warm is None:
            pytest.skip("jit cache introspection unavailable on this jax")
        engine.run([dict(r) for r in self._requests(self.SHARED, 0.0)])
        assert engine.num_compiled_programs() == warm

    @pytest.mark.slow
    @pytest.mark.parametrize("k_chunk", [1, 4, 8])
    @pytest.mark.parametrize("mix", [SHARED, DISJOINT, ((None, 7),)])
    @pytest.mark.parametrize("temperature", [0.0, 0.9])
    def test_full_grid_bit_identical(self, k_chunk, mix, temperature):
        self._assert_paged_identical(k_chunk, mix, temperature)


class TestFinishMasking:
    """On-device finish mask: a slot finishing at in-chunk step j emits
    nothing after j, freezes its KV position, and the engine accounts
    exactly K - 1 - j masked slot-steps."""

    def _eos_case(self, temperature, seed=3):
        """Pick the 4th generated token as EOS: with the prefill token at
        index 0, it lands at in-chunk step j = 2 of the first chunk."""
        model = _llama()
        prompt = _prompts(31, (6,))[0]
        base_engine, base = _run_chunked(
            model, 1,
            [{"prompt": prompt, "max_new_tokens": 20,
              "temperature": temperature, "seed": seed}],
            num_slots=1, buckets=(8,),
        )
        stream = base[0].tokens
        idx = 3
        eos = int(stream[idx])
        assert eos not in stream[:idx].tolist()  # finishes exactly there
        return model, prompt, eos, stream[: idx + 1]

    @pytest.mark.parametrize("temperature", [0.0, 0.8])
    def test_eos_mid_chunk_masks_remaining_steps(self, temperature):
        k_chunk = 16
        model, prompt, eos, expect = self._eos_case(temperature)
        engine, results = _run_chunked(
            model, k_chunk,
            [{"prompt": prompt, "max_new_tokens": 20,
              "temperature": temperature, "seed": 3}],
            num_slots=1, eos_token=eos, buckets=(8,),
        )
        r = results[0]
        assert r.finish_reason == "stop"
        np.testing.assert_array_equal(r.tokens, expect)  # nothing after j
        # EOS emitted at in-chunk step j = 2 -> K - 1 - j wasted
        assert engine.metrics.counters["masked_slot_steps"] == k_chunk - 3
        # the slot's write position froze where the host stopped: 3
        # decode steps consumed (the prefill token rode the prefill
        # dispatch; the EOS token was sampled at step j=2), not K
        frozen = prompt.size + len(expect) - 1
        assert int(engine.cache.pos[0]) == frozen
        # and the device never advanced past it: the masked steps rewrite
        # the frozen row only, so every row past it stayed virgin zeros —
        # an unmasked scan would have written rows up to prompt + K
        k0 = np.asarray(engine.cache.kv[0][0])  # layer 0 K, slot 0 rows
        assert np.all(k0[0, frozen + 1:] == 0)

    def test_masked_steps_zero_when_chunk_fits(self):
        """Requests whose remaining budget is a multiple of K finish at
        the last chunk step: no waste."""
        model = _llama()
        engine, results = _run_chunked(
            model, 4,
            [{"prompt": _prompts(32, (6,))[0], "max_new_tokens": 9}],
            num_slots=1,
        )
        # 1 prefill token + 8 decode tokens = two full K=4 chunks
        assert results[0].finish_reason == "length"
        assert engine.metrics.counters["masked_slot_steps"] == 0
        assert engine.metrics.counters["decode_dispatches"] == 2


class TestPersistentDecode:
    """decode_mode="persistent": ONE while_loop dispatch runs to a
    slot-state fixpoint (or a full ring), the host drains the device
    ring — and the token streams are BIT-identical to the fused K-step
    reference across occupancy x greedy/sampled x shared-prefix/paged,
    because both programs run the same ``_make_decode_body``.  The fast
    tests cover both occupancies, sampling, paging, ring wraparound,
    and the budget-bound exit; the slow sweep runs the full grid."""

    def _requests(self, lengths, temperature, n_new=8):
        return [
            {"prompt": p, "max_new_tokens": n_new,
             "temperature": temperature, "seed": i}
            for i, p in enumerate(_prompts(21, lengths))
        ]

    def _assert_identical(self, lengths, temperature, *, ring=None,
                          page_size=None, n_new=8, **kw):
        model = _llama()
        reqs = self._requests(lengths, temperature, n_new=n_new)
        _, base = _run_chunked(model, 4, reqs)
        engine = ServeEngine(
            model, num_slots=3, max_len=64, prefill_buckets=(16,),
            decode_mode="persistent", ring_capacity=ring,
            page_size=page_size, **kw,
        )
        pers = engine.run([dict(r) for r in reqs])
        for a, b in zip(base, pers):
            assert a.finish_reason == b.finish_reason
            np.testing.assert_array_equal(a.tokens, b.tokens)
        return engine

    def test_greedy_full_and_partial_occupancy_syncs_collapse(self):
        engine = self._assert_identical((6, 11, 9, 4, 13), 0.0)
        snap = engine.metrics.snapshot()
        # THE tentpole invariant: host syncs are exactly the ring
        # drains — prefill defers its fetch, so syncs/token is ~1/wave,
        # not ~1/K (5 requests x 8 tokens through 2 drained waves here)
        assert snap["host_syncs"] == snap["ring_drains"]
        assert snap["loop_iterations"] == snap["decode_steps"]
        assert snap["syncs_per_token"] < 0.11  # vs 0.25 at K=4, 1.1 at K=1
        assert snap["ring_occupancy_hwm"] >= 7  # 7 decode tokens/request
        assert snap["ring_full_drains"] == 0  # default ring = max_len
        self._assert_identical((7,), 0.0)

    def test_sampled_full_and_partial_occupancy(self):
        self._assert_identical((6, 11, 9, 4, 13), 0.9)
        self._assert_identical((7,), 0.9)

    def test_paged_shared_prefix_streams_identical(self):
        rs = np.random.RandomState(17)
        shared = rs.randint(0, 256, (20,)).astype(np.int32)
        reqs = []
        for i, n in enumerate((5, 9, 12)):
            tail = rs.randint(0, 256, (n,)).astype(np.int32)
            reqs.append(
                {"prompt": np.concatenate([shared, tail]),
                 "max_new_tokens": 8, "temperature": 0.0, "seed": i}
            )
        model = _llama()
        _, base = _run_chunked(model, 4, reqs, buckets=(16, 32))
        paged = ServeEngine(
            model, num_slots=3, max_len=64, prefill_buckets=(16, 32),
            decode_mode="persistent", page_size=8,
        )
        cold = paged.run([dict(r) for r in reqs])
        warm = paged.run([dict(r) for r in reqs])  # index now populated
        for a, b, c in zip(base, cold, warm):
            assert a.finish_reason == b.finish_reason == c.finish_reason
            np.testing.assert_array_equal(a.tokens, b.tokens)
            np.testing.assert_array_equal(a.tokens, c.tokens)
        assert paged.metrics.counters["prefix_hit_tokens"] > 0

    def test_ring_wraparound_spans_drains(self):
        """A request outliving one ring continues bit-identically from
        its frozen carry at the next dispatch: the ring is reused
        (linear per dispatch), never circularly overwritten in-loop."""
        engine = self._assert_identical((6, 11, 9), 0.0, ring=3)
        snap = engine.metrics.snapshot()
        assert snap["ring_capacity"] == 3
        assert snap["ring_occupancy_hwm"] == 3  # every ring filled
        assert snap["ring_drains"] >= 3  # 7 decode tokens over 3-rings
        assert snap["ring_full_drains"] >= 2
        assert snap["host_syncs"] == snap["ring_drains"]

    def test_budget_bound_exit_resumes(self):
        """Unit view of one budget-bound exit: the loop stops at the
        ring bound with the request unfinished; the host holds exactly
        first-token + ring tokens and the next step resumes."""
        model = _llama()
        engine = ServeEngine(
            model, num_slots=1, max_len=64, prefill_buckets=(16,),
            decode_mode="persistent", ring_capacity=4,
        )
        h = engine.submit(_prompts(21, (6,))[0], max_new_tokens=12)
        engine.step()
        assert not h.done()  # budget-bound exit, not a finish
        assert len(h._request.generated) == 1 + 4  # prefill + one ring
        assert engine.metrics.counters["ring_full_drains"] == 1
        while engine.step():
            pass
        assert h.done() and h.result().finish_reason == "length"
        assert len(h.result().tokens) == 12
        ref = np.asarray(
            generate(model, jnp.asarray(_prompts(21, (6,))[0][None]), 12)
        )[0]
        np.testing.assert_array_equal(
            np.concatenate([_prompts(21, (6,))[0], h.result().tokens]), ref
        )

    def test_eos_first_token_and_one_token_budget(self):
        """fin0 is computed ON DEVICE (the deferred prefill fetch means
        the host can't pre-retire): an EOS first token or an
        already-spent one-token budget must freeze the slot before
        iteration 0 and still finish with the chunked engine's
        reason."""
        model = _llama()
        prompt = _prompts(3, (5,))[0]
        first = int(
            np.asarray(generate(model, jnp.asarray(prompt[None]), 1))[0, -1]
        )
        engine = ServeEngine(
            model, num_slots=1, max_len=64, prefill_buckets=(16,),
            decode_mode="persistent", eos_token=first,
        )
        r = engine.run([{"prompt": prompt, "max_new_tokens": 8}])[0]
        assert r.finish_reason == "stop" and not r.truncated
        np.testing.assert_array_equal(r.tokens, [first])
        engine2 = ServeEngine(
            model, num_slots=2, max_len=64, prefill_buckets=(16,),
            decode_mode="persistent",
        )
        r2 = engine2.run([{"prompt": prompt, "max_new_tokens": 1}])[0]
        assert r2.finish_reason == "length" and len(r2.tokens) == 1

    def test_frozen_slot_rows_stay_virgin(self):
        """A slot finishing mid-loop freezes on device: the masked
        iterations rewrite the frozen row only, so rows past it stay
        virgin zeros (the chunked finish-mask invariant, loop-sized)."""
        model = _llama()
        prompt = _prompts(31, (6,))[0]
        _, base = _run_chunked(
            model, 1, [{"prompt": prompt, "max_new_tokens": 20}],
            num_slots=1, buckets=(8,),
        )
        eos = int(base[0].tokens[3])
        engine = ServeEngine(
            model, num_slots=2, max_len=64, prefill_buckets=(8,),
            decode_mode="persistent", eos_token=eos,
        )
        # batchmate keeps the loop alive past the first slot's finish
        results = engine.run([
            {"prompt": prompt, "max_new_tokens": 20},
            {"prompt": _prompts(32, (6,))[0], "max_new_tokens": 20,
             "temperature": 0.9, "seed": 5},
        ])
        assert results[0].finish_reason == "stop"
        np.testing.assert_array_equal(results[0].tokens, base[0].tokens[:4])
        frozen = prompt.size + len(results[0].tokens) - 1
        k0 = np.asarray(engine.cache.kv[0][0])  # layer 0 K, slot 0 rows
        assert np.all(k0[0, frozen + 1:] == 0)
        assert engine.metrics.counters["masked_slot_steps"] > 0

    def test_stream_tail_matches_drain(self):
        """Opt-in streamed tail: callbacks fire per loop iteration and
        change nothing about the (authoritative) drained streams."""
        engine = self._assert_identical(
            (6, 11), 0.0, persistent_stream=True
        )
        assert engine.stream_supported in ("io_callback", "debug_callback")
        assert engine.metrics.counters["stream_callbacks"] > 0

    def test_stream_falls_back_to_pure_drain(self, monkeypatch):
        """compat drift shim: with neither io_callback nor
        jax.debug.callback available, persistent_stream silently
        degrades to the pure-drain path — same streams, no error."""
        from torchdistx_tpu.utils import compat

        monkeypatch.setattr(compat, "get_io_callback", lambda: None)
        monkeypatch.setattr(compat, "get_debug_callback", lambda: None)
        engine = self._assert_identical(
            (6, 11), 0.0, persistent_stream=True
        )
        assert engine.stream_supported is None
        assert engine.metrics.counters["stream_callbacks"] == 0

    def test_program_count_stable_after_warmup(self):
        engine = self._assert_identical((6, 9), 0.0)
        warm = engine.num_compiled_programs()
        if warm is None:
            pytest.skip("jit cache introspection unavailable on this jax")
        engine.run([dict(r) for r in self._requests((5, 12, 8), 0.0)])
        assert engine.num_compiled_programs() == warm

    def test_validation(self):
        with pytest.raises(ValueError, match="decode_mode"):
            ServeEngine(_llama(), max_len=32, decode_mode="turbo")
        with pytest.raises(ValueError, match="ring_capacity"):
            ServeEngine(_llama(), max_len=32, ring_capacity=8)
        with pytest.raises(ValueError, match="ring_capacity"):
            ServeEngine(
                _llama(), max_len=32, decode_mode="persistent",
                ring_capacity=0,
            )
        with pytest.raises(ValueError, match="persistent_stream"):
            ServeEngine(_llama(), max_len=32, persistent_stream=True)

    def test_metrics_geometry_in_json_and_prom(self):
        """The ISSUE-6 metric satellite: ring counters in to_json() and
        the Prometheus exposition, ring gauges only when persistent."""
        from torchdistx_tpu.obs import MetricsRegistry
        from torchdistx_tpu.serve.metrics import ServeMetrics as SM

        m = SM(num_slots=2, ring_capacity=16)
        m.count("loop_iterations", 9)
        m.count("ring_drains", 2)
        m.observe_ring(7)
        j = m.to_json()
        assert j["counters"]["loop_iterations"] == 9
        assert j["counters"]["ring_drains"] == 2
        assert j["gauges"]["ring_capacity"] == 16
        assert j["gauges"]["ring_occupancy_hwm"] == 7
        reg = MetricsRegistry()
        reg.register_collector(m.collector(), obj=m)
        text = reg.render()
        assert "tdx_serve_ring_drains_total 2" in text
        assert "tdx_serve_ring_occupancy_hwm 7" in text
        # chunked engines carry the counters (zero) but not the gauges
        assert "ring_capacity" not in SM(num_slots=2).to_json()["gauges"]

    @pytest.mark.slow
    @pytest.mark.parametrize("ring", [None, 3])
    @pytest.mark.parametrize("page_size", [None, 8])
    @pytest.mark.parametrize("lengths", [(6, 11, 9, 4, 13), (7,)])
    @pytest.mark.parametrize("temperature", [0.0, 0.9])
    def test_full_grid_bit_identical(self, ring, page_size, lengths,
                                     temperature):
        self._assert_identical(
            lengths, temperature, ring=ring, page_size=page_size
        )


class TestSchedulerUnit:
    def _req(self, n=4, **kw):
        return Request(
            rid=-1, prompt=np.zeros(n, np.int32), max_new_tokens=4, **kw
        )

    def test_fcfs_blocked_head_blocks_line(self):
        s = Scheduler(num_slots=2, max_tokens_in_flight=16)
        a, b, c = self._req(4), self._req(12), self._req(2)
        for r in (a, b, c):
            s.submit(r)
        admitted = s.admit(now=0.0)
        # a (cost 8) admitted; b (cost 16) over budget; c must NOT skip b
        assert [r.rid for r, _ in admitted] == [a.rid]
        assert s.queue_depth == 2
        s.retire(a)
        assert [r.rid for r, _ in s.admit(now=0.0)] == [b.rid]

    def test_slots_reused_lowest_first(self):
        s = Scheduler(num_slots=2)
        a, b = self._req(), self._req()
        s.submit(a), s.submit(b)
        assert [slot for _, slot in s.admit(now=0.0)] == [0, 1]
        s.retire(a)
        c = self._req()
        s.submit(c)
        assert [slot for _, slot in s.admit(now=0.0)] == [0]

    def test_retire_requires_running(self):
        s = Scheduler(num_slots=1)
        r = self._req()
        s.submit(r)
        with pytest.raises(ValueError, match="not running"):
            s.retire(r)


class TestKVCacheUnit:
    def test_admit_retire_bookkeeping(self):
        cache = SlotKVCache(_llama(), num_slots=2, max_len=16)
        cache.admit(0, 5)
        assert cache.active_count == 1 and cache.pos[0] == 5
        with pytest.raises(ValueError, match="already active"):
            cache.admit(0, 3)
        cache.advance_slot(0)
        assert cache.pos[0] == 6 and cache.pos[1] == 0
        cache.retire(0)
        assert cache.active_count == 0
        with pytest.raises(ValueError, match="outside"):
            cache.admit(1, 17)

    def test_positions_clamped_for_dead_slots(self):
        cache = SlotKVCache(_llama(), num_slots=1, max_len=4)
        cache.pos[0] = 9  # stale beyond geometry
        assert cache.positions()[0] == 3


class TestShardedParams:
    def test_fsdp_sharded_params_serve_and_match_generate(self, mesh8):
        # the advertised params= override with mesh-committed (FSDP)
        # params: the slot cache must follow the params onto the mesh
        # (replicated) or the first dispatch dies with an
        # incompatible-devices jit error
        from jax.sharding import NamedSharding

        from torchdistx_tpu.parallel.fsdp import fsdp_partition_spec

        model = _llama()
        params = {
            name: jax.device_put(
                p,
                NamedSharding(
                    mesh8, fsdp_partition_spec(p.shape, mesh8, "fsdp")
                ),
            )
            for name, p in model.named_parameters()
        }
        engine = ServeEngine(
            model, num_slots=2, max_len=64, prefill_buckets=(16,),
            params=params,
        )
        prompts = _prompts(10, (6, 9))
        results = engine.run(
            [{"prompt": p, "max_new_tokens": 5} for p in prompts]
        )
        for p, r in zip(prompts, results):
            assert r.finish_reason == "length"
            ref = np.asarray(
                generate(model, jnp.asarray(p[None]), 5, params=params)
            )[0]
            np.testing.assert_array_equal(
                np.concatenate([p, r.tokens]), ref
            )


class TestValidation:
    def test_submit_rejects_oversized_and_empty(self):
        engine = ServeEngine(_llama(), num_slots=1, max_len=32)
        with pytest.raises(ValueError, match="exceeds the slot cache"):
            engine.submit(np.zeros(30, np.int32), max_new_tokens=10)
        with pytest.raises(ValueError, match="at least one token"):
            engine.submit(np.zeros(0, np.int32), max_new_tokens=4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            engine.submit(np.zeros(4, np.int32), max_new_tokens=0)

    def test_engine_rejects_bad_geometry(self):
        with pytest.raises(ValueError, match="exceeds the model"):
            ServeEngine(_llama(), max_len=1024)
        with pytest.raises(ValueError, match="top_k"):
            ServeEngine(_llama(), max_len=32, top_k=0)
        with pytest.raises(ValueError, match="decode_chunk"):
            ServeEngine(_llama(), max_len=32, decode_chunk=0)

    def test_prompt_beyond_largest_bucket_raises_at_submit(self):
        """Regression: explicit prefill_buckets are taken as given (no
        silent max_len bucket appended), so a prompt longer than the
        largest bucket must die with a clear ValueError in submit(),
        never inside the prefill jit."""
        engine = ServeEngine(
            _llama(), num_slots=1, max_len=64, prefill_buckets=(8, 16)
        )
        assert engine.prefill_buckets == (8, 16)  # nothing appended
        with pytest.raises(ValueError, match="largest prefill bucket"):
            engine.submit(np.zeros(20, np.int32), max_new_tokens=4)
        # up to the largest bucket still serves fine
        r = engine.run(
            [{"prompt": _prompts(40, (16,))[0], "max_new_tokens": 3}]
        )[0]
        assert r.finish_reason == "length"

    def test_prompt_beyond_room_for_max_new_raises_at_submit(self):
        engine = ServeEngine(_llama(), num_slots=1, max_len=32)
        with pytest.raises(ValueError, match="at most 12 tokens"):
            engine.submit(np.zeros(13, np.int32), max_new_tokens=20)


class TestMetricsUnit:
    def test_histogram_snapshot(self):
        h = Histogram()
        assert h.snapshot()["count"] == 0
        for v in range(1, 101):
            h.record(float(v))
        s = h.snapshot()
        assert s["count"] == 100 and s["max"] == 100.0
        assert abs(s["mean"] - 50.5) < 1e-9
        assert 49 <= s["p50"] <= 52 and 94 <= s["p95"] <= 97

    def test_snapshot_is_json_serializable(self):
        import json

        m = ServeMetrics(num_slots=4)
        m.count("tokens_generated", 9)
        m.count("tokens_decoded", 7)  # 2 of the 9 rode prefill dispatches
        m.observe_gauges(queue_depth=2, active_slots=3)
        m.decode_s.record(0.5)
        snap = m.snapshot()
        parsed = json.loads(json.dumps(snap))
        assert parsed["tokens_generated"] == 9
        assert parsed["queue_depth"] == 2
        assert parsed["slot_occupancy_mean"] == 0.75
        # decode throughput excludes prefill-sampled tokens
        assert parsed["decode_tokens_per_sec"] == 14.0


class TestHBMBudgetGate:
    """The capacity planner's second admission gate (ISSUE 8): an
    engine whose projected peak (weights + KV + per-program temps)
    exceeds ``hbm_budget`` refuses admission with the NAMED reason
    ``hbm_budget`` in the request's lifecycle events plus the
    ``admissions_rejected_hbm`` counter — and admits once the budget is
    raised.  The paged variant pins that the page gate ALONE would have
    admitted (pages were free; only the budget refused)."""

    def test_slab_engine_refuses_then_admits(self):
        engine = ServeEngine(_llama(), num_slots=2, max_len=64, hbm_budget=1)
        h = engine.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=4)
        for _ in range(3):
            engine.step()
        assert not h.done()
        assert engine.scheduler.queue_depth == 1
        assert engine.metrics.counters["admissions_rejected_hbm"] == 3
        gated = [e for e in h._request.events if e[0] == "gated"]
        assert gated and gated[-1][2]["why"] == "hbm_budget"
        # the gate is live: raising the budget re-admits on the next tick
        engine.hbm_budget = 10**15
        while engine.step():
            pass
        assert h.done() and h.result().finish_reason == "length"
        # reason + counter survive into the terminal result's event log
        assert any(
            e[0] == "gated" and (e[2] or {}).get("why") == "hbm_budget"
            for e in h.result().events
        )

    def test_paged_engine_page_gate_alone_would_admit(self):
        engine = ServeEngine(
            _llama(), num_slots=2, max_len=64, page_size=16, hbm_budget=1
        )
        prompt = np.arange(1, 9, dtype=np.int32)
        need = -(-(prompt.size + 4) // engine.page_size)
        assert engine.pool.free_count >= need  # pages were no obstacle
        h = engine.submit(prompt, max_new_tokens=4)
        engine.step()
        assert not h.done()
        assert engine.metrics.counters["admissions_rejected_hbm"] == 1
        # the budget refusal fired BEFORE the page gate: nothing was
        # reserved, so a later admit starts from a clean reservation
        assert engine.pool.in_use == 0
        assert h._request.pages is None
        engine.hbm_budget = None  # disable the gate entirely
        while engine.step():
            pass
        assert h.done() and h.result().finish_reason == "length"

    def test_budget_with_headroom_admits_immediately(self):
        engine = ServeEngine(
            _llama(), num_slots=2, max_len=64, hbm_budget=10**15
        )
        r = engine.run(
            [{"prompt": np.arange(1, 9, dtype=np.int32),
              "max_new_tokens": 3}]
        )[0]
        assert r.finish_reason == "length"
        assert engine.metrics.counters["admissions_rejected_hbm"] == 0

    def test_memory_plan_schema(self):
        engine = ServeEngine(_llama(), num_slots=2, max_len=64)
        plan = engine.memory_plan(budget_bytes=10**12)
        assert plan["schema"] == "tdx-capacity-v1"
        assert plan["components"]["kv_cache"] == engine.cache.nbytes
        assert plan["components"]["weights"] > 0
        assert plan["fits"] is True and plan["headroom_bytes"] > 0


def _llama_tp():
    # default n_kv_heads (= n_heads = 4): divisible by every tp in the
    # grid.  (_llama's n_kv_heads=2 is the divisibility-ERROR case.)
    tdx.manual_seed(0)
    return Llama.from_name("tiny", max_seq_len=64)


def _tp_mesh(tp):
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:tp]), ("tp",))


def _serve_vs_generate(model, engine, prompts, max_new=6):
    """Drive the engine and pin every greedy stream bit-identical to
    the single-device ``generation.generate`` reference."""
    results = engine.run(
        [{"prompt": p, "max_new_tokens": max_new} for p in prompts]
    )
    for p, r in zip(prompts, results):
        assert r.finish_reason == "length" and not r.truncated
        ref = np.asarray(generate(model, jnp.asarray(p[None]), max_new))[0]
        np.testing.assert_array_equal(
            np.concatenate([p, r.tokens]), ref
        )


class TestTPServing:
    """Mesh-parallel serving: params Megatron-sharded (llama_tp_rule),
    KV slabs/pools sharded over the head axis, page tables host-side —
    and every greedy stream still bit-identical to the single-device
    reference (CPU mesh: column-parallel matmuls are exact per element
    and the tiny head-sharded reductions do not reorder a greedy
    argmax).  Fast siblings here; the full tp x K x mode x layout grid
    is the -m slow sweep below."""

    def test_tp2_slab_fused_matches_single_device(self):
        model = _llama_tp()
        engine = ServeEngine(
            model, num_slots=3, max_len=64, prefill_buckets=(16,),
            decode_chunk=4, mesh=_tp_mesh(2),
        )
        assert engine.tp == 2
        _serve_vs_generate(model, engine, _prompts(21, (6, 11, 9, 4, 13)))
        # the KV cache is genuinely head-sharded: each device addresses
        # half the slab bytes, and the admission input reports per-shard
        kv = engine.cache.kv[0][0]
        shard = kv.sharding.shard_shape(kv.shape)
        assert shard[2] == kv.shape[2] // 2
        assert (
            engine.memory_plan()["components"]["kv_cache"]
            == engine.cache.nbytes // 2
        )

    def test_tp2_paged_persistent_matches_single_device(self):
        model = _llama_tp()
        engine = ServeEngine(
            model, num_slots=2, max_len=64, prefill_buckets=(16,),
            decode_mode="persistent", page_size=16, mesh=_tp_mesh(2),
        )
        _serve_vs_generate(model, engine, _prompts(22, (5, 12, 9)))

    def test_tp_mesh_comm_audit_pins_closed_form(self):
        from torchdistx_tpu.obs.comm import comm_audit

        model = _llama_tp()
        engine = ServeEngine(
            model, num_slots=2, max_len=64, prefill_buckets=(16,),
            decode_chunk=4, mesh=_tp_mesh(2),
        )
        with comm_audit() as prof:
            engine.run(
                [{"prompt": p, "max_new_tokens": 6}
                 for p in _prompts(23, (7, 10))]
            )
        c = engine.metrics.counters
        nl, dim = model.cfg.n_layers, model.cfg.dim
        # 2 all-reduces per block (attention out + MLP down), per
        # prefill dispatch and per on-device decode step
        expected_ops = 2 * nl * (c["prefill_calls"] + c["decode_steps"])
        assert prof.ops("all_reduce", "tp") == expected_ops
        # payload: n_tokens x dim x 4B per all-reduce — prefills carry
        # their padded bucket, decode steps carry num_slots rows
        expected_payload = (
            2 * nl * 4 * dim
            * (c["tokens_prefilled"] + c["decode_steps"] * engine.num_slots)
        )
        assert prof.payload_bytes("all_reduce", "tp") == expected_payload
        # ring all-reduce wire ratio 2(n-1)/n = 1.0 at tp=2
        assert prof.wire_bytes("all_reduce", "tp") == expected_payload
        # single-device engines record nothing (guards fingerprinted
        # expectations: the tp=1 rows must stay collective-free)
        single = ServeEngine(
            _llama_tp(), num_slots=2, max_len=64, prefill_buckets=(16,)
        )
        with comm_audit() as empty:
            single.run([{"prompt": _prompts(23, (7,))[0],
                         "max_new_tokens": 4}])
        assert empty.ops() == 0

    def test_kv_head_divisibility_error(self):
        # _llama: n_kv_heads=2 — a 4-way tp mesh cannot shard the head
        # axis; the constructor must say so, not die inside jit
        with pytest.raises(ValueError, match="does not divide"):
            ServeEngine(_llama(), num_slots=2, max_len=64,
                        mesh=_tp_mesh(4))

    def test_mesh_axis_and_rule_validation(self):
        from jax.sharding import Mesh

        bad = Mesh(np.asarray(jax.devices()[:2]), ("data",))
        with pytest.raises(ValueError, match="tp_axis"):
            ServeEngine(_llama_tp(), num_slots=1, max_len=32, mesh=bad)
        from torchdistx_tpu.parallel.tp import llama_tp_rule

        with pytest.raises(ValueError, match="requires mesh"):
            ServeEngine(
                _llama_tp(), num_slots=1, max_len=32,
                tp_rule=llama_tp_rule(_tp_mesh(2)),
            )


@pytest.mark.slow
class TestTPServingSlowGrid:
    """The pinned grid of the issue: tp in {1,2,4} x K in {1,4} x
    {chunked,persistent} x {slab,paged}, every greedy stream
    bit-identical to the single-device reference."""

    @pytest.mark.parametrize("tp", [1, 2, 4])
    @pytest.mark.parametrize("k_chunk", [1, 4])
    @pytest.mark.parametrize("mode", ["chunked", "persistent"])
    @pytest.mark.parametrize("paged", [False, True])
    def test_grid(self, tp, k_chunk, mode, paged):
        model = _llama_tp()
        kw = dict(
            num_slots=2, max_len=64, prefill_buckets=(16,),
            mesh=_tp_mesh(tp),
        )
        if mode == "persistent":
            if k_chunk != 1:
                pytest.skip("persistent mode has no decode_chunk")
            kw["decode_mode"] = "persistent"
        else:
            kw["decode_chunk"] = k_chunk
        if paged:
            kw["page_size"] = 16
        engine = ServeEngine(model, **kw)
        _serve_vs_generate(model, engine, _prompts(31, (6, 13, 9)))


class TestChunkedPrefill:
    """Chunked prefill: a long-prompt admission is split into
    bucket-sized chunks with a decode dispatch interleaved between
    them, so active slots keep emitting — and the streams stay
    bit-identical (interleaving is latency-only)."""

    def _ab(self, *, paged=False, mesh=None):
        model = _llama_tp()
        kw = dict(
            num_slots=3, max_len=64, prefill_buckets=(16, 64),
            decode_chunk=2,
        )
        if paged:
            kw["page_size"] = 16
        if mesh is not None:
            kw["mesh"] = mesh
        plain = ServeEngine(model, **kw)
        chunked = ServeEngine(model, **kw, chunked_prefill=16)

        def scenario(engine):
            shorts = [
                engine.submit(p, max_new_tokens=20)
                for p in _prompts(41, (5, 9))
            ]
            engine.step()
            engine.step()
            long_h = engine.submit(
                _prompts(42, (40,))[0], max_new_tokens=6
            )
            while engine.step():
                pass
            return [h.result() for h in shorts], long_h.result()

        return model, plain, chunked, scenario

    def test_decode_slots_emit_between_chunks(self):
        _, plain, chunked, scenario = self._ab()
        shorts_a, long_a = scenario(plain)
        shorts_b, long_b = scenario(chunked)
        c = chunked.metrics.counters
        assert c["chunked_prefills"] == 1
        # 40-token prompt, threshold 16: chunks of 16+16+8 (the tail
        # rides its own bucket-16 dispatch)
        assert c["prefill_chunks"] == 3
        assert c["prefill_interleaved_dispatches"] == 2
        assert plain.metrics.counters["chunked_prefills"] == 0
        # the latency claim: short slots received tokens BETWEEN the
        # long prompt's chunks — decode_chunk events timestamped inside
        # the admission window (prefill start .. long first token)
        t0 = next(ts for n, ts, d in long_b.events if n == "prefill")
        t1 = next(ts for n, ts, d in long_b.events if n == "first_token")
        interleaved = [
            ts
            for r in shorts_b
            for n, ts, _ in r.events
            if n == "decode_chunk" and t0 < ts < t1
        ]
        assert interleaved, "no decode dispatch landed between chunks"
        # and chunking changed WHEN, never WHAT: all streams identical
        for ra, rb in zip(shorts_a + [long_a], shorts_b + [long_b]):
            np.testing.assert_array_equal(ra.tokens, rb.tokens)

    def test_paged_chunked_prefill_streams_identical(self):
        _, plain, chunked, scenario = self._ab(paged=True)
        shorts_a, long_a = scenario(plain)
        shorts_b, long_b = scenario(chunked)
        assert chunked.metrics.counters["chunked_prefills"] == 1
        assert chunked.metrics.counters["prefill_interleaved_dispatches"] > 0
        for ra, rb in zip(shorts_a + [long_a], shorts_b + [long_b]):
            np.testing.assert_array_equal(ra.tokens, rb.tokens)

    def test_tp_mesh_chunked_prefill_streams_identical(self):
        _, plain, chunked, scenario = self._ab(mesh=_tp_mesh(2))
        shorts_a, long_a = scenario(plain)
        shorts_b, long_b = scenario(chunked)
        assert chunked.metrics.counters["prefill_interleaved_dispatches"] > 0
        for ra, rb in zip(shorts_a + [long_a], shorts_b + [long_b]):
            np.testing.assert_array_equal(ra.tokens, rb.tokens)

    def test_chunked_prefill_requires_bucket(self):
        with pytest.raises(ValueError, match="must be one of"):
            ServeEngine(
                _llama_tp(), num_slots=1, max_len=64,
                prefill_buckets=(16, 64), chunked_prefill=12,
            )

    def test_short_prompts_never_chunk(self):
        engine = ServeEngine(
            _llama_tp(), num_slots=1, max_len=64,
            prefill_buckets=(16, 64), chunked_prefill=16,
        )
        r = engine.run(
            [{"prompt": _prompts(43, (10,))[0], "max_new_tokens": 4}]
        )[0]
        assert r.finish_reason == "length"
        assert engine.metrics.counters["chunked_prefills"] == 0
