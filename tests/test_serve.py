"""Continuous-batching serving engine (torchdistx_tpu.serve).

The load-bearing invariants, pinned on the 8-device CPU mesh:

- **Exactness**: a greedy request served through the slot cache is
  bit-identical to ``generation.generate`` on that prompt alone — padding,
  slot reuse, and batch-mates change nothing.
- **Dispatch discipline**: a full mixed-length continuous-batching run —
  including a late request admitted into a freed (dirty) slot — compiles
  exactly two programs (one prefill bucket + one decode step).
- **Deadlines**: expiry returns a partial result flagged ``truncated``.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu.generation import generate
from torchdistx_tpu.models import GPT2, Llama
from torchdistx_tpu.serve import Request, Scheduler, ServeEngine, SlotKVCache
from torchdistx_tpu.serve.metrics import Histogram, ServeMetrics


def _llama():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


def _gpt2():
    tdx.manual_seed(11)
    return GPT2.from_name("tiny")


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 256, (n,)).astype(np.int32) for n in lengths]


class TestSlotDecodeParity:
    """forward_decode (per-row positions) row-for-row equals
    forward_cached (scalar position) — the primitive the engine's
    bit-identity rests on."""

    def test_slot_attention_matches_scalar_cached_attention(self):
        from torchdistx_tpu.ops.attention import (
            cached_attention,
            slot_cached_attention,
        )

        rs = np.random.RandomState(3)
        b, hq, hkv, d, max_seq = 3, 4, 2, 8, 16
        q = jnp.asarray(rs.randn(b, 1, hq, d), jnp.float32)
        k = jnp.asarray(rs.randn(b, 1, hkv, d), jnp.float32)
        v = jnp.asarray(rs.randn(b, 1, hkv, d), jnp.float32)
        cache = (
            jnp.asarray(rs.randn(b, max_seq, hkv, d), jnp.float32),
            jnp.asarray(rs.randn(b, max_seq, hkv, d), jnp.float32),
        )
        positions = np.array([2, 9, 5], np.int32)
        out, (ck, cv) = slot_cached_attention(
            q, k, v, cache, jnp.asarray(positions)
        )
        for row, p in enumerate(positions):
            r = slice(row, row + 1)
            ref, (rk, rv) = cached_attention(
                q[r], k[r], v[r],
                (cache[0][r], cache[1][r]), int(p), use_flash=False,
            )
            np.testing.assert_array_equal(np.asarray(out[r]), np.asarray(ref))
            np.testing.assert_array_equal(np.asarray(ck[r]), np.asarray(rk))
            np.testing.assert_array_equal(np.asarray(cv[r]), np.asarray(rv))

    def test_model_forward_decode_matches_forward_cached(self):
        for model in (_llama(), _gpt2()):
            rs = np.random.RandomState(4)
            toks = jnp.asarray(rs.randint(0, 256, (3, 1)), jnp.int32)
            positions = np.array([1, 7, 4], np.int32)
            caches = [model.init_cache(1, 16) for _ in range(3)]
            # place a little real content at each row's depth
            seeded = []
            for row, p in enumerate(positions):
                pre = jnp.asarray(
                    rs.randint(0, 256, (1, int(p))), jnp.int32
                )
                _, c = model.forward_cached(pre, caches[row], 0)
                seeded.append(c)
            big = [
                (
                    jnp.concatenate([c[i][0] for c in seeded]),
                    jnp.concatenate([c[i][1] for c in seeded]),
                )
                for i in range(len(seeded[0]))
            ]
            logits, _ = model.forward_decode(
                toks, big, jnp.asarray(positions)
            )
            for row, p in enumerate(positions):
                r = slice(row, row + 1)
                ref, _ = model.forward_cached(toks[r], seeded[row], int(p))
                np.testing.assert_array_equal(
                    np.asarray(logits[r]), np.asarray(ref)
                )


class TestServeExactness:
    def test_greedy_bit_identical_to_sequential_generate(self):
        model = _llama()
        engine = ServeEngine(
            model, num_slots=3, max_len=64, prefill_buckets=(16,)
        )
        prompts = _prompts(0, (6, 11, 9, 4, 13))
        results = engine.run(
            [{"prompt": p, "max_new_tokens": 8} for p in prompts]
        )
        for p, r in zip(prompts, results):
            assert r.finish_reason == "length" and not r.truncated
            ref = np.asarray(generate(model, jnp.asarray(p[None]), 8))[0]
            np.testing.assert_array_equal(
                np.concatenate([p, r.tokens]), ref
            )

    def test_greedy_row_unaffected_by_sampling_batchmate(self):
        model = _gpt2()
        prompts = _prompts(1, (5, 7))
        engine = ServeEngine(model, num_slots=2, max_len=32)
        greedy = engine.submit(prompts[0], max_new_tokens=6)
        engine.submit(
            prompts[1], max_new_tokens=6, temperature=1.0, seed=3
        )
        while engine.step():
            pass
        ref = np.asarray(generate(model, jnp.asarray(prompts[0][None]), 6))[0]
        np.testing.assert_array_equal(
            np.concatenate([prompts[0], greedy.result().tokens]), ref
        )

    def test_sampling_reproducible_per_seed(self):
        model = _gpt2()
        prompt = _prompts(2, (6,))[0]
        engine = ServeEngine(model, num_slots=2, max_len=32, top_k=50)

        def sample(seed):
            h = engine.submit(
                prompt, max_new_tokens=6, temperature=0.8, seed=seed
            )
            while not h.done():
                engine.step()
            return h.result().tokens

        a, b, c = sample(7), sample(7), sample(8)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_eos_stops_with_stop_reason(self):
        model = _llama()
        prompt = _prompts(3, (5,))[0]
        first = np.asarray(generate(model, jnp.asarray(prompt[None]), 1))[
            0, -1
        ]
        engine = ServeEngine(
            model, num_slots=1, max_len=64, eos_token=int(first)
        )
        r = engine.run([{"prompt": prompt, "max_new_tokens": 8}])[0]
        assert r.finish_reason == "stop" and not r.truncated
        np.testing.assert_array_equal(r.tokens, [int(first)])


class TestContinuousBatching:
    def test_late_admit_into_freed_slot_no_recompile(self):
        """Mixed lengths, staggered finishes, a late submit landing in a
        freed (dirty) slot — and the jit cache holds exactly TWO programs
        throughout (one prefill bucket, one decode step)."""
        model = _llama()
        engine = ServeEngine(
            model, num_slots=2, max_len=64, prefill_buckets=(16,)
        )
        prompts = _prompts(5, (4, 9, 7))
        h0 = engine.submit(prompts[0], max_new_tokens=3)
        h1 = engine.submit(prompts[1], max_new_tokens=12)
        while not h0.done():
            engine.step()
        assert not h1.done()  # slot 1 still decoding
        warm = engine.num_compiled_programs()
        if warm is None:
            pytest.skip("jit cache introspection unavailable on this jax")
        assert warm == 2  # one prefill bucket + one decode step
        # late arrival: must reuse h0's freed slot while h1 keeps going
        h2 = engine.submit(prompts[2], max_new_tokens=6)
        while engine.step():
            pass
        assert engine.num_compiled_programs() == warm == 2
        for p, h, n in ((prompts[1], h1, 12), (prompts[2], h2, 6)):
            ref = np.asarray(generate(model, jnp.asarray(p[None]), n))[0]
            np.testing.assert_array_equal(
                np.concatenate([p, h.result().tokens]), ref
            )
        snap = engine.metrics.snapshot()
        assert snap["requests_completed"] == 3
        assert snap["tokens_generated"] == 3 + 12 + 6

    def test_queue_deeper_than_slots_drains_fcfs(self):
        model = _llama()
        engine = ServeEngine(
            model, num_slots=2, max_len=64, prefill_buckets=(16,)
        )
        prompts = _prompts(6, (3, 5, 7, 4, 6, 8))
        results = engine.run(
            [{"prompt": p, "max_new_tokens": 4} for p in prompts]
        )
        assert [r.rid for r in results] == sorted(r.rid for r in results)
        for p, r in zip(prompts, results):
            ref = np.asarray(generate(model, jnp.asarray(p[None]), 4))[0]
            np.testing.assert_array_equal(
                np.concatenate([p, r.tokens]), ref
            )
        assert engine.num_compiled_programs() in (2, None)

    def test_max_tokens_budget_defers_admission(self):
        model = _llama()
        engine = ServeEngine(
            model,
            num_slots=2,
            max_len=64,
            prefill_buckets=(16,),
            max_tokens_in_flight=20,
        )
        prompts = _prompts(7, (6, 6))
        engine.submit(prompts[0], max_new_tokens=8)  # cost 14
        h1 = engine.submit(prompts[1], max_new_tokens=8)  # would be 28 > 20
        engine.step()
        assert engine.scheduler.queue_depth == 1  # deferred, slot free
        while engine.step():
            pass
        assert h1.done()  # admitted after the first retired
        ref = np.asarray(generate(model, jnp.asarray(prompts[1][None]), 8))[0]
        np.testing.assert_array_equal(
            np.concatenate([prompts[1], h1.result().tokens]), ref
        )


class TestDeadlines:
    def test_running_deadline_returns_truncated_partial(self):
        model = _llama()
        engine = ServeEngine(
            model, num_slots=1, max_len=64, prefill_buckets=(16,)
        )
        prompt = _prompts(8, (5,))[0]
        h = engine.submit(prompt, max_new_tokens=40, deadline_s=0.2)
        engine.step()  # prefill + first decode: some tokens exist
        engine.step()
        time.sleep(0.25)
        engine.step()  # past deadline now
        r = h.result()
        assert r.finish_reason == "deadline" and r.truncated
        assert 0 < len(r.tokens) < 40
        # the partial prefix is still exact
        ref = np.asarray(
            generate(model, jnp.asarray(prompt[None]), len(r.tokens))
        )[0]
        np.testing.assert_array_equal(np.concatenate([prompt, r.tokens]), ref)
        assert engine.metrics.snapshot()["requests_truncated"] == 1

    def test_queued_deadline_expires_with_no_tokens(self):
        model = _llama()
        engine = ServeEngine(
            model, num_slots=1, max_len=64, prefill_buckets=(16,)
        )
        prompts = _prompts(9, (5, 6))
        engine.submit(prompts[0], max_new_tokens=30)
        h = engine.submit(prompts[1], max_new_tokens=4, deadline_s=0.0)
        engine.step()
        r = h.result()
        assert r.truncated and r.finish_reason == "deadline"
        assert r.tokens.size == 0


class TestSchedulerUnit:
    def _req(self, n=4, **kw):
        return Request(
            rid=-1, prompt=np.zeros(n, np.int32), max_new_tokens=4, **kw
        )

    def test_fcfs_blocked_head_blocks_line(self):
        s = Scheduler(num_slots=2, max_tokens_in_flight=16)
        a, b, c = self._req(4), self._req(12), self._req(2)
        for r in (a, b, c):
            s.submit(r)
        admitted = s.admit(now=0.0)
        # a (cost 8) admitted; b (cost 16) over budget; c must NOT skip b
        assert [r.rid for r, _ in admitted] == [a.rid]
        assert s.queue_depth == 2
        s.retire(a)
        assert [r.rid for r, _ in s.admit(now=0.0)] == [b.rid]

    def test_slots_reused_lowest_first(self):
        s = Scheduler(num_slots=2)
        a, b = self._req(), self._req()
        s.submit(a), s.submit(b)
        assert [slot for _, slot in s.admit(now=0.0)] == [0, 1]
        s.retire(a)
        c = self._req()
        s.submit(c)
        assert [slot for _, slot in s.admit(now=0.0)] == [0]

    def test_retire_requires_running(self):
        s = Scheduler(num_slots=1)
        r = self._req()
        s.submit(r)
        with pytest.raises(ValueError, match="not running"):
            s.retire(r)


class TestKVCacheUnit:
    def test_admit_retire_bookkeeping(self):
        cache = SlotKVCache(_llama(), num_slots=2, max_len=16)
        cache.admit(0, 5)
        assert cache.active_count == 1 and cache.pos[0] == 5
        with pytest.raises(ValueError, match="already active"):
            cache.admit(0, 3)
        cache.advance()
        assert cache.pos[0] == 6 and cache.pos[1] == 0
        cache.retire(0)
        assert cache.active_count == 0
        with pytest.raises(ValueError, match="outside"):
            cache.admit(1, 17)

    def test_positions_clamped_for_dead_slots(self):
        cache = SlotKVCache(_llama(), num_slots=1, max_len=4)
        cache.pos[0] = 9  # stale beyond geometry
        assert cache.positions()[0] == 3


class TestShardedParams:
    def test_fsdp_sharded_params_serve_and_match_generate(self, mesh8):
        # the advertised params= override with mesh-committed (FSDP)
        # params: the slot cache must follow the params onto the mesh
        # (replicated) or the first dispatch dies with an
        # incompatible-devices jit error
        from jax.sharding import NamedSharding

        from torchdistx_tpu.parallel.fsdp import fsdp_partition_spec

        model = _llama()
        params = {
            name: jax.device_put(
                p,
                NamedSharding(
                    mesh8, fsdp_partition_spec(p.shape, mesh8, "fsdp")
                ),
            )
            for name, p in model.named_parameters()
        }
        engine = ServeEngine(
            model, num_slots=2, max_len=64, prefill_buckets=(16,),
            params=params,
        )
        prompts = _prompts(10, (6, 9))
        results = engine.run(
            [{"prompt": p, "max_new_tokens": 5} for p in prompts]
        )
        for p, r in zip(prompts, results):
            assert r.finish_reason == "length"
            ref = np.asarray(
                generate(model, jnp.asarray(p[None]), 5, params=params)
            )[0]
            np.testing.assert_array_equal(
                np.concatenate([p, r.tokens]), ref
            )


class TestValidation:
    def test_submit_rejects_oversized_and_empty(self):
        engine = ServeEngine(_llama(), num_slots=1, max_len=32)
        with pytest.raises(ValueError, match="exceeds the slot cache"):
            engine.submit(np.zeros(30, np.int32), max_new_tokens=10)
        with pytest.raises(ValueError, match="at least one token"):
            engine.submit(np.zeros(0, np.int32), max_new_tokens=4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            engine.submit(np.zeros(4, np.int32), max_new_tokens=0)

    def test_engine_rejects_bad_geometry(self):
        with pytest.raises(ValueError, match="exceeds the model"):
            ServeEngine(_llama(), max_len=1024)
        with pytest.raises(ValueError, match="top_k"):
            ServeEngine(_llama(), max_len=32, top_k=0)


class TestMetricsUnit:
    def test_histogram_snapshot(self):
        h = Histogram()
        assert h.snapshot()["count"] == 0
        for v in range(1, 101):
            h.record(float(v))
        s = h.snapshot()
        assert s["count"] == 100 and s["max"] == 100.0
        assert abs(s["mean"] - 50.5) < 1e-9
        assert 49 <= s["p50"] <= 52 and 94 <= s["p95"] <= 97

    def test_snapshot_is_json_serializable(self):
        import json

        m = ServeMetrics(num_slots=4)
        m.count("tokens_generated", 9)
        m.count("tokens_decoded", 7)  # 2 of the 9 rode prefill dispatches
        m.observe_gauges(queue_depth=2, active_slots=3)
        m.decode_s.record(0.5)
        snap = m.snapshot()
        parsed = json.loads(json.dumps(snap))
        assert parsed["tokens_generated"] == 9
        assert parsed["queue_depth"] == 2
        assert parsed["slot_occupancy_mean"] == 0.75
        # decode throughput excludes prefill-sampled tokens
        assert parsed["decode_tokens_per_sec"] == 14.0
