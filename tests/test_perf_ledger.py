"""Perf sentinel (obs/ledger.py + obs/gate.py + scripts/perf_gate.py) —
the pinned invariants:

- **Ingest round-trip per artifact family**: every committed artifact
  family (bench wrapper, bare bench record, serve record, multichip,
  campaign, kernel-acceptance, flight dump) normalizes into schema-valid
  ``tdx-ledger-v1`` rows, and the real committed artifacts at the repo
  root backfill into a populated trajectory (r01..r05 + serve + multichip),
  with the wedged-relay rounds (r02..r05) carrying ``quality: degraded``.
- **Exact counter gate**: expectations pinned from a record PASS against
  the same record; perturbing ANY pinned counter by +1 fails the gate —
  and ``scripts/perf_gate.py --strict`` exits nonzero naming the metric.
- **Timing bands are direction-aware**: a tok/s drop beyond tolerance
  fails, a tok/s gain passes; a seconds increase beyond tolerance fails,
  a seconds decrease passes.
- **Degraded rows never baseline**: a degraded ledger row with a better
  value than every complete row must not become the comparison point.
"""

import json
import os
import subprocess
import sys

import pytest

from torchdistx_tpu.obs import gate as gate_mod
from torchdistx_tpu.obs import ledger as ledger_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")


# --------------------------------------------------------------------------
# synthetic records, one per artifact family (tiny, no engine runs)
# --------------------------------------------------------------------------

def serve_record(host_syncs=12, decode_dispatches=10, error=None):
    phase = {
        "bench": "serve",
        "model": "tiny",
        "platform": "cpu",
        "requests": 6,
        "max_new_tokens": 8,
        "num_slots": 2,
        "decode_chunk": 4,
        "decode_mode": "chunked",
        "max_len": 64,
        "drain_wall_s": 0.21,
        "compiled_programs": 3,
        "recompile_measure": {"available": True, "compiles_total": 0},
        "recompile_warmup": {"available": True, "compiles_total": 7},
        "metrics": {
            "counters": {
                "requests_completed": 6,
                "tokens_generated": 48,
                "tokens_decoded": 42,
                "decode_dispatches": decode_dispatches,
                "host_syncs": host_syncs,
                "masked_slot_steps": 0,
            },
            "gauges": {"num_slots": 2},
            "histograms": {
                "ttft_s": {"count": 6, "p50": 0.03, "p95": 0.05},
                "decode_token_s": {"count": 42, "p50": 0.004, "p95": 0.006},
            },
            "derived": {
                "wall_s": 0.5,
                "decode_tokens_per_sec": 200.0,
                "wall_tokens_per_sec": 96.0,
                "syncs_per_token": host_syncs / 48,
                "prefix_hit_rate": None,
            },
        },
    }
    if error:
        phase = {"error": error}
    return {
        "bench": "serve",
        "record_schema": "tdx-record-v1",
        "git_sha": "feedfacecafe",
        "model": "tiny",
        "phases": {"k4": phase},
    }


def bench_record(progress="complete", tokens_per_sec=19515.6):
    return {
        "metric": "deferred_init_materialize_llama2_7b_wall_s",
        "git_sha": "feedfacecafe",
        "value": 13.3,
        "vs_baseline": 4.5,
        "tokens_per_sec": tokens_per_sec,
        "mfu": 0.65,
        "goodput": 0.9,
        "extra": {
            "progress": progress,
            "deferred_init_s": 3.0,
            "materialize_s": 10.3,
            "params": 6738415616,
            "peak_host_rss_gb": 0.25,
            "device": "TPU v5 lite0",
            "train_model": "llama_1b",
            "train_batch": 2,
            "train_seq": 2048,
            "train_window_s": 4.2,
            "train_recompile": {
                "available": True,
                "compiles_total": 3,
                "by_scope": {
                    "warmup": {"compiles": 3},
                    "timed_window": {"compiles": 0},
                },
            },
            "remat": False,
            "optimizer": "anyprecision_adamw",
            "materialize_chunked": {"total_s": 14.9, "materialize_s": 12.1},
        },
    }


def multichip_record(ok=True):
    tail = (
        "dryrun_multichip(8): mesh dp=2 fsdp=2 sp=2, step OK\n"
        'MULTICHIP_LEG {"leg": "fsdp_sp", "seconds": 3.2, "comm_ops": 12, '
        '"comm_bytes_by_axis": {"fsdp": 1024, "sp": 512}, "compiles": 4}\n'
        "dryrun_multichip(8): TP leg OK\n"
    )
    return {"n_devices": 8, "rc": 0 if ok else 1, "ok": ok,
            "skipped": False, "tail": tail}


# --------------------------------------------------------------------------
# row schema + ledger file plumbing
# --------------------------------------------------------------------------

class TestLedgerRows:
    def test_make_row_validates(self):
        row = ledger_mod.make_row(
            run_id="r", source="bench", metric="m", value=1,
            metric_class="counter", quality="complete",
            workload={"phase": "x"},
        )
        assert ledger_mod.validate_ledger_row(row) == []
        assert row["fingerprint"] == "phase=x"

    @pytest.mark.parametrize(
        "patch",
        [
            {"schema": "tdx-ledger-v0"},
            {"source": "mystery"},
            {"metric_class": "vibes"},
            {"quality": "great"},
            {"value": "fast"},
            {"value": float("nan")},
            {"fingerprint": "phase=y"},
        ],
    )
    def test_bad_rows_flagged(self, patch):
        row = ledger_mod.make_row(
            run_id="r", source="bench", metric="m", value=1,
            metric_class="counter", quality="complete",
            workload={"phase": "x"},
        )
        row.update(patch)
        assert ledger_mod.validate_ledger_row(row)

    def test_fingerprint_is_order_independent_and_int_normalized(self):
        a = ledger_mod.fingerprint({"b": 2, "a": 1})
        b = ledger_mod.fingerprint({"a": 1, "b": 2.0})
        assert a == b == "a=1|b=2"

    def test_append_read_roundtrip(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        rows = [
            ledger_mod.make_row(
                run_id=f"r{i}", source="bench", metric="m", value=i,
                metric_class="counter", quality="complete",
            )
            for i in range(3)
        ]
        assert ledger_mod.append_rows(path, rows) == 3
        assert ledger_mod.append_rows(path, rows[:1]) == 1  # append-only
        back = ledger_mod.read_ledger(path)
        assert [r["value"] for r in back] == [0, 1, 2, 0]
        assert ledger_mod.validate_ledger_file(path) == []

    def test_append_rejects_invalid(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        bad = ledger_mod.make_row(
            run_id="r", source="bench", metric="m", value=1,
            metric_class="counter", quality="complete",
        )
        bad["value"] = "fast"
        with pytest.raises(ValueError):
            ledger_mod.append_rows(path, [bad])
        assert not os.path.exists(path)

    def test_whitespace_only_ledger_fails_validation(self, tmp_path):
        path = tmp_path / "empty.jsonl"
        path.write_text("\n\n")
        assert ledger_mod.validate_ledger_file(str(path))

    def test_read_skips_corrupt_tail_validate_flags_it(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        ledger_mod.append_rows(
            path,
            [ledger_mod.make_row(
                run_id="r", source="bench", metric="m", value=1,
                metric_class="counter", quality="complete")],
        )
        with open(path, "a") as f:
            f.write('{"truncated": ')  # killed-run torn write
        assert len(ledger_mod.read_ledger(path)) == 1
        assert ledger_mod.validate_ledger_file(path)


# --------------------------------------------------------------------------
# ingest adapters, one per family
# --------------------------------------------------------------------------

class TestIngest:
    def test_serve_counters_and_classes(self):
        rows = ledger_mod.ingest_serve_record(serve_record(), run_id="s1")
        assert rows and all(
            not ledger_mod.validate_ledger_row(r) for r in rows
        )
        by = {r["metric"]: r for r in rows}
        assert by["host_syncs"]["value"] == 12
        assert by["host_syncs"]["metric_class"] == "counter"
        assert by["syncs_per_token"]["metric_class"] == "counter"
        assert by["decode_tokens_per_sec"]["metric_class"] == "timing"
        assert by["recompile_measure_compiles"]["value"] == 0
        assert by["host_syncs"]["workload"]["phase"] == "k4"
        assert by["host_syncs"]["platform"] == "cpu"
        assert by["host_syncs"]["git_sha"] == "feedfacecafe"
        assert all(r["quality"] == "complete" for r in rows)

    def test_serve_phase_error_degrades_run(self):
        rec = serve_record()
        rec["phases"]["persistent"] = {"error": "deadline share exceeded"}
        rows = ledger_mod.ingest_serve_record(rec, run_id="s1")
        assert rows and all(r["quality"] == "degraded" for r in rows)

    def test_bench_record_rows(self):
        rows = ledger_mod.ingest_bench_record(bench_record(), run_id="b1")
        by = {(r["workload"].get("phase"), r["metric"]): r for r in rows}
        assert by[("train", "tokens_per_sec")]["metric_class"] == "timing"
        assert by[("train", "tokens_per_sec")]["platform"] == "tpu"
        assert by[("train", "train_window_compiles")]["value"] == 0
        assert by[("materialize_7b", "params")]["metric_class"] == "counter"
        assert by[("driver", "bench_complete")]["value"] == 1
        assert all(r["quality"] == "complete" for r in rows)

    def test_bench_wrapper_degraded_wedge(self):
        # the r04/r05 shape: rc=0 but the inner record never got past
        # preflight — everything must land degraded
        inner = bench_record(progress="preflight-failed")
        for k in ("value", "vs_baseline", "tokens_per_sec", "mfu"):
            inner[k] = None
        wrapper = {"n": 4, "rc": 0, "tail": json.dumps(inner), "parsed": inner}
        rows = ledger_mod.ingest_bench_wrapper(wrapper, run_id="r04")
        assert rows and all(r["quality"] == "degraded" for r in rows)
        assert any(r["metric"] == "bench_rc" for r in rows)

    def test_multichip_rows(self):
        rows = ledger_mod.ingest_multichip_record(
            multichip_record(), run_id="m1"
        )
        by = {r["metric"]: r for r in rows}
        assert by["dryrun_legs"]["value"] == 2
        assert by["dryrun_ok"]["value"] == 1
        assert by["leg_comm_bytes"]["value"] == 1536  # summed by-axis
        assert by["leg_comm_bytes"]["workload"]["leg"] == "fsdp_sp"
        assert by["leg_seconds"]["metric_class"] == "timing"

    def test_campaign_delegates_and_overrules_killed_steps(self):
        camp = {
            "status": "partial",
            "steps": {
                "serve_engine_ab": {
                    "rc": 0, "wall_s": 120.0, "records": [serve_record()],
                },
                "bench_full": {
                    "rc": "timeout", "wall_s": 900.0,
                    "records": [bench_record()],
                },
            },
        }
        rows = ledger_mod.ingest_campaign_record(camp, run_id="c1")
        srv = [r for r in rows if r["run_id"] == "c1/serve_engine_ab"]
        bch = [r for r in rows if r["run_id"] == "c1/bench_full"]
        assert srv and all(r["quality"] == "complete" for r in srv)
        # killed step: the record looked complete but the step verdict wins
        assert bch and all(r["quality"] == "degraded" for r in bch)
        # live-append mode skips gracefully-exited steps (they
        # self-appended) but keeps the killed step's harvest
        live = ledger_mod.ingest_campaign_record(
            camp, step_records="failed", run_id="c1"
        )
        assert not [r for r in live if r["run_id"] == "c1/serve_engine_ab"]
        assert [r for r in live if r["run_id"] == "c1/bench_full"]

    def test_flight_dump_rows(self, tmp_path):
        path = str(tmp_path / "flight.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps({"kind": "flight_header",
                                "schema": "tdx-flight-v1",
                                "reason": "bench_train", "dropped": 2}) + "\n")
            f.write(json.dumps({"kind": "step", "loss": 1.0}) + "\n")
            f.write(json.dumps({"kind": "failure", "what": "nan"}) + "\n")
            f.write(json.dumps({"kind": "rollback",
                                "restored_step": 3}) + "\n")
        rows = ledger_mod.ingest_flight_dump(path, run_id="f1")
        by = {r["metric"]: r["value"] for r in rows}
        assert by == {"flight_records": 4, "flight_dropped": 2,
                      "flight_failures": 1, "flight_rollbacks": 1}

    def test_ingest_artifact_rejects_unknown(self, tmp_path):
        p = tmp_path / "mystery.json"
        p.write_text('{"hello": 1}')
        with pytest.raises(ValueError):
            ledger_mod.ingest_artifact(str(p))

    def test_ingest_artifact_explicit_sha_beats_record_stamp(self, tmp_path):
        p = tmp_path / "rec.json"
        p.write_text(json.dumps(serve_record()))  # stamped feedfacecafe
        rows = ledger_mod.ingest_artifact(str(p), git_sha="caller0000")
        assert {r["git_sha"] for r in rows} == {"caller0000"}
        rows = ledger_mod.ingest_artifact(str(p))
        assert {r["git_sha"] for r in rows} == {"feedfacecafe"}

    def test_dirty_artifact_gets_mtime_not_commit_time(self, tmp_path):
        # an untracked/modified artifact is a FRESH run: its rows must
        # not share the committed version's timestamp identity (the
        # gate's never-your-own-baseline rule keys on (run_id, ts))
        p = tmp_path / "BENCH_SERVE_FRESH.json"
        p.write_text(json.dumps(serve_record()))
        rows = ledger_mod.ingest_artifact(str(p))
        assert rows[0]["ts"] == pytest.approx(os.path.getmtime(p), abs=1.0)


class TestBackfillRealArtifacts:
    """The committed repo-root artifacts ARE the backfill corpus — this
    pins the acceptance criterion that every family lands rows."""

    @pytest.fixture(scope="class")
    def rows(self):
        sys.path.insert(0, SCRIPTS)
        try:
            import backfill_ledger
        finally:
            sys.path.remove(SCRIPTS)
        rows, report = backfill_ledger.collect_rows(REPO)
        assert report
        return rows

    def test_all_rows_schema_valid(self, rows):
        assert rows
        assert not [e for r in rows for e in ledger_mod.validate_ledger_row(r)]

    def test_every_committed_family_lands(self, rows):
        runs = {r["run_id"] for r in rows}
        expected = {
            "BENCH_r01", "BENCH_r02", "BENCH_r03", "BENCH_r03_local",
            "BENCH_r04", "BENCH_r05", "BENCH_SERVE_CPU",
            "MULTICHIP_r01", "MULTICHIP_r02", "MULTICHIP_r03",
            "MULTICHIP_r04", "MULTICHIP_r05",
        }
        assert expected <= runs, expected - runs

    def test_wedged_rounds_are_degraded(self, rows):
        for run in ("BENCH_r02", "BENCH_r03", "BENCH_r04", "BENCH_r05"):
            quals = {r["quality"] for r in rows if r["run_id"] == run}
            assert quals == {"degraded"}, (run, quals)

    def test_complete_rounds_attributed_to_commits(self, rows):
        shas = {r["git_sha"] for r in rows if r["run_id"] == "BENCH_r01"}
        assert all(shas), "backfilled rows must carry a commit sha"

    def test_committed_ledger_matches_schema(self):
        path = os.path.join(REPO, "LEDGER.jsonl")
        assert os.path.exists(path), "LEDGER.jsonl must be committed"
        assert ledger_mod.validate_ledger_file(path) == []


# --------------------------------------------------------------------------
# gate semantics
# --------------------------------------------------------------------------

class TestGate:
    def test_exact_pass_and_perturbed_fail(self):
        rows = ledger_mod.ingest_serve_record(serve_record(), run_id="a")
        exp = gate_mod.build_expectations(rows)
        verdict = gate_mod.gate_rows(rows, exp, [])
        assert verdict["ok"], verdict["failures"]
        assert verdict["checked_counters"] > 0
        perturbed = ledger_mod.ingest_serve_record(
            serve_record(host_syncs=13), run_id="b"
        )
        verdict = gate_mod.gate_rows(perturbed, exp, [])
        assert not verdict["ok"]
        failed = {f["metric"] for f in verdict["failures"]}
        # the raw counter AND its derived exact ratio both trip
        assert "host_syncs" in failed and "syncs_per_token" in failed

    def test_missing_counter_row_fails(self):
        rows = ledger_mod.ingest_serve_record(serve_record(), run_id="a")
        exp = gate_mod.build_expectations(rows)
        rows_missing = [r for r in rows if r["metric"] != "host_syncs"]
        verdict = gate_mod.gate_rows(rows_missing, exp, [])
        kinds = {(f["kind"], f["metric"]) for f in verdict["failures"]}
        assert ("missing_counter", "host_syncs") in kinds

    def test_expectations_refuse_degraded_runs(self):
        rec = serve_record()
        rec["phases"]["x"] = {"error": "boom"}
        rows = ledger_mod.ingest_serve_record(rec, run_id="a")
        with pytest.raises(ValueError):
            gate_mod.build_expectations(rows)

    def test_degraded_record_fails_strict_gate(self):
        rec = serve_record()
        rec["phases"]["x"] = {"error": "boom"}
        rows = ledger_mod.ingest_serve_record(rec, run_id="a")
        verdict = gate_mod.gate_rows(rows, None, [])
        assert not verdict["ok"]
        assert any(
            f["kind"] == "degraded_input" for f in verdict["failures"]
        )

    def _timing_row(self, value, run_id, metric="decode_tokens_per_sec",
                    quality="complete"):
        return ledger_mod.make_row(
            run_id=run_id, source="bench_serve", metric=metric, value=value,
            metric_class="timing", quality=quality,
            workload={"phase": "k4"}, platform="cpu",
        )

    def test_timing_band_higher_is_better(self):
        base = [self._timing_row(100.0, "old")]
        ok = gate_mod.gate_rows([self._timing_row(90.0, "new")], None, base)
        assert ok["ok"]  # inside the 25% band
        better = gate_mod.gate_rows(
            [self._timing_row(140.0, "new")], None, base
        )
        assert better["ok"]  # improvements always pass
        bad = gate_mod.gate_rows([self._timing_row(60.0, "new")], None, base)
        assert not bad["ok"]
        f = bad["failures"][0]
        assert f["kind"] == "timing_regression"
        assert f["direction"] == "higher"
        assert f["baseline_run"] == "old"

    def test_timing_band_lower_is_better(self):
        base = [self._timing_row(1.0, "old", metric="drain_wall_s")]
        ok = gate_mod.gate_rows(
            [self._timing_row(1.2, "new", metric="drain_wall_s")], None, base
        )
        assert ok["ok"]
        bad = gate_mod.gate_rows(
            [self._timing_row(1.5, "new", metric="drain_wall_s")], None, base
        )
        assert not bad["ok"]
        assert bad["failures"][0]["direction"] == "lower"

    def test_degraded_rows_never_baseline(self):
        # degraded row is the best value; it must be ignored and the
        # complete row used instead
        base = [
            self._timing_row(1000.0, "wedged", quality="degraded"),
            self._timing_row(100.0, "good"),
        ]
        verdict = gate_mod.gate_rows([self._timing_row(90.0, "new")],
                                     None, base)
        assert verdict["ok"], verdict["failures"]
        # sanity: had the degraded row been the baseline, 90 << 750
        # would have failed the band
        assert gate_mod.gate_rows(
            [self._timing_row(90.0, "new")],
            None,
            [self._timing_row(1000.0, "wedged"),
             self._timing_row(100.0, "good")],
        )["ok"] is False

    def test_new_run_never_its_own_baseline(self):
        rows = [self._timing_row(100.0, "new")]
        verdict = gate_mod.gate_rows(rows, None, rows)
        assert verdict["checked_timings"] == 0
        assert any(s["kind"] == "no_baseline" for s in verdict["skipped"])

    def test_same_name_prior_run_IS_a_baseline(self):
        # the nightly workflow: the same artifact basename is gated
        # night after night — a PRIOR run sharing the run_id (but not
        # the timestamp) must serve as the baseline; only the run's own
        # (run_id, ts) identity is excluded
        prior = self._timing_row(100.0, "BENCH_SERVE_CPU")
        prior["ts"] = 1000.0
        new = self._timing_row(60.0, "BENCH_SERVE_CPU")
        new["ts"] = 2000.0
        verdict = gate_mod.gate_rows([new], None, [prior, dict(new)])
        assert verdict["checked_timings"] == 1
        assert not verdict["ok"]  # 60 < 100 * 0.75 — real regression caught

    def test_direction_registry(self):
        assert gate_mod.timing_direction("decode_tokens_per_sec") == "higher"
        assert gate_mod.timing_direction("mfu") == "higher"
        assert gate_mod.timing_direction("goodput") == "higher"
        assert gate_mod.timing_direction("prefix_hit_rate") == "higher"
        assert gate_mod.timing_direction("drain_wall_s") == "lower"
        assert gate_mod.timing_direction("ttft_s_p95") == "lower"
        assert gate_mod.timing_direction("peak_host_rss_gb") == "lower"

    def test_markdown_render_names_failures(self):
        rows = ledger_mod.ingest_serve_record(
            serve_record(host_syncs=13), run_id="b"
        )
        exp = gate_mod.build_expectations(
            ledger_mod.ingest_serve_record(serve_record(), run_id="a")
        )
        md = gate_mod.render_gate_markdown(gate_mod.gate_rows(rows, exp, []))
        assert "FAIL" in md and "host_syncs" in md


# --------------------------------------------------------------------------
# CLI contracts (subprocess: the nonzero-exit acceptance criterion)
# --------------------------------------------------------------------------

def _run(args, **kw):
    return subprocess.run(
        [sys.executable] + args, capture_output=True, text=True, cwd=REPO,
        timeout=120, **kw,
    )


class TestCLIs:
    @pytest.fixture(scope="class")
    def env(self, tmp_path_factory):
        d = tmp_path_factory.mktemp("perfcli")
        record = d / "record.json"
        record.write_text(json.dumps(serve_record()))
        exp = d / "expect.json"
        r = _run([
            os.path.join(SCRIPTS, "perf_gate.py"), str(record),
            "--update-expectations", str(exp),
        ])
        assert r.returncode == 0, r.stderr
        ledger = d / "ledger.jsonl"
        rows = ledger_mod.ingest_serve_record(
            serve_record(), run_id="prior", ts=1.0
        )
        ledger_mod.append_rows(str(ledger), rows)
        return {"dir": d, "record": record, "exp": exp, "ledger": ledger}

    def test_gate_pass_rc0(self, env):
        r = _run([
            os.path.join(SCRIPTS, "perf_gate.py"), str(env["record"]),
            "--expectations", str(env["exp"]),
            "--ledger", str(env["ledger"]), "--strict",
        ])
        assert r.returncode == 0, r.stderr
        verdict = json.loads(r.stdout.strip().splitlines()[-1])
        assert verdict["ok"] and verdict["schema"] == "tdx-gate-v1"
        # timing rows got real baselines from the prior ledger run
        assert verdict["checked_timings"] > 0

    def test_perturbed_counter_rc_nonzero_names_metric(self, env):
        rec = serve_record()
        rec["phases"]["k4"]["metrics"]["counters"]["decode_dispatches"] += 1
        bad = env["dir"] / "perturbed.json"
        bad.write_text(json.dumps(rec))
        r = _run([
            os.path.join(SCRIPTS, "perf_gate.py"), str(bad),
            "--expectations", str(env["exp"]),
            "--ledger", str(env["ledger"]), "--strict",
        ])
        assert r.returncode != 0
        assert "decode_dispatches" in r.stderr

    def test_gate_append_after_gating(self, env):
        led = env["dir"] / "append.jsonl"
        r = _run([
            os.path.join(SCRIPTS, "perf_gate.py"), str(env["record"]),
            "--expectations", str(env["exp"]),
            "--ledger", str(led), "--append",
        ])
        assert r.returncode == 0, r.stderr
        assert ledger_mod.read_ledger(str(led))

    def test_perf_report_trend_and_ab(self, env):
        rows = ledger_mod.ingest_serve_record(
            serve_record(host_syncs=12), run_id="later", ts=2.0
        )
        ledger_mod.append_rows(str(env["ledger"]), rows)
        r = _run([
            os.path.join(SCRIPTS, "perf_report.py"),
            "--ledger", str(env["ledger"]),
        ])
        assert r.returncode == 0, r.stderr
        assert "host_syncs" in r.stdout and "Perf trend report" in r.stdout
        r = _run([
            os.path.join(SCRIPTS, "perf_report.py"),
            "--ledger", str(env["ledger"]), "--ab", "prior", "later",
        ])
        assert r.returncode == 0, r.stderr
        assert "A/B" in r.stdout and "host_syncs" in r.stdout

    def test_check_obs_artifacts_ledger_mode(self, env):
        chk = os.path.join(SCRIPTS, "check_obs_artifacts.py")
        r = _run([chk, "--ledger", str(env["ledger"])])
        assert r.returncode == 0, r.stderr
        bad = env["dir"] / "bad.jsonl"
        bad.write_text('{"schema": "tdx-ledger-v0"}\n')
        r = _run([chk, "--ledger", str(bad)])
        assert r.returncode != 0
        assert "FAIL" in r.stderr

    def test_committed_expectations_match_committed_smoke_workload(self):
        """The nightly gates BENCH_SERVE_CPU.json (regenerated by the CI
        smoke at --requests 6 --max-new 8 --slots 2 --decode-chunk 4)
        against the committed expectations — the pinned fingerprints
        must describe exactly that invocation."""
        path = os.path.join(REPO, "expectations", "serve_cpu_smoke.json")
        assert os.path.exists(path)
        with open(path) as f:
            doc = json.load(f)
        assert gate_mod.validate_expectations(doc) == []
        fps = set(doc["counters"])
        phase_fps = {fp for fp in fps if "program=" not in fp}
        cost_fps = fps - phase_fps
        # k1 + k4 + persistent + the ISSUE 11 speculate sweep (spec0
        # baseline rides at its own geometry — the spec phases stretch
        # max_new so the self-repetition the n-gram drafter needs can
        # establish, hence their own fingerprint family) + the ISSUE 17
        # int8 --kv-quant-ab rider (kv_dtype=int8 tags its fingerprint,
        # so the quantized family never collides with the default pins)
        # + the ISSUE 19 --numerics rider (numerics=True phase pins and
        # its per-site numerics_site= digest families)
        num_fps = {fp for fp in phase_fps if "phase=numerics" in fp}
        assert len(num_fps) == 4
        assert all("numerics=True" in fp for fp in num_fps)
        assert len(phase_fps - num_fps) == 7
        kvq_fps = {fp for fp in phase_fps if "phase=kv_quant" in fp}
        assert len(kvq_fps) == 1 and "kv_dtype=int8" in next(iter(kvq_fps))
        assert any(
            "kv_dtype=int8" in fp for fp in fps if "program=serve/" in fp
        )
        for fp in fps:
            assert "requests=6" in fp
            assert "model=tiny" in fp and "num_slots=2" in fp
        spec_fps = {fp for fp in phase_fps if "speculate=" in fp}
        assert {fp.split("phase=")[1].split("|")[0] for fp in spec_fps} \
            == {"spec0", "spec2", "spec4"}
        for fp in spec_fps:
            assert "decode_mode=persistent" in fp
        for fp in fps - spec_fps:
            assert "max_new_tokens=8" in fp or "program=" in fp
        assert any("phase=persistent" in fp for fp in phase_fps)
        # cost observatory (ISSUE 8): each phase additionally pins its
        # programs' XLA HLO-analysis counts under program-tagged
        # fingerprints — and ONLY those (buffer-assignment sizes stay
        # out of the pins per gate.DEFAULT_COUNTER_EXCLUDE)
        assert cost_fps and all("program=serve/" in fp for fp in cost_fps)
        for fp in cost_fps:
            assert set(doc["counters"][fp]) <= {
                "cost_flops", "cost_bytes_accessed", "cost_transcendentals"
            }


class TestRecordStamp:
    def test_stamp_has_schema_and_sha(self):
        stamp = ledger_mod.record_stamp()
        assert stamp["record_schema"] == "tdx-record-v1"
        # in this checkout git is available, so the sha must resolve
        assert stamp["git_sha"]

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("TDX_GIT_SHA", "deadbeef")
        assert ledger_mod.git_sha() == "deadbeef"

    def test_append_record_rows_never_raises(self, tmp_path, monkeypatch):
        monkeypatch.setenv(
            "TDX_LEDGER_PATH", str(tmp_path / "nodir" / "x.jsonl")
        )
        # unwritable path: must swallow and return 0, not raise
        assert ledger_mod.append_record_rows(
            serve_record(), source="bench_serve"
        ) == 0

    def test_append_record_rows_disabled(self, tmp_path, monkeypatch):
        path = tmp_path / "led.jsonl"
        monkeypatch.setenv("TDX_LEDGER_PATH", str(path))
        monkeypatch.setenv("TDX_LEDGER", "0")
        assert ledger_mod.append_record_rows(
            serve_record(), source="bench_serve"
        ) == 0
        assert not path.exists()
        monkeypatch.delenv("TDX_LEDGER")
        assert ledger_mod.append_record_rows(
            serve_record(), source="bench_serve"
        ) > 0
        assert path.exists()
