"""Mixtral (sparse-MoE decoder) model family: deferred init parity,
dense-vs-capacity routing agreement, cached decode, EP-sharded training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu.models import Mixtral
from torchdistx_tpu.nn import functional, functional_call
from torchdistx_tpu.parallel import create_mesh


def _tokens(b=2, s=32, vocab=256, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, vocab, (b, s)), jnp.int32
    )


def test_deferred_matches_eager_init():
    tdx.manual_seed(11)
    m_def = tdx.deferred_init(Mixtral.from_name, "tiny")
    assert tdx.is_deferred(m_def)
    tdx.materialize_module(m_def)
    tdx.manual_seed(11)
    m_eager = Mixtral.from_name("tiny")
    p_def = dict(m_def.named_parameters())
    p_eager = dict(m_eager.named_parameters())
    assert p_def.keys() == p_eager.keys()
    for name, a in p_def.items():
        assert np.array_equal(np.asarray(a), np.asarray(p_eager[name])), name


def test_forward_and_aux_loss():
    tdx.manual_seed(12)
    m = Mixtral.from_name("tiny")
    tok = _tokens()
    logits = m(tok)
    assert logits.shape == (2, 32, 256)
    logits2, aux = m.forward_with_aux(tok)
    np.testing.assert_array_equal(np.asarray(logits), np.asarray(logits2))
    # balanced routing has aux ~1; pathological collapse drives it to E
    assert 0.5 < float(aux) < m.cfg.n_experts


def test_capacity_matches_dense_when_sufficient():
    tdx.manual_seed(13)
    m_dense = Mixtral.from_name("tiny")
    tdx.manual_seed(13)
    m_cap = Mixtral.from_name(
        "tiny",
        # capacity >= E/top_k: no token can be dropped -> exact agreement
        capacity_factor=float(4 / 2),
    )
    tok = _tokens(seed=3)
    np.testing.assert_allclose(
        np.asarray(m_dense(tok)), np.asarray(m_cap(tok)), rtol=2e-5, atol=2e-5
    )


def test_cached_decode_matches_full_forward():
    tdx.manual_seed(14)
    m = Mixtral.from_name("tiny")
    tok = _tokens(b=1, s=16, seed=5)
    full = m(tok)
    cache = m.init_cache(1, max_seq=32)
    # prefill 12, then decode 4 one at a time
    logits, cache = m.forward_cached(tok[:, :12], cache, 0)
    np.testing.assert_allclose(
        np.asarray(full[:, :12]), np.asarray(logits), rtol=2e-5, atol=2e-5
    )
    for i in range(12, 16):
        logits, cache = m.forward_cached(tok[:, i : i + 1], cache, i)
        np.testing.assert_allclose(
            np.asarray(full[:, i : i + 1]),
            np.asarray(logits),
            rtol=2e-5,
            atol=2e-5,
        )


def test_ep_sharded_train_step_matches_unsharded():
    mesh = create_mesh({"dp": 2, "ep": 4})
    tdx.manual_seed(15)
    m = tdx.deferred_init(Mixtral.from_name, "tiny")
    tdx.materialize_module(m, sharding_rule=m.shard_rule(mesh))
    params = dict(m.named_parameters())
    w = params["blocks.0.mlp.w_gate"]
    assert w.sharding.spec == P("ep", None, None)

    tok, labels = _tokens(seed=7), _tokens(seed=8)
    tx = optax.sgd(1e-2)

    def loss_fn(p):
        logits, aux = functional_call(
            m, p, (tok,), method="forward_with_aux"
        )
        return functional.cross_entropy(logits, labels) + 1e-2 * aux

    @jax.jit
    def step(p, s):
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, s = tx.update(grads, s, p)
        return jax.tree_util.tree_map(lambda a, u: a + u, p, updates), s, loss

    p1, s1, loss_sharded = step(params, tx.init(params))

    # same math fully replicated
    rep = jax.device_put(params, NamedSharding(mesh, P()))
    p2, s2, loss_rep = step(rep, tx.init(rep))
    np.testing.assert_allclose(
        float(loss_sharded), float(loss_rep), rtol=1e-5
    )
    for name in ("blocks.0.mlp.w_down", "lm_head.weight"):
        np.testing.assert_allclose(
            np.asarray(p1[name]), np.asarray(p2[name]), rtol=2e-5, atol=2e-5
        )


@pytest.mark.slow
def test_generate_greedy_matches_full_recompute():
    tdx.manual_seed(16)
    m = Mixtral.from_name("tiny")
    prompt = _tokens(b=1, s=8, seed=9)
    out = tdx.generate(m, prompt, max_new_tokens=5)
    assert out.shape == (1, 13)
    # greedy decode must equal argmax over the full (uncached) forward
    cur = prompt
    for _ in range(5):
        nxt = jnp.argmax(m(cur)[:, -1], axis=-1)[:, None]
        cur = jnp.concatenate([cur, nxt.astype(cur.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


def test_remat_matches_no_remat():
    tdx.manual_seed(17)
    m = Mixtral.from_name("tiny")
    tdx.manual_seed(17)
    m_remat = Mixtral.from_name("tiny", remat=True)
    tok = _tokens(seed=10)
    np.testing.assert_allclose(
        np.asarray(m(tok)), np.asarray(m_remat(tok)), rtol=1e-6, atol=1e-6
    )
    la, aa = m.forward_with_aux(tok)
    lb, ab = m_remat.forward_with_aux(tok)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(float(aa), float(ab), rtol=1e-6)
    # gradients flow through the rematted aux path
    p = dict(m_remat.named_parameters())
    g = jax.grad(
        lambda pp: functional.cross_entropy(
            functional_call(m_remat, pp, (tok,)), tok
        )
    )(p)
    assert float(jnp.abs(g["blocks.0.mlp.w_gate"]).sum()) > 0
