"""Test harness: force an 8-device CPU platform so distributed behavior runs
without TPU hardware — the analog of the reference emulating multi-node with
single-host multi-GPU (reference tests/python/test_comm_hooks_fsdp.py via
FSDPTest; SURVEY §4)."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# cost-card capture (obs.cost) defaults ON in the serve engine and
# trainer but costs one extra XLA compile per program — a ~75% wall-time
# tax on engine-heavy tests that assert nothing about cards.  Default it
# OFF for the suite; tests/test_obs_cost.py re-enables per test via
# monkeypatch, and an explicit TDX_COST_CARDS=1 run overrides this.
os.environ.setdefault("TDX_COST_CARDS", "0")

# numerics observatory (obs.numerics): OFF suite-wide for the same
# reason — digest taps fuse extra reductions into every traced program.
# tests/test_numerics.py opts in per test (engine kwarg / monkeypatch),
# and an explicit TDX_NUMERICS=1 run overrides this.
os.environ.setdefault("TDX_NUMERICS", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def mesh8():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()).reshape(8), ("fsdp",))


@pytest.fixture
def mesh2x4():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()).reshape(2, 4), ("node", "local"))


@pytest.fixture(autouse=True)
def _reset_rng():
    import torchdistx_tpu as tdx

    tdx.manual_seed(0)
    yield
