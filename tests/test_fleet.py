"""Fleet routing + lifecycle + disaggregation (ISSUE 13).

The pinned invariants, on the 8-device CPU mesh:

- **The router never perturbs cache state**: ``match_len`` is a pure
  probe — no incref, no LRU tick, no recency touch — so polling every
  replica per request leaves the losers' eviction order exactly as if
  the probe never happened.
- **Affinity routes to warmth, but never into a stall**: the request
  goes to the replica whose radix index matches the longest prefix,
  UNLESS that replica's admission gate (free pages / HBM plan) would
  park it — then headroom wins over warmth.
- **Routing decides where, never what**: a 3-replica fleet serving a
  shared-prefix workload produces greedy streams BIT-identical to one
  engine serving the same requests, under every policy.
- **Scale events drop nothing**: a mid-workload ``fleet.remove()``
  drains the replica through ``migrate_to`` into a survivor; every
  outstanding handle resolves bit-identically.
- **Disaggregated handoff is exact**: prefill(tp=2) -> decode(tp=1) KV
  handoff books ring all-gathers at the ``parallel/reshard.py`` closed
  form (g = 2, wire = unit/2 per layer per k/v per request), summary ==
  comm audit == counters, and the streams match a co-located engine.
"""

import numpy as np
import pytest
from jax.sharding import Mesh

import jax
import torchdistx_tpu as tdx
from torchdistx_tpu.models import Llama
from torchdistx_tpu.obs.comm import CommProfile, comm_audit
from torchdistx_tpu.serve import (
    PagePool,
    RadixPrefixIndex,
    RoundRobinPolicy,
    ServeEngine,
    ServeFleet,
)


def _llama():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


def _tp_mesh(tp):
    return Mesh(np.asarray(jax.devices()[:tp]), ("tp",))


def _engine(tp, slots, paged=False, **kw):
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", (32,))
    kw.setdefault("decode_chunk", 2)
    if paged:
        kw.setdefault("page_size", 8)
        kw.setdefault("num_pages", 32)
    if tp > 1:
        kw["mesh"] = _tp_mesh(tp)
    return ServeEngine(_llama(), num_slots=slots, **kw)


def _kv_unit_bytes(engine):
    arr = engine.cache.kv[0][0]
    return int(np.prod(arr.shape[1:])) * np.dtype(arr.dtype).itemsize


def _shared_prefix_prompts(seed, n, prefix_len=16, tail_len=4):
    """n prompts sharing one page-aligned prefix, distinct tails."""
    rs = np.random.RandomState(seed)
    prefix = rs.randint(0, 256, (prefix_len,)).astype(np.int32)
    return [
        np.concatenate([prefix, rs.randint(0, 256, (tail_len,)).astype(np.int32)])
        for _ in range(n)
    ]


class TestMatchLenProbe:
    """Satellite: the read-only radix probe the router polls with."""

    def _warm_index(self):
        pool = PagePool(16)
        idx = RadixPrefixIndex(4)
        tokens = np.arange(8, dtype=np.int32)
        pages = pool.alloc(2)
        idx.insert(tokens, pages, pool)
        return pool, idx, tokens

    def _snapshot(self, pool, idx):
        def nodes(children):
            for node in children.values():
                yield node
                yield from nodes(node.children)

        return (
            idx._tick,
            [(n.page, n.last_used) for n in nodes(idx._children)],
            [pool.refcount(p) for p in range(pool.num_pages)],
        )

    def test_agrees_with_match_caps_included(self):
        pool, idx, tokens = self._warm_index()
        # full 2-page chain needs a prompt of >= 9 tokens (match caps at
        # len(prompt) - 1, like match itself)
        long = np.concatenate([tokens, tokens])
        assert idx.match_len(long) == 8
        assert idx.match_len(tokens) == 4  # 8 tokens -> 1 full page
        assert idx.match_len(tokens[:4]) == 0
        # divergence after the first page stops the walk
        fork = np.concatenate([tokens[:4], tokens[:4] + 1, tokens[:1]])
        assert idx.match_len(fork) == 4
        miss = np.asarray([9, 9, 9, 9, 9], np.int32)
        assert idx.match_len(miss) == 0
        # and every probe's answer equals what match would hand out
        for p in (long, tokens, fork, miss):
            assert idx.match_len(p) == len(idx.match(p)) * idx.page_size

    def test_probe_has_no_side_effects(self):
        pool, idx, tokens = self._warm_index()
        before = self._snapshot(pool, idx)
        long = np.concatenate([tokens, tokens])
        for p in (long, tokens, np.asarray([9] * 6, np.int32)):
            idx.match_len(p)
        assert self._snapshot(pool, idx) == before
        # ...whereas a real match moves the recency tick
        idx.match(long)
        assert self._snapshot(pool, idx) != before


class TestRouting:
    def test_affinity_routes_to_warm_replica(self):
        engines = [_engine(1, 2, paged=True) for _ in range(3)]
        warm = engines[1]
        prompts = _shared_prefix_prompts(3, 3)
        # warm exactly one replica's radix index with the shared prefix
        warm.run([dict(prompt=prompts[0], max_new_tokens=2)])
        assert warm.prefix_index.match_len(prompts[1]) == 16

        fleet = ServeFleet(engines, policy="affinity")
        warm_rid = fleet.replicas[1].rid
        h = fleet.submit(prompts[1], max_new_tokens=2)
        assert fleet.events[-1][0] == "routed"
        assert fleet.events[-1][2]["replica"] == warm_rid
        assert warm.scheduler.queue_depth == 1
        while fleet.step():
            pass
        assert h.done()

    def test_headroom_beats_warmth_when_warm_replica_page_gated(self):
        # the warm replica's pool is too small for the incoming request
        # even net of its prefix hit: affinity must fall back to a cold
        # replica with headroom instead of routing into a page stall
        warm = _engine(1, 2, paged=True, num_pages=4)  # 3 allocatable
        cold = _engine(1, 2, paged=True, num_pages=32)
        prompts = _shared_prefix_prompts(4, 2, prefix_len=8, tail_len=8)
        warm.run([dict(prompt=prompts[0][:9], max_new_tokens=2)])
        assert warm.prefix_index.match_len(prompts[1]) == 8

        fleet = ServeFleet([warm, cold], policy="affinity")
        # 16-token prompt + 16 new = 4 pages, hit covers 1: needs 3 free
        # but the warm pool holds 3 - (index-held) < 3
        assert warm.pool.free_count < 3
        fleet.submit(prompts[1], max_new_tokens=16)
        assert fleet.events[-1][2]["replica"] == fleet.replicas[1].rid
        assert cold.scheduler.queue_depth == 1
        assert warm.scheduler.queue_depth == 0

    def test_rejection_tiebreak_is_windowed(self):
        # a replica gated once must not be disadvantaged in routing
        # ties forever: the tie-break reads the rejection delta since
        # the last fleet tick, not the lifetime counters
        from torchdistx_tpu.serve.fleet import _load_key

        fleet = ServeFleet(
            [_engine(1, 2), _engine(1, 2)], policy="least-loaded"
        )
        a, b = fleet.replicas
        assert _load_key(a) > _load_key(b)  # idle tie -> lowest rid
        a.engine.metrics.count("admissions_rejected_pages", 3)
        assert a.recent_rejections() == 3
        assert _load_key(a) < _load_key(b)  # fresh rejections repel
        fleet.step()  # the window rolls at the tick boundary
        assert a.recent_rejections() == 0
        assert _load_key(a) > _load_key(b)  # bias gone: tie -> rid
        # an engine with pre-fleet gate history joins unpenalized
        used = _engine(1, 2)
        used.metrics.count("admissions_rejected_hbm", 7)
        fleet.add(used)
        assert fleet.replicas[-1].recent_rejections() == 0

    def test_routed_event_records_candidate_scoring(self):
        """PR 14 satellite: the router's decision is never discarded —
        the affinity pick leaves a ``("routed", ...)`` in the request's
        own lifecycle events carrying the full candidate scoring the
        policy saw (per-replica match_len, headroom tie-break values,
        named skip reasons), mirrored into ``fleet.events`` with the
        trace id."""
        engines = [_engine(1, 2, paged=True) for _ in range(3)]
        warm = engines[1]
        prompts = _shared_prefix_prompts(3, 3)
        warm.run([dict(prompt=prompts[0], max_new_tokens=2)])

        fleet = ServeFleet(engines, policy="affinity")
        warm_rid = fleet.replicas[1].rid
        h = fleet.submit(prompts[1], max_new_tokens=2)
        assert h.trace_id is not None
        name, ts, data = h._request.events[-1]
        assert name == "routed"
        assert data["replica"] == warm_rid
        assert data["policy"] == "affinity"
        by_rid = {c["replica"]: c for c in data["candidates"]}
        assert sorted(by_rid) == [r.rid for r in fleet.replicas]
        assert by_rid[warm_rid]["match_len"] == 16
        assert all(
            c["match_len"] == 0
            for rid, c in by_rid.items()
            if rid != warm_rid
        )
        for c in by_rid.values():
            # the _load_key tuple, JSON-able (no Inf), 5 components
            assert isinstance(c["headroom"], list)
            assert len(c["headroom"]) == 5
            assert c["skip"] is None
        # the fleet event mirrors the request's record + the trace id
        ev_name, ev_ts, ev = fleet.events[-1]
        assert ev_name == "routed" and ev_ts == ts
        assert ev["trace_id"] == h.trace_id
        assert ev["candidates"] == data["candidates"]

    def test_page_gate_skip_and_tiebreak_values_recorded(self):
        """The page-gated warm replica shows up in the scoring with
        skip="pages" AND its own ``route_skipped`` lifecycle event; the
        recorded headroom keys order the winner first among admittable
        candidates."""
        warm = _engine(1, 2, paged=True, num_pages=4)  # 3 allocatable
        cold = _engine(1, 2, paged=True, num_pages=32)
        prompts = _shared_prefix_prompts(4, 2, prefix_len=8, tail_len=8)
        warm.run([dict(prompt=prompts[0][:9], max_new_tokens=2)])

        fleet = ServeFleet([warm, cold], policy="affinity")
        h = fleet.submit(prompts[1], max_new_tokens=16)
        events = h._request.events
        (routed,) = [e for e in events if e[0] == "routed"]
        (skip,) = [e for e in events if e[0] == "route_skipped"]
        # the fleet tick rides every routing event (tick 0 = pre-step)
        assert skip[2] == {
            "rid": fleet.replicas[0].rid,
            "why": "pages",
            "tick": 0,
        }
        assert skip[1] == routed[1]  # one decision, one timestamp
        by_rid = {c["replica"]: c for c in routed[2]["candidates"]}
        assert by_rid[fleet.replicas[0].rid]["skip"] == "pages"
        assert by_rid[fleet.replicas[0].rid]["match_len"] == 8  # warm!
        assert by_rid[fleet.replicas[1].rid]["skip"] is None
        assert routed[2]["replica"] == fleet.replicas[1].rid
        # headroom is comparable as recorded: the admitted replica's
        # key beats the gated one's on free pages (index 2)
        hr_warm = by_rid[fleet.replicas[0].rid]["headroom"]
        hr_cold = by_rid[fleet.replicas[1].rid]["headroom"]
        assert hr_cold[2] > hr_warm[2]

    def test_drain_skip_recorded(self):
        """A draining replica never reaches the policy, but the record
        still answers "why not replica 0": scoring covers it with
        skip="draining"."""
        fleet = ServeFleet(
            [_engine(1, 2), _engine(1, 2)], policy="round-robin"
        )
        fleet.replicas[0].engine._draining = True
        h = fleet.submit(np.arange(6, dtype=np.int32), max_new_tokens=2)
        (skip,) = [
            e for e in h._request.events if e[0] == "route_skipped"
        ]
        assert skip[2] == {
            "rid": fleet.replicas[0].rid,
            "why": "draining",
            "tick": 0,
        }
        (routed,) = [e for e in h._request.events if e[0] == "routed"]
        by_rid = {c["replica"]: c for c in routed[2]["candidates"]}
        assert by_rid[fleet.replicas[0].rid]["skip"] == "draining"
        assert routed[2]["replica"] == fleet.replicas[1].rid

    def test_round_robin_cycles_and_policy_objects_plug_in(self):
        engines = [_engine(1, 2) for _ in range(2)]
        fleet = ServeFleet(engines, policy=RoundRobinPolicy())
        prompts = _shared_prefix_prompts(5, 4)
        for p in prompts:
            fleet.submit(p, max_new_tokens=2)
        assert [e.scheduler.queue_depth for e in engines] == [2, 2]
        with pytest.raises(ValueError, match="unknown policy"):
            ServeFleet(engines, policy="warmest")
        with pytest.raises(TypeError, match="route"):
            ServeFleet(engines, policy=object())


class TestFleetStreams:
    def _workload(self, seed=7, n=6):
        prompts = _shared_prefix_prompts(seed, n)
        mnt = [6, 8, 10, 6, 8, 10][:n]
        return [
            dict(prompt=p, max_new_tokens=m) for p, m in zip(prompts, mnt)
        ]

    @pytest.mark.parametrize("policy", ["affinity", "round-robin"])
    def test_three_replica_fleet_bit_identical_to_single_engine(
        self, policy
    ):
        """The acceptance pin: routing decides where, never what."""
        reqs = self._workload()
        ref = _engine(1, 6, paged=True).run(reqs)
        fleet = ServeFleet(
            [_engine(1, 2, paged=True) for _ in range(3)], policy=policy
        )
        out = fleet.run(reqs)
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(o.tokens, r.tokens)
            assert o.finish_reason == r.finish_reason
        # every replica aggregates into one metrics surface
        j = fleet.metrics_json()
        assert j["counters"]["requests_submitted"] == len(reqs)
        assert j["gauges"]["replicas"] == 3
        assert sum(
            r["requests_routed"] for r in j["fleet"]["replicas"]
        ) == len(reqs)

    def test_remove_mid_workload_drops_nothing(self):
        reqs = self._workload(seed=9)
        ref = _engine(1, 6).run(reqs)
        fleet = ServeFleet([_engine(1, 3) for _ in range(3)],
                           policy="round-robin")
        handles = [fleet.submit(**r) for r in reqs]
        fleet.step()  # requests admitted and mid-stream everywhere
        victim = fleet.replicas[0]
        assert victim.engine.scheduler.running  # it holds live work
        summary = fleet.remove(victim.rid)
        assert summary["replica"] == victim.rid
        assert summary["migrated_running"] + summary["migrated_queued"] >= 1
        assert len(fleet.replicas) == 2
        assert all(r.rid != victim.rid for r in fleet.replicas)
        while fleet.step():
            pass
        for h, r in zip(handles, ref):
            assert h.done()
            np.testing.assert_array_equal(h.result().tokens, r.tokens)
        # a fleet event was logged and the victim stopped admitting
        assert fleet.events[-1][0] == "remove"
        # the retired replica's counters stay in the fleet aggregate
        # (monotonic scrape surface): migrations out are still visible
        j = fleet.metrics_json()
        assert j["counters"]["requests_migrated_out"] >= 1
        assert j["counters"]["requests_migrated_out"] == j["counters"][
            "requests_migrated_in"
        ]
        assert j["counters"]["requests_submitted"] == len(reqs)
        with pytest.raises(RuntimeError, match="draining"):
            victim.engine.submit(np.ones(4, np.int32), max_new_tokens=1)

    def test_scatter_failure_readopts_every_unplaced_request(self):
        """The zero-drop contract's failure path: when a queued request
        fits no survivor, the scatter re-adopts it AND the whole
        drained tail behind it into the victim's queue — nothing ends
        up attached to no scheduler."""
        victim = _engine(1, 2, paged=True, num_pages=32)
        small = _engine(1, 2, paged=True, num_pages=4)  # 3 allocatable
        fleet = ServeFleet([victim, small], policy="round-robin")
        fits = np.arange(8, dtype=np.int32)
        big = np.arange(16, dtype=np.int32)
        # FCFS: [fits, big, fits] — big needs 4 pages, small holds 3
        h_a = victim.submit(fits, max_new_tokens=8)
        h_b = victim.submit(big, max_new_tokens=16)
        h_c = victim.submit(fits + 1, max_new_tokens=8)
        with pytest.raises(RuntimeError, match="could absorb"):
            fleet.remove(fleet.replicas[0].rid)
        # the victim stays in rotation, drained, holding the failing
        # request and the tail behind it in FCFS order; the request
        # placed before the failure stays on the survivor
        assert len(fleet.replicas) == 2
        assert victim._draining
        assert [r.rid for r in victim.scheduler.queued] == [
            h_b.rid, h_c.rid
        ]
        assert [r.rid for r in small.scheduler.queued] == [h_a.rid]
        assert victim.metrics.counters["requests_migrated_out"] == 1
        assert small.metrics.counters["requests_migrated_in"] == 1
        # the re-homed request's handle resolves on the survivor
        for _ in range(12):
            fleet.step()
        assert h_a.done()
        assert not h_b.done() and not h_c.done()  # parked, not dropped

    def test_add_warms_into_rotation(self):
        fleet = ServeFleet([_engine(1, 2)], policy="round-robin")
        rid = fleet.add(_engine(1, 2))
        assert [r.rid for r in fleet.replicas] == [0, rid]
        prompts = _shared_prefix_prompts(11, 2)
        for p in prompts:
            fleet.submit(p, max_new_tokens=2)
        assert all(
            r.engine.scheduler.queue_depth == 1 for r in fleet.replicas
        )
        with pytest.raises(RuntimeError, match="last"):
            fleet.remove(rid), fleet.remove(0)


class TestDisaggregated:
    def test_handoff_streams_bit_identical_wire_exact(self):
        """prefill(tp=2) -> decode(tp=1): streams match a co-located
        engine; handoff wire matches the ring closed form exactly and
        summary == comm audit == counters."""
        reqs = [
            dict(prompt=p, max_new_tokens=m)
            for p, m in zip(_shared_prefix_prompts(13, 4), [6, 8, 6, 8])
        ]
        ref = _engine(1, 4).run(reqs)

        pre = _engine(2, 4)
        dec = _engine(1, 4)
        fleet = ServeFleet(
            [pre, dec], disaggregate=True, roles=["prefill", "decode"]
        )
        prof = CommProfile()
        with comm_audit(prof):
            out = fleet.run(reqs)
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(o.tokens, r.tokens)
        # the prefill role never decoded: it generated exactly the first
        # token of each request, the decode role generated the rest
        assert pre.metrics.counters["tokens_generated"] == len(reqs)
        assert pre.metrics.counters["decode_dispatches"] == 0
        assert dec.metrics.counters["prefill_calls"] == 0
        # every request handed off exactly once, wire closed-form: head
        # axis tp=2 -> tp=1 is gather group g=2, unit/2 per layer per k/v
        n_handoffs = pre.metrics.counters["requests_handed_off"]
        assert n_handoffs == len(reqs)
        assert dec.metrics.counters["requests_handed_in"] == len(reqs)
        unit = _kv_unit_bytes(pre)
        expect = len(reqs) * len(pre.cache.kv) * 2 * (unit // 2)
        assert pre.metrics.counters["handoff_wire_bytes"] == expect
        assert int(prof.wire_bytes("all_gather", "tp")) == expect
        handoffs = [e for e in fleet.events if e[0] == "handoff"]
        assert sum(e[2]["wire_bytes"] for e in handoffs) == expect
        # the prefill engine ends empty: slots freed as requests moved
        assert not pre.scheduler.has_work()

    def test_same_sharding_handoff_books_zero_wire(self):
        reqs = [
            dict(prompt=p, max_new_tokens=4)
            for p in _shared_prefix_prompts(15, 2)
        ]
        ref = _engine(1, 2).run(reqs)
        pre, dec = _engine(1, 2), _engine(1, 2)
        fleet = ServeFleet([pre, dec], disaggregate=True)
        prof = CommProfile()
        with comm_audit(prof):
            out = fleet.run(reqs)
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(o.tokens, r.tokens)
        assert pre.metrics.counters["handoff_wire_bytes"] == 0
        assert int(prof.wire_bytes()) == 0

    def test_disagg_paged_handoff_rehomes_pages(self):
        reqs = [
            dict(prompt=p, max_new_tokens=6)
            for p in _shared_prefix_prompts(17, 3)
        ]
        ref = _engine(1, 3, paged=True).run(reqs)
        pre = _engine(1, 3, paged=True)
        dec = _engine(1, 3, paged=True)
        fleet = ServeFleet([pre, dec], disaggregate=True)
        out = fleet.run(reqs)
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(o.tokens, r.tokens)
        assert pre.metrics.counters["handoff_pages_moved"] > 0
        # source pool holds only what its radix index still caches
        assert pre.pool.in_use == len(pre.prefix_index)

    def test_backpressure_parks_then_places(self):
        # one decode slot, two requests: the second prefill parks under
        # back-pressure and hands off once the first finishes — streams
        # still bit-identical to a co-located engine
        reqs = [
            dict(prompt=p, max_new_tokens=4)
            for p in _shared_prefix_prompts(19, 2)
        ]
        ref = _engine(1, 2).run(reqs)
        pre, dec = _engine(1, 2), _engine(1, 1)
        fleet = ServeFleet([pre, dec], disaggregate=True)
        out = fleet.run(reqs)
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(o.tokens, r.tokens)
        assert pre.metrics.counters["requests_handed_off"] == 2
        # the single decode slot serialized the handoffs across ticks
        handoffs = [e for e in fleet.events if e[0] == "handoff"]
        assert len(handoffs) == 2 and handoffs[0][1] < handoffs[1][1]

    def test_never_fitting_handoff_raises_instead_of_spinning(self):
        # a prefilled page chain larger than every decode pool's TOTAL
        # capacity can never be handed off: step() must raise, not park
        # the request forever while run()'s while-loop spins
        pre = _engine(1, 2, paged=True, num_pages=32)
        dec = _engine(1, 2, paged=True, num_pages=4)  # 3 allocatable
        fleet = ServeFleet([pre, dec], disaggregate=True)
        prompt = np.arange(24, dtype=np.int32)  # + 8 new = 4 pages
        fleet.submit(prompt, max_new_tokens=8)
        with pytest.raises(RuntimeError, match="can never be handed"):
            fleet.step()

    def test_disagg_validation(self):
        with pytest.raises(ValueError, match="at least two"):
            ServeFleet([_engine(1, 2)], disaggregate=True)
        with pytest.raises(ValueError, match="chunked-mode"):
            ServeFleet(
                [
                    ServeEngine(
                        _llama(), num_slots=2, max_len=64,
                        prefill_buckets=(16,),
                        decode_mode="persistent",
                    ),
                    _engine(1, 2),
                ],
                disaggregate=True,
            )
        with pytest.raises(ValueError, match="incompatible"):
            ServeFleet(
                [_engine(1, 2), _engine(1, 2, max_len=32)],
                disaggregate=True,
            )
        with pytest.raises(ValueError, match="require disaggregate"):
            ServeFleet([_engine(1, 2)], roles=["prefill"])
