"""Direct tests of the native graph core through the ctypes ABI.

SURVEY §4 calls out the reference's missing C++-core tests (reference
CMakeLists.txt:104-106 `#TODO: Add catch2 tests`, tests/cc/.gitkeep) and
says this framework should test the recorder/replay engine directly.  These
tests drive both the NativeGraph wrapper and the raw `_lib` C functions so
the error/retry paths of the ABI itself are covered:

  - schedule buffer too small (-1) and retry
  - mark_materialized buffer too small (-(needed)) without mutation, retry
  - -2 on unknown nodes
  - record-on-released rejection (-1 -> RuntimeError)
  - pin/unpin GC sequencing
  - NULL-handle tolerance (finalizer-race hardening)
  - threaded recording

Run under ASan via `bash scripts/run-sanitized-tests`.
"""

import ctypes
import threading

import pytest

from torchdistx_tpu._C import (
    NODE_MATERIALIZED,
    NODE_RECORDED,
    NODE_RELEASED,
    NativeGraph,
    _lib,
)

_i64 = ctypes.c_int64


def _buf(n):
    return (ctypes.c_int64 * n)()


def _mark(g, node):
    """mark_materialized via the wrapper (handles retries)."""
    return g.mark_materialized(node)


class TestRecordAndSchedule:
    def test_chronological_ids(self):
        g = NativeGraph()
        ids = [g.record_op(f"op{i}", [], 1) for i in range(5)]
        assert ids == [0, 1, 2, 3, 4]
        assert g.num_nodes() == 5

    def test_dep_filtering_dupes_and_negatives(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        b = g.record_op("b", [a, a, -1, -1], 1)
        assert g.deps(b) == [a]
        assert g.dependents(a) == [b]

    def test_schedule_is_transitive_closure_in_order(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        b = g.record_op("b", [a], 1)
        c = g.record_op("c", [a], 1)
        d = g.record_op("d", [b, c], 1)
        assert g.collect_schedule(d) == [a, b, c, d]
        assert g.collect_schedule(a) == [a]

    def test_schedule_skips_materialized_deps(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        b = g.record_op("b", [a], 1)
        g.pin(a)  # keep a's cache alive
        _mark(g, a)
        assert g.collect_schedule(b) == [b]

    def test_schedule_of_materialized_is_empty(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        g.pin(a)
        _mark(g, a)
        assert g.collect_schedule(a) == []

    def test_schedule_buffer_retry_abi(self):
        # raw ABI: cap smaller than the schedule returns -1 and must not
        # write past the buffer; a second call with enough room succeeds
        g = NativeGraph()
        ids = []
        prev = []
        for i in range(10):
            ids.append(g.record_op(f"n{i}", prev, 1))
            prev = [ids[-1]]
        small = _buf(4)
        n = _lib.tdx_collect_schedule(g._h, ids[-1], small, 4)
        assert n == -1
        big = _buf(16)
        n = _lib.tdx_collect_schedule(g._h, ids[-1], big, 16)
        assert n == 10
        assert list(big[:10]) == ids

    def test_unknown_node_minus_two(self):
        g = NativeGraph()
        out = _buf(4)
        assert _lib.tdx_collect_schedule(g._h, 99, out, 4) == -2
        assert _lib.tdx_get_deps(g._h, 99, out, 4) == -2
        with pytest.raises(RuntimeError, match="unknown node"):
            g.collect_schedule(99)
        with pytest.raises(KeyError):
            g.deps(99)


class TestMaterializeAndGC:
    def test_mark_materialized_releases_unpinned_chain(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        b = g.record_op("b", [a], 1)
        # no pins anywhere: materializing a keeps it (b still needs it),
        # materializing b releases both
        assert _mark(g, a) == []
        assert g.node_state(a) == NODE_MATERIALIZED
        released = _mark(g, b)
        assert set(released) == {a, b}
        assert g.node_state(a) == NODE_RELEASED
        assert g.node_state(b) == NODE_RELEASED
        assert g.num_released() == 2

    def test_pin_blocks_release_unpin_releases(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        g.pin(a)
        assert _mark(g, a) == []  # pinned: kept
        assert g.node_state(a) == NODE_MATERIALIZED
        assert g.unpin(a) is True  # last unpin: now releasable
        assert g.node_state(a) == NODE_RELEASED

    def test_nested_pins(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        g.pin(a)
        g.pin(a)
        _mark(g, a)
        assert g.unpin(a) is False  # one handle still live
        assert g.node_state(a) == NODE_MATERIALIZED
        assert g.unpin(a) is True

    def test_unpin_before_materialize_keeps_recorded(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        g.pin(a)
        assert g.unpin(a) is False  # recorded nodes never release via unpin
        assert g.node_state(a) == NODE_RECORDED

    def test_mark_materialized_buffer_retry_abi_no_mutation(self):
        # >cap releasable ids: returns -(needed) WITHOUT committing, so the
        # caller can retry; after retry all are released exactly once
        g = NativeGraph()
        leaves = [g.record_op(f"l{i}", [], 1) for i in range(100)]
        consumer = g.record_op("c", leaves, 1)
        for leaf in leaves:
            assert _mark(g, leaf) == []
        small = _buf(8)
        n = _lib.tdx_mark_materialized(g._h, consumer, small, 8)
        assert n == -(100 + 1)
        # nothing was mutated by the failed call
        assert g.node_state(consumer) == NODE_RECORDED
        assert g.num_released() == 0
        big = _buf(101)
        n = _lib.tdx_mark_materialized(g._h, consumer, big, 101)
        assert n == 101
        assert set(big[:101]) == set(leaves) | {consumer}
        assert g.num_released() == 101

    def test_double_mark_is_noop(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        g.pin(a)
        _mark(g, a)
        assert _mark(g, a) == []
        assert g.num_materialized() == 1

    def test_record_on_released_rejected(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        b = g.record_op("b", [a], 1)
        _mark(g, a)
        _mark(g, b)  # releases both
        assert g.node_state(a) == NODE_RELEASED
        with pytest.raises(RuntimeError, match="released"):
            g.record_op("late", [a], 1)
        # rejection leaves the graph untouched
        assert g.num_nodes() == 2

    def test_rejected_record_with_mixed_deps_mutates_nothing(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        b = g.record_op("b", [], 1)
        _mark(g, a)  # a has no dependents/pins: released immediately
        assert g.node_state(a) == NODE_RELEASED
        before = g.dependents(b)
        with pytest.raises(RuntimeError):
            g.record_op("bad", [b, a], 1)
        assert g.dependents(b) == before  # validate-before-mutate


class TestMeta:
    def test_output_meta_roundtrip(self):
        g = NativeGraph()
        a = g.record_op("a", [], 2)
        g.set_output_meta(a, 0, (3, 4), 7)
        g.set_output_meta(a, 1, (), 2)
        assert g.get_output_meta(a, 0) == ((3, 4), 7)
        assert g.get_output_meta(a, 1) == ((), 2)

    def test_meta_bad_index(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        with pytest.raises(KeyError):
            g.get_output_meta(a, 5)
        with pytest.raises(KeyError):
            g.get_output_meta(42, 0)

    def test_name_roundtrip(self):
        g = NativeGraph()
        a = g.record_op("kaiming_uniform", [], 1)
        assert g.name(a) == "kaiming_uniform"
        assert g.name(123) == ""


class TestNullHandleHardening:
    def test_all_entry_points_tolerate_null(self):
        # finalizer-race hardening: during cyclic GC the graph can be freed
        # before a FakeArray finalizer calls back in; NULL must be a no-op
        out = _buf(4)
        code = ctypes.c_int32()
        assert _lib.tdx_record_op(None, b"x", out, 0, 1) == -1
        _lib.tdx_set_output_meta(None, 0, 0, out, 0, 0)
        assert _lib.tdx_get_output_meta(None, 0, 0, out, 4, ctypes.byref(code)) == -1
        assert _lib.tdx_collect_schedule(None, 0, out, 4) == -2
        assert _lib.tdx_mark_materialized(None, 0, out, 4) == 0
        assert _lib.tdx_node_state(None, 0) == -1
        _lib.tdx_pin(None, 0)
        assert _lib.tdx_unpin(None, 0) == 0
        assert _lib.tdx_num_nodes(None) == 0
        assert _lib.tdx_get_deps(None, 0, out, 4) == -2
        _lib.tdx_graph_free(None)

    def test_wrapper_tolerates_freed_graph(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)
        g.__del__()  # simulate GC order: graph finalized first
        g.pin(a)  # must not crash
        assert g.unpin(a) is False


class TestThreadedRecord:
    def test_concurrent_recording_unique_ids(self):
        g = NativeGraph()
        ids: list[list[int]] = [[] for _ in range(8)]

        def worker(k):
            for i in range(200):
                ids[k].append(g.record_op(f"t{k}_{i}", [], 1))

        threads = [
            threading.Thread(target=worker, args=(k,)) for k in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        flat = [i for sub in ids for i in sub]
        assert len(flat) == 1600
        assert len(set(flat)) == 1600  # no duplicate ids under contention
        assert g.num_nodes() == 1600
        # per-thread ids are monotonically increasing (chronological)
        for sub in ids:
            assert sub == sorted(sub)

    def test_concurrent_pin_unpin(self):
        g = NativeGraph()
        a = g.record_op("a", [], 1)

        def worker():
            for _ in range(1000):
                g.pin(a)
                g.unpin(a)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert g.node_state(a) == NODE_RECORDED  # balanced: still recorded
        g.pin(a)
        _mark(g, a)
        assert g.unpin(a) is True


class TestRandomizedInvariants:
    """Property-style stress: random DAGs under random pin/materialize/
    unpin interleavings must preserve the core invariants the replay
    engine relies on (states only move recorded -> materialized ->
    released; a node is released only when materialized, unpinned, and
    free of unmaterialized dependents; a schedule is exactly the
    unmaterialized transitive dependency closure)."""

    def _closure(self, deps_of, target, materialized):
        out, stack = set(), [target]
        while stack:
            n = stack.pop()
            if n in out or n in materialized:
                continue
            out.add(n)
            stack.extend(d for d in deps_of[n] if d not in out)
        return out

    def test_random_dags(self):
        import random as pyrandom

        rng = pyrandom.Random(1234)
        for trial in range(25):
            g = NativeGraph()
            n_nodes = rng.randint(5, 40)
            deps_of = {}
            pins = {}
            for i in range(n_nodes):
                k = rng.randint(0, min(i, 4))
                deps = rng.sample(range(i), k) if k else []
                nid = g.record_op(f"n{i}", deps, 1)
                assert nid == i
                deps_of[nid] = deps
                pins[nid] = 0
                if rng.random() < 0.5:
                    g.pin(nid)
                    pins[nid] += 1

            materialized: set = set()
            released: set = set()

            def model_release_check():
                for n in range(n_nodes):
                    s = g.node_state(n)
                    if s == NODE_RELEASED:
                        assert n in materialized, (trial, n, "released before mat")
                        assert pins[n] == 0, (trial, n, "released while pinned")
                    if n in released:
                        assert s == NODE_RELEASED, (trial, n, "resurrected")

            for _ in range(3 * n_nodes):
                op = rng.random()
                n = rng.randrange(n_nodes)
                if op < 0.4 and n not in materialized:
                    # materialize: check the schedule first
                    sched = g.collect_schedule(n)
                    expect = self._closure(deps_of, n, materialized)
                    assert set(sched) == expect, (trial, n)
                    assert sched == sorted(sched)
                    for m in sched:
                        released.update(g.mark_materialized(m))
                        materialized.add(m)
                elif op < 0.7 and pins[n] > 0:
                    if g.unpin(n):
                        released.add(n)
                    pins[n] -= 1
                elif op < 0.85:
                    if g.node_state(n) != NODE_RELEASED:
                        g.pin(n)
                        pins[n] += 1
                model_release_check()

            # drain: everything materializes, all pins drop -> all released
            for n in range(n_nodes):
                if n not in materialized:
                    for m in g.collect_schedule(n):
                        released.update(g.mark_materialized(m))
                        materialized.add(m)
            for n in range(n_nodes):
                while pins[n] > 0:
                    g.unpin(n)
                    pins[n] -= 1
            assert g.num_materialized() == n_nodes
            assert g.num_released() == n_nodes
