"""Self-speculative multi-token decoding (ISSUE 11).

The load-bearing invariants, pinned on the 8-device CPU mesh:

- **Greedy losslessness**: a ``speculate=K`` engine emits BIT-identical
  token streams to the ``speculate=0`` engine across slab/paged x
  chunked/persistent x occupancy — the verify block's row 0 IS the
  one-token forward (every op on the CPU f32 decode path is
  query-row-independent), and accepted rows match the greedy argmax by
  construction.  Sampled (temperature > 0) slots are forced to accept
  length 0, so their fold_in key schedule — and therefore their streams
  — are untouched.
- **Truncation law**: the device-side accepted count is
  ``e = max(1, min(1 + matches, first_eos, budget_left, room_left))``,
  so any finish condition lands exactly on a block's LAST emitted token
  and the host walk never has to split a block (pinned directly against
  ``_make_spec_decode_body`` with a deterministic chain-model stub).
- **KV safety under variable advance**: rejected-lane writes land
  beyond the live depth (overwritten before any accepted token can see
  them) or are DROPPED past the slot's row span — never clamped onto
  the last row, never wrapped into a neighbor slot
  (``scatter_slot_tokens`` / ``paged_scatter_tokens``).
- **Sync discipline**: speculation multiplies tokens per sync; it never
  adds one.  ``host_syncs == ring_drains`` in persistent mode, and the
  draft-economy counters obey ``accepted + rejected_lanes == proposed``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import torchdistx_tpu as tdx
from torchdistx_tpu.generation import (
    _make_decode_body,
    _make_slot_sampler,
    _make_spec_decode_body,
)
from torchdistx_tpu.models import GPT2, Llama
from torchdistx_tpu.serve import ServeEngine

_ULP = 3e-7  # ~2 f32 ulps at unit scale (test_decode_attention.py)


def _llama():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


def _llama_tp():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", max_seq_len=64)


def _gpt2():
    tdx.manual_seed(11)
    return GPT2.from_name("tiny")


def _tp_mesh(tp):
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:tp]), ("tp",))


def _cyclic_prompts():
    """Prompts whose tiny-Llama greedy continuations enter short cycles
    within ~10 tokens — the repetition self-speculation feeds on (the
    vLLM prompt-lookup workload, in miniature)."""
    return [
        np.array([3, 1, 2, 3, 1, 2, 3], np.int32),
        np.array([9, 9, 9, 9], np.int32),
        np.array([5, 7, 5, 7, 5], np.int32),
    ]


def _run(build, max_new=24, temps=None, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 64)
    engine = ServeEngine(build(), **kw)
    reqs = [
        {"prompt": p, "max_new_tokens": max_new} for p in _cyclic_prompts()
    ]
    if temps:
        for r, t in zip(reqs, temps):
            r["temperature"] = t
            r["seed"] = 7
    results = engine.run(reqs)
    return [list(map(int, r.tokens)) for r in results], engine


# --------------------------------------------------------------------------
# the truncation law, pinned directly against the device body
# --------------------------------------------------------------------------


class _ChainModel:
    """Deterministic ``forward_decode`` stub: next token after ``t`` is
    ``(t + 1) % vocab``, emitted as one-hot logits.  The KV pytree is
    passed through untouched — the stub isolates the body's draft/
    verify/truncate arithmetic from any real attention."""

    def __init__(self, vocab):
        self.vocab = vocab

    def forward_decode(self, tokens, cache, positions, page_tables=None):
        nxt = (tokens + 1) % self.vocab
        return jax.nn.one_hot(nxt, self.vocab, dtype=jnp.float32) * 10.0, cache


class TestSpecBodyTruncationLaw:
    V, MAX_LEN, K = 8, 32, 4

    def _step(self, eos=None):
        return _make_spec_decode_body(
            _ChainModel(self.V),
            _make_slot_sampler(jnp.int32, None, None),
            eos_token=eos,
            max_len=self.MAX_LEN,
            speculate=self.K,
            ngram=2,
        )

    def _carry(self, pos, stp=0, tok=None):
        # history = the 0..V-1 chain repeated up to (excluding) pos, so
        # the trailing bigram always has an earlier occurrence and the
        # drafts are exactly the true continuation
        hist = jnp.zeros((1, self.MAX_LEN), jnp.int32)
        hist = hist.at[0, :pos].set(jnp.arange(pos, dtype=jnp.int32) % self.V)
        if tok is None:
            tok = pos % self.V
        return (
            [],  # kv: the stub passes it through
            jnp.asarray([tok], jnp.int32),
            jnp.asarray([pos], jnp.int32),
            jnp.asarray([stp], jnp.int32),
            jnp.asarray([False]),
            hist,
        )

    def _apply(self, step, carry, budget=100, temp=0.0):
        return step(
            {},
            jnp.asarray([temp], jnp.float32),
            jnp.asarray([3], jnp.int32),
            jnp.asarray([budget], jnp.int32),
            (),
            carry,
        )

    def test_full_accept_emits_k_plus_one(self):
        (kv, tok, pos, stp, fin, hist), y, cnt = self._apply(
            self._step(), self._carry(pos=11)
        )
        np.testing.assert_array_equal(np.asarray(y)[0], [4, 5, 6, 7, 0])
        assert int(cnt[0]) == self.K + 1
        assert int(tok[0]) == 0 and int(pos[0]) == 16 and int(stp[0]) == 5
        assert not bool(fin[0])
        # the accepted tokens landed in the history at their stream index
        np.testing.assert_array_equal(
            np.asarray(hist)[0, 12:16], [4, 5, 6, 7]
        )

    def test_eos_inside_accepted_block_truncates(self):
        # continuation from 3 is 4,5,6,7,0 — eos=6 sits at block index 3
        (kv, tok, pos, stp, fin, hist), y, cnt = self._apply(
            self._step(eos=6), self._carry(pos=11)
        )
        assert int(cnt[0]) == 3  # 4, 5, then the EOS — nothing after
        assert int(tok[0]) == 6 and bool(fin[0])
        assert int(pos[0]) == 14 and int(stp[0]) == 3
        # rejected-lane history rows were never written
        np.testing.assert_array_equal(np.asarray(hist)[0, 15:17], [0, 0])

    def test_budget_exhausted_mid_block_truncates(self):
        (kv, tok, pos, stp, fin, hist), y, cnt = self._apply(
            self._step(), self._carry(pos=11, stp=0), budget=2
        )
        assert int(cnt[0]) == 2 and int(tok[0]) == 5
        assert bool(fin[0]) and int(stp[0]) == 2

    def test_cache_room_clamps_the_block(self):
        (kv, tok, pos, stp, fin, hist), y, cnt = self._apply(
            self._step(), self._carry(pos=self.MAX_LEN - 2)
        )
        assert int(cnt[0]) == 2  # only 2 rows of cache left
        assert bool(fin[0])  # slot is full: frozen from here on

    def test_no_ngram_match_falls_back_to_one_token(self):
        # two tokens of history cannot contain an EARLIER bigram match
        carry = self._carry(pos=1, tok=9 % self.V)
        (kv, tok, pos, stp, fin, hist), y, cnt = self._apply(
            self._step(), carry
        )
        assert int(cnt[0]) == 1 and int(pos[0]) == 2
        assert int(tok[0]) == (9 + 1) % self.V

    def test_sampled_row_reduces_to_nonspec_body(self):
        # temperature > 0 forces accept length 0; the one emitted token
        # and the carry advance must equal _make_decode_body's exactly
        # (same sampler, same fold_in(seed, stp) key)
        ref_step = _make_decode_body(
            _ChainModel(self.V),
            _make_slot_sampler(jnp.int32, None, None),
            eos_token=None,
            max_len=self.MAX_LEN,
        )
        kv, tok, pos, stp, fin, hist = self._carry(pos=11)
        temps = jnp.asarray([1.3], jnp.float32)
        seeds = jnp.asarray([3], jnp.int32)
        budgets = jnp.asarray([100], jnp.int32)
        _, rtok, rpos, rstp, rfin = ref_step(
            {}, temps, seeds, budgets, (), (kv, tok, pos, stp, fin)
        )
        (_, stok, spos, sstp, sfin, _), y, cnt = self._apply(
            self._step(), (kv, tok, pos, stp, fin, hist), temp=1.3
        )
        assert int(cnt[0]) == 1
        assert int(stok[0]) == int(rtok[0]) == int(np.asarray(y)[0, 0])
        assert int(spos[0]) == int(rpos[0])
        assert int(sstp[0]) == int(rstp[0])


# --------------------------------------------------------------------------
# multi-token KV scatter: drop semantics, never clamp, never wrap
# --------------------------------------------------------------------------


class TestMultiTokenScatter:
    def test_slab_scatter_drops_overflow_rows(self):
        from torchdistx_tpu.serve.kv_cache import scatter_slot_tokens

        rs = np.random.RandomState(0)
        cache = jnp.zeros((2, 8, 2, 4), jnp.float32)
        x = jnp.asarray(rs.randn(2, 4, 2, 4), jnp.float32)
        out = np.asarray(
            scatter_slot_tokens(cache, x, jnp.asarray([6, 1], jnp.int32))
        )
        # slot 0 at pos 6: rows 6, 7 written; rows 8, 9 DROPPED — not
        # clamped onto row 7, not wrapped into slot 1's row 0/1
        np.testing.assert_array_equal(out[0, 6], np.asarray(x)[0, 0])
        np.testing.assert_array_equal(out[0, 7], np.asarray(x)[0, 1])
        np.testing.assert_array_equal(out[0, :6], 0)
        np.testing.assert_array_equal(out[1, 1:5], np.asarray(x)[1])
        np.testing.assert_array_equal(out[1, 0], 0)
        np.testing.assert_array_equal(out[1, 5:], 0)

    def test_paged_scatter_routes_through_tables_and_drops(self):
        from torchdistx_tpu.serve.kv_cache import paged_scatter_tokens

        rs = np.random.RandomState(1)
        ps, npages = 4, 6
        pool = jnp.zeros((npages, ps, 2, 4), jnp.float32)
        x = jnp.asarray(rs.randn(2, 3, 2, 4), jnp.float32)
        # slot 0: pages [2, 5], logical span 8 rows; slot 1: pages [4, 1]
        tables = jnp.asarray([[2, 5], [4, 1]], jnp.int32)
        out = np.asarray(
            paged_scatter_tokens(
                pool, x, tables, jnp.asarray([3, 6], jnp.int32), ps
            )
        )
        xx = np.asarray(x)
        # slot 0 offsets 3,4,5 -> page 2 row 3, page 5 rows 0,1
        np.testing.assert_array_equal(out[2, 3], xx[0, 0])
        np.testing.assert_array_equal(out[5, 0], xx[0, 1])
        np.testing.assert_array_equal(out[5, 1], xx[0, 2])
        # slot 1 offsets 6,7 -> page 1 rows 2,3; offset 8 is past the
        # table span: DROPPED, not clamped into the last page
        np.testing.assert_array_equal(out[1, 2], xx[1, 0])
        np.testing.assert_array_equal(out[1, 3], xx[1, 1])
        np.testing.assert_array_equal(out[4], 0)  # untouched page
        np.testing.assert_array_equal(out[0], 0)
        np.testing.assert_array_equal(out[3], 0)


# --------------------------------------------------------------------------
# the (B, S) verify attention: jnp block path and the pallas kernels
# --------------------------------------------------------------------------


class TestVerifyBlockAttention:
    def _case(self, rs, b, s, hq, hkv, d, max_seq, positions):
        q = jnp.asarray(rs.randn(b, s, hq, d), jnp.float32)
        ck = jnp.asarray(rs.randn(b, max_seq, hkv, d), jnp.float32)
        cv = jnp.asarray(rs.randn(b, max_seq, hkv, d), jnp.float32)
        return q, ck, cv, jnp.asarray(positions, jnp.int32)

    def test_block_row_i_matches_single_token_at_depth(self):
        # row i of the (B, S) block attention equals the (B, 1)
        # attention at depth pos + i on the same cache — to f32 ulp,
        # not bitwise: every op in the chain is query-row-independent
        # mathematically, but XLA lowers the S=1 and S=3 contractions
        # differently (matvec vs batched matmul accumulation order).
        # The engine-level identity tests pin the thing that must be
        # EXACT — the emitted token streams.
        from torchdistx_tpu.ops.attention import (
            _slot_attend,
            _slot_attend_block,
        )

        rs = np.random.RandomState(2)
        b, s, hq, hkv, d, max_seq = 2, 3, 4, 2, 8, 16
        q, ck, cv, pos = self._case(rs, b, s, hq, hkv, d, max_seq, [5, 9])
        blk = _slot_attend_block(q, ck, cv, pos, 1.0 / np.sqrt(d))
        for i in range(s):
            one = _slot_attend(
                q[:, i : i + 1], ck, cv, pos + i, 1.0 / np.sqrt(d), None
            )
            np.testing.assert_allclose(
                np.asarray(blk)[:, i],
                np.asarray(one)[:, 0],
                rtol=_ULP,
                atol=_ULP,
            )

    @pytest.mark.parametrize("hq,hkv,s", [(4, 2, 2), (4, 4, 3), (8, 2, 5)])
    def test_block_kernel_matches_jnp_path(self, hq, hkv, s):
        from torchdistx_tpu.ops.attention import _slot_attend_block
        from torchdistx_tpu.ops.decode_attention import (
            decode_attention_block,
        )

        rs = np.random.RandomState(hq * 100 + hkv * 10 + s)
        b, d, max_seq = 2, 8, 64
        q, ck, cv, pos = self._case(
            rs, b, s, hq, hkv, d, max_seq, [37, max_seq - s]
        )
        ref = _slot_attend_block(q, ck, cv, pos, 1.0 / np.sqrt(d))
        for block_k in (16, 512):  # multi-block online softmax AND 1-block
            out = decode_attention_block(
                q, ck, cv, pos, block_k=block_k, interpret=True
            )
            np.testing.assert_allclose(out, ref, rtol=_ULP, atol=_ULP)

    def test_block_kernel_position_zero(self):
        from torchdistx_tpu.ops.attention import _slot_attend_block
        from torchdistx_tpu.ops.decode_attention import (
            decode_attention_block,
        )

        rs = np.random.RandomState(5)
        q, ck, cv, pos = self._case(rs, 2, 3, 4, 2, 8, 16, [0, 13])
        ref = _slot_attend_block(q, ck, cv, pos, 1.0 / np.sqrt(8))
        out = decode_attention_block(q, ck, cv, pos, interpret=True)
        np.testing.assert_allclose(out, ref, rtol=_ULP, atol=_ULP)

    @pytest.mark.parametrize("s", [2, 4])
    def test_paged_block_kernel_matches_slab_reference(self, s):
        from torchdistx_tpu.ops.attention import _slot_attend_block
        from torchdistx_tpu.ops.decode_attention import (
            paged_decode_attention_block,
        )

        rs = np.random.RandomState(s)
        b, hq, hkv, d, ps, pp = 2, 4, 2, 8, 8, 4
        q = jnp.asarray(rs.randn(b, s, hq, d), jnp.float32)
        pool_k = jnp.asarray(rs.randn(pp * b, ps, hkv, d), jnp.float32)
        pool_v = jnp.asarray(rs.randn(pp * b, ps, hkv, d), jnp.float32)
        tables = jnp.asarray([[0, 2, 4, 6], [1, 3, 5, 7]], jnp.int32)
        pos = jnp.asarray([13, pp * ps - s], jnp.int32)
        # slab reference: gather each slot's logical rows from the pools
        gather = lambda pool: pool.reshape(-1, hkv, d)[
            (tables[:, :, None] * ps + jnp.arange(ps)[None, None, :])
            .reshape(b, pp * ps)
        ]
        ref = _slot_attend_block(
            q, gather(pool_k), gather(pool_v), pos, 1.0 / np.sqrt(d)
        )
        out = paged_decode_attention_block(
            q, pool_k, pool_v, tables, pos, interpret=True
        )
        np.testing.assert_allclose(out, ref, rtol=_ULP, atol=_ULP)


# --------------------------------------------------------------------------
# engine-level greedy losslessness
# --------------------------------------------------------------------------


class TestSpecEngineIdentity:
    def test_chunked_slab_identity_fast(self):
        base, eng0 = _run(_llama, decode_mode="chunked")
        spec, eng = _run(_llama, decode_mode="chunked", speculate=2)
        assert spec == base
        c = eng.metrics.counters
        assert c["draft_tokens_proposed"] > 0
        assert c["draft_tokens_accepted"] > 0
        assert c["host_syncs"] <= eng0.metrics.counters["host_syncs"]

    def test_persistent_slab_identity_fast(self):
        base, eng0 = _run(_llama, decode_mode="persistent")
        spec, eng = _run(_llama, decode_mode="persistent", speculate=2)
        assert spec == base
        c = eng.metrics.counters
        assert c["draft_tokens_accepted"] > 0
        # speculation multiplies tokens per sync — it never adds one
        assert c["host_syncs"] == eng0.metrics.counters["host_syncs"]
        assert c["host_syncs"] == c["ring_drains"]

    def test_persistent_fewer_loop_iterations(self):
        _, eng0 = _run(_llama, decode_mode="persistent")
        _, eng = _run(_llama, decode_mode="persistent", speculate=4)
        assert (
            eng.metrics.counters["loop_iterations"]
            < eng0.metrics.counters["loop_iterations"]
        )
        atpi = eng.metrics.to_json()["derived"][
            "accepted_tokens_per_iteration"
        ]
        assert atpi is not None and atpi > 1.0

    def test_paged_identity_fast(self):
        base, _ = _run(_llama, decode_mode="persistent")
        spec, _ = _run(
            _llama, decode_mode="persistent", speculate=2, page_size=8
        )
        assert spec == base

    def test_gpt2_identity_fast(self):
        base, _ = _run(_gpt2, decode_mode="persistent")
        spec, eng = _run(_gpt2, decode_mode="persistent", speculate=2)
        assert spec == base
        assert eng.metrics.counters["draft_tokens_proposed"] > 0

    @pytest.mark.slow
    @pytest.mark.parametrize("mode", ["chunked", "persistent"])
    @pytest.mark.parametrize("page_size", [None, 8])
    @pytest.mark.parametrize("speculate", [2, 4])
    @pytest.mark.parametrize("num_slots", [2, 4])
    def test_identity_grid(self, mode, page_size, speculate, num_slots):
        kw = dict(decode_mode=mode, num_slots=num_slots)
        if page_size is not None:
            kw["page_size"] = page_size
        if mode == "chunked":
            kw["decode_chunk"] = 2
        base, _ = _run(_llama, **kw)
        spec, _ = _run(_llama, speculate=speculate, **kw)
        assert spec == base

    def test_sampled_streams_identical_at_accept_zero(self):
        temps = [0.9, 0.0, 1.4]
        for mode in ("chunked", "persistent"):
            base, _ = _run(_llama, decode_mode=mode, temps=temps)
            spec, eng = _run(
                _llama, decode_mode=mode, speculate=2, temps=temps
            )
            assert spec == base
            # the greedy slot still speculates; the sampled ones add
            # proposals (every live iteration proposes) but no accepts
            # beyond what the greedy rows earn
            assert eng.metrics.counters["draft_tokens_proposed"] > 0

    def test_eos_stop_identical(self):
        def go(speculate):
            engine = ServeEngine(
                _llama(),
                num_slots=2,
                max_len=64,
                eos_token=163,
                decode_mode="persistent",
                speculate=speculate,
            )
            res = engine.run(
                [
                    {"prompt": p, "max_new_tokens": 24}
                    for p in _cyclic_prompts()
                ]
            )
            return [(list(map(int, r.tokens)), r.finish_reason) for r in res]

        base, spec = go(0), go(4)
        assert spec == base
        assert any(reason == "stop" for _, reason in base)


# --------------------------------------------------------------------------
# rejected-lane KV virginity
# --------------------------------------------------------------------------


class TestRejectedLaneKV:
    def test_live_rows_match_nonspec(self):
        # rejected-lane writes land beyond the live depth and are
        # overwritten before any accepted token can attend to them —
        # so every REAL row of a finished slot holds the SAME token's
        # K/V projection as the non-speculative engine's, to f32 ulp
        # (the projections run through a (B, K+1) matmul vs a (B, 1)
        # one, so XLA's accumulation order differs; a rejected-lane
        # row surviving would differ at O(1), not O(ulp)).  The
        # stream's last token is never written back (the slot finishes
        # instead), so the real rows are prompt + gen[:-1] == depth-1
        # of them; the row AT depth-1 is each engine's frozen-slot
        # garbage row (non-spec keeps writing it at the frozen pos
        # while other slots decode) and legitimately differs.
        prompts = _cyclic_prompts()
        caches = {}
        for K in (0, 4):
            engine = ServeEngine(
                _llama(),
                num_slots=len(prompts),
                max_len=64,
                decode_mode="persistent",
                speculate=K,
            )
            engine.run([{"prompt": p, "max_new_tokens": 12} for p in prompts])
            caches[K] = engine
        for slot, p in enumerate(prompts):
            real = p.size + 12 - 1
            for (k0, v0), (k1, v1) in zip(
                caches[0].cache.kv, caches[4].cache.kv
            ):
                np.testing.assert_allclose(
                    np.asarray(k0)[slot, :real],
                    np.asarray(k1)[slot, :real],
                    rtol=_ULP,
                    atol=_ULP,
                )
                np.testing.assert_allclose(
                    np.asarray(v0)[slot, :real],
                    np.asarray(v1)[slot, :real],
                    rtol=_ULP,
                    atol=_ULP,
                )

    def test_overflow_never_corrupts_neighbor_slot(self):
        # slot 0 decodes all the way to max_len with K=4 drafts — the
        # final blocks' rejected lanes index past the slab row span and
        # must be DROPPED.  A clamp or flat-index wrap would land them
        # in slot 1's live rows, so slot 1's long-running stream is the
        # corruption detector: both streams must stay bit-identical to
        # the non-speculative engine's.
        reqs = [
            {"prompt": np.array([9, 9, 9, 9], np.int32),
             "max_new_tokens": 60},
            {"prompt": np.array([3, 1, 2, 3, 1, 2, 3], np.int32),
             "max_new_tokens": 40},
        ]

        def go(K):
            engine = ServeEngine(
                _llama(),
                num_slots=2,
                max_len=64,
                decode_mode="persistent",
                speculate=K,
            )
            res = engine.run([dict(r) for r in reqs])
            return [
                (list(map(int, r.tokens)), r.finish_reason) for r in res
            ]

        base, spec = go(0), go(4)
        assert spec == base
        assert len(spec[0][0]) == 60  # slot 0 really hit the boundary


# --------------------------------------------------------------------------
# counters, gauges, config plumbing
# --------------------------------------------------------------------------


class TestSpecMetrics:
    def test_counter_identity_and_derived(self):
        _, eng = _run(_llama, decode_mode="persistent", speculate=2)
        c = eng.metrics.counters
        assert (
            c["draft_tokens_accepted"] + c["spec_rejected_lane_steps"]
            == c["draft_tokens_proposed"]
        )
        j = eng.metrics.to_json()
        assert j["gauges"]["speculate"] == 2
        prop, acc = c["draft_tokens_proposed"], c["draft_tokens_accepted"]
        assert j["derived"]["accept_rate"] == acc / prop
        assert (
            j["derived"]["accepted_tokens_per_iteration"]
            == 1.0 + acc * 2 / prop
        )

    def test_nonspec_engine_reports_zero_and_no_gauge(self):
        _, eng = _run(_llama, decode_mode="persistent")
        j = eng.metrics.to_json()
        assert j["counters"]["draft_tokens_proposed"] == 0
        assert "speculate" not in j["gauges"]
        assert j["derived"]["accept_rate"] is None
        assert j["derived"]["accepted_tokens_per_iteration"] is None

    def test_prometheus_collector_exports_spec_family(self):
        from torchdistx_tpu.obs.metrics import (
            MetricsRegistry,
            parse_prometheus,
        )

        _, eng = _run(_llama, decode_mode="persistent", speculate=2)
        reg = MetricsRegistry()
        reg.register_collector(eng.metrics.collector(), obj=eng.metrics)
        parsed = parse_prometheus(reg.render())
        samples = parsed["samples"]
        c = eng.metrics.counters
        assert (
            samples[("tdx_serve_draft_tokens_proposed_total", ())]
            == c["draft_tokens_proposed"]
        )
        assert (
            samples[("tdx_serve_draft_tokens_accepted_total", ())]
            == c["draft_tokens_accepted"]
        )
        assert samples[("tdx_serve_speculate", ())] == 2
        assert parsed["types"]["tdx_serve_draft_tokens_proposed_total"] == (
            "counter"
        )

    def test_reset_metrics_preserves_spec_gauges(self):
        # the PR 6 regression, extended: a bench per-phase reset must
        # keep the engine-geometry gauges — speculate included
        _, eng = _run(
            _llama, decode_mode="persistent", speculate=2, ring_capacity=32
        )
        fresh = eng.reset_metrics()
        assert fresh is eng.metrics
        j = fresh.to_json()
        assert j["gauges"]["speculate"] == 2
        assert j["gauges"]["ring_capacity"] == 32
        assert j["counters"]["draft_tokens_proposed"] == 0

    def test_config_validation(self):
        with pytest.raises(ValueError, match="speculate must be"):
            ServeEngine(_llama(), num_slots=1, max_len=32, speculate=-1)
        with pytest.raises(ValueError, match="spec_ngram"):
            ServeEngine(
                _llama(), num_slots=1, max_len=32, speculate=2, spec_ngram=0
            )
        with pytest.raises(ValueError, match="persistent_stream"):
            ServeEngine(
                _llama(),
                num_slots=1,
                max_len=32,
                decode_mode="persistent",
                persistent_stream=True,
                speculate=2,
            )


# --------------------------------------------------------------------------
# tensor-parallel serving with speculation
# --------------------------------------------------------------------------


class TestSpecTP:
    def test_tp2_identity_and_collective_closed_form(self):
        from torchdistx_tpu.obs.comm import comm_audit

        prompts = _cyclic_prompts()

        def go(speculate, mesh=None):
            engine = ServeEngine(
                _llama_tp(),
                num_slots=2,
                max_len=64,
                prefill_buckets=(16,),
                decode_mode="persistent",
                speculate=speculate,
                mesh=mesh,
            )
            res = engine.run(
                [{"prompt": p, "max_new_tokens": 16} for p in prompts]
            )
            return [list(map(int, r.tokens)) for r in res], engine

        base, _ = go(0)
        with comm_audit() as prof:
            spec, engine = go(2, mesh=_tp_mesh(2))
        assert spec == base
        c = engine.metrics.counters
        model_cfg = engine.model.cfg
        nl, dim = model_cfg.n_layers, model_cfg.dim
        assert prof.ops("all_reduce", "tp") == 2 * nl * (
            c["prefill_calls"] + c["decode_steps"]
        )
        # every spec decode step verifies num_slots x (K + 1) query rows
        expected_payload = (
            2 * nl * 4 * dim
            * (
                c["tokens_prefilled"]
                + c["decode_steps"] * engine.num_slots * 3
            )
        )
        assert prof.payload_bytes("all_reduce", "tp") == expected_payload

    @pytest.mark.slow
    @pytest.mark.parametrize("mode", ["chunked", "persistent"])
    @pytest.mark.parametrize("page_size", [None, 8])
    def test_tp2_identity_grid(self, mode, page_size):
        prompts = _cyclic_prompts()

        def go(speculate, mesh):
            kw = dict(
                num_slots=2,
                max_len=64,
                prefill_buckets=(16,),
                decode_mode=mode,
                speculate=speculate,
            )
            if page_size is not None:
                kw["page_size"] = page_size
            engine = ServeEngine(_llama_tp(), mesh=mesh, **kw)
            res = engine.run(
                [{"prompt": p, "max_new_tokens": 16} for p in prompts]
            )
            return [list(map(int, r.tokens)) for r in res]

        assert go(2, _tp_mesh(2)) == go(0, None)
