"""Failure detection + elastic recovery (SURVEY §5.3 — absent in the
reference; this framework provides the host-side half of elasticity)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu import nn
from torchdistx_tpu.nn import functional_call
from torchdistx_tpu.trainer import Trainer
from torchdistx_tpu.utils.failure import (
    FailureDetector,
    Heartbeat,
    StepFailure,
    guard_nonfinite_updates,
)


class TestFailureDetector:
    def test_finite_losses_pass(self):
        det = FailureDetector()
        for i, loss in enumerate([1.0, 0.5, 0.25]):
            det.check_loss(i, loss)
        assert det.failures == []

    def test_nan_raises_at_zero_tolerance(self):
        det = FailureDetector()
        with pytest.raises(StepFailure, match="non-finite"):
            det.check_loss(5, float("nan"))
        assert det.failures[0]["kind"] == "nonfinite"

    def test_tolerance_allows_transients(self):
        det = FailureDetector(nan_tolerance=2)
        det.check_loss(1, float("inf"))
        det.check_loss(2, float("nan"))
        det.check_loss(3, 0.7)  # recovered: counter resets
        det.check_loss(4, float("nan"))
        det.check_loss(5, float("nan"))
        with pytest.raises(StepFailure):
            det.check_loss(6, float("nan"))

    def test_reset_restores_tolerance(self):
        # a HANDLED failure must not void the tolerance for the rest of
        # the run
        det = FailureDetector(nan_tolerance=1)
        det.check_loss(1, float("nan"))
        with pytest.raises(StepFailure):
            det.check_loss(2, float("nan"))
        det.reset()
        det.check_loss(3, float("nan"))  # within tolerance again

    def test_window_deadline(self):
        det = FailureDetector(step_deadline_s=0.01)
        with pytest.raises(StepFailure, match="deadline|budget"):
            det.check_window(10, elapsed_s=0.5, n_steps=4)  # 0.5 > 0.04
        det.check_window(11, elapsed_s=0.03, n_steps=4)  # within budget
        with pytest.raises(StepFailure):
            with det.deadline():
                time.sleep(0.05)


class TestGuardNonfiniteUpdates:
    def test_nonfinite_grads_apply_no_update(self):
        params = {"w": jnp.ones((4,))}
        tx = guard_nonfinite_updates(optax.sgd(0.1))
        s = tx.init(params)
        bad = {"w": jnp.full((4,), float("nan"))}
        u, s = tx.update(bad, s, params)
        p2 = optax.apply_updates(params, u)
        np.testing.assert_array_equal(np.asarray(p2["w"]), np.ones(4))
        good = {"w": jnp.ones((4,))}
        u, s = tx.update(good, s, params)
        p3 = optax.apply_updates(params, u)
        assert float(p3["w"][0]) != 1.0  # real update applied


class TestHeartbeat:
    def test_stamps_and_staleness(self, tmp_path):
        path = str(tmp_path / "hb")
        hb = Heartbeat(path, interval_s=0.05)
        with hb:
            hb.step = 42
            time.sleep(0.15)
            assert not Heartbeat.is_stale(path, max_age_s=5.0)
        with open(path) as f:
            stamp, step = f.read().split()
        assert step in ("0", "42")
        assert Heartbeat.is_stale(path, max_age_s=0.0)
        assert Heartbeat.is_stale(str(tmp_path / "missing"), 5.0)

    def test_transient_write_error_does_not_kill_thread(self, tmp_path):
        # A failed beat (e.g. disk full) must not end the daemon loop:
        # liveness reporting resumes once writes succeed again.
        subdir = tmp_path / "sub"
        subdir.mkdir()
        path = str(subdir / "hb")
        hb = Heartbeat(path, interval_s=0.05)
        with hb:
            time.sleep(0.12)
            import os

            # rename (not rmtree) so a concurrent beat creating hb.tmp
            # can't race the directory scan; later beats raise OSError
            os.rename(subdir, tmp_path / "quarantine")
            time.sleep(0.15)
            assert hb._thread.is_alive()
            assert hb.write_failures > 0
            subdir.mkdir()  # writable again
            time.sleep(0.15)
            assert os.path.exists(path)
            assert hb.write_failures == 0


def _make_trainer(tmp_path, inject_nan_after, on_failure, detector):
    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 1)

        def forward(self, x):
            return self.fc(x)

    tdx.manual_seed(0)
    m = tdx.deferred_init(M)
    tdx.materialize_module(m)
    params = dict(m.named_parameters())
    tx = optax.sgd(1e-2)

    counter = {"n": 0}

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((functional_call(m, p, (xb,)) - yb) ** 2)

    def step(p, s, batch):
        counter["n"] += 1
        l, g = jax.value_and_grad(loss_fn)(p, batch)
        u, s = tx.update(g, s, p)
        p = jax.tree_util.tree_map(lambda a, b: a + b, p, u)
        if counter["n"] == inject_nan_after:
            l = l * jnp.float32(float("nan"))
        return p, s, l

    logs = []
    tr = Trainer(
        step,
        params,
        tx.init(params),
        log_every=1,
        log_fn=logs.append,
        checkpoint_dir=str(tmp_path),
        checkpoint_every=2,
        failure_detector=detector,
        on_failure=on_failure,
    )
    return tr, logs


class TestElasticTrainer:
    def test_raise_policy(self, tmp_path):
        tr, _ = _make_trainer(tmp_path, 4, "raise", FailureDetector())
        batch = (jnp.ones((2, 4)), jnp.zeros((2, 1)))
        with pytest.raises(StepFailure):
            tr.fit([batch] * 8)

    def test_restore_policy_rolls_back(self, tmp_path):
        tr, logs = _make_trainer(tmp_path, 5, "restore", FailureDetector())
        batch = (jnp.ones((2, 4)), jnp.zeros((2, 1)))
        # rollback re-runs steps, so supply more batches than num_steps
        tr.fit([batch] * 12, num_steps=8)
        actions = [m for m in logs if "failure" in m]
        assert actions and actions[0]["action"] == "restored"
        # rolled back to the step-4 checkpoint, then continued to 8
        assert tr.global_step == 8
        for leaf in jax.tree_util.tree_leaves(tr.params):
            assert bool(jnp.all(jnp.isfinite(leaf)))

    def test_continue_policy_logs_and_goes_on(self, tmp_path):
        tr, logs = _make_trainer(tmp_path, 3, "continue", FailureDetector())
        batch = (jnp.ones((2, 4)), jnp.zeros((2, 1)))
        tr.fit([batch] * 6, num_steps=6)
        actions = [m for m in logs if "failure" in m]
        assert actions and actions[0]["action"] == "continued"
        assert tr.global_step == 6

    def test_restore_without_checkpoint_raises(self, tmp_path):
        # step 2: the earliest health-checked boundary (step 1's boundary is
        # consumed by the warmup-window reset)
        tr, _ = _make_trainer(tmp_path, 2, "restore", FailureDetector())
        tr.checkpoint_dir = None  # never saves
        batch = (jnp.ones((2, 4)), jnp.zeros((2, 1)))
        with pytest.raises(StepFailure, match="no checkpoint"):
            tr.fit([batch] * 4)

    def test_checkpoint_health_gate(self, tmp_path):
        # tolerance lets the run continue past a NaN boundary; the step-4
        # checkpoint then coincides with non-finite loss and must be
        # skipped, not saved as a poisoned rollback target
        det = FailureDetector(nan_tolerance=10)
        tr, logs = _make_trainer(tmp_path, 4, "continue", det)
        batch = (jnp.ones((2, 4)), jnp.zeros((2, 1)))
        tr.fit([batch] * 6, num_steps=6)
        skips = [m for m in logs if m.get("checkpoint") == "skipped_nonfinite_loss"]
        assert skips and skips[0]["step"] == 4
