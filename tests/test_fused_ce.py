"""Fused LM-head cross-entropy parity (interpret mode; compiled acceptance
is captured by scripts/verify_kernels_onchip.py's fusedce phase).

Spec: fused_linear_cross_entropy(x, w, y) == cross_entropy(x @ w.T, y)
in value and in (dx, dw) gradients, for bf16 and f32, odd shapes, and
every label position (first/last vocab tile)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistx_tpu.nn import functional
from torchdistx_tpu.ops.fused_ce import fused_linear_cross_entropy


def _ref(x, w, labels):
    return functional.cross_entropy(
        jnp.einsum("nd,vd->nv", x, w), labels
    )


def _mk(n, d, v, dtype, seed=0):
    k = jax.random.split(jax.random.PRNGKey(seed), 3)
    x = jax.random.normal(k[0], (n, d), dtype)
    w = jax.random.normal(k[1], (v, d), dtype) * 0.1
    y = jax.random.randint(k[2], (n,), 0, v)
    return x, w, y


@pytest.mark.parametrize(
    "n,d,v,dtype",
    [
        (256, 128, 512, jnp.float32),
        (256, 128, 512, jnp.bfloat16),
        (384, 64, 1000, jnp.float32),  # odd token/vocab block shrink
        (64, 256, 2048, jnp.bfloat16),
    ],
)
def test_loss_and_grads_match_reference(n, d, v, dtype):
    x, w, y = _mk(n, d, v, dtype)
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5

    loss_f = fused_linear_cross_entropy(x, w, y)
    loss_r = _ref(x, w, y)
    np.testing.assert_allclose(
        float(loss_f), float(loss_r), rtol=tol, atol=tol
    )

    gx_f, gw_f = jax.grad(
        lambda x, w: fused_linear_cross_entropy(x, w, y), argnums=(0, 1)
    )(x, w)
    gx_r, gw_r = jax.grad(
        lambda x, w: _ref(x, w, y), argnums=(0, 1)
    )(x, w)
    for a, b in ((gx_f, gx_r), (gw_f, gw_r)):
        scale = np.max(np.abs(np.asarray(b, np.float32))) + 1e-8
        np.testing.assert_allclose(
            np.asarray(a, np.float32) / scale,
            np.asarray(b, np.float32) / scale,
            atol=2e-2 if dtype == jnp.bfloat16 else 1e-5,
        )


def test_leading_dims_flattened():
    x, w, y = _mk(128, 64, 256, jnp.float32, seed=1)
    x3 = x.reshape(4, 32, 64)
    y3 = y.reshape(4, 32)
    a = fused_linear_cross_entropy(x3, w, y3)
    b = fused_linear_cross_entropy(x, w, y)
    np.testing.assert_allclose(float(a), float(b), rtol=1e-6)


def test_labels_at_tile_edges():
    # labels in the first and last columns of first/last vocab tiles: the
    # in-tile one-hot match must catch each exactly once
    n, d, v = 8, 32, 512
    x, w, _ = _mk(n, d, v, jnp.float32, seed=2)
    y = jnp.asarray([0, 1, 127, 128, 255, 256, 510, 511])
    loss_f = fused_linear_cross_entropy(x, w, y, block_v=128)
    np.testing.assert_allclose(float(loss_f), float(_ref(x, w, y)), rtol=1e-5)


def test_cotangent_scaling():
    x, w, y = _mk(64, 32, 128, jnp.float32, seed=3)
    g2 = jax.grad(lambda x: 2.0 * fused_linear_cross_entropy(x, w, y))(x)
    g1 = jax.grad(lambda x: fused_linear_cross_entropy(x, w, y))(x)
    np.testing.assert_allclose(
        np.asarray(g2), 2.0 * np.asarray(g1), rtol=1e-5
    )


def test_shape_validation():
    x, w, y = _mk(64, 32, 128, jnp.float32)
    with pytest.raises(ValueError, match="w must be"):
        fused_linear_cross_entropy(x, w.T, y)
    with pytest.raises(ValueError, match="labels"):
        fused_linear_cross_entropy(x, w, y[:-1])


@pytest.mark.parametrize("family", ["gpt2", "t5"])
def test_model_hidden_path_matches_logits(family):
    # return_hidden + fused CE == cross_entropy(model logits) for the
    # tied-head families (GPT-2 plain tie, T5 scaled tie)
    import torchdistx_tpu as tdx

    tdx.manual_seed(0)
    if family == "gpt2":
        from torchdistx_tpu.models import GPT2

        m = tdx.deferred_init(GPT2.from_name, "tiny")
        tdx.materialize_module(m)
        p = dict(m.named_parameters())
        toks = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0, 64)
        args = (toks,)
        w_key = "tok_emb.weight"
    else:
        from torchdistx_tpu.models import T5

        m = tdx.deferred_init(T5.from_name, "tiny")
        tdx.materialize_module(m)
        p = dict(m.named_parameters())
        toks = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0, 64)
        args = (toks, toks)
        w_key = "shared_emb.weight"
    from torchdistx_tpu.nn import functional_call

    h = functional_call(m, p, args, {"return_hidden": True})
    fused = fused_linear_cross_entropy(h, p[w_key], toks)
    ref = functional.cross_entropy(functional_call(m, p, args), toks)
    np.testing.assert_allclose(float(fused), float(ref), rtol=1e-4)


def test_sequence_parallel_shard_map(mesh8):
    # per-shard fused CE + pmean == global CE (equal shard sizes), in
    # value and in grads — the loss SP training composes with
    from torchdistx_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    n, d, v = 512, 64, 256
    x, w, y = _mk(n, d, v, jnp.float32, seed=4)

    def local_loss(x, w, y):
        return jax.lax.pmean(fused_linear_cross_entropy(x, w, y), "fsdp")

    def sm(f):
        return shard_map(
            f, mesh=mesh8, in_specs=(P("fsdp"), P(), P("fsdp")),
            out_specs=P(), check_vma=False,
        )

    loss_sp = jax.jit(sm(local_loss))(x, w, y)
    np.testing.assert_allclose(float(loss_sp), float(_ref(x, w, y)),
                               rtol=1e-6)
    g_sp = jax.jit(jax.grad(
        lambda x, w: sm(local_loss)(x, w, y), argnums=(0, 1)
    ))(x, w)
    g_ref = jax.grad(
        lambda x, w: _ref(x, w, y), argnums=(0, 1)
    )(x, w)
    for a, b in zip(g_sp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_gpt2_vocab_padding():
    # 50257 has no good block divisor (7*43*167): _blocks must pad the
    # vocab instead of shrinking block_v to 1 (a 50k-step grid), and the
    # padded columns must vanish from the loss and both gradients
    from torchdistx_tpu.ops.fused_ce import _blocks

    bt, bv, n_t, n_v, v_pad, n_pad = _blocks(64, 50257, 256, 512)
    assert bv == 512 and v_pad == 50688 and n_v == 99 and n_pad == 64

    n, d, v = 64, 32, 50257
    x, w, _ = _mk(n, d, v, jnp.float32, seed=6)
    y = jnp.concatenate([
        jnp.asarray([0, 50256, 50255]),  # last true columns
        jax.random.randint(jax.random.PRNGKey(7), (n - 3,), 0, v),
    ])
    loss_f = fused_linear_cross_entropy(x, w, y)
    np.testing.assert_allclose(float(loss_f), float(_ref(x, w, y)),
                               rtol=1e-5)
    gx_f, gw_f = jax.grad(
        lambda x, w: fused_linear_cross_entropy(x, w, y), argnums=(0, 1)
    )(x, w)
    gx_r, gw_r = jax.grad(
        lambda x, w: _ref(x, w, y), argnums=(0, 1)
    )(x, w)
    assert gw_f.shape == (v, d)  # sliced back to the true vocab
    for a, b in ((gx_f, gx_r), (gw_f, gw_r)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        )


def test_prime_token_count_padding():
    # 509 tokens (prime) would shrink block_t to 1; the token dim pads
    # instead, with padded rows masked out of the loss mean and both
    # gradients
    from torchdistx_tpu.ops.fused_ce import _blocks

    bt, bv, n_t, n_v, v_pad, n_pad = _blocks(509, 512, 256, 512)
    assert bt == 256 and n_pad == 512 and n_t == 2

    n, d, v = 509, 32, 512
    x, w, y = _mk(n, d, v, jnp.float32, seed=8)
    loss_f = fused_linear_cross_entropy(x, w, y)
    np.testing.assert_allclose(float(loss_f), float(_ref(x, w, y)),
                               rtol=1e-5)
    gx_f, gw_f = jax.grad(
        lambda x, w: fused_linear_cross_entropy(x, w, y), argnums=(0, 1)
    )(x, w)
    gx_r, gw_r = jax.grad(
        lambda x, w: _ref(x, w, y), argnums=(0, 1)
    )(x, w)
    assert gx_f.shape == (n, d)
    for a, b in ((gx_f, gx_r), (gw_f, gw_r)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_tiny_token_count_pads_to_sublane_minimum():
    # n < 8 divides itself, so neither the shrink nor the n > 8 padding
    # path fired — compiled Mosaic would get a <8-sublane block.  _blocks
    # must pad tiny token counts up to one 8-row block, and the padded
    # rows must vanish from the loss mean and both gradients
    from torchdistx_tpu.ops.fused_ce import _blocks

    for n in (1, 3, 7):
        bt, bv, n_t, n_v, v_pad, n_pad = _blocks(n, 512, 256, 512)
        assert bt == 8 and n_pad == 8 and n_t == 1
    bt, _, n_t, _, _, n_pad = _blocks(8, 512, 256, 512)
    assert bt == 8 and n_pad == 8 and n_t == 1  # exactly 8 needs no pad

    n, d, v = 3, 32, 512
    x, w, y = _mk(n, d, v, jnp.float32, seed=9)
    loss_f = fused_linear_cross_entropy(x, w, y)
    np.testing.assert_allclose(float(loss_f), float(_ref(x, w, y)),
                               rtol=1e-5)
    gx_f, gw_f = jax.grad(
        lambda x, w: fused_linear_cross_entropy(x, w, y), argnums=(0, 1)
    )(x, w)
    gx_r, gw_r = jax.grad(
        lambda x, w: _ref(x, w, y), argnums=(0, 1)
    )(x, w)
    assert gx_f.shape == (n, d)
    for a, b in ((gx_f, gx_r), (gw_f, gw_r)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
