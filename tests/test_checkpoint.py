"""Checkpoint round-trips, incl. the reference's map_location-style
cross-placement restore (test_comm_hooks_fsdp.py:262-331 analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu import nn
from torchdistx_tpu.optimizers import anyprecision_adamw
from torchdistx_tpu.slowmo import SlowMomentumOptimizer
from torchdistx_tpu.utils.checkpoint import (
    load_module,
    restore_checkpoint,
    save_checkpoint,
    save_module,
)


def test_pytree_roundtrip(tmp_path):
    state = {
        "params": {"w": jnp.arange(12.0).reshape(3, 4)},
        "step": jnp.asarray(7),
    }
    save_checkpoint(str(tmp_path / "ck"), state)
    out = restore_checkpoint(str(tmp_path / "ck"))
    np.testing.assert_allclose(np.asarray(out["params"]["w"]), np.asarray(state["params"]["w"]))
    assert int(out["step"]) == 7


def test_restore_into_sharding(tmp_path, mesh8):
    # save replicated, restore sharded — the map_location analog
    state = {"w": jnp.arange(64.0).reshape(8, 8)}
    save_checkpoint(str(tmp_path / "ck"), state)
    sh = NamedSharding(mesh8, P("fsdp"))
    out = restore_checkpoint(str(tmp_path / "ck"), shardings={"w": sh})
    assert out["w"].sharding.is_equivalent_to(sh, 2)
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(state["w"]))


def test_module_roundtrip_with_sharding_rule(tmp_path, mesh8):
    tdx.manual_seed(0)
    m = nn.Linear(16, 8)
    save_module(str(tmp_path / "mod"), m)

    tdx.manual_seed(99)  # different init; load must overwrite
    m2 = nn.Linear(16, 8)

    def rule(path, meta):
        if len(meta.shape) == 2 and meta.shape[0] % 8 == 0:
            return NamedSharding(mesh8, P("fsdp"))
        return None

    load_module(str(tmp_path / "mod"), m2, sharding_rule=rule)
    np.testing.assert_allclose(
        np.asarray(m2._parameters["weight"]), np.asarray(m._parameters["weight"])
    )
    # weight (8, 16) matched the rule -> restored FSDP-sharded over 8 devices
    assert len(m2._parameters["weight"].sharding.device_set) == 8
    # bias (8,) is 1-d -> rule returned None -> default placement
    np.testing.assert_allclose(
        np.asarray(m2._parameters["bias"]), np.asarray(m._parameters["bias"])
    )


def test_restore_like_casts_dtype(tmp_path):
    state = {"w": jnp.ones((4, 4), jnp.float32)}
    save_checkpoint(str(tmp_path / "ck"), state)
    like = {"w": jax.ShapeDtypeStruct((4, 4), jnp.bfloat16)}
    out = restore_checkpoint(str(tmp_path / "ck"), like=like)
    assert out["w"].dtype == jnp.bfloat16


def test_restore_like_structure_mismatch(tmp_path):
    save_checkpoint(str(tmp_path / "ck"), {"w": jnp.ones((2,))})
    with pytest.raises(ValueError, match="does not match"):
        restore_checkpoint(
            str(tmp_path / "ck"),
            like={"w": jnp.ones((2,)), "extra": jnp.ones((1,))},
        )


def test_load_module_strict_mismatch(tmp_path):
    tdx.manual_seed(0)
    m = nn.Linear(4, 4)
    save_module(str(tmp_path / "mod"), m)
    other = nn.Linear(4, 4, bias=False)
    with pytest.raises(KeyError, match="mismatch"):
        load_module(str(tmp_path / "mod"), other)
    load_module(str(tmp_path / "mod"), other, strict=False)  # opt-out works


def test_optimizer_state_roundtrip(tmp_path):
    params = {"w": jnp.ones((4, 4))}
    tx = anyprecision_adamw(1e-2, use_kahan_summation=True)
    s = tx.init(params)
    g = {"w": jnp.full((4, 4), 0.1)}
    u, s = tx.update(g, s, params)
    save_checkpoint(str(tmp_path / "opt"), {"state": s})
    out = restore_checkpoint(str(tmp_path / "opt"))
    np.testing.assert_allclose(
        np.asarray(out["state"]["exp_avg"]["w"]), np.asarray(s.exp_avg["w"])
    )
    assert int(out["state"]["count"]) == 1


def test_slowmo_state_dict_checkpoint(tmp_path):
    params = {"w": jnp.ones((4,))}
    opt = SlowMomentumOptimizer(params, optax.sgd(0.1), slowmo_freq=5, base_lr=0.1)
    params = opt.step(params, {"w": jnp.full((4,), 0.2)})
    sd = opt.state_dict()
    save_checkpoint(str(tmp_path / "slowmo"), sd)
    restored = restore_checkpoint(str(tmp_path / "slowmo"))
    opt2 = SlowMomentumOptimizer({"w": jnp.zeros((4,))}, optax.sgd(0.1), base_lr=0.1)
    # orbax restores the NamedTuple state as nested dicts; rebuild
    from torchdistx_tpu.slowmo.slowmo_optimizer import SlowMomentumState

    restored["state"] = SlowMomentumState(
        count=restored["state"]["count"],
        base_state=opt2.state.base_state,
        prev_params=restored["state"]["prev_params"],
        slow_momentum=restored["state"]["slow_momentum"],
    )
    opt2.load_state_dict(restored)
    assert opt2.slowmo_freq == 5
    np.testing.assert_allclose(
        np.asarray(opt2.state.prev_params["w"]), np.ones(4)
    )


def test_restore_single_sharding_broadcasts_to_every_leaf(tmp_path, mesh8):
    """shardings= accepts a single Sharding (not a pytree): every leaf of
    the checkpoint restores into that placement — the shorthand the
    elastic reshard-via-checkpoint bounce leans on."""
    state = {
        "layer": {"w": jnp.arange(64.0).reshape(8, 8)},
        "b": jnp.arange(8.0),
    }
    save_checkpoint(str(tmp_path / "ck"), state)
    sh = NamedSharding(mesh8, P("fsdp"))
    out = restore_checkpoint(str(tmp_path / "ck"), shardings=sh)
    for leaf in jax.tree_util.tree_leaves(out):
        assert leaf.sharding.is_equivalent_to(sh, leaf.ndim)
    np.testing.assert_array_equal(
        np.asarray(out["layer"]["w"]), np.asarray(state["layer"]["w"])
    )
    np.testing.assert_array_equal(np.asarray(out["b"]), np.asarray(state["b"]))


def test_restore_like_casts_dtype_on_sharded_state(tmp_path, mesh8):
    """like= dtype casting composes with shardings=: an fp32 checkpoint
    restores straight into an FSDP placement AND casts to the bf16
    template without losing the placement."""
    state = {"w": jnp.arange(64.0).reshape(8, 8)}
    save_checkpoint(str(tmp_path / "ck"), state)
    sh = NamedSharding(mesh8, P("fsdp"))
    like = {"w": jax.ShapeDtypeStruct((8, 8), jnp.bfloat16)}
    out = restore_checkpoint(str(tmp_path / "ck"), shardings={"w": sh}, like=like)
    assert out["w"].dtype == jnp.bfloat16
    assert out["w"].sharding.is_equivalent_to(sh, 2)
    np.testing.assert_array_equal(
        np.asarray(out["w"]),
        np.asarray(state["w"]).astype(jnp.bfloat16),
    )


def test_streaming_restore_into_template_shardings(tmp_path, mesh8):
    """shardings_from=: every restored array streams directly into the
    template leaf's sharding (the sharded map_location, without a
    replicated host copy in between), including optimizer NamedTuples."""
    import optax

    from torchdistx_tpu.parallel import fsdp_shard_rule
    from torchdistx_tpu.parallel.fsdp import optimizer_state_shardings

    rule = fsdp_shard_rule(mesh8, "fsdp")
    w = jax.device_put(
        jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
        rule("w", jax.ShapeDtypeStruct((8, 8), jnp.float32)),
    )
    params = {"w": w}
    tx = optax.adam(1e-3)
    state_shape = jax.eval_shape(tx.init, params)
    opt_state = jax.jit(
        tx.init,
        out_shardings=optimizer_state_shardings(state_shape, params, mesh8),
    )(params)
    state = {"params": params, "opt_state": opt_state, "global_step": 7}
    path = str(tmp_path / "stream")
    save_checkpoint(path, state)

    out = restore_checkpoint(path, shardings_from=state)
    assert out["params"]["w"].sharding.is_equivalent_to(w.sharding, 2)
    np.testing.assert_array_equal(np.asarray(out["params"]["w"]), np.asarray(w))
    # optimizer slots (restored as plain nests) landed sharded too
    mu = out["opt_state"]["0"]["mu"]["w"] if isinstance(
        out["opt_state"], dict
    ) else jax.tree_util.tree_leaves(out["opt_state"])[1]
    assert len(mu.sharding.device_set) == 8
    assert int(out["global_step"]) == 7
