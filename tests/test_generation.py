"""KV-cache generation: cached incremental decode must exactly reproduce
full-recompute greedy decoding, and the cached forward must equal the plain
forward position-for-position."""

import jax
import jax.numpy as jnp
import numpy as np

import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu.generation import generate
from torchdistx_tpu.models import Llama
from torchdistx_tpu.nn import functional_call


def _model():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


class TestCachedForward:
    def test_prefill_matches_plain_forward(self):
        m = _model()
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (2, 12)), jnp.int32
        )
        plain = m(tokens)
        cache = m.init_cache(2, 32)
        cached, _ = m.forward_cached(tokens, cache, 0)
        np.testing.assert_allclose(
            np.asarray(cached), np.asarray(plain), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.slow
    def test_incremental_matches_prefill(self):
        m = _model()
        rs = np.random.RandomState(1)
        tokens = jnp.asarray(rs.randint(0, 256, (1, 10)), jnp.int32)
        full = m(tokens)

        cache = m.init_cache(1, 16)
        logits, cache = m.forward_cached(tokens[:, :4], cache, 0)
        outs = [logits]
        for i in range(4, 10):
            logits, cache = m.forward_cached(tokens[:, i : i + 1], cache, i)
            outs.append(logits)
        inc = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(
            np.asarray(inc), np.asarray(full), rtol=3e-5, atol=3e-5
        )


class TestGenerate:
    @pytest.mark.slow
    def test_greedy_matches_full_recompute(self):
        m = _model()
        prompt = jnp.asarray(
            np.random.RandomState(2).randint(0, 256, (2, 6)), jnp.int32
        )
        out = generate(m, prompt, max_new_tokens=8)
        assert out.shape == (2, 14)
        np.testing.assert_array_equal(np.asarray(out[:, :6]), np.asarray(prompt))

        # naive full-recompute greedy reference
        ids = np.asarray(prompt)
        for _ in range(8):
            logits = np.asarray(m(jnp.asarray(ids)))
            ids = np.concatenate(
                [ids, logits[:, -1].argmax(-1, keepdims=True).astype(ids.dtype)],
                axis=1,
            )
        np.testing.assert_array_equal(np.asarray(out), ids)

    def test_sampling_deterministic_per_key(self):
        m = _model()
        prompt = jnp.zeros((1, 4), jnp.int32)
        a = generate(m, prompt, 6, temperature=0.8, key=jax.random.PRNGKey(7))
        b = generate(m, prompt, 6, temperature=0.8, key=jax.random.PRNGKey(7))
        c = generate(m, prompt, 6, temperature=0.8, key=jax.random.PRNGKey(8))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_sampling_requires_key(self):
        m = _model()
        import pytest

        with pytest.raises(ValueError, match="requires a PRNG key"):
            generate(m, jnp.zeros((1, 4), jnp.int32), 4, temperature=1.0)

    def test_zero_new_tokens_returns_prompt(self):
        m = _model()
        prompt = jnp.zeros((1, 4), jnp.int32)
        out = generate(m, prompt, 0)
        assert out is prompt

    def test_exceeding_max_seq_len_raises(self):
        import pytest

        m = _model()  # max_seq_len=64
        with pytest.raises(ValueError, match="maximum sequence length"):
            generate(m, jnp.zeros((1, 32), jnp.int32), 40)


class TestProfilingHelpers:
    def test_trace_and_memory_stats(self, tmp_path):
        import os

        from torchdistx_tpu.utils import (
            annotate,
            device_memory_stats,
            format_memory_stats,
            trace,
        )

        with trace(str(tmp_path)):
            with annotate("probe"):
                jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
        files = sum(len(f) for _, _, f in os.walk(tmp_path))
        assert files > 0
        stats = device_memory_stats()
        assert isinstance(stats, dict) and stats
        assert isinstance(format_memory_stats(stats), str)


class TestGPT2Generate:
    """GPT-2 KV-cache decode (same generate() contract as Llama)."""

    @staticmethod
    def _model():
        from torchdistx_tpu.models import GPT2

        tdx.manual_seed(11)
        m = tdx.deferred_init(GPT2.from_name, "tiny")
        tdx.materialize_module(m)
        return m

    def test_cached_prefill_matches_plain_forward(self):
        m = self._model()
        params = dict(m.named_parameters())
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (2, 12)), jnp.int32
        )
        full = functional_call(m, params, (tokens,))
        cache = m.init_cache(2, 32)
        cached, _ = functional_call(
            m, params, (tokens, cache, 0), method="forward_cached"
        )
        np.testing.assert_allclose(
            np.asarray(full), np.asarray(cached), rtol=2e-5, atol=2e-5
        )

    def test_greedy_matches_full_recompute(self):
        m = self._model()
        prompt = jnp.asarray(
            np.random.RandomState(1).randint(0, 256, (1, 6)), jnp.int32
        )
        out = generate(m, prompt, max_new_tokens=6)
        # re-derive greedily with full forwards
        params = dict(m.named_parameters())
        cur = prompt
        for _ in range(6):
            logits = functional_call(m, params, (cur,))
            nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None].astype(cur.dtype)
            cur = jnp.concatenate([cur, nxt], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))

    def test_limit_enforced(self):
        m = self._model()
        with pytest.raises(ValueError, match="maximum sequence length"):
            generate(m, jnp.zeros((1, 60), jnp.int32), 10)


class TestT5GenerateEncDec:
    """T5 encoder-decoder incremental decode (generate_encdec): greedy
    decode with the KV/cross cache must equal greedy decode by repeated
    full teacher-forced forwards."""

    @staticmethod
    def _model():
        from torchdistx_tpu.models import T5

        tdx.manual_seed(21)
        m = tdx.deferred_init(T5.from_name, "tiny")
        tdx.materialize_module(m)
        return m

    @pytest.mark.slow
    def test_greedy_matches_full_recompute(self):
        from torchdistx_tpu.generation import generate_encdec

        m = self._model()
        params = dict(m.named_parameters())
        enc_tokens = jnp.asarray(
            np.random.RandomState(2).randint(0, 256, (2, 9)), jnp.int32
        )
        n_new = 5
        out = generate_encdec(m, enc_tokens, n_new)
        assert out.shape == (2, n_new)

        # reference: greedy with full decoder forwards (teacher forcing)
        dec = jnp.zeros((2, 1), jnp.int32)  # start token 0
        for _ in range(n_new):
            logits = functional_call(m, params, (enc_tokens, dec))
            nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None].astype(jnp.int32)
            dec = jnp.concatenate([dec, nxt], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(dec[:, 1:]))

    def test_sampling_seeded(self):
        from torchdistx_tpu.generation import generate_encdec

        m = self._model()
        enc = jnp.asarray(
            np.random.RandomState(3).randint(0, 256, (1, 6)), jnp.int32
        )
        a = generate_encdec(m, enc, 4, temperature=0.9, key=jax.random.PRNGKey(1))
        b = generate_encdec(m, enc, 4, temperature=0.9, key=jax.random.PRNGKey(1))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestSamplingFilters:
    def test_top_k_one_equals_greedy(self):
        m = _model()
        prompt = jnp.asarray([[3, 5, 7]], jnp.int32)
        greedy = generate(m, prompt, 6)
        topk1 = generate(
            m, prompt, 6, temperature=1.0, top_k=1, key=jax.random.PRNGKey(0)
        )
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(topk1))

    def test_top_p_one_equals_plain_sampling(self):
        m = _model()
        prompt = jnp.asarray([[2, 4]], jnp.int32)
        a = generate(m, prompt, 5, temperature=0.9, key=jax.random.PRNGKey(5))
        b = generate(
            m, prompt, 5, temperature=0.9, top_p=1.0, key=jax.random.PRNGKey(5)
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_filters_unit_semantics(self):
        from torchdistx_tpu.generation import _apply_top_k, _apply_top_p

        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
        k2 = _apply_top_k(logits, 2)
        assert bool(jnp.isfinite(k2[0, 0])) and bool(jnp.isfinite(k2[0, 1]))
        assert not bool(jnp.isfinite(k2[0, 2])) and not bool(jnp.isfinite(k2[0, 3]))
        # nucleus 0.6: keep tokens whose preceding mass < 0.6 -> {0.5, 0.3}
        p6 = _apply_top_p(logits, 0.6)
        assert bool(jnp.isfinite(p6[0, 0])) and bool(jnp.isfinite(p6[0, 1]))
        assert not bool(jnp.isfinite(p6[0, 2]))
        # always keeps at least top-1
        p_tiny = _apply_top_p(logits, 1e-9)
        assert bool(jnp.isfinite(p_tiny[0, 0]))
        assert not bool(jnp.isfinite(p_tiny[0, 1]))

    def test_invalid_filter_args_raise_loudly(self):
        m = _model()
        p = jnp.zeros((1, 3), jnp.int32)
        with pytest.raises(ValueError, match="top_k"):
            generate(m, p, 2, temperature=1.0, top_k=0, key=jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="top_p"):
            generate(m, p, 2, temperature=1.0, top_p=0.0, key=jax.random.PRNGKey(0))
        # top_k larger than vocab clamps instead of crashing mid-trace
        out = generate(
            m, p, 2, temperature=1.0, top_k=10**6, key=jax.random.PRNGKey(0)
        )
        assert out.shape == (1, 5)


class TestFlashPrefill:
    """The from-empty prefill routes through the flash kernel when
    use_flash resolves on; parity vs the jnp cache path (interpret mode
    on CPU — exact)."""

    def test_cached_attention_flash_prefill_parity(self):
        from torchdistx_tpu.ops.attention import cached_attention

        rs = np.random.RandomState(6)
        b, s, hq, hkv, d, max_seq = 2, 16, 4, 2, 8, 32
        q = jnp.asarray(rs.randn(b, s, hq, d), jnp.float32)
        k = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
        cache = (
            jnp.zeros((b, max_seq, hkv, d)),
            jnp.zeros((b, max_seq, hkv, d)),
        )
        out_jnp, (ck1, cv1) = cached_attention(
            q, k, v, cache, 0, use_flash=False
        )
        out_flash, (ck2, cv2) = cached_attention(
            q, k, v, cache, 0, use_flash=True
        )
        np.testing.assert_allclose(
            np.asarray(out_flash), np.asarray(out_jnp), rtol=2e-5, atol=2e-5
        )
        np.testing.assert_array_equal(np.asarray(ck1), np.asarray(ck2))
        np.testing.assert_array_equal(np.asarray(cv1), np.asarray(cv2))

    def test_traced_cache_pos_stays_on_jnp_path(self):
        # a TRACED cache_pos (mid-cache chunked prefill) must not take the
        # flash branch: its causal mask is end-aligned, not pos-aligned,
        # so at pos > 0 the two paths DIVERGE — chunk 2 must still see
        # chunk 1's cached keys
        from torchdistx_tpu.ops.attention import (
            cached_attention,
            multihead_attention,
        )

        rs = np.random.RandomState(7)
        b, s, hkv, d, max_seq = 1, 8, 2, 8, 32
        q = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
        k = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
        cache = (
            jnp.zeros((b, max_seq, hkv, d)),
            jnp.zeros((b, max_seq, hkv, d)),
        )

        @jax.jit
        def two_chunks(pos):
            # chunk 1 at static 0, chunk 2 at TRACED pos — the traced call
            # must route to the jnp path even with use_flash=True
            _, c = cached_attention(
                q[:, :4], k[:, :4], v[:, :4], cache, 0, use_flash=True
            )
            out2, _ = cached_attention(
                q[:, 4:], k[:, 4:], v[:, 4:], c, pos, use_flash=True
            )
            return out2

        whole = multihead_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(two_chunks(jnp.int32(4))),
            np.asarray(whole[:, 4:]),
            rtol=2e-5,
            atol=2e-5,
        )

    def test_generate_with_flash_prefill_matches_full_recompute(self):
        tdx.manual_seed(8)
        m = Llama.from_name(
            "tiny", n_kv_heads=2, max_seq_len=64, use_flash=True
        )
        prompt = jnp.asarray(
            np.random.RandomState(9).randint(0, 256, (1, 10)), jnp.int32
        )
        out = generate(m, prompt, max_new_tokens=4)
        cur = prompt
        for _ in range(4):
            nxt = jnp.argmax(m(cur)[:, -1], axis=-1)[:, None]
            cur = jnp.concatenate([cur, nxt.astype(cur.dtype)], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))
