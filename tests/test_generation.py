"""KV-cache generation: cached incremental decode must exactly reproduce
full-recompute greedy decoding, and the cached forward must equal the plain
forward position-for-position."""

import jax
import jax.numpy as jnp
import numpy as np

import torchdistx_tpu as tdx
from torchdistx_tpu.generation import generate
from torchdistx_tpu.models import Llama


def _model():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


class TestCachedForward:
    def test_prefill_matches_plain_forward(self):
        m = _model()
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (2, 12)), jnp.int32
        )
        plain = m(tokens)
        cache = m.init_cache(2, 32)
        cached, _ = m.forward_cached(tokens, cache, 0)
        np.testing.assert_allclose(
            np.asarray(cached), np.asarray(plain), rtol=2e-5, atol=2e-5
        )

    def test_incremental_matches_prefill(self):
        m = _model()
        rs = np.random.RandomState(1)
        tokens = jnp.asarray(rs.randint(0, 256, (1, 10)), jnp.int32)
        full = m(tokens)

        cache = m.init_cache(1, 16)
        logits, cache = m.forward_cached(tokens[:, :4], cache, 0)
        outs = [logits]
        for i in range(4, 10):
            logits, cache = m.forward_cached(tokens[:, i : i + 1], cache, i)
            outs.append(logits)
        inc = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(
            np.asarray(inc), np.asarray(full), rtol=3e-5, atol=3e-5
        )


class TestGenerate:
    def test_greedy_matches_full_recompute(self):
        m = _model()
        prompt = jnp.asarray(
            np.random.RandomState(2).randint(0, 256, (2, 6)), jnp.int32
        )
        out = generate(m, prompt, max_new_tokens=8)
        assert out.shape == (2, 14)
        np.testing.assert_array_equal(np.asarray(out[:, :6]), np.asarray(prompt))

        # naive full-recompute greedy reference
        ids = np.asarray(prompt)
        for _ in range(8):
            logits = np.asarray(m(jnp.asarray(ids)))
            ids = np.concatenate(
                [ids, logits[:, -1].argmax(-1, keepdims=True).astype(ids.dtype)],
                axis=1,
            )
        np.testing.assert_array_equal(np.asarray(out), ids)

    def test_sampling_deterministic_per_key(self):
        m = _model()
        prompt = jnp.zeros((1, 4), jnp.int32)
        a = generate(m, prompt, 6, temperature=0.8, key=jax.random.PRNGKey(7))
        b = generate(m, prompt, 6, temperature=0.8, key=jax.random.PRNGKey(7))
        c = generate(m, prompt, 6, temperature=0.8, key=jax.random.PRNGKey(8))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_sampling_requires_key(self):
        m = _model()
        import pytest

        with pytest.raises(ValueError, match="requires a PRNG key"):
            generate(m, jnp.zeros((1, 4), jnp.int32), 4, temperature=1.0)

    def test_zero_new_tokens_returns_prompt(self):
        m = _model()
        prompt = jnp.zeros((1, 4), jnp.int32)
        out = generate(m, prompt, 0)
        assert out is prompt

    def test_exceeding_max_seq_len_raises(self):
        import pytest

        m = _model()  # max_seq_len=64
        with pytest.raises(ValueError, match="maximum sequence length"):
            generate(m, jnp.zeros((1, 32), jnp.int32), 40)


class TestProfilingHelpers:
    def test_trace_and_memory_stats(self, tmp_path):
        import os

        from torchdistx_tpu.utils import (
            annotate,
            device_memory_stats,
            format_memory_stats,
            trace,
        )

        with trace(str(tmp_path)):
            with annotate("probe"):
                jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
        files = sum(len(f) for _, _, f in os.walk(tmp_path))
        assert files > 0
        stats = device_memory_stats()
        assert isinstance(stats, dict) and stats
        assert isinstance(format_memory_stats(stats), str)
