"""Data pipeline + Trainer loop, incl. checkpoint/resume of a full training
run (the reference's save/reload round-trip pattern at trainer scale)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu import nn
from torchdistx_tpu.data import DataLoader, TokenDataset
from torchdistx_tpu.nn import functional_call
from torchdistx_tpu.optimizers import anyprecision_adamw
from torchdistx_tpu.parallel import ShardedTrainStep
from torchdistx_tpu.trainer import Trainer


class TestTokenDataset:
    def test_examples(self):
        ds = TokenDataset(np.arange(100), seq_len=10)
        assert len(ds) == 9
        x, y = ds[0]
        np.testing.assert_array_equal(x, np.arange(10))
        np.testing.assert_array_equal(y, np.arange(1, 11))


class TestDataLoader:
    def test_batching_and_shapes(self):
        ds = TokenDataset(np.arange(1000), seq_len=16)
        dl = DataLoader(ds, batch_size=4, prefetch=0)
        x, y = next(iter(dl))
        assert x.shape == (4, 16) and y.shape == (4, 16)
        assert isinstance(x, jax.Array)

    def test_shuffle_deterministic(self):
        ds = TokenDataset(np.arange(1000), seq_len=8)
        a = DataLoader(ds, batch_size=4, shuffle=True, seed=7, prefetch=0)
        b = DataLoader(ds, batch_size=4, shuffle=True, seed=7, prefetch=0)
        xa, _ = next(iter(a))
        xb, _ = next(iter(b))
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))

    def test_prefetch_matches_sync(self):
        ds = TokenDataset(np.arange(500), seq_len=8)
        sync = [np.asarray(x) for x, _ in DataLoader(ds, 4, prefetch=0)]
        pre = [np.asarray(x) for x, _ in DataLoader(ds, 4, prefetch=3)]
        assert len(sync) == len(pre)
        for a, b in zip(sync, pre):
            np.testing.assert_array_equal(a, b)

    def test_sharded_batches(self, mesh8):
        ds = TokenDataset(np.arange(2000), seq_len=16)
        sh = NamedSharding(mesh8, P("fsdp"))
        dl = DataLoader(ds, batch_size=8, sharding=sh, prefetch=2)
        x, _ = next(iter(dl))
        assert x.sharding.is_equivalent_to(sh, x.ndim)

    def test_resume_state(self):
        ds = TokenDataset(np.arange(1000), seq_len=8)
        dl = DataLoader(ds, batch_size=4, shuffle=True, seed=3, prefetch=0)
        it = iter(dl)
        next(it), next(it)
        sd = dl.state_dict()
        expected = next(it)

        dl2 = DataLoader(ds, batch_size=4, shuffle=True, seed=3, prefetch=0)
        dl2.load_state_dict(sd)
        got = next(iter(dl2))
        np.testing.assert_array_equal(np.asarray(expected[0]), np.asarray(got[0]))

    def test_resume_state_exact_under_prefetch(self):
        # regression: the prefetch worker must not advance resume state
        # beyond what the consumer has received
        ds = TokenDataset(np.arange(1000), seq_len=8)
        dl = DataLoader(ds, batch_size=4, shuffle=True, seed=3, prefetch=3)
        it = iter(dl)
        next(it), next(it)
        assert dl.state_dict()["pos"] == 2
        expected = next(it)
        it.close()  # abandon mid-epoch; worker must shut down

        dl2 = DataLoader(ds, batch_size=4, shuffle=True, seed=3, prefetch=3)
        dl2.load_state_dict({"epoch": 0, "pos": 2, "seed": 3})
        got = next(iter(dl2))
        np.testing.assert_array_equal(np.asarray(expected[0]), np.asarray(got[0]))

    def test_prefetch_thread_shutdown_on_abandon(self):
        import threading

        before = threading.active_count()
        ds = TokenDataset(np.arange(10000), seq_len=8)
        for _ in range(5):
            it = iter(DataLoader(ds, batch_size=4, prefetch=2))
            next(it)
            it.close()
        import time

        time.sleep(0.5)
        assert threading.active_count() <= before + 1


class TestTrainer:
    def _setup(self, mesh):
        tdx.manual_seed(0)
        model = tdx.deferred_init(
            lambda: nn.Sequential(nn.Embedding(64, 32), nn.Linear(32, 64))
        )
        tdx.materialize_module(model)

        def loss_fn(p, batch):
            x, y = batch
            logits = functional_call(model, p, (x,))
            return nn.functional.cross_entropy(logits, y)

        step = ShardedTrainStep(
            loss_fn, anyprecision_adamw(1e-2), mesh, shard_axis="fsdp"
        )
        params = step.shard_params(dict(model.named_parameters()))
        return step, params

    def test_fit_and_resume(self, mesh8, tmp_path):
        step, params = self._setup(mesh8)
        ds = TokenDataset(np.arange(10_000) % 64, seq_len=16)
        logs = []
        trainer = Trainer(
            step,
            params,
            tokens_per_batch=8 * 16,
            checkpoint_dir=str(tmp_path),
            checkpoint_every=5,
            log_every=5,
            log_fn=logs.append,
        )
        dl = DataLoader(ds, batch_size=8, shuffle=True, seed=0)
        out = trainer.fit(iter(dl), num_steps=10)
        assert out["step"] == 10
        assert logs and "tokens_per_sec" in logs[0]
        first_loss, last_loss = logs[0]["loss"], logs[-1]["loss"]
        assert last_loss < first_loss

        # resume from the step-10 checkpoint and keep training
        trainer2 = Trainer(step, params, log_every=5, log_fn=logs.append)
        trainer2.restore(str(tmp_path / "step_10"))
        assert trainer2.global_step == 10
        # optimizer state classes rebuilt (NamedTuple, not dict)
        assert type(trainer2.opt_state).__name__ == "AnyPrecisionAdamWState"
        np.testing.assert_allclose(
            np.asarray(trainer2.opt_state.exp_avg["1.weight"]),
            np.asarray(trainer.opt_state.exp_avg["1.weight"]),
        )
        out2 = trainer2.fit(iter(dl), num_steps=15)
        assert out2["step"] == 15


def test_cost_summary():
    import jax.numpy as jnp

    from torchdistx_tpu.utils.profiling import cost_summary

    def f(a, b):
        return (a @ b).sum()

    a = jnp.ones((64, 32)); b = jnp.ones((32, 16))
    out = cost_summary(f, a, b, peak_flops=1e12)
    # matmul flops = 2*64*32*16
    assert out["flops"] >= 2 * 64 * 32 * 16 * 0.9
    assert out["bytes_accessed"] > 0
    assert out["arithmetic_intensity"] > 0
    assert out["compute_bound_s"] == out["flops"] / 1e12
