"""Collectives + comm hooks + sharded train step.

Test strategy mirrors the reference (SURVEY §4): emulate nodes as mesh
sub-axes on one host, inject deterministic virtual topologies
(state.topologies_set = [perm] + state.topology_cycle = cycle([0]) +
pinned state.iteration — see TestGossipGraD._pin, the analog of
test_comm_hooks_fsdp.py:492-493), and check closed-form expected gradients
computed from rank-valued inputs (:504-525)."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from torchdistx_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu import nn
from torchdistx_tpu.nn import functional_call
from torchdistx_tpu.parallel import (
    GossipGraDState,
    ShardedTrainStep,
    Topology,
    collectives,
    gossip_grad_hook,
    hierarchical_mesh,
)
from torchdistx_tpu.parallel.comm_hooks import HookContext
from torchdistx_tpu.slowmo import SlowMoState, slowmo_hook


def run_on_axis(mesh, fn, x, in_spec, out_spec):
    return shard_map(
        fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec, check_vma=False
    )(x)


class TestCollectives:
    def test_all_reduce_and_mean(self, mesh8):
        x = jnp.arange(8.0)

        out = run_on_axis(
            mesh8, lambda v: collectives.all_reduce(v, "fsdp"), x, P("fsdp"), P("fsdp")
        )
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

        out = run_on_axis(
            mesh8, lambda v: collectives.all_mean(v, "fsdp"), x, P("fsdp"), P("fsdp")
        )
        np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))

    def test_broadcast(self, mesh8):
        x = jnp.arange(8.0)
        out = run_on_axis(
            mesh8,
            lambda v: collectives.broadcast(v, "fsdp", source=3),
            x,
            P("fsdp"),
            P("fsdp"),
        )
        np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))

    def test_exchange_ring(self, mesh8):
        x = jnp.arange(8.0)
        send = [(i + 1) % 8 for i in range(8)]
        recv = [(i - 1) % 8 for i in range(8)]
        out = run_on_axis(
            mesh8,
            lambda v: collectives.exchange(v, "fsdp", send, recv),
            x,
            P("fsdp"),
            P("fsdp"),
        )
        np.testing.assert_allclose(np.asarray(out), np.array(recv, np.float32))

    def test_exchange_invalid_peer_keeps_own_value(self, mesh8):
        # INVALID_PEER members (no incoming edge) must NOT see zeros-that-
        # look-like-data: the default fill="self" hands them their own
        # value back (no-op exchange); fill="zero" restores raw ppermute
        # semantics for callers with their own validity masks.
        x = jnp.arange(8.0) + 1.0  # nonzero everywhere
        # pair exchange among members 0-3 only; 4-7 are INVALID_PEER
        send = [1, 0, 3, 2, -1, -1, -1, -1]
        recv = [1, 0, 3, 2, -1, -1, -1, -1]
        out = run_on_axis(
            mesh8,
            lambda v: collectives.exchange(v, "fsdp", send, recv),
            x,
            P("fsdp"),
            P("fsdp"),
        )
        np.testing.assert_allclose(
            np.asarray(out), np.array([2, 1, 4, 3, 5, 6, 7, 8], np.float32)
        )
        out = run_on_axis(
            mesh8,
            lambda v: collectives.exchange(v, "fsdp", send, recv, fill="zero"),
            x,
            P("fsdp"),
            P("fsdp"),
        )
        np.testing.assert_allclose(
            np.asarray(out), np.array([2, 1, 4, 3, 0, 0, 0, 0], np.float32)
        )

    def test_exchange_inconsistent_peers_raises(self, mesh8):
        x = jnp.arange(8.0)
        send = [(i + 1) % 8 for i in range(8)]
        recv = [(i + 1) % 8 for i in range(8)]  # wrong: implies -1 shift
        with pytest.raises(ValueError, match="inconsistent peer lists"):
            run_on_axis(
                mesh8,
                lambda v: collectives.exchange(v, "fsdp", send, recv),
                x,
                P("fsdp"),
                P("fsdp"),
            )

    def test_shift(self, mesh8):
        x = jnp.arange(8.0)
        out = run_on_axis(
            mesh8, lambda v: collectives.shift(v, "fsdp", 2), x, P("fsdp"), P("fsdp")
        )
        # member (i+2) receives i's value
        expected = np.array([(i - 2) % 8 for i in range(8)], np.float32)
        np.testing.assert_allclose(np.asarray(out), expected)


class TestGossipGraD:
    def _run_hook(self, mesh, state, grads_per_node):
        """grads_per_node: (num_nodes,) values; runs the hook on a
        ('node','local') mesh with the deterministic current topology."""
        ctx_axes = ("node", "local")
        x = jnp.repeat(
            jnp.asarray(grads_per_node), mesh.shape["local"]
        )  # per-device grad, identical within a node

        def body(v):
            ctx = HookContext(replica_axes=ctx_axes, step=state.step_args())
            return gossip_grad_hook(state, v, ctx)

        out = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(("node", "local")),),
            out_specs=P(("node", "local")),
            check_vma=False,
        )(x)
        return np.asarray(out).reshape(mesh.shape["node"], mesh.shape["local"])

    @staticmethod
    def _pin(state, topology, iteration=0):
        """Inject a deterministic virtual topology (the analog of the
        reference tests' state.topologies = itertools.cycle([...]),
        test_comm_hooks_fsdp.py:492-493) and pin the step so
        current_power = iteration % gossip_period."""
        state.topologies_set = [tuple(topology)]
        state.topology_cycle = itertools.cycle([0])
        state.iteration = iteration

    def test_cube_closed_form(self, mesh2x4):
        # 2 nodes x 4 local; CUBE power 0: peer = node ^ 1
        state = GossipGraDState(2, topology=Topology.CUBE, seed=0)
        self._pin(state, [0, 1])
        out = self._run_hook(mesh2x4, state, [0.0, 1.0])
        # intra-node mean keeps node value; gossip: (0+1)/2 = 0.5 everywhere
        np.testing.assert_allclose(out, np.full((2, 4), 0.5))

    def test_dissemination_closed_form(self):
        mesh = hierarchical_mesh(4)  # 4 nodes x 2 local
        state = GossipGraDState(4, topology=Topology.DISSEMINATION, seed=0)
        # gossip_period = 2, so iteration 1 -> power 1
        self._pin(state, [0, 1, 2, 3], iteration=1)
        assert state.current_power == 1
        out = self._run_hook(mesh, state, [0.0, 1.0, 2.0, 3.0])
        # node i receives from (i-2) % 4: out[i] = (i + (i-2)%4) / 2
        expected = np.array(
            [[(i + (i - 2) % 4) / 2.0] * 2 for i in range(4)]
        )
        np.testing.assert_allclose(out, expected)

    def test_dissemination_permuted_topology(self):
        # Non-identity virtual topology: peers are computed on positions in
        # the permutation and mapped back (reference _get_send_recv_peers,
        # gossip_grad.py:238-247 via cur_topology.index/indexing).
        mesh = hierarchical_mesh(4)
        state = GossipGraDState(4, topology=Topology.DISSEMINATION, seed=0)
        topo = [2, 0, 3, 1]  # position of node i: pos = topo.index(i)
        self._pin(state, topo, iteration=0)  # power 0, stride 1
        out = self._run_hook(mesh, state, [0.0, 1.0, 2.0, 3.0])
        # node i (at pos p) receives from topo[(p - 1) % 4]
        pos = {n: p for p, n in enumerate(topo)}
        expected = np.array(
            [[(i + topo[(pos[i] - 1) % 4]) / 2.0] * 2 for i in range(4)]
        )
        np.testing.assert_allclose(out, expected)

    def test_cube_invalid_peer_skips(self):
        # 6 nodes (non-power-of-2): power 2 -> peer = i ^ 4 invalid for i in
        # {2,3} (peers 6,7 do not exist) -> those keep their gradient
        # (reference INVALID_PEER, gossip_grad.py:238-241)
        devs = jax.devices()[:6]
        mesh = Mesh(np.array(devs).reshape(6, 1), ("node", "local"))
        state = GossipGraDState(6, topology=Topology.CUBE, seed=0)
        # gossip_period = ceil(log2(6)) = 3, so iteration 2 -> power 2
        self._pin(state, [0, 1, 2, 3, 4, 5], iteration=2)
        assert state.current_power == 2
        out = self._run_hook(mesh, state, [0.0, 1.0, 2.0, 3.0, 4.0, 5.0])
        expected = np.array(
            [[(0 + 4) / 2], [(1 + 5) / 2], [2.0], [3.0], [(4 + 0) / 2], [(5 + 1) / 2]]
        )
        np.testing.assert_allclose(out, expected)

    def test_default_schedule(self):
        # Reference schedule (gossip_grad.py:236,378-380): power varies
        # EVERY adjusted step as adjusted % gossip_period; the shuffled
        # virtual topology rotates every gossip_period adjusted steps.
        state = GossipGraDState(4, seed=0)
        assert state.gossip_period == 2
        powers, topo_idxs = [], []
        for _ in range(8):
            powers.append(state.current_power)
            topo_idxs.append(state.current_topology_idx)
            state.advance()
        assert powers == [0, 1, 0, 1, 0, 1, 0, 1]
        # one topology held for each full period, rotating each period
        assert topo_idxs[0] == topo_idxs[1]
        assert topo_idxs[2] == topo_idxs[3]
        assert len(set(topo_idxs[::2])) > 1
        # the pre-generated set contains num_nodes seeded permutations
        assert len(state.topologies_set) == 4
        assert all(sorted(t) == [0, 1, 2, 3] for t in state.topologies_set)
        # step_args indexes the deduplicated branch table consistently
        state2 = GossipGraDState(4, seed=0)
        state2.iteration = 3  # period 1, power 1
        specs, index = state2.branch_table()
        assert int(state2.step_args()) == index[
            (state2.current_topology_idx, state2.current_power)
        ]
        # dedup: unique branches never exceed the full (topo, power) grid
        assert len(specs) <= len(state2.topologies_set) * state2.gossip_period

    def test_branch_dedup_two_nodes(self):
        # every 2-node permutation yields the same exchange: 1 unique branch
        state = GossipGraDState(2, seed=0)
        specs, _ = state.branch_table()
        assert len(specs) == 1

    def test_branch_table_bounded_at_pod_scale(self):
        # VERDICT r3 weak#5: un-capped, 64 nodes is worst-case
        # 64 * ceil(log2 64) = 384 CollectivePermute branches in every
        # jitted step.  The max_branches budget (default 64) caps the
        # topology set so the switch stays compile-cheap at pod scale.
        import time as _time

        t0 = _time.perf_counter()
        state = GossipGraDState(64, seed=0)
        specs, index = state.branch_table()
        build_s = _time.perf_counter() - t0
        assert state.gossip_period == 6
        assert len(state.topologies_set) == 64 // 6  # 10 shuffles kept
        assert len(specs) <= state.max_branches
        # every (topology, power) pair still resolves to a branch
        assert set(index) == {
            (t, p)
            for t in range(len(state.topologies_set))
            for p in range(state.gossip_period)
        }
        assert build_s < 5.0, f"branch table build took {build_s:.1f}s"
        # 256 nodes: still bounded by the same budget
        big = GossipGraDState(256, seed=0)
        specs256, _ = big.branch_table()
        assert len(specs256) <= big.max_branches

    @pytest.mark.slow
    def test_max_branches_capped_schedule_executes(self):
        # A capped schedule must still run end-to-end: 8 nodes with a
        # 6-branch budget keeps 2 of 8 shuffles (period 3) and the hook
        # executes every branch of the reduced switch.
        devs = jax.devices()[:8]
        mesh = Mesh(np.array(devs).reshape(8, 1), ("node", "local"))
        state = GossipGraDState(8, seed=0, max_branches=6)
        assert len(state.topologies_set) == 2
        specs, _ = state.branch_table()
        assert len(specs) <= 6
        for _ in range(state.gossip_period * 2):  # sweep both topologies
            out = self._run_hook(
                mesh, state, [float(i) for i in range(8)]
            )
            assert np.isfinite(out).all()
            state.advance()

    def test_max_branches_too_small_rejected(self):
        with pytest.raises(ValueError, match="max_branches"):
            GossipGraDState(64, max_branches=3)  # period 6 won't fit

    def test_num_modules_adjustment(self):
        # num_modules > 1: power/topology advance once per num_modules hook
        # invocations (reference gossip_grad.py:373-379)
        state = GossipGraDState(4, seed=0, num_modules=3)
        powers = []
        for _ in range(6):
            powers.append(state.current_power)
            state.advance()
        assert powers == [0, 0, 0, 1, 1, 1]

    def test_num_modules_schedule_parity(self):
        # k>1 full-schedule parity: per hook call, power follows the
        # reference formula (iter // k) % period EXACTLY, and the virtual
        # topology never changes mid-backward (within one k-call group) —
        # rotating only at window boundaries (our documented deviation:
        # once per gossip_period adjusted steps, not re-drawn every
        # power-0 call; reference gossip_grad.py:373-380)
        k, period, n = 3, 2, 4
        state = GossipGraDState(n, seed=0, num_modules=k)
        assert state.gossip_period == period
        n_calls = k * period * 4  # four full rotation windows
        trace = []
        for it in range(n_calls):
            assert state.current_power == (it // k) % period
            trace.append((state.current_power, state.current_topology_idx))
            state.advance()
        # grouped by backward pass: constant within each k-call group
        for g in range(0, n_calls, k):
            assert len(set(trace[g:g + k])) == 1, trace[g:g + k]
        # topology constant within a window, rotates at window boundaries
        w = k * period
        windows = [trace[i][1] for i in range(0, n_calls, w)]
        for i in range(0, n_calls, w):
            assert len({t for _, t in trace[i:i + w]}) == 1
        assert any(a != b for a, b in zip(windows, windows[1:]))

    def test_get_num_modules(self):
        # the reference's FSDP-module counter analog: parameter-owning
        # submodules are the hook-calling units (gossip_grad.py:319-331)
        from torchdistx_tpu import nn
        from torchdistx_tpu.parallel import get_num_modules

        class Block(nn.Module):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

        class Net(nn.Module):
            def __init__(self):
                super().__init__()
                self.b1 = Block()  # owns no params directly
                self.b2 = Block()

        net = Net()
        # b1.fc and b2.fc own params directly; Block/Net wrappers do not
        assert get_num_modules(net) == 2
        assert get_num_modules(nn.Linear(4, 4)) == 1

        class Empty(nn.Module):
            pass

        assert get_num_modules(Empty()) == 1  # still fires one hook call
        state = GossipGraDState(4, num_modules=get_num_modules(net))
        assert state.num_modules == 2

    def test_cube_odd_nodes_rejected(self):
        # parity: gossip_grad.py:135-139
        with pytest.raises(ValueError, match="uneven"):
            GossipGraDState(3, topology=Topology.CUBE)

    def test_default_topology_is_dissemination(self):
        # parity: gossip_grad.py: 'topology or Topology.DISSEMINATION'
        assert GossipGraDState(4).topology is Topology.DISSEMINATION

    def test_needs_two_nodes(self):
        with pytest.raises(ValueError):
            GossipGraDState(1)


class TestSlowMoHook:
    def test_intra_node_only(self, mesh2x4):
        state = SlowMoState(subgroup_axis="local")
        x = jnp.arange(8.0)

        def body(v):
            ctx = HookContext(replica_axes=("node", "local"), step=None)
            return slowmo_hook(state, v, ctx)

        out = shard_map(
            body,
            mesh=mesh2x4,
            in_specs=(P(("node", "local")),),
            out_specs=P(("node", "local")),
            check_vma=False,
        )(x)
        out = np.asarray(out).reshape(2, 4)
        # averaged within node, NOT across nodes
        np.testing.assert_allclose(out[0], np.full(4, 1.5))
        np.testing.assert_allclose(out[1], np.full(4, 5.5))

    def test_sync_grads_off(self, mesh2x4):
        state = SlowMoState(subgroup_axis="local", sync_grads=False)
        x = jnp.arange(8.0)

        def body(v):
            ctx = HookContext(replica_axes=("node", "local"), step=None)
            return slowmo_hook(state, v, ctx)

        out = shard_map(
            body,
            mesh=mesh2x4,
            in_specs=(P(("node", "local")),),
            out_specs=P(("node", "local")),
            check_vma=False,
        )(x)
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


class MLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _batch(n=16):
    rs = np.random.RandomState(0)
    return (
        rs.randn(n, 16).astype(np.float32),
        rs.randn(n, 4).astype(np.float32),
    )


class TestShardedTrainStep:
    def test_fsdp_matches_single_device(self, mesh8):
        tdx.manual_seed(5)
        model = tdx.deferred_init(MLP)
        tdx.materialize_module(model)
        params = dict(model.named_parameters())

        def loss_fn(p, batch):
            x, y = batch
            return jnp.mean((functional_call(model, p, (x,)) - y) ** 2)

        batch = _batch()

        # single-device reference
        tx = optax.adam(1e-2)

        @jax.jit
        def ref_step(p, s, b):
            g = jax.grad(loss_fn)(p, b)
            u, s = tx.update(g, s, p)
            return jax.tree_util.tree_map(lambda a, b_: a + b_, p, u), s

        ref_p, ref_s = dict(params), tx.init(params)
        for _ in range(3):
            ref_p, ref_s = ref_step(ref_p, ref_s, batch)

        # sharded
        step = ShardedTrainStep(loss_fn, optax.adam(1e-2), mesh8, shard_axis="fsdp")
        p = step.shard_params(params)
        s = step.init_optimizer(p)
        for _ in range(3):
            p, s, loss = step(p, s, batch)

        for k in params:
            np.testing.assert_allclose(
                np.asarray(p[k]), np.asarray(ref_p[k]), rtol=2e-5, atol=2e-6
            )

    def test_divergent_grads_use_full_node_batch(self):
        # regression: with divergent replicas over 'node' and batch sharded
        # over ('node','local'), the trainer must mean-reduce gradients over
        # 'local' — every local device's data counts, per node.
        from torchdistx_tpu.parallel import noop_hook

        mesh = hierarchical_mesh(2)  # 2 nodes x 4 local
        params = {"w": jnp.zeros((1,))}

        def loss_fn(p, batch):
            return jnp.mean(p["w"] * batch)

        lr = 1.0
        step = ShardedTrainStep(
            loss_fn,
            optax.sgd(lr),
            mesh,
            shard_axis=None,
            replica_axes=("node",),
            comm_hook=noop_hook,
            divergent_replicas=True,
            batch_axes=("node", "local"),
        )
        p = step.stack_replicas(params)
        s = step.init_optimizer(p)
        batch = np.arange(16.0, dtype=np.float32)  # rows 0-7 node0, 8-15 node1
        p, s, _ = step(p, s, batch)
        w = np.asarray(p["w"])  # delta = -lr * mean(node rows)
        np.testing.assert_allclose(w[0, 0], -np.mean(batch[:8]), rtol=1e-6)
        np.testing.assert_allclose(w[1, 0], -np.mean(batch[8:]), rtol=1e-6)

    def test_divergent_gossip_training_decreases_loss(self):
        mesh = hierarchical_mesh(4)
        tdx.manual_seed(6)
        model = tdx.deferred_init(MLP)
        tdx.materialize_module(model)
        params = dict(model.named_parameters())

        def loss_fn(p, batch):
            x, y = batch
            return jnp.mean((functional_call(model, p, (x,)) - y) ** 2)

        state = GossipGraDState(4, topology=Topology.DISSEMINATION, seed=0)
        step = ShardedTrainStep(
            loss_fn,
            optax.sgd(5e-2),
            mesh,
            shard_axis=None,
            replica_axes=("node",),
            comm_hook=gossip_grad_hook,
            hook_state=state,
            divergent_replicas=True,
            batch_axes=("node", "local"),
        )
        p = step.stack_replicas(params)
        s = step.init_optimizer(p)
        batch = _batch()
        losses = []
        for _ in range(10):
            p, s, loss = step(p, s, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7
        final = step.consensus(p)
        assert final["fc1.weight"].shape == (32, 16)

    def test_divergent_slowmo_training_end_to_end(self):
        # SlowMo through the full sharded trainer, the reference's
        # test_comm_hooks_fsdp.py:242-331 composition: slowmo_hook does the
        # intra-node ('local') gradient mean, slow_momentum's periodic
        # averaging is the only cross-node sync, and replicas re-converge
        # exactly on every slowmo_freq boundary.
        from torchdistx_tpu.slowmo import slow_momentum

        mesh = hierarchical_mesh(4)
        tdx.manual_seed(9)
        model = tdx.deferred_init(MLP)
        tdx.materialize_module(model)
        params = dict(model.named_parameters())

        def loss_fn(p, batch):
            x, y = batch
            return jnp.mean((functional_call(model, p, (x,)) - y) ** 2)

        freq = 3
        tx = slow_momentum(
            optax.sgd(5e-2),
            slowmo_freq=freq,
            slowmo_factor=0.5,
            slowmo_lr=1.0,
            base_lr=5e-2,
        )
        step = ShardedTrainStep(
            loss_fn,
            tx,
            mesh,
            shard_axis=None,
            replica_axes=("node",),
            comm_hook=slowmo_hook,
            hook_state=SlowMoState(),
            divergent_replicas=True,
            batch_axes=("node", "local"),
        )
        p = step.stack_replicas(params)
        s = step.init_optimizer(p)
        batch = _batch()
        losses = []
        for i in range(1, 10):
            p, s, loss = step(p, s, batch)
            losses.append(float(loss))
            w = np.asarray(p["fc1.weight"])
            same = all(
                np.allclose(w[0], w[r], rtol=1e-6, atol=1e-7)
                for r in range(1, w.shape[0])
            )
            if i % freq == 0:
                # slow step: periodic averaging just re-synced all nodes
                assert same, f"replicas diverged after slow step {i}"
            elif i % freq == 1 and i > 1:
                # first fast step after a slow one: nodes see different
                # data shards and must have drifted apart again
                assert not same, f"replicas unexpectedly in sync at {i}"
        assert losses[-1] < losses[0] * 0.7


class TestShardedAccumulation:
    def test_accum_matches_full_batch(self, mesh8):
        """ShardedTrainStep accum_steps=2 must reproduce the full-batch
        update (same samples, averaged gradients; hook runs once)."""
        tdx.manual_seed(12)
        model = tdx.deferred_init(MLP)
        tdx.materialize_module(model)
        params = dict(model.named_parameters())

        def loss_fn(p, batch):
            x, y = batch
            return jnp.mean((functional_call(model, p, (x,)) - y) ** 2)

        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(16, 16), jnp.float32)
        y = jnp.sum(x[:, :4], axis=1, keepdims=True)

        outs = {}
        for accum in (1, 2):
            step = ShardedTrainStep(
                loss_fn,
                optax.sgd(1e-2),
                mesh8,
                shard_axis="fsdp",
                accum_steps=accum,
            )
            p = step.shard_params(
                jax.tree_util.tree_map(lambda a: a + 0, params)
            )
            s = step.init_optimizer(p)
            p, s, loss = step(p, s, (x, y))
            outs[accum] = (p, float(loss))

        assert np.isclose(outs[1][1], outs[2][1], rtol=1e-5)
        for k in outs[1][0]:
            np.testing.assert_allclose(
                np.asarray(outs[1][0][k]),
                np.asarray(outs[2][0][k]),
                rtol=3e-6,
                atol=3e-7,
                err_msg=k,
            )


class TestOptimizerStateShardings:
    def test_mismatched_shape_state_replicates(self, mesh8):
        # a factored optimizer (Adafactor-style row/col second moments)
        # keeps the param tree's PATHS with differently shaped leaves —
        # the path-subset heuristic alone would hand those the param's
        # PartitionSpec, mis-sharding (or failing to apply to) them.
        # Shape-mismatched leaves must fall back to replicated; exactly
        # sized siblings still inherit.
        from jax.sharding import NamedSharding
        from torchdistx_tpu.parallel.fsdp import optimizer_state_shardings

        params = {
            "w": jax.device_put(
                jnp.zeros((64, 8)), NamedSharding(mesh8, P("fsdp"))
            ),
            "b": jax.device_put(jnp.zeros((8,)), NamedSharding(mesh8, P())),
        }
        state_shape = {
            # row/col factors: param paths, wrong sizes
            "factored": {
                "w": jax.ShapeDtypeStruct((64,), jnp.float32),
                "b": jax.ShapeDtypeStruct((1,), jnp.float32),
            },
            # full-size moments: param paths, exact sizes
            "moments": {
                "w": jax.ShapeDtypeStruct((64, 8), jnp.float32),
                "b": jax.ShapeDtypeStruct((8,), jnp.float32),
            },
            # mixed subtree: one exact leaf, one factored — the gate is
            # per leaf, so the exact sibling keeps its param sharding
            "mixed": {
                "w": jax.ShapeDtypeStruct((64, 8), jnp.float32),
                "b": jax.ShapeDtypeStruct((1,), jnp.float32),
            },
            "count": jax.ShapeDtypeStruct((), jnp.int32),
        }
        sh = optimizer_state_shardings(state_shape, params, mesh8)
        assert sh["factored"]["w"].spec == P()
        assert sh["factored"]["b"].spec == P()
        assert sh["moments"]["w"].spec == P("fsdp")
        assert sh["moments"]["b"].spec == P()
        assert sh["mixed"]["w"].spec == P("fsdp")
        assert sh["mixed"]["b"].spec == P()
        assert sh["count"].spec == P()
