"""SLO observatory (ISSUE 14): spec validation, the deterministic /
timing-derived report split, burn-rate alert states, flight events, the
Prometheus projection, the schema validator, and the ledger's exact
counter pins."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from torchdistx_tpu.obs.slo import (
    SLO_SCHEMA,
    SloSpec,
    evaluate_slo,
    slo_collector,
    validate_slo_report,
)
from torchdistx_tpu.serve.scheduler import Request

SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)


def _req(
    tid,
    *,
    submitted=100.0,
    admitted=100.1,
    first=100.5,
    finished=101.0,
    n_tokens=4,
    reason="length",
):
    r = Request(
        rid=tid,
        prompt=np.arange(4, dtype=np.int32),
        max_new_tokens=n_tokens,
        trace_id=tid,
    )
    r.submitted_at = submitted
    r.admitted_at = admitted
    r.first_token_at = first
    r.finished_at = finished
    r.generated = list(range(n_tokens))
    r.finish_reason = reason
    return r


class TestSloSpec:
    def test_roundtrip_and_file_loading(self, tmp_path):
        spec = SloSpec(
            name="gold",
            ttft_p95_s=0.5,
            e2e_p95_s=2.0,
            deadline_s=3.0,
            attainment_target=0.99,
            windows_s=(60.0, 300.0),
        )
        assert SloSpec.from_json(spec.to_json()) == spec
        p = tmp_path / "spec.json"
        p.write_text(json.dumps(spec.to_json()))
        assert SloSpec.from_json(str(p)) == spec

    def test_committed_specs_parse(self):
        # the two specs the nightly runs under must always load
        for fname in ("slo_fleet_smoke.json", "slo_burn_inject.json"):
            path = os.path.join(
                os.path.dirname(SCRIPTS), "expectations", fname
            )
            spec = SloSpec.from_json(path)
            assert spec.attainment_target == 1.0
        assert SloSpec.from_json(
            os.path.join(
                os.path.dirname(SCRIPTS),
                "expectations",
                "slo_burn_inject.json",
            )
        ).deadline_s == pytest.approx(1e-6)

    def test_validation_rejects_bad_specs(self):
        with pytest.raises(ValueError, match="ttft_p95_s"):
            SloSpec(ttft_p95_s=0.0)
        with pytest.raises(ValueError, match="deadline_s"):
            SloSpec(deadline_s=-1.0)
        with pytest.raises(ValueError, match="attainment_target"):
            SloSpec(attainment_target=1.5)
        with pytest.raises(ValueError, match="ascending"):
            SloSpec(windows_s=(300.0, 60.0))
        with pytest.raises(ValueError, match="ascending"):
            SloSpec(windows_s=(60.0, 60.0))
        with pytest.raises(ValueError, match="at least one"):
            SloSpec(windows_s=())
        with pytest.raises(ValueError, match="burn_threshold"):
            SloSpec(burn_threshold=0.0)
        with pytest.raises(ValueError, match="unknown"):
            SloSpec.from_json({"name": "x", "latency_target": 1.0})


class TestEvaluate:
    def test_counters_are_the_deterministic_half(self):
        reqs = [
            _req(1),
            _req(2, finished=103.0, reason="deadline"),  # truncated
            _req(3, finished=102.0, reason="cache_full"),  # truncated
            _req(4, finished=109.0),  # slow but untruncated
        ]
        spec = SloSpec(name="t", deadline_s=5.0, windows_s=(1000.0,))
        rep = evaluate_slo(spec, reqs, now=110.0, flight=False)
        assert rep["schema"] == SLO_SCHEMA
        assert rep["counters"] == {
            "requests_total": 4,
            "requests_attained": 1,
            "requests_violated": 3,
            "requests_truncated_deadline": 1,
            "requests_truncated_cache_full": 1,
            "tokens_attained": 4,
        }
        assert rep["attainment"]["overall"] == 0.25
        assert rep["attainment"]["ok"] is False
        assert rep["breached"] is True
        # goodput rates derive from the same counters over the span
        span = 109.0 - 100.0
        assert rep["goodput"]["span_s"] == span
        assert rep["goodput"]["requests_attained_per_s"] == 1 / span
        assert rep["goodput"]["tokens_attained_per_s"] == 4 / span

    def test_percentile_targets_and_breached_axes(self):
        # 10 requests, ttft 0.5s each, e2e 1.0s each
        reqs = [_req(i) for i in range(10)]
        spec = SloSpec(name="p", ttft_p95_s=0.6, e2e_p95_s=0.9)
        rep = evaluate_slo(spec, reqs, now=200.0, flight=False)
        assert rep["percentiles"]["ttft_p95_s"]["ok"] is True
        assert rep["percentiles"]["ttft_p95_s"]["measured"] == 0.5
        assert rep["percentiles"]["e2e_p95_s"]["ok"] is False
        assert rep["breached_axes"] == ["e2e_p95_s"]
        assert rep["breached"] is True
        # axes with no target still report measured values
        assert rep["percentiles"]["tpot_p50_s"]["target"] is None

    def test_empty_history_is_indeterminate_not_breached(self):
        rep = evaluate_slo(SloSpec(), [], now=0.0, flight=False)
        assert rep["counters"]["requests_total"] == 0
        assert rep["attainment"]["overall"] is None
        assert rep["breached"] is False
        assert rep["burn"]["state"] == "ok"

    def test_burn_states_escalate_per_window(self):
        # violations confined to the last 60s: the fast window burns
        # (warn), the slow window has enough old good requests to stay
        # under the budget -> not page
        spec = SloSpec(
            name="b",
            deadline_s=2.0,
            attainment_target=0.5,
            windows_s=(60.0, 1000.0),
        )
        now = 1000.0
        old_good = [
            _req(i, submitted=500.0 + i, finished=501.0 + i)
            for i in range(8)
        ]
        fresh_bad = [
            _req(10 + i, submitted=960.0 + i, finished=970.0 + i)
            for i in range(4)
        ]
        rep = evaluate_slo(
            spec, old_good + fresh_bad, now=now, flight=False
        )
        fast, slow = rep["burn"]["windows"]
        assert fast["window_s"] == 60.0 and fast["violations"] == 4
        assert fast["burning"] is True and fast["burn_rate"] == 2.0
        assert slow["violations"] == 4 and slow["requests"] == 12
        assert slow["burning"] is False
        assert rep["burn"]["state"] == "warn"
        # every window burning escalates to page
        rep2 = evaluate_slo(spec, fresh_bad, now=now, flight=False)
        assert rep2["burn"]["state"] == "page"
        # zero budget (100% target): any violation burns, rate is None
        spec3 = SloSpec(name="z", deadline_s=2.0, windows_s=(60.0,))
        rep3 = evaluate_slo(spec3, fresh_bad, now=now, flight=False)
        (w3,) = rep3["burn"]["windows"]
        assert w3["burn_rate"] is None and w3["burning"] is True
        assert rep3["burn"]["state"] == "page"

    def test_breach_lands_a_named_flight_event(self):
        class Flight:
            def __init__(self):
                self.recs = []

            def record(self, kind, **fields):
                self.recs.append((kind, fields))

        fl = Flight()
        spec = SloSpec(name="paged-slo", deadline_s=0.1, windows_s=(60.0,))
        evaluate_slo(
            spec,
            [_req(1, finished=105.0)],
            now=105.0,
            policy="affinity",
            flight=fl,
        )
        assert len(fl.recs) == 1
        kind, fields = fl.recs[0]
        assert kind == "slo_burn"
        assert fields["slo"] == "paged-slo"
        assert fields["policy"] == "affinity"
        assert fields["state"] == "page"
        assert fields["attainment"] == 0.0
        assert fields["requests_violated"] == 1
        # a healthy evaluation records nothing
        ok_spec = SloSpec(name="ok", deadline_s=100.0, windows_s=(60.0,))
        evaluate_slo(ok_spec, [_req(2)], now=101.0, flight=fl)
        assert len(fl.recs) == 1


class TestCollector:
    def test_projection_renders_next_to_fleet_gauges(self):
        from torchdistx_tpu.obs import MetricsRegistry

        class Source:
            def __init__(self, reqs):
                self._reqs = reqs

            def finished_requests(self):
                return self._reqs

        src = Source([_req(1), _req(2, finished=109.0)])
        spec = SloSpec(name="gold", deadline_s=5.0, windows_s=(60.0,))
        registry = MetricsRegistry()
        registry.register_collector(slo_collector(spec, src), obj=src)
        text = registry.render()
        assert 'tdx_slo_requests_total{slo="gold"} 2' in text
        assert 'tdx_slo_requests_attained{slo="gold"} 1' in text
        assert 'tdx_slo_attainment{slo="gold"} 0.5' in text
        assert 'tdx_slo_breached{slo="gold"} 1' in text
        assert 'tdx_slo_burn_state{slo="gold"}' in text
        assert 'window="60.0"' in text
        # weakref: a dead source renders no families and never crashes
        del src
        assert "tdx_slo_requests_total" not in registry.render()


class TestValidator:
    def _good(self):
        spec = SloSpec(name="v", deadline_s=5.0, windows_s=(60.0, 300.0))
        return evaluate_slo(spec, [_req(1)], now=102.0, flight=False)

    def test_good_report_validates(self):
        assert validate_slo_report(self._good()) == []

    def test_corruptions_are_named(self):
        rep = self._good()
        rep["schema"] = "tdx-slo-v0"
        assert any("schema" in e for e in validate_slo_report(rep))
        rep = self._good()
        rep["attainment"]["overall"] = 1.5
        assert any("[0, 1]" in e for e in validate_slo_report(rep))
        rep = self._good()
        rep["counters"]["requests_attained"] = 7
        assert any(
            "attained + violated" in e for e in validate_slo_report(rep)
        )
        rep = self._good()
        rep["burn"]["windows"] = list(reversed(rep["burn"]["windows"]))
        assert any("ascending" in e for e in validate_slo_report(rep))
        rep = self._good()
        rep["burn"]["windows"] = rep["burn"]["windows"][:1]
        assert any(
            "do not match" in e for e in validate_slo_report(rep)
        )
        rep = self._good()
        rep["spec"]["windows_s"] = [300.0, 60.0]
        assert any("parse" in e for e in validate_slo_report(rep))
        assert validate_slo_report([]) != []


class TestLedgerIngest:
    def test_slo_counters_become_exact_pins(self):
        from torchdistx_tpu.obs.ledger import ingest_serve_record

        spec = SloSpec(name="l", deadline_s=5.0, windows_s=(60.0,))
        single = evaluate_slo(spec, [_req(1)], now=102.0, flight=False)
        per_policy = {
            "affinity": single,
            "round_robin": evaluate_slo(
                spec,
                [_req(2), _req(3, finished=109.0)],
                now=110.0,
                flight=False,
            ),
        }
        rows = ingest_serve_record(
            {
                "phases": {
                    "fleet": {"slo": per_policy},
                    "fleet_drain": {"slo": single},
                }
            },
            run_id="r",
            ts=1.0,
        )
        by_key = {
            (r["fingerprint"], r["metric"]): r
            for r in rows
            if r["metric"].startswith("slo_")
        }
        k = ("phase=fleet", "slo_affinity_requests_total")
        assert by_key[k]["value"] == 1
        assert by_key[k]["metric_class"] == "counter"
        assert by_key[
            ("phase=fleet", "slo_round_robin_requests_violated")
        ]["value"] == 1
        assert by_key[
            ("phase=fleet", "slo_round_robin_attainment")
        ]["value"] == 0.5
        assert by_key[
            ("phase=fleet_drain", "slo_requests_attained")
        ]["value"] == 1
        # attainment is a ratio of two deterministic counters — it pins
        # as a counter row, like prefix_hit_rate
        assert all(
            r["metric_class"] == "counter"
            for r in rows
            if r["metric"].startswith("slo_")
        )


class TestSloCLI:
    def test_check_obs_artifacts_slo_mode(self, tmp_path):
        script = os.path.join(SCRIPTS, "check_obs_artifacts.py")
        spec = SloSpec(name="cli", deadline_s=5.0, windows_s=(60.0,))
        rep = evaluate_slo(spec, [_req(1)], now=102.0, flight=False)
        good = {"phases": {"fleet": {"slo": rep}}}
        p_good = tmp_path / "good.json"
        p_good.write_text(json.dumps(good))
        out = subprocess.run(
            [sys.executable, script, "--slo", str(p_good)],
            capture_output=True,
            text=True,
        )
        assert out.returncode == 0, out.stderr
        # a fleet record whose phases carry no slo block must FAIL —
        # silence is not compliance
        p_none = tmp_path / "none.json"
        p_none.write_text(json.dumps({"phases": {"fleet": {}}}))
        out = subprocess.run(
            [sys.executable, script, "--slo", str(p_none)],
            capture_output=True,
            text=True,
        )
        assert out.returncode != 0
        # corrupt attainment fails loudly
        rep_bad = json.loads(json.dumps(rep))
        rep_bad["attainment"]["overall"] = 2.0
        p_bad = tmp_path / "bad.json"
        p_bad.write_text(json.dumps({"phases": {"fleet": {"slo": rep_bad}}}))
        out = subprocess.run(
            [sys.executable, script, "--slo", str(p_bad)],
            capture_output=True,
            text=True,
        )
        assert out.returncode != 0
        assert "[0, 1]" in (out.stderr + out.stdout)

    def test_expect_slo_burn_requires_the_event(self, tmp_path):
        script = os.path.join(SCRIPTS, "check_obs_artifacts.py")
        burn = tmp_path / "flight.jsonl"
        burn.write_text(
            json.dumps(
                {
                    "kind": "slo_burn",
                    "t": 1.0,
                    "slo": "burn-inject",
                    "state": "page",
                }
            )
            + "\n"
        )
        out = subprocess.run(
            [
                sys.executable,
                script,
                "--flight",
                "--expect-slo-burn",
                str(burn),
            ],
            capture_output=True,
            text=True,
        )
        assert out.returncode == 0, out.stderr
        quiet = tmp_path / "quiet.jsonl"
        quiet.write_text(json.dumps({"kind": "stall", "t": 1.0}) + "\n")
        out = subprocess.run(
            [
                sys.executable,
                script,
                "--flight",
                "--expect-slo-burn",
                str(quiet),
            ],
            capture_output=True,
            text=True,
        )
        assert out.returncode != 0
        assert "slo_burn" in (out.stderr + out.stdout)
