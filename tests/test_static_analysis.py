"""tdx-lint contract tests: per-rule fixtures, suppression semantics,
the exact-findings baseline gate, and the CLI's exit-code / JSON-schema
contracts.

Fixture snippets are linted in-memory through ``lint_source`` (the test
seam) — tests/ is deliberately outside the committed lint scope, so
violation snippets here can never leak into the repo baseline.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from torchdistx_tpu.analysis import (
    LINT_SCHEMA,
    RULE_CATALOG,
    compare_to_baseline,
    default_rules,
    finding_key,
    lint_source,
    parse_suppressions,
    run_lint,
    validate_lint_report,
)

REPO_ROOT = Path(__file__).resolve().parent.parent
CLI = REPO_ROOT / "scripts" / "tdx_lint.py"
BASELINE = REPO_ROOT / "expectations" / "static_analysis_baseline.json"


def _lint(source: str, rel_path: str = "pkg/mod.py", shared=None):
    """Lint a dedented snippet, returning (findings, used_suppressions)."""
    return lint_source(
        rel_path, textwrap.dedent(source), default_rules(), shared=shared
    )


def _rules_of(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------------
# suppression comment parsing + TDX100


class TestSuppressions:
    def test_parse_extracts_rules_and_justification(self):
        src = "x = 1  # tdx-lint: disable=TDX102,TDX103 -- seeded bench data\n"
        (sup,) = parse_suppressions("a.py", src)
        assert sup.rules == ("TDX102", "TDX103")
        assert sup.justification == "seeded bench data"
        assert sup.valid

    def test_hash_inside_string_is_not_a_suppression(self):
        src = 's = "# tdx-lint: disable=TDX102 -- not a comment"\n'
        assert parse_suppressions("a.py", src) == []

    def test_valid_suppression_drops_finding_and_is_reported(self):
        findings, used = _lint(
            """\
            import jax
            k = jax.random.PRNGKey(0)  # tdx-lint: disable=TDX102 -- test fixture key
            """
        )
        assert findings == []
        assert len(used) == 1 and used[0].rules == ("TDX102",)

    def test_missing_justification_suppresses_nothing_and_adds_tdx100(self):
        findings, used = _lint(
            """\
            import jax
            k = jax.random.PRNGKey(0)  # tdx-lint: disable=TDX102
            """
        )
        # the original finding survives AND the malformed comment is flagged
        assert _rules_of(findings) == ["TDX100", "TDX102"]
        assert used == []

    def test_suppression_for_wrong_rule_does_not_cover(self):
        findings, _ = _lint(
            """\
            import jax
            k = jax.random.PRNGKey(0)  # tdx-lint: disable=TDX104 -- wrong rule id
            """
        )
        assert _rules_of(findings) == ["TDX102"]


# ---------------------------------------------------------------------------
# per-rule positive/negative fixtures


class TestTDX101DonatedJit:
    def test_donated_jit_without_out_shardings_flagged(self):
        findings, _ = _lint(
            """\
            import jax
            run = jax.jit(step, donate_argnums=(0, 1))
            """
        )
        assert _rules_of(findings) == ["TDX101"]
        assert findings[0].line == 2

    def test_partial_jit_decorator_form_flagged(self):
        findings, _ = _lint(
            """\
            import functools, jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def run(carry):
                return carry
            """
        )
        assert _rules_of(findings) == ["TDX101"]

    def test_donate_argnames_also_flagged(self):
        findings, _ = _lint(
            "import jax\nrun = jax.jit(step, donate_argnames=('params',))\n"
        )
        assert _rules_of(findings) == ["TDX101"]

    def test_out_shardings_satisfies(self):
        findings, _ = _lint(
            """\
            import jax
            run = jax.jit(step, donate_argnums=(0,), out_shardings=(sh, None))
            """
        )
        assert findings == []

    def test_kwargs_splat_satisfies(self):
        findings, _ = _lint(
            "import jax\nrun = jax.jit(step, donate_argnums=(0,), **extra)\n"
        )
        assert findings == []

    def test_undonated_jit_is_fine(self):
        findings, _ = _lint("import jax\nrun = jax.jit(step)\n")
        assert findings == []

    # -- v2: the out_shardings VALUE must cite the plan ------------------

    def test_hand_built_namedsharding_dict_flagged(self):
        findings, _ = _lint(
            """\
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            sh = {"w": NamedSharding(mesh, P("fsdp"))}
            run = jax.jit(step, donate_argnums=(0,), out_shardings=(sh, None))
            """
        )
        assert _rules_of(findings) == ["TDX101"]
        assert "hand-built NamedSharding" in findings[0].message

    def test_bare_namedsharding_literal_flagged(self):
        findings, _ = _lint(
            """\
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            run = jax.jit(
                step,
                donate_argnums=(0,),
                out_shardings=(NamedSharding(mesh, P()), None),
            )
            """
        )
        assert _rules_of(findings) == ["TDX101"]

    def test_plan_shardings_for_satisfies(self):
        findings, _ = _lint(
            """\
            import jax

            run = jax.jit(
                step,
                donate_argnums=(0, 1),
                out_shardings=plan.shardings_for(params, opt_state) + (None,),
            )
            """
        )
        assert findings == []

    def test_tuple_unpack_from_plan_source_satisfies(self):
        findings, _ = _lint(
            """\
            import jax

            p_sh, o_sh = donated_carry_shardings(params, opt_state)
            run = jax.jit(
                step, donate_argnums=(0, 1), out_shardings=(p_sh, o_sh, None)
            )
            """
        )
        assert findings == []

    def test_variable_holding_hand_built_dict_flagged(self):
        findings, _ = _lint(
            """\
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            carry_sh = (
                {"w": NamedSharding(mesh, P("fsdp"))},
                {"w": NamedSharding(mesh, P("fsdp"))},
                None,
            )
            run = jax.jit(step, donate_argnums=(0, 1), out_shardings=carry_sh)
            """
        )
        assert _rules_of(findings) == ["TDX101"]

    def test_unknown_provenance_is_not_flagged(self):
        # lexical rule: an opaque helper the linter cannot see into is
        # given the benefit of the doubt (no NamedSharding in sight)
        findings, _ = _lint(
            """\
            import jax

            run = jax.jit(
                step, donate_argnums=(0,), out_shardings=make_shardings()
            )
            """
        )
        assert findings == []


class TestTDX102StatefulRng:
    def test_raw_prngkey_flagged(self):
        findings, _ = _lint("import jax\nk = jax.random.PRNGKey(42)\n")
        assert _rules_of(findings) == ["TDX102"]
        assert "counter" in findings[0].message

    def test_np_global_generator_flagged(self):
        findings, _ = _lint("import numpy as np\nx = np.random.randn(4)\n")
        assert _rules_of(findings) == ["TDX102"]

    def test_seeded_randomstate_is_fine(self):
        findings, _ = _lint(
            "import numpy as np\nrs = np.random.RandomState(0)\nx = rs.randn(4)\n"
        )
        assert findings == []

    def test_default_rng_is_fine(self):
        findings, _ = _lint(
            "import numpy as np\nrng = np.random.default_rng(0)\n"
        )
        assert findings == []

    def test_utils_rng_module_exempt(self):
        findings, _ = _lint(
            "import jax\nk = jax.random.PRNGKey(0)\n",
            rel_path="torchdistx_tpu/utils/rng.py",
        )
        assert findings == []

    def test_key_plumbing_is_fine(self):
        findings, _ = _lint("import jax\na, b = jax.random.split(key)\n")
        assert findings == []


class TestTDX103RawCollective:
    def test_raw_psum_flagged(self):
        findings, _ = _lint(
            """\
            from jax import lax

            def loss(x):
                return lax.pmean(x, "dp")
            """
        )
        assert _rules_of(findings) == ["TDX103"]
        assert "obs/comm.py" in findings[0].message

    def test_collectives_module_exempt(self):
        findings, _ = _lint(
            'from jax import lax\n\ndef all_mean(x, axis):\n    return lax.pmean(x, axis)\n',
            rel_path="torchdistx_tpu/parallel/collectives.py",
        )
        assert findings == []

    def test_enclosing_booking_call_exempts(self):
        findings, _ = _lint(
            """\
            from jax import lax

            def ring(x, axis, n):
                record_collective("ppermute", axis, x, count=n)
                return lax.ppermute(x, axis, perm)
            """
        )
        assert findings == []

    def test_record_helper_prefix_exempts(self):
        findings, _ = _lint(
            """\
            from jax import lax

            def step(x):
                _record_ring_pass("sp", 8, (x,))
                return lax.all_to_all(x, "sp", 0, 1)
            """
        )
        assert findings == []

    def test_booking_in_sibling_function_does_not_exempt(self):
        findings, _ = _lint(
            """\
            from jax import lax

            def book(x):
                record_collective("psum", "dp", x)

            def loss(x):
                return lax.psum(x, "dp")
            """
        )
        assert _rules_of(findings) == ["TDX103"]


class TestTDX104HostSync:
    def test_item_in_jitted_def_flagged(self):
        findings, _ = _lint(
            """\
            import jax

            @jax.jit
            def step(c):
                v = c.item()
                return v
            """
        )
        assert _rules_of(findings) == ["TDX104"]
        assert findings[0].line == 5

    def test_float_in_scan_body_by_name_flagged(self):
        findings, _ = _lint(
            """\
            from jax import lax

            def body(c, x):
                v = float(c)
                return c, v

            out = lax.scan(body, c0, xs)
            """
        )
        assert _rules_of(findings) == ["TDX104"]

    def test_np_asarray_in_while_loop_lambda_flagged(self):
        findings, _ = _lint(
            """\
            import numpy as np
            from jax import lax

            out = lax.while_loop(cond, lambda c: np.asarray(c), c0)
            """
        )
        assert _rules_of(findings) == ["TDX104"]

    def test_block_until_ready_in_jitted_def_flagged(self):
        findings, _ = _lint(
            """\
            import jax

            @jax.jit
            def step(c):
                return c.block_until_ready()
            """
        )
        assert _rules_of(findings) == ["TDX104"]

    def test_item_in_plain_function_is_fine(self):
        findings, _ = _lint(
            """\
            def fetch(c):
                return c.item()
            """
        )
        assert findings == []

    def test_float_of_constant_is_fine(self):
        findings, _ = _lint(
            """\
            import jax

            @jax.jit
            def step(c):
                return c * float(2)
            """
        )
        assert findings == []


class TestTDX105Metrics:
    def test_counter_set_flagged(self):
        findings, _ = _lint(
            """\
            c = registry.counter("tdx_serve_requests_total")
            c.set(3)
            """
        )
        assert _rules_of(findings) == ["TDX105"]
        assert "monotone" in findings[0].message

    def test_counter_negative_inc_flagged(self):
        findings, _ = _lint(
            """\
            c = registry.counter("tdx_serve_requests_total")
            c.inc(-1)
            """
        )
        assert _rules_of(findings) == ["TDX105"]

    def test_counter_positive_inc_fine(self):
        findings, _ = _lint(
            """\
            c = registry.counter("tdx_serve_requests_total")
            c.inc(2)
            """
        )
        assert findings == []

    def test_gauge_set_fine(self):
        findings, _ = _lint(
            """\
            g = registry.gauge("tdx_serve_depth")
            g.set(3)
            """
        )
        assert findings == []

    def test_unregistered_tdx_metric_family_flagged(self):
        findings, _ = _lint(
            'fam = MetricFamily("tdx_ghost_series_total", "doc")\n'
        )
        assert _rules_of(findings) == ["TDX105"]
        assert "ghost" in findings[0].message

    def test_registration_in_another_file_satisfies(self):
        # cross-file: pass the shared scratchpad between two lint_source
        # calls, the way run_lint's collect pass does for the whole scan set
        shared = {}
        _lint(
            'reg.counter("tdx_ghost_series_total")\n',
            rel_path="pkg/registry.py",
            shared=shared,
        )
        findings, _ = _lint(
            'fam = MetricFamily("tdx_ghost_series_total", "doc")\n',
            rel_path="pkg/exporter.py",
            shared=shared,
        )
        assert findings == []

    def test_collector_prefix_root_satisfies(self):
        shared = {}
        _lint(
            """\
            def collect(prefix="tdx_fleet"):
                pass
            """,
            rel_path="pkg/collector.py",
            shared=shared,
        )
        findings, _ = _lint(
            'fam = MetricFamily("tdx_fleet_route_depth", "doc")\n',
            rel_path="pkg/exporter.py",
            shared=shared,
        )
        assert findings == []

    def test_non_tdx_family_ignored(self):
        findings, _ = _lint(
            'fam = MetricFamily("process_cpu_seconds_total", "doc")\n'
        )
        assert findings == []


class TestTDX106CounterRowDeterminism:
    def test_wall_clock_in_counter_row_function_flagged(self):
        findings, _ = _lint(
            """\
            import time

            def emit(ledger):
                ledger.add(row(name="tdx_x_total", metric_class="counter"))
                return time.time()
            """
        )
        assert _rules_of(findings) == ["TDX106"]
        assert "EXACTLY" in findings[0].message

    def test_set_iteration_in_counter_row_function_flagged(self):
        findings, _ = _lint(
            """\
            def emit(ledger, names):
                for n in set(names):
                    ledger.add(row(name=n, metric_class="counter"))
            """
        )
        assert _rules_of(findings) == ["TDX106"]
        assert "sort" in findings[0].message

    def test_wall_clock_outside_counter_rows_fine(self):
        findings, _ = _lint(
            """\
            import time

            def emit(ledger):
                ledger.add(row(name="tdx_x_ms", metric_class="timing"))
                return time.time()
            """
        )
        assert findings == []

    def test_sorted_iteration_fine(self):
        findings, _ = _lint(
            """\
            def emit(ledger, names):
                for n in sorted(set(names)):
                    ledger.add(row(name=n, metric_class="counter"))
            """
        )
        # sorted(set(...)) iterates the sorted list, not the set
        assert findings == []


# ---------------------------------------------------------------------------
# acceptance: an injected violation of EACH rule is caught with rule id
# and file:line


_VIOLATIONS = {
    # rule id -> (snippet, expected line of the finding)
    "TDX100": ("import jax\nk = jax.random.PRNGKey(0)  # tdx-lint: disable=TDX102\n", 2),
    "TDX101": ("import jax\nrun = jax.jit(f, donate_argnums=(0,))\n", 2),
    "TDX102": ("import jax\nk = jax.random.PRNGKey(0)\n", 2),
    "TDX103": ("from jax import lax\ny = lax.psum(x, 'dp')\n", 2),
    "TDX104": (
        "import jax\n\n@jax.jit\ndef step(c):\n    return c.item()\n",
        5,
    ),
    "TDX105": ("c = reg.counter('tdx_q_total')\nc.dec()\n", 2),
    "TDX106": (
        "import time\n\ndef emit(led):\n"
        "    led.add(row(metric_class='counter'))\n"
        "    return time.perf_counter()\n",
        5,
    ),
}


class TestEveryRuleCatchesInjectedViolation:
    @pytest.mark.parametrize("rule_id", sorted(_VIOLATIONS))
    def test_injected_violation_caught_with_location(self, rule_id):
        snippet, line = _VIOLATIONS[rule_id]
        findings, _ = lint_source("inject/%s.py" % rule_id, snippet, default_rules())
        hits = [f for f in findings if f.rule == rule_id]
        assert hits, "rule %s missed its injected violation" % rule_id
        assert hits[0].path == "inject/%s.py" % rule_id
        assert hits[0].line == line
        assert hits[0].severity == RULE_CATALOG[rule_id][0]

    def test_catalog_covers_all_default_rules(self):
        ids = {r.rule_id for r in default_rules()} | {"TDX100"}
        assert ids == set(RULE_CATALOG) == set(_VIOLATIONS)


# ---------------------------------------------------------------------------
# baseline gate semantics + report schema


def _mkfinding(rule="TDX102", path="a.py", line=1):
    return {
        "rule": rule,
        "severity": "error",
        "path": path,
        "line": line,
        "col": 0,
        "message": "m",
    }


class TestBaselineCompare:
    def test_exact_compare_reports_new_and_fixed(self):
        report = {"findings": [_mkfinding(line=1), _mkfinding(line=2)]}
        baseline = {"findings": [_mkfinding(line=2), _mkfinding(line=3)]}
        diff = compare_to_baseline(report, baseline)
        assert [f["line"] for f in diff["new"]] == [1]
        assert [f["line"] for f in diff["fixed"]] == [3]

    def test_identity_is_rule_path_line_not_message(self):
        a = _mkfinding()
        b = dict(_mkfinding(), message="different wording", col=7)
        diff = compare_to_baseline({"findings": [a]}, {"findings": [b]})
        assert diff == {"new": [], "fixed": []}
        assert finding_key(a) == finding_key(b)


class TestReportSchema:
    def test_run_lint_report_validates(self, tmp_path):
        f = tmp_path / "clean.py"
        f.write_text("x = 1  # tdx-lint: disable=TDX102 -- exercised suppression\n")
        # note: a suppression with no matching finding is unused, so it is
        # NOT reported; add a real finding + suppression pair instead
        f.write_text(
            "import jax\n"
            "k = jax.random.PRNGKey(0)  # tdx-lint: disable=TDX102 -- fixture\n"
        )
        report = run_lint([str(f)], default_rules())
        assert report["schema"] == LINT_SCHEMA
        assert report["files_scanned"] == 1
        assert report["findings"] == []
        assert len(report["suppressions"]) == 1
        assert validate_lint_report(report) == []

    def test_unparseable_file_becomes_tdx000(self, tmp_path):
        f = tmp_path / "broken.py"
        f.write_text("def oops(:\n")
        report = run_lint([str(f)], default_rules())
        assert [x["rule"] for x in report["findings"]] == ["TDX000"]
        assert validate_lint_report(report) == []

    def test_validator_catches_bad_docs(self):
        assert validate_lint_report([]) == ["report is not a JSON object"]
        errs = validate_lint_report({"schema": "nope"})
        assert any(e.startswith("schema:") for e in errs)
        doc = {
            "schema": LINT_SCHEMA,
            "files_scanned": 1,
            "rules": ["TDX101"],
            "findings": [dict(_mkfinding(), severity="fatal", col="0")],
            "suppressions": [
                {"path": "a.py", "line": 1, "rules": ["TDX102"], "justification": " "}
            ],
        }
        errs = validate_lint_report(doc)
        assert any("severity" in e for e in errs)
        assert any(".col" in e for e in errs)
        assert any("justification" in e for e in errs)


# ---------------------------------------------------------------------------
# CLI contracts (exit codes, last-stdout-line JSON verdict)


def _cli(*args, cwd=None):
    return subprocess.run(
        [sys.executable, str(CLI), *map(str, args)],
        capture_output=True,
        text=True,
        cwd=cwd or REPO_ROOT,
        timeout=120,
    )


def _last_json(proc):
    return json.loads(proc.stdout.strip().splitlines()[-1])


class TestCLI:
    def test_violation_fails_strict_naming_rule_and_location(self, tmp_path):
        f = tmp_path / "bad.py"
        f.write_text("import jax\nk = jax.random.PRNGKey(0)\n")
        proc = _cli(f, "--no-baseline", "--strict")
        assert proc.returncode == 1
        assert "TDX102" in proc.stdout
        assert "%s:2" % f in proc.stdout  # per-finding line has file:line
        verdict = _last_json(proc)
        assert verdict["schema"] == "tdx-lint-verdict-v1"
        assert verdict["ok"] is False
        assert verdict["new"][0]["rule"] == "TDX102"

    def test_clean_scan_exits_zero_with_ok_verdict(self, tmp_path):
        f = tmp_path / "ok.py"
        f.write_text("x = 1\n")
        proc = _cli(f, "--no-baseline", "--strict")
        assert proc.returncode == 0
        assert _last_json(proc)["ok"] is True

    def test_missing_baseline_exits_two(self, tmp_path):
        f = tmp_path / "ok.py"
        f.write_text("x = 1\n")
        proc = _cli(f, "--baseline", tmp_path / "absent.json", "--strict")
        assert proc.returncode == 2
        assert "baseline" in proc.stderr

    def test_baseline_roundtrip_then_new_and_fixed_both_fail(self, tmp_path):
        f = tmp_path / "mod.py"
        base = tmp_path / "baseline.json"
        f.write_text("x = 1\n")

        # pin, then strict-pass against the pin
        assert _cli(f, "--baseline", base, "--update-baseline").returncode == 0
        doc = json.loads(base.read_text())
        assert validate_lint_report(doc) == []
        assert _cli(f, "--baseline", base, "--strict").returncode == 0

        # inject a violation -> NEW finding fails, named with rule+file:line
        f.write_text("import jax\nk = jax.random.PRNGKey(0)\n")
        proc = _cli(f, "--baseline", base, "--strict")
        assert proc.returncode == 1
        assert "FAIL: new finding TDX102" in proc.stderr
        assert ":2" in proc.stderr

        # accept it into the baseline, then fix it -> FIXED also fails,
        # pointing at the --update-baseline refresh workflow
        assert _cli(f, "--baseline", base, "--update-baseline").returncode == 0
        f.write_text("x = 1\n")
        proc = _cli(f, "--baseline", base, "--strict")
        assert proc.returncode == 1
        assert "no longer present" in proc.stderr
        assert "--update-baseline" in proc.stderr

    def test_list_rules_prints_catalog(self):
        proc = _cli("--list-rules")
        assert proc.returncode == 0
        for rid in RULE_CATALOG:
            assert rid in proc.stdout


# ---------------------------------------------------------------------------
# the committed repo gate


class TestCommittedBaseline:
    def test_committed_baseline_validates_and_has_no_donation_or_comm_debt(self):
        doc = json.loads(BASELINE.read_text())
        assert validate_lint_report(doc) == []
        rules = [f["rule"] for f in doc["findings"]]
        assert "TDX101" not in rules, "donated-jit debt must be fixed, not pinned"
        assert "TDX103" not in rules, "unbooked-collective debt must be fixed, not pinned"

    def test_repo_scan_matches_committed_baseline_exactly(self):
        report = run_lint(
            ["torchdistx_tpu", "scripts", "__graft_entry__.py", "examples", "bench.py"],
            default_rules(),
            root=str(REPO_ROOT),
        )
        baseline = json.loads(BASELINE.read_text())
        diff = compare_to_baseline(report, baseline)
        assert diff == {"new": [], "fixed": []}, (
            "repo drifted from expectations/static_analysis_baseline.json — "
            "fix the finding or refresh with scripts/tdx_lint.py --update-baseline"
        )
