"""Pallas flash attention: exact agreement with the reference attention (on
CPU via pallas interpret mode; compiled-kernel agreement is exercised on
real TPU hardware by bench/verification runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu.models import Llama
from torchdistx_tpu.ops.attention import multihead_attention
from torchdistx_tpu.ops.flash_attention import flash_attention


@pytest.mark.parametrize(
    "b,s,hq,hkv,causal",
    [
        (2, 128, 4, 4, True),
        (1, 128, 8, 2, True),  # GQA
        (2, 64, 4, 4, False),
    ],
)
def test_matches_reference(b, s, hq, hkv, causal):
    rs = np.random.RandomState(0)
    d = 32
    q = jnp.asarray(rs.randn(b, s, hq, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
    ref = multihead_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=32)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_causal_cross_attention_end_aligned():
    # Sq < Skv (cached decode shape): query i must see keys up to
    # skv - sq + i, matching multihead_attention's end-aligned tril
    rs = np.random.RandomState(3)
    q = jnp.asarray(rs.randn(1, 4, 2, 16), jnp.float32)
    k = jnp.asarray(rs.randn(1, 64, 2, 16), jnp.float32)
    v = jnp.asarray(rs.randn(1, 64, 2, 16), jnp.float32)
    ref = multihead_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=4, block_k=16)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_odd_lengths_auto_block():
    # block sizes reduce to dividing values; odd lengths just work
    rs = np.random.RandomState(5)
    q = jnp.asarray(rs.randn(1, 100, 4, 32), jnp.float32)
    ref = multihead_attention(q, q, q, causal=True)
    out = flash_attention(q, q, q, causal=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_causal_sq_gt_skv_rejected():
    q = jnp.zeros((1, 8, 2, 16))
    k = jnp.zeros((1, 4, 2, 16))
    with pytest.raises(ValueError, match="Sq"):
        flash_attention(q, k, k, causal=True)


def test_gqa_head_mismatch_error():
    q = jnp.zeros((1, 64, 6, 32))
    k = jnp.zeros((1, 64, 4, 32))
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, k, k)


@pytest.mark.parametrize(
    "hq,hkv,sq,skv,causal",
    [
        (2, 2, 64, 64, True),
        (8, 2, 64, 64, True),  # GQA dK/dV group reduction
        (2, 2, 32, 64, True),  # Sq < Skv: end-aligned diag_offset masking
        (2, 2, 64, 64, False),  # non-causal (cross-attention shapes)
    ],
)
def test_gradients_match_reference(hq, hkv, sq, skv, causal):
    # flash fwd + pallas FA2 bwd must give the reference's gradients
    # across every masking regime the backward kernels implement
    rs = np.random.RandomState(7)
    q = jnp.asarray(rs.randn(1, sq, hq, 16), jnp.float32)
    k = jnp.asarray(rs.randn(1, skv, hkv, 16), jnp.float32)
    v = jnp.asarray(rs.randn(1, skv, hkv, 16), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, block_q=32) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(multihead_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )


def test_llama_use_flash_trains():
    import optax

    import torchdistx_tpu as tdx2
    from torchdistx_tpu.nn import functional, functional_call

    tdx2.manual_seed(0)
    m = Llama.from_name("tiny", use_flash=True)
    params = dict(m.named_parameters())
    tokens = jnp.zeros((2, 32), jnp.int32)

    def loss_fn(p):
        logits = functional_call(m, p, (tokens,))
        return functional.cross_entropy(logits, tokens)

    tx = optax.sgd(1e-2)
    s = tx.init(params)
    l0 = float(loss_fn(params))
    for _ in range(3):
        g = jax.grad(loss_fn)(params)
        u, s = tx.update(g, s, params)
        params = jax.tree_util.tree_map(lambda a, b: a + b, params, u)
    assert float(loss_fn(params)) < l0


def test_llama_use_flash_matches_default():
    tdx.manual_seed(0)
    a = Llama.from_name("tiny")
    tdx.manual_seed(0)
    b = Llama.from_name("tiny", use_flash=True)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 256, (2, 64)))
    # odd length: flash path must handle non-256-multiple sequences
    odd = jnp.asarray(np.random.RandomState(2).randint(0, 256, (1, 33)))
    assert b(odd).shape == (1, 33, 256)
    np.testing.assert_allclose(
        np.asarray(a(tokens)), np.asarray(b(tokens)), rtol=2e-4, atol=2e-4
    )


class TestBias:
    """Additive logit bias (T5 relative-position bias) on the flash path."""

    @staticmethod
    def _inputs(b=2, s=32, h=4, d=16, key=0):
        ks = jax.random.split(jax.random.PRNGKey(key), 4)
        q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
        k = jax.random.normal(ks[1], (b, s, h, d), jnp.float32)
        v = jax.random.normal(ks[2], (b, s, h, d), jnp.float32)
        bias = jax.random.normal(ks[3], (h, s, s), jnp.float32)
        return q, k, v, bias

    @staticmethod
    def _reference(q, k, v, bias, causal):
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        logits = logits / np.sqrt(q.shape[-1]) + bias[None]
        if causal:
            s = q.shape[1]
            mask = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(mask, logits, -jnp.inf)
        p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_reference(self, causal):
        q, k, v, bias = self._inputs()
        out = flash_attention(
            q, k, v, bias=bias, causal=causal, block_q=8, block_k=8
        )
        ref = self._reference(q, k, v, bias, causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_gradients_including_dbias(self):
        q, k, v, bias = self._inputs(s=16)

        def flash_loss(q, k, v, b):
            return jnp.sum(
                flash_attention(
                    q, k, v, bias=b, causal=True, block_q=8, block_k=8
                ).astype(jnp.float32) ** 2
            )

        def ref_loss(q, k, v, b):
            return jnp.sum(
                self._reference(q, k, v, b, True).astype(jnp.float32) ** 2
            )

        gf = jax.grad(flash_loss, (0, 1, 2, 3))(q, k, v, bias)
        gr = jax.grad(ref_loss, (0, 1, 2, 3))(q, k, v, bias)
        for name, a, b in zip("qkvB", gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5,
                err_msg=f"d{name}",
            )

    def test_bad_bias_shape_raises(self):
        q, k, v, bias = self._inputs()
        with pytest.raises(ValueError, match="bias shape"):
            flash_attention(q, k, v, bias=bias[:, :8], causal=False)

    def test_gradients_biased_gqa(self):
        # bias + grouped-query heads: the dbias kernel's per-query-head
        # K/V index map (bb * hkv + h // n_rep) must hold under n_rep > 1
        ks = jax.random.split(jax.random.PRNGKey(5), 4)
        b, s, hq, hkv, d = 2, 16, 4, 2, 8
        q = jax.random.normal(ks[0], (b, s, hq, d), jnp.float32)
        k = jax.random.normal(ks[1], (b, s, hkv, d), jnp.float32)
        v = jax.random.normal(ks[2], (b, s, hkv, d), jnp.float32)
        bias = jax.random.normal(ks[3], (hq, s, s), jnp.float32)

        def ref(q, k, v, bias, causal=True):
            kr = jnp.repeat(k, hq // hkv, axis=2)
            vr = jnp.repeat(v, hq // hkv, axis=2)
            return self._reference(q, kr, vr, bias, causal)

        def flash_loss(q, k, v, b_):
            return jnp.sum(
                flash_attention(
                    q, k, v, bias=b_, causal=True, block_q=8, block_k=8
                ).astype(jnp.float32) ** 2
            )

        def ref_loss(q, k, v, b_):
            return jnp.sum(ref(q, k, v, b_).astype(jnp.float32) ** 2)

        gf = jax.grad(flash_loss, (0, 1, 2, 3))(q, k, v, bias)
        gr = jax.grad(ref_loss, (0, 1, 2, 3))(q, k, v, bias)
        for name, a, b_ in zip("qkvB", gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=3e-4, atol=3e-5,
                err_msg=f"d{name}",
            )

    def test_gradients_biased_cross_shape(self):
        # Sq < Skv (decode / cross-attention): the end-aligned diag_offset
        # must mask dbias identically to the forward
        ks = jax.random.split(jax.random.PRNGKey(6), 4)
        b, sq, skv, h, d = 2, 8, 16, 2, 8
        q = jax.random.normal(ks[0], (b, sq, h, d), jnp.float32)
        k = jax.random.normal(ks[1], (b, skv, h, d), jnp.float32)
        v = jax.random.normal(ks[2], (b, skv, h, d), jnp.float32)
        bias = jax.random.normal(ks[3], (h, sq, skv), jnp.float32)

        def ref(q, k, v, bias):
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
            logits = logits / np.sqrt(d) + bias[None]
            rows = (skv - sq) + jnp.arange(sq)[:, None]
            cols = jnp.arange(skv)[None, :]
            logits = jnp.where(cols <= rows, logits, -jnp.inf)
            p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", p, v)

        def flash_loss(q, k, v, b_):
            return jnp.sum(
                flash_attention(
                    q, k, v, bias=b_, causal=True, block_q=8, block_k=8
                ).astype(jnp.float32) ** 2
            )

        def ref_loss(q, k, v, b_):
            return jnp.sum(ref(q, k, v, b_).astype(jnp.float32) ** 2)

        gf = jax.grad(flash_loss, (0, 1, 2, 3))(q, k, v, bias)
        gr = jax.grad(ref_loss, (0, 1, 2, 3))(q, k, v, bias)
        for name, a, b_ in zip("qkvB", gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=3e-4, atol=3e-5,
                err_msg=f"d{name}",
            )

    def test_kernel_grads_match_chunked_reference(self):
        # the retired chunked-recompute backward stays as an independent
        # implementation; kernels must agree with it on the biased path
        from torchdistx_tpu.ops.flash_attention import _flash_bwd_chunked

        q, k, v, bias = self._inputs(s=16)
        g = jax.random.normal(
            jax.random.PRNGKey(9), q.shape, jnp.float32
        )

        def flash_fn(q, k, v, b_):
            return flash_attention(
                q, k, v, bias=b_, causal=True, block_q=8, block_k=8
            )

        _, vjp = jax.vjp(flash_fn, q, k, v, bias)
        dq, dk, dv, db = vjp(g)
        dq_c, dk_c, dv_c, db_c = _flash_bwd_chunked(
            q, k, v, bias, g, True, None, 8
        )
        for name, a, b_ in zip(
            ("dq", "dk", "dv", "dbias"),
            (dq, dk, dv, db),
            (dq_c, dk_c, dv_c, db_c),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=3e-4, atol=3e-5,
                err_msg=name,
            )


class TestRingFlash:
    """Flash-backed ring attention: exact agreement with full attention
    (forward AND whole-ring custom-VJP gradients) on the sp mesh."""

    @staticmethod
    def _mesh_and_inputs(b, s, hq, hkv, d, key=0):
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        rng = np.random.RandomState(key)
        q = jnp.asarray(rng.randn(b, s, hq, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        return mesh, q, k, v

    @staticmethod
    def _ring(mesh, causal):
        from torchdistx_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from torchdistx_tpu.ops.attention import ring_flash_attention

        return shard_map(
            lambda q, k, v: ring_flash_attention(
                q, k, v, axis="sp", causal=causal, block_q=8, block_k=8
            ),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )

    @pytest.mark.parametrize(
        "hq,hkv,causal",
        [(4, 4, True), (8, 2, True), (4, 4, False)],  # incl. GQA
    )
    def test_forward_matches_full_attention(self, hq, hkv, causal):
        mesh, q, k, v = self._mesh_and_inputs(2, 64, hq, hkv, 8)
        out = self._ring(mesh, causal)(q, k, v)
        ref = multihead_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-6
        )

    @pytest.mark.parametrize("hq,hkv", [(4, 4), (8, 2)])
    def test_gradients_match_full_attention(self, hq, hkv):
        mesh, q, k, v = self._mesh_and_inputs(1, 64, hq, hkv, 8)
        ring = self._ring(mesh, True)

        def loss_ring(q_, k_, v_):
            return jnp.sum(jnp.sin(ring(q_, k_, v_)))

        def loss_ref(q_, k_, v_):
            return jnp.sum(
                jnp.sin(multihead_attention(q_, k_, v_, causal=True))
            )

        g = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=1e-5
            )

    def test_unequal_shard_lengths_rejected(self):
        from torchdistx_tpu.ops.attention import ring_flash_attention

        q = jnp.zeros((1, 8, 4, 8))
        k = jnp.zeros((1, 16, 4, 8))
        with pytest.raises(ValueError, match="equal per-shard"):
            ring_flash_attention(q, k, q, axis="sp", causal=True)

    def test_llama_sp_flash_matches_single_device(self):
        # the model-level path: sp_axis + use_flash routes through
        # ring_flash_attention and must agree with the unsharded model
        from torchdistx_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from torchdistx_tpu.nn.module import functional_call
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        tdx.manual_seed(3)
        m_sp = tdx.deferred_init(
            Llama.from_name, "tiny", max_seq_len=64,
            sp_axis="sp", use_flash=True,
        )
        tdx.materialize_module(m_sp)
        from jax.sharding import NamedSharding

        # replicate params over the mesh (single-device-committed arrays
        # can't enter an 8-device shard_map)
        params = jax.device_put(
            dict(m_sp.named_parameters()),
            NamedSharding(mesh, P()),
        )
        tdx.manual_seed(3)
        m_ref = tdx.deferred_init(
            Llama.from_name, "tiny", max_seq_len=64, use_flash=False
        )
        tdx.materialize_module(m_ref)

        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (2, 64)), jnp.int32
        )
        logits_sp = shard_map(
            lambda t: functional_call(m_sp, params, (t,)),
            mesh=mesh,
            in_specs=P(None, "sp"),
            out_specs=P(None, "sp"),
            check_vma=False,
        )(tokens)
        logits_ref = m_ref(tokens)
        np.testing.assert_allclose(
            np.asarray(logits_sp), np.asarray(logits_ref),
            atol=2e-5, rtol=1e-5,
        )


class TestUlysses:
    """All-to-all sequence parallelism: bit-path-identical local attention
    after head/sequence resharding."""

    @staticmethod
    def _ulysses(mesh, causal, use_flash=False):
        from torchdistx_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from torchdistx_tpu.ops.attention import ulysses_attention

        return shard_map(
            lambda q, k, v: ulysses_attention(
                q, k, v, axis="sp", causal=causal, use_flash=use_flash
            ),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )

    @pytest.mark.parametrize(
        "hq,hkv,causal,use_flash",
        [
            (8, 8, True, False),
            (16, 8, True, False),  # GQA (both divisible by 8)
            (8, 8, False, False),
            (8, 8, True, True),  # flash local attention (interpret)
        ],
    )
    def test_matches_full_attention(self, hq, hkv, causal, use_flash):
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        rng = np.random.RandomState(1)
        b, s, d = 2, 64, 8
        q = jnp.asarray(rng.randn(b, s, hq, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        out = self._ulysses(mesh, causal, use_flash)(q, k, v)
        ref = multihead_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    @pytest.mark.parametrize("hq,hkv", [(8, 8), (16, 8)])
    @pytest.mark.slow
    def test_gradients_match_full_attention(self, hq, hkv):
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(1, 64, hq, 8), jnp.float32)
        k = jnp.asarray(rng.randn(1, 64, hkv, 8), jnp.float32)
        v = jnp.asarray(rng.randn(1, 64, hkv, 8), jnp.float32)
        uly = self._ulysses(mesh, True)

        g = jax.grad(
            lambda a, b_, c: jnp.sum(jnp.sin(uly(a, b_, c))),
            argnums=(0, 1, 2),
        )(q, k, v)
        gr = jax.grad(
            lambda a, b_, c: jnp.sum(
                jnp.sin(multihead_attention(a, b_, c, causal=True))
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=1e-5
            )

    def test_indivisible_heads_rejected(self):
        from torchdistx_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from torchdistx_tpu.ops.attention import ulysses_attention
        from torchdistx_tpu.parallel import create_mesh

        q = jnp.zeros((1, 8, 6, 8))  # 6 heads, axis of 8
        mesh = create_mesh({"sp": 8})
        f = shard_map(
            lambda a: ulysses_attention(a, a, a, axis="sp"),
            mesh=mesh,
            in_specs=P(None, "sp"),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
        with pytest.raises(ValueError, match="divisible"):
            f(q)

    def test_llama_sp_mode_ulysses_matches_single_device(self):
        from torchdistx_tpu.parallel.compat import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torchdistx_tpu.nn.module import functional_call
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        tdx.manual_seed(4)
        m_sp = tdx.deferred_init(
            Llama.from_name, "tiny", max_seq_len=64,
            sp_axis="sp", sp_mode="ulysses", n_heads=8, dim=64,
        )
        tdx.materialize_module(m_sp)
        params = jax.device_put(
            dict(m_sp.named_parameters()), NamedSharding(mesh, P())
        )
        tdx.manual_seed(4)
        m_ref = tdx.deferred_init(
            Llama.from_name, "tiny", max_seq_len=64, n_heads=8, dim=64,
        )
        tdx.materialize_module(m_ref)

        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (2, 64)), jnp.int32
        )
        logits_sp = shard_map(
            lambda t: functional_call(m_sp, params, (t,)),
            mesh=mesh,
            in_specs=P(None, "sp"),
            out_specs=P(None, "sp"),
            check_vma=False,
        )(tokens)
        np.testing.assert_allclose(
            np.asarray(logits_sp), np.asarray(m_ref(tokens)),
            atol=2e-5, rtol=1e-5,
        )

    def test_bad_sp_mode_rejected(self):
        with pytest.raises(ValueError, match="sp_mode"):
            Llama.from_name("tiny", sp_mode="spiral")


class TestRingFlashBias:
    """Flash-backed ring attention with the T5-style additive bias: the
    per-hop column slices streamed into the kernels must reproduce full
    biased attention exactly, forward and gradients INCLUDING dbias
    (each device owns its query rows' bias gradient)."""

    @staticmethod
    def _reference(q, k, v, bias, causal):
        hq, hkv = q.shape[2], k.shape[2]
        if hq != hkv:
            k = jnp.repeat(k, hq // hkv, axis=2)
            v = jnp.repeat(v, hq // hkv, axis=2)
        s = q.shape[1]
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        logits = logits / np.sqrt(q.shape[-1]) + bias[None]
        if causal:
            mask = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(mask, logits, -jnp.inf)
        p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    @staticmethod
    def _ring(mesh, causal):
        from torchdistx_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from torchdistx_tpu.ops.attention import ring_flash_attention

        return shard_map(
            lambda q, k, v, bias: ring_flash_attention(
                q, k, v, axis="sp", causal=causal, bias=bias,
                block_q=8, block_k=8,
            ),
            mesh=mesh,
            in_specs=(
                P(None, "sp"), P(None, "sp"), P(None, "sp"),
                P(None, "sp", None),  # query rows sharded, key dim full
            ),
            out_specs=P(None, "sp"),
            check_vma=False,
        )

    @pytest.mark.parametrize(
        "hq,hkv,causal",
        [(4, 4, True), (8, 2, True), (4, 4, False)],
    )
    def test_forward_matches_reference(self, hq, hkv, causal):
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        rng = np.random.RandomState(3)
        b, s, d = 2, 64, 8
        q = jnp.asarray(rng.randn(b, s, hq, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        bias = jnp.asarray(rng.randn(hq, s, s) * 0.5, jnp.float32)
        out = self._ring(mesh, causal)(q, k, v, bias)
        ref = self._reference(q, k, v, bias, causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-6
        )

    @pytest.mark.parametrize("hq,hkv", [(4, 4), (8, 2)])
    def test_gradients_including_dbias(self, hq, hkv):
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        rng = np.random.RandomState(4)
        b, s, d = 1, 64, 8
        q = jnp.asarray(rng.randn(b, s, hq, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        bias = jnp.asarray(rng.randn(hq, s, s) * 0.5, jnp.float32)
        ring = self._ring(mesh, True)

        def loss_ring(q_, k_, v_, b_):
            return jnp.sum(jnp.sin(ring(q_, k_, v_, b_)))

        def loss_ref(q_, k_, v_, b_):
            return jnp.sum(
                jnp.sin(self._reference(q_, k_, v_, b_, True))
            )

        g = jax.grad(loss_ring, argnums=(0, 1, 2, 3))(q, k, v, bias)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
        for name, got, want in zip("qkvB", g, gr):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want),
                rtol=2e-4, atol=2e-5, err_msg=f"d{name}",
            )

    def test_bad_bias_shape_raises(self):
        from torchdistx_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from torchdistx_tpu.ops.attention import ring_flash_attention
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        q = jnp.zeros((1, 64, 4, 8), jnp.float32)
        bad = jnp.zeros((4, 64, 64), jnp.float32)  # key dim sharded
        with pytest.raises(ValueError, match="UNsharded"):
            shard_map(
                lambda q, b: ring_flash_attention(
                    q, q, q, axis="sp", bias=b
                ),
                mesh=mesh,
                in_specs=(P(None, "sp"), P(None, None, "sp")),
                out_specs=P(None, "sp"),
                check_vma=False,
            )(q, bad)


class TestBucketBias:
    """In-kernel bucket bias: the kernels compute each tile's T5
    relative-position bias from the (H, buckets) table in VMEM — outputs
    and ALL gradients (incl. dtable via the fourth kernel) must match the
    materialized-bias path exactly."""

    @staticmethod
    def _setup(s=32, h=4, d=16, buckets=32, max_dist=128, key=0):
        from torchdistx_tpu.ops.flash_attention import rel_pos_bucket

        rs = np.random.RandomState(key)
        q = jnp.asarray(rs.randn(2, s, h, d), jnp.float32)
        k = jnp.asarray(rs.randn(2, s, h, d), jnp.float32)
        v = jnp.asarray(rs.randn(2, s, h, d), jnp.float32)
        table = jnp.asarray(rs.randn(h, buckets) * 0.5, jnp.float32)
        return q, k, v, table, rel_pos_bucket

    @pytest.mark.parametrize("bidir,causal", [(False, True), (True, False)])
    def test_matches_materialized_bias(self, bidir, causal):
        s, buckets, max_dist = 32, 32, 128
        q, k, v, table, bucket_fn = self._setup(s=s)

        bucket = bucket_fn(
            jnp.arange(s)[None, :] - jnp.arange(s)[:, None],
            bidirectional=bidir, buckets=buckets, max_dist=max_dist,
        )
        bias = jnp.transpose(table.T[bucket], (2, 0, 1))

        def ref_loss(q, k, v, t):
            b_ = jnp.transpose(t.T[bucket], (2, 0, 1))
            return jnp.sum(flash_attention(
                q, k, v, bias=b_, causal=causal, block_q=8, block_k=8
            ).astype(jnp.float32) ** 2)

        def tab_loss(q, k, v, t):
            return jnp.sum(flash_attention(
                q, k, v, causal=causal, block_q=8, block_k=8,
                rel_bias_table=t, rel_bias_buckets=buckets,
                rel_bias_max_dist=max_dist, rel_bias_bidirectional=bidir,
            ).astype(jnp.float32) ** 2)

        out_ref = flash_attention(
            q, k, v, bias=bias, causal=causal, block_q=8, block_k=8
        )
        out_tab = flash_attention(
            q, k, v, causal=causal, block_q=8, block_k=8,
            rel_bias_table=table, rel_bias_buckets=buckets,
            rel_bias_max_dist=max_dist, rel_bias_bidirectional=bidir,
        )
        np.testing.assert_allclose(
            np.asarray(out_tab), np.asarray(out_ref), atol=2e-6
        )
        gr = jax.grad(ref_loss, (0, 1, 2, 3))(q, k, v, table)
        gt = jax.grad(tab_loss, (0, 1, 2, 3))(q, k, v, table)
        for name, a, b_ in zip(("dq", "dk", "dv", "dtable"), gt, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=3e-4, atol=3e-5,
                err_msg=name,
            )

    @pytest.mark.slow
    def test_t5_flash_bucket_bias_parity(self):
        from torchdistx_tpu.models import T5
        from torchdistx_tpu.nn import functional, functional_call

        tdx.manual_seed(15)
        a = tdx.deferred_init(T5.from_name, "tiny", use_flash=True)
        tdx.materialize_module(a)
        params = dict(a.named_parameters())
        bkt = T5.from_name("tiny", use_flash=True, flash_bucket_bias=True)
        bkt.load_state_dict(params)
        rs = np.random.RandomState(12)
        src = jnp.asarray(rs.randint(0, 256, (2, 32)), jnp.int32)
        tgt = jnp.asarray(rs.randint(0, 256, (2, 32)), jnp.int32)
        np.testing.assert_allclose(
            np.asarray(bkt(src, tgt)), np.asarray(a(src, tgt)),
            rtol=2e-4, atol=2e-4,
        )

        def loss(m, p):
            return functional.cross_entropy(
                functional_call(m, p, (src, tgt)), tgt
            )

        ga = jax.grad(lambda p: loss(a, p))(params)
        gb = jax.grad(lambda p: loss(bkt, p))(params)
        for k_ in ga:
            np.testing.assert_allclose(
                np.asarray(gb[k_]), np.asarray(ga[k_]),
                rtol=5e-4, atol=5e-5, err_msg=k_,
            )

    def test_rejects_bias_and_table_together(self):
        q, k, v, table, _ = self._setup()
        bias = jnp.zeros((4, 32, 32), jnp.float32)
        with pytest.raises(ValueError, match="not both"):
            flash_attention(q, k, v, bias=bias, rel_bias_table=table)

    def test_rejects_cross_shape(self):
        q, k, v, table, _ = self._setup()
        with pytest.raises(ValueError, match="Sq == Skv"):
            flash_attention(
                q[:, :16], k, v, causal=True, rel_bias_table=table
            )

    def test_bucket_bias_with_sp_rejected(self):
        from torchdistx_tpu.models import T5

        with pytest.raises(ValueError, match="flash_bucket_bias"):
            T5.from_name(
                "tiny", sp_axis="sp", flash_bucket_bias=True,
                use_flash=True,
            )


class TestSlidingWindow:
    """Mistral/Mixtral sliding-window attention: query i sees keys
    (i - window, i].  The kernel prunes out-of-band blocks at the grid
    level; forward, gradients, the jnp path, decode, and the model
    config must all agree."""

    @staticmethod
    def _ref(q, k, v, w):
        s, hq, d = q.shape[1], q.shape[2], q.shape[3]
        if k.shape[2] != hq:
            k = jnp.repeat(k, hq // k.shape[2], axis=2)
            v = jnp.repeat(v, hq // v.shape[2], axis=2)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(
            jnp.float32
        ) / np.sqrt(d)
        i = jnp.arange(s)[:, None]
        j = jnp.arange(s)[None, :]
        mask = (j <= i) & (j > i - w)
        logits = jnp.where(mask, logits, -jnp.inf)
        p = jax.nn.softmax(logits, -1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    @pytest.mark.parametrize("hq,hkv,w", [(4, 4, 10), (8, 2, 16), (4, 4, 1)])
    def test_forward_and_grads_match_reference(self, hq, hkv, w):
        rs = np.random.RandomState(2)
        b, s, d = 2, 64, 16
        q = jnp.asarray(rs.randn(b, s, hq, d), jnp.float32)
        k = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
        out = flash_attention(
            q, k, v, causal=True, window=w, block_q=8, block_k=8
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(q, k, v, w)), atol=2e-6
        )

        def lf(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, window=w, block_q=8, block_k=8
            ).astype(jnp.float32) ** 2)

        def lr(q, k, v):
            return jnp.sum(self._ref(q, k, v, w).astype(jnp.float32) ** 2)

        gf = jax.grad(lf, (0, 1, 2))(q, k, v)
        gr = jax.grad(lr, (0, 1, 2))(q, k, v)
        for name, a, b_ in zip("qkv", gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=3e-4, atol=3e-5,
                err_msg=f"d{name} w={w}",
            )

    def test_jnp_path_matches(self):
        rs = np.random.RandomState(3)
        q = jnp.asarray(rs.randn(1, 32, 2, 8), jnp.float32)
        out = multihead_attention(q, q, q, causal=True, window=6)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(q, q, q, 6)), atol=2e-6
        )

    @pytest.mark.slow
    def test_llama_sliding_window_generate_matches_forward(self):
        # windowed decode through the KV cache must equal the windowed
        # full forward's next-token choices
        from torchdistx_tpu.generation import generate

        tdx.manual_seed(16)
        m = Llama.from_name("tiny", sliding_window=8, use_flash=False)
        toks = jnp.asarray(
            np.random.RandomState(4).randint(0, 256, (1, 12)), jnp.int32
        )
        out = generate(m, toks, max_new_tokens=6)
        # reference: recompute full windowed forward each step
        cur = toks
        for _ in range(6):
            logits = m(cur)
            nxt = jnp.argmax(logits[:, -1], -1)[:, None]
            cur = jnp.concatenate([cur, nxt.astype(cur.dtype)], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))

    def test_validation(self):
        q = jnp.zeros((1, 16, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, q, q, causal=False, window=4)
        with pytest.raises(ValueError, match="mutually exclusive"):
            flash_attention(
                q, q, q, causal=True, window=4,
                bias=jnp.zeros((2, 16, 16)),
            )
        with pytest.raises(ValueError, match="sliding_window"):
            Llama.from_name("tiny", sliding_window=8, sp_axis="sp")

    def test_windowed_flash_prefill(self):
        # cached_attention's flash-prefill branch with a window (padded
        # and unpadded prompt lengths) — interpret mode on CPU
        from torchdistx_tpu.ops.attention import cached_attention

        rs = np.random.RandomState(5)
        for s in (128, 100):  # 128 = no pad; 100 pads to the lane multiple
            q = jnp.asarray(rs.randn(1, s, 2, 8), jnp.float32)
            k = jnp.asarray(rs.randn(1, s, 2, 8), jnp.float32)
            v = jnp.asarray(rs.randn(1, s, 2, 8), jnp.float32)
            cache = (
                jnp.zeros((1, 160, 2, 8), jnp.float32),
                jnp.zeros((1, 160, 2, 8), jnp.float32),
            )
            out_flash, _ = cached_attention(
                q, k, v, cache, 0, use_flash=True, window=12
            )
            out_jnp, _ = cached_attention(
                q, k, v, cache, 0, use_flash=False, window=12
            )
            np.testing.assert_allclose(
                np.asarray(out_flash), np.asarray(out_jnp),
                rtol=2e-5, atol=2e-5, err_msg=f"s={s}",
            )

    def test_window_zero_rejected_everywhere(self):
        q = jnp.zeros((1, 16, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match=">= 1"):
            flash_attention(q, q, q, causal=True, window=0)
        with pytest.raises(ValueError, match=">= 1"):
            multihead_attention(q, q, q, causal=True, window=0)
        with pytest.raises(ValueError, match=">= 1"):
            Llama.from_name("tiny", sliding_window=0)

    def test_windowed_decode_slice_matches_full_band(self):
        # the O(window) single-token decode slice must equal the full
        # max_seq band-mask computation at every cache position
        from torchdistx_tpu.ops.attention import cached_attention

        rs = np.random.RandomState(6)
        max_seq, w, h, d = 32, 8, 2, 8
        ck = jnp.asarray(rs.randn(1, max_seq, h, d), jnp.float32)
        cv = jnp.asarray(rs.randn(1, max_seq, h, d), jnp.float32)
        for pos in (0, 3, 7, 8, 20, max_seq - 1):
            q = jnp.asarray(rs.randn(1, 1, h, d), jnp.float32)
            kn = jnp.asarray(rs.randn(1, 1, h, d), jnp.float32)
            vn = jnp.asarray(rs.randn(1, 1, h, d), jnp.float32)
            # traced position (the generate() scan regime)
            out_w, _ = jax.jit(
                lambda q, kn, vn, p: cached_attention(
                    q, kn, vn, (ck, cv), p, use_flash=False, window=w
                )
            )(q, kn, vn, jnp.int32(pos))
            # full-band reference: window >= max_seq disables the slice
            ck2 = jax.lax.dynamic_update_slice(ck, kn, (0, pos, 0, 0))
            cv2 = jax.lax.dynamic_update_slice(cv, vn, (0, pos, 0, 0))
            logits = jnp.einsum(
                "bqhd,bkhd->bhqk", q, ck2
            ).astype(jnp.float32) / np.sqrt(d)
            j = jnp.arange(max_seq)
            vis = (j <= pos) & (j > pos - w)
            logits = jnp.where(vis[None, None, None], logits, -jnp.inf)
            ref = jnp.einsum(
                "bhqk,bkhd->bqhd",
                jax.nn.softmax(logits, -1).astype(q.dtype), cv2,
            )
            np.testing.assert_allclose(
                np.asarray(out_w), np.asarray(ref), rtol=2e-5, atol=2e-5,
                err_msg=f"pos={pos}",
            )
