"""Fake-mode semantics.  Behavioral spec: reference
tests/python/test_fake.py (enter/exit semantics, meta_like property
preservation and error) plus fake-TPU-without-TPU, the analog of the
reference's fake-CUDA-without-CUDA."""

import jax
import jax.numpy as jnp
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu import ops


def test_fake_creation_inside_mode():
    with tdx.fake_mode():
        x = ops.zeros((3, 4))
    assert tdx.is_fake(x)
    assert x.shape == (3, 4)
    assert x.dtype == jnp.float32
    assert "fake=True" in repr(x)


def test_real_outside_mode():
    x = ops.zeros((3, 4))
    assert not tdx.is_fake(x)
    assert isinstance(x, jax.Array)


def test_mode_is_reentrant():
    with tdx.fake_mode():
        with tdx.fake_mode():
            x = ops.ones((2,))
        y = ops.ones((2,))
    assert tdx.is_fake(x) and tdx.is_fake(y)
    z = ops.ones((2,))
    assert not tdx.is_fake(z)


def test_fake_tpu_claim_without_tpu():
    # On the CPU-only test platform, claim a TPU device anyway — the analog
    # of fake_cuda on a CUDA-less host (reference test_fake.py:13-40).
    with tdx.fake_mode(fake_tpu=True):
        x = ops.zeros((5, 5))
    assert tdx.is_fake(x)
    assert str(x.device) == "tpu:0"


def test_ops_on_fakes_propagate_shapes():
    with tdx.fake_mode():
        a = ops.ones((4, 8))
        b = ops.ones((8, 16))
        c = a @ b
        d = (c + 1.0).astype(jnp.bfloat16)
        s = d.sum(axis=0)
    assert c.shape == (4, 16)
    assert d.dtype == jnp.bfloat16
    assert s.shape == (16,)
    assert all(tdx.is_fake(t) for t in (c, d, s))


def test_fake_from_plain_mode_cannot_materialize():
    with tdx.fake_mode():
        x = ops.zeros((2, 2))
    assert not tdx.can_materialize(x)
    with pytest.raises(RuntimeError, match="cannot be materialized"):
        tdx.materialize_tensor(x)


def test_no_truth_value():
    with tdx.fake_mode():
        x = ops.zeros((2,))
    with pytest.raises(RuntimeError, match="no storage"):
        bool(x)


def test_meta_like_preserves_properties():
    # reference test_fake.py:43-60
    with tdx.fake_mode():
        x = ops.ones((7, 3), dtype=jnp.bfloat16)
    m = tdx.meta_like(x)
    assert isinstance(m, jax.ShapeDtypeStruct)
    assert m.shape == (7, 3)
    assert m.dtype == jnp.bfloat16

    r = jnp.ones((2, 2))
    m2 = tdx.meta_like(r)
    assert m2.shape == (2, 2)


def test_meta_like_rejects_non_array():
    with pytest.raises(ValueError):
        tdx.meta_like(object())


def test_generic_jnp_surface_via_ops():
    with tdx.fake_mode():
        a = ops.ones((2, 3))
        b = ops.concatenate([a, a], axis=0)
        c = ops.exp(b)
    assert b.shape == (4, 3)
    assert c.shape == (4, 3)
    # and on real arrays the same surface executes for real
    r = ops.concatenate([jnp.ones((1, 2)), jnp.zeros((1, 2))], axis=0)
    assert isinstance(r, jax.Array)
    assert r.shape == (2, 2)


class TestCatchAllInterception:
    """The fake-mode escape hatch is closed: plain jnp cannot silently
    allocate, fake args stay intercepted after the mode exits, comparisons
    propagate, and terminal ops materialize (or raise the framework error).
    Parity targets: reference fake.cc:546-548 (catch-all fallback),
    deferred_init.cc:813-825 (aten::item force-materialization)."""

    def test_plain_jnp_creation_is_intercepted(self):
        with tdx.fake_mode():
            z = jnp.zeros((4, 4))
            assert tdx.is_fake(z)
            a = jnp.array([1.0, 2.0])
            assert tdx.is_fake(a)
        # outside the mode, creation is real again
        assert isinstance(jnp.zeros((2,)), jax.Array)

    def test_jax_random_sampling_is_intercepted_keys_stay_real(self):
        import jax.random as jrandom

        with tdx.fake_mode():
            key = jrandom.PRNGKey(0)
            assert not tdx.is_fake(key)  # counter-RNG stream needs real keys
            s = jrandom.normal(key, (8,))
            assert tdx.is_fake(s)

    def test_jax_nn_initializers_are_intercepted(self):
        # Third-party ctor code calls jax.nn.initializers — the closures
        # must not silently allocate under the mode (reference parity: the
        # catch-all really catches everything, fake.cc:546-548).  The
        # interposition hooks the internal module's call-time globals, so
        # even closures created BEFORE any patch (e.g. flax's import-time
        # default_kernel_init) are covered.
        import jax.nn.initializers as ini

        from torchdistx_tpu.ops import _intercept

        try:
            _intercept.uninstall()
            pre_patch = ini.lecun_normal()  # closure made w/ NO patch active
            key = jax.random.PRNGKey(0)
            with tdx.fake_mode():
                assert tdx.is_fake(pre_patch(key, (64, 32)))
                assert tdx.is_fake(ini.zeros(key, (16,)))
                # orthogonal exercises the jnp.linalg.qr submodule path
                assert tdx.is_fake(ini.orthogonal()(key, (8, 8)))
            # outside the mode the same closure is real again
            assert isinstance(pre_patch(key, (4, 4)), jax.Array)
        finally:
            _intercept.ensure_installed()

    def test_module_proxy_resolves_rebinding_live(self, monkeypatch):
        # The initializer-globals proxy caches wrappers per underlying
        # object identity, so a later rebinding of the sampler in the
        # module the proxy stands in for must take effect inside
        # initializer closures exactly as it does for direct callers.
        # The module those closures actually resolve through is a jax
        # layout detail (public jax.random on 0.4.37, jax._src.random on
        # newer layouts) — unwrap the installed proxy to find it.
        import jax._src.nn.initializers as ini_internal
        import jax.nn.initializers as ini

        key = jax.random.PRNGKey(0)
        ini.uniform(1.0)(key, (4,))  # populate the proxy cache

        proxied = ini_internal.random
        target = getattr(proxied, "__wrapped_original__", proxied)
        real_uniform = target.uniform
        calls = []

        def stub(key, shape=(), *args, **kwargs):
            calls.append(tuple(shape))
            return real_uniform(key, shape, *args, **kwargs)

        monkeypatch.setattr(target, "uniform", stub)
        out = ini.uniform(1.0)(key, (4,))
        assert calls == [(4,)], "rebound sampler was not resolved live"
        assert isinstance(out, jax.Array)

    def test_initializer_deferred_replay_bit_identical(self):
        import numpy as np

        import jax.nn.initializers as ini

        def build():
            k = jax.random.PRNGKey(7)
            return {
                "w": ini.glorot_uniform()(k, (32, 16)),
                "q": ini.orthogonal()(k, (16, 16)),
            }

        m = tdx.deferred_init(build)
        assert tdx.is_fake(m["w"]) and tdx.is_fake(m["q"])
        w = tdx.materialize_tensor(m["w"])
        q = tdx.materialize_tensor(m["q"])
        eager = build()
        np.testing.assert_array_equal(np.asarray(w), np.asarray(eager["w"]))
        np.testing.assert_array_equal(np.asarray(q), np.asarray(eager["q"]))

    def test_jax_nn_activations_are_intercepted(self):
        # jax.nn entry points (relu/gelu/softmax/...) are non-jnp surface:
        # before round 4 a fake arg there leaked a raw JAX type error
        # (VERDICT r3 weak#4).  Two-level coverage: the public namespace
        # patch catches attribute-style calls, and the internal functions
        # module's call-time globals (jnp/lax) catch references captured
        # BEFORE any patch existed — same trick as the initializers.
        from torchdistx_tpu.ops import _intercept

        try:
            _intercept.uninstall()
            from jax.nn import gelu as pre_gelu  # captured w/ NO patch
            from jax.nn import relu as pre_relu
        finally:
            _intercept.ensure_installed()
        with tdx.fake_mode():
            x = jnp.ones((4, 8))
            assert tdx.is_fake(jax.nn.gelu(x))
            assert tdx.is_fake(jax.nn.relu(x))
            assert tdx.is_fake(jax.nn.softmax(x, axis=-1))
            assert tdx.is_fake(pre_gelu(x))
            assert tdx.is_fake(pre_relu(x))
        # fake args stay intercepted outside the mode (key-set parity)...
        assert tdx.is_fake(jax.nn.silu(x))
        # ...and real args still execute for real
        real = jax.nn.relu(jnp.array([-1.0, 2.0]))
        assert isinstance(real, jax.Array)
        assert float(real[0]) == 0.0

    def test_jax_nn_deferred_module_bit_identical(self):
        # VERDICT r3 item 6 done-criterion: a module whose ctor runs
        # jax.nn activations under deferred_init materializes
        # bit-identically to eager construction.
        import numpy as np

        def build():
            k = jax.random.PRNGKey(3)
            w = jax.random.normal(k, (16, 16))
            h = jax.nn.gelu(w @ w)
            return {"r": jax.nn.relu(h), "s": jax.nn.softmax(h, axis=-1)}

        m = tdx.deferred_init(build)
        assert tdx.is_fake(m["r"]) and tdx.is_fake(m["s"])
        r = tdx.materialize_tensor(m["r"])
        s = tdx.materialize_tensor(m["s"])
        eager = build()
        np.testing.assert_array_equal(np.asarray(r), np.asarray(eager["r"]))
        np.testing.assert_array_equal(np.asarray(s), np.asarray(eager["s"]))

    def test_numpy_ufunc_interop(self):
        # numpy scalars/arrays mixing with fakes must PROPAGATE (jax.nn
        # bodies do ``np_scalar * x``), while numpy-only ufunc surface
        # (where=/dtype=/out=, .reduce) falls back to the coercion path:
        # deferred fakes force-materialize, plain fakes raise the
        # framework storage error.
        import numpy as np

        with tdx.fake_mode():
            f = jnp.ones((3,))
            assert tdx.is_fake(np.float32(2.0) * f)
            assert tdx.is_fake(np.multiply(np.ones(3), f))
            assert tdx.is_fake(np.sqrt(f))
        # numpy-only kwargs on a plain fake -> framework error, not a
        # silent wrong answer or an opaque NotImplementedError
        with pytest.raises(RuntimeError, match="no storage"):
            np.multiply(np.ones(3), f, where=np.array([True, False, True]))
        # ...and on a deferred fake they materialize and compute for real
        d = tdx.deferred_init(lambda: jnp.full((3,), 2.0))
        out = np.multiply(
            np.ones(3), d, where=np.array([True, False, True]), out=np.zeros(3)
        )
        np.testing.assert_array_equal(out, [2.0, 0.0, 2.0])
        red = np.add.reduce(tdx.deferred_init(lambda: jnp.arange(4.0)))
        assert float(red) == 6.0

    def test_math_on_fakes_works_in_and_out_of_mode(self):
        with tdx.fake_mode():
            z = jnp.ones((3, 3))
            assert tdx.is_fake(jnp.sin(z))
        # leftover fake outside the mode: still intercepted (the record
        # travels with the array, like the reference's tensor key set)
        out = jnp.matmul(z, z)
        assert tdx.is_fake(out) and out.shape == (3, 3)

    def test_comparisons_propagate_not_silently_false(self):
        with tdx.fake_mode():
            f = jnp.ones((3,))
            c = f == 2
            assert tdx.is_fake(c)
            assert c.dtype == jnp.bool_
            with pytest.raises(RuntimeError, match="truth value"):
                bool(c)
            # non-array comparand falls back to identity semantics
            assert (f == None) is False  # noqa: E711
            assert (f != None) is True  # noqa: E711

    def test_terminal_ops_materialize_deferred(self):
        from torchdistx_tpu import nn

        m = tdx.deferred_init(lambda: nn.Linear(4, 4))
        w = m.weight
        assert tdx.is_fake(w)
        total = float(w.sum())  # derived value records + materializes
        assert total == w.sum().item()
        import numpy as np

        arr = np.asarray(w)  # __array__ is terminal too
        assert arr.shape == (4, 4)
        assert w.tolist() == arr.tolist()

    def test_terminal_ops_raise_for_plain_fakes(self):
        with tdx.fake_mode():
            g = jnp.ones(())
        with pytest.raises(RuntimeError, match="plain[\\s\\S]*fake_mode"):
            float(g)
        with pytest.raises(RuntimeError, match="never be materialized"):
            g.item()

    def test_creation_inside_jit_is_not_faked(self):
        # returning a FakeArray into a tracer would corrupt the trace; the
        # trace guard lets jit-compiled creation run for real
        with tdx.fake_mode():
            out = jax.jit(lambda: jnp.zeros(3))()
        assert isinstance(out, jax.Array)

    def test_static_outputs_pass_through(self):
        with tdx.fake_mode():
            f = jnp.ones((2, 5))
            assert jnp.shape(f) == (2, 5)
            assert jnp.ndim(f) == 2


class TestNoDeferredInit:
    """no_deferred_init(): the reference's NoDeferredInit guard
    (deferred_init.h:35-37) as public API — ops inside a suspended section
    run for real and are not recorded."""

    def test_real_compute_inside_deferred(self):
        captured = {}

        def build():
            from torchdistx_tpu import nn

            with tdx.no_deferred_init():
                table = jnp.arange(4.0) * 2  # concrete, unrecorded
                captured["table"] = table
            lin = nn.Linear(4, int(table[3]))  # value usable for shapes
            return lin

        m = tdx.deferred_init(build)
        assert isinstance(captured["table"], jax.Array)
        assert m._parameters["weight"].shape == (6, 4)
        assert tdx.is_fake(m._parameters["weight"])  # recording resumed
        tdx.materialize_module(m)
        assert m._parameters["weight"].shape == (6, 4)

    def test_suspends_plain_fake_mode_too(self):
        with tdx.fake_mode():
            with tdx.no_deferred_init():
                r = jnp.zeros((3,))
                assert isinstance(r, jax.Array)
            f = jnp.zeros((3,))
            assert tdx.is_fake(f)

    def test_restores_after_exception(self):
        def build():
            from torchdistx_tpu import nn

            try:
                with tdx.no_deferred_init():
                    raise RuntimeError("boom")
            except RuntimeError:
                pass
            return nn.Linear(2, 2)

        m = tdx.deferred_init(build)
        assert tdx.is_fake(m._parameters["weight"])

    def test_fake_args_stay_fake_inside_guard(self):
        # parity: the reference's NoDeferredInit clears only the mode key;
        # ops on fake tensor args still dispatch through the Fake handler
        # (a fake has no data to compute with)
        def build():
            from torchdistx_tpu import nn

            lin = nn.Linear(2, 2)
            with tdx.no_deferred_init():
                doubled = lin._parameters["weight"] * 2
            lin.register_parameter("wx2", doubled)
            return lin

        import numpy as np

        m = tdx.deferred_init(build)
        assert tdx.is_fake(m._parameters["wx2"])
        tdx.materialize_module(m)
        np.testing.assert_allclose(
            np.asarray(m._parameters["wx2"]),
            np.asarray(m._parameters["weight"]) * 2,
            rtol=1e-6,
        )
