"""Fake-mode semantics.  Behavioral spec: reference
tests/python/test_fake.py (enter/exit semantics, meta_like property
preservation and error) plus fake-TPU-without-TPU, the analog of the
reference's fake-CUDA-without-CUDA."""

import jax
import jax.numpy as jnp
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu import ops


def test_fake_creation_inside_mode():
    with tdx.fake_mode():
        x = ops.zeros((3, 4))
    assert tdx.is_fake(x)
    assert x.shape == (3, 4)
    assert x.dtype == jnp.float32
    assert "fake=True" in repr(x)


def test_real_outside_mode():
    x = ops.zeros((3, 4))
    assert not tdx.is_fake(x)
    assert isinstance(x, jax.Array)


def test_mode_is_reentrant():
    with tdx.fake_mode():
        with tdx.fake_mode():
            x = ops.ones((2,))
        y = ops.ones((2,))
    assert tdx.is_fake(x) and tdx.is_fake(y)
    z = ops.ones((2,))
    assert not tdx.is_fake(z)


def test_fake_tpu_claim_without_tpu():
    # On the CPU-only test platform, claim a TPU device anyway — the analog
    # of fake_cuda on a CUDA-less host (reference test_fake.py:13-40).
    with tdx.fake_mode(fake_tpu=True):
        x = ops.zeros((5, 5))
    assert tdx.is_fake(x)
    assert str(x.device) == "tpu:0"


def test_ops_on_fakes_propagate_shapes():
    with tdx.fake_mode():
        a = ops.ones((4, 8))
        b = ops.ones((8, 16))
        c = a @ b
        d = (c + 1.0).astype(jnp.bfloat16)
        s = d.sum(axis=0)
    assert c.shape == (4, 16)
    assert d.dtype == jnp.bfloat16
    assert s.shape == (16,)
    assert all(tdx.is_fake(t) for t in (c, d, s))


def test_fake_from_plain_mode_cannot_materialize():
    with tdx.fake_mode():
        x = ops.zeros((2, 2))
    assert not tdx.can_materialize(x)
    with pytest.raises(RuntimeError, match="cannot be materialized"):
        tdx.materialize_tensor(x)


def test_no_truth_value():
    with tdx.fake_mode():
        x = ops.zeros((2,))
    with pytest.raises(RuntimeError, match="no storage"):
        bool(x)


def test_meta_like_preserves_properties():
    # reference test_fake.py:43-60
    with tdx.fake_mode():
        x = ops.ones((7, 3), dtype=jnp.bfloat16)
    m = tdx.meta_like(x)
    assert isinstance(m, jax.ShapeDtypeStruct)
    assert m.shape == (7, 3)
    assert m.dtype == jnp.bfloat16

    r = jnp.ones((2, 2))
    m2 = tdx.meta_like(r)
    assert m2.shape == (2, 2)


def test_meta_like_rejects_non_array():
    with pytest.raises(ValueError):
        tdx.meta_like(object())


def test_generic_jnp_surface_via_ops():
    with tdx.fake_mode():
        a = ops.ones((2, 3))
        b = ops.concatenate([a, a], axis=0)
        c = ops.exp(b)
    assert b.shape == (4, 3)
    assert c.shape == (4, 3)
    # and on real arrays the same surface executes for real
    r = ops.concatenate([jnp.ones((1, 2)), jnp.zeros((1, 2))], axis=0)
    assert isinstance(r, jax.Array)
    assert r.shape == (2, 2)
