"""Host-RAM discipline regression for the sharded materialization path
(BASELINE.json config 3; VERDICT round-1 weak #8).

The claim (interop/torch_interop.py:8-10, _graph.py replay docstring): the
replay path stages O(one tensor) of host memory, never a full model copy.
On the 8-virtual-device CPU mesh the "device" buffers themselves live in
host RAM, so the observable bound is

    RSS delta  <=  total param bytes  +  one-tensor slack

i.e. materialization must not double-buffer (host copy + device copy).  On
a real TPU the same machinery measures ~0.23 GB host RSS for a 13.5 GB
model (bench.py), which is the stronger form of the claim.

The measurement runs in a FRESH subprocess (scripts/bench_baseline_configs
config 3): ru_maxrss is a process-lifetime high-water mark, so measuring
inside the long-lived pytest process would let any earlier memory peak make
the bound vacuously pass.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_sharded_materialize_rss_bound():
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "bench_baseline_configs.py"),
            "--cpu",
        ],
        capture_output=True,
        text=True,
        env=env,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(line) for line in proc.stdout.strip().splitlines()]
    cfg3 = next(r for r in rows if r.get("config") == 3)
    delta = cfg3["peak_host_rss_delta_gb"]
    params_gb = cfg3["param_bytes_gb"]
    # one-tensor slack: tok_emb (50257 x 1280 x 4B ~ 0.26 GB) + allocator
    # headroom; a double-buffered implementation would show ~2x params
    assert delta < params_gb + 0.8, (
        f"sharded materialize RSS delta {delta:.2f} GB exceeds params "
        f"({params_gb:.2f} GB) + one-tensor slack — host-RAM discipline "
        "regression (O(one-tensor) staging claim)"
    )
    # and the sharded path really fanned out over 8 devices
    assert cfg3["n_devices"] == 8
