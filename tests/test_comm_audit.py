"""Training-side observability (ISSUE 5) — the pinned invariants:

- **Analytic collective pins** (obs.comm): trace-time byte/op counts on
  the 8-device CPU mesh match the closed-form expectations for four
  legs — FSDP (gradient reduce-scatter payload == sharded parameter
  bytes, wire == (n-1)/n of it), TP (one forward all-reduce + one
  backward psum per Megatron layer), PP (1F1B exchanges ==
  2*(M + 2*(S-1))), GossipGraD (node-axis exchange of the full gradient
  bytes, one per traced branch).  A cached program's second call records
  NOTHING — the profile is per compiled program.
- **Sharding audit** (obs.memory): a deliberately replicated large
  parameter is flagged; replication the intended rule asked for is not;
  an optimizer state initialized without ``optimizer_state_shardings``
  is flagged against its sharded parameter.
- **Crash path** (obs.flight): an injected-NaN ``fit()`` writes a
  schema-valid flight dump whose last entries show the rollback
  (restored step + checkpoint path), and the streaming sink is readable
  BEFORE close (per-event flush — the ``kill -9`` contract).
- **Runtime gauges**: the default registry exposes flight depth and
  ``tdx_jit_cache_size{fn=...}`` with zero wiring.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu import nn
from torchdistx_tpu.nn import functional_call
from torchdistx_tpu.obs import comm_audit, sharding_report
from torchdistx_tpu.obs.comm import (
    CommProfile,
    record_collective,
    validate_comm_profile,
)
from torchdistx_tpu.obs.flight import FlightRecorder, validate_flight_jsonl
from torchdistx_tpu.parallel import (
    ShardedTrainStep,
    collectives,
    create_mesh,
    fsdp_shard_rule,
    optimizer_state_shardings,
)
from torchdistx_tpu.parallel.compat import shard_map
from torchdistx_tpu.trainer import Trainer
from torchdistx_tpu.utils.failure import FailureDetector

F32 = 4  # bytes


class MLP(nn.Module):
    def __init__(self, d=16, h=64):
        super().__init__()
        self.fc1 = nn.Linear(d, h)
        self.fc2 = nn.Linear(h, d)

    def forward(self, x):
        return self.fc2(jax.nn.relu(self.fc1(x)))


def _materialized_mlp():
    tdx.manual_seed(0)
    m = tdx.deferred_init(MLP)
    tdx.materialize_module(m)
    return m


def _mse_step(model, mesh, **kw):
    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((functional_call(model, p, (x,)) - y) ** 2)

    return ShardedTrainStep(loss_fn, optax.sgd(1e-2), mesh, **kw)


class TestCommAuditFSDP:
    """FSDP gradient sync bytes == parameter bytes (the ISSUE 5 pin)."""

    def test_closed_form_bytes_and_caching(self):
        n = 8
        mesh = create_mesh({"fsdp": n})
        model = _materialized_mlp()
        step = _mse_step(model, mesh, shard_axis="fsdp")
        params = step.shard_params(dict(model.named_parameters()))
        opt = step.init_optimizer(params)
        x = np.zeros((8, 16), np.float32)

        with comm_audit() as prof:
            params, opt, _ = step(params, opt, (x, x))

        # fc1/fc2 weights (1024 elems each) shard; biases (64/16) stay
        # replicated below min_shard_elems
        sharded_bytes = (64 * 16 + 16 * 64) * F32
        bias_bytes = (64 + 16) * F32
        assert prof.ops("all_gather", "fsdp") == 2
        assert prof.ops("reduce_scatter", "fsdp") == 2
        assert prof.payload_bytes("all_gather", "fsdp") == sharded_bytes
        assert prof.payload_bytes("reduce_scatter", "fsdp") == sharded_bytes
        # ring wire bytes: (n-1)/n of the payload, exactly
        assert prof.wire_bytes("reduce_scatter", "fsdp") == (
            sharded_bytes * (n - 1) / n
        )
        assert prof.wire_bytes("all_gather", "fsdp") == (
            sharded_bytes * (n - 1) / n
        )
        # replicated-leaf grads pmean (2 biases) + the loss pmean
        assert prof.ops("pmean", "fsdp") == 3
        assert prof.payload_bytes("pmean", "fsdp") == bias_bytes + F32

        # cached program: the second call must record NOTHING
        with comm_audit() as prof2:
            step(params, opt, (x, x))
        assert not prof2

    def test_profile_json_schema(self):
        prof = CommProfile()
        with comm_audit(prof):
            record_collective(
                "all_reduce", "dp", payload_bytes=1024, axis_size=4
            )
        doc = prof.to_json()
        assert validate_comm_profile(doc) == []
        assert doc["bytes_by_axis"] == {"dp": 1536}  # 2*(3/4)*1024
        # corrupt it -> the validator must say so
        doc["entries"][0]["ops"] = "three"
        assert validate_comm_profile(doc)
        assert validate_comm_profile({"schema": "nope"})

    def test_nested_audits_both_record(self):
        outer, inner = CommProfile(), CommProfile()
        with comm_audit(outer):
            with comm_audit(inner):
                record_collective(
                    "all_reduce", "dp", payload_bytes=8, axis_size=2
                )
        assert outer.ops() == inner.ops() == 1


class TestCommAuditTP:
    """Megatron f/g collectives: one fwd all-reduce + one bwd psum per
    layer, activation-sized."""

    def test_per_layer_allreduce_counts(self):
        n, d, h, b = 8, 16, 64, 4
        n_layers = 3
        mesh = create_mesh({"tp": n})
        rs = np.random.RandomState(0)
        ws = {
            f"w1_{i}": jnp.asarray(rs.randn(d, h).astype(np.float32))
            for i in range(n_layers)
        } | {
            f"w2_{i}": jnp.asarray(rs.randn(h, d).astype(np.float32))
            for i in range(n_layers)
        }
        x = jnp.asarray(rs.randn(b, d).astype(np.float32))

        def loss_fn(p, x):
            h_act = x
            for i in range(n_layers):
                xin = collectives.copy_psum_grad(h_act, "tp")
                mid = jax.nn.relu(xin @ p[f"w1_{i}"])
                h_act = collectives.allreduce_linear(
                    mid @ p[f"w2_{i}"], "tp"
                )
            return jnp.sum(h_act)

        def body(p, x):
            # differentiate wrt the input too (as an embedding below the
            # first TP layer would): every layer's input cotangent is
            # live, so every f-backward psum traces
            return jax.grad(loss_fn, argnums=(0, 1))(p, x)[0]

        specs = {
            f"w1_{i}": P(None, "tp") for i in range(n_layers)
        } | {f"w2_{i}": P("tp", None) for i in range(n_layers)}
        f = jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(specs, P()),
                out_specs=specs,
                check_vma=False,
            )
        )
        with comm_audit() as prof:
            jax.block_until_ready(f(ws, x))

        act_bytes = b * d * F32
        # forward: exactly one activation all-reduce per layer
        assert prof.ops("allreduce_linear", "tp") == n_layers
        assert prof.payload_bytes("allreduce_linear", "tp") == (
            n_layers * act_bytes
        )
        assert prof.wire_bytes("allreduce_linear", "tp") == (
            n_layers * act_bytes * 2 * (n - 1) / n
        )
        # backward: one psum per layer where the activation entered (f's
        # custom VJP), zero-wire identity for g's backward
        assert prof.ops("copy_psum_grad_bwd", "tp") == n_layers
        assert prof.payload_bytes("copy_psum_grad_bwd", "tp") == (
            n_layers * act_bytes
        )
        assert prof.ops("allreduce_linear_bwd", "tp") == n_layers
        assert prof.wire_bytes("allreduce_linear_bwd", "tp") == 0

    def test_dead_input_cotangent_is_pruned(self):
        """grad wrt params only: the FIRST layer's f-backward psum has a
        dead cotangent (nothing upstream is differentiated) and JAX
        prunes it — the audit must show n_layers-1, not n_layers, or the
        analytic model overstates backward traffic."""
        n, d, b, n_layers = 8, 16, 4, 3
        mesh = create_mesh({"tp": n})
        rs = np.random.RandomState(0)
        ws = {
            f"w_{i}": jnp.asarray(rs.randn(d, d).astype(np.float32))
            for i in range(n_layers)
        }

        def loss_fn(p, x):
            h_act = x
            for i in range(n_layers):
                xin = collectives.copy_psum_grad(h_act, "tp")
                h_act = collectives.allreduce_linear(
                    xin @ p[f"w_{i}"], "tp"
                )
            return jnp.sum(h_act)

        f = jax.jit(
            shard_map(
                lambda p, x: jax.grad(loss_fn)(p, x),
                mesh=mesh,
                in_specs=({k: P() for k in ws}, P()),
                out_specs={k: P() for k in ws},
                check_vma=False,
            )
        )
        with comm_audit() as prof:
            jax.block_until_ready(
                f(ws, jnp.asarray(rs.randn(b, d).astype(np.float32)))
            )
        assert prof.ops("copy_psum_grad_bwd", "tp") == n_layers - 1


class TestCommAuditPP:
    """1F1B schedule: exchange ops == 2*(M + 2*(S-1)) of one microbatch
    activation each (scan trip counts recorded statically)."""

    def test_1f1b_exchange_closed_form(self):
        from torchdistx_tpu.parallel.pp import (
            pipeline_train_step,
            split_microbatches,
            stack_pipeline_stages,
        )

        S = 4
        mesh = create_mesh({"pp": S}, devices=jax.devices()[:S])
        d, b, n_micro = 8, 2, 6
        rs = np.random.RandomState(1)
        stages = [
            {"w": jnp.asarray(rs.randn(d, d).astype(np.float32) * 0.1)}
            for _ in range(S)
        ]
        stacked = stack_pipeline_stages(stages, mesh, axis="pp")
        mb = split_microbatches(
            jnp.asarray(rs.randn(n_micro * b, d).astype(np.float32)),
            n_micro,
        )
        tgt = jnp.zeros_like(mb)

        def stage_fn(p, x):
            return x + jnp.tanh(x @ p["w"])

        with comm_audit() as prof:
            loss, grads = pipeline_train_step(
                stacked, mb, tgt,
                mesh=mesh, stage_fn=stage_fn,
                loss_fn=lambda y, t: jnp.mean((y - t) ** 2),
                axis="pp",
            )
            jax.block_until_ready(loss)

        ticks = n_micro + 2 * (S - 1)
        act_bytes = b * d * F32
        assert prof.ops("exchange", "pp") == 2 * ticks
        assert prof.payload_bytes("exchange", "pp") == (
            2 * ticks * act_bytes
        )
        # each lockstep ppermute drives S-1 of the S ring links
        assert prof.wire_bytes("exchange", "pp") == pytest.approx(
            2 * ticks * act_bytes * (S - 1) / S
        )
        # the loss replication psum
        assert prof.ops("all_reduce", "pp") == 1


class TestCommAuditGossip:
    """GossipGraD: intra-node all-mean of the full gradient once per
    step, one node-axis exchange per traced schedule branch."""

    def test_gossip_bytes(self, mesh2x4):
        from torchdistx_tpu.parallel import (
            GossipGraDState,
            gossip_grad_hook,
        )

        tdx.manual_seed(3)
        model = _materialized_mlp()
        gparams = dict(model.named_parameters())
        state = GossipGraDState(2, node_axis="node", local_axis="local")
        n_branches = len(state.branch_table()[0])
        step = _mse_step(
            model, mesh2x4,
            shard_axis=None,
            replica_axes=("node",),
            comm_hook=gossip_grad_hook,
            hook_state=state,
            divergent_replicas=True,
            batch_axes=("node", "local"),
        )
        p = step.stack_replicas(gparams)
        s = step.init_optimizer(p)
        x = np.zeros((8, 16), np.float32)
        y = np.zeros((8, 16), np.float32)
        with comm_audit() as prof:
            p, s, _ = step(p, s, (x, y))

        # per-replica gradient bytes: the hook sees the (1, ...) stacked
        # local view — same element count as the parameters themselves
        grad_bytes = sum(
            int(np.prod(v.shape)) * F32 for v in gparams.values()
        )
        # local-axis combine: the hook owns only replica_axes=("node",),
        # so the trainer's grad_reduce_axes pmean carries the local-axis
        # gradient traffic (+ the scalar loss-replication pmean)
        assert prof.ops("pmean", "local") == 2
        assert prof.payload_bytes("pmean", "local") == grad_bytes + F32
        # every lax.switch branch traces: one exchange per branch, each
        # of the full gradient (a conservative upper bound by design —
        # exactly n_branches at trace time)
        assert prof.ops("exchange", "node") == n_branches
        assert prof.payload_bytes("exchange", "node") == (
            n_branches * grad_bytes
        )


class TestCommAuditRing:
    """Sequence-parallel attention traffic is booked (the TDX103 fix):
    ring passes record n ppermute ops per rotating tensor (the length-n
    scan executes every rotation, INCLUDING the final home-coming hop —
    the audit books what runs, not the textbook n-1), Ulysses records
    its four all-to-alls.  Payloads are exact per-device block bytes."""

    def _qkv(self, b, s, h, d, seed=0):
        rs = np.random.RandomState(seed)
        return tuple(
            jnp.asarray(rs.randn(b, s, h, d), jnp.float32) for _ in range(3)
        )

    def test_jnp_ring_forward_closed_form(self, mesh8):
        from torchdistx_tpu.ops.attention import ring_attention

        n = 8
        b, s, h, d = 2, 64, 4, 16
        q, k, v = self._qkv(b, s, h, d)
        fn = jax.jit(
            shard_map(
                lambda q_, k_, v_: ring_attention(
                    q_, k_, v_, axis="fsdp", causal=True
                ),
                mesh=mesh8,
                in_specs=(P(None, "fsdp"),) * 3,
                out_specs=P(None, "fsdp"),
                check_vma=False,
            )
        )
        with comm_audit() as prof:
            fn(q, k, v)
        # rotating carry: K block, V block, 4-byte block index
        blk = b * (s // n) * h * d * F32
        ring_bytes = n * (2 * blk + 4)
        assert prof.ops("ppermute", "fsdp") == 3 * n
        assert prof.payload_bytes("ppermute", "fsdp") == ring_bytes
        # full-rotation ring hop: every device sends, wire ratio 1.0
        assert prof.wire_bytes("ppermute", "fsdp") == ring_bytes
        assert validate_comm_profile(prof.to_json()) == []

        # cached program: the second call must record NOTHING
        with comm_audit() as prof2:
            fn(q, k, v)
        assert not prof2

    def test_flash_ring_backward_books_five_tensors(self, mesh8):
        from torchdistx_tpu.ops.attention import ring_flash_attention

        n = 8
        b, s, h, d = 1, 64, 4, 8
        q, k, v = self._qkv(b, s, h, d, seed=1)
        ring = shard_map(
            lambda q_, k_, v_: ring_flash_attention(
                q_, k_, v_, axis="fsdp", causal=True, block_q=8, block_k=8
            ),
            mesh=mesh8,
            in_specs=(P(None, "fsdp"),) * 3,
            out_specs=P(None, "fsdp"),
            check_vma=False,
        )
        grad_fn = jax.jit(
            jax.grad(
                lambda q_, k_, v_: jnp.sum(jnp.sin(ring(q_, k_, v_))),
                argnums=(0, 1, 2),
            )
        )
        with comm_audit() as prof:
            grad_fn(q, k, v)
        kv = b * (s // n) * h * d * F32
        # forward ring: K, V, index; backward ring: K, V, their f32
        # gradient accumulators, index — five rotating tensors
        fwd_bytes = n * (2 * kv + 4)
        bwd_bytes = n * (4 * kv + 4)
        assert prof.ops("ppermute", "fsdp") == (3 + 5) * n
        assert prof.payload_bytes("ppermute", "fsdp") == fwd_bytes + bwd_bytes
        assert prof.wire_bytes("ppermute", "fsdp") == fwd_bytes + bwd_bytes

    def test_ulysses_all_to_all_closed_form(self, mesh8):
        from torchdistx_tpu.ops.attention import ulysses_attention

        n = 8
        b, s, h, d = 2, 64, 8, 16
        q, k, v = self._qkv(b, s, h, d, seed=2)
        fn = jax.jit(
            shard_map(
                lambda q_, k_, v_: ulysses_attention(
                    q_, k_, v_, axis="fsdp", causal=True, use_flash=False
                ),
                mesh=mesh8,
                in_specs=(P(None, "fsdp"),) * 3,
                out_specs=P(None, "fsdp"),
                check_vma=False,
            )
        )
        with comm_audit() as prof:
            fn(q, k, v)
        # q/k/v reshard out, attention output reshards back: four
        # all-to-alls of one per-device tensor each
        t = b * (s // n) * h * d * F32
        assert prof.ops("all_to_all", "fsdp") == 4
        assert prof.payload_bytes("all_to_all", "fsdp") == 4 * t
        # each device keeps its own slice: (n-1)/n of the payload on wire
        assert prof.wire_bytes("all_to_all", "fsdp") == 4 * t * (n - 1) / n


class TestShardingAudit:
    def test_flags_deliberate_replication(self, mesh8):
        big = jax.device_put(
            jnp.zeros((64, 64), jnp.float32), NamedSharding(mesh8, P())
        )
        sharded = jax.device_put(
            jnp.zeros((64, 64), jnp.float32),
            NamedSharding(mesh8, P("fsdp", None)),
        )
        small = jax.device_put(
            jnp.zeros((8,), jnp.float32), NamedSharding(mesh8, P())
        )
        rep = sharding_report(
            {"big": big, "sharded": sharded, "small": small}
        )
        kinds = {(f["kind"], f["path"]) for f in rep["flags"]}
        assert ("accidental_replication", "big") in kinds
        assert all(p != "sharded" for _, p in kinds)
        assert all(p != "small" for _, p in kinds)  # under min_shard_elems
        assert rep["total_bytes"] == (64 * 64 * 2 + 8) * F32
        # per-device: one full copy of big + 1/8 of sharded + small
        assert rep["bytes_per_device"] == (
            64 * 64 * F32 + 64 * 64 * F32 // 8 + 8 * F32
        )

    def test_planned_replication_not_flagged(self, mesh8):
        big = jax.device_put(
            jnp.zeros((64, 64), jnp.float32), NamedSharding(mesh8, P())
        )
        rep = sharding_report(
            {"big": big},
            intended_rule=lambda path, a: NamedSharding(mesh8, P()),
        )
        assert rep["flags"] == []
        # ... but an intended-vs-actual mismatch IS flagged
        rep2 = sharding_report(
            {"big": big},
            intended_rule=lambda path, a: NamedSharding(
                mesh8, P("fsdp", None)
            ),
        )
        assert [f["kind"] for f in rep2["flags"]] == ["sharding_mismatch"]

    def test_flags_unsharded_optimizer_state(self, mesh8):
        model = _materialized_mlp()
        params = {
            k: jax.device_put(
                v,
                NamedSharding(
                    mesh8,
                    P("fsdp", None) if v.ndim == 2 else P(),
                ),
            )
            for k, v in dict(model.named_parameters()).items()
        }
        opt = optax.adam(1e-3)
        # WITHOUT optimizer_state_shardings: moments land replicated
        bad_state = jax.jit(opt.init)(
            jax.device_put(
                {k: np.asarray(v) for k, v in params.items()},
                NamedSharding(mesh8, P()),
            )
        )
        rep = sharding_report(params, optimizer_state=bad_state)
        bad = [
            f for f in rep["flags"]
            if f["kind"] == "unsharded_optimizer_state"
        ]
        # adam keeps mu and nu per sharded weight -> 2 slots x 2 weights
        assert len(bad) == 4
        assert all("optimizer_state_shardings" in f["detail"] for f in bad)

        # WITH the proper out_shardings: clean report
        shardings = optimizer_state_shardings(
            jax.eval_shape(opt.init, params), params, mesh8
        )
        good_state = jax.jit(opt.init, out_shardings=shardings)(params)
        rep2 = sharding_report(params, optimizer_state=good_state)
        assert [
            f for f in rep2["flags"]
            if f["kind"] == "unsharded_optimizer_state"
        ] == []


class TestFlightRecorder:
    def test_ring_bound_and_dump_header(self, tmp_path):
        rec = FlightRecorder(capacity=4)
        for i in range(10):
            rec.record("tick", i=i)
        assert rec.depth == 4 and rec.recorded_total == 10
        path = rec.dump(str(tmp_path / "d.jsonl"), reason="test")
        assert validate_flight_jsonl(path) == []
        lines = [json.loads(x) for x in open(path)]
        assert lines[0]["kind"] == "flight_header"
        assert lines[0]["reason"] == "test"
        assert lines[0]["dropped"] == 6
        assert [e["i"] for e in lines[1:]] == [6, 7, 8, 9]

    def test_stream_flushes_per_event(self, tmp_path):
        # kill -9 semantics: every record must be ON DISK before close
        path = str(tmp_path / "stream.jsonl")
        rec = FlightRecorder(path=path)
        rec.record("a", x=1)
        rec.record("b", y=2)
        with open(path) as f:  # recorder still open — no close, no flush call
            lines = [json.loads(ln) for ln in f.read().splitlines()]
        assert [e["kind"] for e in lines] == ["a", "b"]
        assert validate_flight_jsonl(path) == []
        rec.close_stream()

    def test_validator_rejects_garbage(self, tmp_path):
        p = tmp_path / "bad.jsonl"
        p.write_text('{"kind": "ok", "t": 1.0}\nnot json\n{"t": 2.0}\n')
        errs = validate_flight_jsonl(str(p))
        assert len(errs) == 2  # bad line + missing kind


def _fit_nan_rollback(tmp_path, on_failure="restore"):
    """Shared crash-path scaffold: 4 clean steps (checkpoint at 2/4),
    then a poisoned parameter."""
    mesh = create_mesh({"fsdp": 8})
    model = _materialized_mlp()
    step = _mse_step(model, mesh, shard_axis="fsdp")
    params = step.shard_params(dict(model.named_parameters()))
    opt = step.init_optimizer(params)
    rs = np.random.RandomState(0)
    batches = [
        (b, b) for b in (rs.randn(8, 16).astype(np.float32)
                         for _ in range(8))
    ]
    rec = FlightRecorder(dump_dir=str(tmp_path))
    trainer = Trainer(
        step, params, opt,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
        log_every=1, log_fn=lambda m: None,
        failure_detector=FailureDetector(nan_tolerance=0),
        on_failure=on_failure, flight=rec,
    )
    trainer.fit(batches[:4])
    poisoned = dict(trainer.params)
    k0 = next(iter(poisoned))
    poisoned[k0] = poisoned[k0] * jnp.float32(np.nan)
    trainer.params = poisoned
    return trainer, batches


class TestCrashPath:
    def test_nan_rollback_writes_flight_dump(self, tmp_path):
        trainer, batches = _fit_nan_rollback(tmp_path)
        res = trainer.fit(batches[4:])
        assert np.isfinite(res["loss"])  # rollback recovered the run

        dump = trainer.last_flight_dump
        assert dump and os.path.dirname(dump) == str(tmp_path)
        assert validate_flight_jsonl(dump) == []
        recs = [json.loads(ln) for ln in open(dump)]
        # the LAST entries show the incident: failure then rollback
        assert [r["kind"] for r in recs[-2:]] == ["failure", "rollback"]
        rb = recs[-1]
        assert rb["action"] == "restored"
        assert rb["restored_step"] == 4
        assert rb["checkpoint"].endswith("step_4")
        assert recs[-2]["failure_kind"] == "nonfinite"
        # step records carry the telemetry fields the ISSUE names
        step_rec = next(r for r in recs if r["kind"] == "step")
        for field in ("loss", "rng_counter", "comm", "steps_per_sec"):
            assert field in step_rec
        assert validate_comm_profile(
            trainer.comm_profile.to_json()
        ) == []

    def test_raise_policy_dumps_on_exception(self, tmp_path):
        trainer, batches = _fit_nan_rollback(tmp_path, on_failure="raise")
        with pytest.raises(Exception):
            trainer.fit(batches[4:])
        dump = trainer.last_flight_dump
        assert dump and validate_flight_jsonl(dump) == []
        recs = [json.loads(ln) for ln in open(dump)]
        assert recs[-1]["kind"] == "exception"
        assert "StepFailure" in recs[-1]["error"]

    def test_detector_counters_scrapeable(self, tmp_path):
        from torchdistx_tpu.obs.metrics import (
            MetricsRegistry,
            parse_prometheus,
        )

        trainer, batches = _fit_nan_rollback(tmp_path)
        trainer.fit(batches[4:])
        reg = MetricsRegistry()
        reg.register_collector(trainer.metrics_collector(), obj=trainer)
        parsed = parse_prometheus(reg.render())
        s = parsed["samples"]
        assert s[("tdx_train_failures_total", ())] == 1
        assert s[
            ("tdx_train_failure_events_total", (("kind", "nonfinite"),))
        ] == 1
        assert s[("tdx_train_consecutive_nonfinite", ())] == 0  # reset
        assert 0 < s[("tdx_train_goodput", ())] <= 1


class TestRuntimeGauges:
    def test_default_registry_serves_flight_and_jit_gauges(self):
        from torchdistx_tpu.obs.metrics import (
            default_registry,
            parse_prometheus,
        )
        from torchdistx_tpu.obs.recompile import track_jit_cache

        jitted = jax.jit(lambda x: x + 1)
        jitted(jnp.zeros(4))
        track_jit_cache("audit_test_fn", jitted)
        parsed = parse_prometheus(default_registry().render())
        s = parsed["samples"]
        assert ("tdx_flight_depth", ()) in s
        assert ("tdx_flight_capacity", ()) in s
        key = ("tdx_jit_cache_size", (("fn", "audit_test_fn"),))
        assert s[key] >= 1

    def test_trainer_mfu_gauge(self, tmp_path):
        mesh = create_mesh({"fsdp": 8})
        model = _materialized_mlp()
        step = _mse_step(model, mesh, shard_axis="fsdp")
        params = step.shard_params(dict(model.named_parameters()))
        opt = step.init_optimizer(params)
        batches = [(np.zeros((8, 16), np.float32),) * 2 for _ in range(4)]
        trainer = Trainer(
            step, params, opt, log_every=1, log_fn=lambda m: None,
            tokens_per_batch=128, flops_per_token=1000.0,
            peak_flops=1e9,
            flight=FlightRecorder(dump_dir=str(tmp_path)),
        )
        trainer.fit(batches)
        assert trainer.metrics["tokens_per_sec"] > 0
        assert trainer.metrics["mfu"] == pytest.approx(
            trainer.metrics["tokens_per_sec"] * 1000.0 / 1e9
        )
        assert 0 < trainer.metrics["goodput"] <= 1
