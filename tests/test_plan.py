"""Declarative sharding plans (parallel/plan.py).

Five pinned behaviours from the issue:
  1. rule resolution — first-match-wins precedence, no-match fallback
     (replicated, or FSDP over default_axis);
  2. plan-vs-manual bit-identity: materializing under the plan's rule
     and deriving optimizer shardings from it must reproduce the
     pre-plan manual wiring EXACTLY (placements and bits) for fsdp,
     tp=2, and dp x tp layouts on the 8-device CPU mesh;
  3. ZeRO-2: a dp-replicated model trained with plan-sharded optimizer
     state is BITWISE identical to the replicated-optimizer oracle
     (elementwise update math), while optimizer bytes/device drop to
     1/dp;
  4. closed-form wire pins: the ZeRO-2 updated-params all-gather books
     exactly ``(n-1)/n * participating_bytes`` per step into the comm
     audit, equal to ``plan.price_step`` (plan == audit == counters);
  5. loud failure: a plan overshooting a named per-device budget raises
     PlanError naming the budget at plan time, on both the
     shape-only (capacity_plan) and materialized (sharding_report)
     validation paths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu.models import Llama
from torchdistx_tpu.nn import functional, functional_call
from torchdistx_tpu.obs.comm import comm_audit
from torchdistx_tpu.parallel import (
    GSPMDTrainStep,
    PlanError,
    ShardingPlan,
    create_mesh,
    fsdp_partition_spec,
    llama_tp_plan,
    optimizer_state_shardings,
)
from torchdistx_tpu.parallel.fsdp import fsdp_shard_rule

GIB = 1024**3


def _llama_params(seed, sharding_rule=None):
    tdx.manual_seed(seed)
    model = tdx.deferred_init(Llama.from_name, "tiny")
    if sharding_rule is None:
        tdx.materialize_module(model)
    else:
        tdx.materialize_module(model, sharding_rule=sharding_rule)
    return model, dict(model.named_parameters())


def _loss_fn(model):
    def loss_fn(p, batch):
        tokens, labels = batch
        logits = functional_call(model, p, (tokens,))
        return functional.cross_entropy(logits, labels)

    return loss_fn


def _data(vocab=256, b=8, s=16, seed=0):
    # globally unique tokens: the ZeRO-2 bitwise-vs-oracle assertions
    # are about the elementwise update math being exactly shardable —
    # duplicate tokens would additionally test embedding scatter-add
    # summation order, which the partitioner is free to reassociate
    rs = np.random.RandomState(seed)
    tokens = rs.permutation(vocab)[: b * s].reshape(b, s).astype(np.int32)
    labels = rs.randint(0, vocab, (b, s)).astype(np.int32)
    return tokens, labels


class TestRuleResolution:
    def test_first_match_wins(self, mesh8):
        plan = ShardingPlan(
            mesh8,
            rules=(
                (r"\.weight$", P("fsdp", None)),
                (r"attn\..*\.weight$", P(None, "fsdp")),
            ),
        )
        # both patterns match; the FIRST rule is the plan's answer
        assert plan.spec_for("blocks.0.attn.wq.weight", (64, 64)) == P(
            "fsdp", None
        )
        # re.search, not fullmatch: substrings anywhere in the path hit
        assert plan.spec_for("deep.nesting.attn.weight", (64, 64)) == P(
            "fsdp", None
        )

    def test_no_match_falls_back_to_replicated(self, mesh8):
        plan = ShardingPlan(mesh8, rules=((r"\.weight$", P("fsdp", None)),))
        assert plan.spec_for("something.bias", (64,)) == P()
        assert plan.maybe_spec_for("something.bias", (64,)) is None

    def test_no_match_with_default_axis_fsdp_shards(self, mesh8):
        plan = ShardingPlan(mesh8, default_axis="fsdp")
        assert plan.spec_for("h", (4096, 64)) == fsdp_partition_spec(
            (4096, 64), mesh8, "fsdp", 1024
        )
        # below min_shard_elems the fallback replicates...
        assert plan.spec_for("tiny.bias", (8,)) == P()
        # ...but an EXPLICIT rule applies even to tiny tensors
        ruled = ShardingPlan(
            mesh8, rules=((r"bias$", P("fsdp")),), default_axis="fsdp"
        )
        assert ruled.spec_for("tiny.bias", (8,)) == P("fsdp")

    def test_unknown_axes_fail_loudly(self, mesh8):
        with pytest.raises(PlanError, match="default_axis"):
            ShardingPlan(mesh8, default_axis="nope")
        with pytest.raises(PlanError, match="references axis"):
            ShardingPlan(mesh8, rules=((r".", P("tp")),))
        with pytest.raises(PlanError, match="requires dp_axis"):
            ShardingPlan(mesh8, zero2=True)

    def test_with_mesh_carries_rules(self, mesh8):
        from jax.sharding import Mesh

        plan = ShardingPlan(
            mesh8, rules=((r"w", P("fsdp")),), default_axis="fsdp"
        )
        small = Mesh(np.array(jax.devices()[:4]).reshape(4), ("fsdp",))
        moved = plan.with_mesh(small)
        assert moved.rules == plan.rules
        assert moved.spec_for("w", (8, 8)) == P("fsdp")
        assert int(moved.mesh.shape["fsdp"]) == 4

    def test_with_mesh_rejects_missing_axis_eagerly(self, mesh8, mesh2x4):
        plan = ShardingPlan(mesh8, default_axis="fsdp")
        with pytest.raises(PlanError):
            plan.with_mesh(mesh2x4)


class TestPlanVsManual:
    """The plan must reproduce the manual wiring it subsumes, bit for
    bit: same placements, same materialized values, same derived
    optimizer shardings."""

    def _assert_same_shardings(self, a, b):
        fa = jax.tree_util.tree_leaves(
            a, is_leaf=lambda x: isinstance(x, NamedSharding)
        )
        fb = jax.tree_util.tree_leaves(
            b, is_leaf=lambda x: isinstance(x, NamedSharding)
        )
        assert len(fa) == len(fb)
        for sa, sb in zip(fa, fb):
            assert sa.spec == sb.spec, (sa, sb)

    def _check(self, mesh, plan, manual_rule):
        _, manual = _llama_params(0, manual_rule)
        _, planned = _llama_params(0, plan.as_rule())
        for k in manual:
            assert planned[k].sharding.spec == manual[k].sharding.spec, k
            np.testing.assert_array_equal(
                np.asarray(planned[k]), np.asarray(manual[k]), err_msg=k
            )
        tx = optax.adam(1e-3)
        state_shape = jax.eval_shape(tx.init, planned)
        self._assert_same_shardings(
            plan.optimizer_state_shardings(state_shape, planned),
            optimizer_state_shardings(state_shape, manual, mesh),
        )

    def test_fsdp(self, mesh8):
        self._check(
            mesh8,
            ShardingPlan.fsdp(mesh8),
            fsdp_shard_rule(mesh8, axis="fsdp"),
        )

    def test_tp2(self):
        from torchdistx_tpu.parallel.tp import llama_tp_rule

        mesh = create_mesh({"dp": 4, "tp": 2})
        self._check(
            mesh, llama_tp_plan(mesh, "tp"), llama_tp_rule(mesh, "tp")
        )

    def test_dp_x_tp_2d(self):
        from torchdistx_tpu.parallel.tp import llama_tp_rule

        mesh = create_mesh({"fsdp": 4, "tp": 2})
        self._check(
            mesh,
            llama_tp_plan(mesh, "tp", fsdp_axis="fsdp"),
            llama_tp_rule(mesh, "tp", fsdp_axis="fsdp"),
        )


class TestZero2:
    """Automatic ZeRO-2 weight-update sharding (arXiv:2004.13336): the
    plan replicates params over dp but shards optimizer slots + the
    update anyway, all-gathering updated params — bitwise identical to
    the replicated oracle, at 1/dp optimizer memory."""

    def _setup(self):
        mesh = create_mesh({"dp": 8})
        plan = ShardingPlan(mesh, dp_axis="dp", zero2=True, min_shard_elems=1)
        model, params = _llama_params(0, plan.as_rule())
        return mesh, plan, model, params

    def _opt_bytes_per_device(self, state):
        total = 0
        for leaf in jax.tree_util.tree_leaves(state):
            if not isinstance(leaf, jax.Array):
                continue
            shard = leaf.sharding.shard_shape(leaf.shape)
            total += int(np.prod(shard, dtype=np.int64)) * leaf.dtype.itemsize
        return total

    def test_ten_steps_bitwise_vs_replicated_oracle(self):
        mesh, plan, model, params = self._setup()
        loss_fn = _loss_fn(model)
        # momentum SGD: param-shaped slots, no scalar count leaf — the
        # 1/dp assertion below is exact
        tx = optax.sgd(1e-1, momentum=0.9)
        batch = _data()

        step = GSPMDTrainStep(loss_fn, tx, mesh, batch_spec=P("dp"), plan=plan)
        state = step.init_optimizer(params)
        # plan-derived slots are dp-sharded even though params replicate
        sharded = [
            l for l in jax.tree_util.tree_leaves(state)
            if isinstance(l, jax.Array) and "dp" in str(l.sharding.spec)
        ]
        assert sharded, "no dp-sharded optimizer slot found"
        # optimizer bytes/device == 1/dp of the replicated footprint
        slot_total = sum(
            int(np.prod(l.shape, dtype=np.int64)) * l.dtype.itemsize
            for l in jax.tree_util.tree_leaves(state)
            if isinstance(l, jax.Array)
        )
        assert self._opt_bytes_per_device(state) * 8 == slot_total

        # oracle: identical step, replicated optimizer state (no plan)
        _, oparams = _llama_params(0, plan.as_rule())
        ostep = GSPMDTrainStep(loss_fn, tx, mesh, batch_spec=P("dp"))
        ostate = ostep.init_optimizer(oparams)

        for _ in range(10):
            params, state, loss = step(params, state, batch)
            oparams, ostate, oloss = ostep(oparams, ostate, batch)
        jax.block_until_ready((params, oparams))
        for k in oparams:
            np.testing.assert_array_equal(
                np.asarray(params[k]), np.asarray(oparams[k]), err_msg=k
            )
        np.testing.assert_array_equal(np.asarray(loss), np.asarray(oloss))
        # params stayed replicated (the plan's own placement for them)
        assert all(
            not str(v.sharding.spec).count("dp") for v in params.values()
        )

    def test_wire_pins_match_comm_audit_exactly(self):
        mesh, plan, model, params = self._setup()
        loss_fn = _loss_fn(model)
        tx = optax.sgd(1e-1, momentum=0.9)
        batch = _data()
        step = GSPMDTrainStep(loss_fn, tx, mesh, batch_spec=P("dp"), plan=plan)
        state = step.init_optimizer(params)

        param_bytes = sum(
            int(np.prod(v.shape, dtype=np.int64)) * v.dtype.itemsize
            for v in params.values()
        )
        # every tiny-Llama param has an 8-divisible dim, so with
        # min_shard_elems=1 ALL param bytes participate
        assert plan.zero2_participating_bytes(params) == param_bytes

        rows = plan.price_step(params)
        assert [r["kind"] for r in rows] == ["all_gather"]
        (row,) = rows
        assert row["axis"] == "dp"
        assert row["payload_bytes"] == param_bytes
        assert row["wire_bytes"] == param_bytes * 7 // 8  # (n-1)/n closed form

        k = 4
        with comm_audit() as prof:
            for _ in range(k):
                params, state, _ = step(params, state, batch)
        assert prof.ops("all_gather", "dp") == k
        assert prof.payload_bytes("all_gather", "dp") == k * param_bytes
        assert int(round(prof.wire_bytes("all_gather", "dp"))) == (
            k * plan.step_wire_bytes(params, "all_gather")
        )
        assert plan.step_wire_bytes(params) == param_bytes * 7 // 8

    def test_non_zero2_plan_prices_no_gather(self, mesh8):
        plan = ShardingPlan.replicated(mesh8)
        _, params = _llama_params(0)
        assert plan.price_step(params) == []
        assert plan.zero2_participating_bytes(params) == 0


class TestValidate:
    def test_budget_overshoot_fails_loudly_closed_form(self, mesh8):
        # 5B f32 params fully replicated: 20 GB/device > 16 GiB, priced
        # from ShapeDtypeStructs alone — nothing is allocated
        params = {
            "giant.weight": jax.ShapeDtypeStruct((50_000, 100_000), jnp.float32)
        }
        plan = ShardingPlan.replicated(mesh8)
        with pytest.raises(PlanError) as ei:
            plan.validate(
                params,
                budget_bytes_per_device=16 * GIB,
                budget_name="v5e HBM",
            )
        msg = str(ei.value)
        assert "v5e HBM" in msg  # the budget is NAMED
        assert str(16 * GIB) in msg  # ...with numbers
        assert "20000000000" in msg

    def test_sharded_plan_fits_same_budget(self, mesh8):
        params = {
            "giant.weight": jax.ShapeDtypeStruct((50_000, 100_000), jnp.float32)
        }
        doc = ShardingPlan.fsdp(mesh8).validate(
            params, budget_bytes_per_device=16 * GIB
        )
        assert doc["fits"] is True
        assert doc["components"]["params"] == 20_000_000_000 // 8

    def test_optimizer_state_counted_in_capacity(self, mesh8):
        params = {
            "giant.weight": jax.ShapeDtypeStruct((50_000, 100_000), jnp.float32)
        }
        state = jax.eval_shape(optax.adam(1e-3).init, params)
        plan = ShardingPlan.fsdp(mesh8)
        doc = plan.validate(params, optimizer_state=state)
        # adam: mu + nu sharded like the param (2x params per device),
        # plus the replicated 4-byte int32 step counter
        assert doc["components"]["optimizer_state"] == (
            2 * doc["components"]["params"] + 4
        )

    def test_materialized_mismatch_fails_loudly(self, mesh8):
        # params placed REPLICATED while the plan demands fsdp sharding
        x = jax.device_put(
            jnp.zeros((4096, 64)), NamedSharding(mesh8, P())
        )
        plan = ShardingPlan.fsdp(mesh8)
        with pytest.raises(PlanError, match="sharding_mismatch"):
            plan.validate({"w": x})

    def test_materialized_conforming_passes(self, mesh8):
        plan = ShardingPlan.fsdp(mesh8)
        x = jax.device_put(
            jnp.zeros((4096, 64)),
            NamedSharding(mesh8, plan.spec_for("w", (4096, 64))),
        )
        report = plan.validate({"w": x})
        assert report["flags"] == []


class TestServeEnginePlan:
    def test_plan_drives_params_and_kv_pool(self):
        from torchdistx_tpu.models import LlamaConfig
        from torchdistx_tpu.serve.engine import ServeEngine

        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("tp",))
        cfg = LlamaConfig(
            vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
            max_seq_len=64,
        )
        tdx.manual_seed(0)
        model = Llama(cfg)
        eng = ServeEngine(model, num_slots=2, max_len=32, mesh=mesh)
        # default plan is llama_tp_plan; params and the KV pool both
        # follow it — the kv_cache pseudo-path rule IS the pool layout
        assert isinstance(eng.plan, ShardingPlan)
        assert eng.params["blocks.0.attn.wq.weight"].sharding.spec == P(
            "tp", None
        )
        assert eng._kv_sharding.spec == eng.plan.maybe_spec_for(
            "kv_cache", ()
        )
        h = eng.submit([1, 2, 3], max_new_tokens=4)
        while not h.done():
            eng.step()
        assert len(h.result().tokens) == 4

    def test_tp_rule_is_a_deprecation_shim(self):
        import warnings

        from torchdistx_tpu.models import LlamaConfig
        from torchdistx_tpu.parallel.tp import llama_tp_rule
        from torchdistx_tpu.serve.engine import ServeEngine

        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("tp",))
        cfg = LlamaConfig(
            vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
            max_seq_len=64,
        )
        tdx.manual_seed(0)
        model = Llama(cfg)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            eng = ServeEngine(
                model, num_slots=2, max_len=32, mesh=mesh,
                tp_rule=llama_tp_rule(mesh),
            )
        assert any(
            issubclass(x.category, DeprecationWarning) for x in w
        )
        assert eng.plan is None  # a bare rule cannot be lifted to a plan
        with pytest.raises(ValueError, match="not both"):
            ServeEngine(
                model, num_slots=2, max_len=32, mesh=mesh,
                plan=llama_tp_plan(mesh), tp_rule=llama_tp_rule(mesh),
            )
        with pytest.raises(ValueError, match="plan requires mesh"):
            ServeEngine(
                model, num_slots=2, max_len=32, plan=llama_tp_plan(mesh)
            )


class TestReshardToPlan:
    def test_transition_prices_then_books_identically(self, mesh8):
        from torchdistx_tpu.parallel import (
            plan_transition_wire_bytes,
            reshard_to_plan,
        )

        src = ShardingPlan.fsdp(mesh8)
        _, params = _llama_params(0, src.as_rule())
        tx = optax.sgd(1e-1, momentum=0.9)
        state = jax.jit(
            tx.init,
            out_shardings=src.optimizer_state_shardings(
                jax.eval_shape(tx.init, params), params
            ),
        )(params)

        target = ShardingPlan.replicated(mesh8)
        expected = plan_transition_wire_bytes(
            params, target, optimizer_state=state
        )
        assert expected > 0  # unsharding moves (g-1)/g of sharded bytes
        with comm_audit() as prof:
            new_params, new_state = reshard_to_plan(
                params, target, optimizer_state=state
            )
        assert int(round(prof.wire_bytes("all_gather"))) == expected
        for v in new_params.values():
            assert v.sharding.spec == P()
        for l in jax.tree_util.tree_leaves(new_state):
            if isinstance(l, jax.Array):
                assert l.sharding.spec == P()
