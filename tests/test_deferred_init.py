"""Deferred-init semantics.  Behavioral spec: reference
tests/python/test_deferred_init.py — materialize is a no-op on real arrays,
identity/aliasing across materialization, is_deferred lifecycle across
partial materialization — plus this framework's sharded materialization."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu import nn, ops


class MLP(nn.Module):
    def __init__(self, din=16, dh=32, dout=8):
        super().__init__()
        self.fc1 = nn.Linear(din, dh)
        self.fc2 = nn.Linear(dh, dout)
        self.norm = nn.LayerNorm(dh)

    def forward(self, x):
        return self.fc2(self.norm(nn.functional.relu(self.fc1(x))))


def test_materialize_noop_on_real():
    # reference test_deferred_init.py:21-26
    x = jnp.ones((3, 3))
    assert tdx.materialize_tensor(x) is x


def test_deferred_module_has_fake_params():
    m = tdx.deferred_init(MLP)
    assert tdx.is_deferred(m)
    for _, p in m.named_parameters():
        assert tdx.is_fake(p)
        assert tdx.can_materialize(p)


def test_materialize_matches_eager_init():
    tdx.manual_seed(42)
    m = tdx.deferred_init(MLP)
    tdx.materialize_module(m)
    tdx.manual_seed(42)
    m2 = MLP()
    for (k1, p1), (k2, p2) in zip(m.named_parameters(), m2.named_parameters()):
        assert k1 == k2
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2))


def test_terminal_op_inside_deferred_context():
    # A terminal op (float()) on a deferred fake *inside* the still-active
    # deferred_init() forces an eager replay while the jnp interception
    # layer is installed and the mode is on; replay must suspend the mode
    # so recorded creation closures execute for real instead of re-faking
    # (the reference's NoDeferredInit guard around replay,
    # deferred_init.cc:769).  Regression: advisor round-2 medium finding.
    def build():
        w = ops.zeros((4,))
        s = float(jnp.sum(w))  # terminal: materializes w mid-context
        t = ops.ones((2,))  # recording must still work afterwards
        return {"w": w, "s": s, "t": t}

    m = tdx.deferred_init(build)
    assert m["s"] == 0.0
    w = tdx.materialize_tensor(m["w"])
    np.testing.assert_array_equal(np.asarray(w), np.zeros((4,)))
    t = tdx.materialize_tensor(m["t"])
    np.testing.assert_array_equal(np.asarray(t), np.ones((2,)))


def test_identity_same_fake_same_array():
    # reference test_deferred_init.py:29-45
    m = tdx.deferred_init(nn.Linear, 4, 4)
    w = m._parameters["weight"]
    a = tdx.materialize_tensor(w)
    b = tdx.materialize_tensor(w)
    assert a is b


def test_shared_parameter_aliasing():
    # param2 = param1 sharing (reference test_deferred_init.py:29-45)
    class Tied(nn.Module):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(10, 6)
            self.register_parameter("head", self.emb._parameters["weight"])

    t = tdx.deferred_init(Tied)
    assert t._parameters["head"] is t.emb._parameters["weight"]
    tdx.materialize_module(t)
    assert t._parameters["head"] is t.emb._parameters["weight"]
    assert isinstance(t._parameters["head"], jax.Array)


def test_is_deferred_lifecycle_partial_materialization():
    # reference test_deferred_init.py:47-75
    m = tdx.deferred_init(MLP)
    assert tdx.is_deferred(m)
    tdx.materialize_module(m.fc1)
    assert not tdx.is_deferred(m.fc1)
    assert tdx.is_deferred(m)  # fc2/norm still fake
    tdx.materialize_module(m)
    assert not tdx.is_deferred(m)


def test_forward_after_materialize():
    m = tdx.deferred_init(MLP)
    tdx.materialize_module(m)
    y = m(jnp.ones((2, 16)))
    assert y.shape == (2, 8)
    assert isinstance(y, jax.Array)


def test_buffers_only():
    class WithBuf(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)
            self.register_buffer("scale", ops.ones((4,)))

    m = tdx.deferred_init(WithBuf)
    tdx.materialize_module(m, buffers_only=True)
    assert isinstance(m._buffers["scale"], jax.Array)
    assert tdx.is_fake(m.fc._parameters["weight"])


def test_check_fn_selective():
    m = tdx.deferred_init(MLP)
    tdx.materialize_module(m, check_fn=lambda mod: not isinstance(mod, nn.LayerNorm))
    assert tdx.is_fake(m.norm._parameters["weight"])
    assert isinstance(m.fc1._parameters["weight"], jax.Array)


def test_dependent_ops_replay():
    # an op chain on params is recorded and replays correctly
    def build():
        lin = nn.Linear(4, 4, bias=False)
        w2 = lin._parameters["weight"] * 2.0 + 1.0
        lin.register_parameter("wx2", w2)
        return lin

    tdx.manual_seed(7)
    m = tdx.deferred_init(build)
    tdx.materialize_module(m)
    np.testing.assert_allclose(
        np.asarray(m._parameters["wx2"]),
        np.asarray(m._parameters["weight"]) * 2.0 + 1.0,
        rtol=1e-6,
    )


def test_mixing_sessions_rejected():
    m1 = tdx.deferred_init(nn.Linear, 4, 4)
    w1 = m1._parameters["weight"]

    def build():
        lin = nn.Linear(4, 4)
        lin.register_parameter("stolen", w1 + 0.0)
        return lin

    with pytest.raises(RuntimeError, match="different deferred-init session"):
        tdx.deferred_init(build)


def test_nested_deferred_rejected():
    with pytest.raises(RuntimeError, match="nested"):
        tdx.deferred_init(lambda: tdx.deferred_init(nn.Linear, 2, 2))


def test_sharded_materialization(mesh8):
    tdx.manual_seed(3)
    m = tdx.deferred_init(nn.Linear, 64, 32)

    def rule(path, fake):
        if fake.ndim >= 1 and fake.shape[0] % 8 == 0:
            return NamedSharding(mesh8, P("fsdp"))
        return None

    tdx.materialize_module(m, sharding_rule=rule)
    assert len(m._parameters["weight"].sharding.device_set) == 8
    tdx.manual_seed(3)
    m2 = nn.Linear(64, 32)
    np.testing.assert_allclose(
        np.asarray(m._parameters["weight"]), np.asarray(m2._parameters["weight"])
    )
    np.testing.assert_allclose(
        np.asarray(m._parameters["bias"]), np.asarray(m2._parameters["bias"])
    )


def test_graph_gc_releases_replay_caches():
    tdx.manual_seed(0)
    m = tdx.deferred_init(MLP)
    session = m.fc1._parameters["weight"]._session
    tdx.materialize_module(m)
    # after full materialization every node is materialized; caches for
    # intermediate nodes (the init ops feeding each param) must be dropped
    g = session.graph
    assert g.num_materialized() == g.num_nodes()
    # entries remaining in the cache correspond only to nodes still pinned
    # by... nothing: the module now holds real arrays, fakes are gone
    import gc

    gc.collect()
    assert g.num_released() == g.num_nodes()
    assert len(session.cache) == 0
    assert len(session.closures) == 0


def test_double_materialize_is_stable_noop():
    """Deliberate deviation from the reference, which raises on a second
    materialize_module (reference deferred_init.py:110-113): here
    materialization is identity-preserving, so a second call returns the
    very same jax.Array objects (documented in materialize_module)."""
    m = tdx.deferred_init(lambda: nn.Linear(4, 4))
    tdx.materialize_module(m)
    first = dict(m.named_parameters())
    tdx.materialize_module(m)  # no error, no change
    second = dict(m.named_parameters())
    assert all(first[k] is second[k] for k in first)


class TestRecordTimeSafety:
    """Mutation guards + captured execution context (reference
    deferred_init.cc:205-215,227-254,464-496,640-667)."""

    def test_small_numpy_arg_copied_at_record(self):
        # small arrays are deep-copied: post-record mutation cannot change
        # materialization, which stays bit-identical to eager init
        src = np.arange(6, dtype=np.float32)
        fake = tdx.deferred_init(lambda: ops.asarray(src) * 2.0)
        src[:] = -1.0  # mutate AFTER record
        out = np.asarray(tdx.materialize_tensor(fake))
        np.testing.assert_array_equal(out, np.arange(6, dtype=np.float32) * 2)

    def test_large_numpy_arg_mutation_raises(self):
        # large arrays are fingerprinted, not copied; mutation -> loud error
        # (the version-counter analog)
        src = np.ones((600, 600), dtype=np.float32)  # 1.44 MB > threshold
        fake = tdx.deferred_init(lambda: ops.asarray(src) + 1.0)
        src[123, 456] = 7.0
        with pytest.raises(RuntimeError, match="mutated before"):
            tdx.materialize_tensor(fake)

    def test_large_numpy_arg_unmutated_ok(self):
        src = np.full((600, 600), 3.0, dtype=np.float32)
        fake = tdx.deferred_init(lambda: ops.asarray(src) + 1.0)
        out = np.asarray(tdx.materialize_tensor(fake))
        assert (out == 4.0).all()

    def test_replay_reinstates_recorded_config(self):
        # the captured-context analog of the reference's ThreadLocalState
        # replay guard: the closure must execute under the jax config that
        # was ambient at record time, not at materialize time
        seen = []

        def probing_zeros():
            seen.append(jax.config.jax_default_matmul_precision)
            return jnp.zeros((2,))

        with jax.default_matmul_precision("float32"):
            fake = tdx.deferred_init(lambda: ops.apply_op(probing_zeros))
        assert seen[-1] == "float32"  # record-time trace
        seen.clear()
        assert jax.config.jax_default_matmul_precision != "float32"
        tdx.materialize_tensor(fake)
        assert seen[-1] == "float32"  # replay reinstated the context
        # and ambient config is restored afterwards
        assert jax.config.jax_default_matmul_precision != "float32"

    def test_replay_matches_eager_under_x64_context(self):
        def build():
            return ops.arange(3, dtype=jnp.float64) * 1e-9 + 1.0

        jax.config.update("jax_enable_x64", True)
        try:
            eager = np.asarray(build())  # real f64 values
            fake = tdx.deferred_init(build)
        finally:
            jax.config.update("jax_enable_x64", False)
        # materialize OUTSIDE the x64 context: captured config must win
        out = tdx.materialize_tensor(fake)
        assert out.dtype == jnp.float64
        np.testing.assert_array_equal(np.asarray(out), eager)


class TestChunkedReplay:
    """replay_mode='chunked': jitted chunk execution must match eager
    replay up to XLA fusion reassociation (~1 ulp — bit-identity is an
    eager-mode guarantee only), and structurally repeated layers must
    share compiled chunks."""

    def _materialize(self, mode, chunk_size=48, n_layers=None):
        from torchdistx_tpu._graph import RecordingSession

        old_mode, old_cs = RecordingSession.replay_mode, RecordingSession.chunk_size
        RecordingSession.replay_mode = mode
        RecordingSession.chunk_size = chunk_size
        try:
            from torchdistx_tpu.models import Llama

            kw = {"n_layers": n_layers} if n_layers else {}
            tdx.manual_seed(42)
            m = tdx.deferred_init(Llama.from_name, "tiny", **kw)
            session = next(iter(
                p for _, p in m.named_parameters()
            ))._session
            tdx.materialize_module(m)
            params = {k: np.asarray(v) for k, v in m.named_parameters()}
            return params, session
        finally:
            RecordingSession.replay_mode = old_mode
            RecordingSession.chunk_size = old_cs

    def test_chunked_matches_eager(self):
        eager, _ = self._materialize("eager")
        chunked, session = self._materialize("chunked", chunk_size=16)
        assert eager.keys() == chunked.keys()
        for k in eager:
            np.testing.assert_allclose(
                eager[k], chunked[k], rtol=2e-6, atol=1e-8, err_msg=k
            )

    def test_period_aligned_chunks_share_compiles(self):
        # 6 identical layers: period-aligned chunking must give far fewer
        # unique compiled chunks than dispatched chunks
        _, session = self._materialize("chunked", chunk_size=8, n_layers=6)
        assert session.chunk_dispatches > 0
        assert session.chunk_compiles < session.chunk_dispatches / 2, (
            session.chunk_compiles,
            session.chunk_dispatches,
        )
        # executors are dropped once the graph is fully materialized
        assert session._chunk_cache == {}

    def test_auto_mode_decisions(self):
        # auto compares estimated compile counts (distinct closure sigs vs
        # weighted distinct chunk sigs): conv graphs chunk, transformer
        # graphs stay eager, and off-accelerator everything stays eager
        from torchdistx_tpu.models import Llama
        from torchdistx_tpu.models.resnet import resnet50

        tdx.manual_seed(0)
        rn = tdx.deferred_init(resnet50)
        s_rn = next(p for _, p in rn.named_parameters())._session
        nids = sorted(s_rn.closures.keys())
        assert s_rn._choose_replay_mode(nids, platform="tpu") == "chunked"
        assert s_rn._choose_replay_mode(nids, platform="cpu") == "eager"

        tdx.manual_seed(0)
        ll = tdx.deferred_init(Llama.from_name, "tiny")
        s_ll = next(p for _, p in ll.named_parameters())._session
        nids = sorted(s_ll.closures.keys())
        assert s_ll._choose_replay_mode(nids, platform="tpu") == "eager"
        assert s_ll._choose_replay_mode(nids, platform="cpu") == "eager"

    def test_auto_mode_materializes_bit_identical_on_cpu(self):
        # auto resolves to eager on CPU: bit-identity must hold end-to-end
        eager, _ = self._materialize("eager")
        auto, _ = self._materialize("auto")
        for k in eager:
            np.testing.assert_array_equal(eager[k], auto[k], err_msg=k)

    def test_unknown_replay_mode_raises(self):
        with pytest.raises(ValueError, match="replay_mode"):
            self._materialize("bogus")

    def test_chunk_bounds_cover_everything(self):
        from torchdistx_tpu._graph import _chunk_bounds

        def check(names, cs):
            bounds = _chunk_bounds(names, cs)
            covered = [i for a, b in bounds for i in range(a, b)]
            assert covered == list(range(len(names))), (bounds, len(names))
            assert all(b > a for a, b in bounds)

        # review repro: prologue (3) not a multiple of chunk_size (8),
        # period 10 — ops [3, 8) must not be skipped
        names = ["emb"] * 3 + ["a", "b", "c", "d", "e", "f", "g", "h", "i", "j"] * 6 + ["norm"]
        check(names, 8)
        # short period (2) smaller than chunk_size: grouped chunks
        names2 = ["w", "b"] * 40
        check(names2, 16)
        bounds2 = _chunk_bounds(names2, 16)
        assert max(b - a for a, b in bounds2) == 16  # grouping happened
        # no period at all
        check([f"op{i}" for i in range(37)], 8)
        # degenerate sizes
        check(["x"] * 5, 8)
        check([], 8)

    def test_chunked_sharded_targets(self, mesh8):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from torchdistx_tpu._graph import RecordingSession

        old = RecordingSession.replay_mode
        RecordingSession.replay_mode = "chunked"
        try:
            tdx.manual_seed(3)
            m = tdx.deferred_init(MLP)
            tdx.materialize_module(
                m,
                sharding_rule=lambda path, fake: NamedSharding(mesh8, P())
                if fake.ndim < 2
                else NamedSharding(mesh8, P("fsdp")),
            )
            w = dict(m.named_parameters())["fc1.weight"]
            assert len(w.sharding.device_set) == 8
        finally:
            RecordingSession.replay_mode = old
        # same seed, eager, single device: same values up to the chunked
        # mode's ~1-ulp fusion tolerance
        tdx.manual_seed(3)
        m2 = tdx.deferred_init(MLP)
        tdx.materialize_module(m2)
        np.testing.assert_allclose(
            np.asarray(w),
            np.asarray(dict(m2.named_parameters())["fc1.weight"]),
            rtol=2e-6,
            atol=1e-8,
        )

    def test_chunked_partial_then_rest(self):
        from torchdistx_tpu._graph import RecordingSession

        old = RecordingSession.replay_mode
        RecordingSession.replay_mode = "chunked"
        try:
            tdx.manual_seed(4)
            m = tdx.deferred_init(MLP)
            # materialize one tensor first (partial), then the module
            w = tdx.materialize_tensor(dict(m.named_parameters())["fc2.weight"])
            tdx.materialize_module(m)
            assert dict(m.named_parameters())["fc2.weight"] is w
        finally:
            RecordingSession.replay_mode = old

    def test_signature_distinguishes_defaults_and_bound_methods(self):
        from torchdistx_tpu._graph import _callable_sig

        f1 = eval("lambda x, scale=1.0: x * scale")
        f2 = eval("lambda x, scale=2.0: x * scale")
        assert _callable_sig(f1) != _callable_sig(f2)

        class Cfg:
            def __init__(self, s):
                self.s = s

            def init(self, x):
                return x * self.s

        a, b = Cfg(1.0), Cfg(2.0)
        assert _callable_sig(a.init) != _callable_sig(b.init)
        assert _callable_sig(a.init) == _callable_sig(a.init)

    def test_unknown_replay_mode_rejected(self):
        from torchdistx_tpu._graph import RecordingSession

        old = RecordingSession.replay_mode
        RecordingSession.replay_mode = "chunkd"  # typo'd mode must not
        try:                                      # silently run eager
            m = tdx.deferred_init(lambda: nn.Linear(2, 2))
            with pytest.raises(ValueError, match="unknown replay_mode"):
                tdx.materialize_module(m)
        finally:
            RecordingSession.replay_mode = old
