"""Serve-side elasticity (ISSUE 12): ``drain()`` + ``migrate_to()``.

The pinned invariants, on the 8-device CPU mesh:

- **Drain is a named refusal, not a silent stall**: a draining engine
  raises on ``submit()`` with the reason in the message, and the queued
  FCFS head gets a ``("gated", {"why": "draining"})`` lifecycle event.
  The drain gate runs BEFORE the hbm/page gates, so draining reserves
  nothing a migration would have to unwind.
- **Zero drops, bit-identical streams**: migrating a live engine —
  suspended mid-stream slots WITH their KV state, plus the whole queue
  — onto a differently shaped engine (tp=2 -> tp=1, different slot
  count) completes every request with greedy token streams
  BIT-identical to an undrained run on the source shape.  Outstanding
  ``RequestHandle``s stay valid (requests move rid-intact).
- **Exact migration wire accounting**: the KV handoff books ring
  all-gathers per the ``parallel/reshard.py`` closed form — tp=2
  head-sharded cache to tp=1 replicated is gather group ``g = 2``,
  wire = ``S/2`` per moved row/page per layer per k/v array; a
  same-shape migration books ZERO.  ``migrate_to``'s summary, the comm
  audit, and the ``migration_wire_bytes`` counter all agree.
- **Atomic validation**: shape/capacity mismatches fail BEFORE any
  state moves — both engines are untouched afterwards.
"""

import numpy as np
import pytest
from jax.sharding import Mesh

import jax
import torchdistx_tpu as tdx
from torchdistx_tpu.models import Llama
from torchdistx_tpu.obs.comm import CommProfile, comm_audit
from torchdistx_tpu.serve import ServeEngine


def _llama():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 256, (n,)).astype(np.int32) for n in lengths]


def _tp_mesh(tp):
    return Mesh(np.asarray(jax.devices()[:tp]), ("tp",))


def _engine(tp, slots, paged=False, **kw):
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("decode_chunk", 2)
    if paged:
        kw.setdefault("page_size", 8)
        kw.setdefault("num_pages", 32)
    if tp > 1:
        kw["mesh"] = _tp_mesh(tp)
    return ServeEngine(_llama(), num_slots=slots, **kw)


def _kv_unit_bytes(engine, paged):
    """Bytes of one slot row (slab) or one page (paged) of one k/v
    array — dims [1:] of the cache geometry."""
    arr = engine.cache.kv[0][0]
    return int(np.prod(arr.shape[1:])) * np.dtype(arr.dtype).itemsize


class TestDrain:
    def test_submit_refused_with_named_reason(self):
        eng = _engine(1, 2)
        eng.drain()
        with pytest.raises(RuntimeError, match="draining"):
            eng.submit(_prompts(0, (5,))[0], max_new_tokens=2)
        assert eng.metrics.counters["submits_rejected_draining"] == 1

    def test_queued_head_gets_draining_gate_event(self):
        eng = _engine(1, 1)
        p = _prompts(1, (5, 6))
        h0 = eng.submit(p[0], max_new_tokens=4)
        h1 = eng.submit(p[1], max_new_tokens=4)
        eng.step()  # admits p0; p1 queued behind the single slot
        left = eng.drain()
        assert left == 2
        head = eng.scheduler.queued[0]
        gated = [e for e in head.events if e[0] == "gated"]
        assert gated and gated[-1][2]["why"] == "draining"
        # steps during drain admit nothing but keep decoding
        eng.step()
        assert eng.scheduler.queue_depth == 1
        assert gated[-1][2]["why"] == "draining"
        del h0, h1

    def test_drain_wins_over_page_gate_and_reserves_nothing(self):
        # pool sized so the queued head is PAGE-gated pre-drain; after
        # drain() the named cause flips to "draining" and no pages are
        # reserved by later steps
        eng = _engine(1, 2, paged=True, num_pages=5)  # 4 allocatable
        p = _prompts(2, (8, 8))
        eng.submit(p[0], max_new_tokens=8)  # 2 pages
        eng.submit(p[1], max_new_tokens=8)
        eng.step()  # admits p0 (2 pages); p1 blocked: needs 2, 2 free?
        # force the page squeeze regardless of rounding: fill the pool
        in_use_before = eng.pool.in_use
        eng.drain()
        eng.step()
        assert eng.pool.in_use == in_use_before  # drain reserved nothing
        head = eng.scheduler.queued
        if head:  # p1 still queued: its latest gate cause is the drain
            gated = [e for e in head[0].events if e[0] == "gated"]
            assert gated[-1][2]["why"] == "draining"

    def test_drain_complete_finishes_running_keeps_queued(self):
        eng = _engine(1, 1)
        p = _prompts(3, (5, 6))
        h0 = eng.submit(p[0], max_new_tokens=3)
        h1 = eng.submit(p[1], max_new_tokens=3)
        eng.step()
        left = eng.drain(complete=True)
        assert left == 1  # the queued request survives, un-admitted
        assert h0.done() and not h1.done()
        assert h0.result().finish_reason == "length"


class TestMigrate:
    def _run_elastic(self, paged, tp_from=2, tp_to=1, slots_from=3,
                     slots_to=4, steps_before=2):
        """Shared scenario: reference run on the source shape, then an
        elastic run suspended mid-stream and migrated.  Returns
        (handles, ref_tokens, summary, prof, src, dst)."""
        prompts = _prompts(7, (6, 9, 5, 11))
        mnt = [8, 10, 12, 6]
        ref = _engine(tp_from, slots_from, paged).run(
            [dict(prompt=p, max_new_tokens=m)
             for p, m in zip(prompts, mnt)]
        )
        ref_tokens = [r.tokens for r in ref]

        src = _engine(tp_from, slots_from, paged)
        dst = _engine(tp_to, slots_to, paged)
        handles = [
            src.submit(p, max_new_tokens=m)
            for p, m in zip(prompts, mnt)
        ]
        for _ in range(steps_before):
            src.step()
        src.drain()
        prof = CommProfile()
        with comm_audit(prof):
            summary = src.migrate_to(dst)
        while dst.step():
            pass
        return handles, ref_tokens, summary, prof, src, dst

    def test_tp2_to_tp1_bit_identical_zero_drops(self):
        """The acceptance pin: tp=2 -> tp=1 with a different slot
        count, in-flight requests suspended mid-stream, every stream
        completes bit-identically, nothing dropped, wire bytes exact."""
        handles, ref_tokens, summary, prof, src, dst = self._run_elastic(
            paged=False
        )
        assert summary["migrated_running"] == 3
        assert summary["migrated_queued"] == 1
        assert (summary["tp_from"], summary["tp_to"]) == (2, 1)
        assert (summary["slots_from"], summary["slots_to"]) == (3, 4)
        # zero drops: every handle resolves, streams bit-identical
        for h, ref in zip(handles, ref_tokens):
            assert h.done()
            np.testing.assert_array_equal(h.result().tokens, ref)
        assert all(
            h.result().finish_reason == "length" for h in handles
        )
        # closed form: head axis tp=2 -> replicated is g=2; one gather
        # per migrated row per layer per k/v array at unit/2 wire
        unit = _kv_unit_bytes(src, paged=False)
        n_layers = len(src.cache.kv)
        expect = 3 * n_layers * 2 * (unit // 2)
        assert summary["wire_bytes"] == expect
        assert int(prof.wire_bytes("all_gather", "tp")) == expect
        assert src.metrics.counters["migration_wire_bytes"] == expect
        assert src.metrics.counters["requests_migrated_out"] == 4
        assert dst.metrics.counters["requests_migrated_in"] == 4
        # the source is empty (and still refuses submissions)
        assert not src.scheduler.has_work()
        with pytest.raises(RuntimeError, match="draining"):
            src.submit(np.ones(4, np.int32), max_new_tokens=1)

    def test_same_shape_migration_books_zero_wire(self):
        handles, ref_tokens, summary, prof, _, _ = self._run_elastic(
            paged=False, tp_from=1, tp_to=1, slots_from=2, slots_to=3
        )
        assert summary["wire_bytes"] == 0 == int(prof.wire_bytes())
        assert summary["collectives"] == 0
        for h, ref in zip(handles, ref_tokens):
            np.testing.assert_array_equal(h.result().tokens, ref)

    @pytest.mark.slow
    @pytest.mark.parametrize("paged", [False, True])
    @pytest.mark.parametrize(
        "tp_from,tp_to,slots_from,slots_to",
        [(2, 1, 3, 4), (1, 2, 3, 3), (2, 2, 2, 4)],
    )
    def test_migration_grid(self, paged, tp_from, tp_to, slots_from,
                            slots_to):
        """The -m slow grid: tp up/down/same x slab/paged x slot count
        up/down, all bit-identical with exact wire accounting."""
        handles, ref_tokens, summary, prof, src, _ = self._run_elastic(
            paged, tp_from, tp_to, slots_from, slots_to,
            steps_before=3,
        )
        for h, ref in zip(handles, ref_tokens):
            np.testing.assert_array_equal(h.result().tokens, ref)
        unit = _kv_unit_bytes(src, paged)
        n_layers = len(src.cache.kv)
        # the gather group is set by the SOURCE's head-axis split vs what
        # the target layout preserves: g = tp_from / gcd(tp_from, tp_to)
        g = max(1, tp_from // int(np.gcd(tp_from, tp_to)))
        n_units = (
            summary["pages_moved"] if paged else summary["migrated_running"]
        )
        expect = (
            n_units * n_layers * 2 * (unit * (g - 1) // g) if g > 1 else 0
        )
        assert summary["wire_bytes"] == expect
        assert int(prof.wire_bytes()) == expect

    def test_paged_migration_fast_pin(self):
        handles, ref_tokens, summary, prof, src, dst = self._run_elastic(
            paged=True
        )
        for h, ref in zip(handles, ref_tokens):
            np.testing.assert_array_equal(h.result().tokens, ref)
        # page chains were re-homed: target table rows point at freshly
        # allocated target pages, source pool fully released
        assert src.pool.in_use == 0
        unit = _kv_unit_bytes(src, paged=True)
        n_layers = len(src.cache.kv)
        assert summary["pages_moved"] > 0
        assert summary["wire_bytes"] == (
            summary["pages_moved"] * n_layers * 2 * (unit // 2)
        )
        assert int(prof.wire_bytes("all_gather", "tp")) == (
            summary["wire_bytes"]
        )


class TestMigrateValidation:
    def test_rejects_self_and_shape_mismatches(self):
        a = _engine(1, 2)
        with pytest.raises(ValueError, match="itself"):
            a.migrate_to(a)
        b_paged = _engine(1, 2, paged=True)
        with pytest.raises(RuntimeError, match="slab and paged"):
            a.migrate_to(b_paged)
        c = _engine(1, 2, max_len=32)
        with pytest.raises(RuntimeError, match="max_len"):
            a.migrate_to(c)
        d = _engine(1, 2)
        d.drain()
        e = _engine(1, 2)
        with pytest.raises(RuntimeError, match="target is itself"):
            e.migrate_to(d)

    def test_capacity_validation_moves_nothing(self):
        prompts = _prompts(9, (5, 6, 7))
        src = _engine(1, 3)
        dst = _engine(1, 1)  # too small for 3 suspended slots
        handles = [
            src.submit(p, max_new_tokens=8) for p in prompts
        ]
        src.step()
        src.drain()
        with pytest.raises(RuntimeError, match="free"):
            src.migrate_to(dst)
        # atomic: everything still on the source, nothing on the target
        assert len(src.scheduler.running) == 3
        assert not dst.scheduler.has_work()
        assert dst.scheduler.free_slot_count == 1
        del handles
