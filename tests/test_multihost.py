"""Tests for parallel.multihost — the init_process_group analog.

Two layers: mocked argument-plumbing contract tests (explicit args pass
through, the reference ecosystem's MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE
trio is honored, single-host auto-detection passes nothing), plus a REAL
two-process integration test (VERDICT r3 item 8): a localhost coordinator,
``init_multihost`` in each process, and one cross-process psum over the
resulting 2-device global mesh — the actual jax.distributed handshake and
a Gloo CPU collective, un-mocked.
"""

import os
import socket
import subprocess
import sys
import textwrap
from unittest import mock

import jax
import pytest

from torchdistx_tpu.parallel import multihost


def _init_with(monkeypatch, env, **kwargs):
    for k in ("MASTER_ADDR", "MASTER_PORT", "RANK", "WORLD_SIZE"):
        monkeypatch.delenv(k, raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    with mock.patch.object(jax.distributed, "initialize") as init:
        multihost.init_multihost(**kwargs)
    assert init.call_count == 1
    return init.call_args.kwargs


class TestInitMultihost:
    def test_autodetect_passes_nothing(self, monkeypatch):
        # TPU-pod path: jax.distributed.initialize() autodetects everything
        assert _init_with(monkeypatch, {}) == {}

    def test_explicit_args_pass_through(self, monkeypatch):
        got = _init_with(
            monkeypatch,
            {},
            coordinator_address="coord:1234",
            num_processes=4,
            process_id=2,
        )
        assert got == {
            "coordinator_address": "coord:1234",
            "num_processes": 4,
            "process_id": 2,
        }

    def test_torchrun_env_trio_honored(self, monkeypatch):
        # the reference ecosystem's MASTER_ADDR/RANK/WORLD_SIZE convention
        got = _init_with(
            monkeypatch,
            {
                "MASTER_ADDR": "10.0.0.1",
                "MASTER_PORT": "29500",
                "WORLD_SIZE": "16",
                "RANK": "3",
            },
        )
        assert got == {
            "coordinator_address": "10.0.0.1:29500",
            "num_processes": 16,
            "process_id": 3,
        }

    def test_env_port_defaults(self, monkeypatch):
        got = _init_with(monkeypatch, {"MASTER_ADDR": "h"})
        assert got["coordinator_address"] == "h:8476"

    def test_explicit_beats_env(self, monkeypatch):
        got = _init_with(
            monkeypatch,
            {"MASTER_ADDR": "env-host", "WORLD_SIZE": "2", "RANK": "1"},
            coordinator_address="explicit:1",
        )
        assert got["coordinator_address"] == "explicit:1"
        # env still fills the fields not given explicitly
        assert got["num_processes"] == 2
        assert got["process_id"] == 1


_WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, port = int(sys.argv[1]), sys.argv[2]
    from torchdistx_tpu.parallel import multihost
    multihost.init_multihost(
        coordinator_address=f"localhost:{port}",
        num_processes=2,
        process_id=pid,
    )
    assert multihost.is_multihost()
    assert multihost.process_count() == 2
    assert multihost.process_index() == pid
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = jax.devices()  # global view: one CPU device per process
    assert len(devs) == 2, devs
    mesh = Mesh(np.array(devs), ("dp",))
    try:
        arr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("dp")), np.full((1,), float(pid + 1))
        )
        out = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(arr)
        val = float(np.asarray(out.addressable_data(0)))
    except RuntimeError as e:
        # some jaxlib CPU backends lack cross-process collectives
        # ("Multiprocess computations aren't implemented on the CPU
        # backend"); the distributed handshake above still ran un-mocked
        if "Multiprocess computations" in str(e):
            print(f"SKIPCOLLECTIVE {pid} {e}", flush=True)
            sys.exit(0)
        raise
    assert val == 3.0, val  # 1.0 (proc 0) + 2.0 (proc 1), psum'd
    print(f"OK {pid} {val}", flush=True)
    """
)


class TestRealTwoProcess:
    def test_two_process_psum_via_init_multihost(self, tmp_path):
        # The handshake itself, un-mocked: spawn two fresh processes with a
        # localhost coordinator; each runs init_multihost and the pair
        # executes one cross-process reduction.
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        script = tmp_path / "worker.py"
        script.write_text(_WORKER)
        env = dict(os.environ)
        # the workers manage their own platform/device-count config; the
        # test runner's 8-virtual-device forcing must not leak in
        env.pop("XLA_FLAGS", None)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (os.path.dirname(os.path.dirname(__file__)),
                        env.get("PYTHONPATH")) if p
        )
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(i), str(port)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
            for i in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=120)
                outs.append(out)
        finally:
            for p in procs:
                p.kill()
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {i} failed:\n{out}"
        if any("SKIPCOLLECTIVE" in out for out in outs):
            pytest.skip(
                "handshake verified (init_multihost + 2-device global "
                "mesh), but this jaxlib's CPU backend lacks "
                "cross-process collectives"
            )
        for i, out in enumerate(outs):
            assert f"OK {i} 3.0" in out, out


class TestQueries:
    def test_single_host_queries(self):
        # on this single-process test runner the queries must agree with jax
        assert multihost.is_multihost() is False
        assert multihost.process_index() == jax.process_index() == 0
        assert multihost.process_count() == jax.process_count() == 1
