"""Unit tests for parallel.multihost — the init_process_group analog.

No cluster exists here, so ``jax.distributed.initialize`` is mocked
(VERDICT r2 weak #7): the tests pin down the argument-plumbing contract —
explicit args pass through, the reference ecosystem's
MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE trio is honored, and single-host
auto-detection passes nothing.
"""

from unittest import mock

import jax

from torchdistx_tpu.parallel import multihost


def _init_with(monkeypatch, env, **kwargs):
    for k in ("MASTER_ADDR", "MASTER_PORT", "RANK", "WORLD_SIZE"):
        monkeypatch.delenv(k, raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    with mock.patch.object(jax.distributed, "initialize") as init:
        multihost.init_multihost(**kwargs)
    assert init.call_count == 1
    return init.call_args.kwargs


class TestInitMultihost:
    def test_autodetect_passes_nothing(self, monkeypatch):
        # TPU-pod path: jax.distributed.initialize() autodetects everything
        assert _init_with(monkeypatch, {}) == {}

    def test_explicit_args_pass_through(self, monkeypatch):
        got = _init_with(
            monkeypatch,
            {},
            coordinator_address="coord:1234",
            num_processes=4,
            process_id=2,
        )
        assert got == {
            "coordinator_address": "coord:1234",
            "num_processes": 4,
            "process_id": 2,
        }

    def test_torchrun_env_trio_honored(self, monkeypatch):
        # the reference ecosystem's MASTER_ADDR/RANK/WORLD_SIZE convention
        got = _init_with(
            monkeypatch,
            {
                "MASTER_ADDR": "10.0.0.1",
                "MASTER_PORT": "29500",
                "WORLD_SIZE": "16",
                "RANK": "3",
            },
        )
        assert got == {
            "coordinator_address": "10.0.0.1:29500",
            "num_processes": 16,
            "process_id": 3,
        }

    def test_env_port_defaults(self, monkeypatch):
        got = _init_with(monkeypatch, {"MASTER_ADDR": "h"})
        assert got["coordinator_address"] == "h:8476"

    def test_explicit_beats_env(self, monkeypatch):
        got = _init_with(
            monkeypatch,
            {"MASTER_ADDR": "env-host", "WORLD_SIZE": "2", "RANK": "1"},
            coordinator_address="explicit:1",
        )
        assert got["coordinator_address"] == "explicit:1"
        # env still fills the fields not given explicitly
        assert got["num_processes"] == 2
        assert got["process_id"] == 1


class TestQueries:
    def test_single_host_queries(self):
        # on this single-process test runner the queries must agree with jax
        assert multihost.is_multihost() is False
        assert multihost.process_index() == jax.process_index() == 0
        assert multihost.process_count() == jax.process_count() == 1
