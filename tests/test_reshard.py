"""Elastic resharding (ISSUE 12): on-mesh pytree redistribution with
closed-form wire accounting, and the Trainer's device-loss recovery.

Pinned invariants:

- **Redistribution model** (parallel/reshard.py, arXiv:2112.01075): an
  8-way-sharded leaf unsharding to replicated books a ring all-gather of
  ``7S/8`` wire bytes; a same-layout move books zero; 8-way -> 4-way
  books ``S/2`` (gather group ``g = 2``).  The booked profile matches
  ``reshard_wire_bytes``'s closed form exactly.
- **Survivability** (``can_reshard_live``): replicated leaves survive
  any shrink; an 8-way-sharded leaf does NOT survive onto 4 devices —
  the checkpoint-bounce path is mandatory there.
- **Trainer elasticity**: an injected ``device_loss`` under
  ``on_failure="reshard"`` shrinks the mesh 8 -> 4 and continues with a
  loss stream and final parameters BIT-IDENTICAL to a fresh 4-device
  run transplanted from the recovery step — for BOTH the live path
  (a dp replica dies, survivors hold a full copy) and the
  checkpoint-bounce path (fsdp shards lived on the lost devices).
  Migration wire bytes land in the trainer's comm profile as the exact
  ring-model numbers, and the flight recorder shows
  ``reshard_start``/``reshard_done`` naming both mesh shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu import nn
from torchdistx_tpu.nn import functional_call
from torchdistx_tpu.obs.comm import CommProfile, comm_audit
from torchdistx_tpu.parallel import (
    ShardedTrainStep,
    can_reshard_live,
    create_mesh,
    optimizer_state_shardings,
    plan_reshard,
    reshard,
    reshard_via_checkpoint,
    reshard_wire_bytes,
)
from torchdistx_tpu.trainer import Trainer
from torchdistx_tpu.utils.failure import FailureDetector, StepFailure

F32 = 4


def _mesh(n, axis="fsdp"):
    return Mesh(np.asarray(jax.devices()[:n]), (axis,))


def _sharded(mesh, shape, spec):
    x = jnp.arange(int(np.prod(shape)), dtype=jnp.float32).reshape(shape)
    return jax.device_put(x, NamedSharding(mesh, spec))


class TestReshardPlan:
    def test_unshard_8_way_books_seven_eighths(self):
        m8 = _mesh(8)
        x = _sharded(m8, (64, 16), P("fsdp"))
        S = 64 * 16 * F32
        repl = NamedSharding(m8, P())
        plan = plan_reshard({"x": x}, repl)
        assert len(plan) == 1
        assert plan[0]["gather_group"] == 8
        assert plan[0]["wire_bytes"] == S * 7 // 8
        assert reshard_wire_bytes({"x": x}, repl) == S * 7 // 8

    def test_same_layout_books_zero(self):
        m8 = _mesh(8)
        x = _sharded(m8, (64, 16), P("fsdp"))
        assert plan_reshard({"x": x}, {"x": x.sharding}) == []
        # replicated source: every device already holds everything
        r = _sharded(m8, (64, 16), P())
        assert reshard_wire_bytes({"r": r}, NamedSharding(m8, P("fsdp"))) == 0

    def test_8_to_4_books_half(self):
        m8, m4 = _mesh(8), _mesh(4)
        x = _sharded(m8, (64, 16), P("fsdp"))
        S = 64 * 16 * F32
        tgt = NamedSharding(m4, P("fsdp"))
        plan = plan_reshard([x], [tgt])
        assert plan[0]["gather_group"] == 2  # gcd(8, 4) = 4 preserved
        assert plan[0]["wire_bytes"] == S // 2

    def test_reshard_books_into_audit_and_moves(self):
        m8, m4 = _mesh(8), _mesh(4)
        x = _sharded(m8, (64, 16), P("fsdp"))
        tgt = NamedSharding(m4, P("fsdp"))
        prof = CommProfile()
        with comm_audit(prof):
            out = reshard({"x": x}, {"x": tgt})
        assert out["x"].sharding == tgt
        np.testing.assert_array_equal(np.asarray(out["x"]), np.asarray(x))
        S = 64 * 16 * F32
        assert int(prof.wire_bytes("all_gather", "fsdp")) == S // 2
        assert int(prof.payload_bytes("all_gather", "fsdp")) == S
        assert prof.ops("all_gather") == 1

    def test_leaf_count_mismatch_raises(self):
        m8 = _mesh(8)
        x = _sharded(m8, (8, 8), P())
        with pytest.raises(ValueError, match="leaves"):
            plan_reshard({"a": x, "b": x}, {"a": x.sharding})

    def test_can_reshard_live(self):
        m8, m4 = _mesh(8), _mesh(4)
        sharded8 = _sharded(m8, (64, 16), P("fsdp"))
        repl8 = _sharded(m8, (64, 16), P())
        # 8-way shards: half of them ONLY exist on the lost devices
        assert not can_reshard_live({"w": sharded8}, m4)
        # replicated: any survivor holds a full copy
        assert can_reshard_live({"w": repl8}, m4)
        assert can_reshard_live({"w": sharded8}, m8)

    def test_bounce_books_broadcast(self, tmp_path):
        m8, m4 = _mesh(8), _mesh(4)
        x = _sharded(m8, (64, 16), P("fsdp"))
        tgt = NamedSharding(m4, P("fsdp"))
        prof = CommProfile()
        with comm_audit(prof):
            out = reshard_via_checkpoint(
                {"x": x}, str(tmp_path / "bounce"), {"x": tgt}
            )
        assert out["x"].sharding == tgt
        np.testing.assert_array_equal(np.asarray(out["x"]), np.asarray(x))
        S = 64 * 16 * F32
        # host-to-mesh fan-out: ring broadcast over the 4 target devices
        assert int(prof.wire_bytes("broadcast", "fsdp")) == S * 3 // 4


# -- Trainer elasticity ---------------------------------------------------


class MLP(nn.Module):
    def __init__(self, d=16, h=64):
        super().__init__()
        self.fc1 = nn.Linear(d, h)
        self.fc2 = nn.Linear(h, d)

    def forward(self, x):
        return self.fc2(jax.nn.relu(self.fc1(x)))


def _materialized_mlp():
    tdx.manual_seed(0)
    m = tdx.deferred_init(MLP)
    tdx.materialize_module(m)
    return m


def _step(model, mesh, **kw):
    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((functional_call(model, p, (x,)) - y) ** 2)

    return ShardedTrainStep(loss_fn, optax.adam(1e-2), mesh, **kw)


def _batches(n):
    rs = np.random.RandomState(0)
    return [
        (b, b)
        for b in (rs.randn(8, 16).astype(np.float32) for _ in range(n))
    ]


def _trainer(step, params, opt, tmp_path, logs, det=None, flight=None):
    return Trainer(
        step,
        params,
        opt,
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=100,  # never: reshard must move LIVE state
        log_every=1,
        log_fn=logs.append,
        failure_detector=det,
        on_failure="reshard",
        flight=flight,
    )


def _transplant_reference(model, mesh_small, params, opt, batches, tmp_path):
    """The acceptance oracle: place the recovery-step state onto a fresh
    small-mesh step and train it forward — the elastic run must match
    this bitwise."""
    from torchdistx_tpu.obs.flight import FlightRecorder

    step = _step(model, mesh_small, shard_axis="fsdp")
    p = jax.device_put(params, step.param_sharding(params))
    o = jax.device_put(
        opt, optimizer_state_shardings(opt, p, mesh_small)
    )
    rec = FlightRecorder(dump_dir=str(tmp_path))
    tr = Trainer(
        step, p, o,
        checkpoint_dir=str(tmp_path / "ref_ck"), checkpoint_every=100,
        log_every=1, log_fn=lambda m: None, flight=rec,
    )
    tr.fit(batches)
    return tr, rec


class TestTrainerElastic:
    def test_bounce_8_to_4_bit_consistent(self, tmp_path):
        """fsdp=8 shards die with the lost devices -> checkpoint bounce;
        the continued loss stream and final params match a fresh
        4-device run from the recovery step bitwise."""
        from torchdistx_tpu.obs.flight import FlightRecorder

        batches = _batches(10)
        mesh8 = create_mesh({"fsdp": 8})
        model = _materialized_mlp()
        step = _step(model, mesh8, shard_axis="fsdp")
        params = step.shard_params(dict(model.named_parameters()))
        # host snapshot of the init: the jitted step donates its param
        # buffers, so the oracle replay needs its own copies
        init_np = jax.tree_util.tree_map(np.asarray, params)
        opt = step.init_optimizer(params)
        det = FailureDetector()
        logs = []
        rec = FlightRecorder(dump_dir=str(tmp_path))
        tr = _trainer(step, params, opt, tmp_path, logs, det, flight=rec)
        tr.fit(batches[:5])
        det.inject_device_loss(4)
        tr.fit(batches[5:])

        fails = [m for m in logs if "failure" in m]
        assert fails and fails[0]["action"] == "resharded"
        assert fails[0]["failure"] == "device_loss"
        assert dict(tr.step.mesh.shape) == {"fsdp": 4}
        for leaf in jax.tree_util.tree_leaves(tr.params):
            assert len(leaf.sharding.device_set) == 4
        assert tr._t_reshard > 0.0

        # flight shows the migration with both mesh shapes
        events = [
            r for r in rec.records() if r["kind"].startswith("reshard")
        ]
        assert [e["kind"] for e in events] == [
            "reshard_start", "reshard_done",
        ]
        assert events[0]["mesh_from"] == {"fsdp": 8}
        assert events[0]["mesh_to"] == {"fsdp": 4}
        done = events[1]
        assert done["mode"] == "checkpoint"

        # exact ring-model wire bytes: one broadcast per leaf onto the
        # 4 surviving devices
        nbytes = sum(
            int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
            for t in (tr.params, tr.opt_state)
            for l in jax.tree_util.tree_leaves(t)
        )
        assert done["wire_bytes"] == nbytes * 3 // 4
        assert int(tr.comm_profile.wire_bytes("broadcast")) == (
            nbytes * 3 // 4
        )

        # bit-consistent continuation vs the transplant oracle: the
        # failing window's step RAN before the boundary check raised,
        # so recovery happens from the post-step-6 state — replay a
        # clean 8-mesh run to that step (deterministic: same init, same
        # batches), then transplant onto a fresh 4-device mesh
        rec_step = events[0]["step"]
        assert rec_step == 6
        ref8 = Trainer(
            step,
            step.shard_params(init_np),
            log_every=1, log_fn=lambda m: None,
        )
        ref8.fit(batches[:rec_step])
        mesh4 = _mesh(4)
        ref_tr, ref_rec = _transplant_reference(
            model, mesh4, ref8.params, ref8.opt_state,
            batches[rec_step:], tmp_path,
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(tr.params),
            jax.tree_util.tree_leaves(ref_tr.params),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the post-reshard LOSS STREAM matches bitwise too (flight step
        # records carry the unrounded loss; the oracle's first boundary
        # is consumed by its warmup-window reset, so compare the
        # overlapping tail)
        elastic_losses = [
            r["loss"] for r in rec.records()
            if r["kind"] == "step" and r["step"] > rec_step
        ]
        ref_losses = [
            r["loss"] for r in ref_rec.records() if r["kind"] == "step"
        ]
        assert len(ref_losses) >= 2
        assert elastic_losses[-len(ref_losses):] == ref_losses

    def test_live_dp_shrink_zero_wire_bit_consistent(self, tmp_path):
        """A dp replica dies but the surviving fsdp=4 group holds a full
        copy -> live redistribution, zero wire bytes, bit-consistent
        continuation."""
        batches = _batches(8)
        devs = np.asarray(jax.devices())
        mesh_big = Mesh(devs.reshape(2, 4), ("dp", "fsdp"))
        mesh_small = Mesh(devs[:4].reshape(1, 4), ("dp", "fsdp"))
        model = _materialized_mlp()
        step = _step(model, mesh_big, shard_axis="fsdp")
        params = step.shard_params(dict(model.named_parameters()))
        opt = step.init_optimizer(params)
        logs = []
        tr = _trainer(step, params, opt, tmp_path, logs)
        tr.fit(batches[:4])
        p4 = jax.tree_util.tree_map(np.asarray, tr.params)
        o4 = jax.tree_util.tree_map(np.asarray, tr.opt_state)

        prof = CommProfile()
        with comm_audit(prof):
            mode = tr.reshard(mesh=mesh_small)
        assert mode == "live"
        # fsdp layout preserved on the survivors: g == 1 everywhere
        assert prof.ops() == 0 and int(prof.wire_bytes()) == 0
        for leaf in jax.tree_util.tree_leaves(tr.params):
            assert len(leaf.sharding.device_set) == 4
        tr.fit(batches[4:])

        ref_tr, _ = _transplant_reference(
            model, mesh_small,
            jax.tree_util.tree_map(jnp.asarray, p4),
            jax.tree_util.tree_map(jnp.asarray, o4),
            batches[4:], tmp_path,
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(tr.params),
            jax.tree_util.tree_leaves(ref_tr.params),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_shrunk_mesh_shapes(self):
        devs = np.asarray(jax.devices())
        m = Mesh(devs.reshape(2, 4), ("dp", "fsdp"))
        small = Trainer._shrunk_mesh(m, 4)
        assert dict(small.shape) == {"dp": 1, "fsdp": 4}
        m1 = Mesh(devs, ("fsdp",))
        assert dict(Trainer._shrunk_mesh(m1, 4).shape) == {"fsdp": 4}
        with pytest.raises(StepFailure):
            Trainer._shrunk_mesh(m1, 3)  # 5 survivors divide nothing
