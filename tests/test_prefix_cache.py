"""Shared-prefix paged KV cache (serve/prefix_cache.py + the paged side
of serve/kv_cache.py and the engine integration).

The load-bearing invariants:

- **Allocator discipline**: pages are refcounted; the scratch page is
  never handed out; a page returns to the free list only when no table
  and no index entry references it.
- **Radix index semantics**: matches are full-page, page-aligned, and
  capped at ``len(prompt) - 1`` tokens (the last prompt token's logits
  must be computed); insertion adopts pages with the index's own
  refcount; eviction is LRU over leaves and never touches a page a
  running request references.
- **No KV leakage across page reuse**: a short request admitted into a
  retired long request's pages produces a stream bit-identical to a
  fresh engine's — the paged rewrite of the slab stale-row regression.
- **Admission gates on pages**: a pool smaller than the worst-case
  footprint defers requests (FCFS) instead of corrupting streams, and
  submit() rejects requests that could NEVER fit.

Engine-level bit-identity of paged-vs-slab streams across the
K x occupancy x prefix-mix grid lives in tests/test_serve.py.
"""

import numpy as np
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu.models import Llama
from torchdistx_tpu.serve import PagePool, RadixPrefixIndex, ServeEngine
from torchdistx_tpu.serve.prefix_cache import SCRATCH_PAGE


def _llama():
    tdx.manual_seed(0)
    return Llama.from_name("tiny", n_kv_heads=2, max_seq_len=64)


def _prompts(seed, lengths):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 256, (n,)).astype(np.int32) for n in lengths]


class TestPagePool:
    def test_alloc_lowest_first_and_scratch_reserved(self):
        pool = PagePool(6)
        assert pool.capacity == 5
        pages = pool.alloc(3)
        assert pages == [1, 2, 3]  # SCRATCH_PAGE = 0 never allocated
        assert SCRATCH_PAGE not in pages
        assert pool.in_use == 3 and pool.free_count == 2

    def test_refcount_lifecycle(self):
        pool = PagePool(4)
        (p,) = pool.alloc(1)
        pool.incref([p])
        assert pool.decref([p]) == 0  # one holder left
        assert pool.free_count == 2
        assert pool.decref([p]) == 1  # now free
        assert pool.free_count == 3
        with pytest.raises(RuntimeError, match="decref of free"):
            pool.decref([p])
        with pytest.raises(RuntimeError, match="incref of free"):
            pool.incref([p])

    def test_freed_pages_reallocate_lowest_first(self):
        pool = PagePool(5)
        a = pool.alloc(3)  # [1, 2, 3]
        pool.decref([a[1]])  # free page 2
        pool.decref([a[0]])  # free page 1
        assert pool.alloc(2) == [1, 2]

    def test_over_allocation_is_a_bug_not_backpressure(self):
        pool = PagePool(3)
        with pytest.raises(RuntimeError, match="over-allocated"):
            pool.alloc(3)

    def test_high_water(self):
        pool = PagePool(6)
        a = pool.alloc(4)
        pool.decref(a)
        pool.alloc(1)
        assert pool.high_water == 4

    def test_too_small(self):
        with pytest.raises(ValueError, match="num_pages"):
            PagePool(1)


class TestRadixPrefixIndex:
    def _toks(self, *vals):
        return np.asarray(vals, np.int32)

    def test_match_is_page_aligned_and_caps_at_last_token(self):
        pool, idx = PagePool(8), RadixPrefixIndex(page_size=4)
        pages = pool.alloc(2)
        idx.insert(self._toks(*range(8)), pages, pool)
        # full prompt == cached tokens: the LAST token must be computed,
        # so only the first page may be served from cache
        assert idx.match(self._toks(*range(8))) == pages[:1]
        # one token past: both pages hit
        assert idx.match(self._toks(*list(range(8)) + [99])) == pages
        # divergence mid-chain: only the common prefix page
        assert idx.match(self._toks(0, 1, 2, 3, 9, 9, 9, 9, 5)) == pages[:1]
        # sub-page prompts never match
        assert idx.match(self._toks(0, 1, 2)) == []

    def test_insert_adopts_refcount_and_first_writer_wins(self):
        pool, idx = PagePool(8), RadixPrefixIndex(page_size=4)
        a = pool.alloc(1)
        assert idx.insert(self._toks(*range(4)), a, pool) == 1
        assert pool.refcount(a[0]) == 2  # request + index
        b = pool.alloc(1)
        # same tokens computed again: the index keeps its page
        assert idx.insert(self._toks(*range(4)), b, pool) == 0
        assert pool.refcount(b[0]) == 1  # stays the request's alone
        assert idx.match(self._toks(*list(range(4)) + [7])) == a

    def test_insert_requires_page_alignment(self):
        pool, idx = PagePool(4), RadixPrefixIndex(page_size=4)
        with pytest.raises(ValueError, match="page-aligned"):
            idx.insert(self._toks(0, 1, 2), pool.alloc(1), pool)

    def test_evict_lru_leaves_first(self):
        pool, idx = PagePool(8), RadixPrefixIndex(page_size=2)
        chain = pool.alloc(2)  # one 2-page chain
        other = pool.alloc(1)  # one unrelated page
        idx.insert(self._toks(0, 1, 2, 3), chain, pool)
        idx.insert(self._toks(9, 9), other, pool)
        pool.decref(chain)
        pool.decref(other)  # requests retired; index holds everything
        idx.match(self._toks(9, 9, 5))  # touch `other`: now most recent
        # the chain is LRU: its leaf goes first, then (a leaf now) its
        # root — `other`, though a leaf all along, is more recent and
        # survives both evictions
        assert idx.evict(pool, 2) == 2
        assert idx.match(self._toks(0, 1, 2, 3, 5)) == []
        assert idx.match(self._toks(9, 9, 5)) == other

    def test_evict_never_touches_referenced_pages(self):
        pool, idx = PagePool(4), RadixPrefixIndex(page_size=2)
        busy = pool.alloc(1)  # still referenced by a "running request"
        idx.insert(self._toks(0, 1), busy, pool)
        assert idx.evict(pool, 1) == 0  # nothing evictable
        pool.decref(busy)
        assert idx.evict(pool, 1) == 1

    def test_len_counts_pages(self):
        pool, idx = PagePool(8), RadixPrefixIndex(page_size=2)
        idx.insert(self._toks(0, 1, 2, 3), pool.alloc(2), pool)
        assert len(idx) == 2


class TestPagedEngineIntegration:
    def test_no_kv_leakage_across_page_reuse(self):
        """The paged stale-row regression (kv_cache.py docstring): retire
        a LONG request, admit a SHORTER one whose pages land on the
        retired request's freed pages (prefix_cache off so retire frees
        them), and pin the new stream against a fresh engine's."""
        model = _llama()
        long_p, short_p = _prompts(3, (40, 6))
        engine = ServeEngine(
            model, num_slots=1, max_len=64, page_size=8,
            num_pages=8, prefix_cache=False,
        )
        engine.run([{"prompt": long_p, "max_new_tokens": 8}])
        assert engine.pool.in_use == 0  # all pages freed at retire
        got = engine.run([{"prompt": short_p, "max_new_tokens": 8}])[0]
        fresh = ServeEngine(
            model, num_slots=1, max_len=64, page_size=8,
            num_pages=8, prefix_cache=False,
        ).run([{"prompt": short_p, "max_new_tokens": 8}])[0]
        np.testing.assert_array_equal(got.tokens, fresh.tokens)

    def test_admission_gates_on_free_pages(self):
        """A pool with room for one request at a time serves a deeper
        queue FCFS: the page gate defers instead of over-admitting, and
        every stream stays exact."""
        model = _llama()
        prompts = _prompts(4, (10, 12, 9))
        reqs = [{"prompt": p, "max_new_tokens": 6} for p in prompts]
        # footprint per request: ceil((len + 6) / 8) <= 3 pages; 3 usable
        # pages => one request in flight at a time
        engine = ServeEngine(
            model, num_slots=3, max_len=64, page_size=8, num_pages=4,
            prefix_cache=False,
        )
        engine.submit(**reqs[0])
        engine.submit(**reqs[1])
        engine.step()
        assert engine.cache.active_count == 1  # second deferred on pages
        assert engine.scheduler.queue_depth == 1
        results = engine.run([dict(r) for r in reqs[2:]])
        baseline = ServeEngine(model, num_slots=3, max_len=64)
        base = baseline.run([dict(r) for r in reqs])
        np.testing.assert_array_equal(base[2].tokens, results[0].tokens)

    def test_eviction_under_pool_pressure_keeps_streams_exact(self):
        """Disjoint prompts churn through a small pool: the index must
        evict to admit, streams stay bit-identical to the slab engine,
        and the eviction counter records it."""
        model = _llama()
        prompts = _prompts(5, (17, 18, 19, 20))
        reqs = [{"prompt": p, "max_new_tokens": 5} for p in prompts]
        paged = ServeEngine(
            model, num_slots=2, max_len=64, page_size=8, num_pages=8
        )
        base = ServeEngine(model, num_slots=2, max_len=64)
        got = paged.run([dict(r) for r in reqs])
        want = base.run([dict(r) for r in reqs])
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a.tokens, b.tokens)
        assert paged.metrics.counters["pages_evicted"] > 0

    def test_prefix_hit_skips_prefill_compute(self):
        """Second identical burst: warm prefill buckets shrink to the
        suffix, the hit-rate metrics show it, and pages-in-use high
        water stays within the pool."""
        model = _llama()
        rs = np.random.RandomState(7)
        shared = rs.randint(0, 256, (16,)).astype(np.int32)
        reqs = [
            {"prompt": np.concatenate(
                [shared, rs.randint(0, 256, (n,)).astype(np.int32)]),
             "max_new_tokens": 4}
            for n in (3, 5)
        ]
        engine = ServeEngine(
            model, num_slots=2, max_len=64, page_size=8
        )
        engine.run([dict(r) for r in reqs])
        cold = engine.metrics.counters["tokens_prefilled"]
        from torchdistx_tpu.serve.metrics import ServeMetrics

        engine.metrics = ServeMetrics(engine.num_slots, engine.num_pages)
        engine.run([dict(r) for r in reqs])
        snap = engine.metrics.snapshot()
        assert snap["tokens_prefilled"] < cold  # warm < cold, strictly
        assert snap["prefix_hit_tokens"] >= 16 * 2  # both shared prefixes
        assert 0 < snap["prefix_hit_rate"] <= 1
        assert snap["pages_in_use_hwm"] <= engine.pool.capacity

    def test_submit_rejects_unservable_footprint(self):
        engine = ServeEngine(
            _llama(), num_slots=1, max_len=64, page_size=8, num_pages=4
        )
        # 3 usable pages = 24 rows; 20 + 8 = 28 rows can never fit
        with pytest.raises(ValueError, match="allocatable pages"):
            engine.submit(np.zeros(20, np.int32), max_new_tokens=8)
        with pytest.raises(ValueError, match="max_new_tokens"):
            engine.submit(np.zeros(4, np.int32), max_new_tokens=0)
        with pytest.raises(ValueError, match="max_new_tokens"):
            engine.submit(np.zeros(4, np.int32), max_new_tokens=-3)

    def test_engine_rejects_bad_page_geometry(self):
        with pytest.raises(ValueError, match="multiple of page_size"):
            ServeEngine(_llama(), max_len=64, page_size=7)
        with pytest.raises(ValueError, match="num_pages requires"):
            ServeEngine(_llama(), max_len=64, num_pages=8)

    def test_retired_slot_tables_point_at_scratch(self):
        """After retire, the slot's whole table row names the scratch
        page — the fused chunk's frozen writes must never land in a page
        another request may now own."""
        engine = ServeEngine(
            _llama(), num_slots=1, max_len=64, page_size=8, decode_chunk=4
        )
        engine.run([{"prompt": _prompts(8, (9,))[0], "max_new_tokens": 5}])
        assert np.all(engine.cache.page_tables[0] == SCRATCH_PAGE)

    def test_metrics_to_json_schema(self):
        import json

        engine = ServeEngine(
            _llama(), num_slots=2, max_len=64, page_size=8
        )
        engine.run([{"prompt": _prompts(9, (6,))[0], "max_new_tokens": 3}])
        j = json.loads(json.dumps(engine.metrics.to_json()))
        assert set(j) == {"counters", "gauges", "histograms", "derived"}
        assert j["counters"]["requests_completed"] == 1
        assert j["gauges"]["num_pages"] == engine.num_pages
        assert j["gauges"]["pages_in_use_hwm"] >= 1
        assert "prefix_hit_rate" in j["derived"]
        assert j["histograms"]["prefill_s"]["count"] == 1
        # snapshot() is a strict flattening of to_json()
        snap = engine.metrics.snapshot()
        for k, v in j["counters"].items():
            assert snap[k] == v
        assert snap["prefill_s_count"] == 1
