"""nn.Module object-model edge cases (ADVICE round-1 items)."""

import jax
import jax.numpy as jnp
import pytest

from torchdistx_tpu import nn


class Tiny(nn.Module):
    def __init__(self):
        super().__init__()
        self.weight = nn.Parameter(jnp.ones((2, 3)))
        self.running = nn.Buffer(jnp.zeros((3,)))

    def forward(self, x):
        return x @ self.weight


class TestSetattrOverRegistered:
    def test_bare_array_updates_parameter_store(self):
        m = Tiny()
        new = jnp.full((2, 3), 7.0)
        m.weight = new  # no Parameter() wrapper
        # forward() and named_parameters must agree (no shadowing)
        assert (dict(m.named_parameters())["weight"] == new).all()
        assert (m.weight == new).all()
        assert (m.state_dict()["weight"] == new).all()

    def test_bare_array_updates_buffer_store(self):
        m = Tiny()
        new = jnp.full((3,), 2.0)
        m.running = new
        assert (dict(m.named_buffers())["running"] == new).all()

    def test_non_array_assignment_still_plain_attribute(self):
        m = Tiny()
        m.note = "hello"
        assert m.note == "hello"
        assert "note" not in m._parameters


class TestLoadStateDictValidation:
    def test_shape_mismatch_raises(self):
        m = Tiny()
        bad = dict(m.state_dict())
        bad["weight"] = jnp.ones((3, 2))
        with pytest.raises(ValueError, match="shape mismatch.*weight"):
            m.load_state_dict(bad)

    def test_dtype_mismatch_casts(self):
        # torch parity: load_state_dict copies via Tensor.copy_, which casts
        m = Tiny()
        sd = dict(m.state_dict())
        sd["weight"] = jnp.full((2, 3), 1.5, jnp.bfloat16)
        m.load_state_dict(sd)
        assert m.weight.dtype == jnp.float32
        assert (m.weight == 1.5).all()

    def test_pre_init_assignment_messages(self):
        class Broken(nn.Module):
            def __init__(self):
                self.w = nn.Parameter(jnp.ones(3))  # no super().__init__()

        with pytest.raises(AttributeError, match="before Module.__init__"):
            Broken()

        class PlainAttrFirst(nn.Module):
            def __init__(self):
                self.dim = 4  # plain attribute before super() is fine
                super().__init__()
                self.w = nn.Parameter(jnp.ones(self.dim))

        m = PlainAttrFirst()
        assert m.dim == 4 and m.w.shape == (4,)

    def test_matching_load_roundtrips(self):
        m = Tiny()
        sd = {k: v * 2 for k, v in m.state_dict().items()}
        m.load_state_dict(sd)
        assert (m.weight == 2.0).all()

    def test_missing_key_raises_strict(self):
        m = Tiny()
        with pytest.raises(KeyError):
            m.load_state_dict({"weight": jnp.ones((2, 3))})


class TestApplyAndTo:
    def test_apply_children_first(self):
        order = []

        class Outer(nn.Module):
            def __init__(self):
                super().__init__()
                self.inner = Tiny()

        m = Outer()
        m.apply(lambda mod: order.append(type(mod).__name__))
        assert order == ["Tiny", "Outer"]

    def test_to_dtype_casts_everything(self):
        m = Tiny()
        m.to(dtype=jnp.bfloat16)
        assert m.weight.dtype == jnp.bfloat16
        assert m._buffers["running"].dtype == jnp.bfloat16

    def test_to_sharding_rule(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"x": 8})

        class Wide(nn.Module):
            def __init__(self):
                super().__init__()
                self.w = nn.Parameter(jnp.ones((16, 4)))

        m = Wide()
        m.to(sharding=lambda path, leaf: NamedSharding(mesh, P("x")))
        assert len(m.w.sharding.device_set) == 8

    def test_to_on_fake_raises(self):
        import torchdistx_tpu as tdx

        m = tdx.deferred_init(Tiny)
        with pytest.raises(TypeError, match="materialize first"):
            m.to(dtype=jnp.bfloat16)

    def test_to_keeps_integer_buffers(self):
        class WithCounter(nn.Module):
            def __init__(self):
                super().__init__()
                self.w = nn.Parameter(jnp.ones((4,)))
                self.steps = nn.Buffer(jnp.zeros((), jnp.int32))

        m = WithCounter()
        m.to(dtype=jnp.bfloat16)
        assert m.w.dtype == jnp.bfloat16
        assert m._buffers["steps"].dtype == jnp.int32  # untouched

    def test_to_is_transactional_on_fakes(self):
        import torchdistx_tpu as tdx

        m = tdx.deferred_init(Tiny)
        # all fake -> raises BEFORE mutating anything
        with pytest.raises(TypeError):
            m.to(dtype=jnp.bfloat16)
        assert all(
            not isinstance(v, jax.Array) for v in m.state_dict().values()
        )

    def test_to_rejects_non_float_dtype(self):
        m = Tiny()
        with pytest.raises(TypeError, match="floating-point"):
            m.to(dtype=jnp.int32)

    def test_to_accepts_numpy_entries(self):
        import numpy as np

        m = Tiny()
        m.register_buffer("host_buf", np.ones((3,), np.float32))
        m.to(dtype=jnp.bfloat16)  # numpy entries convert, not rejected
        assert m._buffers["host_buf"].dtype == jnp.bfloat16
