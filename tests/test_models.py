"""Model families: construction (eager + deferred), forward shapes, jit,
parameter counts, ring attention equivalence."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from torchdistx_tpu.parallel.compat import shard_map
from jax.sharding import PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu.models import GPT2, Llama, T5, resnet18, resnet50
from torchdistx_tpu.nn import functional_call
from torchdistx_tpu.ops.attention import multihead_attention, ring_attention


class TestLlama:
    def test_deferred_then_forward(self):
        tdx.manual_seed(0)
        m = tdx.deferred_init(Llama.from_name, "tiny")
        assert tdx.is_deferred(m)
        tdx.materialize_module(m)
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = m(tokens)
        assert logits.shape == (2, 16, 256)

    def test_jit_forward(self):
        tdx.manual_seed(0)
        m = Llama.from_name("tiny")
        params = dict(m.named_parameters())
        tokens = jnp.zeros((2, 16), jnp.int32)
        f = jax.jit(lambda p, t: functional_call(m, p, (t,)))
        np.testing.assert_allclose(
            np.asarray(f(params, tokens)), np.asarray(m(tokens)), rtol=2e-5, atol=1e-5
        )

    def test_7b_param_count_under_fake_mode(self):
        # the north-star model is constructible with zero storage
        with tdx.fake_mode():
            m = Llama.from_name("llama2_7b")
        n = m.num_params()
        assert 6.5e9 < n < 7.5e9  # ~6.74B

    def test_gqa_heads(self):
        tdx.manual_seed(0)
        m = Llama.from_name("tiny", n_kv_heads=2)
        logits = m(jnp.zeros((1, 8), jnp.int32))
        assert logits.shape == (1, 8, 256)


class TestGPT2:
    def test_deferred_and_shapes(self):
        tdx.manual_seed(1)
        m = tdx.deferred_init(GPT2.from_name, "tiny")
        tdx.materialize_module(m)
        logits = m(jnp.zeros((2, 12), jnp.int32))
        assert logits.shape == (2, 12, 256)

    def test_gpt2_large_param_count(self):
        with tdx.fake_mode():
            m = GPT2.from_name("gpt2_large")
        # GPT-2 large ~774M params (tied head)
        assert 7.0e8 < m.num_params() < 8.5e8


class TestResNet:
    def test_resnet18_forward(self):
        tdx.manual_seed(2)
        m = tdx.deferred_init(resnet18, num_classes=10)
        tdx.materialize_module(m)
        m.eval()
        out = m(jnp.ones((2, 3, 32, 32)))
        assert out.shape == (2, 10)

    def test_resnet50_param_count(self):
        with tdx.fake_mode():
            m = resnet50()
        # torchvision resnet50 = 25.557M params
        assert 25.0e6 < m.num_params() < 26.2e6


class TestT5:
    def test_deferred_and_shapes(self):
        tdx.manual_seed(3)
        m = tdx.deferred_init(T5.from_name, "tiny")
        tdx.materialize_module(m)
        logits = m(jnp.zeros((2, 10), jnp.int32), jnp.zeros((2, 6), jnp.int32))
        assert logits.shape == (2, 6, 256)

    def test_t5_3b_param_count(self):
        with tdx.fake_mode():
            m = T5.from_name("t5_3b")
        assert 2.6e9 < m.num_params() < 3.2e9


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, mesh8, causal):
        rs = np.random.RandomState(0)
        b, s, h, d = 2, 64, 4, 16
        q = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
        v = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)

        full = multihead_attention(q, k, v, causal=causal)

        ring = shard_map(
            lambda q_, k_, v_: ring_attention(q_, k_, v_, axis="fsdp", causal=causal),
            mesh=mesh8,
            in_specs=(P(None, "fsdp"), P(None, "fsdp"), P(None, "fsdp")),
            out_specs=P(None, "fsdp"),
            check_vma=False,
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full), rtol=2e-4, atol=2e-5)

    def test_gqa_ring(self, mesh8):
        rs = np.random.RandomState(1)
        b, s, hq, hkv, d = 1, 32, 8, 2, 8
        q = jnp.asarray(rs.randn(b, s, hq, d), jnp.float32)
        k = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
        full = multihead_attention(q, k, v, causal=True)
        ring = shard_map(
            lambda q_, k_, v_: ring_attention(q_, k_, v_, axis="fsdp", causal=True),
            mesh=mesh8,
            in_specs=(P(None, "fsdp"), P(None, "fsdp"), P(None, "fsdp")),
            out_specs=P(None, "fsdp"),
            check_vma=False,
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full), rtol=2e-4, atol=2e-5)


class TestT5Flash:
    def test_flash_self_attention_matches_einsum(self):
        from torchdistx_tpu.models import T5

        tdx.manual_seed(31)
        m = tdx.deferred_init(T5.from_name, "tiny")
        tdx.materialize_module(m)
        params = dict(m.named_parameters())
        enc = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (2, 24)), jnp.int32
        )
        dec = jnp.asarray(
            np.random.RandomState(1).randint(0, 256, (2, 16)), jnp.int32
        )
        base = functional_call(m, params, (enc, dec))
        for blk in list(m.enc_blocks) + list(m.dec_blocks):
            blk.self_attn.cfg = dataclasses.replace(
                blk.self_attn.cfg, use_flash=True
            )
        flash = functional_call(m, params, (enc, dec))
        np.testing.assert_allclose(
            np.asarray(base), np.asarray(flash), rtol=3e-5, atol=3e-5
        )


class TestRingAttentionBias:
    @pytest.mark.parametrize("causal", [True, False])
    def test_ring_with_bias_matches_full(self, mesh8, causal):
        """Bias sharded by query rows (H, sq_local, S_global): ring must
        equal full attention with the same global bias — the T5-under-SP
        long-context path."""
        rs = np.random.RandomState(2)
        b, s, h, d = 1, 64, 4, 16
        q = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
        v = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
        bias = jnp.asarray(rs.randn(h, s, s) * 0.5, jnp.float32)

        # reference: full attention + bias (unscaled-compatible path)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        logits = logits / np.sqrt(d) + bias[None]
        if causal:
            mask = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(mask, logits, -jnp.inf)
        full = jnp.einsum(
            "bhqk,bkhd->bqhd", jax.nn.softmax(logits, -1).astype(q.dtype), v
        )

        ring = shard_map(
            lambda q_, k_, v_, b_: ring_attention(
                q_, k_, v_, axis="fsdp", causal=causal, bias=b_
            ),
            mesh=mesh8,
            in_specs=(
                P(None, "fsdp"),
                P(None, "fsdp"),
                P(None, "fsdp"),
                P(None, "fsdp", None),  # bias rows follow the query shard
            ),
            out_specs=P(None, "fsdp"),
            check_vma=False,
        )(q, k, v, bias)
        np.testing.assert_allclose(
            np.asarray(ring), np.asarray(full), rtol=2e-4, atol=2e-5
        )


class TestT5SequenceParallel:
    """T5 with sp_axis: the whole encoder-decoder forward inside
    shard_map (sequence sharded) must equal the unsharded model — the
    rel-pos bias rides per-device row slices through the ring paths and
    cross-attention rings over the encoder's key shards."""

    @pytest.mark.parametrize("use_flash", [False, True])
    @pytest.mark.slow
    def test_sp_forward_matches_unsharded(self, use_flash):
        from torchdistx_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from torchdistx_tpu.models import T5
        from torchdistx_tpu.nn import functional_call
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        tdx.manual_seed(11)
        plain = tdx.deferred_init(T5.from_name, "tiny", use_flash=use_flash)
        tdx.materialize_module(plain)
        params = dict(plain.named_parameters())
        sp = T5.from_name("tiny", use_flash=use_flash, sp_axis="sp")
        sp.load_state_dict(params)
        from jax.sharding import NamedSharding

        params = jax.device_put(params, NamedSharding(mesh, P()))

        rs = np.random.RandomState(7)
        # UNEQUAL enc/dec lengths: cross-attention rings q shards of 4
        # over encoder key shards of 8 — the sq != skv ring path
        src = jnp.asarray(rs.randint(0, 256, (2, 64)), jnp.int32)
        tgt = jnp.asarray(rs.randint(0, 256, (2, 32)), jnp.int32)

        ref = plain(src, tgt)
        out = shard_map(
            lambda p, s, t: functional_call(sp, p, (s, t)),
            mesh=mesh,
            in_specs=(P(), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )(params, src, tgt)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
        )

    @pytest.mark.slow
    def test_sp_gradients_match_unsharded(self):
        from torchdistx_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from torchdistx_tpu.models import T5
        from torchdistx_tpu.nn import functional, functional_call
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        tdx.manual_seed(12)
        plain = tdx.deferred_init(T5.from_name, "tiny")
        tdx.materialize_module(plain)
        params = dict(plain.named_parameters())
        sp = T5.from_name("tiny", sp_axis="sp")
        sp.load_state_dict(params)
        from jax.sharding import NamedSharding

        sp_params = jax.device_put(params, NamedSharding(mesh, P()))

        rs = np.random.RandomState(8)
        src = jnp.asarray(rs.randint(0, 256, (1, 64)), jnp.int32)
        tgt = jnp.asarray(rs.randint(0, 256, (1, 64)), jnp.int32)

        def loss_plain(p):
            return functional.cross_entropy(
                functional_call(plain, p, (src, tgt)), tgt
            )

        def loss_sp(p):
            def inner(p, s, t):
                logits = functional_call(sp, p, (s, t))
                return jax.lax.pmean(
                    functional.cross_entropy(logits, t), "sp"
                )

            return shard_map(
                inner,
                mesh=mesh,
                in_specs=(P(), P(None, "sp"), P(None, "sp")),
                out_specs=P(),
                check_vma=False,
            )(p, src, tgt)

        gp = jax.grad(loss_plain)(params)
        gs = jax.grad(loss_sp)(sp_params)
        # rel-bias table must receive the ring-accumulated dbias
        key = next(k for k in gp if "rel_bias" in k)
        np.testing.assert_allclose(
            np.asarray(gs[key]), np.asarray(gp[key]),
            rtol=3e-4, atol=3e-5, err_msg=key,
        )
        for k in gp:
            np.testing.assert_allclose(
                np.asarray(gs[k]), np.asarray(gp[k]),
                rtol=5e-4, atol=5e-5, err_msg=k,
            )


class TestSequenceParallelFamilies:
    """SP must hold across model families, not just Llama: GPT-2
    (learned positions offset per shard) and Mixtral (MoE FFN under the
    ring) — forward parity vs the unsharded model on the sp mesh."""

    @staticmethod
    def _sp_forward(model_sp, params, mesh, *args):
        from torchdistx_tpu.parallel.compat import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torchdistx_tpu.nn import functional_call

        params = jax.device_put(params, NamedSharding(mesh, P()))
        specs = tuple(P(None, "sp") for _ in args)
        return shard_map(
            lambda p, *a: functional_call(model_sp, p, a),
            mesh=mesh,
            in_specs=(P(),) + specs,
            out_specs=P(None, "sp"),
            check_vma=False,
        )(params, *args)

    @pytest.mark.parametrize("sp_mode", ["ring", "ulysses"])
    @pytest.mark.slow
    def test_gpt2_sp_matches_unsharded(self, sp_mode):
        from torchdistx_tpu.models import GPT2
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        # ulysses reshards heads over the axis: use 8 heads for 8 devices
        kw = {"n_heads": 8} if sp_mode == "ulysses" else {}
        tdx.manual_seed(13)
        plain = tdx.deferred_init(GPT2.from_name, "tiny", **kw)
        tdx.materialize_module(plain)
        params = dict(plain.named_parameters())
        sp = GPT2.from_name("tiny", sp_axis="sp", sp_mode=sp_mode, **kw)
        sp.load_state_dict(params)

        toks = jnp.asarray(
            np.random.RandomState(9).randint(0, 256, (2, 64)), jnp.int32
        )
        ref = plain(toks)
        out = self._sp_forward(sp, params, mesh, toks)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
        )

    @pytest.mark.slow
    def test_mixtral_sp_matches_unsharded(self):
        from torchdistx_tpu.models import Mixtral
        from torchdistx_tpu.parallel import create_mesh

        mesh = create_mesh({"sp": 8})
        tdx.manual_seed(14)
        plain = tdx.deferred_init(Mixtral.from_name, "tiny")
        tdx.materialize_module(plain)
        params = dict(plain.named_parameters())
        sp = Mixtral.from_name("tiny", sp_axis="sp")
        sp.load_state_dict(params)

        toks = jnp.asarray(
            np.random.RandomState(10).randint(0, 256, (2, 64)), jnp.int32
        )
        ref = plain(toks)
        out = self._sp_forward(sp, params, mesh, toks)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=3e-4, atol=3e-4
        )


def test_mistral_7b_preset():
    # Mistral-7B = Llama arch + GQA(8 kv heads) + 4096 sliding window;
    # param count must match the published 7.24B
    from torchdistx_tpu.models import Llama

    with tdx.fake_mode():
        m = Llama.from_name("mistral_7b")
    assert m.num_params() == 7241732096
    assert m.cfg.sliding_window == 4096 and m.cfg.n_kv_heads == 8


def test_llama3_8b_preset():
    # Llama-3-8B: GQA(8 kv), 128256 vocab, theta 5e5 — published 8.03B
    from torchdistx_tpu.models import Llama

    with tdx.fake_mode():
        m = Llama.from_name("llama3_8b")
    assert m.num_params() == 8030261248
    assert m.cfg.n_kv_heads == 8 and m.cfg.rope_theta == 500000.0


class TestRematPolicy:
    def test_grads_identical_across_policies(self):
        # remat changes WHAT is saved, never the math: loss and grads must
        # match bitwise-closely across off/full/dots
        import torchdistx_tpu as tdx
        from torchdistx_tpu.models import Llama
        from torchdistx_tpu.nn import functional, functional_call

        results = {}
        for policy, remat in [(None, False), ("full", True), ("dots", True)]:
            tdx.manual_seed(0)
            kw = dict(max_seq_len=32, remat=remat, use_flash=False)
            if policy:
                kw["remat_policy"] = policy
            m = tdx.deferred_init(Llama.from_name, "tiny", **kw)
            tdx.materialize_module(m)
            p = dict(m.named_parameters())
            toks = jnp.asarray(
                np.random.RandomState(0).randint(0, 64, (2, 32)), jnp.int32
            )

            def loss(p):
                return functional.cross_entropy(
                    functional_call(m, p, (toks,)), toks
                )

            l, g = jax.value_and_grad(loss)(p)
            results[policy or "off"] = (float(l), g)

        l0, g0 = results["off"]
        for k in ("full", "dots"):
            l1, g1 = results[k]
            np.testing.assert_allclose(l1, l0, rtol=1e-6)
            for a, b in zip(
                jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g0)
            ):
                np.testing.assert_allclose(
                    np.asarray(a, np.float32), np.asarray(b, np.float32),
                    atol=1e-5,
                )

    def test_unknown_policy_rejected_at_construction(self):
        from torchdistx_tpu.models import Llama

        with pytest.raises(ValueError, match="remat_policy"):
            Llama.from_name("tiny", remat_policy="typo")

    def test_mixtral_honors_policy(self):
        # the MoE training path threads the same policy (and the same
        # grads-invariance) as the inherited Llama paths
        import torchdistx_tpu as tdx
        from torchdistx_tpu.models import Mixtral
        from torchdistx_tpu.nn import functional, functional_call

        results = {}
        for policy in ("full", "dots"):
            tdx.manual_seed(0)
            m = tdx.deferred_init(
                Mixtral.from_name, "tiny", remat=True, remat_policy=policy,
                use_flash=False,
            )
            tdx.materialize_module(m)
            p = dict(m.named_parameters())
            toks = jnp.asarray(
                np.random.RandomState(0).randint(0, 64, (2, 16)), jnp.int32
            )

            def loss(p):
                logits, aux = functional_call(
                    m, p, (toks,), method="forward_with_aux"
                )
                return functional.cross_entropy(logits, toks) + 0.01 * aux

            l, g = jax.value_and_grad(loss)(p)
            results[policy] = (float(l), g)
        np.testing.assert_allclose(
            results["dots"][0], results["full"][0], rtol=1e-6
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(results["dots"][1]),
            jax.tree_util.tree_leaves(results["full"][1]),
        ):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=1e-5,
            )
