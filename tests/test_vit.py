"""ViT family: deferred-init parity, fake-mode construction at real
scale, published parameter counts, and a sharded train step."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu import nn
from torchdistx_tpu.models import ViT, ViTConfig
from torchdistx_tpu.nn import functional, functional_call


def _images(b=2, size=32, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randn(b, 3, size, size), jnp.float32
    )


def test_published_param_counts():
    # fake mode: zero array storage even at the 300M scale
    with tdx.fake_mode():
        assert ViT.from_name("vit_b16").num_params() == 86_567_656
        assert ViT.from_name("vit_l16").num_params() == 304_326_632


def test_deferred_matches_eager_bitwise():
    tdx.manual_seed(0)
    m = tdx.deferred_init(ViT.from_name, "tiny")
    tdx.materialize_module(m)
    tdx.manual_seed(0)
    m2 = ViT.from_name("tiny")
    for (k1, p1), (k2, p2) in zip(
        sorted(m.named_parameters()), sorted(m2.named_parameters())
    ):
        assert k1 == k2
        np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))


def test_forward_shapes_and_hidden():
    tdx.manual_seed(0)
    m = ViT.from_name("tiny")
    logits = m(_images())
    assert logits.shape == (2, 10)
    h = m(_images(), return_hidden=True)
    assert h.shape == (2, 1 + m.cfg.n_patches, m.cfg.dim)
    # CLS readout equals head(hidden[:, 0])
    np.testing.assert_allclose(
        np.asarray(m.head(h[:, 0])), np.asarray(logits), rtol=1e-6
    )


def test_bad_patch_size_rejected():
    with pytest.raises(ValueError, match="not divisible"):
        ViTConfig(image_size=224, patch_size=15)


def test_sharded_train_step_loss_decreases(mesh8):
    from torchdistx_tpu.parallel import ShardedTrainStep, fsdp_shard_rule

    tdx.manual_seed(0)
    m = tdx.deferred_init(ViT.from_name, "tiny")
    tdx.materialize_module(m, sharding_rule=fsdp_shard_rule(mesh8))
    params = dict(m.named_parameters())

    imgs = _images(b=8)
    labels = jnp.asarray(np.arange(8) % 10)

    def loss_fn(p, batch):
        x, y = batch
        return functional.cross_entropy(functional_call(m, p, (x,)), y)

    step = ShardedTrainStep(
        loss_fn, optax.adam(1e-3), mesh8, shard_axis="fsdp"
    )
    params = step.shard_params(params)
    s = step.init_optimizer(params)
    losses = []
    for _ in range(5):
        params, s, loss = step(params, s, (imgs, labels))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
