"""Weight-only int8 inference quantization: error bounds, model-level
logits fidelity, generation, and the storage reduction that motivates it
(decode is weight-read-bound)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchdistx_tpu as tdx
from torchdistx_tpu import nn
from torchdistx_tpu.nn import QuantizedLinear, quantize_module


def _param_bytes(m):
    return sum(
        p.size * p.dtype.itemsize for _, p in m.named_parameters()
    )


class TestQuantizedLinear:
    def test_matches_linear_within_quant_error(self):
        tdx.manual_seed(0)
        lin = nn.Linear(64, 32)
        q = QuantizedLinear.from_linear(lin)
        x = jnp.asarray(np.random.RandomState(0).randn(8, 64), jnp.float32)
        y, yq = lin(x), q(x)
        # per-output-channel absmax: weight error <= scale/2 per element;
        # output error accumulates ~sqrt(in) * |x| * scale / 2
        w = np.asarray(lin.weight, np.float32)
        scale = np.abs(w).max(axis=1) / 127.0
        bound = (
            np.sqrt(64) * np.abs(np.asarray(x)).max() * scale.max() * 0.75
        )
        assert np.abs(np.asarray(y - yq)).max() <= bound
        # relative fidelity is ~1%
        rel = np.linalg.norm(np.asarray(y - yq)) / np.linalg.norm(
            np.asarray(y)
        )
        assert rel < 0.02, rel

    def test_storage_reduction(self):
        lin = nn.Linear(256, 256, dtype=jnp.float32)
        q = QuantizedLinear.from_linear(lin)
        # int8 codes + f32 scale + f32 bias vs f32 weight + bias
        assert _param_bytes(q) < 0.3 * _param_bytes(lin)
        assert q.weight_q.dtype == jnp.int8

    def test_jits(self):
        lin = nn.Linear(16, 16)
        q = QuantizedLinear.from_linear(lin)
        x = jnp.ones((2, 16))
        y = jax.jit(lambda x: q(x))(x)
        assert y.shape == (2, 16) and bool(jnp.all(jnp.isfinite(y)))

    def test_bare_linear_rejected(self):
        with pytest.raises(ValueError, match="Linear CHILDREN"):
            quantize_module(nn.Linear(4, 4))


class TestQuantizeModule:
    def test_llama_logits_fidelity_and_generate(self):
        from torchdistx_tpu.generation import generate
        from torchdistx_tpu.models import Llama

        tdx.manual_seed(1)
        m = tdx.deferred_init(Llama.from_name, "tiny")
        tdx.materialize_module(m)
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 256, (1, 16)), jnp.int32
        )
        ref_logits = np.asarray(m(toks), np.float32)
        bytes_before = _param_bytes(m)

        quantize_module(m)
        assert any(
            isinstance(mod, QuantizedLinear)
            for _, mod in m.named_modules()
        )
        q_logits = np.asarray(m(toks), np.float32)
        bytes_after = _param_bytes(m)

        # logits stay close relative to their own scale (weight-only int8)
        denom = np.abs(ref_logits).max()
        assert np.abs(q_logits - ref_logits).max() / denom < 0.05
        # Linears dominate the tiny model less than a 7B, but storage
        # must still drop substantially
        assert bytes_after < 0.65 * bytes_before

        out = generate(m, toks[:, :8], max_new_tokens=8)
        assert out.shape == (1, 16)

    def test_filter_fn_excludes_layers(self):
        tdx.manual_seed(2)
        from torchdistx_tpu.models import Llama

        m = tdx.deferred_init(Llama.from_name, "tiny")
        tdx.materialize_module(m)
        quantize_module(m, filter_fn=lambda path, lin: "lm_head" not in path)
        kinds = {
            path: type(mod).__name__
            for path, mod in m.named_modules()
            if type(mod).__name__ in ("Linear", "QuantizedLinear")
        }
        lm = [p for p in kinds if "lm_head" in p]
        others = [p for p in kinds if "lm_head" not in p]
        assert lm and all(kinds[p] == "Linear" for p in lm)
        assert others and all(
            kinds[p] == "QuantizedLinear" for p in others
        )

    def test_state_dict_round_trip(self):
        tdx.manual_seed(3)

        class Tiny(nn.Module):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, x):
                return self.fc(x)

        a = Tiny()
        quantize_module(a)
        sd = a.state_dict()
        assert sd["fc.weight_q"].dtype == jnp.int8

        b = Tiny()
        quantize_module(b)
        b.load_state_dict(sd)
        x = jnp.ones((2, 8))
        np.testing.assert_array_equal(np.asarray(a(x)), np.asarray(b(x)))


class TestQuantizedMoE:
    def test_mixtral_expert_weights_quantize(self):
        # MoE expert weights are >95% of a Mixtral block's bytes; the
        # silent-skip regression left them full-precision
        from torchdistx_tpu.models import Mixtral
        from torchdistx_tpu.nn import QuantizedMoE

        tdx.manual_seed(5)
        m = tdx.deferred_init(Mixtral.from_name, "tiny")
        tdx.materialize_module(m)
        toks = jnp.asarray(
            np.random.RandomState(3).randint(0, 256, (1, 16)), jnp.int32
        )
        ref = np.asarray(m(toks), np.float32)
        b0 = _param_bytes(m)
        quantize_module(m)
        assert any(
            isinstance(mod, QuantizedMoE) for _, mod in m.named_modules()
        )
        q = np.asarray(m(toks), np.float32)
        b1 = _param_bytes(m)
        # MoE fidelity needs a robust metric: a near-tie top-k routing
        # choice can flip under ANY precision change (bf16-only casts
        # show the same max-norm spikes), swinging one token's logits.
        # The bulk of logits must stay tight and greedy decoding stable.
        rel = np.abs(q - ref) / np.abs(ref).max()
        assert np.quantile(rel, 0.99) < 0.05, np.quantile(rel, 0.99)
        assert (q.argmax(-1) == ref.argmax(-1)).mean() > 0.9
        assert b1 < 0.55 * b0, (b0, b1)
        # capacity + gather dispatch also run quantized
        tdx.manual_seed(5)
        g = tdx.deferred_init(
            Mixtral.from_name, "tiny", capacity_factor=2.0,
            moe_dispatch="gather",
        )
        tdx.materialize_module(g)
        quantize_module(g)
        out = g(toks)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_to_bf16_preserves_scales(self):
        from torchdistx_tpu.nn import QuantizedMoE  # noqa: F401

        tdx.manual_seed(6)

        class Net(nn.Module):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(16, 16)

            def forward(self, x):
                return self.fc(x)

        m = Net()
        quantize_module(m)
        m.to(jnp.bfloat16)
        # codes are int (never cast); scales are declared _keep_dtype and
        # must stay f32 through Module.to — bias becomes bf16
        assert m.fc.weight_q.dtype == jnp.int8
        assert m.fc.scale.dtype == jnp.float32
        assert m.fc.bias.dtype == jnp.bfloat16
        y = m(jnp.ones((2, 16), jnp.bfloat16))
        assert y.dtype == jnp.bfloat16

    def test_bare_moe_rejected_and_from_moe_works(self):
        from torchdistx_tpu.nn.moe import MoE
        from torchdistx_tpu.nn import QuantizedMoE

        tdx.manual_seed(8)
        moe = MoE(16, 32, 4, 2)
        with pytest.raises(ValueError, match="MoE CHILDREN"):
            quantize_module(moe)
        q = QuantizedMoE.from_moe(moe)
        x = jnp.asarray(np.random.RandomState(6).randn(2, 8, 16), jnp.float32)
        ya, yb = moe(x), q(x)
        rel = np.abs(np.asarray(ya - yb)) / np.abs(np.asarray(ya)).max()
        assert np.quantile(rel, 0.99) < 0.05

    def test_filter_excluded_moe_keeps_router(self):
        # a filtered-out MoE must not be PARTIALLY quantized (its router
        # previously got swapped even when the filter rejected the layer)
        from torchdistx_tpu.models import Mixtral
        from torchdistx_tpu.nn.moe import MoE
        from torchdistx_tpu.nn import QuantizedMoE

        tdx.manual_seed(9)
        m = tdx.deferred_init(Mixtral.from_name, "tiny")
        tdx.materialize_module(m)
        quantize_module(
            m, filter_fn=lambda path, mod: not isinstance(mod, MoE)
        )
        for path, mod in m.named_modules():
            assert not isinstance(mod, QuantizedMoE), path
            if isinstance(mod, MoE):
                assert type(mod.router).__name__ == "Linear", path


def test_t5_quantized_encdec_generate():
    # the encoder-decoder decode path projects encoder K/V through
    # (now-quantized) Linears at cache init — whole pipeline must run
    # and stay greedy-stable
    from torchdistx_tpu.generation import generate_encdec
    from torchdistx_tpu.models import T5

    tdx.manual_seed(10)
    m = tdx.deferred_init(T5.from_name, "tiny")
    tdx.materialize_module(m)
    src = jnp.asarray(
        np.random.RandomState(7).randint(0, 256, (1, 16)), jnp.int32
    )
    ref = np.asarray(generate_encdec(m, src, max_new_tokens=8))
    quantize_module(m)
    out = np.asarray(generate_encdec(m, src, max_new_tokens=8))
    assert out.shape == ref.shape
    assert (out == ref).mean() > 0.7  # greedy agreement (int8 fidelity)
