"""MoE layer + expert parallelism: routing correctness, deferred init,
ep-sharded == unsharded, gradient flow."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu.nn import functional_call
from torchdistx_tpu.nn.moe import MoE, moe_shard_rule
from torchdistx_tpu.parallel import create_mesh


def test_topk_routing_selects_k_experts():
    tdx.manual_seed(0)
    m = MoE(16, 32, n_experts=4, top_k=1)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16), jnp.float32)
    y = m(x)
    assert y.shape == (2, 8, 16)
    # top-1: output must equal the single selected expert's output weighted 1
    logits = np.asarray(m.router(x))
    sel = logits.argmax(-1)
    h = jax.nn.silu(jnp.einsum("bsd,edf->bsef", x, m.w_gate)) * jnp.einsum(
        "bsd,edf->bsef", x, m.w_up
    )
    eo = np.asarray(jnp.einsum("bsef,efd->bsed", h, m.w_down))
    expected = np.take_along_axis(eo, sel[..., None, None], axis=2)[:, :, 0]
    np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-5, atol=1e-6)


def test_deferred_init_moe():
    tdx.manual_seed(1)
    m = tdx.deferred_init(MoE, 8, 16, n_experts=4, top_k=2)
    assert tdx.is_deferred(m)
    tdx.materialize_module(m)
    y = m(jnp.ones((2, 4, 8)))
    assert y.shape == (2, 4, 8)


def test_ep_sharded_matches_unsharded():
    mesh = create_mesh({"dp": 2, "ep": 4})
    tdx.manual_seed(2)
    m = tdx.deferred_init(MoE, 16, 32, n_experts=8, top_k=2)
    tdx.materialize_module(m, sharding_rule=moe_shard_rule(mesh, "ep"))
    assert m._parameters["w_up"].sharding.spec == P("ep", None, None)
    params = dict(m.named_parameters())

    x = jnp.asarray(np.random.RandomState(1).randn(4, 8, 16), jnp.float32)
    sharded = jax.jit(lambda p, x: functional_call(m, p, (x,)))(params, x)

    tdx.manual_seed(2)
    m2 = MoE(16, 32, n_experts=8, top_k=2)
    unsharded = m2(x)
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(unsharded), rtol=1e-4, atol=1e-5
    )


def test_gradients_flow_and_balance_loss():
    tdx.manual_seed(3)
    m = MoE(8, 16, n_experts=4, top_k=2)
    params = dict(m.named_parameters())
    x = jnp.asarray(np.random.RandomState(2).randn(2, 4, 8), jnp.float32)

    def loss(p):
        y, aux = functional_call(m, p, (x,), {"return_aux": True})
        return jnp.mean(y**2) + 0.01 * aux

    g = jax.grad(loss)(params)
    for k in ("w_up", "w_gate", "w_down", "router.weight"):
        assert float(jnp.abs(g[k]).sum()) > 0.0, k


def test_invalid_topk():
    import pytest

    with pytest.raises(ValueError, match="top_k"):
        MoE(8, 16, n_experts=4, top_k=5)


class TestCapacityDispatch:
    """Capacity-based token dispatch must equal the dense path when no
    token can be dropped (capacity_factor >= E / top_k), and must drop the
    overflow (zero combine weight) when capacity is tight."""

    def test_matches_dense_when_capacity_sufficient(self):
        tdx.manual_seed(5)
        dense = tdx.deferred_init(MoE, 16, 32, 4, 2)
        tdx.materialize_module(dense)
        params = dict(dense.named_parameters())

        disp = MoE(16, 32, 4, 2, capacity_factor=4 / 2)  # C = n: no drops
        disp.load_state_dict(params)

        x = jnp.asarray(
            np.random.RandomState(0).randn(3, 8, 16).astype(np.float32)
        )
        y_dense = dense(x)
        y_disp = disp(x)
        np.testing.assert_allclose(
            np.asarray(y_dense), np.asarray(y_disp), rtol=2e-5, atol=2e-5
        )

    def test_gradients_flow(self):
        tdx.manual_seed(6)
        m = MoE(8, 16, 4, 2, capacity_factor=2.0)
        params = dict(m.named_parameters())
        x = jnp.asarray(np.random.RandomState(1).randn(4, 8).astype(np.float32))

        def loss(p):
            return jnp.mean(functional_call(m, p, (x,)) ** 2)

        g = jax.grad(loss)(params)
        assert all(jnp.all(jnp.isfinite(v)) for v in g.values())
        assert float(jnp.abs(g["w_gate"]).sum()) > 0

    def test_tight_capacity_drops_tokens(self):
        tdx.manual_seed(7)
        # capacity_factor tiny -> C = 1: most tokens dropped, output is
        # partial but finite; combine weights for dropped tokens are zero
        m = MoE(8, 16, 4, 1, capacity_factor=0.1)
        x = jnp.asarray(np.random.RandomState(2).randn(16, 8).astype(np.float32))
        y = m(x)
        assert y.shape == x.shape
        assert bool(jnp.all(jnp.isfinite(y)))
        # at least one token passed through, not all
        norms = jnp.linalg.norm(y, axis=-1)
        assert float(jnp.max(norms)) > 0
        assert float(jnp.min(norms)) == 0.0

    def test_gather_dispatch_matches_einsum(self):
        # gather mode removes the O(n*E*C*D) bookkeeping MACs; outputs and
        # gradients must agree with the einsum path — including under
        # tight capacity, where both must drop the SAME tokens (shared
        # GShard slot assignment)
        for cf in (2.0, 0.5):
            tdx.manual_seed(9)
            a = tdx.deferred_init(
                MoE, 16, 32, 4, 2, capacity_factor=cf
            )
            tdx.materialize_module(a)
            params = dict(a.named_parameters())
            b = MoE(
                16, 32, 4, 2, capacity_factor=cf, dispatch_mode="gather"
            )
            b.load_state_dict(params)
            x = jnp.asarray(
                np.random.RandomState(4).randn(3, 8, 16).astype(np.float32)
            )
            ya, yb = a(x), b(x)
            np.testing.assert_allclose(
                np.asarray(ya), np.asarray(yb), rtol=2e-5, atol=2e-5,
                err_msg=f"capacity_factor={cf}",
            )

            def loss(p, m):
                return jnp.mean(functional_call(m, p, (x,)) ** 2)

            ga = jax.grad(lambda p: loss(p, a))(params)
            gb = jax.grad(lambda p: loss(p, b))(params)
            for k in ga:
                np.testing.assert_allclose(
                    np.asarray(ga[k]), np.asarray(gb[k]),
                    rtol=2e-4, atol=1e-6,
                    err_msg=f"grad {k} capacity_factor={cf}",
                )

    def test_gather_dispatch_jits(self):
        m = MoE(8, 16, 4, 2, capacity_factor=1.5, dispatch_mode="gather")
        x = jnp.asarray(np.random.RandomState(5).randn(2, 4, 8).astype(np.float32))
        y = jax.jit(lambda x: m(x))(x)
        assert y.shape == x.shape and bool(jnp.all(jnp.isfinite(y)))

    def test_bad_dispatch_mode_rejected(self):
        with pytest.raises(ValueError, match="dispatch_mode"):
            MoE(8, 16, 4, 2, dispatch_mode="bogus")

    def test_gather_without_capacity_rejected(self):
        # silent fallback to dense compute would waste E/top_k x FLOPs
        with pytest.raises(ValueError, match="capacity_factor"):
            MoE(8, 16, 4, 2, dispatch_mode="gather")

    def test_ep_sharded_dispatch(self):
        mesh = create_mesh({"ep": 4}, devices=jax.devices()[:4])
        tdx.manual_seed(8)
        m = tdx.deferred_init(MoE, 16, 32, 4, 2, capacity_factor=2.0)
        tdx.materialize_module(m, sharding_rule=moe_shard_rule(mesh, "ep"))
        x = jnp.asarray(np.random.RandomState(3).randn(2, 8, 16).astype(np.float32))
        y = m(x)
        assert y.shape == x.shape
        assert bool(jnp.all(jnp.isfinite(np.asarray(y))))
