"""MoE layer + expert parallelism: routing correctness, deferred init,
ep-sharded == unsharded, gradient flow."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu import nn
from torchdistx_tpu.nn import functional_call
from torchdistx_tpu.nn.moe import MoE, moe_shard_rule
from torchdistx_tpu.parallel import create_mesh


def test_topk_routing_selects_k_experts():
    tdx.manual_seed(0)
    m = MoE(16, 32, n_experts=4, top_k=1)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16), jnp.float32)
    y = m(x)
    assert y.shape == (2, 8, 16)
    # top-1: output must equal the single selected expert's output weighted 1
    logits = np.asarray(m.router(x))
    sel = logits.argmax(-1)
    h = jax.nn.silu(jnp.einsum("bsd,edf->bsef", x, m.w_gate)) * jnp.einsum(
        "bsd,edf->bsef", x, m.w_up
    )
    eo = np.asarray(jnp.einsum("bsef,efd->bsed", h, m.w_down))
    expected = np.take_along_axis(eo, sel[..., None, None], axis=2)[:, :, 0]
    np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-5, atol=1e-6)


def test_deferred_init_moe():
    tdx.manual_seed(1)
    m = tdx.deferred_init(MoE, 8, 16, n_experts=4, top_k=2)
    assert tdx.is_deferred(m)
    tdx.materialize_module(m)
    y = m(jnp.ones((2, 4, 8)))
    assert y.shape == (2, 4, 8)


def test_ep_sharded_matches_unsharded():
    mesh = create_mesh({"dp": 2, "ep": 4})
    tdx.manual_seed(2)
    m = tdx.deferred_init(MoE, 16, 32, n_experts=8, top_k=2)
    tdx.materialize_module(m, sharding_rule=moe_shard_rule(mesh, "ep"))
    assert m._parameters["w_up"].sharding.spec == P("ep", None, None)
    params = dict(m.named_parameters())

    x = jnp.asarray(np.random.RandomState(1).randn(4, 8, 16), jnp.float32)
    sharded = jax.jit(lambda p, x: functional_call(m, p, (x,)))(params, x)

    tdx.manual_seed(2)
    m2 = MoE(16, 32, n_experts=8, top_k=2)
    unsharded = m2(x)
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(unsharded), rtol=1e-4, atol=1e-5
    )


def test_gradients_flow_and_balance_loss():
    tdx.manual_seed(3)
    m = MoE(8, 16, n_experts=4, top_k=2)
    params = dict(m.named_parameters())
    x = jnp.asarray(np.random.RandomState(2).randn(2, 4, 8), jnp.float32)

    def loss(p):
        y, aux = functional_call(m, p, (x,), {"return_aux": True})
        return jnp.mean(y**2) + 0.01 * aux

    g = jax.grad(loss)(params)
    for k in ("w_up", "w_gate", "w_down", "router.weight"):
        assert float(jnp.abs(g[k]).sum()) > 0.0, k


def test_invalid_topk():
    import pytest

    with pytest.raises(ValueError, match="top_k"):
        MoE(8, 16, n_experts=4, top_k=5)
