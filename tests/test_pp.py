"""Pipeline parallelism: pipelined forward/backward must exactly equal
sequential layer application."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu.parallel import create_mesh
from torchdistx_tpu.parallel.pp import pipeline_apply, stack_pipeline_stages


def _stages(n_stages, d, key=0):
    rs = np.random.RandomState(key)
    return [
        {
            "w": jnp.asarray(rs.randn(d, d).astype(np.float32) * 0.1),
            "b": jnp.asarray(rs.randn(d).astype(np.float32) * 0.1),
        }
        for _ in range(n_stages)
    ]


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _sequential(stages, micro):
    out = []
    for m in micro:
        x = m
        for p in stages:
            x = _stage_fn(p, x)
        out.append(x)
    return jnp.stack(out)


class TestPipeline:
    def test_forward_matches_sequential(self):
        mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
        stages = _stages(4, 16)
        stacked = stack_pipeline_stages(stages, mesh)
        micro = jnp.asarray(
            np.random.RandomState(1).randn(6, 8, 16).astype(np.float32)
        )
        out = pipeline_apply(stacked, micro, mesh=mesh, stage_fn=_stage_fn)
        ref = _sequential(stages, micro)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-6, atol=2e-6)

    def test_micro_count_not_multiple_of_stages(self):
        mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
        stages = _stages(4, 8, key=2)
        stacked = stack_pipeline_stages(stages, mesh)
        micro = jnp.asarray(
            np.random.RandomState(3).randn(5, 4, 8).astype(np.float32)
        )
        out = pipeline_apply(stacked, micro, mesh=mesh, stage_fn=_stage_fn)
        ref = _sequential(stages, micro)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-6, atol=2e-6)

    def test_gradients_match_sequential(self):
        mesh = create_mesh({"pp": 2}, devices=jax.devices()[:2])
        stages = _stages(2, 8, key=4)
        stacked = stack_pipeline_stages(stages, mesh)
        micro = jnp.asarray(
            np.random.RandomState(5).randn(4, 4, 8).astype(np.float32)
        )

        def pipe_loss(sp):
            return jnp.mean(
                pipeline_apply(sp, micro, mesh=mesh, stage_fn=_stage_fn) ** 2
            )

        def seq_loss(stage_list):
            return jnp.mean(_sequential(stage_list, micro) ** 2)

        g_pipe = jax.grad(pipe_loss)(stacked)
        g_seq = jax.grad(seq_loss)(stages)
        for i in range(2):
            np.testing.assert_allclose(
                np.asarray(g_pipe["w"][i]),
                np.asarray(g_seq[i]["w"]),
                rtol=1e-5,
                atol=1e-6,
            )

    def test_jit_and_train(self):
        import optax

        mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
        stages = _stages(4, 8, key=6)
        stacked = stack_pipeline_stages(stages, mesh)
        micro = jnp.asarray(
            np.random.RandomState(7).randn(4, 8, 8).astype(np.float32)
        )
        target = jnp.ones((4, 8, 8))
        tx = optax.sgd(0.1)

        @jax.jit
        def step(p, s):
            def loss_fn(p):
                out = pipeline_apply(p, micro, mesh=mesh, stage_fn=_stage_fn)
                return jnp.mean((out - target) ** 2)

            loss, g = jax.value_and_grad(loss_fn)(p)
            u, s = tx.update(g, s, p)
            return jax.tree_util.tree_map(lambda a, b: a + b, p, u), s, loss

        s = tx.init(stacked)
        losses = []
        for _ in range(5):
            stacked, s, loss = step(stacked, s)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_wrong_stage_count(self):
        mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="stages"):
            stack_pipeline_stages(_stages(3, 8), mesh)
