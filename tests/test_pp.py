"""Pipeline parallelism: pipelined forward/backward must exactly equal
sequential layer application — for the GPipe forward (autodiff backward)
and the 1F1B train step (manual backward pipeline), on real transformer
stages produced by deferred_init."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import torchdistx_tpu as tdx
from torchdistx_tpu.nn import functional_call
from torchdistx_tpu.parallel import create_mesh
from torchdistx_tpu.parallel.pp import (
    pipeline_apply,
    pipeline_train_step,
    split_microbatches,
    stack_pipeline_stages,
)


def _stages(n_stages, d, key=0):
    rs = np.random.RandomState(key)
    return [
        {
            "w": jnp.asarray(rs.randn(d, d).astype(np.float32) * 0.1),
            "b": jnp.asarray(rs.randn(d).astype(np.float32) * 0.1),
        }
        for _ in range(n_stages)
    ]


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _sequential(stages, micro):
    out = []
    for m in micro:
        x = m
        for p in stages:
            x = _stage_fn(p, x)
        out.append(x)
    return jnp.stack(out)


class TestPipeline:
    def test_forward_matches_sequential(self):
        mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
        stages = _stages(4, 16)
        stacked = stack_pipeline_stages(stages, mesh)
        micro = jnp.asarray(
            np.random.RandomState(1).randn(6, 8, 16).astype(np.float32)
        )
        out = pipeline_apply(stacked, micro, mesh=mesh, stage_fn=_stage_fn)
        ref = _sequential(stages, micro)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-6, atol=2e-6)

    def test_micro_count_not_multiple_of_stages(self):
        mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
        stages = _stages(4, 8, key=2)
        stacked = stack_pipeline_stages(stages, mesh)
        micro = jnp.asarray(
            np.random.RandomState(3).randn(5, 4, 8).astype(np.float32)
        )
        out = pipeline_apply(stacked, micro, mesh=mesh, stage_fn=_stage_fn)
        ref = _sequential(stages, micro)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-6, atol=2e-6)

    def test_gradients_match_sequential(self):
        mesh = create_mesh({"pp": 2}, devices=jax.devices()[:2])
        stages = _stages(2, 8, key=4)
        stacked = stack_pipeline_stages(stages, mesh)
        micro = jnp.asarray(
            np.random.RandomState(5).randn(4, 4, 8).astype(np.float32)
        )

        def pipe_loss(sp):
            return jnp.mean(
                pipeline_apply(sp, micro, mesh=mesh, stage_fn=_stage_fn) ** 2
            )

        def seq_loss(stage_list):
            return jnp.mean(_sequential(stage_list, micro) ** 2)

        g_pipe = jax.grad(pipe_loss)(stacked)
        g_seq = jax.grad(seq_loss)(stages)
        for i in range(2):
            np.testing.assert_allclose(
                np.asarray(g_pipe["w"][i]),
                np.asarray(g_seq[i]["w"]),
                rtol=1e-5,
                atol=1e-6,
            )

    def test_jit_and_train(self):
        import optax

        mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
        stages = _stages(4, 8, key=6)
        stacked = stack_pipeline_stages(stages, mesh)
        micro = jnp.asarray(
            np.random.RandomState(7).randn(4, 8, 8).astype(np.float32)
        )
        target = jnp.ones((4, 8, 8))
        tx = optax.sgd(0.1)

        @jax.jit
        def step(p, s):
            def loss_fn(p):
                out = pipeline_apply(p, micro, mesh=mesh, stage_fn=_stage_fn)
                return jnp.mean((out - target) ** 2)

            loss, g = jax.value_and_grad(loss_fn)(p)
            u, s = tx.update(g, s, p)
            return jax.tree_util.tree_map(lambda a, b: a + b, p, u), s, loss

        s = tx.init(stacked)
        losses = []
        for _ in range(5):
            stacked, s, loss = step(stacked, s)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_wrong_stage_count(self):
        mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="stages"):
            stack_pipeline_stages(_stages(3, 8), mesh)


def _mse(y, t):
    return jnp.mean((y - t) ** 2)


def _seq_loss(stage_list, micro, tgt, stage_fn, loss_fn=_mse):
    tot = 0.0
    for i in range(micro.shape[0]):
        x = micro[i]
        for p in stage_list:
            x = stage_fn(p, x)
        tot = tot + loss_fn(x, tgt[i])
    return tot / micro.shape[0]


class TestPipelineTrainStep:
    """1F1B schedule: loss and per-stage grads must match the unpipelined
    model's autodiff exactly (CPU f32 is exact)."""

    def test_loss_and_grads_match_sequential(self):
        mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
        stages = _stages(4, 16, key=10)
        stacked = stack_pipeline_stages(stages, mesh)
        rs = np.random.RandomState(11)
        mb = jnp.asarray(rs.randn(6, 8, 16).astype(np.float32))
        tgt = jnp.asarray(rs.randn(6, 8, 16).astype(np.float32))

        loss, g = pipeline_train_step(
            stacked, mb, tgt, mesh=mesh, stage_fn=_stage_fn, loss_fn=_mse
        )
        l_ref, g_ref = jax.value_and_grad(_seq_loss)(
            stages, mb, tgt, _stage_fn
        )
        np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-6)
        for i in range(4):
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    np.asarray(g[k][i]),
                    np.asarray(g_ref[i][k]),
                    rtol=1e-5,
                    atol=1e-6,
                )

    def test_fewer_micro_than_stages(self):
        # M < S: warmup/cooldown masks must keep the math exact
        mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
        stages = _stages(4, 8, key=12)
        stacked = stack_pipeline_stages(stages, mesh)
        rs = np.random.RandomState(13)
        mb = jnp.asarray(rs.randn(2, 4, 8).astype(np.float32))
        tgt = jnp.asarray(rs.randn(2, 4, 8).astype(np.float32))
        loss, g = pipeline_train_step(
            stacked, mb, tgt, mesh=mesh, stage_fn=_stage_fn, loss_fn=_mse
        )
        l_ref, g_ref = jax.value_and_grad(_seq_loss)(
            stages, mb, tgt, _stage_fn
        )
        np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(g["w"][0]), np.asarray(g_ref[0]["w"]),
            rtol=1e-5, atol=1e-6,
        )

    def test_composed_dp_axis(self):
        # batch sharded over dp (NOT replicated to every stage); grads
        # pmean over dp must equal the global-batch sequential grads
        mesh = create_mesh({"dp": 2, "pp": 4})
        stages = _stages(4, 8, key=14)
        stacked = stack_pipeline_stages(stages, mesh)
        rs = np.random.RandomState(15)
        mb = jnp.asarray(rs.randn(4, 8, 8).astype(np.float32))
        tgt = jnp.asarray(rs.randn(4, 8, 8).astype(np.float32))
        mb = jax.device_put(mb, NamedSharding(mesh, P(None, "dp")))
        tgt = jax.device_put(tgt, NamedSharding(mesh, P(None, "dp")))
        loss, g = pipeline_train_step(
            stacked, mb, tgt,
            mesh=mesh, stage_fn=_stage_fn, loss_fn=_mse, dp_axis="dp",
        )
        l_ref, g_ref = jax.value_and_grad(_seq_loss)(
            stages, mb, tgt, _stage_fn
        )
        np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-6)
        for i in range(4):
            np.testing.assert_allclose(
                np.asarray(g["w"][i]), np.asarray(g_ref[i]["w"]),
                rtol=1e-5, atol=1e-6,
            )

    def test_training_reduces_loss(self):
        import optax

        mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
        stages = _stages(4, 8, key=16)
        stacked = stack_pipeline_stages(stages, mesh)
        rs = np.random.RandomState(17)
        batch = jnp.asarray(rs.randn(16, 8).astype(np.float32))
        target = jnp.zeros((16, 8), jnp.float32)  # learnable target
        mb = split_microbatches(batch, 4)
        tgt = split_microbatches(target, 4)
        tx = optax.sgd(0.3)
        s = tx.init(stacked)

        @jax.jit
        def step(p, s):
            loss, g = pipeline_train_step(
                p, mb, tgt, mesh=mesh, stage_fn=_stage_fn, loss_fn=_mse
            )
            u, s = tx.update(g, s, p)
            return optax.apply_updates(p, u), s, loss

        losses = []
        for _ in range(8):
            stacked, s, loss = step(stacked, s)
            losses.append(float(loss))
        assert losses[-1] < 0.5 * losses[0]


class Test3DComposition:
    """The canonical 3D parallelism: dp x tp x pp in ONE 1F1B train step.
    Megatron column/row-split MLP stages (``param_specs`` shards the
    weights over tp; the f/g custom-VJP collectives carry the tp
    reductions inside ``stage_fn``), microbatch batch dim over dp, stages
    over pp — loss and every stage's global gradient must equal
    single-device unpipelined autodiff exactly (VERDICT r3 item 7;
    reference motivation deferred_init.rst:26-27)."""

    @staticmethod
    def _tp_stage_fn(p, x):
        # weights arrive tp-LOCAL: w1 (h/tp, d) column-parallel, w2
        # (d, h/tp) row-parallel; activations tp-replicated at the edges.
        # Megatron f/g operators (collectives.copy_psum_grad /
        # allreduce_linear) carry the tp collectives with the correct
        # custom VJPs — a plain psum double-counts grads under
        # check_vma=False (see collectives.allreduce_linear docstring).
        from torchdistx_tpu.parallel import collectives

        xin = collectives.copy_psum_grad(x, "tp")
        h = jax.nn.relu(xin @ p["w1"].T + p["b1"])
        y = collectives.allreduce_linear(h @ p["w2"].T, "tp") + p["b2"]
        return x + y

    @staticmethod
    def _ref_stage_fn(p, x):
        h = jax.nn.relu(x @ p["w1"].T + p["b1"])
        return x + h @ p["w2"].T + p["b2"]

    def test_forward_pipeline_apply_with_tp_specs(self):
        # pipeline_apply's param_specs hook: tp-sharded stage weights in
        # the forward-only GPipe schedule must match sequential exactly
        mesh = create_mesh({"tp": 2, "pp": 4})
        d, h = 8, 16
        rs = np.random.RandomState(20)
        stages = [
            {
                "w1": jnp.asarray(rs.randn(h, d).astype(np.float32) * 0.1),
                "b1": jnp.asarray(rs.randn(h).astype(np.float32) * 0.1),
                "w2": jnp.asarray(rs.randn(d, h).astype(np.float32) * 0.1),
                "b2": jnp.asarray(rs.randn(d).astype(np.float32) * 0.1),
            }
            for _ in range(4)
        ]
        specs = {
            "w1": P("pp", "tp", None),
            "b1": P("pp", "tp"),
            "w2": P("pp", None, "tp"),
            "b2": P("pp", None),
        }
        stacked = jax.device_put(
            stack_pipeline_stages(stages, mesh),
            {k: NamedSharding(mesh, s) for k, s in specs.items()},
        )
        mb = jnp.asarray(rs.randn(3, 4, d).astype(np.float32))
        out = pipeline_apply(
            stacked, mb, mesh=mesh, stage_fn=self._tp_stage_fn,
            param_specs=specs,
        )
        ref = mb
        for p in stages:
            ref = jax.vmap(lambda x, p=p: self._ref_stage_fn(p, x))(ref)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
        )

    def test_dp_tp_pp_loss_and_grads_match_single_device(self):
        mesh = create_mesh({"dp": 2, "tp": 2, "pp": 2})
        d, h = 8, 16
        rs = np.random.RandomState(21)
        stages = [
            {
                "w1": jnp.asarray(rs.randn(h, d).astype(np.float32) * 0.1),
                "b1": jnp.asarray(rs.randn(h).astype(np.float32) * 0.1),
                "w2": jnp.asarray(rs.randn(d, h).astype(np.float32) * 0.1),
                "b2": jnp.asarray(rs.randn(d).astype(np.float32) * 0.1),
            }
            for _ in range(2)
        ]
        specs = {
            "w1": P("pp", "tp", None),
            "b1": P("pp", "tp"),
            "w2": P("pp", None, "tp"),
            "b2": P("pp", None),
        }
        stacked = stack_pipeline_stages(stages, mesh)
        stacked = jax.device_put(
            stacked,
            {k: NamedSharding(mesh, s) for k, s in specs.items()},
        )
        mb = jnp.asarray(rs.randn(4, 4, d).astype(np.float32))
        tgt = jnp.asarray(rs.randn(4, 4, d).astype(np.float32))
        mb = jax.device_put(mb, NamedSharding(mesh, P(None, "dp")))
        tgt = jax.device_put(tgt, NamedSharding(mesh, P(None, "dp")))

        loss, g = pipeline_train_step(
            stacked, mb, tgt,
            mesh=mesh,
            stage_fn=self._tp_stage_fn,
            loss_fn=_mse,
            dp_axis="dp",
            param_specs=specs,
        )
        l_ref, g_ref = jax.value_and_grad(_seq_loss)(
            stages, mb, tgt, self._ref_stage_fn
        )
        np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-6)
        for i in range(2):
            for k in ("w1", "b1", "w2", "b2"):
                np.testing.assert_allclose(
                    np.asarray(g[k][i]),
                    np.asarray(g_ref[i][k]),
                    rtol=1e-5,
                    atol=1e-6,
                )


class TestLlamaPipeline:
    """The VERDICT bar: stage params produced by deferred_init from real
    Llama blocks, stacked with stack_pipeline_stages, trained with the
    1F1B step — and the pipelined loss/grads equal the unpipelined
    model's."""

    def _cfg(self):
        from torchdistx_tpu.models.llama import LlamaConfig

        return LlamaConfig(
            vocab_size=64,
            dim=32,
            n_layers=4,  # 1 block per stage on pp=4
            n_heads=4,
            n_kv_heads=2,
            max_seq_len=16,
            dtype=jnp.float32,
            use_flash=False,
        )

    @pytest.mark.slow
    def test_llama_blocks_deferred_init_pp_matches_unpipelined(self):
        from torchdistx_tpu.models.llama import pp_stage

        cfg = self._cfg()
        Stage = pp_stage(cfg)
        mesh = create_mesh({"dp": 2, "pp": 4})

        # one deferred-init per stage; materialize; stack over pp
        stage_params = []
        for i in range(4):
            tdx.manual_seed(100 + i)
            m = tdx.deferred_init(Stage)
            assert tdx.is_deferred(m)
            tdx.materialize_module(m)
            stage_params.append(dict(m.named_parameters()))
        stacked = stack_pipeline_stages(stage_params, mesh)

        template = Stage()  # structure only; params bound per call
        stage_fn = lambda p, x: functional_call(template, p, (x,))  # noqa: E731

        rs = np.random.RandomState(21)
        B, S = 4, 8
        hidden = jnp.asarray(rs.randn(8, B, S, cfg.dim).astype(np.float32))
        tgt = jnp.asarray(rs.randn(8, B, S, cfg.dim).astype(np.float32))

        # reference on the plain (unsharded) arrays first
        l_ref, g_ref = jax.value_and_grad(_seq_loss)(
            stage_params, hidden, tgt, stage_fn
        )

        hidden = jax.device_put(hidden, NamedSharding(mesh, P(None, "dp")))
        tgt = jax.device_put(tgt, NamedSharding(mesh, P(None, "dp")))
        loss, g = pipeline_train_step(
            stacked, hidden, tgt,
            mesh=mesh, stage_fn=stage_fn, loss_fn=_mse, dp_axis="dp",
        )
        np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)
        ref_by_stage = [jax.tree_util.tree_leaves(gr) for gr in g_ref]
        pp_leaves = jax.tree_util.tree_leaves(g)
        for i in range(4):
            for pp_leaf, ref_leaf in zip(pp_leaves, ref_by_stage[i]):
                np.testing.assert_allclose(
                    np.asarray(pp_leaf[i]),
                    np.asarray(ref_leaf),
                    rtol=2e-4,
                    atol=1e-5,
                )
